#!/usr/bin/env python
"""Front-door load bench -> GATE_BENCH.json (ROADMAP item 1's
acceptance artifact).

Four legs over the demo gate (two Poisson operators under a memory
budget that fits only ONE resident at a time — every tenant switch is
a forced page-out/page-in):

* **multi-client overload leg** — N client threads POST a mixed-class
  request stream (interactive / batch / besteffort, round-robin over
  both tenants) against the HTTP surface while dispatch is held, so
  the gate queue genuinely crosses the shed watermark: besteffort is
  refused with the typed 429 + ``Retry-After`` `LoadShedded` while
  interactive and batch keep being admitted; dispatch then resumes and
  the backlog drains under EDF with the tenant alternation forcing
  >= 1 eviction DURING the load. Per-class attainment is read from the
  pamon registry deltas (``gate.slo.requests``/``gate.slo.hits`` —
  the same counters ``tools/pamon.py`` renders), cross-checked against
  the client-side outcome table.
* **eviction-cost leg** — the same solve on a resident tenant (warm)
  vs right after a page-out (cold: fresh `SolveService` + lazy
  re-stage + solve); the difference is the measured price of paging.
* **saturation leg (v2, pafleet)** — an OPEN-LOOP arrival sweep: per
  offered-load level, one `http_solve` client per request fires at its
  scheduled arrival time regardless of completions (the PR 12 retry
  client IS the loadgen: a shed 429 / backpressure 503 backs off and
  resubmits under its own budget), classes rotating so the lowest
  class genuinely crosses the watermark at overload. Levels are
  multiples of the machine's PROBED warm capacity (0.25x / 1x / 4x),
  so the sweep brackets the knee on any host. Per level the leg
  records offered vs sustained throughput, per-class attainment from
  the ``gate.slo.*`` deltas, and p50/p99 from the pamon
  ``service.total_s`` histogram snapshot delta (the same buckets
  ``tools/pamon.py`` renders) cross-checked against client-side
  walls; the knee is the highest level that still completes every
  request, keeps interactive attainment at target, and sustains
  >= ``SATURATION_SUSTAIN_RATIO`` of the offered rate.
* **bands** — ``interactive_attainment`` must meet the 0.9 target
  WHILE shedding is active (the ROADMAP acceptance line, measured not
  asserted), every shed must land on the lowest class
  (``besteffort_shed_share``), the eviction round-trip ratio is a
  structural canary, and the saturation knee must exist
  (``saturation_knee_rps`` > 0) with interactive attainment at the
  knee still at target (``saturation_attainment_at_knee``). All
  canary-kind: they gate on every platform (tools/pareg.py --check),
  and none is a device-throughput claim — the knee's absolute rps is
  recorded but only its existence and its SLO are banded.

``--dry-run`` prints without writing.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: Guard bands for the committed artifact (canary kind: structural
#: claims about the gate's behavior under overload — they must hold on
#: every platform the bench runs on).
GATE_BANDS = {
    "interactive_attainment": (0.9, 1.0, "canary"),
    "besteffort_shed_share": (0.999, 1.0, "canary"),
    "eviction_roundtrip_ratio": (0.8, 500.0, "canary"),
    # the knee is machine-relative (levels are multiples of probed
    # capacity), so the band only asserts it EXISTS and keeps SLO —
    # never an absolute-throughput claim
    "saturation_knee_rps": (1e-3, 1e9, "canary"),
    "saturation_attainment_at_knee": (0.9, 1.0, "canary"),
}

METHODOLOGY = "v2-gate-load-saturation"

#: The interactive class's SLO attainment target the overload leg must
#: meet while shedding is active (the band's lower edge).
ATTAINMENT_TARGET = 0.9

CLIENTS = 3
#: Per client: phase 1 submits (interactive, batch) — the protected
#: backlog; phase 2 submits (besteffort, interactive) at full depth.
REQUESTS_PER_CLIENT = 4
CLASSES = ("interactive", "batch", "besteffort")

#: Saturation sweep: offered levels as multiples of the probed warm
#: capacity, requests per level, and the sustained/offered ratio a
#: level must hold to count as "keeping up" for the knee.
SATURATION_LEVELS = (0.25, 1.0, 4.0)
SATURATION_REQUESTS = 10
SATURATION_SUSTAIN_RATIO = 0.7


def _post(url, payload):
    req = urllib.request.Request(
        url + "/v1/solve", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _poll(url, rid, timeout_s=300.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        with urllib.request.urlopen(f"{url}/v1/solve/{rid}") as resp:
            poll = json.loads(resp.read())
        if poll["state"] not in ("gate-queued", "queued", "running"):
            return poll
        time.sleep(0.005)
    raise TimeoutError(rid)


def run_multi_client(gate, srv, systems):
    """The overload leg (see module docstring). Returns the record
    fragment."""
    from partitionedarrays_jl_tpu import telemetry
    from partitionedarrays_jl_tpu.models.solvers import gather_pvector

    reg = telemetry.registry()

    def gauge(name):
        return reg.snapshot()["counters"].get(name, 0)

    names = sorted(systems)
    rhs = {
        name: (
            gather_pvector(systems[name][1]).tolist(),
            gather_pvector(systems[name][3]).tolist(),
        )
        for name in names
    }
    before = {
        "evictions": gauge("gate.evictions"),
        "page_ins": gauge("gate.page_ins"),
        **{
            f"req.{c}": gauge(
                f"gate.slo.requests{{slo_class={c}}}"
            ) for c in CLASSES
        },
        **{
            f"hit.{c}": gauge(f"gate.slo.hits{{slo_class={c}}}")
            for c in CLASSES
        },
        **{
            f"shed.{c}": gauge(f"gate.shed{{slo_class={c}}}")
            for c in CLASSES
        },
    }
    outcomes = []
    olock = threading.Lock()
    gate.paused = True  # hold dispatch: the backlog must really build

    def client(cid, phase_classes, phase):
        for i, cls in enumerate(phase_classes):
            tenant = names[(cid + i) % len(names)]
            b, x0 = rhs[tenant]
            status, payload = _post(srv.url, {
                "tenant": tenant, "b": b, "x0": x0, "tol": 1e-9,
                "deadline": 600.0, "slo_class": cls,
                "tag": f"bench-{cid}-{phase}-{i}",
            })
            with olock:
                outcomes.append((cls, status, payload))

    def run_phase(phase, phase_classes):
        threads = [
            threading.Thread(
                target=client, args=(cid, phase_classes, phase)
            )
            for cid in range(CLIENTS)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    # phase 1 — protected classes build the backlog past the
    # watermark; phase 2 — besteffort arrives at full depth (all shed,
    # deterministically) while interactive keeps being admitted
    run_phase(1, ("interactive", "batch"))
    assert gate.depth() >= gate.watermark, (
        gate.depth(), gate.watermark,
    )
    run_phase(2, ("besteffort", "interactive"))
    t0 = time.perf_counter()
    gate.paused = False
    finals = []
    for cls, status, payload in outcomes:
        if status == 202:
            finals.append((cls, _poll(srv.url, payload["id"])))
    drain_wall = time.perf_counter() - t0
    # the pump accounts terminal requests on its next tick — settle
    # before reading the SLO deltas
    for _ in range(1000):
        gate.account()
        with gate._lock:
            if not gate._inflight:
                break
        time.sleep(0.005)
    after = {
        "evictions": gauge("gate.evictions"),
        "page_ins": gauge("gate.page_ins"),
        **{
            f"req.{c}": gauge(
                f"gate.slo.requests{{slo_class={c}}}"
            ) for c in CLASSES
        },
        **{
            f"hit.{c}": gauge(f"gate.slo.hits{{slo_class={c}}}")
            for c in CLASSES
        },
        **{
            f"shed.{c}": gauge(f"gate.shed{{slo_class={c}}}")
            for c in CLASSES
        },
    }
    delta = {k: after[k] - before[k] for k in before}
    per_class = {}
    for cls in CLASSES:
        submitted = sum(1 for c, _s, _p in outcomes if c == cls)
        shed = sum(
            1 for c, s, _p in outcomes if c == cls and s == 429
        )
        done = sum(
            1 for c, p in finals if c == cls and p["state"] == "done"
        )
        # attainment via pamon: the registry's requests/hits deltas
        req_m, hit_m = delta[f"req.{cls}"], delta[f"hit.{cls}"]
        per_class[cls] = {
            "submitted": submitted,
            "shed": shed,
            "done": done,
            "pamon_requests": req_m,
            "pamon_hits": hit_m,
            "attainment": round(hit_m / req_m, 6) if req_m else None,
        }
        assert delta[f"shed.{cls}"] == shed, (cls, delta, shed)
        assert req_m == submitted - shed, (cls, delta, per_class)
        assert hit_m == done, (cls, delta, per_class)
    total_shed = sum(r["shed"] for r in per_class.values())
    admitted = sum(
        r["submitted"] - r["shed"] for r in per_class.values()
    )
    return {
        "clients": CLIENTS,
        "requests_per_client": REQUESTS_PER_CLIENT,
        "classes": list(CLASSES),
        "submitted": CLIENTS * REQUESTS_PER_CLIENT,
        "admitted": admitted,
        "shed_total": total_shed,
        "shed_rate": round(
            total_shed / (CLIENTS * REQUESTS_PER_CLIENT), 6
        ),
        "evictions_during_load": delta["evictions"],
        "page_ins_during_load": delta["page_ins"],
        "drain_wall_s": round(drain_wall, 6),
        "drained_requests_per_s": round(admitted / drain_wall, 3),
        "attainment_target": ATTAINMENT_TARGET,
        "per_class": per_class,
    }


def _hist_window(before: dict, after: dict):
    """`LatencyHistogram` of just the observations landing between two
    snapshots of the same histogram (exact bucket-count differences;
    the window's min/max are unknowable from snapshots, so quantiles
    read pure bucket edges — still conservative upper bounds)."""
    from partitionedarrays_jl_tpu.telemetry.histogram import (
        LatencyHistogram,
    )

    h = LatencyHistogram()
    b0 = {
        int(i): int(c) for i, c in (before.get("buckets") or {}).items()
    }
    for i, c in (after.get("buckets") or {}).items():
        d = int(c) - b0.get(int(i), 0)
        if d:
            h.counts[int(i)] += d
    h.total = int(after["count"]) - int(before["count"])
    h.sum = float(after["sum"]) - float(before["sum"])
    return h


def run_saturation(gate, srv, systems):
    """The open-loop saturation sweep (see module docstring). Returns
    the record fragment with the per-level curve and the knee."""
    from partitionedarrays_jl_tpu import telemetry
    from partitionedarrays_jl_tpu.frontdoor import http_solve
    from partitionedarrays_jl_tpu.models.solvers import gather_pvector

    reg = telemetry.registry()

    def counters():
        return reg.snapshot()["counters"]

    def hist_snap():
        return reg.snapshot()["histograms"].get(
            "service.total_s",
            {"count": 0, "sum": 0.0, "buckets": {}},
        )

    def settle():
        # terminal requests are SLO-accounted on the pump's next tick
        for _ in range(1000):
            gate.account()
            with gate._lock:
                if not gate._inflight:
                    break
            time.sleep(0.005)

    # one tenant only: the sweep measures the gate+service pipeline,
    # not the paging path (the overload leg already forces evictions)
    tenant = min(systems, key=lambda n: systems[n][0].rows.ngids)
    _A, bvec, _xe, x0 = systems[tenant]
    b = gather_pvector(bvec).tolist()
    x0 = gather_pvector(x0).tolist()

    def one(cls, tag):
        t0 = time.perf_counter()
        out = http_solve(
            srv.url, tenant, b, x0=x0, tol=1e-9, deadline=600.0,
            slo_class=cls, tag=tag, poll_s=0.002, timeout_s=120.0,
            retries=8, retry_cap_s=0.5,
        )
        return out, time.perf_counter() - t0

    # -- capacity probe: warm resident + compiled, then min-of-3 warm
    # HTTP round-trips define this machine's base rate; levels are
    # MULTIPLES of it, so the sweep brackets the knee on any host
    one("interactive", "sat-warm")
    base_s = min(
        one("interactive", f"sat-probe-{k}")[1] for k in range(3)
    )
    settle()
    base_rps = 1.0 / max(base_s, 1e-6)

    levels = []
    n = SATURATION_REQUESTS
    for mult in SATURATION_LEVELS:
        rps = base_rps * mult
        interval = 1.0 / rps
        before_c, before_h = counters(), hist_snap()
        results = [None] * n
        start = time.perf_counter() + 0.05

        def client(i):
            # open-loop: fire at the scheduled arrival slot no matter
            # what earlier requests are doing
            time.sleep(max(0.0, start + i * interval - time.perf_counter()))
            results[i] = one(
                CLASSES[i % len(CLASSES)], f"sat-{mult}-{i}"
            )

        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(n)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        window_s = time.perf_counter() - start
        settle()
        after_c, after_h = counters(), hist_snap()

        walls = sorted(w for _o, w in results)
        done = sum(
            1 for o, _w in results if o.get("state") == "done"
        )
        hist = _hist_window(before_h, after_h)
        attainment = {}
        for cls in CLASSES:
            req = (
                after_c.get(f"gate.slo.requests{{slo_class={cls}}}", 0)
                - before_c.get(
                    f"gate.slo.requests{{slo_class={cls}}}", 0
                )
            )
            hit = (
                after_c.get(f"gate.slo.hits{{slo_class={cls}}}", 0)
                - before_c.get(f"gate.slo.hits{{slo_class={cls}}}", 0)
            )
            attainment[cls] = (
                round(hit / req, 6) if req else None
            )
        shed = sum(
            after_c.get(f"gate.shed{{slo_class={cls}}}", 0)
            - before_c.get(f"gate.shed{{slo_class={cls}}}", 0)
            for cls in CLASSES
        )
        sustained_rps = done / window_s if window_s > 0 else 0.0
        sustained_ratio = sustained_rps / rps
        ia = attainment["interactive"]
        meets = (
            done == n
            and ia is not None and ia >= ATTAINMENT_TARGET
            and sustained_ratio >= SATURATION_SUSTAIN_RATIO
        )
        levels.append({
            "capacity_multiple": mult,
            "offered_rps": round(rps, 3),
            "requests": n,
            "done": done,
            "shed_retries": shed,
            "window_s": round(window_s, 6),
            "sustained_rps": round(sustained_rps, 3),
            "sustained_ratio": round(sustained_ratio, 6),
            # pamon is the primary read: service.total_s bucket deltas
            "pamon_count": hist.total,
            "pamon_p50_s": hist.quantile(0.5),
            "pamon_p99_s": hist.quantile(0.99),
            # client-side cross-check (includes queueing + retries)
            "client_p50_s": round(walls[len(walls) // 2], 6),
            "client_p99_s": round(walls[-1], 6),
            "attainment": attainment,
            "meets_slo": meets,
        })
    knee = None
    for lv in levels:
        if lv["meets_slo"]:
            knee = lv
    return {
        "tenant": tenant,
        "probe_base_s": round(base_s, 6),
        "probe_base_rps": round(base_rps, 3),
        "levels_capacity_multiples": list(SATURATION_LEVELS),
        "requests_per_level": n,
        "sustain_ratio_target": SATURATION_SUSTAIN_RATIO,
        "attainment_target": ATTAINMENT_TARGET,
        "curve": levels,
        "knee": knee,
    }


def run_eviction_cost(gate, systems):
    """Warm vs post-eviction (cold) solve wall on the larger tenant."""
    name = max(systems, key=lambda n: systems[n][0].rows.ngids)
    A, b, xe, x0 = systems[name]

    def solve():
        t0 = time.perf_counter()
        h = gate.submit(name, b, x0=x0, tol=1e-9,
                        slo_class="interactive")
        while not h.done():
            time.sleep(0.001)
        h.result()
        return time.perf_counter() - t0

    solve()  # ensure resident + warm
    warm = min(solve() for _ in range(3))
    gate.evict(name)
    cold = solve()  # page-in + lazy re-stage + solve
    return {
        "tenant": name,
        "warm_solve_s": round(warm, 6),
        "cold_solve_s": round(cold, 6),
        "page_in_overhead_s": round(max(0.0, cold - warm), 6),
        "ratio": round(cold / warm, 3),
    }


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    dry = "--dry-run" in argv

    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "pagate", os.path.join(REPO, "tools", "pagate.py")
    )
    pg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pg)

    from partitionedarrays_jl_tpu.frontdoor import serve_gate
    from partitionedarrays_jl_tpu.telemetry import artifacts

    gate, systems = pg.build_demo_gate(budget="one", shed_watermark=4)
    srv = serve_gate(gate, port=0)
    try:
        multi = run_multi_client(gate, srv, systems)
        sat = run_saturation(gate, srv, systems)
        evict = run_eviction_cost(gate, systems)
    finally:
        srv.stop()

    shed_by_class = {
        cls: multi["per_class"][cls]["shed"] for cls in CLASSES
    }
    measured = {
        "interactive_attainment": multi["per_class"]["interactive"][
            "attainment"
        ],
        "besteffort_shed_share": (
            round(shed_by_class["besteffort"] / multi["shed_total"], 6)
            if multi["shed_total"] else None
        ),
        "eviction_roundtrip_ratio": evict["ratio"],
        "saturation_knee_rps": (
            sat["knee"]["offered_rps"] if sat["knee"] else None
        ),
        "saturation_attainment_at_knee": (
            sat["knee"]["attainment"]["interactive"]
            if sat["knee"] else None
        ),
    }
    rec = {
        "methodology": METHODOLOGY,
        "protocol": (
            f"{CLIENTS} client threads x {REQUESTS_PER_CLIENT} "
            "mixed-class HTTP requests round-robin over "
            f"{len(systems)} Poisson tenants under a one-resident "
            "memory budget; dispatch held while phase 1 "
            "(interactive+batch) builds the backlog past "
            "PA_GATE_SHED_DEPTH, then phase 2 submits besteffort "
            "(shed typed with Retry-After, deterministically at full "
            "depth) alongside interactive (still admitted); dispatch "
            "released and drained under EDF with the tenant "
            "alternation forcing evictions during load; "
            "attainment from the pamon gate.slo.* registry deltas, "
            "cross-checked against client-side outcomes; eviction "
            "cost = cold (page-in + lazy re-stage + solve) vs warm "
            "min-of-3 solve wall on the larger tenant; saturation = "
            f"open-loop arrival sweep at {SATURATION_LEVELS} x the "
            f"probed warm capacity, {SATURATION_REQUESTS} http_solve "
            "retry clients per level fired at scheduled arrival slots "
            "(classes rotating), p50/p99 from the service.total_s "
            "histogram snapshot delta, attainment from gate.slo.* "
            "deltas; knee = highest level completing every request "
            "with interactive attainment at target and sustained/"
            f"offered >= {SATURATION_SUSTAIN_RATIO}"
        ),
        "tenants": [
            {
                "tenant": name,
                "ngids": systems[name][0].rows.ngids,
                "footprint_bytes": gate.registry.tenant(
                    name
                ).footprint_bytes,
            }
            for name in sorted(systems)
        ],
        "budget_bytes": gate.registry.budget,
        "shed_watermark": gate.watermark,
        "multi_client": multi,
        "saturation": sat,
        "eviction_cost": evict,
        "bands": {},
    }
    ok = True
    for key, (lo, hi, kind) in GATE_BANDS.items():
        v = measured[key]
        in_band = (v is not None) and lo <= v <= hi
        rec["bands"][key] = {
            "lo": lo, "hi": hi, "measured": v, "in_band": in_band,
            "kind": kind,
        }
        ok = ok and in_band
    rec["bands_ok_device"] = ok
    if not ok:
        print("bench_gate: BAND FAILURE", file=sys.stderr)
    artifacts.write(
        os.path.join(REPO, "GATE_BENCH.json"), rec, tool="bench_gate",
        dry_run=dry,
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
