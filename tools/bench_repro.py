"""Reproducibility study for the driver-recorded metrics (VERDICT r3
directive 5): run the halo and SpMV legs of bench.py K times each in ONE
process and print the distribution, so the documented bands come from a
measured spread instead of round-to-round anecdotes, and so the halo
value/ratio swing (11.1 GB/s / 137x in docs vs 20.3 GB/s / 65.4x in
BENCH_r03) can be attributed to the device numerator or the host-oracle
denominator.

The committed record (``docs/repro_r5.json`` by default) goes through
the shared schema-versioned artifact writer (`telemetry.artifacts`),
the same envelope every committed bench artifact carries;
tests/test_doc_consistency.py checks it. ``--dry-run`` prints without
committing.

    python tools/bench_repro.py          # 5 reps each, ~10 min on chip
    PA_REPRO_REPS=8 python tools/bench_repro.py
"""
from __future__ import annotations

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax

    import bench
    import partitionedarrays_jl_tpu as pa
    from partitionedarrays_jl_tpu.parallel.tpu import TPUBackend

    reps = int(os.environ.get("PA_REPRO_REPS", "5"))
    n = int(os.environ.get("PA_BENCH_N", "192"))
    backend = TPUBackend(devices=jax.devices()[:1])
    out = {"n": n, "reps": reps, "halo": [], "halo_host_oracle": [],
           "spmv": [], "methodology": bench.METHODOLOGY}

    # --- halo leg, reps times (device numerator AND host denominator
    # recorded separately per rep) --------------------------------------
    for r in range(reps):
        rec = bench.bench_halo(n, backend, pa)
        out["halo"].append(rec["value"])
        out["halo_host_oracle"].append(rec["host_oracle_bytes_per_s"])
        print(f"halo rep {r}: {rec['value']/1e9:.2f} GB/s device, "
              f"{rec['host_oracle_bytes_per_s']/1e6:.1f} MB/s host",
              flush=True)

    # --- SpMV leg, reps times, via the SHIPPED chain builder -----------
    run_chain, _A, _x, _dA, flops = bench.spmv_chain(n, backend, pa)
    for r in range(reps):
        dt = bench.marginal_chain_time(run_chain, 50, 450)
        g = flops / dt / 1e9
        out["spmv"].append(round(g, 1))
        print(f"spmv rep {r}: {g:.1f} GFLOP/s", flush=True)

    for k in ("halo", "halo_host_oracle", "spmv"):
        v = out[k]
        out[k + "_stats"] = {
            "min": min(v), "max": max(v),
            "median": statistics.median(v),
            "spread_pct": round(100 * (max(v) - min(v)) / statistics.median(v), 1),
        }
    print(json.dumps(out, indent=1), flush=True)
    from partitionedarrays_jl_tpu.telemetry import artifacts

    name = os.environ.get("PA_REPRO_NAME", "repro_r5.json")
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", name)
    artifacts.write(
        path, out, tool="bench_repro", dry_run="--dry-run" in sys.argv[1:]
    )


if __name__ == "__main__":
    main()
