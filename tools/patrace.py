#!/usr/bin/env python
"""patrace — inspect runtime solver telemetry (SolveRecords).

Reads the schema-versioned record JSONs the telemetry layer persists
(set ``PA_METRICS_DIR=<dir>`` before the run; every finished or aborted
solve writes one record there) and answers the questions an operator
asks after the fact:

* ``--last``       summarize the newest record: solver, config, status,
                   iterations, residual head/tail, the event log (fault
                   injections, health errors, SDC detections/rollbacks,
                   checkpoint saves/restores, restarts), and the
                   static-vs-measured comms accounting.
* ``--list``       one line per persisted record, oldest first.
* ``--trace OUT``  export the newest ``--n`` records (default 8) as one
                   Chrome-trace/Perfetto JSON — load at
                   https://ui.perfetto.dev or chrome://tracing.
* ``--diff-static`` run the static-vs-measured comms reconciliation
                   over the lowering matrix (probe solves on the CPU
                   mesh — the same check `tools/palint.py --check`
                   gates on) and print the per-case verdict. ``--full``
                   widens the fast subset to all 15 cases.
* ``--phases P``   merge a `telemetry.profile` PhaseProfile JSON
                   (written by ``tools/paprof.py --profile OUT``) into
                   the ``--trace`` export as its own synthetic-
                   iteration track — phase attribution lands on the
                   same Perfetto timeline as the solve records (alone,
                   ``--phases`` just renders the phase table).
* ``--service``    join the solve service's request-level records into
                   per-SLAB timelines: because events append to every
                   active record, one poisoned-column incident is
                   smeared across K separate request records — this
                   leg dedups and merges them so the incident reads as
                   a single story (formation, verdicts, ejection, each
                   request's outcome).

Usage:
    PA_METRICS_DIR=/tmp/rec python your_solve.py
    python tools/patrace.py --last --dir /tmp/rec
    python tools/patrace.py --trace trace.json --dir /tmp/rec
    python tools/patrace.py --diff-static
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _records_dir(args):
    d = args.dir or os.environ.get("PA_METRICS_DIR")
    if not d:
        print(
            "patrace: no record directory — pass --dir or set "
            "PA_METRICS_DIR (records persist only when it was set for "
            "the run)",
            file=sys.stderr,
        )
        return None
    return d


def _load_all(d):
    from partitionedarrays_jl_tpu.telemetry import (
        RECORD_SCHEMA_VERSION,
        list_persisted_records,
        load_record,
    )

    out = []
    for path in list_persisted_records(d):
        try:
            rec = load_record(path)
        except (OSError, json.JSONDecodeError) as e:
            print(f"patrace: skipping unreadable {path}: {e}",
                  file=sys.stderr)
            continue
        if rec.get("schema_version", 0) > RECORD_SCHEMA_VERSION:
            print(
                f"patrace: {os.path.basename(path)} has newer "
                f"schema_version {rec.get('schema_version')} (this tool "
                f"speaks {RECORD_SCHEMA_VERSION}) — fields may be "
                "missing from the summary",
                file=sys.stderr,
            )
        out.append((path, rec))
    return out


def _fmt_events(rec):
    lines = []
    for ev in rec.get("events") or []:
        it = ev.get("iteration")
        at = f" it={it}" if it is not None else ""
        label = ev.get("label") or ""
        details = ev.get("details") or {}
        extra = ", ".join(
            f"{k}={v}" for k, v in sorted(details.items())
            if k not in ("message",)
        )
        lines.append(
            f"    [{ev.get('t', 0.0):9.4f}s] {ev.get('kind')}"
            f"{':' + label if label else ''}{at}"
            + (f"  ({extra})" if extra else "")
        )
    return lines


def _summarize(path, rec):
    print(f"record: {os.path.basename(path)}")
    print(
        f"  solver={rec.get('solver')} status={rec.get('status')} "
        f"converged={rec.get('converged')} iterations={rec.get('iterations')} "
        f"wall={rec.get('wall_s') if rec.get('wall_s') is None else round(rec['wall_s'], 4)}s"
    )
    cfg = rec.get("config") or {}
    shown = {k: v for k, v in cfg.items() if k != "pa_env"}
    print(f"  config: {json.dumps(shown, sort_keys=True, default=str)}")
    trace = rec.get("trace")
    if trace:
        print(
            f"  trace: {trace.get('trace_id')} "
            f"(span {trace.get('span_id')} — tools/patx.py "
            f"{trace.get('trace_id')} renders the tree)"
        )
    res = rec.get("residuals") or []
    if res:
        head = ", ".join(f"{v:.3e}" for v in res[:3])
        tail = ", ".join(f"{v:.3e}" for v in res[-2:])
        print(f"  residuals[{len(res)}]: {head} ... {tail}")
    alpha = rec.get("alpha")
    if alpha:
        if isinstance(alpha[0], list):  # block solve: per-column lists
            shape = f"{len(alpha)} columns x {len(alpha[0])} entries"
            n = len(alpha[0])
        else:
            shape = f"{len(alpha)} entries"
            n = len(alpha)
        start = rec.get("trace_start") or 0
        window = f", iterations {start}..{start + n - 1}" if start else ""
        print(f"  alpha/beta trace: {shape} (PA_TRACE_ITERS ring{window})")
    else:
        # trace-ring exemption honesty (round 17 — paspec): a body that
        # cannot carry the ring says so via the typed event — surface
        # it here so a missing spectrum is explained, not mysterious
        unavailable = [
            ev for ev in rec.get("events") or []
            if ev.get("kind") == "trace_unavailable"
        ]
        if unavailable:
            ev = unavailable[0]
            det = ev.get("details") or {}
            print(
                f"  alpha/beta trace: UNAVAILABLE — body "
                f"{ev.get('label')!r} (requested depth "
                f"{det.get('requested')}; {det.get('reason', '')})"
            )
    err = rec.get("error")
    if err:
        print(f"  error: {err.get('type')}: {err.get('message')}")
    comms = rec.get("comms")
    if comms:
        print(f"  comms (iterations={comms.get('iterations')}):")
        for kind, v in sorted((comms.get("observed") or {}).items()):
            if v.get("ops"):
                per = (comms.get("per_iteration") or {}).get(kind, {})
                print(
                    f"    {kind}: {v['ops']} ops, {v['bytes']} B "
                    f"({per.get('ops', 0)} ops/it, "
                    f"{per.get('bytes', 0)} B/it per device)"
                )
    events = rec.get("events") or []
    print(f"  events [{len(events)}]:")
    for line in _fmt_events(rec):
        print(line)


def _service_slabs(recs):
    """Group service-request records into slab stories.

    Returns ``[(members, member_recs, events)]`` where ``events`` is the
    deduped, absolute-time-sorted union of the members' event logs.
    Records are joined on the ``requests`` list each non-topped-up
    ``slab_formed`` event carries; an event belongs to a slab when it
    names a member (label, ``details.request``) or the slab itself
    (``details.requests`` overlap). Dedup key is the event's content —
    the same event lands in every record that was active when it fired,
    with per-record relative clocks, so identity must come from WHAT
    happened, not when each record saw it."""
    svc = [
        (path, rec) for path, rec in recs
        if rec.get("solver") == "service-request"
    ]
    by_tag = {}
    for _path, rec in svc:
        tag = (rec.get("config") or {}).get("request")
        if tag is not None:
            by_tag.setdefault(tag, rec)

    # two passes: base slabs first, THEN top-up extensions — records
    # persist at finish time, so a topped-up request that terminated
    # before the founding members files its record (and its
    # topped_up slab_formed event) ahead of the base formation
    slabs = []  # [{"members": set, "order": [tags]}]
    topups = []
    for _path, rec in svc:
        for ev in rec.get("events") or []:
            if ev.get("kind") != "slab_formed":
                continue
            details = ev.get("details") or {}
            tags = list(details.get("requests") or [])
            if not tags:
                continue
            if details.get("topped_up"):
                topups.append(tags)
                continue
            if not any(s["members"] == set(tags) for s in slabs):
                slabs.append({"members": set(tags), "order": tags})
    for tags in topups:
        for s in slabs:  # extend the slab the arrivals joined
            if s["members"] & set(tags):
                for t in tags:
                    if t not in s["members"]:
                        s["members"].add(t)
                        s["order"].append(t)
                break

    out = []
    for s in slabs:
        members = s["members"]
        member_recs = [
            (t, by_tag[t]) for t in s["order"] if t in by_tag
        ]
        seen = {}
        unnamed = {}
        continuation = {}
        t_form = None
        for tag, rec in member_recs:
            t0 = rec.get("started_at") or 0.0
            for ev in rec.get("events") or []:
                details = ev.get("details") or {}
                abs_t = t0 + (ev.get("t") or 0.0)
                key = (
                    ev.get("kind"), ev.get("label"),
                    json.dumps(details, sort_keys=True, default=str),
                )
                named = (
                    ev.get("label") in members
                    or details.get("request") in members
                    or bool(set(details.get("requests") or []) & members)
                )
                if not named:
                    # column_verdict carries column INDICES, not tags —
                    # window it into the slab below (a member's record
                    # can hold an EARLIER slab's verdicts from its
                    # queued phase; those predate this slab's formation)
                    if ev.get("kind") == "column_verdict":
                        if key not in unnamed or abs_t < unnamed[key][0]:
                            unnamed[key] = (abs_t, ev)
                    # solo-retry CONTINUATION events (the nested solve
                    # of an ejected member: faults, health errors,
                    # aborted attempts, recovery restarts) don't name
                    # the request — window them into the member's
                    # ejection->terminal interval below instead of
                    # silently dropping the retry story
                    elif ev.get("kind") in _CONTINUATION_KINDS:
                        # per-attempt identity: the iteration joins the
                        # key (two columns' otherwise-identical typed
                        # errors are two attempts, not one event)
                        ckey = key + (ev.get("iteration"),)
                        if ckey not in continuation or abs_t < (
                            continuation[ckey][0]
                        ):
                            continuation[ckey] = (abs_t, ev)
                    continue
                if ev.get("kind") == "slab_formed" and not details.get(
                    "topped_up"
                ):
                    t_form = abs_t if t_form is None else min(t_form,
                                                              abs_t)
                if key not in seen or abs_t < seen[key][0]:
                    seen[key] = (abs_t, ev)
        for key, (abs_t, ev) in unnamed.items():
            if t_form is None or abs_t >= t_form - 1e-3:
                seen.setdefault(key, (abs_t, ev))
        t_last = _last_terminal(member_recs)
        for key, (abs_t, ev) in continuation.items():
            # inside the slab's life: formation .. last member terminal
            if t_form is not None and abs_t < t_form - 1e-3:
                continue
            if t_last is not None and abs_t > t_last + 1e-3:
                continue
            owner = _retry_window_owner(member_recs, abs_t)
            if owner is not None:
                ev = dict(ev)
                ev["details"] = dict(
                    ev.get("details") or {}, retry_of=owner
                )
            seen.setdefault(key, (abs_t, ev))
        events = sorted(seen.values(), key=lambda kv: kv[0])
        out.append((s["order"], member_recs, events))
    return out


#: Event kinds a member's solo retry (or its recovery ladder) emits
#: WITHOUT naming the request — joined into the slab view by their
#: ejection-window timing (`_retry_window_owner`). Pre-fix, a slab
#: whose every request was ejected rendered only the bare
#: formed/ejected/done skeleton: the whole retry story (the aborted
#: attempts, the faults that caused them, the checkpoint restarts)
#: was silently dropped as unnamed.
_CONTINUATION_KINDS = (
    "fault_injected", "health_error", "solve_aborted", "restart",
    "checkpoint_save", "checkpoint_restore", "sdc_detection",
    "sdc_rollback", "sdc_escalation",
)


def _last_terminal(member_recs):
    """Latest request_done/request_failed time across the members."""
    t_last = None
    for tag, rec in member_recs:
        t0 = rec.get("started_at") or 0.0
        for ev in rec.get("events") or []:
            if (
                ev.get("kind") in ("request_done", "request_failed")
                and ev.get("label") == tag
            ):
                at = t0 + (ev.get("t") or 0.0)
                t_last = at if t_last is None else max(t_last, at)
    return t_last


def _retry_window_owner(member_recs, abs_t):
    """The member whose ejection->terminal window contains ``abs_t``
    (windows are sequential — the verdict loop retries one ejected
    column at a time — so the nearest preceding ejection wins)."""
    best = None
    for tag, rec in member_recs:
        t0 = rec.get("started_at") or 0.0
        t_eject = None
        t_term = None
        for ev in rec.get("events") or []:
            details = ev.get("details") or {}
            at = t0 + (ev.get("t") or 0.0)
            if (
                ev.get("kind") == "column_ejected"
                and details.get("request") == tag
                and t_eject is None
            ):
                t_eject = at
            if (
                ev.get("kind") in ("request_done", "request_failed")
                and ev.get("label") == tag
            ):
                t_term = at
        if t_eject is None or abs_t < t_eject - 1e-3:
            continue
        if t_term is not None and abs_t > t_term + 1e-3:
            continue
        if best is None or t_eject > best[0]:
            best = (t_eject, tag)
    return best[1] if best is not None else None


def _service_timeline(recs) -> int:
    """--service: print one joined timeline per slab."""
    slabs = _service_slabs(recs)
    if not slabs:
        print(
            "patrace --service: no service-request records found "
            "(submit through SolveService with PA_METRICS_DIR set)",
            file=sys.stderr,
        )
        return 1
    for i, (members, member_recs, events) in enumerate(slabs):
        print(f"slab {i}: K={len(members)} requests: "
              + ", ".join(members))
        t0 = events[0][0] if events else 0.0
        for abs_t, ev in events:
            label = ev.get("label") or ""
            it = ev.get("iteration")
            at = f" it={it}" if it is not None else ""
            details = ev.get("details") or {}
            extra = ", ".join(
                f"{k}={v}" for k, v in sorted(details.items())
                if k not in ("message",)
            )
            print(
                f"    [{abs_t - t0:9.4f}s] {ev.get('kind')}"
                f"{':' + label if label else ''}{at}"
                + (f"  ({extra})" if extra else "")
            )
        outcomes = []
        for tag, rec in member_recs:
            if rec.get("status") == "raised":
                err = (rec.get("error") or {}).get("type", "error")
                outcomes.append(f"{tag} FAILED({err})")
            else:
                outcomes.append(
                    f"{tag} {rec.get('status') or 'done'}"
                    f"(it={rec.get('iterations')})"
                )
        print("  outcomes: " + "; ".join(outcomes))
    return 0


def _diff_static(full: bool) -> int:
    # CPU mesh setup — same pattern as tools/palint.py: the dev image
    # may pre-import jax on another platform, so update the config too
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ["JAX_ENABLE_X64"] = "true"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from partitionedarrays_jl_tpu.analysis import build_reports
    from partitionedarrays_jl_tpu.telemetry import reconcile

    cases, reports = build_reports(fast=not full, with_runtime=True)
    failed = False
    for name, case in sorted(cases.items()):
        comms = case.get("runtime_comms")
        rep = reports.get(name)
        if comms is None or rep is None:
            continue
        mismatches = reconcile(rep, comms)
        verdict = "OK" if not mismatches else "MISMATCH"
        print(
            f"  {name:26s} it={comms.get('iterations', '?'):>3} "
            f"static-vs-measured: {verdict}"
        )
        for m in mismatches:
            print(f"      {m}")
            failed = True
    print("patrace --diff-static:", "FAILED" if failed else "OK")
    return 1 if failed else 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", help="record directory (default: PA_METRICS_DIR)")
    ap.add_argument("--last", action="store_true",
                    help="summarize the newest record")
    ap.add_argument("--list", action="store_true", dest="list_",
                    help="list persisted records")
    ap.add_argument("--json", action="store_true",
                    help="with --last: dump the raw record JSON")
    ap.add_argument("--trace", metavar="OUT",
                    help="write newest --n records as Chrome-trace JSON")
    ap.add_argument("--n", type=int, default=8,
                    help="record count for --trace (default 8)")
    ap.add_argument("--phases", metavar="PROFILE",
                    help="PhaseProfile JSON to merge into --trace "
                         "(or render standalone)")
    ap.add_argument("--iterations", type=int, default=4,
                    help="synthetic iterations for --phases spans "
                         "(default 4)")
    ap.add_argument("--diff-static", action="store_true",
                    help="probe-solve the lowering matrix and reconcile "
                         "measured comms against the lowered programs")
    ap.add_argument("--full", action="store_true",
                    help="with --diff-static: all 15 matrix cases")
    ap.add_argument("--service", action="store_true",
                    help="join service-request records into per-slab "
                         "timelines")
    args = ap.parse_args(argv)

    if args.diff_static:
        return _diff_static(args.full)

    phase_profile = None
    if args.phases:
        from partitionedarrays_jl_tpu.telemetry import (
            PHASE_SCHEMA_VERSION,
            render_phase_profile,
        )

        phase_profile = json.load(open(args.phases))
        if phase_profile.get("phase_schema_version") != (
            PHASE_SCHEMA_VERSION
        ):
            print(
                f"patrace: {args.phases} has phase_schema_version "
                f"{phase_profile.get('phase_schema_version')!r} (this "
                f"tool speaks {PHASE_SCHEMA_VERSION})",
                file=sys.stderr,
            )
            return 2
        if not args.trace:
            # render the table, then fall through to any OTHER
            # requested leg (--service/--last/--list must still run)
            print(render_phase_profile(phase_profile))
            if not (args.last or args.list_ or args.service):
                return 0

    if not (args.last or args.list_ or args.trace or args.service):
        ap.print_help()
        return 2

    if args.trace and phase_profile is not None and not (
        args.dir or os.environ.get("PA_METRICS_DIR")
    ):
        # phases-only timeline: no records required
        from partitionedarrays_jl_tpu.telemetry import (
            phase_trace_events,
            write_chrome_trace,
        )

        write_chrome_trace(
            args.trace,
            extra_events=phase_trace_events(
                phase_profile, iterations=args.iterations
            ),
        )
        print(f"wrote {args.trace} (phase profile only)")
        return 0

    d = _records_dir(args)
    if d is None:
        return 2
    recs = _load_all(d)
    if not recs:
        print(f"patrace: no records under {d}", file=sys.stderr)
        return 1

    if args.service:
        return _service_timeline(recs)

    if args.list_:
        for path, rec in recs:
            print(
                f"{os.path.basename(path)}  {rec.get('solver'):>20s}  "
                f"status={rec.get('status')}  it={rec.get('iterations')}  "
                f"events={len(rec.get('events') or [])}"
            )
    if args.last:
        path, rec = recs[-1]
        if args.json:
            print(json.dumps(rec, indent=1, sort_keys=True))
        else:
            _summarize(path, rec)
    if args.trace:
        from partitionedarrays_jl_tpu.telemetry import write_chrome_trace

        newest = [rec for _, rec in recs[-max(1, args.n):]]
        extra = None
        if phase_profile is not None:
            from partitionedarrays_jl_tpu.telemetry import (
                phase_trace_events,
            )

            extra = phase_trace_events(
                phase_profile, iterations=args.iterations
            )
        write_chrome_trace(args.trace, records=newest, extra_events=extra)
        merged = " + phase profile" if extra else ""
        print(f"wrote {args.trace} ({len(newest)} records{merged})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
