"""End-to-end solve cost on one real chip: multigrid-preconditioned CG
vs plain CG at 192³ (f32).

Methodology (docs/performance.md): per-iteration marginal cost by
differencing two compiled maxiter-pinned runs (each solve is one
dependency chain ending in host scalars), median of three rounds; the
iteration counts to tolerance come from real converged solves. The
product of the two is the honest derived solve time.
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax

    import partitionedarrays_jl_tpu as pa
    from partitionedarrays_jl_tpu.models import assemble_poisson
    from partitionedarrays_jl_tpu.parallel.tpu import (
        DeviceVector, TPUBackend, _b_on_cols_layout, device_matrix,
        make_cg_fn,
    )
    from partitionedarrays_jl_tpu.parallel.tpu_gmg import (
        _device_hierarchy, _gmg_operands, make_gmg_pcg_fn,
    )

    n = int(os.environ.get("PA_BENCH_N", "192"))
    # PA_GMG_PERIODIC=1 benches the TORUS problem instead (round-5
    # directive 4's done-criterion: periodic V-cycle transfer cost at
    # the equal-box level — the Galerkin levels must take stencil_fast
    # with the wrapped-segment mask, not the assembled-matrix path)
    periodic = os.environ.get("PA_GMG_PERIODIC", "0") == "1"
    backend = TPUBackend(devices=jax.devices()[:1])

    def driver(parts):
        if periodic:
            from partitionedarrays_jl_tpu.models import (
                assemble_poisson_periodic,
            )

            Ah, bh, x_exact, x0 = assemble_poisson_periodic(
                parts, (n, n, n), shift=1.0, dtype=np.float32
            )
            # 1/16 scaling like the Dirichlet leg: bounded under the
            # maxiter-pinned timing chains
            Ah.values = pa.map_parts(
                lambda M: pa.CSRMatrix(
                    M.indptr, M.indices,
                    (M.data / 16.0).astype(np.float32), M.shape,
                ),
                Ah.values,
            )
            Ah.invalidate_blocks()
            bh = pa.PVector(
                pa.map_parts(
                    lambda v: (np.asarray(v) / 16.0).astype(np.float32),
                    bh.values,
                ),
                bh.rows,
            )
            t0 = time.time()
            h = pa.gmg_hierarchy(
                parts, Ah, (n, n, n), coarse_threshold=500
            )
            return Ah, bh, h, time.time() - t0

        A, b, x_exact, x0 = assemble_poisson(parts, (n, n, n))

        def cast(M):
            return pa.CSRMatrix(
                M.indptr, M.indices, (M.data / 16.0).astype(np.float32), M.shape
            )

        A.values = pa.map_parts(cast, A.values)
        A.invalidate_blocks()
        b = A @ pa.PVector(
            pa.map_parts(
                lambda v: np.asarray(v, np.float32), x_exact.values
            ),
            x_exact.rows,
        )
        Ah, bh = pa.decouple_dirichlet(A, b)
        t0 = time.time()
        h = pa.gmg_hierarchy(parts, Ah, (n, n, n), coarse_threshold=500)
        t_build = time.time() - t0
        return Ah, bh, h, t_build

    print("building operator + hierarchy ...", flush=True)
    Ah, bh, h, t_build = pa.prun(driver, backend, (1, 1, 1))
    print(f"hierarchy: {len(h.levels)} levels, build {t_build:.1f}s", flush=True)

    dA = device_matrix(Ah, backend)
    db = _b_on_cols_layout(bh, dA)
    x0 = pa.PVector.full(0.0, Ah.cols, dtype=np.float32)
    dx0 = DeviceVector.from_pvector(x0, backend, dA.col_layout)

    # converged iteration counts (real solves, honest residuals)
    xg, ig = pa.pcg(Ah, bh, minv=h, tol=1e-5)
    xc, ic = pa.cg(Ah, bh, tol=1e-5)
    print(
        f"iterations to 1e-5: pcg+gmg={ig['iterations']} "
        f"plain cg={ic['iterations']}", flush=True,
    )

    # marginal per-iteration costs
    def measure(make, k0, k1):
        solves = {k: make(k) for k in (k0, k1)}
        for s in solves.values():
            _ = [float(v) for v in s(db.data, dx0.data)[1:4]]

        def run_k(k):
            ts = []
            for _ in range(5):
                t0 = time.perf_counter()
                out = solves[k](db.data, dx0.data)
                _ = float(out[1])
                ts.append(time.perf_counter() - t0)
            return float(np.median(ts))

        per = []
        for _ in range(3):
            per.append((run_k(k1) - run_k(k0)) / (k1 - k0))
        return float(np.median(per))

    dt_gmg = measure(
        lambda k: make_gmg_pcg_fn(h, backend, tol=0.0, maxiter=k), 10, 60
    )

    def mk_cg(k):
        fn = make_cg_fn(dA, tol=0.0, maxiter=k)
        return lambda b_, x_: fn(b_, x_, None)

    dt_cg = measure(mk_cg, 100, 500)
    t_gmg = ig["iterations"] * dt_gmg
    t_cg = ic["iterations"] * dt_cg
    print(
        f"per-iteration: pcg+gmg={dt_gmg * 1e3:.2f} ms, plain cg="
        f"{dt_cg * 1e3:.3f} ms"
    )
    print(
        f"derived solve time to 1e-5 at {n}^3: pcg+gmg="
        f"{t_gmg * 1e3:.1f} ms, plain cg={t_cg * 1e3:.1f} ms, "
        f"speedup={t_cg / t_gmg:.1f}x"
    )

    # artifact: per-mode record incl. which transfer path each level
    # staged (the periodic claim is empty unless the Galerkin levels
    # really took the stencil path)
    import json

    dh = _device_hierarchy(h, backend)
    rec = {
        "n": n,
        "mode": "periodic-torus" if periodic else "dirichlet",
        "levels": len(h.levels),
        "transfer_paths": [
            (
                f"stencil[{len(l['stencil'])}]"
                if "stencil" in l
                else ("structured-S" if "dS" in l else "assembled")
            )
            for l in dh["levels"]
        ],
        "iterations_pcg_gmg": ig["iterations"],
        "iterations_cg": ic["iterations"],
        "gmg_ms_per_it": round(dt_gmg * 1e3, 3),
        "cg_ms_per_it": round(dt_cg * 1e3, 4),
        "derived_speedup": round(t_cg / max(t_gmg, 1e-12), 2),
    }
    out_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "GMG_BENCH.json",
    )
    # merge per mode so the periodic and dirichlet records coexist
    from partitionedarrays_jl_tpu.telemetry import artifacts

    try:
        with open(out_path) as f:
            all_rec = json.load(f)
    except Exception:
        all_rec = {}
    all_rec[rec["mode"]] = rec
    # the envelope may predate this run (merged artifact): refresh the
    # fields that describe THIS write, keep the per-mode records
    all_rec.pop("platform", None)
    all_rec.pop("pa_env", None)
    artifacts.write(out_path, all_rec, tool="bench_gmg", echo=False)
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
