"""1e8-DOF end-to-end scale check (the reference's large-assembly config):
assemble a 464^3 3-D Poisson operator on host, lower it, and compare the
compiled SpMV against the f32 host oracle. Run on a real chip with no
extra env (first compile is slow); shrink with PA_SCALE_N for smoke runs.

    python tools/scale_check.py            # 464^3 = 99.9M DOFs
    PA_SCALE_N=192 python tools/scale_check.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax

    import partitionedarrays_jl_tpu as pa
    from partitionedarrays_jl_tpu.models import assemble_poisson
    from partitionedarrays_jl_tpu.parallel.tpu import (
        DeviceVector,
        TPUBackend,
        device_matrix,
        make_spmv_fn,
    )

    n = int(os.environ.get("PA_SCALE_N", "464"))
    backend = TPUBackend(devices=jax.devices()[:1])

    def driver(parts):
        t0 = time.perf_counter()
        A, b, xe, x0 = assemble_poisson(parts, (n, n, n))
        t1 = time.perf_counter()
        print(f"assembly {n}^3 = {n**3/1e6:.1f}M DOFs: {t1-t0:.1f}s", flush=True)
        A.values = pa.map_parts(
            lambda M: pa.CSRMatrix(
                M.indptr, M.indices, M.data.astype(np.float32), M.shape
            ),
            A.values,
        )
        A.invalidate_blocks()
        xe.values = pa.map_parts(lambda v: np.asarray(v, np.float32), xe.values)
        host = pa.gather_pvector(A @ xe)
        t2 = time.perf_counter()
        print(f"host oracle SpMV: {t2-t1:.1f}s", flush=True)
        dA = device_matrix(A, backend)
        t3 = time.perf_counter()
        print(
            f"device lowering: {t3-t2:.1f}s mode={dA.dia_mode} "
            f"padded={dA.pallas_plan is not None}",
            flush=True,
        )
        dx = DeviceVector.from_pvector(xe, backend, dA.col_layout)
        y = make_spmv_fn(dA)(dx.data)
        got = pa.gather_pvector(
            DeviceVector(y, A.rows, dA.row_layout, backend).to_pvector()
        )
        t4 = time.perf_counter()
        print(f"compiled SpMV: {t4-t3:.1f}s (incl. compile+transfer)", flush=True)
        err = np.max(np.abs(host - got)) / np.max(np.abs(host))
        print(f"rel err vs host oracle: {err:.2e}", flush=True)
        assert err < 1e-5
        return True

    pa.prun(driver, backend, (1, 1, 1))
    print("scale check OK", flush=True)


if __name__ == "__main__":
    main()
