#!/usr/bin/env python
"""pareg — the perf-trajectory ledger and regression sentinel.

The committed ``*_BENCH.json`` artifacts are point-in-time snapshots;
`telemetry.ledger` folds them into ONE ``PERF_LEDGER.json`` of
per-metric series and validates any artifact — committed or fresh —
against its recorded band and its last-known-good point. This tool is
the operator console and the CI gate:

* ``--check``            validate the WHOLE committed set: every
                         artifact's envelope, band arithmetic, and
                         device gates, plus ledger coverage and
                         staleness. Exits nonzero on any failure —
                         the tier-1 smoke (tests/test_pareg.py).
* ``--check PATH [...]`` validate specific artifact files (a fresh
                         bench output before committing it); each is
                         also compared against the committed ledger's
                         last point when its name is ledger-known.
* ``--update``           rebuild/extend ``PERF_LEDGER.json`` from the
                         committed artifacts (through the shared
                         `telemetry.artifacts` envelope writer);
                         ``--dry-run`` prints without writing.
* ``--list``             render the ledger's series table.

Usage:
    python tools/pareg.py --check
    python tools/pareg.py --check /tmp/fresh_SCALE_BENCH.json
    python tools/pareg.py --update
    python tools/pareg.py --list
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _load_ledger():
    from partitionedarrays_jl_tpu.telemetry import ledger

    path = os.path.join(REPO, ledger.LEDGER_NAME)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def _check(paths) -> int:
    from partitionedarrays_jl_tpu.telemetry import ledger

    failures = []
    if paths:
        led = _load_ledger()
        for path in paths:
            with open(path, encoding="utf-8") as f:
                rec = json.load(f)
            failures.extend(
                ledger.check_artifact(
                    os.path.basename(path), rec, ledger=led
                )
            )
    else:
        failures = ledger.check_repo(REPO)
    for f in failures:
        print(f"pareg --check FAILURE: {f}", file=sys.stderr)
    n = len(ledger.artifact_paths(REPO)) if not paths else len(paths)
    print(
        f"pareg --check: {'FAILED' if failures else 'OK'} "
        f"({n} artifact(s), {len(failures)} failure(s))"
    )
    return 1 if failures else 0


def _update(dry_run: bool) -> int:
    from partitionedarrays_jl_tpu.telemetry import artifacts, ledger

    prev = _load_ledger()
    led = (
        ledger.update_ledger(prev, REPO)
        if prev and prev.get("ledger_schema_version")
        == ledger.LEDGER_SCHEMA_VERSION
        else ledger.build_ledger(REPO)
    )
    artifacts.write(
        os.path.join(REPO, ledger.LEDGER_NAME), led, tool="pareg",
        dry_run=dry_run,
    )
    print(
        f"ledger: {len(led['artifacts'])} artifacts, "
        f"{len(led['series'])} metric series"
    )
    return 0


def _list() -> int:
    led = _load_ledger()
    if led is None:
        print("pareg: no committed PERF_LEDGER.json — run --update",
              file=sys.stderr)
        return 1
    print(
        f"PERF_LEDGER.json (schema {led.get('ledger_schema_version')}): "
        f"{len(led.get('artifacts') or {})} artifacts"
    )
    for key, points in sorted((led.get("series") or {}).items()):
        last = points[-1]
        band = (
            f" band=[{last['lo']}, {last['hi']}] ({last['kind']})"
            if last.get("lo") is not None or last.get("hi") is not None
            else ""
        )
        verdict = (
            "in-band" if last.get("in_band")
            else "OUT" if last.get("in_band") is False
            else "unmeasured" if last.get("value") is None
            else "unbanded"
        )
        print(
            f"  {key:58s} {len(points)} pt "
            f"last={last.get('value')}{band} [{verdict}]"
        )
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", nargs="*", metavar="PATH",
                    help="validate artifacts (no PATH = whole "
                         "committed set + ledger)")
    ap.add_argument("--update", action="store_true",
                    help="rebuild/extend PERF_LEDGER.json")
    ap.add_argument("--dry-run", action="store_true",
                    help="with --update: print instead of writing")
    ap.add_argument("--list", action="store_true", dest="list_",
                    help="render the committed ledger")
    args = ap.parse_args(argv)

    if args.update:
        return _update(args.dry_run)
    if args.list_:
        return _list()
    if args.check is not None:
        return _check(args.check)
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
