#!/usr/bin/env python
"""pamon — live service observability: metric snapshots, SLO
attainment, and the measured throughput model.

The operator console of the `telemetry.registry` metrics plane
(docs/observability.md has the metric catalog). Data sources:

* in-process — ``--check`` / ``--demo`` run a small solve service and
  render its live registry (the tier-1 smoke path);
* a snapshot file — ``--snapshot FILE`` renders a registry export
  (``telemetry.registry().to_json()`` written by your process, e.g.
  `tools/paserve.py --metrics-json`); ``--watch`` re-reads it every
  ``--interval`` seconds and shows histogram deltas since the last
  poll;
* the committed model — ``--model [PATH]`` renders
  ``THROUGHPUT_MODEL.json`` (default: the repo's committed artifact),
  the online-measured per-RHS curve that feeds adaptive K;
* a live fleet — ``--fleet FLEET_DIR`` renders one row per gate
  replica (lease state/age, queue depth, residency, and the
  admitted/shed/forwarded/adopted/lease_missed counters read from
  each replica's ``/metrics.json``); ``--watch`` polls and shows
  per-replica deltas.

Output modes: the default table, ``--prom`` (Prometheus text
exposition), ``--json`` (the raw snapshot), ``--slo`` (deadline
attainment per tolerance class).

Usage:
    python tools/pamon.py --check                  # tier-1 smoke
    python tools/pamon.py --demo --slo
    python tools/pamon.py --snapshot metrics.json --watch --interval 2
    python tools/pamon.py --model --json
    python tools/pamon.py --snapshot metrics.json --prom
    python tools/pamon.py --fleet /tmp/fleet --watch --interval 2
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------


def _hist_line(name, snap):
    from partitionedarrays_jl_tpu.telemetry import LatencyHistogram

    h = LatencyHistogram.from_snapshot(snap)
    if h.total == 0:
        return f"  {name:32s} count=0"
    return (
        f"  {name:32s} count={h.total:<6d} mean={h.mean():.6f}s "
        f"p50<={h.quantile(0.5):.6f}s p90<={h.quantile(0.9):.6f}s "
        f"p99<={h.quantile(0.99):.6f}s max={h.max:.6f}s"
    )


def render_snapshot(snap, prev=None):
    """The default table: counters, gauges, histogram summaries (with
    deltas against ``prev`` in watch mode)."""
    from partitionedarrays_jl_tpu.telemetry import LatencyHistogram

    lines = []
    counters = snap.get("counters") or {}
    if counters:
        lines.append("counters:")
        prev_c = (prev or {}).get("counters") or {}
        for name, v in sorted(counters.items()):
            d = v - prev_c.get(name, 0)
            delta = f"  (+{d})" if prev is not None and d else ""
            lines.append(f"  {name:32s} {v}{delta}")
    gauges = snap.get("gauges") or {}
    if gauges:
        lines.append("gauges:")
        for name, v in sorted(gauges.items()):
            lines.append(f"  {name:32s} {v:g}")
    hists = snap.get("histograms") or {}
    if hists:
        lines.append("histograms (quantiles are bucket upper edges):")
        prev_h = (prev or {}).get("histograms") or {}
        for name, hsnap in sorted(hists.items()):
            lines.append(_hist_line(name, hsnap))
            if prev is not None and name in prev_h:
                d = LatencyHistogram.from_snapshot(hsnap).delta(
                    prev_h[name]
                )
                if d["count"]:
                    lines.append(
                        f"  {'':32s} +{d['count']} since last poll "
                        f"(+{d['sum']:.6f}s)"
                    )
    return "\n".join(lines) if lines else "(registry empty)"


def render_gate(snap, prev=None):
    """The front-door view (round 14 — pagate): tenant residency
    (resident/evicted, footprint vs budget) and per-SLO-class
    attainment with deltas against ``prev`` in watch mode. Pure
    rendering over the existing snapshot — the gate collects nothing
    new for this view."""
    counters = snap.get("counters") or {}
    gauges = snap.get("gauges") or {}
    if not any(k.startswith("gate.") for k in
               list(counters) + list(gauges)):
        return ""
    lines = ["front door (pagate):"]
    budget = gauges.get("gate.mem_budget_bytes", 0)
    resident = gauges.get("gate.resident_bytes", 0)
    lines.append(
        f"  resident {resident:,.0f} B / budget "
        + (f"{budget:,.0f} B" if budget else "unbounded")
        + f"  queue_depth={gauges.get('gate.queue_depth', 0):g}"
        + f"  evictions={counters.get('gate.evictions', 0)}"
        + f"  page_ins={counters.get('gate.page_ins', 0)}"
    )
    tenants = {}
    for name, v in gauges.items():
        for field, prefix in (
            ("resident", "gate.tenant_resident{tenant="),
            ("footprint", "gate.tenant_footprint_bytes{tenant="),
        ):
            if name.startswith(prefix):
                tenant = name[len(prefix):].rstrip("}")
                tenants.setdefault(tenant, {})[field] = v
    for tenant in sorted(tenants):
        row = tenants[tenant]
        state = "resident" if row.get("resident") else "EVICTED"
        lines.append(
            f"  tenant {tenant:16s} {state:8s} "
            f"footprint={row.get('footprint', 0):,.0f} B"
        )
    classes = {}
    prev_c = (prev or {}).get("counters") or {}
    for name, v in counters.items():
        for field, prefix in (
            ("requests", "gate.slo.requests{slo_class="),
            ("hits", "gate.slo.hits{slo_class="),
            ("shed", "gate.shed{slo_class="),
        ):
            if name.startswith(prefix):
                cls = name[len(prefix):].rstrip("}")
                classes.setdefault(cls, {})[field] = v
                classes[cls][field + "_d"] = v - prev_c.get(name, 0)
    if classes:
        lines.append("  SLO classes (attainment = hits/requests):")
    for cls in sorted(classes):
        row = classes[cls]
        req, hit = row.get("requests", 0), row.get("hits", 0)
        rate = hit / req if req else 0.0
        line = (
            f"    class={cls:12s} requests={req:<5d} hits={hit:<5d} "
            f"shed={row.get('shed', 0):<5d} attainment={rate:.1%}"
        )
        if prev is not None and (
            row.get("requests_d") or row.get("shed_d")
        ):
            line += (
                f"  (+{row.get('requests_d', 0)} req, "
                f"+{row.get('hits_d', 0)} hit, "
                f"+{row.get('shed_d', 0)} shed since last poll)"
            )
        lines.append(line)
    return "\n".join(lines)


def _fleet_fetch(fleet_dir):
    """Per-replica rows for ``--fleet``: url + lease state from the
    fleet dir, ``/healthz`` + ``/metrics.json`` over HTTP. Never
    raises — a dead, unreachable, or lease-corrupt replica is a
    rendered state, not a crash."""
    import urllib.request

    from partitionedarrays_jl_tpu.frontdoor import fleet as _fleet

    fm = _fleet.FleetMap(fleet_dir)
    lease_s = _fleet.fleet_lease_s()
    rows = {}
    for r in fm.replicas():
        row = {
            "url": fm.url(r), "lease": "absent",
            "health": {}, "counters": {}, "gauges": {},
        }
        try:
            lease = fm.lease(r)
            if lease is not None:
                age = time.time() - float(lease.get("wall", 0.0))
                row["lease_age_s"] = age
                row["lease"] = (
                    "STALE" if age > 3 * lease_s else "live"
                )
        except _fleet.LeaseCorruptError:
            row["lease"] = "CORRUPT"
        if row["url"]:
            try:
                with urllib.request.urlopen(
                    row["url"] + "/healthz", timeout=2.0
                ) as resp:
                    row["health"] = json.loads(resp.read())
                with urllib.request.urlopen(
                    row["url"] + "/metrics.json", timeout=2.0
                ) as resp:
                    snap = json.loads(resp.read())
                row["counters"] = snap.get("counters") or {}
                row["gauges"] = snap.get("gauges") or {}
            except (OSError, ValueError):
                row["down"] = True
        else:
            row["down"] = True
        rows[r] = row
    return rows


def _fleet_row_vals(row):
    """The counted columns of one fleet row (summed over labels)."""
    c = row.get("counters") or {}

    def tot(name):
        return sum(
            v for k, v in c.items()
            if k == name or k.startswith(name + "{")
        )

    return {
        "admitted": tot("service.admitted"),
        "shed": tot("gate.shed"),
        "forwarded": tot("fleet.forwarded"),
        "adopted": tot("fleet.adopted"),
        "lease_missed": tot("fleet.lease_missed"),
    }


def render_fleet(rows, prev=None):
    """The fleet view (round 16 — pafleet): one row per replica —
    liveness, lease state/age, queue depth, tenant residency, and the
    admitted/shed/forwarded/adopted/lease_missed counters (summed over
    labels), with deltas against ``prev`` in watch mode. Pure
    rendering over each replica's own ``/metrics.json`` registry —
    the fleet collects nothing new for this view."""
    if not rows:
        return "(fleet dir has no replicas)"
    lines = ["gate fleet (pafleet):"]
    for r in sorted(rows):
        row = rows[r]
        lease = row["lease"]
        if "lease_age_s" in row:
            lease += f"({row['lease_age_s']:.1f}s)"
        if row.get("down"):
            lines.append(f"  {r:8s} DOWN lease={lease}")
            continue
        g = row.get("gauges") or {}
        depth = row.get("health", {}).get(
            "queue_depth", g.get("gate.queue_depth", 0)
        )
        resident = sum(
            1 for k, v in g.items()
            if k.startswith("gate.tenant_resident{") and v
        )
        vals = _fleet_row_vals(row)
        line = (
            f"  {r:8s} UP   lease={lease:14s} depth={depth:<4g} "
            f"resident={resident} "
            + " ".join(f"{k}={v}" for k, v in vals.items())
        )
        if prev is not None and r in prev and not prev[r].get("down"):
            pvals = _fleet_row_vals(prev[r])
            deltas = [
                f"+{vals[k] - pvals[k]} {k}"
                for k in vals if vals[k] != pvals[k]
            ]
            if deltas:
                line += "  (" + ", ".join(deltas) + " since last poll)"
        lines.append(line)
    return "\n".join(lines)


def render_conv(snap, prev=None):
    """The convergence-observatory view (round 17 — paspec): per-tenant
    predicted-vs-actual iteration forecast error (p50/p90 relative
    error bracketed from the `spec.iters_rel_error{tenant=…}` histogram
    buckets) plus the prediction/infeasibility/anomaly counters, with
    `--watch` deltas against ``prev``. Pure rendering over the existing
    snapshot."""
    from partitionedarrays_jl_tpu.telemetry import LatencyHistogram

    counters = snap.get("counters") or {}
    hists = snap.get("histograms") or {}
    conv = {
        name: hsnap for name, hsnap in hists.items()
        if name.startswith("spec.iters_rel_error{tenant=")
    }
    spec_counters = {
        name: v for name, v in counters.items()
        if name.startswith("spec.")
    }
    if not conv and not spec_counters:
        return ""
    lines = ["convergence observatory (paspec):"]
    lines.append(
        "  predictions={}  infeasible={}".format(
            counters.get("spec.predictions", 0),
            counters.get("spec.infeasible", 0),
        )
        + "".join(
            f"  anomalies[{n.split('kind=', 1)[1].rstrip('}')}]={v}"
            for n, v in sorted(counters.items())
            if n.startswith("spec.anomalies{")
        )
    )
    if conv:
        lines.append(
            "  forecast error |predicted-actual|/actual "
            "(quantiles are bucket upper edges):"
        )
    prev_h = (prev or {}).get("histograms") or {}
    for name, hsnap in sorted(conv.items()):
        tenant = name.split("tenant=", 1)[1].rstrip("}")
        h = LatencyHistogram.from_snapshot(hsnap)
        if h.total == 0:
            lines.append(f"    tenant {tenant:16s} count=0")
            continue
        line = (
            f"    tenant {tenant:16s} count={h.total:<5d} "
            f"p50<={h.quantile(0.5):.3g} p90<={h.quantile(0.9):.3g} "
            f"mean={h.mean():.3g}"
        )
        if prev is not None and name in prev_h:
            d = h.delta(prev_h[name])
            if d["count"]:
                line += f"  (+{d['count']} since last poll)"
        lines.append(line)
    return "\n".join(lines)


def render_slo(snap):
    """Deadline attainment per tolerance class + the slack
    distribution."""
    counters = snap.get("counters") or {}
    classes = {}
    for name, v in counters.items():
        if name.startswith("service.slo.requests{"):
            cls = name.split("tol_class=", 1)[1].rstrip("}")
            classes.setdefault(cls, {})["requests"] = v
        elif name.startswith("service.slo.hits{"):
            cls = name.split("tol_class=", 1)[1].rstrip("}")
            classes.setdefault(cls, {})["hits"] = v
    lines = ["SLO attainment (deadline-carrying requests):"]
    if not classes:
        lines.append("  (no deadline-carrying requests observed)")
    for cls in sorted(classes):
        req = classes[cls].get("requests", 0)
        hit = classes[cls].get("hits", 0)
        rate = hit / req if req else 0.0
        lines.append(
            f"  tol_class={cls:8s} requests={req:<5d} hits={hit:<5d} "
            f"attainment={rate:.1%}"
        )
    slack = (snap.get("histograms") or {}).get("service.deadline_slack_s")
    if slack:
        lines.append(_hist_line("service.deadline_slack_s", slack))
    return "\n".join(lines)


def render_model(rec):
    """The measured per-RHS throughput table (the adaptive-K input)."""
    lines = [
        f"throughput model (schema {rec.get('throughput_schema_version')}"
        f", ewma_alpha={rec.get('ewma_alpha')}, "
        f"platform={rec.get('platform', '?')}):"
    ]
    entries = rec.get("entries") or []
    if not entries:
        lines.append("  (no measured entries)")
    groups = {}
    for e in entries:
        groups.setdefault((e["fingerprint"], e["dtype"]), []).append(e)
    for (fp, dt), es in sorted(groups.items()):
        lines.append(f"  operator {fp} [{dt}]:")
        base = next(
            (e["per_rhs_s_per_it"] for e in es if e["K"] == 1), None
        )
        for e in sorted(es, key=lambda e: e["K"]):
            gain = (
                f"  per-RHS x{base / e['per_rhs_s_per_it']:.2f} vs K=1"
                if base
                else ""
            )
            lines.append(
                f"    K={e['K']:<3d} s_per_it={e['s_per_it']:.6f} "
                f"per_rhs={e['per_rhs_s_per_it']:.6f} "
                f"samples={e['samples']}{gain}"
            )
    ref = rec.get("reference_curve")
    if ref:
        lines.append(
            f"  reference curve ({ref.get('source')}, n={ref.get('n')}, "
            f"device record):"
        )
        for k, v in sorted(
            ref.get("per_rhs_s_per_it", {}).items(), key=lambda t: int(t[0])
        ):
            sp = ref.get("per_rhs_speedup_vs_k1", {}).get(k)
            lines.append(
                f"    K={k:<3s} per_rhs={v:.6f}"
                + (f"  x{sp:.2f} vs K=1" if sp else "")
            )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# the in-process demo (also the --check smoke)
# ---------------------------------------------------------------------------


def _run_demo():
    """A small drained service: every metric family in the catalog gets
    exercised — admission (+1 rejection), coalescing, a deadline class,
    completion — against the sequential backend."""
    import partitionedarrays_jl_tpu as pa
    from partitionedarrays_jl_tpu.models import assemble_poisson
    from partitionedarrays_jl_tpu.service import (
        AdmissionRejected,
        SolveService,
    )

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        svc = SolveService(A, kmax=4, queue_depth=4)
        handles = [
            svc.submit(b, x0=x0, tol=1e-9, deadline=3600.0,
                       tag=f"demo-{i}")
            for i in range(4)
        ]
        try:  # the 5th overflows the bound: typed backpressure
            svc.submit(b, x0=x0, tol=1e-9, tag="demo-over")
        except AdmissionRejected:
            pass
        profile = svc.queue_profile()
        svc.drain()
        for h in handles:
            h.result()
        # second wave: the operator is now spectrally measured, so
        # these requests carry forecasts — the --conv view's feed
        h2 = svc.submit(b, x0=x0, tol=1e-9, deadline=3600.0,
                        tag="demo-forecast")
        svc.drain()
        h2.result()
        return svc.fingerprint, profile, dict(svc.stats)

    return pa.prun(driver, pa.sequential, (2, 2))


def _check() -> int:
    """--check: run the demo, assert the metrics plane saw it, render
    every surface once. Exit nonzero on any broken invariant."""
    from partitionedarrays_jl_tpu import telemetry

    reg = telemetry.registry()
    base = reg.snapshot()

    def c(name):
        return (base.get("counters") or {}).get(name, 0)

    before = {
        k: c(k)
        for k in ("service.admitted",
                  "service.rejected{reason=queue_full}",
                  "service.completed")
    }
    fingerprint, profile, stats = _run_demo()
    snap = reg.snapshot()
    failures = []

    def expect(cond, msg):
        if not cond:
            failures.append(msg)

    counters = snap["counters"]
    expect(
        counters.get("service.admitted", 0) - before["service.admitted"]
        == 5,
        "admitted counter must advance by the demo's 5 admissions "
        "(4 first-wave + 1 forecast-wave)",
    )
    expect(
        counters.get("service.rejected{reason=queue_full}", 0)
        - before["service.rejected{reason=queue_full}"] == 1,
        "queue_full-reason rejected counter must advance by the "
        "demo's 1 overflow",
    )
    expect(
        counters.get("service.completed", 0)
        - before["service.completed"] == 5,
        "completed counter must advance by 5",
    )
    # the convergence observatory saw the forecast wave: a prediction
    # was stamped, the realized-error histogram observed it, and the
    # --conv view renders it (metrics declared in-CATALOG)
    from partitionedarrays_jl_tpu.telemetry import CATALOG

    for name in ("spec.predictions", "spec.infeasible",
                 "spec.anomalies", "spec.iters_rel_error"):
        expect(name in CATALOG, f"{name} must be declared in CATALOG")
    expect(counters.get("spec.predictions", 0) >= 1,
           "the measured-operator wave must stamp a forecast")
    conv_h = [
        k for k in snap["histograms"]
        if k.startswith("spec.iters_rel_error{tenant=")
    ]
    expect(
        conv_h and snap["histograms"][conv_h[0]].get("count", 0) >= 1,
        "forecast realized-error histogram must have observations",
    )
    conv = render_conv(snap)
    expect("convergence observatory" in conv,
           "--conv view must render the observatory table")
    print(conv)
    hists = snap["histograms"]
    for name in ("service.queue_wait_s", "service.total_s",
                 "service.solve_s", "service.slab_wait_s"):
        expect(
            (hists.get(name) or {}).get("count", 0) > 0,
            f"histogram {name} must have observations",
        )
    expect(
        any(k.startswith("service.slo.requests{") for k in counters),
        "SLO accounting must tick for the deadline-carrying demo class",
    )
    expect(profile == [] or isinstance(profile, list),
           "queue_profile must return a list")
    model = telemetry.throughput_model()
    curve = model.curve(fingerprint, "float64")
    curve.update(model.curve(fingerprint, "float32"))
    expect(
        bool(curve),
        "the throughput model must hold a measured entry for the demo "
        f"operator {fingerprint}",
    )
    # every export surface renders without raising
    print(render_snapshot(snap))
    print()
    print(render_slo(snap))
    print()
    prom = reg.to_prometheus()
    expect("pa_service_total_s_count" in prom,
           "prometheus export must expose the total-latency histogram")
    json.loads(reg.to_json())
    model_path = os.path.join(REPO, "THROUGHPUT_MODEL.json")
    if os.path.exists(model_path):
        rec = json.load(open(model_path))
        print(render_model(rec))
        expect(
            rec.get("throughput_schema_version")
            == telemetry.THROUGHPUT_SCHEMA_VERSION,
            "committed THROUGHPUT_MODEL.json schema mismatch",
        )
    for f in failures:
        print(f"pamon --check FAILURE: {f}", file=sys.stderr)
    print("pamon --check:", "FAILED" if failures else "OK")
    return 1 if failures else 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="in-process smoke: demo service + invariants")
    ap.add_argument("--demo", action="store_true",
                    help="run the demo service, then render")
    ap.add_argument("--snapshot", metavar="FILE",
                    help="render a registry snapshot JSON export")
    ap.add_argument("--model", nargs="?", const=os.path.join(
        REPO, "THROUGHPUT_MODEL.json"), metavar="PATH",
        help="render a THROUGHPUT_MODEL.json (default: committed)")
    ap.add_argument("--prom", action="store_true",
                    help="Prometheus text exposition format")
    ap.add_argument("--json", action="store_true", dest="json_",
                    help="raw snapshot JSON")
    ap.add_argument("--slo", action="store_true",
                    help="SLO attainment per tolerance class")
    ap.add_argument("--conv", action="store_true",
                    help="convergence observatory: per-tenant "
                         "predicted-vs-actual forecast error")
    ap.add_argument("--fleet", metavar="FLEET_DIR",
                    help="per-replica fleet view: lease state, depth, "
                         "admitted/shed/forwarded/adopted from each "
                         "replica's /metrics.json (--watch for deltas)")
    ap.add_argument("--watch", action="store_true",
                    help="with --snapshot: poll and show deltas")
    ap.add_argument("--interval", type=float, default=5.0,
                    help="watch poll seconds (default 5)")
    ap.add_argument("--iterations", type=int, default=0,
                    help="watch iterations (0 = until interrupted)")
    args = ap.parse_args(argv)

    if args.check:
        return _check()

    if args.fleet:
        prev = None
        i = 0
        while True:
            rows = _fleet_fetch(args.fleet)
            if args.watch:
                print(f"--- pamon fleet poll {i} ---")
            print(render_fleet(rows, prev=prev))
            if not args.watch:
                return 0
            prev = rows
            i += 1
            if args.iterations and i >= args.iterations:
                return 0
            time.sleep(args.interval)

    if args.model is not None and not (args.demo or args.snapshot):
        rec = json.load(open(args.model))
        if args.json_:
            print(json.dumps(rec, indent=1, sort_keys=True))
        else:
            print(render_model(rec))
        return 0

    snap = None
    if args.demo:
        from partitionedarrays_jl_tpu import telemetry

        _run_demo()
        reg = telemetry.registry()
        snap = reg.snapshot()
        if args.prom:
            print(reg.to_prometheus())
            return 0
    elif args.snapshot:
        if args.watch:
            prev = None
            i = 0
            while True:
                snap = json.load(open(args.snapshot))
                print(f"--- pamon watch poll {i} ---")
                print(render_snapshot(snap, prev=prev))
                gate = render_gate(snap, prev=prev)
                if gate:
                    print(gate)
                if args.conv:
                    conv = render_conv(snap, prev=prev)
                    print(conv or "(no forecast observations yet)")
                if args.slo:
                    print(render_slo(snap))
                prev = snap
                i += 1
                if args.iterations and i >= args.iterations:
                    return 0
                time.sleep(args.interval)
        snap = json.load(open(args.snapshot))
    else:
        ap.print_help()
        return 2

    if args.json_:
        print(json.dumps(snap, indent=1, sort_keys=True))
    elif args.prom:
        # re-render a file snapshot as prometheus text is not supported
        # (the registry object is needed); --demo --prom handled above
        print("pamon: --prom needs --demo (live registry)",
              file=sys.stderr)
        return 2
    else:
        print(render_snapshot(snap))
        gate = render_gate(snap)
        if gate:
            print(gate)
    if args.conv:
        conv = render_conv(snap)
        print(conv or "(no forecast observations yet)")
    if args.slo:
        print(render_slo(snap))
    if args.model is not None:
        print(render_model(json.load(open(args.model))))
    return 0


if __name__ == "__main__":
    sys.exit(main())
