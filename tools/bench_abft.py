"""ABFT clean-path A/B -> ABFT_BENCH.json.

The round-8 SDC tentpole's perf artifact: per-iteration cost of the
compiled CG body with the full in-graph defense ON (``PA_TPU_ABFT=1``
checksum lanes + the default 32-iteration true-residual audit) vs OFF,
on the streaming-DIA variable-coefficient operator. The acceptance
criterion is a <= 5% clean-path overhead at 320^3 on device — the
detection machinery rides EXISTING collectives (checksum lanes on the
dot all_gather, one extra slot per exchange round, the audit's operand
select on the loop's one SpMV call site), so the cost is the checksum
sweeps (two extra owned-region reductions + the w·x product) and the
1/32 audit stall-trips, not extra wire.

Also recorded: the HLO per-kind collective-count parity between the two
programs (the zero-extra-collectives claim, asserted at record time AND
re-checked against the committed artifact by tests/test_abft.py /
tests/test_doc_consistency.py).

Protocol: the fixed-trip compiled-CG marginal of bench.py
(`cg_marginal_s_per_it`): two maxiter legs, warmed, median-of-5,
differenced; tol=0 pins the trip count. ``--n`` overrides the size
list for smoke runs; ``--dry-run`` prints without committing. The
committed record names its platform — device-kind bands gate only
records measured on real TPUs.
"""
from __future__ import annotations

import importlib.util
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

#: Guard bands for the committed artifact. Keys match
#: ABFT_BENCH.json["bands"]; tests/test_doc_consistency.py asserts the
#: committed artifact and this table agree, and that device-kind bands
#: hold whenever the record was measured on a real TPU. The 320^3
#: ceiling of 1.05 IS the round-8 acceptance criterion.
ABFT_BANDS = {
    "clean_overhead_ratio_320": (0.90, 1.05, "device"),
    "clean_overhead_ratio_192": (0.90, 1.10, "device"),
}

METHODOLOGY = "v1-abft"

#: Device sizes (the acceptance pair). A non-TPU platform records its
#: own (smaller) sizes honestly under platform="cpu" — useful as a
#: structural canary, not as the acceptance measurement.
DEVICE_SIZES = (192, 320)
HOST_SIZES = (32, 48)


def _load_sibling(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           f"{name}.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "bench.py",
        ),
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench


def _collective_counts(fn, *args):
    txt = fn.jit_fn.lower(*args).as_text()
    return {
        k: len(re.findall(k, txt))
        for k in ("collective_permute", "all_gather", "all_reduce")
    }


def _parity_probe(pa, A, backend):
    """Lower the ABFT-on and -off programs for one small operator and
    record per-kind collective counts — the parity claim, measured.
    PA_TPU_BOX=0 on both sides so the A/B compares like exchange plans
    (ABFT itself pins the generic plan)."""
    from partitionedarrays_jl_tpu.parallel.tpu import (
        _matrix_operands, device_matrix, make_cg_fn,
    )

    out = {}
    old_box = os.environ.get("PA_TPU_BOX")
    os.environ["PA_TPU_BOX"] = "0"
    try:
        for label, abft in (("on", "1"), ("off", None)):
            if abft:
                os.environ["PA_TPU_ABFT"] = abft
            else:
                os.environ.pop("PA_TPU_ABFT", None)
            dA = device_matrix(A, backend)
            ops = _matrix_operands(dA)
            fn = make_cg_fn(dA, tol=1e-9, maxiter=50)
            db = np.zeros((dA.col_plan.layout.P, dA.col_plan.layout.W))
            out[label] = _collective_counts(fn, db, db, db, ops)
    finally:
        os.environ.pop("PA_TPU_ABFT", None)
        if old_box is None:
            os.environ.pop("PA_TPU_BOX", None)
        else:
            os.environ["PA_TPU_BOX"] = old_box
    return {
        "counts_on": out["on"],
        "counts_off": out["off"],
        "parity": out["on"] == out["off"],
    }


def main():
    import jax

    import partitionedarrays_jl_tpu as pa
    from partitionedarrays_jl_tpu.parallel.tpu import (
        TPUBackend, device_matrix,
    )

    bench = _load_bench()
    bench_mr = _load_sibling("bench_multirhs")

    argv = sys.argv[1:]
    dry = "--dry-run" in argv
    platform = jax.devices()[0].platform
    sizes = list(DEVICE_SIZES if platform == "tpu" else HOST_SIZES)
    if "--n" in argv:
        sizes = [int(argv[argv.index("--n") + 1])]
    backend = TPUBackend(devices=jax.devices()[:1])

    rows = []
    for n in sizes:
        A = pa.prun(
            lambda parts: bench_mr.assemble_varcoef_poisson(
                parts, (n, n, n), pa, np.float32
            ),
            backend, (1, 1, 1),
        )
        legs = {}
        for label, abft in (("off", None), ("on", "1")):
            if abft:
                os.environ["PA_TPU_ABFT"] = abft
            else:
                os.environ.pop("PA_TPU_ABFT", None)
            dA = device_matrix(A, backend)
            legs[label] = bench.cg_marginal_s_per_it(pa, dA, 40, 240)
        os.environ.pop("PA_TPU_ABFT", None)
        rows.append(
            {
                "n": n,
                "dofs": n ** 3,
                "abft_off_s_per_it": round(legs["off"], 9),
                "abft_on_s_per_it": round(legs["on"], 9),
                "overhead_ratio": round(legs["on"] / legs["off"], 4),
            }
        )
        print(f"[bench_abft] n={n}: {rows[-1]}", flush=True)

    # collective parity on a small MULTI-part fixture (a single-part
    # mesh has no collectives to count); 8 virtual devices on cpu, the
    # real chips on tpu. assemble_poisson handles multi-part ghost
    # discovery (the varcoef assembler is single-chip-only).
    from partitionedarrays_jl_tpu.models import assemble_poisson

    ndev = min(8, len(jax.devices()))
    pbackend = TPUBackend(devices=jax.devices()[:ndev])
    pgrid = (2, 2, 2) if ndev >= 8 else (ndev, 1, 1)
    Ap = pa.prun(
        lambda parts: assemble_poisson(parts, (16, 16, 16))[0],
        pbackend, pgrid,
    )
    parity = _parity_probe(pa, Ap, pbackend)
    assert parity["parity"], (
        "ABFT must not add collectives: " + json.dumps(parity)
    )

    by_n = {r["n"]: r for r in rows}
    bands = {}
    for key, (lo, hi, kind) in ABFT_BANDS.items():
        n = int(key.rsplit("_", 1)[-1])
        row = by_n.get(n)
        measured = row["overhead_ratio"] if row else None
        bands[key] = {
            "lo": lo,
            "hi": hi,
            "kind": kind,
            "measured": measured,
            "in_band": (
                (lo <= measured <= hi) if measured is not None else None
            ),
        }
    rec = {
        "methodology": METHODOLOGY,
        "protocol": (
            "fixed-trip compiled-CG marginal (bench.py "
            "cg_marginal_s_per_it): two maxiter legs, warmed, "
            "median-of-5, differenced; tol=0 pins the trip count; "
            "ABFT leg = PA_TPU_ABFT=1 with the default 32-iteration "
            "audit (its stall trips are part of the measured cost)"
        ),
        "platform": platform,
        "dtype": "float32",
        "operator": (
            "variable-coefficient 7-point diffusion (streaming-DIA "
            "lowering — the large-N value-streaming operator the "
            "checksum sweeps compete with)"
        ),
        "sizes": rows,
        "collective_parity": parity,
        "bands": bands,
        "bands_ok_device": (
            all(
                b["in_band"]
                for b in bands.values()
                if b["kind"] == "device" and b["measured"] is not None
            )
            if platform == "tpu"
            else None
        ),
        "note": (
            "device-kind bands gate records measured on real TPUs; a "
            "cpu-platform record is the structural canary (parity + "
            "protocol + artifact wiring), not the acceptance number. "
            "XLA-CPU copies while-loop carries (incl. the R*3*W "
            "rollback ring) every trip instead of aliasing them, so "
            "cpu overhead ratios run far above the device target and "
            "vary with host load"
        ),
    }
    from partitionedarrays_jl_tpu.telemetry import artifacts

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "ABFT_BENCH.json",
    )
    artifacts.write(path, rec, tool="bench_abft", dry_run=dry)


if __name__ == "__main__":
    main()
