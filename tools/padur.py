#!/usr/bin/env python
"""padur — crash-durability drills for the front door.

The proof harness of `partitionedarrays_jl_tpu.frontdoor.journal`: a
gate that journals every request lifecycle transition ahead of the
client ack must survive its own death — kill -9 the serving process
mid-slab, restart against the same journal + checkpoint directories,
and every admitted request either completes BITWISE equal to its solo
solve or fails typed: zero lost, zero duplicated (a retried
idempotency-key submit returns the original id and result).

Usage:
    python tools/padur.py serve --journal-dir D [--checkpoint-dir C]
        [--port 0] [--url-file F] [--slab-delay 0.0] [--shed-depth N]
    python tools/padur.py --check          # tier-1 smoke (in-process)
    python tools/padur.py --drill          # full SIGKILL drill
                                           # (subprocess; -m slow)

``serve`` runs one demo Poisson tenant behind the HTTP gate with the
journal enabled, recovers any prior journal on startup, writes its URL
to ``--url-file``, and shuts down gracefully on SIGTERM/SIGINT
(drain-or-checkpoint — the `serve_until_signalled` exit-code contract:
0 after a clean signalled shutdown). ``--slab-delay`` stretches each
block solve so a drill can land SIGKILL mid-slab deterministically.

``--check`` is the fast in-process smoke wired into tier-1: journal
append/rotate/replay round-trip, one forced torn-tail recovery, one
mid-file corruption refusal, and a gate journal round trip with an
idempotency-key replay across a simulated crash.

``--drill`` is the real thing (registered under the ``slow`` pytest
marker): SIGKILL the serving subprocess mid-slab over HTTP, restart it
on the same journal, and assert the zero-lost / zero-duplicated /
bitwise-or-typed contract end to end.
"""
import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: The drill tenant: one Poisson operator (sequential backend — the
#: journal is host-side policy; the backend is whatever tenants run).
DRILL_GRID = (12, 12)
DRILL_TENANT = "poisson12"


def build_drill_gate(journal_dir, checkpoint_dir=None, shed_depth=4096,
                     slab_delay=0.0, start_workers=True):
    """One-tenant demo gate with the journal enabled; recovers any
    prior journal (tenants must be registered first — operators are
    code, not journal payload). ``slab_delay`` sleeps inside every
    block solve so a SIGKILL can land mid-slab."""
    import partitionedarrays_jl_tpu as pa
    from partitionedarrays_jl_tpu.frontdoor import Gate
    from partitionedarrays_jl_tpu.models import assemble_poisson

    if checkpoint_dir is None:
        checkpoint_dir = os.path.join(journal_dir, "svc-ckpt")
    A, b, xe, x0 = pa.prun(
        lambda parts: assemble_poisson(parts, DRILL_GRID),
        pa.sequential, (2, 2),
    )
    gate = Gate(
        journal_dir=journal_dir, checkpoint_dir=checkpoint_dir,
        shed_watermark=shed_depth, start_workers=start_workers,
    )
    if slab_delay > 0.0:
        _install_slab_delay(gate, float(slab_delay))
    gate.register(DRILL_TENANT, A, kmax=4)
    summary = gate.recover()
    return gate, (A, b, xe, x0), summary


def _install_slab_delay(gate, delay: float) -> None:
    """Chain onto the registry's page-in hook: every service built for
    a tenant sleeps ``delay`` inside `_block_solve` — the drill's
    window for landing SIGKILL mid-slab."""
    prev = gate.registry.on_page_in

    def hook(name, tenant):
        if prev is not None:
            prev(name, tenant)
        svc = tenant.svc
        if svc is None or getattr(svc, "_padur_delayed", False):
            return
        orig = svc._block_solve

        def slow_block_solve(*args, **kwargs):
            time.sleep(delay)
            return orig(*args, **kwargs)

        svc._block_solve = slow_block_solve
        svc._padur_delayed = True

    gate.registry.on_page_in = hook


def _drill_rhs(n, i):
    import numpy as np

    rng = np.random.default_rng(4000 + i)
    return rng.standard_normal(n)


# ---------------------------------------------------------------------------
# serve
# ---------------------------------------------------------------------------


def cmd_serve(args) -> int:
    from partitionedarrays_jl_tpu.frontdoor import (
        serve_gate,
        serve_until_signalled,
    )

    gate, _sys, summary = build_drill_gate(
        args.journal_dir, checkpoint_dir=args.checkpoint_dir,
        shed_depth=args.shed_depth, slab_delay=args.slab_delay,
    )
    srv = serve_gate(gate, host=args.host, port=args.port)
    if args.url_file:
        tmp = args.url_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(srv.url)
        os.replace(tmp, args.url_file)
    print(
        f"padur: serving {DRILL_TENANT} at {srv.url} "
        f"(journal={args.journal_dir}, recovered={summary})",
        flush=True,
    )
    rc = serve_until_signalled(srv, drain=args.drain)
    ckpt = gate.registry._tenants[DRILL_TENANT]
    print(
        "padur: shutdown "
        f"({'drain' if args.drain else 'checkpoint'}) rc={rc} "
        f"pending={ckpt.svc.pending() if ckpt.svc else 0}",
        flush=True,
    )
    return rc


# ---------------------------------------------------------------------------
# --check: the tier-1 smoke
# ---------------------------------------------------------------------------


def _check() -> int:
    import numpy as np

    import partitionedarrays_jl_tpu as pa
    from partitionedarrays_jl_tpu import telemetry
    from partitionedarrays_jl_tpu.frontdoor import (
        Gate,
        JournalCorruptError,
        RequestJournal,
        read_journal,
    )
    from partitionedarrays_jl_tpu.models import (
        assemble_poisson,
        cg,
        gather_pvector,
    )

    failures = []

    def expect(cond, msg):
        if not cond:
            failures.append(msg)

    root = tempfile.mkdtemp(prefix="padur-check-")

    # -- leg 1: journal round trip + fsync'd rotation -------------------
    jd = os.path.join(root, "unit")
    j = RequestJournal(jd, fsync=True, segment_bytes=4096)
    for i in range(40):
        j.append("shed", tag=f"r{i}", slo_class="besteffort", depth=i)
    segs = j.segments()
    expect(len(segs) >= 2, f"rotation must produce >1 segment ({segs})")
    j.close()
    j2 = RequestJournal(jd, fsync=False)
    sheds = [r for r in j2.prior_records if r["kind"] == "shed"]
    expect(len(sheds) == 40, f"replay must return all 40 ({len(sheds)})")
    expect(
        [r["tag"] for r in sheds] == [f"r{i}" for i in range(40)],
        "replay must preserve append order",
    )
    expect(j2.epoch == 2, f"epoch must increment per open ({j2.epoch})")
    seqs = [r["seq"] for r in j2.prior_records]
    expect(seqs == sorted(seqs), "seq must be monotonic across segments")
    j2.close()

    # -- leg 2: forced torn tail -> truncate + typed event --------------
    trunc0 = telemetry.counter("journal.truncated")
    ev0 = telemetry.counter("events.journal_truncated")
    last = sorted(j2.segments())[-1]
    with open(last, "ab") as f:
        f.write(b'{"kind":"completed","seq":999,"torn')  # no crc, torn
    j3 = RequestJournal(jd, fsync=False)
    expect(
        len([r for r in j3.prior_records if r["kind"] == "shed"]) == 40,
        "torn tail must not eat clean records",
    )
    expect(
        telemetry.counter("journal.truncated") == trunc0 + 1,
        "torn tail must bump journal.truncated",
    )
    expect(
        telemetry.counter("events.journal_truncated") == ev0 + 1,
        "torn tail must emit journal_truncated",
    )
    j3.close()

    # -- leg 3: mid-file corruption refuses typed -----------------------
    jc = os.path.join(root, "corrupt")
    jx = RequestJournal(jc, fsync=False)
    jx.append("shed", tag="a", slo_class="x", depth=0)
    jx.append("shed", tag="b", slo_class="x", depth=1)
    jx.close()
    seg = sorted(jx.segments())[0]
    data = bytearray(open(seg, "rb").read())
    data[data.find(b'"tag":"a"') + 8] = ord("z")  # flip a byte mid-file
    open(seg, "wb").write(bytes(data))
    try:
        read_journal(jc, strict=True)
        expect(False, "mid-file corruption must raise JournalCorruptError")
    except JournalCorruptError:
        pass

    # -- leg 4: gate journal round trip + idempotency across a crash ----
    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        x_solo, _ = cg(A, b, x0=x0, tol=1e-9)
        gd = os.path.join(root, "gate")
        g1 = Gate(journal_dir=gd)
        g1.register("t", A, kmax=4)
        h1 = g1.submit("t", b, x0=x0, tol=1e-9, tag="done-req",
                       idempotency_key="check-key")
        g1.drain()
        x1 = gather_pvector(h1.result()[0])
        hq = g1.submit("t", b, x0=x0, tol=1e-9, tag="queued-req")
        # crash: no shutdown — g1 is simply abandoned
        adm0 = telemetry.counter("service.admitted")
        g2 = Gate(journal_dir=gd)
        g2.register("t", A, kmax=4)
        s = g2.recover()
        expect(
            s["completed"] == 1 and s["requeued"] == 1,
            f"recovery summary wrong: {s}",
        )
        hr = g2.handle(h1.rid)
        expect(hr is not None and hr.state == "done",
               "completed request must be servable from the journal")
        expect(
            np.array_equal(hr.result()[0], x1),
            "recovered result must be BITWISE the original",
        )
        # idempotent replay across the restart: original id, original
        # result, NO new admission
        h1b = g2.submit("t", b, idempotency_key="check-key")
        expect(h1b is hr, "idempotency key must return the original")
        expect(
            telemetry.counter("service.admitted") == adm0,
            "an idempotent replay must not admit a second solve",
        )
        g2.drain()
        xq, iq = g2.handle(hq.rid).result()
        expect(iq["converged"], "requeued request must complete")
        expect(
            np.array_equal(gather_pvector(xq), gather_pvector(x_solo)),
            "requeued request must complete bitwise-equal to solo",
        )
        return True

    expect(pa.prun(driver, pa.sequential, (2, 2)), "driver failed")

    for f in failures:
        print(f"padur --check FAILURE: {f}", file=sys.stderr)
    print("padur --check:", "FAILED" if failures else "OK")
    return 1 if failures else 0


# ---------------------------------------------------------------------------
# --drill: the SIGKILL crash drill (slow)
# ---------------------------------------------------------------------------


def _wait_for(predicate, timeout_s, what):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        v = predicate()
        if v:
            return v
        time.sleep(0.05)
    raise TimeoutError(f"padur drill: timed out waiting for {what}")


def _spawn_server(journal_dir, ckpt_dir, url_file, slab_delay):
    if os.path.exists(url_file):
        os.unlink(url_file)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PA_GATE_JOURNAL_FSYNC="1",
               # patx: spans persist next to the journal so the drill
               # reconstructs ONE stitched trace across the SIGKILL
               # (PA_TX pinned on — the drill asserts trace ids, so an
               # operator env with PA_TX=0 must not fail it spuriously)
               PA_TX="1",
               PA_TX_DIR=os.path.join(journal_dir, "tx"))
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "serve",
         "--journal-dir", journal_dir, "--checkpoint-dir", ckpt_dir,
         "--port", "0", "--url-file", url_file,
         "--slab-delay", str(slab_delay)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )

    def url_ready():
        if proc.poll() is not None:
            out = proc.stdout.read()
            raise RuntimeError(f"padur serve died at startup:\n{out}")
        return os.path.exists(url_file) and open(url_file).read()

    url = _wait_for(url_ready, 90.0, "server url")
    return proc, url


def _post(url, payload):
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        url + "/v1/solve", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _poll(url, rid, timeout_s=120.0):
    import urllib.request

    def terminal():
        with urllib.request.urlopen(
            f"{url}/v1/solve/{rid}", timeout=30
        ) as resp:
            poll = json.loads(resp.read())
        return (
            poll
            if poll["state"] not in ("gate-queued", "queued", "running")
            else None
        )

    return _wait_for(terminal, timeout_s, f"request {rid}")


def _drill(slab_delay: float = 0.5, n_requests: int = 4) -> int:
    """SIGKILL the serving gate mid-slab over HTTP, restart against the
    same journal + checkpoint dir, and assert: every admitted request
    completes bitwise-equal to its solo solve or fails typed — zero
    lost, zero duplicated (the idempotency-key resubmit returns the
    original result)."""
    import numpy as np

    import partitionedarrays_jl_tpu as pa
    from partitionedarrays_jl_tpu.frontdoor import read_journal
    from partitionedarrays_jl_tpu.models import (
        assemble_poisson,
        cg,
        gather_pvector,
        scatter_pvector_values,
    )

    failures = []

    def expect(cond, msg):
        if not cond:
            failures.append(msg)

    from partitionedarrays_jl_tpu.telemetry import tracing

    root = tempfile.mkdtemp(prefix="padur-drill-")
    jd = os.path.join(root, "journal")
    cd = os.path.join(root, "ckpt")
    uf = os.path.join(root, "url")

    # the oracle: each request's SOLO solve, in-process (deadline-free
    # requests run unchunked, so the served block solve per column IS
    # the solo trajectory — bitwise)
    def oracle(parts):
        A, b, xe, x0 = assemble_poisson(parts, DRILL_GRID)
        n = A.rows.ngids
        out = []
        for i in range(n_requests):
            bg = _drill_rhs(n, i)
            bv = scatter_pvector_values(
                np.asarray(bg, dtype=np.float64), A.cols
            )
            x, info = cg(A, bv, tol=1e-9)
            out.append((bg, gather_pvector(x), info["iterations"]))
        return out

    solo = pa.prun(oracle, pa.sequential, (2, 2))

    print(f"padur drill: starting server (journal={jd})", flush=True)
    proc, url = _spawn_server(jd, cd, uf, slab_delay)
    ids = []
    traces = {}  # rid -> trace_id acknowledged pre-crash
    try:
        for i in range(n_requests):
            status, payload = _post(url, {
                "tenant": DRILL_TENANT,
                "b": [float(v) for v in solo[i][0]],
                "tol": 1e-9,
                "tag": f"drill-{i}",
                "idempotency_key": f"drill-key-{i}",
            })
            expect(status == 202, f"submit {i} must 202 (got {status})")
            ids.append(payload["id"])
            expect(
                bool(payload.get("trace_id")),
                f"submit {i} must acknowledge a trace_id",
            )
            traces[payload["id"]] = payload.get("trace_id")
        # land the kill MID-SLAB: wait for a dispatch to be journaled
        # (the slab is then sleeping inside _block_solve), then -9
        _wait_for(
            lambda: any(
                r.get("kind") == "dispatched"
                for r in read_journal(jd)
            ),
            60.0, "a dispatched record",
        )
        time.sleep(slab_delay / 4)  # into the slab's sleep window
        proc.send_signal(signal.SIGKILL)
        proc.wait()
        print("padur drill: SIGKILL delivered mid-slab", flush=True)
    except BaseException:
        proc.kill()
        proc.wait()
        raise

    completed_before = sum(
        1 for r in read_journal(jd) if r.get("kind") == "completed"
    )
    expect(
        completed_before < n_requests,
        "the kill must land before every request completed "
        f"(completed={completed_before}) — raise --slab-delay",
    )

    # restart on the same journal; no slab delay (finish fast)
    proc2, url2 = _spawn_server(jd, cd, uf, 0.0)
    try:
        results = {}
        for i, rid in enumerate(ids):
            poll = _poll(url2, rid)
            results[rid] = poll
            expect(
                poll["state"] in ("done", "failed"),
                f"{rid}: must reach a terminal state ({poll['state']})",
            )
            expect(
                poll.get("trace_id") == traces[rid],
                f"{rid}: the recovered request must keep its ORIGINAL "
                f"trace_id ({traces[rid]} -> {poll.get('trace_id')})",
            )
            if poll["state"] == "done":
                expect(
                    poll["x"] == [float(v) for v in solo[i][1]],
                    f"{rid}: recovered result must be BITWISE the solo "
                    "solve",
                )
                expect(
                    poll["info"]["iterations"] == solo[i][2]
                    or poll["info"].get("recovered", False),
                    f"{rid}: iteration count must match solo",
                )
            else:
                expect(
                    bool(poll.get("error")),
                    f"{rid}: a failure must be TYPED ({poll})",
                )
        done = sum(
            1 for p in results.values() if p["state"] == "done"
        )
        print(
            f"padur drill: {done}/{n_requests} done, "
            f"{n_requests - done} typed-failed, 0 lost", flush=True,
        )
        # zero duplicated: the idempotency-key resubmit returns the
        # ORIGINAL id + result, and the journal holds exactly one
        # completed record per rid
        status, payload = _post(url2, {
            "tenant": DRILL_TENANT,
            "b": [float(v) for v in solo[0][0]],
            "tol": 1e-9,
            "idempotency_key": "drill-key-0",
        })
        expect(
            payload.get("id") == ids[0] and payload.get("replayed"),
            f"idempotent resubmit must return the original id "
            f"({payload})",
        )
        poll = _poll(url2, ids[0])
        expect(
            poll["state"] == "done"
            and poll["x"] == [float(v) for v in solo[0][1]],
            "idempotent resubmit must serve the original bitwise result",
        )
        # graceful shutdown: the SIGTERM exit-code contract
        proc2.send_signal(signal.SIGTERM)
        rc2 = proc2.wait(timeout=60)
        expect(rc2 == 0, f"SIGTERM shutdown must exit 0 (got {rc2})")
    except BaseException:
        proc2.kill()
        proc2.wait()
        raise

    recs = read_journal(jd)
    per_rid = {}
    for r in recs:
        if r.get("kind") == "completed":
            per_rid[r["rid"]] = per_rid.get(r["rid"], 0) + 1
    expect(
        all(c == 1 for c in per_rid.values()),
        f"zero duplicated: one completed record per rid ({per_rid})",
    )
    terminal = {
        r["rid"] for r in recs if r.get("kind") in ("completed", "failed")
    }
    expect(
        set(ids) <= terminal,
        f"zero lost: every admitted id must reach a terminal record "
        f"(missing: {set(ids) - terminal})",
    )

    # -- patx: ONE stitched trace per admitted request ------------------
    spans = tracing.load_spans(os.path.join(jd, "tx"))
    interrupted_total = 0
    for rid in ids:
        tid = traces[rid]
        mine = [s for s in spans if s.get("trace_id") == tid]
        expect(mine, f"{rid}: no spans persisted for trace {tid}")
        for p in tracing.verify_trace(spans, tid):
            expect(False, f"{rid}: {p}")  # incl. ZERO orphan spans
        tids = {s["trace_id"] for s in mine}
        expect(
            tids == {tid},
            f"{rid}: the crash must not fork the trace ({tids})",
        )
        interrupted = [
            s for s in mine if s.get("status") == "interrupted"
        ]
        interrupted_total += len(interrupted)
        # a request the kill caught mid-flight stitches: its post-crash
        # root span parents to the (interrupted) pre-crash root
        stitched = [
            s for s in mine
            if s["kind"] == "rpc.request" and s.get("attrs", {}).get(
                "recovered"
            )
        ]
        for s in stitched:
            expect(
                s.get("parent_id") in {m["span_id"] for m in mine},
                f"{rid}: recovered root must parent to the pre-crash "
                "root span",
            )
    expect(
        interrupted_total >= 1,
        "the SIGKILL must leave at least one interrupted span "
        "(something was mid-flight)",
    )
    print(
        f"padur drill: {len(ids)} stitched traces, "
        f"{interrupted_total} interrupted spans, 0 orphans",
        flush=True,
    )

    for f in failures:
        print(f"padur --drill FAILURE: {f}", file=sys.stderr)
    print("padur --drill:", "FAILED" if failures else "OK")
    return 1 if failures else 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="in-process smoke: journal round-trip, torn "
                         "tail, gate recovery + idempotency")
    ap.add_argument("--drill", action="store_true",
                    help="SIGKILL crash drill over HTTP (subprocess)")
    ap.add_argument("--slab-delay", type=float, default=0.5,
                    help="drill: per-slab sleep widening the kill "
                         "window (serve: injected into _block_solve)")
    sub = ap.add_subparsers(dest="cmd")
    ps = sub.add_parser("serve", help="serve the drill tenant")
    ps.add_argument("--journal-dir", required=True)
    ps.add_argument("--checkpoint-dir", default=None)
    ps.add_argument("--host", default="127.0.0.1")
    ps.add_argument("--port", type=int, default=0)
    ps.add_argument("--url-file", default=None)
    ps.add_argument("--slab-delay", type=float, default=0.0)
    ps.add_argument("--shed-depth", type=int, default=4096)
    ps.add_argument("--drain", action="store_true",
                    help="drain on SIGTERM instead of checkpointing")
    args = ap.parse_args(argv)

    if args.check:
        return _check()
    if args.drill:
        return _drill(slab_delay=args.slab_delay)
    if args.cmd == "serve":
        return cmd_serve(args)
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
