#!/usr/bin/env python
"""paserve — run the solve service against a demo operator.

The CLI harness of `partitionedarrays_jl_tpu.service.SolveService`: it
assembles a Poisson system, starts a service, submits a batch of
requests (optionally poisoning one with a NaN right-hand side to watch
the blast-radius containment work, optionally with per-request
deadlines), drains, and prints one outcome line per request plus the
service stats — the smallest end-to-end path through admission,
coalescing, the compiled block slab, ejection, and typed failure.

Usage:
    python tools/paserve.py --grid 8 8 --requests 6 --kmax 4
    python tools/paserve.py --grid 8 8 8 --requests 8 --poison 3
    python tools/paserve.py --backend tpu --requests 8 --deadline 30
    python tools/paserve.py ... --summary-json out.json
    python tools/paserve.py ... --metrics-json m.json   # pamon --snapshot

Exit status: 0 when every request ends in a documented terminal state
(done, or failed-with-typed-error for poisoned requests), 1 otherwise.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _build_requests(pa, A, b, x0, n_requests, poison, seed=0):
    """The demo request mix: the assembled (b, x0) plus scaled variants
    — the system is linear, so scaling BOTH keeps the Dirichlet
    boundary rows consistent — with request ``poison`` (if any)
    NaN-poisoned in one owned entry of its b."""
    import numpy as np

    out = []
    for i in range(n_requests):
        bi, x0i = b.copy(), x0.copy()
        if i:
            scale = 1.0 + 0.25 * i

            # scale all local values in place (owned and ghost scale
            # together, so no exchange is needed)
            def _scale(iset, vals, s=scale):
                np.asarray(vals)[...] *= s

            pa.map_parts(_scale, bi.rows.partition, bi.values)
            pa.map_parts(_scale, x0i.rows.partition, x0i.values)
        if poison is not None and i == poison:
            def _poison(iset, vals):
                if int(iset.part) == 0 and len(np.asarray(vals)):
                    np.asarray(vals)[0] = np.nan

            pa.map_parts(_poison, bi.rows.partition, bi.values)
        out.append((bi, x0i))
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--grid", type=int, nargs="+", default=[8, 8],
                    help="Poisson grid (2-D or 3-D), default 8 8")
    ap.add_argument("--parts", type=int, nargs="+", default=None,
                    help="part grid (default 2 2 [2])")
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--kmax", type=int, default=None,
                    help="slab width bound (default PA_SERVE_KMAX)")
    ap.add_argument("--queue-depth", type=int, default=None)
    ap.add_argument("--chunk", type=int, default=None)
    ap.add_argument("--tol", type=float, default=1e-9)
    ap.add_argument("--maxiter", type=int, default=None)
    ap.add_argument("--deadline", type=float, default=None,
                    help="per-request deadline seconds (slabs chunk)")
    ap.add_argument("--poison", type=int, default=None,
                    help="NaN-poison request #N (containment demo)")
    ap.add_argument("--retries", type=int, default=None)
    ap.add_argument("--backend", choices=("seq", "tpu"), default="seq")
    ap.add_argument("--summary-json", default=None,
                    help="write the outcome summary as JSON")
    ap.add_argument("--metrics-json", default=None,
                    help="export the metric-registry snapshot as JSON "
                         "(render/watch it with tools/pamon.py "
                         "--snapshot)")
    args = ap.parse_args(argv)

    import partitionedarrays_jl_tpu as pa
    from partitionedarrays_jl_tpu.models import assemble_poisson
    from partitionedarrays_jl_tpu.service import SolveService

    grid = tuple(args.grid)
    parts_grid = (
        tuple(args.parts) if args.parts else (2,) * len(grid)
    )
    if args.backend == "tpu":
        need = 1
        for p in parts_grid:
            need *= p
        # standalone runs need the virtual CPU mesh (same setup as
        # tools/patrace.py --diff-static); in-process tier-1 use
        # inherits the conftest mesh. XLA_FLAGS acts at first backend
        # init, so this works even when jax is already imported.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={max(need, 8)}"
            ).strip()
        import jax

        if not os.environ.get("JAX_PLATFORMS"):
            os.environ["JAX_PLATFORMS"] = "cpu"
            jax.config.update("jax_platforms", "cpu")
        backend = pa.TPUBackend(devices=jax.devices()[:need])
    else:
        backend = pa.sequential

    rows = []

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, grid)
        svc = SolveService(
            A, kmax=args.kmax, queue_depth=args.queue_depth,
            chunk=args.chunk, retries=args.retries,
        )
        bs = _build_requests(pa, A, b, x0, args.requests, args.poison)
        handles = []
        for i, (bi, x0i) in enumerate(bs):
            handles.append(
                svc.submit(
                    bi, x0=x0i, tol=args.tol, maxiter=args.maxiter,
                    deadline=args.deadline, tag=f"req-{i}",
                )
            )
        svc.drain()
        stats = svc.shutdown()
        for i, h in enumerate(handles):
            row = {"request": h.tag, "state": h.state,
                   "iterations": h.iterations}
            if h.state == "done":
                _x, info = h.result()
                row["converged"] = bool(info["converged"])
                row["status"] = str(info["status"])
            elif h.state == "failed":
                row["error"] = type(h.error).__name__
            rows.append(row)
        return stats

    stats = pa.prun(driver, backend, parts_grid)

    for row in rows:
        line = (
            f"  {row['request']:>8s}  {row['state']:>6s}  "
            f"it={row['iterations']:>4d}"
        )
        if "converged" in row:
            line += f"  converged={row['converged']}  {row['status']}"
        if "error" in row:
            line += f"  {row['error']}"
        print(line)
    print(f"stats: {json.dumps(stats, sort_keys=True)}")

    ok = True
    for i, row in enumerate(rows):
        if args.poison is not None and i == args.poison:
            ok = ok and row["state"] == "failed"
        else:
            ok = ok and row["state"] == "done" and row.get("converged")
    if args.summary_json:
        with open(args.summary_json, "w", encoding="utf-8") as f:
            json.dump(
                {"requests": rows, "stats": stats, "ok": ok},
                f, indent=1, sort_keys=True,
            )
        print(f"wrote {args.summary_json}")
    if args.metrics_json:
        from partitionedarrays_jl_tpu import telemetry

        with open(args.metrics_json, "w", encoding="utf-8") as f:
            f.write(telemetry.registry().to_json())
        print(f"wrote {args.metrics_json}")
    print("paserve:", "OK" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
