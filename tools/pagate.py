#!/usr/bin/env python
"""pagate — the out-of-process multi-tenant front door, from the CLI.

The operator console of `partitionedarrays_jl_tpu.frontdoor`: serve N
demo operators behind the HTTP/JSON gate, submit solves from another
process, and generate mixed-class load. The demo registry is two
Poisson operators under a deliberately tight ``PA_GATE_MEM_BUDGET``
(only one fits resident at a time), so alternating tenants exercises
the LRU page-out/page-in ladder and mixed-class overload exercises
EDF + SLO-class shedding — the whole ROADMAP item 1 surface from a
shell.

Usage:
    python tools/pagate.py serve [--port 8642] [--budget one]
    python tools/pagate.py submit --url http://127.0.0.1:8642 \
        --tenant poisson8 [--slo-class interactive] [--deadline 30]
    python tools/pagate.py loadgen --url ... --clients 4 --requests 24 \
        [--mixed]
    python tools/pagate.py --check        # tier-1 smoke (in-process)

``--check`` serves on an ephemeral port, runs a mixed-class demo that
forces at least one load-shed (typed 429 + Retry-After) and at least
one eviction (alternating tenants under the tight budget), and asserts
the outcome table, the event trails, and the metric deltas. Exit
status 0 iff every invariant held.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: The demo tenants: name -> Poisson grid (sequential backend, (2, 2)
#: parts — the gate is host-side policy; the backend is whatever the
#: tenants' services run).
DEMO_TENANTS = {"poisson8": (8, 8), "poisson12": (12, 12)}


def build_demo_gate(budget: str = "one", shed_watermark: int = 4,
                    start_workers: bool = True, checkpoint_dir=None,
                    journal_dir=None, rid_namespace=None):
    """The demo registry: both Poisson tenants under a budget. With
    ``budget="one"`` only the larger tenant fits resident at a time
    (every tenant switch is a page-out/page-in); ``"all"`` fits both;
    an integer string is taken as bytes. ``checkpoint_dir`` defaults to
    a fresh temp dir so an eviction catching a slab mid-flight takes
    the checkpoint/resume path instead of losing the iterate.
    ``journal_dir`` enables the padur write-ahead journal — a prior
    journal in that directory is recovered after registration."""
    import tempfile

    if checkpoint_dir is None:
        checkpoint_dir = tempfile.mkdtemp(prefix="pagate-ckpt-")
    import partitionedarrays_jl_tpu as pa
    from partitionedarrays_jl_tpu.frontdoor import (
        Gate,
        operator_footprint_bytes,
    )
    from partitionedarrays_jl_tpu.models import assemble_poisson

    systems = {
        name: pa.prun(
            lambda parts, g=grid: assemble_poisson(parts, g),
            pa.sequential, (2, 2),
        )
        for name, grid in DEMO_TENANTS.items()
    }
    fps = {
        name: operator_footprint_bytes(sys_[0], 4)
        for name, sys_ in systems.items()
    }
    if budget == "one":
        budget_bytes = max(fps.values()) + 16
    elif budget == "all":
        budget_bytes = sum(fps.values()) + 16
    else:
        budget_bytes = int(budget)
    gate = Gate(
        mem_budget_bytes=budget_bytes, shed_watermark=shed_watermark,
        start_workers=start_workers, checkpoint_dir=checkpoint_dir,
        journal_dir=journal_dir, rid_namespace=rid_namespace,
    )
    for name, (A, b, xe, x0) in systems.items():
        gate.register(name, A, kmax=4)
    if gate.journal is not None:
        gate.recover()
    return gate, systems


def _demo_rhs(systems, tenant):
    from partitionedarrays_jl_tpu.models.solvers import gather_pvector

    A, b, xe, x0 = systems[tenant]
    return gather_pvector(b), gather_pvector(x0)


# ---------------------------------------------------------------------------
# subcommands
# ---------------------------------------------------------------------------


def cmd_serve(args) -> int:
    from partitionedarrays_jl_tpu.frontdoor import (
        serve_gate,
        serve_until_signalled,
    )

    gate, _systems = build_demo_gate(budget=args.budget,
                                     shed_watermark=args.shed_depth,
                                     journal_dir=args.journal_dir)
    srv = serve_gate(gate, host=args.host, port=args.port,
                     verbose=args.verbose)
    print(f"pagate: serving {sorted(DEMO_TENANTS)} at {srv.url}")
    print("  endpoints: POST /v1/solve; GET /v1/solve/<id>, "
          "/v1/tenants, /healthz, /metrics")
    # SIGTERM/SIGINT drain-or-checkpoint instead of dying mid-slab
    # (drain=False: in-flight iterates checkpoint at the next chunk
    # boundary and a journaling gate resumes them on the next start)
    rc = serve_until_signalled(srv, drain=False)
    print("pagate: shutdown (checkpoint)")
    return rc


def cmd_submit(args) -> int:
    """One client-side solve: fetch the tenant's size from the server,
    build the demo right-hand side, submit-poll-fetch."""
    import urllib.request

    import numpy as np

    from partitionedarrays_jl_tpu.frontdoor import http_solve

    with urllib.request.urlopen(args.url + "/v1/tenants") as resp:
        tenants = {
            t["tenant"]: t for t in json.loads(resp.read())["tenants"]
        }
    if args.tenant not in tenants:
        print(f"pagate: unknown tenant {args.tenant!r} "
              f"(server has {sorted(tenants)})", file=sys.stderr)
        return 2
    n = tenants[args.tenant]["ngids"]
    rng = np.random.default_rng(args.seed)
    b = (
        rng.standard_normal(n) if args.b == "random"
        else np.full(n, float(args.b))
    )
    out = http_solve(
        args.url, args.tenant, b, tol=args.tol, maxiter=args.maxiter,
        deadline=args.deadline, slo_class=args.slo_class,
        tag=args.tag or f"cli-{args.seed}",
    )
    state = out.get("state", out.get("error"))
    print(f"  {args.tenant:>10s}  {state}  "
          + json.dumps(out.get("info") or
                       {k: out[k] for k in ("error", "retry_after_s")
                        if k in out}))
    return 0 if out.get("state") == "done" else 1


def cmd_loadgen(args) -> int:
    """Multi-client mixed-class load: ``--clients`` threads submit
    round-robin over the server's tenants; prints the per-class
    outcome table (done / shed / failed) and the residency table."""
    import threading
    import urllib.request

    import numpy as np

    from partitionedarrays_jl_tpu.frontdoor import http_solve

    import secrets

    with urllib.request.urlopen(args.url + "/v1/tenants") as resp:
        tenants = json.loads(resp.read())["tenants"]
    classes = args.classes.split(",")
    results = []
    rlock = threading.Lock()
    # per-RUN nonce: idempotency keys must dedupe retries WITHIN this
    # run, not collide with a previous run against the same (possibly
    # journal-recovered) gate — a nonce-less key would make the second
    # loadgen a zero-load replay of stale results
    nonce = secrets.token_hex(3)

    def client(cid):
        rng = np.random.default_rng(1000 + cid)
        for i in range(args.requests):
            t = tenants[(cid + i) % len(tenants)]
            cls = classes[(cid + i) % len(classes)]
            b = rng.standard_normal(t["ngids"])
            # client resilience lives in http_solve now (429 honors
            # the measured Retry-After, transient connection failures
            # retry with backoff+jitter) — no hand-rolled sleeps here,
            # and the idempotency key makes every retry double-solve-
            # safe
            out = http_solve(
                args.url, t["tenant"], b, tol=args.tol,
                deadline=args.deadline, slo_class=cls,
                tag=f"lg-{cid}-{i}", retries=args.retries,
                idempotency_key=f"lg-{nonce}-{cid}-{i}",
            )
            with rlock:
                results.append((cls, out))

    threads = [
        threading.Thread(target=client, args=(cid,))
        for cid in range(args.clients)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    table = {}
    for cls, out in results:
        row = table.setdefault(cls, {"done": 0, "shed": 0, "failed": 0})
        if out.get("state") == "done":
            row["done"] += 1
        elif out.get("error") == "LoadShedded":
            row["shed"] += 1
        else:
            row["failed"] += 1
    for cls in sorted(table):
        row = table[cls]
        total = sum(row.values())
        print(f"  class={cls:12s} done={row['done']:<4d} "
              f"shed={row['shed']:<4d} failed={row['failed']:<4d} "
              f"attainment={row['done'] / total:.1%}")
    with urllib.request.urlopen(args.url + "/v1/tenants") as resp:
        for t in json.loads(resp.read())["tenants"]:
            print(f"  tenant {t['tenant']:12s} "
                  f"{'resident' if t['resident'] else 'EVICTED':8s} "
                  f"evictions={t['evictions']} page_ins={t['page_ins']}")
    return 0


# ---------------------------------------------------------------------------
# --check: the tier-1 smoke
# ---------------------------------------------------------------------------


def _check() -> int:
    """Serve on an ephemeral port, run a mixed-class demo including at
    least one shed and one eviction, assert the outcome table, event
    trails, and metric deltas."""
    import numpy as np

    from partitionedarrays_jl_tpu import telemetry
    from partitionedarrays_jl_tpu.frontdoor import serve_gate

    failures = []

    def expect(cond, msg):
        if not cond:
            failures.append(msg)

    reg = telemetry.registry()

    def counters():
        snap = reg.snapshot()["counters"]
        return {
            k: snap.get(k, 0)
            for k in (
                "gate.evictions", "gate.page_ins",
                "gate.shed{slo_class=besteffort}",
                "gate.slo.requests{slo_class=interactive}",
                "gate.slo.hits{slo_class=interactive}",
            )
        }

    ev_shed0 = telemetry.counter("events.load_shedded")
    ev_evict0 = telemetry.counter("events.tenant_evicted")
    ev_page0 = telemetry.counter("events.tenant_paged_in")
    c0 = counters()
    gate, systems = build_demo_gate(budget="one", shed_watermark=3)
    srv = serve_gate(gate, port=0)
    outcomes = []
    try:
        from partitionedarrays_jl_tpu.frontdoor import http_solve

        # leg 1 — the eviction ladder: alternating tenants under the
        # one-resident budget forces a page-out/page-in per switch
        for tenant in ("poisson8", "poisson12", "poisson8"):
            b, x0 = _demo_rhs(systems, tenant)
            out = http_solve(srv.url, tenant, b, x0=x0, tol=1e-9,
                             deadline=600.0, slo_class="interactive",
                             tag=f"check-{tenant}")
            outcomes.append((tenant, "interactive", out))
            expect(out["state"] == "done",
                   f"{tenant}: interactive solve must finish "
                   f"(got {out.get('state') or out.get('error')})")
            expect(out.get("info", {}).get("converged"),
                   f"{tenant}: demo solve must converge")
        # leg 2 — overload: pause dispatch, build a backlog past the
        # watermark, and watch the lowest class shed typed while
        # interactive keeps being admitted
        gate.paused = True
        b, x0 = _demo_rhs(systems, "poisson8")
        # submit without polling (bare POSTs) so the backlog stays
        import urllib.error
        import urllib.request

        def post(cls, tag):
            req = urllib.request.Request(
                srv.url + "/v1/solve",
                data=json.dumps({
                    "tenant": "poisson8", "b": list(map(float, b)),
                    "x0": list(map(float, x0)), "tol": 1e-9,
                    "slo_class": cls, "tag": tag,
                }).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            try:
                with urllib.request.urlopen(req) as resp:
                    return resp.status, json.loads(resp.read()), {}
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read()), dict(e.headers)

        ids = []
        for i in range(3):
            status, payload, _ = post("besteffort", f"check-bg-{i}")
            expect(status == 202, f"backlog submit {i} must be 202")
            ids.append(payload.get("id"))
        status, payload, headers = post("besteffort", "check-shed")
        outcomes.append(("poisson8", "besteffort", payload))
        expect(status == 429,
               f"besteffort past the watermark must shed (got {status})")
        expect(payload.get("error") == "LoadShedded",
               "shed must be the typed LoadShedded payload")
        expect("Retry-After" in headers,
               "shed response must carry Retry-After")
        status, payload, _ = post("interactive", "check-keep")
        expect(status == 202,
               f"interactive must be admitted while besteffort sheds "
               f"(got {status})")
        ids.append(payload.get("id"))
        # the readiness-probe-grade /healthz: depth, residency,
        # journal epoch, uptime (with the backlog still held)
        with urllib.request.urlopen(srv.url + "/healthz") as resp:
            health = json.loads(resp.read())
        expect(health.get("ok") is True, "healthz must report ok")
        expect(health.get("queue_depth", 0) >= 4,
               f"healthz must report the held backlog ({health})")
        expect(health.get("resident") == ["poisson8"],
               f"healthz must list the resident tenants ({health})")
        expect("journal_epoch" in health,
               "healthz must report the journal epoch (null journal-"
               "off)")
        expect(
            isinstance(health.get("uptime_s"), (int, float))
            and health["uptime_s"] >= 0.0,
            f"healthz must report uptime_s ({health})",
        )
        gate.paused = False
        for rid in ids:
            import time

            for _ in range(2000):
                with urllib.request.urlopen(
                    f"{srv.url}/v1/solve/{rid}"
                ) as resp:
                    poll = json.loads(resp.read())
                if poll["state"] not in ("gate-queued", "queued",
                                         "running"):
                    break
                time.sleep(0.005)
            expect(poll["state"] == "done",
                   f"backlog request {rid} must finish "
                   f"(got {poll['state']})")
    finally:
        srv.stop()
    c1 = counters()
    d = {k: c1[k] - c0[k] for k in c0}
    expect(d["gate.evictions"] >= 1,
           f"the tenant switches must evict at least once ({d})")
    expect(d["gate.page_ins"] >= 3,
           f"page-ins must cover registration + re-stages ({d})")
    expect(d["gate.shed{slo_class=besteffort}"] == 1,
           f"exactly the one shed must count ({d})")
    expect(
        d["gate.slo.hits{slo_class=interactive}"]
        == d["gate.slo.requests{slo_class=interactive}"] >= 4,
        f"interactive attainment must stay 100% ({d})",
    )
    # the event trails narrate the same incidents the metrics counted
    expect(telemetry.counter("events.load_shedded") == ev_shed0 + 1,
           "load_shedded event must fire once")
    expect(telemetry.counter("events.tenant_evicted")
           >= ev_evict0 + 1, "tenant_evicted events must fire")
    expect(telemetry.counter("events.tenant_paged_in")
           >= ev_page0 + 3, "tenant_paged_in events must fire")
    for tenant, cls, out in outcomes:
        state = out.get("state") or out.get("error")
        print(f"  {tenant:>10s}  {cls:12s} {state}")
    for f in failures:
        print(f"pagate --check FAILURE: {f}", file=sys.stderr)
    print("pagate --check:", "FAILED" if failures else "OK")
    return 1 if failures else 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="in-process smoke: serve + mixed-class demo "
                         "with one shed and one eviction")
    sub = ap.add_subparsers(dest="cmd")
    ps = sub.add_parser("serve", help="serve the demo tenants")
    ps.add_argument("--host", default="127.0.0.1")
    ps.add_argument("--port", type=int, default=None,
                    help="default PA_GATE_PORT (8642); 0 = ephemeral")
    ps.add_argument("--budget", default="one",
                    help="'one' (default: one resident tenant), 'all', "
                         "or bytes")
    ps.add_argument("--shed-depth", type=int, default=4)
    ps.add_argument("--journal-dir", default=None,
                    help="enable the padur write-ahead journal there "
                         "(default: PA_GATE_JOURNAL_DIR or off)")
    ps.add_argument("--verbose", action="store_true")
    pc = sub.add_parser("submit", help="submit one solve to a server")
    pc.add_argument("--url", required=True)
    pc.add_argument("--tenant", required=True)
    pc.add_argument("--b", default="random",
                    help="'random' or a constant value")
    pc.add_argument("--seed", type=int, default=0)
    pc.add_argument("--tol", type=float, default=1e-9)
    pc.add_argument("--maxiter", type=int, default=None)
    pc.add_argument("--deadline", type=float, default=None)
    pc.add_argument("--slo-class", default=None)
    pc.add_argument("--tag", default="")
    pl = sub.add_parser("loadgen", help="multi-client mixed load")
    pl.add_argument("--url", required=True)
    pl.add_argument("--clients", type=int, default=4)
    pl.add_argument("--requests", type=int, default=8,
                    help="requests per client")
    pl.add_argument("--classes",
                    default="interactive,batch,besteffort")
    pl.add_argument("--tol", type=float, default=1e-9)
    pl.add_argument("--deadline", type=float, default=None)
    pl.add_argument("--retries", type=int, default=0,
                    help="http_solve resilience: retry shed (429, "
                         "honoring Retry-After) and transient "
                         "connection failures this many times")
    args = ap.parse_args(argv)

    if args.check:
        return _check()
    if args.cmd == "serve":
        return cmd_serve(args)
    if args.cmd == "submit":
        return cmd_submit(args)
    if args.cmd == "loadgen":
        return cmd_loadgen(args)
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
