#!/usr/bin/env python
"""patx — end-to-end distributed request traces (span trees).

Reads the per-process span JSONL the tracing plane persists (set
``PA_TX_DIR=<dir>`` for the serving process — `tools/pagate.py serve`
and `tools/padur.py serve` inherit it) and answers the question the
aggregate planes cannot: where did THIS request's time go, from HTTP
ingress through the gate's EDF queue, a possible eviction/requeue or
page-in, the slab, and its chunks — stitched across a crash when the
gate journals (recovered requests keep their original trace_id).

Usage:
    python tools/patx.py <trace_id> --dir /tmp/tx     # render the tree
    python tools/patx.py --list --dir /tmp/tx         # all traces
    python tools/patx.py --slow 5 --dir /tmp/tx       # worst 5 by total
    python tools/patx.py <trace_id> --trace out.json  # Perfetto export
    python tools/patx.py --trace out.json             # ... all traces
    python tools/patx.py <trace_id> --phases PHASE_PROFILE.json
                                   # mount solver.phase spans under
                                   # each slab.solve (measured per-
                                   # iteration attribution, scaled)
    python tools/patx.py --check   # tier-1 smoke: ephemeral gate over
                                   # HTTP -> reconstruct -> assert the
                                   # span-tree invariants

The Perfetto export (``--trace``) writes spans as complete events plus
FLOW arrows along every parent->child edge, onto the same timeline
`tools/patrace.py --trace` uses — records and spans load together.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _load(args):
    from partitionedarrays_jl_tpu.telemetry import tracing

    d = args.dir or os.environ.get("PA_TX_DIR")
    if not d:
        print(
            "patx: no span directory — pass --dir or set PA_TX_DIR "
            "(spans persist only when it was set for the serving "
            "process)",
            file=sys.stderr,
        )
        return None
    spans = tracing.load_spans(d)
    if not spans:
        print(f"patx: no spans under {d}", file=sys.stderr)
        return None
    return spans


def _mount_phases(spans, path):
    from partitionedarrays_jl_tpu.telemetry import tracing

    profile = json.load(open(path))
    added = tracing.mount_phase_spans(spans, profile)
    if not added:
        print(
            f"patx: {path} holds no positive phase attribution — "
            "nothing mounted",
            file=sys.stderr,
        )
    return spans + added


def _list(spans, slow=None):
    from partitionedarrays_jl_tpu.telemetry import tracing

    rows = [
        tracing.trace_summary(spans, tid)
        for tid in tracing.trace_ids(spans)
    ]
    if slow is not None:
        rows.sort(key=lambda r: -r["total_s"])
        rows = rows[:slow]
    print(f"{'trace_id':32s}  {'spans':>5s}  {'total':>10s}  dominant")
    for r in rows:
        mark = " [interrupted]" if r["interrupted"] else ""
        print(
            f"{r['trace_id']:32s}  {r['spans']:5d}  "
            f"{r['total_s'] * 1e3:8.2f}ms  {r['dominant']}{mark}"
        )
    return 0


def _check() -> int:
    """Tier-1 smoke: ephemeral HTTP gate -> spans -> invariants."""
    import tempfile

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    txd = tempfile.mkdtemp(prefix="patx-check-")
    os.environ["PA_TX"] = "1"  # the smoke asserts spans exist
    os.environ["PA_TX_DIR"] = txd

    import partitionedarrays_jl_tpu as pa
    from partitionedarrays_jl_tpu.frontdoor import (
        http_solve,
        serve_gate,
    )
    from partitionedarrays_jl_tpu.frontdoor import Gate
    from partitionedarrays_jl_tpu.models import (
        assemble_poisson,
        gather_pvector,
    )
    from partitionedarrays_jl_tpu.telemetry import tracing

    failures = []

    def expect(cond, msg):
        if not cond:
            failures.append(msg)

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        gate = Gate(start_workers=True)
        gate.register("t", A, kmax=2)
        srv = serve_gate(gate, port=0)
        try:
            bg, x0g = gather_pvector(b), gather_pvector(x0)
            # one client-minted trace, one server-minted
            tp = tracing.mint_trace().traceparent()
            out1 = http_solve(srv.url, "t", bg, x0=x0g, tol=1e-9,
                              tag="patx-1", traceparent=tp)
            out2 = http_solve(srv.url, "t", bg, x0=x0g, tol=1e-9,
                              tag="patx-2")
            expect(out1["state"] == "done", f"solve 1 failed: {out1}")
            expect(out2["state"] == "done", f"solve 2 failed: {out2}")
            expect(
                out1.get("trace_id") == tp.split("-")[1],
                "the client's traceparent trace_id must be joined, "
                f"not replaced ({out1.get('trace_id')})",
            )
            expect(
                bool(out2.get("trace_id")),
                "a submit without traceparent must get a minted trace",
            )
            gate.drain()
            gate.account()
        finally:
            srv.stop()
        return out1["trace_id"], out2["trace_id"]

    tids = pa.prun(driver, pa.sequential, (2, 2))
    spans = tracing.load_spans(txd)
    expect(
        tids[0] != tids[1], "the two requests must be distinct traces"
    )
    for tid in tids:
        mine = [s for s in spans if s["trace_id"] == tid]
        for p in tracing.verify_trace(spans, tid):
            expect(False, p)
        kinds = {s["kind"] for s in mine}
        expect(
            {"rpc.request", "gate.queue", "slab.solve", "chunk"}
            <= kinds,
            f"trace {tid} missing span kinds (have {sorted(kinds)})",
        )
        roots, orphans = tracing.span_tree(mine)
        expect(len(roots) == 1, f"trace {tid}: want ONE root")
        expect(not orphans, f"trace {tid}: orphans {orphans}")
        expect(
            roots and roots[0]["kind"] == "rpc.request",
            f"trace {tid}: root must be rpc.request",
        )
        by_id = {s["span_id"]: s for s in mine}
        for s in mine:
            if s["kind"] == "slab.solve":
                expect(
                    by_id[s["parent_id"]]["kind"] == "rpc.request",
                    "slab.solve must parent to the request root",
                )
            if s["kind"] == "chunk":
                expect(
                    by_id[s["parent_id"]]["kind"] == "slab.solve",
                    "chunk must parent to slab.solve",
                )
        summ = tracing.trace_summary(mine, tid)
        expect(
            summ["dominant"] == "slab.solve",
            f"trace {tid}: a drained solve's dominant span must be "
            f"slab.solve (got {summ['dominant']})",
        )
    # the client's remote parent must be flagged, never an orphan
    for f in failures:
        print(f"patx --check FAILURE: {f}", file=sys.stderr)
    print("patx --check:", "FAILED" if failures else "OK")
    return 1 if failures else 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace_id", nargs="?",
                    help="trace to render (patx --list shows them)")
    ap.add_argument("--dir", help="span directory (default PA_TX_DIR)")
    ap.add_argument("--list", action="store_true", dest="list_",
                    help="one line per trace")
    ap.add_argument("--slow", type=int, metavar="N",
                    help="the N worst traces by total latency")
    ap.add_argument("--trace", metavar="OUT",
                    help="Perfetto/Chrome-trace export (flow events "
                         "link the span edges)")
    ap.add_argument("--phases", metavar="PROFILE",
                    help="paprof PhaseProfile JSON to mount as "
                         "solver.phase children of slab.solve spans")
    ap.add_argument("--json", action="store_true",
                    help="dump the selected trace's spans as JSON")
    ap.add_argument("--check", action="store_true",
                    help="tier-1 smoke: ephemeral gate -> span-tree "
                         "invariants")
    args = ap.parse_args(argv)

    if args.check:
        return _check()

    spans = _load(args)
    if spans is None:
        return 2
    if args.phases:
        spans = _mount_phases(spans, args.phases)

    from partitionedarrays_jl_tpu.telemetry import tracing

    if args.list_ or args.slow is not None:
        return _list(spans, slow=args.slow)

    if args.trace:
        from partitionedarrays_jl_tpu.telemetry import (
            write_chrome_trace,
        )

        events = tracing.trace_chrome_events(
            spans, trace_id=args.trace_id
        )
        write_chrome_trace(args.trace, extra_events=events)
        n = (
            1 if args.trace_id is not None
            else len(tracing.trace_ids(spans))
        )
        print(f"wrote {args.trace} ({n} trace(s), flow-linked)")
        if args.trace_id is None:
            return 0

    if args.trace_id is None:
        ap.print_help()
        return 2
    mine = [s for s in spans if s["trace_id"] == args.trace_id]
    if not mine:
        print(f"patx: no spans for trace {args.trace_id}",
              file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(mine, indent=1, sort_keys=True))
        return 0
    print(tracing.render_trace(spans, args.trace_id))
    problems = tracing.verify_trace(spans, args.trace_id)
    for p in problems:
        print(f"  WARNING: {p}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
