"""1e8-DOF end-to-end assemble + solve on one chip, with a JSON artifact
(BASELINE.json configs[3]-scale evidence; reference anchor: the
strong-scaling FE workload of /root/reference/README.md:49-63).

Assembles the 464^3 (= 99.9M DOF) 3-D Poisson operator on host, lowers
it to the coded-DIA device form, runs ONE compiled CG solve to 1e-5, and
records every phase in ``SCALE_BENCH.json`` (repo root) plus a final
JSON line on stdout. Shrink with PA_SCALE_N for smoke runs.

    python tools/bench_scale.py            # 464^3, writes SCALE_BENCH.json
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax

    import partitionedarrays_jl_tpu as pa
    from partitionedarrays_jl_tpu.models import assemble_poisson
    from partitionedarrays_jl_tpu.parallel.tpu import (
        DeviceVector,
        TPUBackend,
        _b_on_cols_layout,
        device_matrix,
        make_cg_fn,
    )

    n = int(os.environ.get("PA_SCALE_N", "464"))
    tol = float(os.environ.get("PA_SCALE_TOL", "1e-5"))
    out_path = os.environ.get(
        "PA_SCALE_OUT",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "SCALE_BENCH.json"),
    )
    backend = TPUBackend(devices=jax.devices()[:1])
    rec = {"n": n, "dofs": n**3, "dtype": "float32", "tol": tol}

    def driver(parts):
        t0 = time.perf_counter()
        A, b, xe, x0 = assemble_poisson(parts, (n, n, n))
        rec["assembly_s"] = round(time.perf_counter() - t0, 2)
        print(f"assembly {n}^3 = {n**3/1e6:.1f}M DOFs: {rec['assembly_s']}s", flush=True)

        t0 = time.perf_counter()
        A.values = pa.map_parts(
            lambda M: pa.CSRMatrix(
                M.indptr, M.indices, M.data.astype(np.float32), M.shape
            ),
            A.values,
        )
        A.invalidate_blocks()
        b.values = pa.map_parts(lambda v: np.asarray(v, np.float32), b.values)
        xe.values = pa.map_parts(lambda v: np.asarray(v, np.float32), xe.values)
        Ah, bh = pa.decouple_dirichlet(A, b)
        rec["cast_decouple_s"] = round(time.perf_counter() - t0, 2)

        t0 = time.perf_counter()
        dA = device_matrix(Ah, backend)
        rec["lowering_s"] = round(time.perf_counter() - t0, 2)
        rec["dia_mode"] = dA.dia_mode
        rec["nnz"] = int(dA.flops_per_spmv // 2)
        print(
            f"lowering: {rec['lowering_s']}s mode={dA.dia_mode} "
            f"nnz={rec['nnz']/1e6:.0f}M",
            flush=True,
        )

        t0 = time.perf_counter()
        db = _b_on_cols_layout(bh, dA)
        x0v = pa.PVector.full(0.0, Ah.cols, dtype=np.float32)
        dx0 = DeviceVector.from_pvector(x0v, backend, dA.col_layout)
        solve = make_cg_fn(dA, tol=tol, maxiter=20000)
        rec["staging_s"] = round(time.perf_counter() - t0, 2)

        # compile (first call) separated from the steady-state solve
        t0 = time.perf_counter()
        out = solve(db.data, dx0.data, None)
        it = int(out[3])
        rec["first_solve_s"] = round(time.perf_counter() - t0, 2)
        t0 = time.perf_counter()
        out = solve(db.data, dx0.data, None)
        rs, rs0, it = float(out[1]), float(out[2]), int(out[3])
        rec["solve_s"] = round(time.perf_counter() - t0, 2)
        rec["iterations"] = it
        rec["rel_residual"] = float(np.sqrt(rs) / max(1.0, np.sqrt(rs0)))
        rec["converged"] = bool(np.sqrt(rs) <= tol * max(1.0, np.sqrt(rs0)))
        rec["per_iteration_ms"] = round(rec["solve_s"] * 1e3 / max(it, 1), 3)
        rec["spmv_equiv_gflops"] = round(
            dA.flops_per_spmv * it / rec["solve_s"] / 1e9, 1
        )

        # solution quality vs the manufactured solution (err checked the
        # reference's way: test_fdm.jl's norm(x - x_exact) gate)
        x = DeviceVector(out[0], Ah.cols, dA.col_layout, backend).to_pvector()
        err = float((x - xe).norm() / xe.norm())
        rec["rel_err_vs_exact"] = err
        print(
            f"solve: {rec['solve_s']}s, {it} iterations, "
            f"rel_res={rec['rel_residual']:.2e}, rel_err={err:.2e}",
            flush=True,
        )
        assert rec["converged"], rec
        return True

    pa.prun(driver, backend, (1, 1, 1))
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1, sort_keys=True)
    print(json.dumps({"metric": f"e2e_solve_s_poisson3d_{n}cube_f32",
                      "value": rec["solve_s"], "unit": "s",
                      "vs_baseline": rec["per_iteration_ms"]}))


if __name__ == "__main__":
    main()
