"""1e8-DOF end-to-end assemble + solve on one chip, with a JSON artifact
(BASELINE.json configs[3]-scale evidence; reference anchor: the
strong-scaling FE workload of /root/reference/README.md:49-63).

Assembles the 464^3 (= 99.9M DOF) 3-D Poisson operator on host, lowers
it to the coded-DIA device form, runs ONE compiled CG solve to 1e-5, and
records every phase in ``SCALE_BENCH.json`` (repo root) plus a final
JSON line on stdout. Shrink with PA_SCALE_N for smoke runs.

    python tools/bench_scale.py            # 464^3, writes SCALE_BENCH.json

``PA_TPU_PLAN_PROCS=K`` (K>1) routes the assembly emission through K
spawned workers over row slabs (native/parallel_emit.py) — byte-
identical operator; ~1x or slower on a 1-core host (spawn overhead, the
documented no-op), scales assembly_s on multi-core planning hosts.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

#: Reproducibility bands for the 464^3 flagship record (round-5
#: directive 5). Device-timed metrics get HARD bands (a same-chip rerun
#: outside them means a kernel regression or relay trouble); host phases
#: get ADVISORY bands — the driver shares this single-core host with
#: background compiles, and contention alone has doubled host phases
#: between otherwise identical runs (r4: hierarchy 86 s quiet vs 139 s
#: contended). The guard rule: investigate a host-phase excursion only
#: if it reproduces on a quiet host. Provenance: r4/r5 runs +
#: SCALE_CURVE.json, docs/performance.md.
SCALE_BANDS = {
    # r6: the fused streaming CG body (PA_TPU_FUSED_CG default) merges
    # the loop's separate axpy/dot sweeps into the SpMV passes; the 464^3
    # iteration drops 9.32 -> ~6.8 ms (SCALE_CURVE.json r6 leg). The r5
    # band on the standard body was 8.0-10.5; a reading above 7.8 now
    # means the fusion disengaged (or regressed) — that is the point of
    # the band.
    "per_iteration_ms": (5.8, 7.8, "device"),
    "gmg.per_iteration_ms": (170.0, 215.0, "device"),
    # host-advisory bands gate the HIGH side only (faster is fine);
    # r4-r5 observed ranges: assembly 51-108, lowering 31-77 (the 77
    # ran with concurrent host work), hierarchy 78-139
    "assembly_s": (0.0, 130.0, "host-advisory"),
    "lowering_s": (0.0, 95.0, "host-advisory"),
    "gmg.hierarchy_s": (0.0, 165.0, "host-advisory"),
}


def annotate_bands(rec):
    """Stamp each banded metric with its band + in/out verdict (only at
    the flagship n=464 — the bands are calibrated there)."""
    if rec.get("n") != 464:
        return
    out = {}
    for key, (lo, hi, kind) in SCALE_BANDS.items():
        node, k = (
            (rec.get("gmg", {}), key.split(".", 1)[1])
            if key.startswith("gmg.")
            else (rec, key)
        )
        if k not in node:
            continue
        v = node[k]
        out[key] = {
            "lo": lo, "hi": hi, "measured": v, "kind": kind,
            "in_band": bool(lo <= v <= hi),
        }
    rec["bands"] = out
    device_keys = {
        k for k, (_lo, _hi, kind) in SCALE_BANDS.items() if kind == "device"
    }
    if device_keys <= set(out):
        rec["bands_ok_device"] = all(
            out[k]["in_band"] for k in device_keys
        )
        rec.pop("bands_missing", None)  # earlier partial flushes set it
    else:
        # a leg died before its banded metric was recorded: the verdict
        # must not read as "all device bands passed"
        rec["bands_ok_device"] = None
        rec["bands_missing"] = sorted(device_keys - set(out))


def main():
    import jax

    import partitionedarrays_jl_tpu as pa
    from partitionedarrays_jl_tpu.models import assemble_poisson
    from partitionedarrays_jl_tpu.parallel.tpu import (
        DeviceVector,
        TPUBackend,
        _b_on_cols_layout,
        device_matrix,
        make_cg_fn,
    )

    n = int(os.environ.get("PA_SCALE_N", "464"))
    tol = float(os.environ.get("PA_SCALE_TOL", "1e-5"))
    out_path = os.environ.get(
        "PA_SCALE_OUT",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "SCALE_BENCH.json"),
    )
    backend = TPUBackend(devices=jax.devices()[:1])
    rec = {"n": n, "dofs": n**3, "dtype": "float32", "tol": tol}

    # persistent compilation cache (round-5 directive 1): cold = compile
    # into a FRESH cache dir (so the recorded cold number is honest even
    # when the bench reruns); warm = clear the in-process executable
    # caches, rebuild the same program, and let XLA load from disk. A
    # production run points PA_TPU_COMPILE_CACHE at a persistent dir and
    # pays the warm number on every process after the first.
    cache_on = os.environ.get("PA_SCALE_CACHE", "1") != "0"
    if cache_on:
        import tempfile

        user_dir = os.environ.get("PA_SCALE_CACHE_DIR")
        cache_dir = user_dir or tempfile.mkdtemp(prefix="pa_scale_xla_")
        if not user_dir:
            # bench-created dirs hold hundreds of MB of serialized
            # executables; don't leak them into /tmp on every run
            import atexit
            import shutil

            atexit.register(shutil.rmtree, cache_dir, ignore_errors=True)
        pa.enable_compilation_cache(cache_dir)
        rec["compile_cache_dir"] = cache_dir
        # a reused PA_SCALE_CACHE_DIR serves the FIRST solve from disk
        # too — record it so a "cold ~= warm" artifact is explainable
        rec["cold_cache_prepopulated"] = bool(os.listdir(cache_dir))

    def _warm_compile(build_fn, *call_args):
        """Clear in-process executable caches, rebuild the compiled
        program, run one call (served from the persistent cache), and
        return (seconds, out) — (None, None) if the relay flakes (the
        steady-state numbers recorded before this call must survive)."""
        try:
            jax.clear_caches()
            t0 = time.perf_counter()
            fn = build_fn()
            out = fn(*call_args)
            jax.block_until_ready(out)
            return round(time.perf_counter() - t0, 2), out
        except Exception as e:  # relay remote_compile drops responses
            print(f"warm compile failed (non-fatal): {e}", flush=True)
            return None, None

    def driver(parts):
        # round-4 fused pipeline: assemble DIRECTLY in f32 with the
        # Dirichlet decoupling applied in-kernel (b̂ = Â @ x̂ exactly for
        # identity-row systems) — the separate volume-sized cast +
        # decouple_dirichlet passes no longer exist on this path
        t0 = time.perf_counter()
        Ah, bh, xe, x0 = assemble_poisson(
            parts, (n, n, n), dtype=np.float32, decoupled=True
        )
        rec["assembly_s"] = round(time.perf_counter() - t0, 2)
        rec["fused_f32_decoupled_assembly"] = True
        rec["cast_decouple_s"] = 0.0  # fused into assembly_s
        print(f"assembly {n}^3 = {n**3/1e6:.1f}M DOFs: {rec['assembly_s']}s", flush=True)

        t0 = time.perf_counter()
        dA = device_matrix(Ah, backend)
        rec["lowering_s"] = round(time.perf_counter() - t0, 2)
        rec["dia_mode"] = dA.dia_mode
        rec["nnz"] = int(dA.flops_per_spmv // 2)
        print(
            f"lowering: {rec['lowering_s']}s mode={dA.dia_mode} "
            f"nnz={rec['nnz']/1e6:.0f}M",
            flush=True,
        )

        t0 = time.perf_counter()
        db = _b_on_cols_layout(bh, dA)
        x0v = pa.PVector.full(0.0, Ah.cols, dtype=np.float32)
        dx0 = DeviceVector.from_pvector(x0v, backend, dA.col_layout)
        solve = make_cg_fn(dA, tol=tol, maxiter=20000)
        rec["staging_s"] = round(time.perf_counter() - t0, 2)

        # compile (first call) separated from the steady-state solve
        t0 = time.perf_counter()
        out = solve(db.data, dx0.data, None)
        it = int(out[3])
        rec["first_solve_s"] = round(time.perf_counter() - t0, 2)
        rec["first_solve_cold_s"] = rec["first_solve_s"]
        t0 = time.perf_counter()
        out = solve(db.data, dx0.data, None)
        rs, rs0, it = float(out[1]), float(out[2]), int(out[3])
        rec["solve_s"] = round(time.perf_counter() - t0, 2)
        rec["iterations"] = it
        rec["rel_residual"] = float(np.sqrt(rs) / max(1.0, np.sqrt(rs0)))
        rec["converged"] = bool(np.sqrt(rs) <= tol * max(1.0, np.sqrt(rs0)))
        rec["per_iteration_ms"] = round(rec["solve_s"] * 1e3 / max(it, 1), 3)
        rec["spmv_equiv_gflops"] = round(
            dA.flops_per_spmv * it / rec["solve_s"] / 1e9, 1
        )

        # solution quality vs the manufactured solution (err checked the
        # reference's way: test_fdm.jl's norm(x - x_exact) gate)
        x = DeviceVector(out[0], Ah.cols, dA.col_layout, backend).to_pvector()
        err = float((x - xe).norm() / xe.norm())
        rec["rel_err_vs_exact"] = err
        print(
            f"solve: {rec['solve_s']}s, {it} iterations, "
            f"rel_res={rec['rel_residual']:.2e}, rel_err={err:.2e}",
            flush=True,
        )
        assert rec["converged"], rec
        # warm-compile measurement LAST in the leg: it clears the
        # in-process executable caches, which would otherwise pollute
        # the steady solve_s above with a retrace
        _flush()  # the CG leg's numbers survive any GMG-leg failure
        if cache_on:
            warm_s, wout = _warm_compile(
                lambda: make_cg_fn(dA, tol=tol, maxiter=20000),
                db.data, dx0.data, None,
            )
            if warm_s is not None:
                rec["first_solve_warm_s"] = warm_s
                # the disk-cached executable must be the SAME program:
                # the warm solve's iterate count must match the cold one
                assert int(wout[3]) == it, (int(wout[3]), it)
                print(
                    f"first solve: cold {rec['first_solve_cold_s']}s, "
                    f"warm {warm_s}s (persistent cache)",
                    flush=True,
                )
                _flush()

        # --- GMG-PCG leg: the headline capability at the headline scale
        # (CG iteration counts grow ~O(n); multigrid's stay flat) -------
        if os.environ.get("PA_SCALE_GMG", "1") != "0":
            g = {}
            t0 = time.perf_counter()
            h = pa.gmg_hierarchy(parts, Ah, (n, n, n), coarse_threshold=1000)
            g["hierarchy_s"] = round(time.perf_counter() - t0, 2)
            g["levels"] = len(h.levels)
            print(
                f"gmg hierarchy: {g['hierarchy_s']}s, {g['levels']} levels",
                flush=True,
            )
            # time the compiled program only: vectors staged ONCE like
            # the CG leg above (the axon relay tunnels host<->device at
            # tens of MB/s, so per-call PVector staging would swamp the
            # solve by 10-100x; on a real TPU host staging is PCIe-fast)
            from partitionedarrays_jl_tpu.parallel.tpu_gmg import (
                make_gmg_pcg_fn,
            )

            rec["gmg"] = g  # partial numbers survive relay flakes
            gfn = make_gmg_pcg_fn(h, backend, tol, 200)
            dbg = _b_on_cols_layout(bh, dA)
            dx0g = DeviceVector.from_pvector(
                pa.PVector.full(0.0, Ah.cols, dtype=np.float32),
                backend, dA.col_layout,
            )
            out = None
            for attempt in range(3):
                # the relay's remote_compile endpoint drops large compile
                # responses occasionally; the request is idempotent. `out`
                # commits only after the forcing fetch succeeds, so a
                # flake in EITHER step leaves a clean retry state.
                try:
                    t0 = time.perf_counter()
                    attempt_out = gfn(dbg.data, dx0g.data)
                    git = int(attempt_out[3])
                    out = attempt_out
                    break
                except Exception as e:
                    print(
                        f"gmg compile attempt {attempt + 1} failed: {e}",
                        flush=True,
                    )
                    g["compile_error"] = f"{type(e).__name__}: {e}"[:300]
                    _flush()
                    time.sleep(30)
            if out is None:
                return True
            g.pop("compile_error", None)
            g["first_solve_s"] = round(time.perf_counter() - t0, 2)
            g["first_solve_cold_s"] = g["first_solve_s"]
            g["iterations"] = git
            _flush()  # survive flakes in the remaining legs
            t0 = time.perf_counter()
            out = gfn(dbg.data, dx0g.data)
            rsg, rs0g, git = float(out[1]), float(out[2]), int(out[3])
            g["solve_s"] = round(time.perf_counter() - t0, 2)
            g["iterations"] = git
            g["converged"] = bool(
                np.sqrt(rsg) <= tol * max(1.0, np.sqrt(rs0g))
            )
            g["per_iteration_ms"] = round(
                g["solve_s"] * 1e3 / max(git, 1), 3
            )
            xg = DeviceVector(
                out[0], Ah.cols, dA.col_layout, backend
            ).to_pvector()
            errg = float((xg - xe).norm() / xe.norm())
            g["rel_err_vs_exact"] = errg
            g["speedup_vs_cg_solve"] = round(
                rec["solve_s"] / max(g["solve_s"], 1e-9), 2
            )
            print(
                f"gmg solve: {g['solve_s']}s, {g['iterations']} iterations "
                f"({g['per_iteration_ms']} ms/it), rel_err={errg:.2e}, "
                f"{g['speedup_vs_cg_solve']}x over CG",
                flush=True,
            )
            assert g["converged"], g
            _flush()  # steady GMG numbers survive a warm-compile flake
            if cache_on:
                warm_s, wout = _warm_compile(
                    lambda: make_gmg_pcg_fn(h, backend, tol, 200),
                    dbg.data, dx0g.data,
                )
                if warm_s is not None:
                    g["first_solve_warm_s"] = warm_s
                    assert int(wout[3]) == git, (int(wout[3]), git)
                    # the headline: what a second process pays before its
                    # first 1e8-DOF GMG solve with the cache populated
                    rec["warm_setup_total_s"] = round(
                        rec["assembly_s"] + rec["lowering_s"]
                        + rec["staging_s"] + g["hierarchy_s"] + warm_s, 2
                    )
                    print(
                        f"gmg first solve: cold {g['first_solve_cold_s']}s"
                        f", warm {warm_s}s (persistent cache); total warm"
                        f" setup {rec['warm_setup_total_s']}s",
                        flush=True,
                    )
        return True

    def _flush():
        from partitionedarrays_jl_tpu.telemetry import artifacts

        annotate_bands(rec)
        artifacts.write(out_path, rec, tool="bench_scale", echo=False)

    pa.prun(driver, backend, (1, 1, 1))
    _flush()
    print(json.dumps({"metric": f"e2e_solve_s_poisson3d_{n}cube_f32",
                      "value": rec["solve_s"], "unit": "s",
                      "vs_baseline": rec["per_iteration_ms"]}))


def curve():
    """Scaling curve (round-5 directive 2): kernel-only SpMV, CG
    iteration, and pure vector-op (stream) marginal costs at several
    problem sizes on the SAME marginal-chain protocol the 192^3 bands
    use — so the 464^3 per-DOF cliff is measured, not inferred from the
    full-solve wall/iters number. Writes SCALE_CURVE.json.

        python tools/bench_scale.py curve
        PA_CURVE_SIZES=96,192 python tools/bench_scale.py curve
    """
    from functools import partial

    import jax

    import bench as benchmod
    import partitionedarrays_jl_tpu as pa
    from partitionedarrays_jl_tpu.parallel.tpu import TPUBackend

    sizes = [
        int(s)
        for s in os.environ.get("PA_CURVE_SIZES", "96,192,296,464").split(",")
    ]
    backend = TPUBackend(devices=jax.devices()[:1])
    out_path = os.environ.get(
        "PA_CURVE_OUT",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "SCALE_CURVE.json",
        ),
    )
    rows = []
    rec = {
        "methodology": benchmod.METHODOLOGY,
        "protocol": "marginal-chain (bench.py) at EVERY size: kernel-only "
        "SpMV fori_loop chain; fixed-trip compiled-CG marginal; 3-pass "
        "stream chain y = c*y + x on the (1, W) vector layout",
        "sizes": rows,
    }

    def _flush():
        from partitionedarrays_jl_tpu.telemetry import artifacts

        artifacts.write(out_path, rec, tool="bench_scale", echo=False)

    for n in sizes:
        dofs = n**3
        r = {"n": n, "dofs": dofs}
        rows.append(r)
        run_chain, A, x, dA, flops = benchmod.spmv_chain(n, backend, pa)
        r["dia_mode"] = dA.dia_mode
        # chain lengths scaled so the marginal signal stays ~0.5-5 s at
        # every size (the 192^3 default would run 9+ s chains at 464^3)
        kspan = max(100, min(450, int(3.5e9 / dofs)))
        dt = benchmod.marginal_chain_time(run_chain, 50, 50 + kspan)
        r["spmv_s"] = dt
        r["spmv_gflops"] = round(flops / dt / 1e9, 1)
        r["spmv_ps_per_dof"] = round(dt / dofs * 1e12, 1)
        print(json.dumps(r), flush=True)

        # CG marginal on the same operator (the band's protocol): the
        # shipped default (fused body) is the headline, and the standard
        # body rides along as the A/B — inside the 292-300 XLA anomaly
        # window this pair IS the packed-carry-escape measurement
        # (docs/performance.md §Per-DOF scaling)
        k1, k2 = (60, 1000) if dofs < 2e7 else (40, 440)
        # both bodies PINNED explicitly (not env-resolved): the artifact's
        # note declares cg_s_per_it IS the fused body, so a run under
        # PA_TPU_FUSED_CG=0 must not silently record a standard-vs-
        # standard self-comparison as the A/B
        it_s = benchmod.cg_marginal_s_per_it(pa, dA, k1, k2, fused=True)
        r["cg_s_per_it"] = round(it_s, 7)
        r["cg_ps_per_dof"] = round(it_s / dofs * 1e12, 1)
        r["cg_over_spmv"] = round(it_s / dt, 2)
        it_std = benchmod.cg_marginal_s_per_it(pa, dA, k1, k2, fused=False)
        r["cg_unfused_s_per_it"] = round(it_std, 7)
        r["cg_fused_speedup"] = round(it_std / it_s, 2)

        # stream leg: 3-access elementwise chain on the live vector
        # layout -> effective HBM GB/s for the CG's axpy-shaped traffic
        W = dA.col_layout.W
        y0 = np.ones((1, W), dtype=np.float32)
        yv = jax.device_put(y0)
        c = np.float32(0.999)

        @partial(jax.jit, static_argnums=1)
        def stream_chain(y, k):
            def step(i, v):
                return c * v + y  # read v, read y, write v
            return jax.lax.fori_loop(0, k, step, y).sum()

        ks = max(100, min(1000, int(2.0e10 / W)))
        sdt = benchmod.marginal_chain_time(
            lambda k: float(stream_chain(yv, k)), 50, 50 + ks
        )
        r["stream_s"] = sdt
        r["stream_gb_per_s"] = round(3 * W * 4 / sdt / 1e9, 1)
        r["vector_slots_W"] = W
        print(json.dumps(r), flush=True)
        _flush()
        # free staged operator before the next (bigger) size
        del run_chain, A, x, dA
        jax.clear_caches()

    _flush()
    print(json.dumps({"metric": "scale_curve_sizes", "value": len(rows),
                      "unit": "sizes", "vs_baseline": 0.0}))


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "curve":
        curve()
    else:
        main()
