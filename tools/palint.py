#!/usr/bin/env python
"""palint — the static program-contract gate.

Checks two things and exits nonzero if either fails:

1. **Program contracts** (`analysis.contracts`): lower the compiled-CG
   lowering matrix (`parallel.tpu.lowering_matrix` — standard / fused /
   block K∈{1,4} × ABFT on-off × strict-bits, plus the f32-staged
   dtype-closure probes) against the fixed (6,6,6)/(2,2,2) probe system
   and check every registered contract: ABFT per-kind collective
   parity, K-independence, block ≤ solo, fused adds no collectives,
   dtype closure, no host transfer inside the loop, and the compiled
   copy budget (the PR 2 canary — needs ``--compile``, on by default).
2. **Env-key lint** (`analysis.env_lint`): every ``PA_*`` env read in
   the package inventoried; every lowering-affecting one must be
   resolved by a registered cache-key site (`_lowering_env_key` /
   `_gmg_env_key` / `_sdc_config`) and documented in docs/api.md's
   environment table (both directions).

Usage:
    python tools/palint.py --check            # the full gate (CI)
    python tools/palint.py --check --fast     # tier-1 subset
    python tools/palint.py --report           # per-case inventories
    python tools/palint.py --check --no-compile --skip-lint

Always runs on the CPU host mesh (8 virtual devices), even when real
accelerators are visible — the contracts count STRUCTURE, which is
identical across platforms, and forcing CPU keeps the gate fast and
runnable anywhere.
"""
import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _setup_jax():
    # plain assignment + config.update, NOT setdefault: the dev image's
    # sitecustomize exports JAX_PLATFORMS=axon (the real-TPU tunnel) and
    # pre-imports jax, so env vars alone are too late — same pattern as
    # tests/conftest.py. The contracts count structure, which is
    # identical on the virtual CPU mesh.
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ["JAX_ENABLE_X64"] = "true"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    return jax


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="run the full gate (contracts + env lint)")
    ap.add_argument("--report", action="store_true",
                    help="print per-case program inventories")
    ap.add_argument("--fast", action="store_true",
                    help="tier-1 subset of the lowering matrix")
    ap.add_argument("--no-compile", action="store_true",
                    help="skip the compiled-HLO copy-budget cases")
    ap.add_argument("--no-runtime", action="store_true",
                    help="skip the probe solves behind the "
                         "static-measured comms reconciliation contract")
    ap.add_argument("--skip-matrix", action="store_true",
                    help="env lint only")
    ap.add_argument("--skip-lint", action="store_true",
                    help="contract matrix only")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)
    if not (args.check or args.report):
        ap.print_help()
        return 2

    failed = False

    from partitionedarrays_jl_tpu.analysis import env_lint

    if not args.skip_lint:
        violations = env_lint.lint_env_keys()
        cls = env_lint.classify()
        n_low = sum(1 for e in cls.values() if e["class"] == "lowering")
        print(
            f"env lint: {len(cls)} PA_* flags inventoried, {n_low} "
            "lowering-affecting, all key-covered"
            if not violations
            else f"env lint: {len(violations)} violation(s)"
        )
        for v in violations:
            print(f"  LINT: {v}")
            failed = True
        if args.verbose and not violations:
            for name, e in sorted(cls.items()):
                keyed = e["keyed_by"] or "-"
                print(f"  {name:32s} {e['class']:9s} keyed_by={keyed}")

    if not args.skip_matrix:
        _setup_jax()
        from partitionedarrays_jl_tpu.analysis import (
            build_reports,
            check_contracts,
        )

        log = (lambda m: print(f"  {m}")) if args.verbose else None
        cases, reports = build_reports(
            fast=args.fast,
            with_compiled=not args.no_compile,
            with_runtime=not args.no_runtime,
            verbose=log,
        )
        if args.report or args.verbose:
            for name in sorted(reports):
                print(f"  {name:28s} {reports[name].summary()}")
        violations = check_contracts(reports, cases)
        print(
            f"contracts: {len(cases)} cases lowered"
            + ("" if args.no_compile else " (+ compiled copy-budget legs)")
            + ("" if args.no_runtime
               else " (+ runtime comms-reconciliation probes)")
            + (
                ", all contracts hold"
                if not violations
                else f", {len(violations)} VIOLATION(S)"
            )
        )
        for v in violations:
            print(f"  CONTRACT: {v}")
            failed = True

    if args.check:
        print("palint:", "FAILED" if failed else "OK")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
