#!/usr/bin/env python
"""palint — the static program-contract gate.

Checks three things and exits nonzero if any fails:

1. **Program contracts** (`analysis.contracts`): lower the compiled-CG
   lowering matrix (`parallel.tpu.lowering_matrix` — standard / fused /
   block K∈{1,4} × ABFT on-off × strict-bits, plus the f32-staged
   dtype-closure probes) against the fixed (6,6,6)/(2,2,2) probe system
   and check every registered contract: ABFT per-kind collective
   parity, K-independence, block ≤ solo, fused adds no collectives,
   dtype closure, no host transfer inside the loop, the compiled
   copy budget (the PR 2 canary — needs ``--compile``, on by default),
   per-case plan soundness audits, and the static memory budgets
   (`analysis.memory_report`; per-case footprints in ``--report``,
   committed via ``--write-memory`` → MEMORY_FOOTPRINT.json).
2. **Plan soundness** (`analysis.plan_verifier`): statically verify
   every backend's exchange plans on the probe fixtures — the host
   `Exchanger`, the generic index plan (``PA_TPU_BOX=0``) and the box
   slice plan — against the probe operator's sparsity: send/recv
   symmetry, ghost-write race freedom, coverage/dead slots, and
   ppermute-round validity.
3. **Env-key lint** (`analysis.env_lint`): every ``PA_*`` env read in
   the package inventoried; every lowering-affecting one must be
   resolved by a registered cache-key site (`_lowering_env_key` /
   `_gmg_env_key` / `_sdc_config`) and documented in docs/api.md's
   environment table (both directions).

Usage:
    python tools/palint.py --check            # the full gate (CI)
    python tools/palint.py --check --fast     # tier-1 subset
    python tools/palint.py --report           # per-case inventories
    python tools/palint.py --check --no-compile --skip-lint
    python tools/palint.py --check --write-memory  # refresh artifact

Always runs on the CPU host mesh (8 virtual devices), even when real
accelerators are visible — the contracts count STRUCTURE, which is
identical across platforms, and forcing CPU keeps the gate fast and
runnable anywhere.
"""
import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _setup_jax():
    # plain assignment + config.update, NOT setdefault: the dev image's
    # sitecustomize exports JAX_PLATFORMS=axon (the real-TPU tunnel) and
    # pre-imports jax, so env vars alone are too late — same pattern as
    # tests/conftest.py. The contracts count structure, which is
    # identical on the virtual CPU mesh.
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ["JAX_ENABLE_X64"] = "true"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    return jax


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="run the full gate (contracts + env lint)")
    ap.add_argument("--report", action="store_true",
                    help="print per-case program inventories")
    ap.add_argument("--fast", action="store_true",
                    help="tier-1 subset of the lowering matrix")
    ap.add_argument("--no-compile", action="store_true",
                    help="skip the compiled-HLO copy-budget cases")
    ap.add_argument("--no-runtime", action="store_true",
                    help="skip the probe solves behind the "
                         "static-measured comms reconciliation contract")
    ap.add_argument("--no-memory", action="store_true",
                    help="skip the static memory footprints / budgets")
    ap.add_argument("--skip-matrix", action="store_true",
                    help="skip the contract matrix")
    ap.add_argument("--skip-plans", action="store_true",
                    help="skip the standalone plan-soundness leg")
    ap.add_argument("--skip-lint", action="store_true",
                    help="skip the env-key lint")
    ap.add_argument("--write-memory", metavar="PATH", nargs="?",
                    const=os.path.join(REPO, "MEMORY_FOOTPRINT.json"),
                    default=None,
                    help="write the per-case footprint artifact "
                         "(default: MEMORY_FOOTPRINT.json; implies the "
                         "matrix + memory legs)")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)
    if not (args.check or args.report):
        ap.print_help()
        return 2

    failed = False

    from partitionedarrays_jl_tpu.analysis import env_lint

    if not args.skip_lint:
        violations = env_lint.lint_env_keys()
        cls = env_lint.classify()
        n_low = sum(1 for e in cls.values() if e["class"] == "lowering")
        print(
            f"env lint: {len(cls)} PA_* flags inventoried, {n_low} "
            "lowering-affecting, all key-covered"
            if not violations
            else f"env lint: {len(violations)} violation(s)"
        )
        for v in violations:
            print(f"  LINT: {v}")
            failed = True
        if args.verbose and not violations:
            for name, e in sorted(cls.items()):
                keyed = e["keyed_by"] or "-"
                print(f"  {name:32s} {e['class']:9s} keyed_by={keyed}")

    if not args.skip_plans:
        _setup_jax()
        n_plans, defects = _plan_soundness_leg(
            verbose=(lambda m: print(f"  {m}")) if args.verbose else None
        )
        print(
            f"plan soundness: {n_plans} plans verified "
            "(host exchanger, generic index plan, box slice plan)"
            + (
                ", all sound"
                if not defects
                else f", {len(defects)} DEFECT(S)"
            )
        )
        for d in defects:
            print(f"  PLAN: {d}")
            failed = True

    if not args.skip_matrix or args.write_memory:
        _setup_jax()
        from partitionedarrays_jl_tpu.analysis import (
            build_reports,
            check_contracts,
            footprint_table,
        )

        log = (lambda m: print(f"  {m}")) if args.verbose else None
        with_memory = not args.no_memory or bool(args.write_memory)
        cases, reports = build_reports(
            fast=args.fast,
            with_compiled=not args.no_compile,
            with_runtime=not args.no_runtime,
            with_plans=not args.skip_plans,
            with_memory=with_memory,
            verbose=log,
        )
        if args.report or args.verbose:
            for name in sorted(reports):
                print(f"  {name:28s} {reports[name].summary()}")
            if with_memory:
                print("  static memory footprints (B, probe scale):")
                for line in footprint_table(cases).splitlines():
                    print(f"    {line}")
        violations = check_contracts(reports, cases)
        print(
            f"contracts: {len(cases)} cases lowered"
            + ("" if args.no_compile else " (+ compiled copy-budget legs)")
            + ("" if args.no_runtime
               else " (+ runtime comms-reconciliation probes)")
            + ("" if args.skip_plans else " (+ plan audits)")
            + ("" if not with_memory else " (+ memory footprints)")
            + (
                ", all contracts hold"
                if not violations
                else f", {len(violations)} VIOLATION(S)"
            )
        )
        for v in violations:
            print(f"  CONTRACT: {v}")
            failed = True
        if args.write_memory:
            if args.fast:
                print("refusing --write-memory with --fast: the "
                      "committed artifact covers the FULL matrix")
                failed = True
            else:
                from partitionedarrays_jl_tpu.analysis import (
                    memory_report,
                )

                memory_report.write_artifact(
                    args.write_memory, cases, tool="palint"
                )

    if args.check:
        print("palint:", "FAILED" if failed else "OK")
    return 1 if failed else 0


def _plan_soundness_leg(verbose=None):
    """Statically verify every backend's plans over the probe system:
    the host column `Exchanger`, plus the device plan under BOTH env
    flavors (box slice plan under the default env, generic index plan
    under ``PA_TPU_BOX=0``), each against the probe operator's actual
    referenced-ghost sparsity."""
    import jax

    from partitionedarrays_jl_tpu.analysis import plan_verifier as pv
    from partitionedarrays_jl_tpu.parallel.tpu import (
        _MATRIX_BASE_ENV,
        _env_overrides,
        _matrix_probe_system,
        TPUBackend,
        device_matrix,
    )

    backend = TPUBackend(devices=jax.devices()[:8])
    defects, n_plans = [], 0
    for flavor, env in (("box", {}), ("generic", {"PA_TPU_BOX": "0"})):
        e = dict(_MATRIX_BASE_ENV)
        e.update(env)
        with _env_overrides(e):
            A, _b, _x0 = _matrix_probe_system(backend, "f64")
            dA = device_matrix(A, backend)
            ref = pv.referenced_ghosts(A)
            targets = [(f"device-{flavor}", dA.col_plan, None)]
            if flavor == "box":  # host plan is env-independent
                targets.insert(
                    0, ("host-exchanger", A.cols.exchanger,
                        A.cols.partition)
                )
            for nm, plan, parts in targets:
                if verbose:
                    verbose(f"verifying {nm} ...")
                n_plans += 1
                defects.extend(
                    pv.verify_plan(plan, parts=parts, referenced=ref,
                                   name=nm)
                )
    return n_plans, defects


if __name__ == "__main__":
    sys.exit(main())
