"""Irregular-graph SpMV throughput on one chip (BASELINE configs[5]):
the Morton-ordered unstructured-tet elasticity operator. The generic
lowering is padded-ELL, whose per-element gathers run element-at-a-time
on TPU; the shipped fast path is the node-block BSR lowering
(`DeviceMatrix._detect_bsr`): one gather index per bs×bs block + batched
einsum block products (measured 27x over ELL when first prototyped).
This tool records the before/after on the real integrated paths.

    python tools/bench_irregular.py          # 32^3 nodes = 98k dofs
    PA_IRR_N=24 python tools/bench_irregular.py
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax

    import partitionedarrays_jl_tpu as pa
    from partitionedarrays_jl_tpu.models import assemble_elasticity_tet
    from partitionedarrays_jl_tpu.ops.sparse import csr_spmv
    from partitionedarrays_jl_tpu.parallel.tpu import (
        DeviceMatrix,
        DeviceVector,
        TPUBackend,
        device_matrix,
        make_spmv_fn,
    )

    n = int(os.environ.get("PA_IRR_N", "32"))
    backend = TPUBackend(devices=jax.devices()[:1])

    def driver(parts):
        t0 = time.perf_counter()
        A, b, xe, x0 = assemble_elasticity_tet(parts, (n, n, n))
        print(
            f"assembled {n}^3 nodes = {A.rows.ngids/1e3:.0f}k dofs "
            f"in {time.perf_counter()-t0:.1f}s",
            flush=True,
        )
        A.values = pa.map_parts(
            lambda M: pa.CSRMatrix(
                M.indptr, M.indices,
                (M.data / np.abs(M.data).max()).astype(np.float32), M.shape
            ),
            A.values,
        )
        A.invalidate_blocks()
        xe.values = pa.map_parts(lambda v: np.asarray(v, np.float32), xe.values)
        return A, xe

    A, xe = pa.prun(driver, backend, 1)
    M = A.values.part_values()[0]
    lengths = np.diff(M.indptr)
    L = int(lengths.max())
    nnz, rows = int(M.nnz), M.shape[0]
    print(
        f"nnz={nnz/1e6:.1f}M rows={rows/1e3:.0f}k ELL width L={L} "
        f"(mean row {nnz/rows:.1f}) padding overhead {rows*L/nnz:.2f}x",
        flush=True,
    )

    import statistics
    from functools import partial

    def measure(dA, label):
        dx = DeviceVector.from_pvector(xe, backend, dA.col_layout)
        spmv = make_spmv_fn(dA)
        flops = dA.flops_per_spmv

        @partial(jax.jit, static_argnums=1)
        def chain(x, k):
            return jax.lax.fori_loop(
                0, k, lambda i, y: spmv(y) * np.float32(1e-3), x
            ).sum()

        def chain_time(k, nreps=5):
            float(chain(dx.data, k))
            float(chain(dx.data, k))
            ts = []
            for _ in range(nreps):
                t0 = time.perf_counter()
                v = float(chain(dx.data, k))
                ts.append(time.perf_counter() - t0)
            assert v == v
            return statistics.median(ts)

        def measure_once():
            k1, k2 = 20, 220
            t1 = chain_time(k1)
            for _ in range(4):
                t2 = chain_time(k2)
                dt = (t2 - t1) / (k2 - k1)
                if dt > 0:
                    return dt
                k2 *= 2
            return t2 / (k2 // 2)

        dt = sorted(measure_once() for _ in range(3))[1]
        print(
            f"{label}: {dt*1e6:.1f} us -> {flops/dt/1e9:.1f} GFLOP/s",
            flush=True,
        )
        return dt

    # integrated default: the BSR node-block path
    dA = device_matrix(A, backend)
    assert dA.bsr_bs == 3, f"expected 3x3 BSR lowering, got {dA.bsr_bs}"
    dt_bsr = measure(dA, "BSR(3x3) SpMV (default lowering)")

    # forced generic ELL (the pre-round-2 lowering), same matrix
    os.environ["PA_TPU_BSR"] = "0"
    try:
        dA_ell = DeviceMatrix(A, backend)
    finally:
        del os.environ["PA_TPU_BSR"]
    assert dA_ell.bsr_bs is None and dA_ell.dia_mode is None
    dt_ell = measure(dA_ell, "padded-ELL SpMV (PA_TPU_BSR=0)")

    xv = np.asarray(xe.values.part_values()[0], dtype=np.float32)
    csr_spmv(M, xv)
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        csr_spmv(M, xv)
        ts.append(time.perf_counter() - t0)
    host_dt = statistics.median(ts)
    print(
        f"host oracle: {host_dt*1e3:.1f} ms; BSR vs ELL {dt_ell/dt_bsr:.1f}x, "
        f"BSR vs host {host_dt/dt_bsr:.1f}x",
        flush=True,
    )


if __name__ == "__main__":
    main()
