"""Irregular-graph SpMV throughput on one chip (BASELINE configs[5]):
the Morton-ordered unstructured-tet elasticity operator. The generic
lowering is padded-ELL, whose per-element gathers run element-at-a-time
on TPU; the shipped fast path is the node-block BSR lowering
(`DeviceMatrix._detect_bsr`): one gather index per bs×bs block + batched
einsum block products (measured 27x over ELL when first prototyped).
This tool records the before/after on the real integrated paths.

    python tools/bench_irregular.py          # 32^3 nodes = 98k dofs
    PA_IRR_N=24 python tools/bench_irregular.py
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax

    import partitionedarrays_jl_tpu as pa
    from partitionedarrays_jl_tpu.models import assemble_elasticity_tet
    from partitionedarrays_jl_tpu.ops.sparse import csr_spmv
    from partitionedarrays_jl_tpu.parallel.tpu import (
        DeviceMatrix,
        DeviceVector,
        TPUBackend,
        device_matrix,
        make_spmv_fn,
    )

    n = int(os.environ.get("PA_IRR_N", "32"))
    backend = TPUBackend(devices=jax.devices()[:1])

    def driver(parts):
        t0 = time.perf_counter()
        A, b, xe, x0 = assemble_elasticity_tet(parts, (n, n, n))
        print(
            f"assembled {n}^3 nodes = {A.rows.ngids/1e3:.0f}k dofs "
            f"in {time.perf_counter()-t0:.1f}s",
            flush=True,
        )
        A.values = pa.map_parts(
            lambda M: pa.CSRMatrix(
                M.indptr, M.indices,
                (M.data / np.abs(M.data).max()).astype(np.float32), M.shape
            ),
            A.values,
        )
        A.invalidate_blocks()
        xe.values = pa.map_parts(lambda v: np.asarray(v, np.float32), xe.values)
        return A, xe

    A, xe = pa.prun(driver, backend, 1)
    M = A.values.part_values()[0]
    lengths = np.diff(M.indptr)
    L = int(lengths.max())
    nnz, rows = int(M.nnz), M.shape[0]
    print(
        f"nnz={nnz/1e6:.1f}M rows={rows/1e3:.0f}k ELL width L={L} "
        f"(mean row {nnz/rows:.1f}) padding overhead {rows*L/nnz:.2f}x",
        flush=True,
    )

    import statistics
    from functools import partial

    def measure(dA, label):
        from partitionedarrays_jl_tpu.parallel.tpu import (
            _matrix_operands, _shard_ops, _spmv_body,
        )

        dx = DeviceVector.from_pvector(xe, backend, dA.col_layout)
        flops = dA.flops_per_spmv
        # the timing chain must pass the staged matrix operands as
        # ARGUMENTS: closing over them would inline hundreds of MB of
        # constants into the relay's compile request (HTTP 413 on the
        # SD lowering's densified blocks)
        ops = _matrix_operands(dA)
        body = _spmv_body(dA)
        mesh = backend.mesh(dA.row_layout.P)
        spec = backend.parts_spec()
        specs = jax.tree.map(lambda _: spec, ops)

        @partial(jax.jit, static_argnums=2)
        def chain(x, m, k):
            def shard_fn(xs, ms):
                mm = _shard_ops(jax, ms)

                def step(_, y):
                    y2, _x = body(y, mm)
                    return y2 * np.float32(1e-3)

                return jax.lax.fori_loop(0, k, step, xs[0])[None]

            from jax import shard_map

            return shard_map(
                shard_fn, mesh=mesh, in_specs=(spec, specs),
                out_specs=spec, check_vma=False,
            )(x, m).sum()

        def chain_time(k, nreps=5):
            float(chain(dx.data, ops, k))
            float(chain(dx.data, ops, k))
            ts = []
            for _ in range(nreps):
                t0 = time.perf_counter()
                v = float(chain(dx.data, ops, k))
                ts.append(time.perf_counter() - t0)
            assert v == v
            return statistics.median(ts)

        def measure_once():
            k1, k2 = 20, 220
            t1 = chain_time(k1)
            for _ in range(4):
                t2 = chain_time(k2)
                dt = (t2 - t1) / (k2 - k1)
                if dt > 0:
                    return dt
                k2 *= 2
            return t2 / (k2 // 2)

        dt = sorted(measure_once() for _ in range(3))[1]
        print(
            f"{label}: {dt*1e6:.1f} us -> {flops/dt/1e9:.1f} GFLOP/s",
            flush=True,
        )
        return dt

    # integrated default: the supernode-dense MXU path (round 4)
    dA = device_matrix(A, backend)
    assert dA.sd_bs == 3, f"expected 3x3 SD lowering, got {dA.sd_bs}"
    dt_sd = measure(dA, "SD supernode-dense SpMV (default lowering)")

    # forced BSR (the round-2/3 default), same matrix
    os.environ["PA_TPU_SD"] = "0"
    try:
        dA_bsr = DeviceMatrix(A, backend)
        assert dA_bsr.bsr_bs == 3, f"expected 3x3 BSR, got {dA_bsr.bsr_bs}"
        dt_bsr = measure(dA_bsr, "BSR(3x3) SpMV (PA_TPU_SD=0)")

        # forced generic ELL (the pre-round-2 lowering)
        os.environ["PA_TPU_BSR"] = "0"
        try:
            dA_ell = DeviceMatrix(A, backend)
        finally:
            del os.environ["PA_TPU_BSR"]
    finally:
        del os.environ["PA_TPU_SD"]
    assert dA_ell.bsr_bs is None and dA_ell.dia_mode is None
    dt_ell = measure(dA_ell, "padded-ELL SpMV (both fast paths off)")

    xv = np.asarray(xe.values.part_values()[0], dtype=np.float32)
    csr_spmv(M, xv)
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        csr_spmv(M, xv)
        ts.append(time.perf_counter() - t0)
    host_dt = statistics.median(ts)
    flops = dA.flops_per_spmv  # same dA as the SD leg above
    print(
        f"host oracle: {host_dt*1e3:.1f} ms; SD vs BSR {dt_bsr/dt_sd:.1f}x, "
        f"BSR vs ELL {dt_ell/dt_bsr:.1f}x, SD vs host {host_dt/dt_sd:.1f}x",
        flush=True,
    )
    import json

    print(json.dumps({
        "metric": f"irregular_spmv_gflops_tet_elasticity_{n}cube_f32",
        "value": round(flops / dt_sd / 1e9, 2),
        "unit": "GFLOP/s",
        "vs_baseline": round(dt_bsr / dt_sd, 2),
        "bsr_gflops": round(flops / dt_bsr / 1e9, 2),
        "ell_gflops": round(flops / dt_ell / 1e9, 2),
    }))


if __name__ == "__main__":
    main()
