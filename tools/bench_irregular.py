"""Irregular-graph SpMV throughput on one chip (BASELINE configs[5]):
the Morton-ordered unstructured-tet elasticity operator, at SEVERAL mesh
sizes, recorded to ``IRREGULAR_BENCH.json`` with a reproducibility band
at EVERY size (round-5 directive 3 introduced the 32^3 band — the
round-4 "11.1 GFLOP/s" lived only in a commit message; round 6 banded
the 48^3/64^3 rows too, so regressions there no longer ship silently).

Lowerings measured per size on the real integrated paths:
* SD — supernode-dense MXU path with BUCKETED group widths (default),
* BSR — 3x3 node-block gather path (PA_TPU_SD=0),
* ELL — generic padded-ELL (both fast paths off; smallest size only,
  its element-at-a-time gathers take minutes on big meshes).

    python tools/bench_irregular.py            # sizes 32,48
    PA_IRR_SIZES=32 python tools/bench_irregular.py
    PA_IRR_ELL=0 ...                           # skip the ELL leg
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

#: reproducibility bands for the SD GFLOP/s at EVERY measured size (not
#: just the 32^3 headline — a silent 48^3/64^3 regression used to ship
#: unbanded), derived from repeated same-protocol runs on this chip —
#: see docs/performance.md (irregular section) for the provenance table.
#: 64^3 is legitimately lower (wider per-group unions, see the row note).
BANDS_SD = {
    32: (10.0, 14.0),
    48: (9.5, 13.5),
    64: (4.5, 7.5),
}
METHODOLOGY = "v6-irregular"


def measure(dA, label, backend, xe, jax):
    import statistics
    from functools import partial

    from partitionedarrays_jl_tpu.parallel.tpu import (
        DeviceVector, _matrix_operands, _shard_ops, _spmv_body,
    )

    dx = DeviceVector.from_pvector(xe, backend, dA.col_layout)
    flops = dA.flops_per_spmv
    # the timing chain must pass the staged matrix operands as
    # ARGUMENTS: closing over them would inline hundreds of MB of
    # constants into the relay's compile request (HTTP 413 on the
    # SD lowering's densified blocks)
    ops = _matrix_operands(dA)
    body = _spmv_body(dA)
    mesh = backend.mesh(dA.row_layout.P)
    spec = backend.parts_spec()
    specs = jax.tree.map(lambda _: spec, ops)

    @partial(jax.jit, static_argnums=2)
    def chain(x, m, k):
        def shard_fn(xs, ms):
            mm = _shard_ops(jax, ms)

            def step(_, y):
                y2, _x = body(y, mm)
                return y2 * np.float32(1e-3)

            return jax.lax.fori_loop(0, k, step, xs[0])[None]

        from partitionedarrays_jl_tpu.parallel.tpu import _shard_map
        shard_map = _shard_map()

        return shard_map(
            shard_fn, mesh=mesh, in_specs=(spec, specs),
            out_specs=spec, check_vma=False,
        )(x, m).sum()

    def chain_time(k, nreps=5):
        float(chain(dx.data, ops, k))
        float(chain(dx.data, ops, k))
        ts = []
        for _ in range(nreps):
            t0 = time.perf_counter()
            v = float(chain(dx.data, ops, k))
            ts.append(time.perf_counter() - t0)
        assert v == v
        return statistics.median(ts)

    def measure_once():
        k1, k2 = 20, 220
        t1 = chain_time(k1)
        for _ in range(4):
            t2 = chain_time(k2)
            dt = (t2 - t1) / (k2 - k1)
            if dt > 0:
                return dt
            k2 *= 2
        return t2 / (k2 // 2)

    dt = sorted(measure_once() for _ in range(3))[1]
    print(
        f"{label}: {dt*1e6:.1f} us -> {flops/dt/1e9:.1f} GFLOP/s",
        flush=True,
    )
    return dt


def bench_size(n, backend, jax, pa, with_ell):
    from partitionedarrays_jl_tpu.models import assemble_elasticity_tet
    from partitionedarrays_jl_tpu.ops.sparse import csr_spmv
    from partitionedarrays_jl_tpu.parallel.tpu import (
        DeviceMatrix, device_matrix,
    )

    def driver(parts):
        t0 = time.perf_counter()
        A, b, xe, x0 = assemble_elasticity_tet(parts, (n, n, n))
        print(
            f"assembled {n}^3 nodes = {A.rows.ngids/1e3:.0f}k dofs "
            f"in {time.perf_counter()-t0:.1f}s",
            flush=True,
        )
        A.values = pa.map_parts(
            lambda M: pa.CSRMatrix(
                M.indptr, M.indices,
                (M.data / np.abs(M.data).max()).astype(np.float32), M.shape
            ),
            A.values,
        )
        A.invalidate_blocks()
        xe.values = pa.map_parts(lambda v: np.asarray(v, np.float32), xe.values)
        return A, xe

    A, xe = pa.prun(driver, backend, 1)
    M = A.values.part_values()[0]
    nnz, rows = int(M.nnz), M.shape[0]
    rec = {"n": n, "dofs": rows, "nnz": nnz}

    # integrated default: the supernode-dense MXU path, bucketed widths
    dA = device_matrix(A, backend)
    rec["lowering"] = (
        "sd" if dA.sd_bs else ("bsr" if dA.bsr_bs else "ell")
    )
    if dA.sd_bs:
        rec["sd_buckets"] = len(dA.sd_idx)
        rec["sd_widths"] = [int(v.shape[-1]) for v in dA.sd_vals]
    flops = dA.flops_per_spmv
    dt_sd = measure(
        dA, f"{n}^3 default ({rec['lowering']})", backend, xe, jax
    )
    # key the record by what actually ran: a part that lowered to BSR or
    # ELL must not stamp its rate under `sd_gflops`
    rec[f"{rec['lowering']}_gflops"] = round(flops / dt_sd / 1e9, 2)

    os.environ["PA_TPU_SD"] = "0"
    try:
        # a part whose DEFAULT lowering was already bsr/ell keeps the
        # default run's number — re-measuring the same lowering would
        # silently overwrite it and self-compare in the summary
        if rec["lowering"] != "bsr":
            dA_bsr = DeviceMatrix(A, backend)
            assert dA_bsr.bsr_bs == 3, dA_bsr.bsr_bs
            dt_bsr = measure(dA_bsr, f"{n}^3 BSR(3x3)", backend, xe, jax)
            rec["bsr_gflops"] = round(flops / dt_bsr / 1e9, 2)
        if with_ell and rec["lowering"] != "ell":
            from partitionedarrays_jl_tpu.parallel.tpu import (
                ELLFootprintError,
            )

            os.environ["PA_TPU_BSR"] = "0"
            try:
                dA_ell = DeviceMatrix(A, backend)
            except ELLFootprintError as e:
                # the library's footprint guard (the former inline n<64
                # check here, moved into the lowering itself) refuses the
                # program that faulted the relay's TPU worker at 64^3 —
                # record the refusal instead of a number
                print(f"{n}^3 padded-ELL refused by footprint guard", flush=True)
                rec["ell_skipped"] = f"footprint guard: {e}"[:200]
                dA_ell = None
            finally:
                del os.environ["PA_TPU_BSR"]
            if dA_ell is not None:
                assert dA_ell.bsr_bs is None and dA_ell.dia_mode is None
                dt_ell = measure(
                    dA_ell, f"{n}^3 padded-ELL", backend, xe, jax
                )
                rec["ell_gflops"] = round(flops / dt_ell / 1e9, 2)
    finally:
        del os.environ["PA_TPU_SD"]

    # host oracle on the same local CSR
    import statistics

    xv = np.asarray(xe.values.part_values()[0], dtype=np.float32)
    csr_spmv(M, xv)
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        csr_spmv(M, xv)
        ts.append(time.perf_counter() - t0)
    rec["host_gflops"] = round(flops / statistics.median(ts) / 1e9, 2)
    return rec


def oh_bucket_ab(n, backend, jax, pa):
    """A/B of the BUCKETED A_oh boundary-block staging (round-7
    satellite, closing the round-4 directive-7 leftover): lower the
    multi-part elasticity operator with PA_TPU_OH_BUCKETS on (default)
    and off (one global-width pad), and record the padded ghost-NODE
    gather count per SpMV for each — on a TPU the element-at-a-time
    gathers ARE the boundary cost, so the static count is the signal
    (the kernel is identical math either way; tests pin value parity).
    Needs >= 2 devices for a real boundary block; returns None
    otherwise."""
    from partitionedarrays_jl_tpu.models import assemble_elasticity_tet
    from partitionedarrays_jl_tpu.parallel.tpu import DeviceMatrix

    del backend  # the A/B builds its own multi-part mesh
    devs = jax.devices()
    P = max(p for p in (8, 4, 2, 1) if p <= len(devs))
    if P < 2:
        return None

    def driver(parts):
        A, b, xe, x0 = assemble_elasticity_tet(parts, (n, n, n))
        A.values = pa.map_parts(
            lambda M: pa.CSRMatrix(
                M.indptr, M.indices,
                (M.data / np.abs(M.data).max()).astype(np.float32),
                M.shape,
            ),
            A.values,
        )
        A.invalidate_blocks()
        return A

    from partitionedarrays_jl_tpu.parallel.tpu import TPUBackend

    b2 = TPUBackend(devices=devs[:P])
    A = pa.prun(driver, b2, P)

    def gathers(dA):
        if dA.ohb_bs is None:
            return None
        return int(
            sum(
                int(np.prod(c.shape[:3]))  # P * rows_c * Lb_c node ids
                for c in dA.ohb_cols
            )
        )

    dA_b = DeviceMatrix(A, b2)
    os.environ["PA_TPU_OH_BUCKETS"] = "0"
    try:
        dA_g = DeviceMatrix(A, b2)
    finally:
        del os.environ["PA_TPU_OH_BUCKETS"]
    gb, gg = gathers(dA_b), gathers(dA_g)
    if gb is None or gg is None:
        return {"n": n, "parts": P, "note": "A_oh node-block path did not engage"}
    return {
        "n": n,
        "parts": P,
        "oh_buckets": len(dA_b.ohb_cols),
        "bucket_widths": [int(c.shape[-1]) for c in dA_b.ohb_cols],
        "global_pad_width": int(dA_g.ohb_cols[0].shape[-1]),
        "padded_node_gathers_bucketed": gb,
        "padded_node_gathers_global": gg,
        "gather_reduction": round(gg / max(gb, 1), 3),
    }


def main():
    import jax

    import partitionedarrays_jl_tpu as pa
    from partitionedarrays_jl_tpu.parallel.tpu import TPUBackend

    sizes = [
        int(s) for s in os.environ.get("PA_IRR_SIZES", "32,48").split(",")
    ]
    out_path = os.environ.get(
        "PA_IRR_OUT",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "IRREGULAR_BENCH.json",
        ),
    )
    from partitionedarrays_jl_tpu.telemetry import artifacts

    backend = TPUBackend(devices=jax.devices()[:1])
    rows = []
    rec = {"methodology": METHODOLOGY, "sizes": rows}
    for n in sizes:
        # ELL only on the SMALLEST mesh (docstring contract): its
        # element-at-a-time gathers take minutes on bigger ones. The
        # former inline 64^3 fault check now lives in the LIBRARY
        # (tpu.py:_ell_guard_check) — bench_size records a clean refusal
        # if this size's footprint is past the device-fault ceiling.
        # PA_IRR_ELL=0 skips the leg entirely.
        r = bench_size(
            n, backend, jax, pa,
            with_ell=(
                n == min(sizes)
                and os.environ.get("PA_IRR_ELL", "1") != "0"
            ),
        )
        if n in BANDS_SD and r["lowering"] == "sd":
            # the bands are calibrated for the supernode-dense lowering;
            # stamping one on a BSR/ELL fallback would mislabel the
            # artifact. EVERY banded size gets a verdict so 48^3/64^3
            # regressions no longer ship silently.
            lo, hi = BANDS_SD[n]
            r["band"] = {
                "key": f"irregular_sd_gflops_{n}",
                "lo": lo, "hi": hi, "measured": r["sd_gflops"],
            }
            r["in_band"] = bool(lo <= r["sd_gflops"] <= hi)
        rows.append(r)
        artifacts.write(out_path, rec, tool="bench_irregular", echo=False)
        jax.clear_caches()
    try:
        ab = oh_bucket_ab(min(sizes), backend, jax, pa)
        if ab is not None:
            rec["oh_bucket_ab"] = ab
            print(json.dumps({"oh_bucket_ab": ab}), flush=True)
            artifacts.write(out_path, rec, tool="bench_irregular",
                            echo=False)
    except Exception as e:  # the A/B must never mask the primary rows
        print(f"oh-bucket A/B failed: {type(e).__name__}: {e}", file=sys.stderr)
    head = rows[0]
    head_gflops = head[f"{head['lowering']}_gflops"]
    # vs_baseline compares the default lowering against the dedicated
    # BSR run; when the default IS bsr there is no distinct baseline —
    # emit null rather than a vacuous 1.0
    vs = (
        round(head_gflops / max(head["bsr_gflops"], 1e-9), 2)
        if head["lowering"] != "bsr" and "bsr_gflops" in head
        else None
    )
    print(json.dumps({
        "metric": f"irregular_spmv_gflops_tet_elasticity_{sizes[0]}cube_f32",
        "value": head_gflops,
        "unit": "GFLOP/s",
        "vs_baseline": vs,
        "artifact": os.path.basename(out_path),
    }))


if __name__ == "__main__":
    main()
