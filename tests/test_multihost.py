"""Multi-host legs exercised with REAL OS processes (reference analog:
the mpiexec suite, test/mpi/runtests.jl:1-20 — each test spawns a real
multi-rank job and asserts clean completion).

Two tiers, split by what they actually need (ISSUE 18):

* **Plan-soundness legs** — replicated planning must produce the
  IDENTICAL exchange schedule on every controller. That is host-side
  NumPy work, so it runs through the `tools/plan_multiproc.py` spawn
  harness on EVERY host: K real processes each build + verify the
  two-level plan and the parent pins cross-process digest agreement.
  No backend capability involved — these legs never skip.
* **Execution legs** — two `jax.distributed` CPU processes x 4 virtual
  devices form one 8-device global mesh and run the compiled CG over
  it. Only THESE carry the named skip for jaxlib CPU runtimes without
  cross-process collectives (the documented backend limitation).
"""
import json
import os
import socket
import subprocess
import sys

import pytest

TESTS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TESTS)
WORKER = os.path.join(TESTS, "_multihost_worker.py")


def _plan_multiproc():
    """Import the harness as a REAL module (not an importlib shim):
    the spawn pool pickles its worker by reference, so the children
    must be able to ``import plan_multiproc`` — they inherit this
    process's sys.path."""
    tools = os.path.join(REPO, "tools")
    if tools not in sys.path:
        sys.path.insert(0, tools)
    import plan_multiproc

    return plan_multiproc

#: jaxlib builds whose CPU runtime lacks cross-process collectives fail
#: the compiled solve with exactly this error. That is a missing BACKEND
#: capability, not a bug in this library's multi-host story — skip with
#: the reason instead of failing, and keep the full assertion strength
#: wherever the capability exists (real multiprocess CPU builds, TPU
#: slices). The string is jaxlib's own message, matched verbatim.
_NO_MULTIPROCESS_BACKEND = (
    "Multiprocess computations aren't implemented on the CPU backend"
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_twolevel_plan_agreement():
    """Plan-soundness leg (never skips): two REAL spawned processes
    each build the (2, 4)-part two-level plan from the identical
    replicated inputs, run the full verifier battery in-process (the
    worker asserts zero defects), and the structural digests —
    `plan_fingerprint` + `canonical_exchange_fingerprint` — agree
    across processes. A forked schedule would deadlock the paired
    ppermutes on a real slice; this pins it at plan time."""
    pm = _plan_multiproc()
    results, agree = pm.run_twolevel(procs=2)
    assert agree, [r["digest"] for r in results]
    assert len(results) == 2
    for r in results:
        # the dcn-weighted probe's aggregation structure: 8 flat
        # cross-node edges collapse to 2 node-pair transfers, through
        # the staged gather/node/scatter tiers
        assert r["slow_edges_flat"] == 8 and r["node_pairs"] == 2
        assert r["use"] is True
        for tier in ("gather", "node", "scatter"):
            assert tier in r["tiers"], r["tiers"]
        assert r["wire_rounds"] == sum(
            1 for t in r["tiers"] if t not in ("local_out", "local_in")
        )
    # distinct OS processes, both distinct from this controller
    assert len({r["pid"] for r in results} | {os.getpid()}) == 3


def test_two_process_twolevel_plan_cli_smoke():
    """The harness's operator surface: `plan_multiproc.py --twolevel`
    exits zero and reports agreement — the command a multi-host
    operator runs before committing a node map to a job config."""
    out = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "plan_multiproc.py"),
         "--twolevel"],
        capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stderr or out.stdout
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["agree"] is True
    assert rec["metric"] == "twolevel_plan_cross_process_agreement"


def test_two_process_fdm_solve():
    """Execution leg: the compiled CG over a true two-process global
    mesh (named skip below when the jaxlib CPU runtime cannot execute
    cross-process programs — plan soundness is covered unskippably
    above)."""
    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "JAX_ENABLE_X64")
    }
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(port), str(pid), "2"],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any(
        p.returncode != 0 and _NO_MULTIPROCESS_BACKEND in out
        for p, out in zip(procs, outs)
    ):
        # the cluster formed (jax.distributed handshake succeeded) but
        # the runtime cannot EXECUTE cross-process programs — a
        # documented jaxlib CPU-backend limitation in this environment
        pytest.skip(
            "jaxlib CPU runtime lacks multiprocess collectives "
            f"({_NO_MULTIPROCESS_BACKEND!r}); the two-process DCN smoke "
            "test needs a multiprocess-capable backend (TPU slice or a "
            "jaxlib CPU build with cross-process support)"
        )
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out}"
        assert f"MULTIHOST_OK pid={pid}" in out, out
