"""Two-process multi-host smoke test: the DCN story exercised with REAL
processes (reference analog: the mpiexec suite, test/mpi/runtests.jl:1-20
— each test spawns a real multi-rank job and asserts clean completion).

Two `jax.distributed` CPU processes x 4 virtual devices each form one
8-device global mesh; both run the identical FDM driver (replicated
planning), the compiled CG executes over the global mesh, and each
controller checks the solve plus cross-process agreement of the result.
"""
import os
import socket
import subprocess
import sys

WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_fdm_solve():
    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "JAX_ENABLE_X64")
    }
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(port), str(pid), "2"],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out}"
        assert f"MULTIHOST_OK pid={pid}" in out, out
