"""Two-process multi-host smoke test: the DCN story exercised with REAL
processes (reference analog: the mpiexec suite, test/mpi/runtests.jl:1-20
— each test spawns a real multi-rank job and asserts clean completion).

Two `jax.distributed` CPU processes x 4 virtual devices each form one
8-device global mesh; both run the identical FDM driver (replicated
planning), the compiled CG executes over the global mesh, and each
controller checks the solve plus cross-process agreement of the result.
"""
import os
import socket
import subprocess
import sys

import pytest

WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_multihost_worker.py")

#: jaxlib builds whose CPU runtime lacks cross-process collectives fail
#: the compiled solve with exactly this error. That is a missing BACKEND
#: capability, not a bug in this library's multi-host story — skip with
#: the reason instead of failing, and keep the full assertion strength
#: wherever the capability exists (real multiprocess CPU builds, TPU
#: slices). The string is jaxlib's own message, matched verbatim.
_NO_MULTIPROCESS_BACKEND = (
    "Multiprocess computations aren't implemented on the CPU backend"
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_fdm_solve():
    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "JAX_ENABLE_X64")
    }
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(port), str(pid), "2"],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any(
        p.returncode != 0 and _NO_MULTIPROCESS_BACKEND in out
        for p, out in zip(procs, outs)
    ):
        # the cluster formed (jax.distributed handshake succeeded) but
        # the runtime cannot EXECUTE cross-process programs — a
        # documented jaxlib CPU-backend limitation in this environment
        pytest.skip(
            "jaxlib CPU runtime lacks multiprocess collectives "
            f"({_NO_MULTIPROCESS_BACKEND!r}); the two-process DCN smoke "
            "test needs a multiprocess-capable backend (TPU slice or a "
            "jaxlib CPU build with cross-process support)"
        )
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out}"
        assert f"MULTIHOST_OK pid={pid}" in out, out
