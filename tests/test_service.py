"""pasolve — the fault-isolating multi-tenant solve service
(`partitionedarrays_jl_tpu.service`).

The four contracts pinned here:

* **Admission** — bounded queue + typed `AdmissionRejected`
  backpressure (never unbounded buffering, never a silent drop), and a
  draining service refuses new work.
* **Coalescing** — FIFO slabs of compatible requests (same
  tol/maxiter/dtype) up to ``PA_SERVE_KMAX``, ragged leftovers run
  as-is, late-arriving compatible requests top a chunked slab back up.
* **Containment** — THE tentpole pin: an injected fault hitting exactly
  one request in a K=4 slab fails/retries that request with its typed
  error and full event trail, while every co-batched request completes
  with a trajectory BITWISE equal to its solo solve (strict-bits,
  4-part conformance fixture); and the service consumes the IDENTICAL
  compiled program as the bare block body (program-cache hit — zero
  extra collectives by construction, with the K-independence HLO pin
  re-run through service-shaped parameters).
* **Deadlines / lifecycle** — per-request deadlines enforced at chunk
  boundaries as typed `SolveDeadlineError`; drain/shutdown refuses
  admissions, checkpoints in-flight iterates, suspends never-started
  requests.

Budget note: everything host-path runs on the sequential backend
(tiny 8x8 Poisson, milliseconds); only the containment + parity tests
compile device programs, on the tiny 4-part fixture.
"""
import json
import os

import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu import telemetry
from partitionedarrays_jl_tpu.models import assemble_poisson, gather_pvector
from partitionedarrays_jl_tpu.parallel.faults import inject_faults
from partitionedarrays_jl_tpu.parallel.health import (
    NonFiniteError,
    SolveDeadlineError,
)
from partitionedarrays_jl_tpu.service import (
    AdmissionRejected,
    SolveService,
    compat_key,
    next_slab,
    top_up,
)

from test_fused_cg import _fixture_spd_system

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(driver):
    assert pa.prun(driver, pa.sequential, (2, 2))


def _has_event(rec, kind, label=None):
    return any(
        e.kind == kind and (label is None or e.label == label)
        for e in rec.events
    )


class FakeClock:
    """Deterministic service clock: every reading advances by ``dt``."""

    def __init__(self, dt=1.0):
        self.t = 0.0
        self.dt = dt

    def __call__(self):
        self.t += self.dt
        return self.t


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------


def test_admission_backpressure_typed_and_counted():
    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        svc = SolveService(A, queue_depth=2)
        svc.submit(b, x0=x0, tag="a")
        svc.submit(b, x0=x0, tag="b")
        before = telemetry.counter("events.admission_rejected")
        with pytest.raises(AdmissionRejected) as ei:
            svc.submit(b, x0=x0, tag="c")
        assert ei.value.diagnostics["reason"] == "queue_full"
        assert ei.value.diagnostics["queued"] == 2
        assert ei.value.diagnostics["depth"] == 2
        assert telemetry.counter("events.admission_rejected") == before + 1
        assert svc.stats["rejected"] == 1
        # draining the queue frees capacity again
        svc.drain()
        svc.submit(b, x0=x0, tag="c2")
        svc.drain()
        assert svc.stats["admitted"] == 3
        return True

    _run(driver)


def test_admission_validates_request_shape():
    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        svc = SolveService(A)
        with pytest.raises(Exception, match="tol"):
            svc.submit(b, tol=0.0)
        with pytest.raises(Exception, match="deadline"):
            svc.submit(b, deadline=-1.0)
        with pytest.raises(Exception, match="maxiter"):
            svc.submit(b, maxiter=0)
        return True

    _run(driver)


# ---------------------------------------------------------------------------
# slab coalescing
# ---------------------------------------------------------------------------


def test_coalescing_rules_and_ragged_leftovers():
    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        svc = SolveService(A, kmax=4, queue_depth=16)
        hs = [
            svc.submit(b, x0=x0, tol=1e-9, tag=f"t{i}") for i in range(5)
        ]
        other = svc.submit(b, x0=x0, tol=1e-6, tag="loose")
        # FIFO anchor: first slab is the four oldest tol=1e-9 requests
        # (the tol=1e-6 request keeps its place for its own slab), then
        # the ragged leftover, then the incompatible one
        assert svc.step() == 4
        assert [h.state for h in hs[:4]] == ["done"] * 4
        assert hs[4].state == "queued" and other.state == "queued"
        svc.drain()
        assert svc.stats["slabs"] == 3
        assert all(h.result()[1]["converged"] for h in hs)
        assert other.result()[1]["converged"]
        # per-request record: queue + slab + done events all present
        rec = hs[0].record
        assert _has_event(rec, "request_queued", "t0")
        assert _has_event(rec, "slab_formed", "K=4")
        assert _has_event(rec, "request_done", "t0")
        return True

    _run(driver)


def test_batcher_unit_fifo_and_top_up():
    class R:
        def __init__(self, tol, maxiter=100, dtype="float64"):
            self.tol, self.maxiter = tol, maxiter

            class B:
                pass

            self.b = B()
            self.b.dtype = np.dtype(dtype)

    q = [R(1e-8), R(1e-8), R(1e-6), R(1e-8), R(1e-8)]
    slab = next_slab(q, kmax=3)
    assert [r.tol for r in slab] == [1e-8] * 3
    assert [r.tol for r in q] == [1e-6, 1e-8]
    assert compat_key(slab[0]) == (1e-8, 100, "float64")
    added = top_up(q, slab, kmax=5)
    assert [r.tol for r in added] == [1e-8]
    assert [r.tol for r in q] == [1e-6]
    # dtype splits slabs too: an f32 request cannot share an f64 slab
    q2 = [R(1e-8), R(1e-8, dtype="float32")]
    assert len(next_slab(q2, kmax=4)) == 1 and len(q2) == 1


# ---------------------------------------------------------------------------
# containment: ejection + solo retry on the host oracle
# ---------------------------------------------------------------------------


def test_transient_fault_ejects_then_solo_retry_heals():
    """A one-shot wire fault poisons ONE column of a host slab: that
    column is ejected and retried SOLO (the fault does not refire), the
    co-batched column never notices, and both end bitwise equal to the
    clean solves — with the whole story in the event log."""

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        x_clean, _ = pa.cg(A, b, x0=x0, tol=1e-9)
        svc = SolveService(A, kmax=2, retries=1, retry_backoff=0.0)
        # call=5 lands inside the FIRST column's solo loop (the host
        # slab runs columns in sequence, ~14 exchanges each)
        with inject_faults("nan@part=1,call=5", seed=1):
            r0 = svc.submit(b, x0=x0, tol=1e-9, tag="poisoned")
            r1 = svc.submit(b, x0=x0, tol=1e-9, tag="clean")
            svc.drain()
        assert r0.state == "done" and r1.state == "done"
        x0_, i0 = r0.result()
        x1_, i1 = r1.result()
        assert i0["resolved_via"] == "solo_retry"
        assert i0["converged"] and i1["converged"]
        np.testing.assert_array_equal(
            gather_pvector(x0_), gather_pvector(x_clean)
        )
        np.testing.assert_array_equal(
            gather_pvector(x1_), gather_pvector(x_clean)
        )
        assert svc.stats["ejected"] == 1
        assert svc.stats["retried_solo"] == 1
        rec = r0.record
        assert _has_event(rec, "fault_injected", "nan")
        assert _has_event(rec, "column_verdict", "block-host")
        assert _has_event(rec, "column_ejected", "NonFiniteError")
        assert _has_event(rec, "request_done", "poisoned")
        # the clean request's record shows NO recovery of its own
        assert not _has_event(r1.record, "request_failed")
        return True

    _run(driver)


def test_persistent_fault_fails_typed_after_retries():
    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        bad = b.copy()

        def poison(i, vals):
            if int(i.part) == 0:
                np.asarray(vals)[0] = np.nan

        pa.map_parts(poison, bad.rows.partition, bad.values)
        svc = SolveService(A, kmax=3, retries=1, retry_backoff=0.0)
        rb = svc.submit(bad, x0=x0, tol=1e-9, tag="bad")
        rg = svc.submit(b, x0=x0, tol=1e-9, tag="good")
        svc.drain()
        assert rg.result()[1]["converged"]
        assert rb.state == "failed"
        with pytest.raises(NonFiniteError):
            rb.result()
        assert isinstance(rb.error, NonFiniteError)
        assert svc.stats["failed"] == 1 and svc.stats["ejected"] == 1
        # failed request's record is finalized as an aborted record
        # with the trail: ejection, then the typed failure
        assert rb.record.status == "raised"
        assert _has_event(rb.record, "column_ejected")
        assert _has_event(rb.record, "request_failed", "bad")
        return True

    _run(driver)


def test_solo_retry_budget_not_multiplied(tmp_path):
    """With a service ``checkpoint_dir`` the solo path is
    `solve_with_recovery`, which owns the WHOLE retry budget as
    checkpoint-tier restarts: ``retries`` solver invocations total. It
    used to be wrapped in `retry_with_backoff` ON TOP of its own
    ``max_restarts``, multiplying the budgets into retries × (1 +
    restarts) full solves of a deterministically-failing request."""

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        bad = b.copy()

        def poison(i, vals):
            if int(i.part) == 0:
                np.asarray(vals)[0] = np.nan

        pa.map_parts(poison, bad.rows.partition, bad.values)
        before = telemetry.counter("events.health_error")
        svc = SolveService(
            A, kmax=2, retries=2, retry_backoff=0.0,
            checkpoint_dir=str(tmp_path / "ck"),
        )
        rb = svc.submit(bad, x0=x0, tol=1e-9, tag="bad")
        svc.drain()
        assert rb.state == "failed"
        # one detection in the slab + exactly ``retries`` solo
        # attempts — the multiplied budget fired 2×(1+2)=6 solo solves
        attempts = telemetry.counter("events.health_error") - before
        assert attempts == 1 + 2, attempts
        return True

    _run(driver)


def test_solo_retry_stops_at_deadline():
    """A deadline-carrying request cannot keep retrying solo past its
    deadline: the service passes its deadline test as
    `retry_with_backoff`'s ``give_up`` hook, so once the clock runs out
    the remaining attempts are abandoned and the request fails typed."""

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        bad = b.copy()

        def poison(i, vals):
            if int(i.part) == 0:
                np.asarray(vals)[0] = np.nan

        pa.map_parts(poison, bad.rows.partition, bad.values)
        before = telemetry.counter("events.health_error")
        # every clock reading advances 1s: the generous-looking
        # deadline is over after the first solo attempt's readings
        svc = SolveService(
            A, kmax=2, retries=8, retry_backoff=0.0,
            clock=FakeClock(dt=1.0),
        )
        rb = svc.submit(bad, x0=x0, tol=1e-9, deadline=4.0, tag="bad")
        svc.drain()
        assert rb.state == "failed"
        with pytest.raises(NonFiniteError):
            rb.result()
        attempts = telemetry.counter("events.health_error") - before
        assert attempts < 1 + 8, (
            f"give_up did not cut the retry budget: {attempts} "
            "health errors for a request whose deadline expired"
        )
        return True

    _run(driver)


# ---------------------------------------------------------------------------
# deadlines at chunk boundaries
# ---------------------------------------------------------------------------


def test_deadline_expires_typed_at_chunk_boundary():
    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        clock = FakeClock(dt=1.0)
        svc = SolveService(A, kmax=2, chunk=4, clock=clock)
        rd = svc.submit(b, x0=x0, tol=1e-9, deadline=0.5, tag="tight")
        rf = svc.submit(b, x0=x0, tol=1e-9, tag="free")
        svc.drain()
        assert rd.state == "failed"
        with pytest.raises(SolveDeadlineError) as ei:
            rd.result()
        d = ei.value.diagnostics
        assert d["request"] == "tight" and d["deadline_s"] == 0.5
        assert d["iteration"] == rd.iterations > 0
        # the co-batched request without a deadline completes
        assert rf.result()[1]["converged"]
        assert svc.stats["deadline_expired"] == 1
        rec = rd.record
        assert _has_event(rec, "deadline_expired", "tight")
        assert _has_event(rec, "health_error", "SolveDeadlineError")
        # a generous deadline does NOT expire
        clock2 = FakeClock(dt=0.001)
        svc2 = SolveService(A, kmax=2, chunk=4, clock=clock2)
        ok = svc2.submit(b, x0=x0, tol=1e-9, deadline=60.0, tag="roomy")
        svc2.drain()
        assert ok.result()[1]["converged"]
        assert svc2.stats["deadline_expired"] == 0
        return True

    _run(driver)


def test_chunked_solve_keeps_original_convergence_target():
    """Chunk continuation must not re-baseline the convergence
    criterion: each chunk is a fresh cg call whose relative test runs
    against the CHUNK-start residual, so on a large-norm system the
    effective threshold used to tighten from tol·‖r0‖ toward absolute
    tol as chunks progressed — burning extra iterations against the
    deadline and over-solving past the request's contract. The target
    is now fixed at the request's first chunk."""

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        # a large-norm variant (scale b AND x0: the system is linear,
        # so the Dirichlet rows stay consistent) — ‖r0‖ ≈ 9e4 makes the
        # relative target tol·‖r0‖ five orders looser than absolute tol
        big, bx0 = b.copy(), x0.copy()

        def _scale(iset, vals):
            np.asarray(vals)[...] *= 1e4

        pa.map_parts(_scale, big.rows.partition, big.values)
        pa.map_parts(_scale, bx0.rows.partition, bx0.values)
        tol = 1e-9
        from partitionedarrays_jl_tpu.models.solvers import cg

        _, solo = cg(A, big, x0=bx0, tol=tol)
        assert solo["converged"]
        r0 = float(np.asarray(solo["residuals"])[0])
        target = tol * max(1.0, r0)
        assert target > 100 * tol  # the regression needs a loose target

        # multi-chunk (chunk < solo iterations): the request must stop
        # at the first boundary meeting ITS OWN target — converged with
        # a residual under tol·‖r0‖ but NOT over-solved to absolute tol
        # (the re-baselined criterion drove it there before the fix)
        clock = FakeClock(dt=0.001)
        svc = SolveService(A, kmax=2, chunk=10, clock=clock)
        h = svc.submit(big, x0=bx0, tol=tol, deadline=1e6, tag="big")
        svc.drain()
        _, inf = h.result()
        res_end = float(np.asarray(inf["residuals"])[-1])
        assert inf["converged"] and inf["status"] == "converged"
        assert res_end <= target  # the verdict is honest
        assert res_end > 10 * tol, (
            "chunked solve over-solved to the re-baselined absolute "
            f"tolerance ({res_end:.3e}) instead of stopping at the "
            f"request's target ({target:.3e})"
        )
        # single-chunk (chunk ≥ solo iterations): identical to solo
        svc2 = SolveService(
            A, kmax=2, chunk=25, clock=FakeClock(dt=0.001)
        )
        h2 = svc2.submit(big, x0=bx0, tol=tol, deadline=1e6, tag="one")
        svc2.drain()
        _, inf2 = h2.result()
        assert inf2["converged"]
        assert h2.iterations == solo["iterations"]
        return True

    _run(driver)


def test_chunk_boundary_top_up_rebatches_late_arrivals():
    """A chunked slab tops itself back up with compatible requests that
    arrived after it started — the re-batching leg of coalescing."""

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        svc = SolveService(A, kmax=4, chunk=3, clock=FakeClock(0.001))
        r1 = svc.submit(b, x0=x0, tol=1e-9, deadline=99.0, tag="early")
        # a late compatible request lands in the queue mid-slab: inject
        # it by hooking the clock (called once per chunk boundary)
        state = {"n": 0, "late": None}
        base = svc.clock

        def clock():
            state["n"] += 1
            if state["n"] == 1 and state["late"] is None:
                state["late"] = svc.submit(
                    b, x0=x0, tol=1e-9, deadline=99.0, tag="late"
                )
            return base()

        svc.clock = clock
        svc.drain()
        late = state["late"]
        assert r1.result()[1]["converged"]
        assert late is not None and late.result()[1]["converged"]
        # the late request rode the SAME slab (no second slab formed
        # for it): one initial slab_formed plus one topped_up event
        assert svc.stats["slabs"] == 1
        assert any(
            e.kind == "slab_formed" and e.details.get("topped_up")
            for e in late.record.events
        )
        return True

    _run(driver)


# ---------------------------------------------------------------------------
# drain / shutdown lifecycle
# ---------------------------------------------------------------------------


def test_shutdown_drains_then_refuses():
    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        svc = SolveService(A)
        h = svc.submit(b, x0=x0, tol=1e-9)
        stats = svc.shutdown(drain=True)
        assert h.result()[1]["converged"]
        assert stats["completed"] == 1
        with pytest.raises(AdmissionRejected) as ei:
            svc.submit(b, x0=x0)
        assert ei.value.diagnostics["reason"] == "draining"
        return True

    _run(driver)


def test_nondrain_shutdown_checkpoints_inflight_and_suspends(tmp_path):
    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        ckdir = str(tmp_path / "svc-ck")
        svc = SolveService(
            A, kmax=1, chunk=4, checkpoint_dir=ckdir,
            clock=FakeClock(0.001),
        )
        # deadline makes the slab chunked, so the stop flag is seen at
        # the first chunk boundary with a real in-flight iterate
        r1 = svc.submit(b, x0=x0, tol=1e-12, deadline=99.0, tag="infl")
        r2 = svc.submit(b, x0=x0, tol=1e-9, tag="queued")
        svc._stop = True  # what shutdown(drain=False) sets mid-run
        assert svc.step() == 1  # the slab stops at its first boundary
        assert r1.state == "checkpointed"
        assert r1.iterations == 4 and r1.checkpoint_path
        with pytest.raises(RuntimeError, match="checkpointed"):
            r1.result()
        # the checkpointed iterate is loadable and resumable
        from partitionedarrays_jl_tpu.models.solvers import (
            _solver_state_ranges,
        )
        from partitionedarrays_jl_tpu.parallel.checkpoint import (
            load_solver_state,
        )

        st = load_solver_state(
            r1.checkpoint_path, _solver_state_ranges(A, b)
        )
        assert int(st["meta"]["it"]) == 4
        svc2 = SolveService(A)
        done = svc2.submit(b, x0=st["x"], tol=1e-9, tag="resumed")
        svc2.drain()
        assert done.result()[1]["converged"]
        # shutdown suspends the never-started request
        stats = svc.shutdown(drain=False)
        assert r2.state == "suspended" and stats["suspended"] == 1
        with pytest.raises(RuntimeError, match="resubmit"):
            r2.result()
        assert _has_event(r1.record, "request_checkpointed", "infl")
        assert _has_event(r2.record, "request_suspended", "queued")
        return True

    _run(driver)


def test_worker_thread_smoke():
    """The live-server mode: background worker drains submissions; a
    draining shutdown joins it and finishes the queue."""

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        svc = SolveService(A, kmax=2).start()
        hs = [svc.submit(b, x0=x0, tol=1e-9) for _ in range(3)]
        stats = svc.shutdown(drain=True)
        assert stats["completed"] == 3
        assert all(h.result()[1]["converged"] for h in hs)
        return True

    _run(driver)


# ---------------------------------------------------------------------------
# the paserve CLI
# ---------------------------------------------------------------------------


def test_paserve_cli_smoke(tmp_path, capsys):
    """The CLI harness end to end, in-process (a subprocess would
    re-import jax and burn tier-1 budget — the patrace precedent):
    a poisoned request fails typed, the rest complete, exit 0."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "paserve_cli", os.path.join(REPO, "tools", "paserve.py")
    )
    paserve = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(paserve)
    out_json = str(tmp_path / "serve.json")
    rc = paserve.main(
        [
            "--grid", "8", "8", "--requests", "4", "--kmax", "2",
            "--poison", "1", "--summary-json", out_json,
        ]
    )
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "paserve: OK" in out
    assert "NonFiniteError" in out
    summary = json.load(open(out_json))
    assert summary["ok"] is True
    assert summary["stats"]["ejected"] == 1
    assert summary["stats"]["admitted"] == 4
    states = [r["state"] for r in summary["requests"]]
    assert states == ["done", "failed", "done", "done"]


# ---------------------------------------------------------------------------
# the tentpole pin: bitwise containment in a shared compiled slab
# ---------------------------------------------------------------------------


def test_containment_bitwise_strict_bits_k4(monkeypatch):
    """One NaN-poisoned request in a K=4 compiled slab (strict-bits,
    4-part conformance fixture): the poisoned request fails with its
    typed error and full event trail; every co-batched request
    completes with a trajectory BITWISE equal to its solo solve."""
    monkeypatch.setenv("PA_TPU_STRICT_BITS", "1")
    import jax

    backend = pa.TPUBackend(devices=jax.devices()[:4])

    def driver(parts):
        A, b = _fixture_spd_system(parts)
        variants = []
        for j, f in enumerate((1.0, 0.5, 2.0)):
            bj = b.copy()

            def _scale(iset, vals, s=f):
                np.asarray(vals)[...] *= s

            pa.map_parts(_scale, bj.rows.partition, bj.values)
            variants.append(bj)
        bad = b.copy()

        def poison(i, vals):
            if int(i.part) == 1:
                np.asarray(vals)[0] = np.nan

        pa.map_parts(poison, bad.rows.partition, bad.values)
        return A, variants, bad

    A, variants, bad = pa.prun(driver, backend, 4)
    svc = SolveService(A, kmax=4, retries=0)
    hs = [
        svc.submit(bk, tol=1e-10, maxiter=200, tag=f"v{k}")
        for k, bk in enumerate(variants)
    ]
    hbad = svc.submit(bad, tol=1e-10, maxiter=200, tag="poisoned")
    svc.drain()
    assert svc.stats["slabs"] == 1  # ONE K=4 compiled slab
    # the poisoned request: typed failure + full event trail
    assert hbad.state == "failed"
    with pytest.raises(NonFiniteError):
        hbad.result()
    assert _has_event(hbad.record, "column_verdict")
    assert _has_event(hbad.record, "column_ejected", "nonfinite")
    assert _has_event(hbad.record, "request_failed", "poisoned")
    # every co-batched request: bitwise equal to its solo solve
    from partitionedarrays_jl_tpu.parallel.tpu import tpu_cg

    for k, (h, bk) in enumerate(zip(hs, variants)):
        x, info = h.result()
        x_solo, i_solo = tpu_cg(A, bk, tol=1e-10, maxiter=200)
        assert info["converged"] and i_solo["converged"]
        assert info["iterations"] == i_solo["iterations"], k
        np.testing.assert_array_equal(
            gather_pvector(x), gather_pvector(x_solo)
        )
        n = i_solo["iterations"] + 1
        np.testing.assert_array_equal(
            np.asarray(info["residuals"])[:n],
            np.asarray(i_solo["residuals"])[:n],
        )


def test_device_verdict_disabled_with_health_checks_off(monkeypatch):
    """PA_HEALTH_CHECKS=0 disables the device per-column verdict along
    with the guards: `column_health` must agree with the per-column
    infos (it used to flag 'nonfinite' while `columns` kept the plain
    solver outcome) and match the host oracle, where no
    SolverHealthError fires with health off."""
    import jax

    from partitionedarrays_jl_tpu.parallel.tpu import tpu_block_cg

    backend = pa.TPUBackend(devices=jax.devices()[:4])

    def driver(parts):
        A, b = _fixture_spd_system(parts)
        bad = b.copy()

        def poison(i, vals):
            if int(i.part) == 1:
                np.asarray(vals)[0] = np.nan

        pa.map_parts(poison, bad.rows.partition, bad.values)
        return A, b, bad

    A, b, bad = pa.prun(driver, backend, 4)
    # health ON: BOTH exports flag the poisoned column
    _, info = tpu_block_cg(
        A, [b, bad], tol=1e-8, maxiter=8, column_errors="report"
    )
    assert info["column_health"][1]["status"] == "nonfinite"
    assert info["columns"][1]["status"] == "nonfinite"
    # health OFF (host-side flag, same compiled program): no verdict
    # anywhere — the two per-column exports still agree
    monkeypatch.setenv("PA_HEALTH_CHECKS", "0")
    _, info = tpu_block_cg(
        A, [b, bad], tol=1e-8, maxiter=8, column_errors="report"
    )
    for col, verdict in zip(info["columns"], info["column_health"]):
        assert verdict["status"] == "ok"
        assert col["status"] != "nonfinite"


def test_service_consumes_bare_block_program(monkeypatch):
    """Zero extra collectives, pinned structurally: the service's slab
    solve consumes the SAME cached compiled program as a bare
    `tpu_block_cg` of the same shape (program-cache hit, byte-identical
    HLO), and the per-iteration collective count of that program is
    K-independent through service-shaped parameters."""
    import jax

    from partitionedarrays_jl_tpu.analysis import collective_counts
    from partitionedarrays_jl_tpu.parallel.tpu import (
        _block_on_cols_layout,
        _matrix_operands,
        device_matrix,
        make_cg_fn,
        tpu_block_cg,
    )

    backend = pa.TPUBackend(devices=jax.devices()[:4])

    def driver(parts):
        A, b = _fixture_spd_system(parts)
        return A, b

    A, b = pa.prun(driver, backend, 4)
    B = [b.copy() for _ in range(4)]
    # bare block body first: builds and caches the compiled program
    tpu_block_cg(A, B, tol=1e-8, maxiter=50)
    hits = telemetry.counter("program_cache.hit")
    svc = SolveService(A, kmax=4)
    hs = [svc.submit(bk, tol=1e-8, maxiter=50) for bk in B]
    svc.drain()
    for h in hs:
        h.result()
    assert telemetry.counter("program_cache.hit") > hits, (
        "the service must reuse the bare block body's compiled program"
    )
    # and that program's per-iteration collective count is K-independent
    # (the HLO A/B of test_block_cg, re-run at the service's shape)
    dA = device_matrix(A, backend)
    ops = _matrix_operands(dA)
    counts = {}
    for K in (1, 4):
        db = _block_on_cols_layout([b] * K, dA)
        dx0 = _block_on_cols_layout(
            [pa.PVector.full(0.0, A.cols) for _ in range(K)],
            dA, with_ghosts=True,
        )
        fn = make_cg_fn(dA, tol=1e-8, maxiter=50, rhs_batch=K)
        counts[K] = collective_counts(fn, db, dx0, db[..., 0], ops)
    assert any(counts[1].values())
    assert counts[1] == counts[4], counts
