"""Krylov solver suite: preconditioned CG + BiCGStab on both backends.

The reference gets its solver breadth for free from IterativeSolvers.jl
(src/Interfaces.jl:2752-2757 — any of its Krylov methods runs distributed
on a PSparseMatrix). This framework ships the loops natively, host and
compiled; seq-vs-TPU iteration parity is the determinism gate."""
import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu.models import (
    assemble_poisson,
    bicgstab,
    cg,
    gather_pvector,
    jacobi_preconditioner,
    pcg,
)


def _setup(parts, ns=(10, 10, 10)):
    # x0 imposes the Dirichlet rows exactly, so the Krylov iteration runs
    # on the interior (SPD) operator — same device as the fdm driver
    return assemble_poisson(parts, ns)


def _err(x, x_exact):
    return float(np.linalg.norm(gather_pvector(x) - gather_pvector(x_exact)))


def _stencil_1d(parts, N, diag, off_val=-1.0):
    """Shared 1-D 3-point stencil fixture: tridiag(off_val, diag, off_val)
    over a 1-D block partition — the known-spectrum operator
    (eigenvalues diag + 2*off_val*cos(k*pi/(N+1))) used across the
    spectrum/eigensolver tests."""
    rows = pa.prange(parts, N)

    def coo(i):
        g = np.asarray(i.oid_to_gid)
        I = [g]
        J = [g]
        V = [np.full(len(g), diag)]
        for off in (-1, 1):
            gj = g + off
            k = (gj >= 0) & (gj < N)
            I.append(g[k])
            J.append(gj[k])
            V.append(np.full(int(k.sum()), off_val))
        return np.concatenate(I), np.concatenate(J), np.concatenate(V)

    c = pa.map_parts(coo, rows.partition)
    cols = pa.add_gids(rows, pa.map_parts(lambda t: t[1], c))
    return pa.PSparseMatrix.from_coo(
        pa.map_parts(lambda t: t[0], c),
        pa.map_parts(lambda t: t[1], c),
        pa.map_parts(lambda t: t[2], c),
        rows, cols, ids="global",
    )



def test_pcg_converges_sequential():
    def driver(parts):
        A, b, x_exact, x0 = _setup(parts)
        x, info = pcg(A, b, x0=x0, tol=1e-9)
        assert info["converged"]
        assert _err(x, x_exact) < 1e-5
        # Jacobi-preconditioned CG must not be slower than plain CG here
        _, info_plain = cg(A, b, x0=x0, tol=1e-9)
        assert info["iterations"] <= info_plain["iterations"] + 2
        return True

    assert pa.prun(driver, pa.sequential, (2, 2, 2))


def test_pcg_seq_vs_tpu_parity():
    def run(backend):
        def driver(parts):
            A, b, x_exact, x0 = _setup(parts)
            x, info = pcg(A, b, x0=x0, tol=1e-9)
            return _err(x, x_exact), info["iterations"], info["residuals"]

        return pa.prun(driver, backend, (2, 2, 2))

    err_s, it_s, res_s = run(pa.sequential)
    err_t, it_t, res_t = run(pa.tpu)
    assert it_s == it_t
    np.testing.assert_allclose(err_t, err_s, rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(res_t[: len(res_s)], res_s, rtol=1e-10)


def test_bicgstab_spd_converges_both_backends():
    for backend in (pa.sequential, pa.tpu):
        def driver(parts):
            A, b, x_exact, x0 = _setup(parts)
            x, info = bicgstab(A, b, x0=x0, tol=1e-9)
            assert info["converged"], info
            return _err(x, x_exact)

        err = pa.prun(driver, backend, (2, 2, 2))
        assert err < 1e-5, err


def test_bicgstab_seq_vs_tpu_near_parity():
    """BiCGStab amplifies ulp-level SpMV differences (XLA emits FMAs the
    host kernel cannot) through its omega/alpha ratios, so — unlike CG,
    whose iteration counts match exactly — the gate here is near-parity:
    both backends converge to the same solution within a step or two."""

    def run(backend):
        def driver(parts):
            A, b, x_exact, x0 = _setup(parts, (12, 12))
            x, info = bicgstab(A, b, x0=x0, tol=1e-8)
            assert info["converged"]
            return info["iterations"], _err(x, x_exact)

        return pa.prun(driver, backend, (2, 2))

    it_s, err_s = run(pa.sequential)
    it_t, err_t = run(pa.tpu)
    assert abs(it_s - it_t) <= 2, (it_s, it_t)
    assert err_s < 1e-6 and err_t < 1e-6


def test_bicgstab_nonsymmetric():
    """A convection-perturbed operator (nonsymmetric): CG's theory breaks,
    BiCGStab must still converge."""

    def driver(parts):
        A, b, x_exact, x0 = _setup(parts, (8, 8, 8))

        # perturb off-diagonals asymmetrically: A[i, i+1] *= 1.5 on owned
        def perturb(M):
            data = M.data.copy()
            r = M.row_of_nz()
            data[M.indices == r + 1] *= 1.5
            return pa.CSRMatrix(M.indptr, M.indices, data, M.shape)

        A.values = pa.map_parts(perturb, A.values)
        A.invalidate_blocks()
        bn = A @ pa.PVector.full(1.0, A.cols)
        x, info = bicgstab(A, bn, tol=1e-10)
        assert info["converged"], info
        res = A @ x
        err = np.linalg.norm(gather_pvector(res) - gather_pvector(bn))
        assert err < 1e-6, err
        return True

    assert pa.prun(driver, pa.sequential, (2, 2, 2))
    assert pa.prun(driver, pa.tpu, (2, 2, 2))


def test_jacobi_preconditioner_values():
    def driver(parts):
        A, b, _, _x0 = _setup(parts, (6, 6, 6))
        minv = jacobi_preconditioner(A)

        # owned entries must equal 1/diag(A) exactly
        def check_part(iset, M, mv):
            r = M.row_of_nz()
            hits = np.nonzero(M.indices == r)[0]
            d = np.ones(iset.num_oids)
            d[r[hits]] = M.data[hits]
            got = np.asarray(mv)[: iset.num_oids]
            np.testing.assert_array_equal(got, 1.0 / d)
            return True

        pa.map_parts(check_part, A.cols.partition, A.owned_owned_values, minv.values)
        return True

    assert pa.prun(driver, pa.sequential, (2, 2, 2))


def test_plu_factor_reuse_and_refactorize():
    """PLU factors once on MAIN and solves many right-hand sides; a
    rescaled operator is handled by refactorize
    (reference PLU/lu/ldiv!: src/Interfaces.jl:2641-2662)."""
    def driver(parts):
        A, b, x_exact, x0 = pa.assemble_poisson(parts, (5, 5, 5))
        F = pa.lu(A)
        x1 = F.solve(b)
        assert (x1 - x_exact).norm() < 1e-9
        b2 = A @ (x_exact * 2.0)
        x2 = F.solve(b2)
        assert (x2 - x_exact * 2.0).norm() < 1e-9
        # rescaled operator: stale factors are wrong, refactorize fixes
        A2 = 2.0 * A
        F.refactorize(A2)
        x3 = F.solve(b2)
        assert (x3 - x_exact).norm() < 1e-9
        return True

    assert pa.prun(driver, pa.sequential, (2, 2, 2))


def test_chebyshev_solver_both_backends():
    """Chebyshev iteration (no inner products in the loop — the only
    per-iteration collective is the SpMV halo) with Gershgorin-estimated
    spectrum bounds, against the CG solution."""
    N = 40

    def driver(parts):
        A = _stencil_1d(parts, N, 2.0)
        lmin = 2 - 2 * np.cos(np.pi / (N + 1))
        lmax = 2 - 2 * np.cos(N * np.pi / (N + 1))
        glo, ghi = pa.gershgorin_bounds(A)
        assert glo <= lmin and ghi >= lmax
        b = pa.PVector.full(1.0, A.cols)
        x, info = pa.chebyshev_solve(A, b, lmin, lmax, tol=1e-10, maxiter=5000)
        assert info["converged"]
        xc, _ = pa.cg(A, b, tol=1e-12)
        err = np.abs(pa.gather_pvector(x) - pa.gather_pvector(xc)).max()
        assert err < 1e-7
        return True

    assert pa.prun(driver, pa.sequential, 4)
    assert pa.prun(driver, pa.tpu, 4)


def test_chebyshev_rejects_bad_bounds():
    def driver(parts):
        A, b, xe, x0 = pa.assemble_poisson(parts, (4, 4, 4))
        with pytest.raises(AssertionError):
            pa.chebyshev_solve(A, b, lmin=2.0, lmax=1.0)
        return True

    assert pa.prun(driver, pa.sequential, (2, 2, 2))


def test_gmres_spd_converges_both_backends():
    for backend in (pa.sequential, pa.tpu):
        def driver(parts):
            A, b, x_exact, x0 = _setup(parts)
            x, info = pa.gmres(A, b, x0=x0, restart=20, tol=1e-9)
            assert info["converged"], info
            return _err(x, x_exact)

        err = pa.prun(driver, backend, (2, 2, 2))
        assert err < 1e-5, err


def test_gmres_nonsymmetric_with_restarts():
    """Convection-perturbed operator (nonsymmetric) with a restart small
    enough to force several cycles; GMRES must still converge on both
    backends. BiCGStab-style near-parity gate: the host runs MGS, the
    device runs CGS2, so convergence agrees to rounding, not bitwise."""

    def run(backend):
        def driver(parts):
            A, b, x_exact, x0 = _setup(parts, (8, 8, 8))

            def perturb(M):
                data = M.data.copy()
                r = M.row_of_nz()
                data[M.indices == r + 1] *= 1.5
                return pa.CSRMatrix(M.indptr, M.indices, data, M.shape)

            A.values = pa.map_parts(perturb, A.values)
            A.invalidate_blocks()
            bn = A @ pa.PVector.full(1.0, A.cols)
            x, info = pa.gmres(A, bn, restart=8, tol=1e-10)
            assert info["converged"], info
            res = A @ x
            err = np.linalg.norm(gather_pvector(res) - gather_pvector(bn))
            return info["iterations"], err

        return pa.prun(driver, backend, (2, 2, 2))

    it_s, err_s = run(pa.sequential)
    it_t, err_t = run(pa.tpu)
    assert err_s < 1e-6 and err_t < 1e-6, (err_s, err_t)
    assert abs(it_s - it_t) <= max(4, it_s // 4), (it_s, it_t)


def test_gmres_jacobi_preconditioned():
    """Left Jacobi preconditioning must not hurt (and the preconditioned
    residual history must still drive convergence to the true solution)."""

    def driver(parts):
        A, b, x_exact, x0 = _setup(parts)
        minv = jacobi_preconditioner(A)
        x, info = pa.gmres(A, b, x0=x0, restart=20, tol=1e-9, minv=minv)
        assert info["converged"]
        _, info_plain = pa.gmres(A, b, x0=x0, restart=20, tol=1e-9)
        assert info["iterations"] <= info_plain["iterations"] + 2
        return _err(x, x_exact)

    err = pa.prun(driver, pa.sequential, (2, 2, 2))
    assert err < 1e-5, err


def test_gmres_residual_history_monotone_within_cycle():
    """|g[j+1]| is non-increasing inside an Arnoldi cycle by construction;
    spot-check the recorded history respects that (up to restart seams)."""

    def driver(parts):
        A, b, x_exact, x0 = _setup(parts, (8, 8, 8))
        x, info = pa.gmres(A, b, x0=x0, restart=50, tol=1e-9)
        res = info["residuals"]
        # single cycle (restart > iterations): strictly monotone decrease
        assert info["iterations"] < 50
        assert np.all(np.diff(res) <= 1e-12 * res[0])
        return True

    assert pa.prun(driver, pa.sequential, (2, 2, 2))


def test_minres_spd_both_backends():
    def driver(parts):
        A, b, x_exact, x0 = _setup(parts)
        x, info = pa.minres(A, b, x0=x0, tol=1e-9)
        assert info["converged"], info
        assert _err(x, x_exact) < 1e-5
        return info["iterations"]

    it_s = pa.prun(driver, pa.sequential, (2, 2, 2))
    it_t = pa.prun(driver, pa.tpu, (2, 2, 2))
    # same update sequence host/device: iteration counts agree like CG's
    assert abs(it_s - it_t) <= 2, (it_s, it_t)


def test_minres_symmetric_indefinite():
    """A truly symmetric indefinite operator (1-D Laplacian minus a shift
    inside the spectrum): CG's theory breaks, MINRES must converge. Note
    the Poisson FDM fixture is NOT eligible here — its Dirichlet
    conditions are imposed as identity rows, which leaves the full matrix
    nonsymmetric (the Lanczos recurrence only survives that when every
    Krylov vector is zero on the boundary rows, as in the SPD test
    above)."""
    N = 40
    sigma = 1.0  # spectrum of the stencil is (0, 4): strictly inside

    def driver(parts):
        A = _stencil_1d(parts, N, 2.0 - sigma)
        # indefiniteness: eigenvalues 2-σ-2cos(kπ/(N+1)) straddle zero
        lo, hi = pa.gershgorin_bounds(A)
        assert lo < 0 < hi
        xs = pa.PVector.full(1.0, A.cols)
        bs = A @ xs
        xm, info = pa.minres(A, bs, tol=1e-10)
        assert info["converged"], info
        r2 = A @ xm
        err = np.linalg.norm(gather_pvector(r2) - gather_pvector(bs))
        assert err < 1e-6, err
        return True

    assert pa.prun(driver, pa.sequential, 4)
    assert pa.prun(driver, pa.tpu, 4)


def test_gmres_matches_cg_solution_on_spd():
    """On an SPD system GMRES and CG must land on the same solution."""

    def driver(parts):
        A, b, x_exact, x0 = _setup(parts, (8, 8, 8))
        xg, ig = pa.gmres(A, b, x0=x0, restart=30, tol=1e-11)
        xc, ic = pa.cg(A, b, x0=x0, tol=1e-11)
        assert ig["converged"] and ic["converged"]
        d = np.abs(gather_pvector(xg) - gather_pvector(xc)).max()
        assert d < 1e-8, d
        return True

    assert pa.prun(driver, pa.sequential, (2, 2, 2))


def test_block_jacobi_ilu_preconditioner():
    """Additive-Schwarz ILUT blocks: the preconditioner for
    unstructured operators where no grid hierarchy exists. Must beat (or
    match) point-Jacobi on the tet-elasticity fixture and solve to the
    same solution."""

    def driver(parts):
        A, b, x_exact, x0 = pa.assemble_elasticity_tet(parts, (5, 5, 5))
        m = pa.block_jacobi_ilu(A)
        x, info = pa.pcg(A, b, x0=x0, minv=m, tol=1e-10)
        assert info["converged"], info
        _, ij = pa.pcg(A, b, x0=x0, tol=1e-10)
        assert info["iterations"] <= ij["iterations"], (
            info["iterations"], ij["iterations"],
        )
        err = np.abs(gather_pvector(x) - gather_pvector(x_exact)).max()
        assert err < 1e-6, err
        return True

    assert pa.prun(driver, pa.sequential, 4)


def test_lanczos_bounds_bracket_known_spectrum():
    """1-D Laplacian: eigenvalues are 2−2cos(kπ/(N+1)); the Lanczos
    estimates (with default safety) must bracket the true extremes, and
    drive chebyshev_solve without hand-supplied bounds."""
    N = 40

    def driver(parts):
        A = _stencil_1d(parts, N, 2.0)
        lmin_true = 2 - 2 * np.cos(np.pi / (N + 1))
        lmax_true = 2 - 2 * np.cos(N * np.pi / (N + 1))
        lo, hi = pa.lanczos_bounds(A, iters=30)
        assert lo <= lmin_true <= hi, (lo, lmin_true)
        assert lo <= lmax_true <= hi, (lmax_true, hi)
        assert hi <= 1.1 * lmax_true  # the estimate is tight, not Gershgorin-loose
        b = pa.PVector.full(1.0, A.cols)
        x, info = pa.chebyshev_solve(A, b, lo, hi, tol=1e-10, maxiter=5000)
        assert info["converged"]
        xc, _ = pa.cg(A, b, tol=1e-12)
        assert np.abs(pa.gather_pvector(x) - pa.gather_pvector(xc)).max() < 1e-7
        return True

    assert pa.prun(driver, pa.sequential, 4)


def test_gmres_with_callable_preconditioner():
    """GMRES accepts callable preconditioners (multigrid hierarchy here)
    on the host path — left-preconditioned with a fixed linear operator."""

    def driver(parts):
        ns = (10, 10, 10)
        A, b, x_exact, _ = pa.assemble_poisson(parts, ns)
        Ah, bh = pa.decouple_dirichlet(A, b)
        h = pa.gmg_hierarchy(parts, Ah, ns, coarse_threshold=100)
        x, info = pa.gmres(Ah, bh, restart=20, tol=1e-10, minv=h)
        assert info["converged"], info
        _, iplain = pa.gmres(Ah, bh, restart=20, tol=1e-10)
        assert info["iterations"] < iplain["iterations"]
        err = np.abs(gather_pvector(x) - gather_pvector(x_exact)).max()
        assert err < 1e-6, err
        return True

    assert pa.prun(driver, pa.sequential, (2, 2, 2))


def test_additive_schwarz_modes():
    """Overlapping Schwarz via ghost-row replication (exchange_coo):
    'asm' (symmetric combine) is CG-safe; 'ras' (restricted) is the
    stronger variant for GMRES — and must clearly beat the
    non-overlapping block-Jacobi there. Textbook behavior to respect:
    plain ASM double-counts overlap corrections, so it is NOT asserted
    to beat block-Jacobi, only to stay in its neighborhood."""

    def driver(parts):
        A, b, x_exact, x0 = pa.assemble_elasticity_tet(parts, (6, 6, 6))
        asm = pa.additive_schwarz(A)
        ras = pa.additive_schwarz(A, mode="ras")
        bj = pa.block_jacobi_ilu(A)

        xa, ia = pa.pcg(A, b, x0=x0, minv=asm, tol=1e-10)
        _, ib = pa.pcg(A, b, x0=x0, minv=bj, tol=1e-10)
        assert ia["converged"]
        assert ia["iterations"] <= ib["iterations"] + 5, (
            ia["iterations"], ib["iterations"],
        )
        ea = np.abs(gather_pvector(xa) - gather_pvector(x_exact)).max()
        assert ea < 1e-7, ea

        xr, ir = pa.gmres(A, b, x0=x0, restart=30, tol=1e-10, minv=ras)
        _, ig = pa.gmres(A, b, x0=x0, restart=30, tol=1e-10, minv=bj)
        assert ir["converged"]
        assert ir["iterations"] < ig["iterations"], (
            ir["iterations"], ig["iterations"],
        )
        er = np.abs(gather_pvector(xr) - gather_pvector(x_exact)).max()
        assert er < 1e-6, er
        return True

    assert pa.prun(driver, pa.sequential, 8)


def test_additive_schwarz_single_part_degenerates_to_exact():
    """With one part there is no overlap and the 'block' is the whole
    operator: one application solves the system (up to ILU fill drop)."""

    def driver(parts):
        A, b, x_exact, x0 = pa.assemble_poisson(parts, (6, 6, 6))
        m = pa.additive_schwarz(A, fill_factor=50)
        x, info = pa.pcg(A, b, x0=x0, minv=m, tol=1e-10)
        assert info["converged"] and info["iterations"] <= 3, info["iterations"]
        return True

    assert pa.prun(driver, pa.sequential, (1, 1, 1))


def test_lanczos_bounds_indefinite_and_negative_spectra():
    """The margins must widen the interval OUTWARD regardless of sign:
    for −Laplacian (negative spectrum) and the shifted indefinite
    operator, the returned interval still brackets the true extremes
    (a naive multiplicative safety factor inverts direction on negative
    Ritz values)."""
    N = 40


    def driver(parts):
        th = np.pi / (N + 1)
        # negative-definite: spectrum of -(2,-1 stencil) = (-4, 0)
        An = _stencil_1d(parts, N, -2.0, off_val=1.0)
        lmin = -(2 - 2 * np.cos(N * th))
        lmax = -(2 - 2 * np.cos(th))
        lo, hi = pa.lanczos_bounds(An, iters=30)
        assert lo <= lmin and hi >= lmax, (lo, lmin, lmax, hi)
        # indefinite: spectrum of (1,-1 stencil) straddles zero
        Ai = _stencil_1d(parts, N, 1.0)
        lo2, hi2 = pa.lanczos_bounds(Ai, iters=30)
        assert lo2 < 0 < hi2
        assert lo2 <= 1 - 2 * np.cos(N * th) and hi2 >= 1 - 2 * np.cos(th)
        return True

    assert pa.prun(driver, pa.sequential, 4)


def test_lobpcg_eigenpairs():
    """Distributed LOBPCG vs the 1-D Laplacian's known spectrum: smallest
    and largest blocks, plus preconditioned acceleration (the
    IterativeSolvers.jl `lobpcg` parity item,
    reference src/Interfaces.jl:2752-2757)."""
    N = 40


    def driver(parts):
        A = _stencil_1d(parts, N, 2.0)
        th = np.pi / (N + 1)
        true_small = np.array([2 - 2 * np.cos(k * th) for k in (1, 2, 3)])
        lam, X, info = pa.lobpcg(A, nev=3, tol=1e-6, maxiter=300)
        assert info["converged"], info["iterations"]
        np.testing.assert_allclose(lam, true_small, rtol=1e-7)
        # the pairs satisfy A x = λ x to the requested tolerance
        r0 = np.linalg.norm(
            pa.gather_pvector(A @ X[0]) - lam[0] * pa.gather_pvector(X[0])
        )
        assert r0 < 1e-5, r0

        true_large = np.array([2 - 2 * np.cos(k * th) for k in (N, N - 1)])
        lamL, _, infoL = pa.lobpcg(A, nev=2, largest=True, tol=1e-6, maxiter=300)
        assert infoL["converged"]
        np.testing.assert_allclose(lamL, true_large, rtol=1e-7)

        # a preconditioner accelerates markedly
        m = pa.block_jacobi_ilu(A, fill_factor=20)
        lam2, _, info2 = pa.lobpcg(A, nev=3, minv=m, tol=1e-6, maxiter=300)
        assert info2["converged"]
        assert info2["iterations"] < info["iterations"] // 2, (
            info2["iterations"], info["iterations"],
        )
        np.testing.assert_allclose(lam2, true_small, rtol=1e-7)
        return True

    assert pa.prun(driver, pa.sequential, 4)


def test_lobpcg_matches_lanczos_extremes():
    """Consistency between the two spectrum tools on the Poisson
    operator: LOBPCG's converged extremes must lie inside the
    lanczos_bounds interval."""

    def driver(parts):
        A, b, _, _ = pa.assemble_poisson(parts, (8, 8))
        Ah = pa.decouple_dirichlet(A)
        lo, hi = pa.lanczos_bounds(Ah, iters=40)
        lam_s, _, i1 = pa.lobpcg(Ah, nev=1, tol=1e-6, maxiter=400)
        lam_l, _, i2 = pa.lobpcg(Ah, nev=1, largest=True, tol=1e-6, maxiter=400)
        assert i1["converged"] and i2["converged"]
        assert lo <= lam_s[0] <= lam_l[0] <= hi
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_lobpcg_compiled_matches_host_eigenpairs():
    """On the TPU backend the whole eigensolve is ONE compiled program
    (parallel/tpu_lobpcg.py). Host and device stabilize the basis
    differently (rank dropping vs masked diagonal penalty), so the gate
    is eigenpair agreement, not iteration parity."""
    N = 40

    def driver(parts):
        A = _stencil_1d(parts, N, 2.0)
        lam, X, info = pa.lobpcg(A, nev=3, tol=1e-6, maxiter=300)
        assert info["converged"], info["iterations"]
        r0 = np.linalg.norm(
            pa.gather_pvector(A @ X[0]) - lam[0] * pa.gather_pvector(X[0])
        )
        return lam, r0

    lam_s, r_s = pa.prun(driver, pa.sequential, 4)
    lam_t, r_t = pa.prun(driver, pa.tpu, 4)
    np.testing.assert_allclose(lam_t, lam_s, rtol=1e-8)
    assert r_s < 1e-5 and r_t < 1e-5

    # preconditioned largest-mode on the device path
    def driver2(parts):
        A = _stencil_1d(parts, N, 2.0)
        lam, _, info = pa.lobpcg(
            A, nev=2, minv=pa.jacobi_preconditioner(A), largest=True,
            tol=1e-6, maxiter=300,
        )
        assert info["converged"]
        return lam

    th = np.pi / (N + 1)
    lam_l = pa.prun(driver2, pa.tpu, 4)
    np.testing.assert_allclose(
        lam_l, [2 - 2 * np.cos(N * th), 2 - 2 * np.cos((N - 1) * th)],
        rtol=1e-7,
    )


def test_bicgstab_right_preconditioned():
    """Right-preconditioned BiCGStab: Jacobi-diagonal form runs compiled
    on the device with host iteration near-parity; the RAS callable cuts
    iterations on the nonsymmetric advection operator. Right
    preconditioning keeps TRUE residuals, so the convergence test means
    the same thing as the unpreconditioned loop's."""

    def driver(parts):
        A, b, x_exact, x0 = pa.assemble_advection_fv(parts, (14, 14))
        minv = jacobi_preconditioner(A)
        x, info = pa.bicgstab(A, b, x0=x0, minv=minv, tol=1e-10)
        assert info["converged"], info
        err = np.abs(gather_pvector(x) - gather_pvector(x_exact)).max()
        assert err < 1e-7, err
        # the RAS callable (host path) must beat plain bicgstab
        ras = pa.additive_schwarz(A, mode="ras")
        xr, ir = pa.bicgstab(A, b, x0=x0, minv=ras, tol=1e-10)
        _, ip = pa.bicgstab(A, b, x0=x0, tol=1e-10)
        assert ir["converged"] and ir["iterations"] < ip["iterations"], (
            ir["iterations"], ip["iterations"],
        )
        errr = np.abs(gather_pvector(xr) - gather_pvector(x_exact)).max()
        assert errr < 1e-7, errr
        return info["iterations"]

    it_s = pa.prun(driver, pa.sequential, (2, 2))
    it_t = pa.prun(driver, pa.tpu, (2, 2))
    # BiCGStab amplifies ulp differences; near-parity like the plain test
    assert abs(it_s - it_t) <= 2, (it_s, it_t)


def test_block_jacobi_ic0_preconditioner():
    """IC(0) blocks: exactly symmetric (L Lᵀ) — PCG's conjugacy holds
    exactly, unlike the ILU blocks. On the SPD Poisson operator (an
    M-matrix: IC(0) is breakdown-free, no shift) it must beat
    point-Jacobi PCG and match the exact solution."""

    def driver(parts):
        A, b, x_exact, x0 = pa.assemble_poisson(parts, (8, 8, 8))
        Ah, bh = pa.decouple_dirichlet(A, b)
        m = pa.block_jacobi_ic0(Ah)
        x, info = pa.pcg(Ah, bh, minv=m, tol=1e-10)
        assert info["converged"], info
        mj = pa.jacobi_preconditioner(Ah)
        _, ij = pa.pcg(Ah, bh, minv=mj, tol=1e-10)
        assert info["iterations"] <= ij["iterations"], (
            info["iterations"], ij["iterations"],
        )
        err = np.abs(gather_pvector(x) - gather_pvector(x_exact)).max()
        assert err < 1e-6, err
        return True

    assert pa.prun(driver, pa.sequential, (2, 2, 1))


def test_ic0_rejects_nonsymmetric_block():
    """The tet-elasticity fixture uses row-replacement Dirichlet BCs, so
    its blocks are NONsymmetric — IC(0) must refuse loudly (a silently
    symmetrized factor made PCG diverge when this was first wired)."""

    def driver(parts):
        A, b, x_exact, x0 = pa.assemble_elasticity_tet(parts, (4, 4, 4))
        with pytest.raises(ValueError, match="not symmetric"):
            pa.block_jacobi_ic0(A)
        return True

    assert pa.prun(driver, pa.sequential, 2)


def test_ic0_exact_on_full_pattern():
    """On a dense-pattern SPD matrix IC(0) IS the Cholesky factor: the
    preconditioned solve must converge in one iteration."""

    def driver(parts):
        n = 12
        rows = pa.uniform_partition(parts, n)
        rng = np.random.default_rng(3)
        C = rng.standard_normal((n, n))
        S = C @ C.T + n * np.eye(n)

        def local(iset):
            g = np.asarray(iset.oid_to_gid)
            I = np.repeat(g, n)
            J = np.tile(np.arange(n, dtype=np.int64), len(g))
            return I, J, S[g].ravel()

        coo = pa.map_parts(local, rows.partition)
        I = pa.map_parts(lambda c: c[0], coo)
        J = pa.map_parts(lambda c: c[1], coo)
        V = pa.map_parts(lambda c: c[2], coo)
        cols = pa.add_gids(rows, J)
        A = pa.PSparseMatrix.from_coo(I, J, V, rows, cols, ids="global")
        b = pa.PVector.full(1.0, rows)
        # single part: the owned-owned block is the whole matrix
        m = pa.block_jacobi_ic0(A)
        x, info = pa.pcg(A, b, minv=m, tol=1e-10)
        assert info["converged"] and info["iterations"] <= 2, info
        return True

    assert pa.prun(driver, pa.sequential, 1)


def test_ic0_rejects_indefinite():
    def driver(parts):
        n = 8
        rows = pa.uniform_partition(parts, n)

        def local(iset):
            g = np.asarray(iset.oid_to_gid)
            return g.copy(), g.copy(), np.where(g == n - 1, -1.0, 1.0)

        coo = pa.map_parts(local, rows.partition)
        I = pa.map_parts(lambda c: c[0], coo)
        J = pa.map_parts(lambda c: c[1], coo)
        V = pa.map_parts(lambda c: c[2], coo)
        cols = pa.add_gids(rows, J)
        A = pa.PSparseMatrix.from_coo(I, J, V, rows, cols, ids="global")
        with pytest.raises(np.linalg.LinAlgError):
            pa.block_jacobi_ic0(A)
        return True

    assert pa.prun(driver, pa.sequential, 2)


def test_additive_schwarz_ic0_symmetric_for_pcg():
    """ASM with IC(0) blocks is exactly symmetric: PCG must converge and
    beat plain CG in iterations on the FDM operator."""

    def driver(parts):
        # large enough that the overlap pays for ASM's double counting
        # (at 8x8 point-Jacobi still wins on iterations)
        A, b, x_exact, x0 = pa.assemble_poisson(parts, (16, 16))
        Ah, bh = pa.decouple_dirichlet(A, b)
        m = pa.additive_schwarz(Ah, mode="asm", factor="ic0")
        x, info = pa.pcg(Ah, bh, minv=m, tol=1e-10)
        assert info["converged"], info
        # beats point-Jacobi (the cheap symmetric baseline); zero-fill
        # blocks are weaker than the ILUT variant, so plain-CG parity is
        # not claimed at this size
        mj = pa.jacobi_preconditioner(Ah)
        _, ic = pa.pcg(Ah, bh, minv=mj, tol=1e-10)
        assert info["iterations"] <= ic["iterations"]
        err = np.abs(gather_pvector(x) - gather_pvector(x_exact)).max()
        assert err < 1e-6, err
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_fgmres_matches_gmres_with_constant_preconditioner():
    """With a CONSTANT diagonal preconditioner FGMRES and GMRES solve the
    same system to the same answer (histories differ by norm convention:
    fgmres reports true residuals)."""

    def driver(parts):
        A, b, x_exact, x0 = pa.assemble_advection_fv(
            parts, (10, 10), velocity=(8.0, 3.0)
        )
        mv = pa.jacobi_preconditioner(A)
        xf, inf_f = pa.fgmres(A, b, minv=mv, tol=1e-10, restart=20)
        xg, inf_g = pa.gmres(A, b, minv=mv, tol=1e-10, restart=20)
        assert inf_f["converged"] and inf_g["converged"]
        d = np.abs(gather_pvector(xf) - gather_pvector(xg)).max()
        assert d < 1e-7, d
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_fgmres_with_inner_iterative_preconditioner():
    """The flexible property: the preconditioner is itself an ITERATIVE
    solve (inner CG with a loose tolerance), different from one
    application to the next — plain GMRES's theory breaks here, FGMRES
    is built for it. Converges in (far) fewer outer iterations than
    unpreconditioned, and to the right answer."""

    def driver(parts):
        A, b, x_exact, x0 = pa.assemble_poisson(parts, (10, 10))
        Ah, bh = pa.decouple_dirichlet(A, b)
        calls = {"n": 0}

        def inner(r):
            # iteration-varying: the inner tolerance loosens as calls
            # accumulate — a deliberately NON-constant operator
            calls["n"] += 1
            z, _ = pa.cg(Ah, r, tol=1e-2 if calls["n"] % 2 else 1e-1, maxiter=50)
            return z

        x, info = pa.fgmres(Ah, bh, minv=inner, tol=1e-8, restart=20)
        assert info["converged"], info
        assert calls["n"] >= 2
        _, i0 = pa.fgmres(Ah, bh, tol=1e-8, restart=20)
        assert info["iterations"] < i0["iterations"]
        err = np.abs(gather_pvector(x) - gather_pvector(x_exact)).max()
        assert err < 1e-5, err
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_fgmres_with_gmg_preconditioner():
    """FGMRES wrapping the multigrid V-cycle — the flagship pairing for
    nonsymmetric problems with an elliptic core."""

    def driver(parts):
        n = 16
        A, b, x_exact, x0 = pa.assemble_poisson(parts, (n, n))
        Ah, bh = pa.decouple_dirichlet(A, b)
        h = pa.gmg_hierarchy(parts, Ah, (n, n), coarse_threshold=20)
        x, info = pa.fgmres(Ah, bh, minv=h, tol=1e-9, restart=10)
        assert info["converged"], info
        assert info["iterations"] <= 12, info["iterations"]
        err = np.abs(gather_pvector(x) - gather_pvector(x_exact)).max()
        assert err < 1e-6, err
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_lobpcg_gmg_preconditioned_compiled():
    """Multigrid-preconditioned modal analysis as ONE compiled program:
    lobpcg(A, minv=hierarchy) on the TPU backend inlines the V-cycle per
    residual block row. Must find the known smallest Laplacian
    eigenvalues and converge in (far) fewer iterations than the
    unpreconditioned compiled solve."""

    def driver(parts):
        n = 16
        A, b, x_exact, x0 = pa.assemble_poisson(parts, (n, n))
        Ah, _ = pa.decouple_dirichlet(A, b)
        h = pa.gmg_hierarchy(parts, Ah, (n, n), coarse_threshold=20)
        lam, X, info = pa.lobpcg(Ah, nev=2, minv=h, tol=1e-7, maxiter=200)
        lam0, _, info0 = pa.lobpcg(Ah, nev=2, tol=1e-7, maxiter=200)
        # reference: the independently-validated host eigensolver
        lam_h, _, info_h = pa.lobpcg(
            Ah, nev=2, minv=pa.jacobi_preconditioner(Ah), tol=1e-9,
            maxiter=500,
        )
        assert info["converged"], info
        assert info_h["converged"], info_h
        np.testing.assert_allclose(lam, lam_h, rtol=1e-5)
        if info0["converged"]:
            assert info["iterations"] <= info0["iterations"], (
                info["iterations"], info0["iterations"],
            )
        return True

    assert pa.prun(driver, pa.tpu, (2, 2))


def test_tolerance_floor_warning_and_stall_status():
    """VERDICT r3 directive 4: a float32 run with a tolerance below the
    dtype resolution floor (~50x eps) must surface a RuntimeWarning at
    solver entry, and the info dict must say "stalled" — the honest name
    for restart cycles oscillating at the f32 floor with an accurate
    solution — instead of a silent converged=False. A reachable
    tolerance on the same operator reports "converged"."""

    def _f32(A, b):
        A.values = pa.map_parts(
            lambda M: pa.CSRMatrix(
                M.indptr, M.indices, M.data.astype(np.float32), M.shape
            ),
            A.values,
        )
        A.invalidate_blocks()
        b.values = pa.map_parts(lambda v: np.asarray(v, np.float32), b.values)
        return A, b

    def driver(parts):
        A, b, x_exact, _ = pa.assemble_poisson(parts, (12, 12, 12))
        Ah, bh = pa.decouple_dirichlet(A, b)
        Ah, bh = _f32(Ah, bh)
        with pytest.warns(RuntimeWarning, match="resolution floor"):
            x, info = pa.fgmres(Ah, bh, tol=1e-12, restart=10, maxiter=100)
        assert not info["converged"]
        assert info["status"] == "stalled", info
        assert info.get("tol_below_dtype_floor") is True
        # ... while the SOLUTION is accurate — the classic footgun shape
        err = np.abs(gather_pvector(x) - gather_pvector(x_exact)).max()
        assert err < 1e-4, err
        x2, info2 = pa.fgmres(Ah, bh, tol=1e-4, restart=10, maxiter=200)
        assert info2["converged"] and info2["status"] == "converged"
        assert "tol_below_dtype_floor" not in info2
        return True

    assert pa.prun(driver, pa.sequential, (2, 2, 2))


def test_tolerance_floor_compiled_fgmres_gmg():
    """The compiled FGMRES+GMG path (the r3 probe's config, f32 this
    time ON PURPOSE): entry warning fires and status distinguishes the
    stall from a genuine non-convergence."""

    def driver(parts):
        ns = (16, 16, 16)
        A, b, x_exact, _ = pa.assemble_poisson(parts, ns)
        Ah, bh = pa.decouple_dirichlet(A, b)
        Ah.values = pa.map_parts(
            lambda M: pa.CSRMatrix(
                M.indptr, M.indices, M.data.astype(np.float32), M.shape
            ),
            Ah.values,
        )
        Ah.invalidate_blocks()
        bh.values = pa.map_parts(
            lambda v: np.asarray(v, np.float32), bh.values
        )
        h = pa.gmg_hierarchy(parts, Ah, ns, coarse_threshold=100)
        with pytest.warns(RuntimeWarning, match="resolution floor"):
            xt, info = pa.tpu_fgmres_gmg(
                h, bh, tol=1e-12, restart=12, maxiter=60
            )
        assert not info["converged"]
        assert info["status"] == "stalled", info
        err = np.abs(
            pa.gather_pvector(xt) - pa.gather_pvector(x_exact)
        ).max()
        assert err < 1e-3, err
        return True

    assert pa.prun(driver, pa.tpu, (2, 2, 2))


def test_recurrence_underflow_below_floor_reports_stalled():
    """The CG-family version of the floor footgun: with tol below the
    f32 floor the RECURRENCE residual can underflow past the test while
    the true b - Ax residual floors above it. The info contract must
    recompute the true residual in exactly this regime and report
    stalled, not a converged=True lie."""

    def driver(parts):
        A, b, x_exact, _ = pa.assemble_poisson(parts, (10, 10, 10))
        Ah, bh = pa.decouple_dirichlet(A, b)
        Ah.values = pa.map_parts(
            lambda M: pa.CSRMatrix(
                M.indptr, M.indices, M.data.astype(np.float32), M.shape
            ),
            Ah.values,
        )
        Ah.invalidate_blocks()
        bh.values = pa.map_parts(lambda v: np.asarray(v, np.float32), bh.values)
        mv = pa.jacobi_preconditioner(Ah)
        with pytest.warns(RuntimeWarning, match="resolution floor"):
            x, info = pa.pcg(Ah, bh, minv=mv, tol=1e-12, maxiter=300)
        assert not info["converged"]
        assert info["status"] == "stalled", info
        err = np.abs(gather_pvector(x) - gather_pvector(x_exact)).max()
        assert err < 1e-3, err  # the solution itself is fine
        x2, info2 = pa.pcg(Ah, bh, minv=mv, tol=1e-4, maxiter=300)
        assert info2["converged"] and info2["status"] == "converged"
        return True

    assert pa.prun(driver, pa.sequential, (2, 2, 2))
