"""paplan — the static exchange-plan soundness verifier.

Four layers, each pinned here:

* **Negative corpus** (tests/fixtures/paplan/): one COMMITTED mutated-
  plan fixture per defect class — overlapping ghost slot, dropped
  slot, asymmetric counts, self-send round, dead slot — each caught by
  exactly its check; the unmutated base plan verifies clean. A
  verifier without negative tests is a verifier that may be checking
  nothing (the same discipline docs/static_analysis.md demands of
  contracts).
* **Device plans**: the generic index plan and the box slice plan
  verify sound as built (pure-numpy construction — no compile), and
  seeded slot/round mutations on each are caught.
* **Construction-time gate**: ``PA_PLAN_VERIFY=1`` verifies at the
  plan build sites and raises the typed `PlanSoundnessError`; clean
  builds pass through untouched.
* **Rebuild/restore equality** (the ROADMAP item 4 invariant): a plan
  rebuilt from the same partition is fingerprint-IDENTICAL; a plan
  rebuilt from a checkpoint-restored partition (the PR 1 repartition
  smoke's path, which renumbers ghost lids) verifies sound and
  exchanges the identical global columns over the identical edges
  (`canonical_exchange_fingerprint`).

Plus the tier-1 CLI gate: ``tools/palint.py --check --fast`` exit
status asserted in-process, so a contract-registry or verifier
regression fails the suite, not just the CLI.
"""
import copy
import glob
import os

import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu.analysis import plan_verifier as pv
from partitionedarrays_jl_tpu.parallel.health import PlanSoundnessError
from partitionedarrays_jl_tpu.parallel.tpu import (
    DeviceExchangePlan,
    DeviceLayout,
)
from partitionedarrays_jl_tpu.parallel.tpu_box import (
    BoxExchangePlan,
    analyze_box_structure,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXDIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "fixtures", "paplan")

DEFECT_FIXTURES = [
    ("overlapping_ghost_slot.json", "ghost-race"),
    ("dropped_slot.json", "coverage"),
    ("asymmetric_counts.json", "symmetry"),
    ("self_send_round.json", "rounds"),
    ("dead_slot.json", "dead-slot"),
]

#: The ISSUE-18 staged-schedule corpus ("paplan-twolevel-fixture"
#: format): mutated TWO-LEVEL plans whose flat logical-delivery view is
#: sound — only the staged schedule is corrupted, so nothing but the
#: schedule simulation can catch them.
TWOLEVEL_FIXTURES = [
    ("twolevel_rep_slot.json", "coverage"),
]


# ---------------------------------------------------------------------------
# the committed negative corpus
# ---------------------------------------------------------------------------


def test_corpus_is_complete():
    """One committed fixture per defect class, plus the clean base —
    and no fixture class is missing from PLAN_CHECKS."""
    names = {os.path.basename(p) for p in glob.glob(
        os.path.join(FIXDIR, "*.json")
    )}
    assert names == (
        {n for n, _ in DEFECT_FIXTURES}
        | {n for n, _ in TWOLEVEL_FIXTURES}
        | {"clean.json"}
    )
    assert {c for _, c in DEFECT_FIXTURES} == set(pv.PLAN_CHECKS)


@pytest.mark.parametrize("name,check", TWOLEVEL_FIXTURES)
def test_twolevel_defect_fixture_caught(name, check):
    plan, ref, defect = pv.load_twolevel_fixture(
        os.path.join(FIXDIR, name)
    )
    assert defect == check, "fixture self-description drifted"
    defects = pv.verify_twolevel_plan(plan, referenced=ref)
    assert defects, f"{name}: verifier saw nothing"
    checks = {d.check for d in defects}
    assert check in checks, (name, checks)
    hit = next(d for d in defects if d.check == check)
    assert hit.part is not None and hit.message


def test_clean_fixture_verifies_sound():
    ex, parts, ref, defect = pv.load_exchanger_fixture(
        os.path.join(FIXDIR, "clean.json")
    )
    assert defect is None
    assert pv.verify_exchanger(ex, parts, referenced=ref) == []


@pytest.mark.parametrize("name,check", DEFECT_FIXTURES)
def test_defect_fixture_caught_by_its_check(name, check):
    ex, parts, ref, defect = pv.load_exchanger_fixture(
        os.path.join(FIXDIR, name)
    )
    assert defect == check, "fixture self-description drifted"
    defects = pv.verify_exchanger(ex, parts, referenced=ref)
    assert defects, f"{name}: verifier saw nothing"
    checks = {d.check for d in defects}
    assert check in checks, (name, checks)
    # the defect report carries actionable part/slot diagnostics
    hit = next(d for d in defects if d.check == check)
    assert hit.part is not None and hit.message


def test_check_plan_raises_typed_with_diagnostics():
    ex, parts, ref, _ = pv.load_exchanger_fixture(
        os.path.join(FIXDIR, "overlapping_ghost_slot.json")
    )
    with pytest.raises(PlanSoundnessError) as ei:
        pv.check_plan(ex, parts=parts, referenced=ref, context="corpus")
    diag = ei.value.diagnostics
    assert "ghost-race" in diag["checks"]
    assert diag["defects"] and diag["defects"][0]["check"]
    assert diag["context"] == "corpus"


# ---------------------------------------------------------------------------
# device plans (pure-numpy construction — no compile, host backend)
# ---------------------------------------------------------------------------


def _probe_system(parts):
    A, b, xe, x0 = pa.assemble_poisson(parts, (6, 6))
    return A


def test_device_plans_verify_sound_and_mutations_caught():
    def driver(parts):
        A = _probe_system(parts)
        rows = A.cols
        ref = pv.referenced_ghosts(A)
        # every ghost of the assembled operator is genuinely referenced
        assert all(m.all() for m in ref)

        layout = DeviceLayout(rows, padded=False)
        plan = DeviceExchangePlan(rows.exchanger, layout)
        assert pv.verify_device_plan(plan, referenced=ref) == []

        # seeded: redirect one receive slot onto another -> ghost-race
        # (and the orphaned slot becomes a coverage hole)
        bad = DeviceExchangePlan(rows.exchanger, layout)
        q, r = next(
            (q, r)
            for q in range(layout.P) for r in range(bad.R)
            if (bad.rcv_idx[q, r] != layout.trash).sum() >= 2
        )
        slots = np.nonzero(bad.rcv_idx[q, r] != layout.trash)[0]
        bad.rcv_idx = bad.rcv_idx.copy()
        bad.rcv_idx[q, r, slots[1]] = bad.rcv_idx[q, r, slots[0]]
        checks = {d.check for d in pv.verify_device_plan(bad, referenced=ref)}
        assert "ghost-race" in checks

        # seeded: a self-send edge smuggled into a round -> rounds
        bad2 = DeviceExchangePlan(rows.exchanger, layout)
        perms = [list(p) for p in bad2.perms]
        perms[0] = list(perms[0]) + [(0, 0)]
        bad2.perms = tuple(tuple(p) for p in perms)
        checks = {d.check for d in pv.verify_device_plan(bad2, referenced=ref)}
        assert "rounds" in checks

        # the box slice plan of the same partition
        info = analyze_box_structure(rows)
        assert info is not None, "probe partition lost its box structure"
        blayout = DeviceLayout(rows, padded=False, box_info=info)
        bplan = BoxExchangePlan(blayout, info)
        assert pv.verify_box_plan(bplan, referenced=ref) == []

        # seeded: collide two segment slots on one part -> ghost-race
        info2 = analyze_box_structure(rows)
        p = next(
            p for p in range(info2.P)
            if len(np.asarray(info2.ghost_rel_slots[p])) >= 2
        )
        rel = np.asarray(info2.ghost_rel_slots[p]).copy()
        rel[1] = rel[0]
        info2.ghost_rel_slots = (
            list(info2.ghost_rel_slots[:p]) + [rel]
            + list(info2.ghost_rel_slots[p + 1:])
        )
        bad3 = BoxExchangePlan(blayout, info2)
        checks = {d.check for d in pv.verify_box_plan(bad3, referenced=ref)}
        assert "ghost-race" in checks
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_construction_time_gate_catches_corrupted_plan(monkeypatch):
    """PA_PLAN_VERIFY=1: a clean build passes through; a corrupted
    host plan is refused at the DEVICE-PLAN build site with the typed
    error, before any program could lower from it."""
    monkeypatch.setenv("PA_PLAN_VERIFY", "1")

    def driver(parts):
        A = _probe_system(parts)
        rows = A.cols
        from partitionedarrays_jl_tpu.parallel.tpu import (
            device_exchange_plan,
        )

        # clean: the gate verifies and passes (both plan flavors)
        plan = device_exchange_plan(rows)
        assert plan is device_exchange_plan(rows)  # cached, not re-run

        # corrupt the HOST plan in place (an overlapping ghost slot),
        # then force the device plan to rebuild from it
        ex = rows.exchanger
        t = next(
            t for t in ex.lids_rcv.part_values() if len(t.data) >= 2
        )
        t.data[1] = t.data[0]
        monkeypatch.setenv("PA_TPU_BOX", "0")  # generic plan reads lids
        rows._device_plan = {}
        for attr in ("_device_layout", "_box_info"):
            if hasattr(rows, attr):
                delattr(rows, attr)
        with pytest.raises(PlanSoundnessError) as ei:
            device_exchange_plan(rows)
        assert "ghost-race" in ei.value.diagnostics["checks"]
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_exchanger_construction_gate_passes_clean(monkeypatch):
    monkeypatch.setenv("PA_PLAN_VERIFY", "1")

    def driver(parts):
        rows = pa.cartesian_partition(parts, (6, 6), pa.with_ghost)
        ex = rows.exchanger  # from_partition runs the gate
        assert ex is not None
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


# ---------------------------------------------------------------------------
# rebuild / checkpoint-restore equality (the ROADMAP item 4 invariant)
# ---------------------------------------------------------------------------


def test_rebuilt_plan_fingerprint_identical_and_sound():
    def driver(parts):
        A = _probe_system(parts)
        rows = A.cols
        fp0 = pv.plan_fingerprint(rows.exchanger)
        dev0 = pv.plan_fingerprint(
            DeviceExchangePlan(rows.exchanger, DeviceLayout(rows))
        )
        rows.invalidate_exchanger()
        ex1 = rows.exchanger  # rebuilt from the same partition
        assert pv.plans_equal(ex1, ex1)
        assert pv.plan_fingerprint(ex1) == fp0
        assert pv.plan_fingerprint(
            DeviceExchangePlan(ex1, DeviceLayout(rows))
        ) == dev0
        ref = pv.referenced_ghosts(A)
        assert pv.verify_exchanger(ex1, rows.partition, referenced=ref) == []
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_checkpoint_restored_partition_plans_sound_and_canonical_equal(
    tmp_path,
):
    """The PR 1 repartition-smoke path: save the operator, restore it
    into a FRESH partition (which renumbers ghost lids in column-sorted
    order). The rebuilt plans must verify sound against the restored
    operator's sparsity and exchange the IDENTICAL global columns over
    the identical edges — the invariant ROADMAP item 4's incremental
    re-plan will rely on. (Exact slot-level fingerprints legitimately
    differ across the two lid orders; `plan_fingerprint` equality is
    pinned for the same-partition rebuild above.)"""
    p = str(tmp_path / "A.npz")
    state = {}

    def save(parts):
        A = _probe_system(parts)
        state["canonical"] = pv.canonical_exchange_fingerprint(
            A.cols.exchanger, A.cols.partition
        )
        pa.save_psparse(p, A)
        return True

    def load(parts):
        rows = pa.cartesian_partition(parts, (6, 6), pa.no_ghost)
        A2 = pa.load_psparse(p, rows)
        ref = pv.referenced_ghosts(A2)
        defects = pv.verify_exchanger(
            A2.cols.exchanger, A2.cols.partition, referenced=ref
        )
        assert defects == [], [str(d) for d in defects]
        plan = DeviceExchangePlan(A2.cols.exchanger, DeviceLayout(A2.cols))
        assert pv.verify_device_plan(plan, referenced=ref) == []
        assert pv.canonical_exchange_fingerprint(
            A2.cols.exchanger, A2.cols.partition
        ) == state["canonical"]
        return True

    assert pa.prun(save, pa.sequential, (2, 2))
    assert pa.prun(load, pa.sequential, (2, 2))


# ---------------------------------------------------------------------------
# the tier-1 CLI gate (ISSUE 8 satellite: a contract-registry or
# verifier regression fails the SUITE, not just the CLI)
# ---------------------------------------------------------------------------


def test_palint_check_fast_exits_zero():
    """`tools/palint.py --check --fast` (env lint + plan-soundness leg;
    the fast contract matrix itself is exercised in-process by
    tests/test_static_analysis.py, so the CLI leg skips re-lowering it
    to stay inside the tier-1 time budget) must exit 0."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "palint_t1", os.path.join(REPO, "tools", "palint.py")
    )
    palint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(palint)
    rc = palint.main(["--check", "--fast", "--skip-matrix"])
    assert rc == 0


def test_palint_check_exits_nonzero_on_plan_defect(monkeypatch):
    """The CLI's teeth for the new leg: a verifier that reports a
    defect must turn into exit 1."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "palint_t1b", os.path.join(REPO, "tools", "palint.py")
    )
    palint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(palint)
    monkeypatch.setattr(
        palint, "_plan_soundness_leg",
        lambda verbose=None: (1, [pv.PlanDefect(
            "ghost-race", "device-generic", 0, "seeded defect"
        )]),
    )
    rc = palint.main(["--check", "--fast", "--skip-matrix",
                      "--skip-lint"])
    assert rc == 1
