"""Checkpoint/resume: partition-independent save + restore, incl. onto a
different part count, and a restartable CG run (an aux subsystem the
reference lacks — SURVEY.md §5.4)."""
import os

import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu.models import assemble_poisson, cg, gather_pvector


def test_pvector_roundtrip_same_partition(tmp_path):
    p = str(tmp_path / "v.npz")

    def driver(parts):
        rows = pa.prange(parts, (8, 8), pa.with_ghost)
        v = pa.PVector(
            pa.map_parts(lambda i: i.lid_to_gid * 0.5, rows.partition), rows
        )
        pa.save_pvector(p, v)
        w = pa.load_pvector(p, rows)
        for a, b in zip(v.values, w.values):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_pvector_restore_onto_different_part_count(tmp_path):
    p = str(tmp_path / "v.npz")

    def save4(parts):
        rows = pa.prange(parts, 24)
        v = pa.PVector(
            pa.map_parts(lambda i: np.sin(i.lid_to_gid + 0.5), rows.partition), rows
        )
        pa.save_pvector(p, v)
        return gather_pvector(v)

    def load3(parts):
        rows = pa.prange(parts, 24)
        w = pa.load_pvector(p, rows)
        return gather_pvector(w)

    a = pa.prun(save4, pa.sequential, 4)
    b = pa.prun(load3, pa.sequential, 3)
    np.testing.assert_array_equal(a, b)


def test_mismatched_size_rejected(tmp_path):
    p = str(tmp_path / "v.npz")

    def driver(parts):
        rows = pa.prange(parts, 16)
        pa.save_pvector(p, pa.PVector.full(1.0, rows))
        bad = pa.prange(parts, 17)
        with pytest.raises(ValueError):
            pa.load_pvector(p, bad)
        return True

    assert pa.prun(driver, pa.sequential, 2)


def test_psparse_roundtrip_and_repartition(tmp_path):
    p = str(tmp_path / "A.npz")
    xs = {}

    def save(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (6, 6))
        pa.save_psparse(p, A)
        xs["x"] = gather_pvector(x_exact)
        xs["b"] = gather_pvector(b)
        return True

    def load(parts):
        rows = pa.prange(parts, 36)
        A = pa.load_psparse(p, rows)
        xv = pa.PVector(
            pa.map_parts(lambda i: xs["x"][i.lid_to_gid], A.cols.partition), A.cols
        )
        b2 = A @ xv
        np.testing.assert_allclose(gather_pvector(b2), xs["b"], rtol=1e-13)
        return True

    assert pa.prun(save, pa.sequential, (2, 2))
    assert pa.prun(load, pa.sequential, 3)  # different count AND layout


def test_checkpoint_manifest_and_cg_resume(tmp_path):
    d = str(tmp_path / "ckpt")

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (10, 10))
        # uninterrupted run for the gold answer
        x_full, info_full = cg(A, b, x0=x0, tol=1e-10)
        # interrupted run: stop early, checkpoint, restore, resume
        x_half, _ = cg(A, b, x0=x0, tol=1e-10, maxiter=5)
        pa.save_checkpoint(d, {"x": x_half, "b": b, "A": A}, meta={"it": 5})
        state = pa.load_checkpoint(
            d, {"x": A.cols, "b": A.rows, "A": (A.rows, A.cols)}
        )
        assert state["meta"]["it"] == 5
        x_res, info_res = cg(state["A"], state["b"], x0=state["x"], tol=1e-10)
        assert info_res["converged"]
        err = np.linalg.norm(gather_pvector(x_res) - gather_pvector(x_full))
        assert err < 1e-8, err
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_pvector_sharded_roundtrip_cross_partition(tmp_path):
    d = str(tmp_path / "vshard")

    def save4(parts):
        rows = pa.prange(parts, 30)
        v = pa.PVector(
            pa.map_parts(lambda i: np.cos(i.lid_to_gid * 0.3), rows.partition),
            rows,
        )
        pa.save_pvector_sharded(d, v)
        return gather_pvector(v)

    def load3(parts):
        # ghosted Cartesian target: ghost entries must come back exact
        rows = pa.prange(parts, (6, 5), pa.with_ghost)
        w = pa.load_pvector_sharded(d, rows)
        for iset, vals in zip(
            rows.partition.part_values(), w.values.part_values()
        ):
            np.testing.assert_allclose(
                np.asarray(vals), np.cos(np.asarray(iset.lid_to_gid) * 0.3)
            )
        return gather_pvector(w)

    a = pa.prun(save4, pa.sequential, 4)
    b = pa.prun(load3, pa.sequential, (3, 1))
    np.testing.assert_array_equal(a, b)
    import os

    assert os.path.isfile(os.path.join(d, "index.json"))
    import glob
    import json

    assert len(glob.glob(os.path.join(d, "shard00003-*.npz"))) == 1
    # a second in-place save publishes a fresh generation and RETAINS the
    # previous one as the bit-rot fallback (KEEP_GENERATIONS=2); a third
    # save rotates the oldest out (crash-atomicity: index.json names the
    # retained generations, everything else is garbage)
    with open(os.path.join(d, "index.json")) as f:
        gen1 = json.load(f)["gen"]
    pa.prun(save4, pa.sequential, 4)
    with open(os.path.join(d, "index.json")) as f:
        idx2 = json.load(f)
    gen2 = idx2["gen"]
    assert gen1 != gen2
    assert [g["gen"] for g in idx2["generations"]] == [gen2, gen1]
    shards = glob.glob(os.path.join(d, "shard*.npz"))
    assert len(shards) == 8 and all(
        f"-{gen2}." in s or f"-{gen1}." in s for s in shards
    )
    pa.prun(save4, pa.sequential, 4)
    with open(os.path.join(d, "index.json")) as f:
        idx3 = json.load(f)
    gen3 = idx3["gen"]
    assert [g["gen"] for g in idx3["generations"]] == [gen3, gen2]
    shards = glob.glob(os.path.join(d, "shard*.npz"))
    assert len(shards) == 8 and not any(f"-{gen1}." in s for s in shards)
    # every retained shard's CRC is committed in its generation entry
    for g in idx3["generations"]:
        assert set(g["shards"]) == {
            os.path.basename(s)
            for s in glob.glob(os.path.join(d, f"shard*-{g['gen']}.npz"))
        }


def test_sharded_truncated_shard_falls_back_to_previous_generation(
    tmp_path, capsys
):
    """Bit-rot defense: truncate one shard of the NEWEST generation
    mid-directory — the loader detects the CRC mismatch and falls back
    to the previous committed generation (written before the value
    change, so the values prove which generation was read). Rotting
    BOTH generations raises the typed CheckpointCorruptError."""
    import glob
    import json
    import os

    from partitionedarrays_jl_tpu.parallel.checkpoint import (
        CheckpointCorruptError,
    )

    d = str(tmp_path / "vshard")
    vals = {}

    def save(parts, scale):
        rows = pa.prange(parts, 24)
        v = pa.PVector(
            pa.map_parts(
                lambda i: scale * np.asarray(i.oid_to_gid, dtype=float),
                rows.partition,
            ),
            rows,
        )
        pa.save_pvector_sharded(d, v)
        vals[scale] = gather_pvector(v)
        return True

    def load(parts):
        rows = pa.prange(parts, 24)
        return gather_pvector(pa.load_pvector_sharded(d, rows))

    assert pa.prun(save, pa.sequential, 4, 1.0)  # generation 1
    assert pa.prun(save, pa.sequential, 4, 2.0)  # generation 2 (newest)
    with open(os.path.join(d, "index.json")) as f:
        idx = json.load(f)
    gen2, gen1 = [g["gen"] for g in idx["generations"]]
    # truncate one newest-generation shard (a crash/bit-rot mid-file)
    victim = sorted(glob.glob(os.path.join(d, f"shard*-{gen2}.npz")))[1]
    with open(victim, "r+b") as f:
        f.truncate(os.path.getsize(victim) // 2)
    out = pa.prun(load, pa.sequential, 4)
    np.testing.assert_array_equal(out, vals[1.0])  # the FALLBACK values
    assert "falling back" in capsys.readouterr().err
    # rot the fallback too: no clean generation left -> typed error
    victim1 = sorted(glob.glob(os.path.join(d, f"shard*-{gen1}.npz")))[0]
    with open(victim1, "r+b") as f:
        f.write(b"\x00" * 16)
    with pytest.raises(CheckpointCorruptError):
        pa.prun(load, pa.sequential, 4)


def test_whole_object_checkpoint_crc_detects_rot(tmp_path):
    """Non-sharded checkpoints record per-object CRCs in the manifest;
    a truncated object file raises CheckpointCorruptError instead of a
    deep np.load crash, and solve_with_recovery degrades that to a
    scratch restart rather than dying (covered by the recovery path's
    except clause)."""
    from partitionedarrays_jl_tpu.parallel.checkpoint import (
        CheckpointCorruptError,
        load_checkpoint,
        save_checkpoint,
    )

    d = str(tmp_path / "ck")

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (6, 6))
        save_checkpoint(d, {"x": b}, meta={"it": 3})
        p = os.path.join(d, "x.npz")
        with open(p, "r+b") as f:
            f.truncate(os.path.getsize(p) - 8)
        with pytest.raises(CheckpointCorruptError):
            load_checkpoint(d, {"x": b.rows})
        return True

    import os

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_psparse_sharded_roundtrip_and_repartition(tmp_path):
    d = str(tmp_path / "Ashard")
    xs = {}

    def save(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (6, 6))
        pa.save_psparse_sharded(d, A)
        xs["x"] = gather_pvector(x_exact)
        xs["b"] = gather_pvector(b)
        return True

    def load(parts):
        rows = pa.prange(parts, 36)
        A = pa.load_psparse_sharded(d, rows)
        xv = pa.PVector(
            pa.map_parts(lambda i: xs["x"][i.lid_to_gid], A.cols.partition),
            A.cols,
        )
        b2 = A @ xv
        np.testing.assert_allclose(gather_pvector(b2), xs["b"], rtol=1e-13)
        return True

    assert pa.prun(save, pa.sequential, (2, 2))
    assert pa.prun(load, pa.sequential, 3)


def test_sharded_checkpoint_manifest_roundtrip(tmp_path):
    d = str(tmp_path / "ckpt_sharded")

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        pa.save_checkpoint(
            d, {"x": x_exact, "A": A}, meta={"it": 3}, sharded=True
        )
        state = pa.load_checkpoint(d, {"x": A.cols, "A": (A.rows, A.cols)})
        assert state["meta"]["it"] == 3
        np.testing.assert_array_equal(
            gather_pvector(state["x"]), gather_pvector(x_exact)
        )
        r = state["A"] @ x_exact
        q = A @ x_exact
        np.testing.assert_allclose(
            gather_pvector(r), gather_pvector(q), rtol=1e-14
        )
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_sharded_wrong_kind_and_size_rejected(tmp_path):
    d = str(tmp_path / "v")

    def driver(parts):
        rows = pa.prange(parts, 16)
        pa.save_pvector_sharded(d, pa.PVector.full(1.0, rows))
        bad = pa.prange(parts, 17)
        with pytest.raises(ValueError):
            pa.load_pvector_sharded(d, bad)
        with pytest.raises(ValueError):
            pa.load_psparse_sharded(d, rows)
        return True

    assert pa.prun(driver, pa.sequential, 4)
