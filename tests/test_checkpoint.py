"""Checkpoint/resume: partition-independent save + restore, incl. onto a
different part count, and a restartable CG run (an aux subsystem the
reference lacks — SURVEY.md §5.4)."""
import os

import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu.models import assemble_poisson, cg, gather_pvector


def test_pvector_roundtrip_same_partition(tmp_path):
    p = str(tmp_path / "v.npz")

    def driver(parts):
        rows = pa.prange(parts, (8, 8), pa.with_ghost)
        v = pa.PVector(
            pa.map_parts(lambda i: i.lid_to_gid * 0.5, rows.partition), rows
        )
        pa.save_pvector(p, v)
        w = pa.load_pvector(p, rows)
        for a, b in zip(v.values, w.values):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_pvector_restore_onto_different_part_count(tmp_path):
    p = str(tmp_path / "v.npz")

    def save4(parts):
        rows = pa.prange(parts, 24)
        v = pa.PVector(
            pa.map_parts(lambda i: np.sin(i.lid_to_gid + 0.5), rows.partition), rows
        )
        pa.save_pvector(p, v)
        return gather_pvector(v)

    def load3(parts):
        rows = pa.prange(parts, 24)
        w = pa.load_pvector(p, rows)
        return gather_pvector(w)

    a = pa.prun(save4, pa.sequential, 4)
    b = pa.prun(load3, pa.sequential, 3)
    np.testing.assert_array_equal(a, b)


def test_mismatched_size_rejected(tmp_path):
    p = str(tmp_path / "v.npz")

    def driver(parts):
        rows = pa.prange(parts, 16)
        pa.save_pvector(p, pa.PVector.full(1.0, rows))
        bad = pa.prange(parts, 17)
        with pytest.raises(ValueError):
            pa.load_pvector(p, bad)
        return True

    assert pa.prun(driver, pa.sequential, 2)


def test_psparse_roundtrip_and_repartition(tmp_path):
    p = str(tmp_path / "A.npz")
    xs = {}

    def save(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (6, 6))
        pa.save_psparse(p, A)
        xs["x"] = gather_pvector(x_exact)
        xs["b"] = gather_pvector(b)
        return True

    def load(parts):
        rows = pa.prange(parts, 36)
        A = pa.load_psparse(p, rows)
        xv = pa.PVector(
            pa.map_parts(lambda i: xs["x"][i.lid_to_gid], A.cols.partition), A.cols
        )
        b2 = A @ xv
        np.testing.assert_allclose(gather_pvector(b2), xs["b"], rtol=1e-13)
        return True

    assert pa.prun(save, pa.sequential, (2, 2))
    assert pa.prun(load, pa.sequential, 3)  # different count AND layout


def test_checkpoint_manifest_and_cg_resume(tmp_path):
    d = str(tmp_path / "ckpt")

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (10, 10))
        # uninterrupted run for the gold answer
        x_full, info_full = cg(A, b, x0=x0, tol=1e-10)
        # interrupted run: stop early, checkpoint, restore, resume
        x_half, _ = cg(A, b, x0=x0, tol=1e-10, maxiter=5)
        pa.save_checkpoint(d, {"x": x_half, "b": b, "A": A}, meta={"it": 5})
        state = pa.load_checkpoint(
            d, {"x": A.cols, "b": A.rows, "A": (A.rows, A.cols)}
        )
        assert state["meta"]["it"] == 5
        x_res, info_res = cg(state["A"], state["b"], x0=state["x"], tol=1e-10)
        assert info_res["converged"]
        err = np.linalg.norm(gather_pvector(x_res) - gather_pvector(x_full))
        assert err < 1e-8, err
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_pvector_sharded_roundtrip_cross_partition(tmp_path):
    d = str(tmp_path / "vshard")

    def save4(parts):
        rows = pa.prange(parts, 30)
        v = pa.PVector(
            pa.map_parts(lambda i: np.cos(i.lid_to_gid * 0.3), rows.partition),
            rows,
        )
        pa.save_pvector_sharded(d, v)
        return gather_pvector(v)

    def load3(parts):
        # ghosted Cartesian target: ghost entries must come back exact
        rows = pa.prange(parts, (6, 5), pa.with_ghost)
        w = pa.load_pvector_sharded(d, rows)
        for iset, vals in zip(
            rows.partition.part_values(), w.values.part_values()
        ):
            np.testing.assert_allclose(
                np.asarray(vals), np.cos(np.asarray(iset.lid_to_gid) * 0.3)
            )
        return gather_pvector(w)

    a = pa.prun(save4, pa.sequential, 4)
    b = pa.prun(load3, pa.sequential, (3, 1))
    np.testing.assert_array_equal(a, b)
    import os

    assert os.path.isfile(os.path.join(d, "index.json"))
    import glob
    import json

    assert len(glob.glob(os.path.join(d, "shard00003-*.npz"))) == 1
    # a second in-place save publishes a fresh generation and removes the
    # old shards (crash-atomicity: index.json names the live generation)
    with open(os.path.join(d, "index.json")) as f:
        gen1 = json.load(f)["gen"]
    pa.prun(save4, pa.sequential, 4)
    with open(os.path.join(d, "index.json")) as f:
        gen2 = json.load(f)["gen"]
    assert gen1 != gen2
    shards = glob.glob(os.path.join(d, "shard*.npz"))
    assert len(shards) == 4 and all(f"-{gen2}." in s for s in shards)


def test_psparse_sharded_roundtrip_and_repartition(tmp_path):
    d = str(tmp_path / "Ashard")
    xs = {}

    def save(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (6, 6))
        pa.save_psparse_sharded(d, A)
        xs["x"] = gather_pvector(x_exact)
        xs["b"] = gather_pvector(b)
        return True

    def load(parts):
        rows = pa.prange(parts, 36)
        A = pa.load_psparse_sharded(d, rows)
        xv = pa.PVector(
            pa.map_parts(lambda i: xs["x"][i.lid_to_gid], A.cols.partition),
            A.cols,
        )
        b2 = A @ xv
        np.testing.assert_allclose(gather_pvector(b2), xs["b"], rtol=1e-13)
        return True

    assert pa.prun(save, pa.sequential, (2, 2))
    assert pa.prun(load, pa.sequential, 3)


def test_sharded_checkpoint_manifest_roundtrip(tmp_path):
    d = str(tmp_path / "ckpt_sharded")

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        pa.save_checkpoint(
            d, {"x": x_exact, "A": A}, meta={"it": 3}, sharded=True
        )
        state = pa.load_checkpoint(d, {"x": A.cols, "A": (A.rows, A.cols)})
        assert state["meta"]["it"] == 3
        np.testing.assert_array_equal(
            gather_pvector(state["x"]), gather_pvector(x_exact)
        )
        r = state["A"] @ x_exact
        q = A @ x_exact
        np.testing.assert_allclose(
            gather_pvector(r), gather_pvector(q), rtol=1e-14
        )
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_sharded_wrong_kind_and_size_rejected(tmp_path):
    d = str(tmp_path / "v")

    def driver(parts):
        rows = pa.prange(parts, 16)
        pa.save_pvector_sharded(d, pa.PVector.full(1.0, rows))
        bad = pa.prange(parts, 17)
        with pytest.raises(ValueError):
            pa.load_pvector_sharded(d, bad)
        with pytest.raises(ValueError):
            pa.load_psparse_sharded(d, rows)
        return True

    assert pa.prun(driver, pa.sequential, 4)
