"""End-to-end: N-D Poisson FDM assemble + CG solve on the sequential backend.

The baseline workload (reference: test/test_fdm.jl, BASELINE.json
configs[0]): 10^3 grid over 2x2x2 = 8 parts, correctness gate
norm(x - x̂) < 1e-5 (reference: test/test_fdm.jl:118).
"""
import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu.models import poisson_fdm_driver


def test_fdm_3d_8_parts():
    err, info = pa.prun(poisson_fdm_driver, pa.sequential, (2, 2, 2), (10, 10, 10))
    assert info["converged"]
    assert err < 1e-5


def test_fdm_2d_4_parts():
    err, info = pa.prun(poisson_fdm_driver, pa.sequential, (2, 2), (16, 16))
    assert info["converged"]
    assert err < 1e-5


def test_fdm_1d_uneven_parts():
    err, info = pa.prun(poisson_fdm_driver, pa.sequential, (3,), (17,))
    assert info["converged"]
    assert err < 1e-5


def test_fdm_single_part_matches_multi():
    """Multi-part decomposition must not change the answer: residual
    histories on 1 part and 8 parts agree to machine precision (the
    determinism contract SURVEY.md §7 carries to the TPU backend)."""
    err1, info1 = pa.prun(poisson_fdm_driver, pa.sequential, (1, 1, 1), (8, 8, 8))
    err8, info8 = pa.prun(poisson_fdm_driver, pa.sequential, (2, 2, 2), (8, 8, 8))
    assert err1 < 1e-5 and err8 < 1e-5
    n = min(len(info1["residuals"]), len(info8["residuals"]))
    assert np.allclose(info1["residuals"][:n], info8["residuals"][:n], rtol=1e-9)


# ---------------------------------------------------------------------------
# round-4 fused COO-free stencil assembly (planning.cpp:stencil_emit_dim)
# ---------------------------------------------------------------------------


def _global_triplets_sorted(A):
    from partitionedarrays_jl_tpu.parallel.psparse import (
        psparse_global_triplets,
    )

    out = []
    for gi, gj, v in psparse_global_triplets(A).part_values():
        o = np.lexsort((gj, gi))
        out.append((gi[o], gj[o], v[o]))
    return out


def _assemble_both(parts, ns, dtype, decoupled):
    """(fused, generic) assemblies of the same system. The generic path
    is forced by masking the fast-path constructor."""
    from partitionedarrays_jl_tpu.models import poisson_fdm as pf

    fast = pf.assemble_poisson(parts, ns, dtype=dtype, decoupled=decoupled)
    orig = pf._try_stencil_fast
    pf._try_stencil_fast = lambda *a, **k: None
    try:
        gen = pf.assemble_poisson(parts, ns, dtype=dtype, decoupled=decoupled)
    finally:
        pf._try_stencil_fast = orig
    return fast, gen


@pytest.mark.parametrize(
    "ns,pshape",
    [
        ((7, 6, 5), (2, 2, 1)),
        ((12, 13, 11), (2, 2, 2)),
        ((9, 8), (3, 2)),
        ((30,), (4,)),
        ((3, 3), (2, 1)),  # all-boundary grid: identity everywhere
    ],
)
@pytest.mark.parametrize("decoupled", [False, True])
def test_stencil_fast_matches_coo(ns, pshape, decoupled):
    """The fused native assembly and the generic COO pipeline must agree
    entry-for-entry in GLOBAL id space (local layouts legitimately
    differ: the fused cols PRange appends ghosts gid-sorted, the COO one
    in first-touch order), and on the owned values of b, x̂, x0."""
    from partitionedarrays_jl_tpu.parallel.pvector import _owned

    def driver(parts):
        (A1, b1, xe1, x01), (A2, b2, xe2, x02) = _assemble_both(
            parts, ns, np.float64, decoupled
        )
        for (i1, j1, v1), (i2, j2, v2) in zip(
            _global_triplets_sorted(A1), _global_triplets_sorted(A2)
        ):
            assert np.array_equal(i1, i2) and np.array_equal(j1, j2)
            assert np.array_equal(v1, v2)
        for u, w in ((b1, b2), (xe1, xe2), (x01, x02)):
            for iu, vu, iw, vw in zip(
                u.rows.partition.part_values(),
                u.values.part_values(),
                w.rows.partition.part_values(),
                w.values.part_values(),
            ):
                # b̂ from the fused path is Â @ x̂; the generic path
                # subtracts the lifted couplings — equal in exact
                # arithmetic, compared to rounding here
                assert np.allclose(
                    _owned(iu, vu), _owned(iw, vw), rtol=1e-12, atol=1e-13
                )
        return True

    pa.prun(driver, pa.sequential, pshape)


def test_stencil_fast_f32_decoupled_solves():
    """The fused f32 decoupled system (the flagship bench pipeline) is
    symmetric, consistent, and CG-solvable to the manufactured field."""
    from partitionedarrays_jl_tpu.models import assemble_poisson
    from partitionedarrays_jl_tpu.models.solvers import cg

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(
            parts, (12, 11, 10), dtype=np.float32, decoupled=True
        )
        assert A.dtype == np.float32
        x, info = cg(A, b, x0=x0, tol=1e-5, maxiter=2000)
        assert info["converged"]
        assert float((x - xe).norm() / xe.norm()) < 1e-4
        return True

    pa.prun(driver, pa.sequential, (2, 2, 1))


def test_assemble_poisson_periodic_wraps_and_is_spd():
    """The shifted torus Laplacian (round-5): every row sums to `shift`
    (the -1 arms cancel the 2*dim against the wrap — no boundary rows),
    the operator is symmetric, and b = A @ x̂ holds for the periodic
    manufactured field."""
    ns = (6, 5, 4)

    def driver(parts):
        A, b, xe, x0 = pa.assemble_poisson_periodic(parts, ns, shift=0.5)
        M = pa.gather_psparse(A)
        dense = M.toarray()
        n = dense.shape[0]
        assert n == 6 * 5 * 4
        # row sums == shift exactly (wrap closure: no dropped arms)
        np.testing.assert_allclose(
            dense.sum(axis=1), np.full(n, 0.5), rtol=0, atol=1e-12
        )
        # symmetric (torus stencil with constant coefficients)
        np.testing.assert_allclose(dense, dense.T, rtol=0, atol=0)
        # SPD: smallest eigenvalue == shift (constant mode) > 0
        w = np.linalg.eigvalsh(dense)
        assert w.min() > 0.49, w.min()
        # b really is A @ x̂
        xg = pa.gather_pvector(xe)
        bg = pa.gather_pvector(b)
        np.testing.assert_allclose(dense @ xg, bg, rtol=1e-12, atol=1e-12)
        # wrap coupling present: cell (0,0,0) couples to (5,0,0)
        j = np.ravel_multi_index((5, 0, 0), ns)
        assert dense[0, j] == -1.0
        return True

    assert pa.prun(driver, pa.sequential, (2, 2, 2))
