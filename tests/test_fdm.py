"""End-to-end: N-D Poisson FDM assemble + CG solve on the sequential backend.

The baseline workload (reference: test/test_fdm.jl, BASELINE.json
configs[0]): 10^3 grid over 2x2x2 = 8 parts, correctness gate
norm(x - x̂) < 1e-5 (reference: test/test_fdm.jl:118).
"""
import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu.models import poisson_fdm_driver


def test_fdm_3d_8_parts():
    err, info = pa.prun(poisson_fdm_driver, pa.sequential, (2, 2, 2), (10, 10, 10))
    assert info["converged"]
    assert err < 1e-5


def test_fdm_2d_4_parts():
    err, info = pa.prun(poisson_fdm_driver, pa.sequential, (2, 2), (16, 16))
    assert info["converged"]
    assert err < 1e-5


def test_fdm_1d_uneven_parts():
    err, info = pa.prun(poisson_fdm_driver, pa.sequential, (3,), (17,))
    assert info["converged"]
    assert err < 1e-5


def test_fdm_single_part_matches_multi():
    """Multi-part decomposition must not change the answer: residual
    histories on 1 part and 8 parts agree to machine precision (the
    determinism contract SURVEY.md §7 carries to the TPU backend)."""
    err1, info1 = pa.prun(poisson_fdm_driver, pa.sequential, (1, 1, 1), (8, 8, 8))
    err8, info8 = pa.prun(poisson_fdm_driver, pa.sequential, (2, 2, 2), (8, 8, 8))
    assert err1 < 1e-5 and err8 < 1e-5
    n = min(len(info1["residuals"]), len(info8["residuals"]))
    assert np.allclose(info1["residuals"][:n], info8["residuals"][:n], rtol=1e-9)
