"""paspec — the convergence observatory
(`partitionedarrays_jl_tpu.telemetry.spectrum`).

The contracts pinned here:

* **Lanczos reconstruction** — the CG α/β recurrence reconstructs the
  exact eigenvalues of a known-spectrum operator (synthetic dense CG),
  and the κ̂ estimated from the DEVICE trace ring on the analytic
  Poisson FDM fixture lies inside the documented band of the closed-
  form value (the `tools/paspec.py --check` pin, run in-process).
* **Forecaster** — `predict_iters` is monotone non-increasing in tol,
  exact (1 iteration) on a uniform diagonal operator with known
  spectrum, and its realized error on the conformance probe stays
  inside the committed band.
* **Block-vs-solo** — under strict-bits the block ring's per-column
  spectra equal the solo solves' spectra EXACTLY (the trajectories are
  bitwise, so the tridiagonals are too).
* **Trace-ring exemption honesty** — a body that cannot carry the ring
  (pipelined) emits the typed ``trace_unavailable`` event naming
  itself instead of silently returning no spectrum.
* **Overhead** — the solver path never reads ``PA_SPEC*``: the block
  program lowers to byte-identical StableHLO with the observatory and
  admission fully enabled vs disabled.
* **Admission** — `DeadlineInfeasible` end-to-end over HTTP: typed 422
  refusal at the gate door with predicted_s/available_s diagnostics,
  zero solver iterations spent; the chaos-matrix row pins the
  in-process service variant with full metric deltas.

Budget note: the device legs reuse the tiny (6,6,6)/8-part fixture;
everything else is sequential-backend or pure numpy.
"""
import json
import os
import urllib.request

import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu import telemetry
from partitionedarrays_jl_tpu.models import assemble_poisson, cg, pcg
from partitionedarrays_jl_tpu.parallel.health import DeadlineInfeasible
from partitionedarrays_jl_tpu.service import SolveService
from partitionedarrays_jl_tpu.telemetry import spectrum

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _backend(n=8):
    import jax

    from partitionedarrays_jl_tpu.parallel.tpu import TPUBackend

    return TPUBackend(devices=jax.devices()[:n])


# ---------------------------------------------------------------------------
# Lanczos reconstruction: exact on a synthetic known-spectrum system
# ---------------------------------------------------------------------------


def _dense_cg_ab(A, b, iters):
    """Textbook dense CG collecting the α/β recurrence — the oracle the
    reconstruction formulas are checked against."""
    x = np.zeros_like(b)
    r = b - A @ x
    p = r.copy()
    rs = float(r @ r)
    alphas, betas = [], []
    for _ in range(iters):
        q = A @ p
        alpha = rs / float(p @ q)
        x += alpha * p
        r -= alpha * q
        rs_new = float(r @ r)
        beta = rs_new / rs
        p = r + beta * p
        alphas.append(alpha)
        betas.append(beta)
        rs = rs_new
        if rs == 0.0:
            break
    return alphas, betas


def test_lanczos_reconstruction_exact_on_known_spectrum():
    """After k = #distinct-eigenvalues CG iterations the reconstructed
    T_k's Ritz values ARE the eigenvalues (CG–Lanczos equivalence,
    exact to rounding on a well-separated synthetic spectrum)."""
    eigs = np.array([1.0, 2.0, 4.0, 8.0])
    rng = np.random.default_rng(0)
    Q, _ = np.linalg.qr(rng.standard_normal((4, 4)))
    A = Q @ np.diag(eigs) @ Q.T
    b = rng.standard_normal(4)
    alphas, betas = _dense_cg_ab(A, b, 4)
    ritz = spectrum.ritz_values(alphas, betas)
    np.testing.assert_allclose(ritz, eigs, rtol=1e-8)
    est = spectrum.estimate_solve(alphas, betas, None)
    assert est["kappa"] == pytest.approx(8.0, rel=1e-8)
    # None-masked tails (the block-solve convention) truncate cleanly
    ritz2 = spectrum.ritz_values(
        list(alphas[:2]) + [None, None], list(betas[:2]) + [None, None]
    )
    assert len(ritz2) == 2
    # no usable coefficients -> no claim
    assert spectrum.ritz_values([], []) is None
    assert spectrum.estimate_solve(None, None, None) is None


def test_trailing_window_reconstruction_stays_inside_spectrum():
    """A trailing window (wrapped ring / resumed host loop,
    ``trace_start > 0``) must spend its first pair completing the next
    diagonal entry: the reconstruction IS the true principal submatrix
    ``T[j0+1:, j0+1:]`` (checked against the full T explicitly), so
    its eigenvalues interlace and stay INSIDE the spectrum — a naive
    rebuild would leak a Ritz value below λmin and inflate κ̂ into the
    admission path."""
    eigs = np.linspace(1.0, 30.0, 12)
    rng = np.random.default_rng(3)
    Q, _ = np.linalg.qr(rng.standard_normal((12, 12)))
    A = Q @ np.diag(eigs) @ Q.T
    b = rng.standard_normal(12)
    alphas, betas = _dense_cg_ab(A, b, 10)
    j0 = 3
    d_full, e_full = spectrum.lanczos_tridiagonal(alphas, betas)
    T = np.diag(d_full) + np.diag(e_full, 1) + np.diag(e_full, -1)
    want = np.linalg.eigvalsh(T[j0 + 1:, j0 + 1:])
    got = spectrum.ritz_values(
        alphas[j0:], betas[j0:], trace_start=j0
    )
    np.testing.assert_allclose(got, want, rtol=1e-10)
    assert got[0] >= eigs[0] - 1e-8 and got[-1] <= eigs[-1] + 1e-8
    # the naive (trace_start-ignorant) rebuild demonstrably leaks low
    naive = spectrum.ritz_values(alphas[j0:], betas[j0:])
    assert naive[0] < got[0]


# ---------------------------------------------------------------------------
# the forecaster
# ---------------------------------------------------------------------------


def test_predict_iters_monotone_in_tol_and_edges():
    """Tightening tol can never DECREASE the forecast (the blended rate
    is target-independent); unmeasured specs make no claim; an already-
    satisfied target predicts 0."""
    spec = {"kappa": 50.0, "rate": 0.3, "samples": 4}
    tols = [1e-2, 1e-4, 1e-6, 1e-8, 1e-10, 1e-12]
    preds = [
        spectrum.predict_iters(spec, t, r0_norm=10.0) for t in tols
    ]
    assert all(isinstance(p, int) and p >= 1 for p in preds)
    assert preds == sorted(preds), preds
    # rate-only and kappa-only specs both forecast
    assert spectrum.predict_iters(
        {"rate": 0.5, "samples": 1}, 1e-6
    ) >= 1
    assert spectrum.predict_iters({"kappa": 100.0}, 1e-6) >= 1
    # no measurement -> no claim; satisfied target -> 0; None spec
    assert spectrum.predict_iters({}, 1e-8) is None
    assert spectrum.predict_iters(None, 1e-8) is None
    assert spectrum.predict_iters(spec, 0.5, r0_norm=0.1) == 0


def _diagonal_operator(parts, N=24, diag=3.0):
    """A = diag·I over a 1-D block partition — the known-spectrum
    (single eigenvalue, κ = 1) fixture."""
    rows = pa.prange(parts, N)

    def coo(i):
        g = np.asarray(i.oid_to_gid)
        # I and J must be distinct buffers: from_coo renumbers in place
        return g.copy(), g.copy(), np.full(len(g), diag)

    c = pa.map_parts(coo, rows.partition)
    cols = pa.add_gids(rows, pa.map_parts(lambda t: t[1], c))
    return pa.PSparseMatrix.from_coo(
        pa.map_parts(lambda t: t[0], c),
        pa.map_parts(lambda t: t[1], c),
        pa.map_parts(lambda t: t[2], c),
        rows, cols, ids="global",
    )


def test_predict_iters_exact_on_uniform_diagonal():
    """A uniform diagonal operator (κ = 1, one distinct eigenvalue):
    CG converges in exactly one iteration, the ring reconstructs the
    eigenvalue exactly, and the forecaster predicts exactly 1."""

    def driver(parts):
        A = _diagonal_operator(parts, 24, 3.0)  # A = 3 I
        xe = pa.PVector.full(1.0, A.cols)
        b = A @ xe
        telemetry.reset_store()
        x, info = cg(A, b, tol=1e-10)
        assert info["iterations"] == 1
        rec = info.record
        # T_1 = [[1/alpha_0]] = [[3.0]] exactly
        ritz = spectrum.ritz_values(rec.alpha, rec.beta)
        assert ritz is not None and ritz[0] == pytest.approx(3.0)
        spec = telemetry.spectrum_store().spec(
            telemetry.spectrum_fingerprint(A), "float64", "none"
        )
        assert spec["kappa"] == pytest.approx(1.0)
        r0 = float(info["residuals"][0])
        for tol in (1e-4, 1e-8, 1e-12):
            assert spectrum.predict_iters(spec, tol, r0_norm=r0) == 1
        return True

    assert pa.prun(driver, pa.sequential, 2)


def test_spectrum_fingerprint_is_value_sensitive():
    """Two same-shaped operators must NOT share a spectrum-store key:
    κ/rate are value-bound, so the spectral fingerprint digests the
    value streams while the throughput key stays shape-only (cost IS
    shape-bound) — the cross-tenant blending guard."""
    from partitionedarrays_jl_tpu.telemetry.throughput import (
        operator_fingerprint,
    )

    def driver(parts):
        A1 = _diagonal_operator(parts, 24, 3.0)
        A2 = _diagonal_operator(parts, 24, 7.0)  # same shape, new values
        assert operator_fingerprint(A1) == operator_fingerprint(A2)
        f1 = telemetry.spectrum_fingerprint(A1)
        f2 = telemetry.spectrum_fingerprint(A2)
        assert f1 != f2
        assert f1.startswith(operator_fingerprint(A1))
        # cached: the O(nnz) digest is paid once per operator
        assert telemetry.spectrum_fingerprint(A1) is f1
        return True

    assert pa.prun(driver, pa.sequential, 2)


def test_warm_start_forecasts_remaining_work():
    """A resubmission FROM a (near-)converged iterate (the eviction-
    requeue / journal-resume shape) must forecast its REMAINING work:
    ``residual_norm(A, b, x0)`` is ~0 at the solution, the target is
    already met, and the forecast is 0 — a cold ``‖b‖`` forecast here
    could refuse a finished request as infeasible."""

    def driver(parts):
        A = _diagonal_operator(parts, 24, 3.0)
        xe = pa.PVector.full(1.0, A.cols)
        b = A @ xe
        cold = spectrum.residual_norm(A, b)
        warm = spectrum.residual_norm(A, b, xe)
        assert cold > 1.0 and warm <= 1e-12 * cold
        spec = {"kappa": 100.0, "rate": 0.9, "samples": 4}
        assert spectrum.predict_iters(spec, 1e-8, r0_norm=cold) > 10
        assert spectrum.predict_iters(spec, 1e-8, r0_norm=warm) == 0
        return True

    assert pa.prun(driver, pa.sequential, 2)


def test_paspec_check_covers_kappa_band_forecast_and_feasibility():
    """`tools/paspec.py --check` in-process: device probe with the
    trace ring, κ̂ inside the documented band of the ANALYTIC Poisson
    value, forecaster validated on three (operator, tol) pairs, and
    the PA_SPEC_ADMIT feasibility verdict demonstrated (typed refusal,
    zero iterations) — exit status is the contract."""
    import importlib.util

    path = os.path.join(REPO, "tools", "paspec.py")
    spec_ = importlib.util.spec_from_file_location("paspec_t", path)
    mod = importlib.util.module_from_spec(spec_)
    spec_.loader.exec_module(mod)
    assert mod.check() == 0


# ---------------------------------------------------------------------------
# block ring vs solo (strict-bits)
# ---------------------------------------------------------------------------


def test_block_per_column_spectra_match_solo_bitwise(monkeypatch):
    """Strict-bits: each block column's trajectory IS its solo
    trajectory (PR 3), so the per-column rings reconstruct IDENTICAL
    spectra — masked post-convergence trips truncate, never pollute."""
    monkeypatch.setenv("PA_TPU_STRICT_BITS", "1")
    monkeypatch.setenv("PA_TRACE_ITERS", "128")
    from partitionedarrays_jl_tpu.parallel.tpu import tpu_block_cg, tpu_cg

    backend = _backend()

    def probe(parts):
        A, b, xe, x0 = assemble_poisson(parts, (6, 6, 6))
        b2 = pa.PVector(
            pa.map_parts(lambda v: v * 1.5, b.values), b.rows
        )
        return A, b, b2, x0

    A, b, b2, x0 = pa.prun(probe, backend, (2, 2, 2))

    def driver(parts):
        xs, binfo = tpu_block_cg(
            A, [b, b2], X0=[x0, x0], tol=1e-9, maxiter=100
        )
        brec = binfo.record
        assert isinstance(brec.alpha[0], list) and len(brec.alpha) == 2
        for k, bk in enumerate((b, b2)):
            x, sinfo = tpu_cg(A, bk, x0=x0, tol=1e-9, maxiter=100)
            eb = telemetry.estimate_solve(
                brec.alpha[k], brec.beta[k],
                binfo["columns"][k]["residuals"],
            )
            es = telemetry.estimate_solve(
                sinfo.record.alpha, sinfo.record.beta,
                sinfo["residuals"],
            )
            assert eb["ritz_k"] == es["ritz_k"]
            assert eb["lam_min"] == es["lam_min"]  # bitwise-equal rings
            assert eb["lam_max"] == es["lam_max"]
            assert eb["kappa"] == es["kappa"]
        return True

    assert pa.prun(driver, backend, (2, 2, 2))


def test_trace_unavailable_event_names_the_body(monkeypatch):
    """Trace-ring exemption honesty: a pipelined solve under
    PA_TRACE_ITERS cannot carry the ring — it must say so typed
    (``trace_unavailable`` naming the body) instead of silently
    returning a record with no spectrum."""
    monkeypatch.setenv("PA_TRACE_ITERS", "64")
    from partitionedarrays_jl_tpu.parallel.tpu import tpu_cg

    backend = _backend()

    def probe(parts):
        A, b, xe, x0 = assemble_poisson(parts, (6, 6, 6))
        return A, b, x0

    A, b, x0 = pa.prun(probe, backend, (2, 2, 2))

    def driver(parts):
        x, info = tpu_cg(A, b, x0=x0, tol=1e-9, maxiter=100,
                         pipelined=True)
        rec = info.record
        assert rec.alpha is None  # no ring on the pipelined body
        evs = rec.events_of("trace_unavailable")
        assert evs and evs[0].label == "pipelined"
        assert evs[0].details["requested"] == 64
        # the spectrum layer still measured the RATE from the history
        est = telemetry.estimate_solve(
            rec.alpha, rec.beta, info["residuals"]
        )
        assert est["lam_min"] is None and est["rate"] is not None
        return True

    assert pa.prun(driver, backend, (2, 2, 2))


# ---------------------------------------------------------------------------
# anomaly detectors
# ---------------------------------------------------------------------------


def test_anomaly_detectors_classify_trajectories():
    """Synthetic trajectories hit exactly their documented class, and a
    degraded preconditioner (κ̂ drift vs the stored baseline) is
    flagged only against a measured baseline."""
    W = spectrum.ANOMALY_WINDOW
    # clean geometric convergence: nothing fires
    clean = [10.0 * 0.5 ** i for i in range(3 * W)]
    assert spectrum.detect_anomalies(None, clean, None, True, "none") == []
    # plateau (fp floor) on an unconverged solve: stagnation
    stalled = [10.0 * 0.5 ** i for i in range(W)] + [1e-12] * (2 * W)
    assert spectrum.detect_anomalies(
        None, stalled, None, False, "none"
    ) == ["stagnation"]
    # growth far above the best-seen: divergence
    diverging = [1.0, 0.5, 0.2, 5.0, 40.0]
    assert spectrum.detect_anomalies(
        None, diverging, None, False, "none"
    ) == ["divergence"]
    # preconditioner degradation: κ̂ drifted 4x above a measured prior
    prior = {"kappa": 10.0, "rate": 0.2, "samples": 3}
    est = {"kappa": 100.0, "rate": 0.2}
    assert spectrum.detect_anomalies(
        est, clean, prior, True, "diag"
    ) == ["precond_degradation"]
    # ... but never for unpreconditioned solves or unmeasured priors
    assert spectrum.detect_anomalies(est, clean, prior, True, "none") == []
    assert spectrum.detect_anomalies(
        est, clean, {"kappa": 10.0, "rate": 0.2, "samples": 1}, True,
        "diag",
    ) == []


def test_stagnation_anomaly_emitted_through_observe_path():
    """The observe wiring end-to-end: a stalled trajectory fed through
    `observe_solve` lands a ``convergence_anomaly`` event on the ACTIVE
    record and ticks the labeled ``spec.anomalies`` counter (the
    CATALOG row); the estimate still enters the store."""

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        W = spectrum.ANOMALY_WINDOW
        stalled = [10.0 * 0.5 ** i for i in range(W)] + [1e-12] * (
            2 * W
        )
        c0 = telemetry.registry().counter(
            "spec.anomalies", labels={"kind": "stagnation"}
        ).value
        with telemetry.solve_scope("cg", backend="host") as rec:
            est = telemetry.observe_solve(
                A, rec,
                info={"residuals": stalled, "converged": False},
                dtype=np.float64,
            )
            assert est is not None and est["rate"] is not None
            evs = rec.events_of("convergence_anomaly")
            assert evs and evs[0].label == "stagnation"
        assert telemetry.registry().counter(
            "spec.anomalies", labels={"kind": "stagnation"}
        ).value == c0 + 1
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


# ---------------------------------------------------------------------------
# the overhead contract
# ---------------------------------------------------------------------------


def test_spec_env_is_invisible_to_compiled_programs(monkeypatch):
    """The solver path never reads PA_SPEC*: the block program lowers
    to byte-identical StableHLO with the observatory + admission fully
    on vs fully off (the PR 6/9/13 convention)."""
    from partitionedarrays_jl_tpu.parallel.tpu import (
        _matrix_operands,
        device_matrix,
        make_cg_fn,
    )

    backend = _backend()
    A = pa.prun(
        lambda parts: assemble_poisson(parts, (6, 6, 6))[0],
        backend, (2, 2, 2),
    )
    dA = device_matrix(A, backend)
    ops = _matrix_operands(dA)
    P, W = dA.col_plan.layout.P, dA.col_plan.layout.W
    z = np.zeros((P, W, 4))

    def text():
        fn = make_cg_fn(dA, tol=1e-9, maxiter=50, rhs_batch=4)
        return fn.jit_fn.lower(z, z, z[..., 0], ops).as_text()

    monkeypatch.setenv("PA_SPEC", "0")
    monkeypatch.setenv("PA_SPEC_ADMIT", "0")
    off = text()
    monkeypatch.setenv("PA_SPEC", "1")
    monkeypatch.setenv("PA_SPEC_ADMIT", "1")
    on = text()
    assert on == off


# ---------------------------------------------------------------------------
# DeadlineInfeasible end-to-end over HTTP
# ---------------------------------------------------------------------------


def test_deadline_infeasible_typed_422_over_http(monkeypatch):
    """The acceptance pin: an infeasible deadline is refused typed at
    the GATE door over HTTP — 422 DeadlineInfeasible with
    predicted_s/available_s diagnostics, never dispatched, zero solver
    iterations spent, event trail + metric deltas — and distinct from
    429 (shed) / 503 (queue backpressure)."""
    from partitionedarrays_jl_tpu.frontdoor import (
        Gate,
        http_solve,
        serve_gate,
    )
    from partitionedarrays_jl_tpu.models import gather_pvector

    A, b, xe, x0 = pa.prun(
        lambda parts: assemble_poisson(parts, (8, 8)),
        pa.sequential, (2, 2),
    )
    gate = Gate(start_workers=True)
    gate.register("p8", A, kmax=2)
    srv = serve_gate(gate, port=0)
    try:
        bg, x0g = gather_pvector(b), gather_pvector(x0)
        # train: one completed request measures spectrum + throughput
        out = http_solve(srv.url, "p8", bg, x0=x0g, tol=1e-9,
                         tag="train")
        assert out["state"] == "done" and out["info"]["converged"]
        svc = gate.service("p8")
        reg = telemetry.registry()
        admitted0 = reg.counter("service.admitted").value
        infeasible0 = reg.counter("spec.infeasible").value
        ev_inf0 = telemetry.counter("events.deadline_infeasible")
        ev_health0 = telemetry.counter("events.health_error")
        monkeypatch.setenv("PA_SPEC_ADMIT", "1")
        out = http_solve(srv.url, "p8", bg, x0=x0g, tol=1e-9,
                         deadline=1e-9, tag="doomed")
        assert out["http_status"] == 422
        assert out["error"] == "DeadlineInfeasible"
        d = out["diagnostics"]
        assert d["predicted_s"] > d["available_s"]
        assert d["predicted_iters"] >= 1 and d["s_per_it"] > 0
        # refused at the door: nothing reached the tenant service, the
        # typed counters and events tell exactly one story
        assert reg.counter("service.admitted").value == admitted0
        assert reg.counter("spec.infeasible").value == infeasible0 + 1
        assert telemetry.counter("events.deadline_infeasible") == (
            ev_inf0 + 1
        )
        assert telemetry.counter("events.health_error") == (
            ev_health0 + 1
        )
        assert svc.stats["slabs"] == 1  # only the training slab ran
        # a generous deadline admits and completes under the same env
        out = http_solve(srv.url, "p8", bg, x0=x0g, tol=1e-9,
                         deadline=3600.0, tag="fine")
        assert out["state"] == "done" and out["info"]["converged"]
        monkeypatch.delenv("PA_SPEC_ADMIT")
        # default-off: the same hopeless deadline is admitted and can
        # only fail later by EXPIRY (the pre-paspec behavior preserved)
        out = http_solve(srv.url, "p8", bg, x0=x0g, tol=1e-9,
                         deadline=1e-9, tag="legacy")
        assert out.get("http_status") != 422
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# the committed artifact
# ---------------------------------------------------------------------------


def test_committed_spectrum_artifact_store_roundtrip():
    """The committed SPECTRUM.json loads back into a `SpectrumStore`
    whose spec forecasts — the admission path can bootstrap from the
    committed record before any live solve measures."""
    rec = json.load(open(os.path.join(REPO, "SPECTRUM.json")))
    st = telemetry.SpectrumStore.load(rec)
    conf = rec["conformance"]
    spec = st.spec(conf["fingerprint"], conf["dtype"],
                   conf["minv_class"])
    assert spec is not None and spec["kappa"] is not None
    pred = spectrum.predict_iters(spec, 1e-8, r0_norm=100.0)
    assert isinstance(pred, int) and pred >= 1
