"""padur — the crash-durable front door
(`partitionedarrays_jl_tpu.frontdoor.journal` + `Gate.recover`).

The contracts pinned here:

* **Journal format** — append-only JSONL segments with per-record
  CRC32, monotonic seq across epochs, fsync'd rotation; replay returns
  exactly what was appended, in order.
* **Torn tail vs corruption** — a defective LAST record truncates with
  the ``journal_truncated`` event + counter (the expected crash
  artifact); a defective record anywhere else raises the typed
  `JournalCorruptError` (acknowledged history is damaged).
* **Recovery ladder** — completed requests serve their RECORDED
  results bitwise; failed requests re-raise typed with the original
  class name; in-flight requests resume from chunk-checkpointed
  iterates; queued requests re-enter EDF and complete bitwise-equal to
  their solo solves; a request whose deadline passed during the outage
  fails typed instead of solving late.
* **Idempotency** — a retried submit with the same key returns the
  original id and (once done) the original bitwise result — never a
  second solve, across restarts included.
* **Request-id collision safety** — ids are epoch-qualified: two gate
  generations can never mint the same id, and `/v1/solve/<id>` for a
  pre-restart id either serves the recovered state (journal on) or
  404s typed (journal off) — never someone else's result.
* **Client resilience** — `http_solve(retries=N)` retries transient
  connection failures via `retry_with_backoff` and honors 429
  ``Retry-After``, with ``give_up`` on the overall deadline.
* **Overhead** — with every ``PA_GATE_JOURNAL*`` knob set and a
  journaling gate actively serving, the block body lowers to
  byte-identical StableHLO vs the journal-off baseline.

The full SIGKILL drill (subprocess, kill -9 mid-slab over HTTP) runs
under the ``slow`` marker; the graceful-SIGTERM exit-code contract has
its own (fast) subprocess test.
"""
import json
import os
import signal
import subprocess
import sys
import time
import urllib.error

import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu import telemetry
from partitionedarrays_jl_tpu.frontdoor import (
    Gate,
    JournalCorruptError,
    RecoveredError,
    RequestJournal,
    http_solve,
    read_journal,
    serve_gate,
)
from partitionedarrays_jl_tpu.models import (
    assemble_poisson,
    cg,
    gather_pvector,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _poisson(grid=(8, 8)):
    return pa.prun(
        lambda parts: assemble_poisson(parts, grid), pa.sequential, (2, 2)
    )


def _counter(name, labels=None):
    return telemetry.registry().counter(name, labels=labels).value


# ---------------------------------------------------------------------------
# the journal itself
# ---------------------------------------------------------------------------


def test_journal_roundtrip_rotation_and_epochs(tmp_path):
    """Append -> rotate -> replay round trip: every record comes back
    CRC-verified in order, seq stays monotonic across segments and
    epochs, and each open starts a fresh epoch + segment."""
    jd = str(tmp_path / "j")
    a0 = _counter("journal.appends")
    r0 = _counter("journal.rotations")
    j = RequestJournal(jd, fsync=True, segment_bytes=4096)
    for i in range(40):
        j.append("shed", tag=f"r{i}", slo_class="besteffort", depth=i)
    assert len(j.segments()) >= 2, "must rotate past segment_bytes"
    assert _counter("journal.appends") == a0 + 41  # + the epoch record
    assert _counter("journal.rotations") >= r0 + 1
    j.close()
    j2 = RequestJournal(jd, fsync=False)
    sheds = [r for r in j2.prior_records if r["kind"] == "shed"]
    assert [r["tag"] for r in sheds] == [f"r{i}" for i in range(40)]
    assert all("wall" in r for r in sheds)
    seqs = [r["seq"] for r in j2.prior_records]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    assert j2.epoch == 2
    # the new epoch's appends continue the seq line
    rec = j2.append("shed", tag="post", slo_class="x", depth=0)
    assert rec["seq"] > max(seqs)
    j2.close()


def test_torn_tail_truncates_mid_file_corruption_raises(tmp_path):
    """The WAL convention: a torn LAST record truncates (counted +
    evented, clean prefix preserved); a bad record followed by clean
    data is real corruption and raises typed."""
    jd = str(tmp_path / "torn")
    j = RequestJournal(jd, fsync=False)
    for i in range(3):
        j.append("shed", tag=f"t{i}", slo_class="x", depth=i)
    j.close()
    last = sorted(j.segments())[-1]
    with open(last, "ab") as f:
        f.write(b'{"kind":"completed","seq":99,"x":[0.1')  # torn write
    t0 = _counter("journal.truncated")
    ev0 = telemetry.counter("events.journal_truncated")
    j2 = RequestJournal(jd, fsync=False)
    assert [
        r["tag"] for r in j2.prior_records if r["kind"] == "shed"
    ] == ["t0", "t1", "t2"], "clean prefix must survive the torn tail"
    assert _counter("journal.truncated") == t0 + 1
    assert telemetry.counter("events.journal_truncated") == ev0 + 1
    # the truncation is durable: a THIRD open sees a clean journal
    j2.close()
    t1 = _counter("journal.truncated")
    j3 = RequestJournal(jd, fsync=False)
    assert _counter("journal.truncated") == t1
    j3.close()
    # mid-file corruption: flip a byte in the FIRST record
    jc = str(tmp_path / "corrupt")
    jx = RequestJournal(jc, fsync=False)
    jx.append("shed", tag="aaaa", slo_class="x", depth=0)
    jx.append("shed", tag="bbbb", slo_class="x", depth=1)
    jx.close()
    seg = sorted(jx.segments())[0]
    data = bytearray(open(seg, "rb").read())
    data[data.find(b"aaaa")] = ord("z")
    open(seg, "wb").write(bytes(data))
    with pytest.raises(JournalCorruptError):
        read_journal(jc, strict=True)
    # a fresh gate open over the damaged journal refuses too
    with pytest.raises(JournalCorruptError):
        RequestJournal(jc, fsync=False)


# ---------------------------------------------------------------------------
# recovery
# ---------------------------------------------------------------------------


def test_recover_completed_failed_and_queued(tmp_path):
    """The recovery ladder over a simulated crash (the first gate is
    simply abandoned — no shutdown runs): a completed request serves
    its recorded result BITWISE, a failed request re-raises typed with
    the original class name, and a queued-but-never-dispatched request
    re-enters EDF and completes bitwise-equal to its solo solve."""
    A, b, xe, x0 = _poisson((8, 8))
    x_solo = gather_pvector(cg(A, b, x0=x0, tol=1e-9)[0])
    jd = str(tmp_path / "j")
    g1 = Gate(journal_dir=jd)
    g1.register("t", A, kmax=4, chunk=2)
    h_done = g1.submit("t", b, x0=x0, tol=1e-9, tag="done-req")
    h_fail = g1.submit("t", b, x0=x0, tol=1e-9, maxiter=5000,
                       deadline=1e-7, slo_class="interactive",
                       tag="fail-req")
    g1.drain()
    assert h_done.state == "done" and h_fail.state == "failed"
    x1 = gather_pvector(h_done.result()[0])
    h_q = g1.submit("t", b, x0=x0, tol=1e-9, tag="queued-req")
    assert h_q.state == "gate-queued"
    # ---- crash ----
    m0 = {
        o: _counter("gate.recovered", labels={"outcome": o})
        for o in ("completed", "failed", "requeued")
    }
    ev0 = telemetry.counter("events.gate_recovered")
    g2 = Gate(journal_dir=jd)
    g2.register("t", A, kmax=4)
    summary = g2.recover()
    assert summary["completed"] == 1 and summary["failed"] == 1
    assert summary["requeued"] == 1 and summary["expired"] == 0
    for o in m0:
        assert _counter(
            "gate.recovered", labels={"outcome": o}
        ) == m0[o] + 1
    assert telemetry.counter("events.gate_recovered") == ev0 + 1
    # completed: bitwise from the record, no solve
    hr = g2.handle(h_done.rid)
    xr, ir = hr.result()
    assert ir["recovered"] and ir["converged"]
    np.testing.assert_array_equal(xr, x1)
    # failed: typed with the ORIGINAL class name preserved
    hf = g2.handle(h_fail.rid)
    assert hf.state == "failed"
    with pytest.raises(RecoveredError) as ei:
        hf.result()
    assert ei.value.error_type == "SolveDeadlineError"
    # recover() is one-shot: a second replay would re-enqueue (and
    # double-solve) the queued request
    with pytest.raises(Exception, match="already replayed"):
        g2.recover()
    # queued: resubmitted, completes bitwise vs solo
    g2.drain()
    xq, iq = g2.handle(h_q.rid).result()
    assert iq["converged"]
    np.testing.assert_array_equal(gather_pvector(xq), x_solo)


def test_recover_resumes_inflight_from_chunk_checkpoint(tmp_path):
    """A chunked request crash-frozen mid-solve resumes from its
    journal-checkpointed iterate: the resubmission's x0 is the saved
    iterate (iterations already spent come off the budget, the
    deadline clock resumes against wall time) and the request
    completes instead of restarting from zero."""
    A, b, xe, x0 = _poisson((12, 12))
    x_direct = gather_pvector(cg(A, b, x0=x0, tol=1e-9)[0])
    jd = str(tmp_path / "j")
    g1 = Gate(journal_dir=jd, checkpoint_dir=str(tmp_path / "c"))
    g1.register("t", A, kmax=2, chunk=4)
    h = g1.submit("t", b, x0=x0, tol=1e-9, maxiter=400,
                  deadline=3600.0, slo_class="interactive",
                  tag="inflight")
    g1.pump(dispatch_only=True)
    svc = g1.service("t")
    svc._stop = True  # freeze after ONE chunk — a crash mid-solve
    svc.step()
    it_done = h.request.iterations
    assert it_done > 0
    kinds = [r["kind"] for r in read_journal(jd)]
    assert kinds.count("chunk") >= 1, kinds
    # ---- crash ----
    g2 = Gate(journal_dir=jd, checkpoint_dir=str(tmp_path / "c2"))
    g2.register("t", A, kmax=2, chunk=4)
    summary = g2.recover()
    assert summary["resumed"] == 1, summary
    h2 = g2.handle(h.rid)
    # the resubmission carries the checkpointed iterate and the
    # REDUCED budget — resumed, not reset
    assert h2.kwargs["x0"] is not None
    assert h2.kwargs["maxiter"] == 400 - it_done
    assert h2.kwargs["deadline"] < 3600.0
    g2.drain()
    x, info = h2.result()
    assert info["converged"]
    np.testing.assert_allclose(
        gather_pvector(x), x_direct, rtol=0, atol=1e-6
    )


def test_recover_expired_deadline_fails_typed(tmp_path):
    """The deadline clock RESUMES across the outage: a journaled
    request whose deadline already passed by recovery time fails typed
    (`SolveDeadlineError` on the wire) instead of solving late."""
    A, b, xe, x0 = _poisson((8, 8))
    jd = str(tmp_path / "j")
    g1 = Gate(journal_dir=jd)
    g1.register("t", A, kmax=4)
    g1.paused = True
    h = g1.submit("t", b, x0=x0, tol=1e-9, deadline=0.05,
                  slo_class="interactive", tag="expired")
    # ---- crash; the "outage" outlives the deadline ----
    time.sleep(0.1)
    g2 = Gate(journal_dir=jd)
    g2.register("t", A, kmax=4)
    summary = g2.recover()
    assert summary["expired"] == 1, summary
    h2 = g2.handle(h.rid)
    assert h2.state == "failed"
    with pytest.raises(Exception) as ei:
        h2.result()
    assert type(ei.value).__name__ == "SolveDeadlineError"
    # the typed failure is journaled, so the NEXT generation serves it
    # from the record without re-deciding
    g3 = Gate(journal_dir=jd)
    g3.register("t", A, kmax=4)
    assert g3.recover()["failed"] == 1
    with pytest.raises(RecoveredError) as ei:
        g3.handle(h.rid).result()
    assert ei.value.error_type == "SolveDeadlineError"


def test_idempotency_key_never_double_solves(tmp_path):
    """A retried submit with the same idempotency key returns the
    ORIGINAL handle/result and admits nothing new — live, and across a
    crash-recovery (where the key map is rebuilt from the journal)."""
    A, b, xe, x0 = _poisson((8, 8))
    jd = str(tmp_path / "j")
    g1 = Gate(journal_dir=jd)
    g1.register("t", A, kmax=4)
    hits0 = _counter("gate.idempotent_hits")
    ev0 = telemetry.counter("events.idempotent_replay")
    h1 = g1.submit("t", b, x0=x0, tol=1e-9, idempotency_key="k")
    g1.drain()
    x1 = gather_pvector(h1.result()[0])
    adm0 = _counter("service.admitted")
    assert g1.submit("t", b, idempotency_key="k") is h1
    assert _counter("gate.idempotent_hits") == hits0 + 1
    assert telemetry.counter("events.idempotent_replay") == ev0 + 1
    assert _counter("service.admitted") == adm0, "no second solve"
    # ---- crash ----
    g2 = Gate(journal_dir=jd)
    g2.register("t", A, kmax=4)
    g2.recover()
    h2 = g2.submit("t", b, idempotency_key="k")
    assert h2.rid == h1.rid
    np.testing.assert_array_equal(h2.result()[0], x1)
    assert _counter("service.admitted") == adm0
    assert _counter("gate.idempotent_hits") == hits0 + 2


def test_request_ids_collision_safe_and_pre_restart_poll(tmp_path):
    """Satellite bugfix: ids are epoch-qualified, so two gate
    generations can never mint the same id. Journal-off, a pre-restart
    id polls as a typed 404 (never someone else's result); journal-on,
    it serves the recovered state."""
    A, b, xe, x0 = _poisson((8, 8))
    # journal-off: disjoint id spaces across "restarts"
    ga, gb = Gate(), Gate()
    ga.register("t", A, kmax=4)
    gb.register("t", A, kmax=4)
    ha = ga.submit("t", b, x0=x0, tol=1e-9)
    hb = gb.submit("t", b, x0=x0, tol=1e-9)
    assert ha.rid != hb.rid
    ga.drain()
    gb.drain()
    # journal-on: ids carry the journal epoch and stay resolvable
    jd = str(tmp_path / "j")
    g1 = Gate(journal_dir=jd, start_workers=True)
    g1.register("t", A, kmax=4)
    srv = serve_gate(g1, port=0)
    try:
        bg, x0g = gather_pvector(b), gather_pvector(x0)
        out = http_solve(srv.url, "t", bg, x0=x0g, tol=1e-9)
        assert out["state"] == "done"
        rid = out["id"]
    finally:
        srv.stop(drain=False)
    # restarted server, same journal: the PRE-RESTART id still serves
    g2 = Gate(journal_dir=jd, start_workers=True)
    g2.register("t", A, kmax=4)
    g2.recover()
    srv2 = serve_gate(g2, port=0)
    try:
        import urllib.request

        with urllib.request.urlopen(
            f"{srv2.url}/v1/solve/{rid}"
        ) as resp:
            poll = json.loads(resp.read())
        assert poll["state"] == "done" and poll["info"]["recovered"]
        np.testing.assert_array_equal(np.asarray(poll["x"]),
                                      np.asarray(out["x"]))
        # a journal-OFF restart 404s the pre-restart id typed
        g3 = Gate(start_workers=True)
        g3.register("t", A, kmax=4)
        srv3 = serve_gate(g3, port=0)
        try:
            urllib.request.urlopen(f"{srv3.url}/v1/solve/{rid}")
            raise AssertionError("pre-restart id must 404 journal-off")
        except urllib.error.HTTPError as e:
            assert e.code == 404
            assert json.loads(e.read())["error"] == "UnknownRequest"
        finally:
            srv3.stop(drain=False)
    finally:
        srv2.stop(drain=False)


def test_terminal_state_not_acknowledged_before_journaled(tmp_path):
    """The write-ahead invariant applied to COMPLETION: on a journaling
    gate, a finished request reads ``running`` (and ``result()``
    refuses) until its terminal record is durably appended — a client
    can never observe an outcome a crash could then contradict. The
    non-journaling gate is unmasked (no behavior change)."""
    A, b, xe, x0 = _poisson((8, 8))
    jd = str(tmp_path / "j")
    g = Gate(journal_dir=jd)
    g.register("t", A, kmax=4)
    h = g.submit("t", b, x0=x0, tol=1e-9, tag="wal")
    g.pump(dispatch_only=True)
    g.service("t").drain()  # the slab finishes; account() has NOT run
    assert h.request.state == "done"
    assert h.state == "running", "unjournaled terminal must not ack"
    with pytest.raises(RuntimeError, match="journal record"):
        h.result()
    kinds = [r["kind"] for r in read_journal(jd)]
    assert "completed" not in kinds
    g.account()  # journals the terminal, then acknowledges
    assert h.state == "done"
    assert h.result()[1]["converged"]
    kinds = [r["kind"] for r in read_journal(jd)]
    assert kinds.count("completed") == 1
    # journal-off: terminal is visible immediately (unchanged)
    g2 = Gate()
    g2.register("t", A, kmax=4)
    h2 = g2.submit("t", b, x0=x0, tol=1e-9)
    g2.pump(dispatch_only=True)
    g2.service("t").drain()
    assert h2.state == "done"


# ---------------------------------------------------------------------------
# http_solve client resilience (injected failures — no real server)
# ---------------------------------------------------------------------------


class _FakeResponse:
    def __init__(self, status, payload):
        self.status = status
        self._payload = payload

    def read(self):
        return json.dumps(self._payload).encode()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class _FakeHTTPError(urllib.error.HTTPError):
    def __init__(self, url, code, payload, headers=None):
        import email.message

        msg = email.message.Message()
        for k, v in (headers or {}).items():
            msg[k] = str(v)
        super().__init__(url, code, "err", msg, None)
        self._payload = payload

    def read(self):
        return json.dumps(self._payload).encode()


def test_http_solve_retries_transient_and_honors_retry_after():
    """Client resilience with injected failures: two connection
    refusals then success (retry_with_backoff path), a 429 honoring
    the measured Retry-After (capped) before resubmitting, and every
    sleep visible to the injected clock — no real waiting."""
    sleeps = []
    script = [
        urllib.error.URLError("refused"),          # submit try 1
        ConnectionResetError("reset"),             # submit try 2
        _FakeHTTPError("u", 429,                   # submit try 3: shed
                       {"error": "LoadShedded", "retry_after_s": 2.5},
                       {"Retry-After": "3"}),
        _FakeResponse(202, {"id": "r1-0", "state": "gate-queued"}),
        _FakeResponse(200, {"id": "r1-0", "state": "running"}),
        urllib.error.URLError("mid-poll restart"),  # poll hiccup
        _FakeResponse(200, {"id": "r1-0", "state": "done",
                            "x": [1.0, 2.0],
                            "info": {"converged": True,
                                     "iterations": 3,
                                     "status": "converged"}}),
    ]

    def opener(req):
        ev = script.pop(0)
        if isinstance(ev, Exception):
            raise ev
        return ev

    out = http_solve(
        "http://fake", "t", [0.0, 0.0], tol=1e-9, retries=3,
        retry_cap_s=1.5, opener=opener, sleep=sleeps.append,
        poll_s=0.0, timeout_s=60.0,
    )
    assert out["state"] == "done" and out["x"] == [1.0, 2.0]
    assert not script, "every scripted exchange must be consumed"
    # the 429 sleep honored retry_after_s but respected the cap
    assert 1.5 in sleeps, sleeps
    # transient retries actually backed off (nonzero sleeps besides
    # the poll's zero-second ticks)
    assert sum(1 for s in sleeps if s > 0) >= 3, sleeps


def test_http_solve_gives_up_on_deadline():
    """The give_up hook: once the overall timeout budget is spent, a
    transient failure re-raises instead of retrying forever."""
    calls = []

    def opener(req):
        calls.append(1)
        raise urllib.error.URLError("down")

    with pytest.raises(urllib.error.URLError):
        http_solve(
            "http://fake", "t", [0.0], retries=50,
            opener=opener, sleep=lambda s: None, timeout_s=0.0,
        )
    assert len(calls) == 1, "deadline already spent -> no retries"


def test_http_solve_zero_retries_unchanged():
    """The default (retries=0) keeps the one-shot contract benches
    depend on: a 429 returns the typed payload immediately."""
    def opener(req):
        raise _FakeHTTPError(
            "u", 429, {"error": "LoadShedded", "retry_after_s": 9.0},
            {"Retry-After": "9"},
        )

    out = http_solve("http://fake", "t", [0.0], opener=opener,
                     sleep=lambda s: (_ for _ in ()).throw(
                         AssertionError("must not sleep")))
    assert out["http_status"] == 429
    assert out["error"] == "LoadShedded"
    assert out["retry_after"] == "9"


# ---------------------------------------------------------------------------
# overhead pin: the journal adds ZERO in-graph work
# ---------------------------------------------------------------------------


def test_journal_enabled_block_program_hlo_identical(
    tmp_path, monkeypatch
):
    """The PR 6/9/11 convention: with every PA_GATE_JOURNAL* knob set
    and a JOURNALING gate actively serving (admit/dispatch/chunk/
    complete all journaled), the block body lowers to byte-identical
    StableHLO vs the PA_GATE_JOURNAL=0 baseline — durability is
    host-side bookkeeping, never graph work."""
    import jax

    from partitionedarrays_jl_tpu.parallel.tpu import (
        TPUBackend,
        _matrix_operands,
        device_matrix,
        make_cg_fn,
    )

    backend = TPUBackend(devices=jax.devices()[:8])
    A = pa.prun(
        lambda parts: assemble_poisson(parts, (6, 6, 6))[0],
        backend, (2, 2, 2),
    )
    dA = device_matrix(A, backend)
    ops = _matrix_operands(dA)
    P, W = dA.col_plan.layout.P, dA.col_plan.layout.W
    zb = np.zeros((P, W, 2))

    def text():
        fn = make_cg_fn(dA, tol=1e-9, maxiter=50, rhs_batch=2)
        return fn.jit_fn.lower(zb, zb, zb[..., 0], ops).as_text()

    monkeypatch.setenv("PA_GATE_JOURNAL", "0")
    baseline = text()
    monkeypatch.setenv("PA_GATE_JOURNAL", "1")
    monkeypatch.setenv("PA_GATE_JOURNAL_DIR", str(tmp_path / "envj"))
    monkeypatch.setenv("PA_GATE_JOURNAL_FSYNC", "1")
    As, bs, xes, x0s = _poisson((8, 8))
    gate = Gate(checkpoint_dir=str(tmp_path / "c"))
    assert gate.journal is not None, "env dir must enable the journal"
    gate.register("seq", As, kmax=2, chunk=4)
    h = gate.submit("seq", bs, x0=x0s, tol=1e-9, deadline=600.0,
                    slo_class="interactive", idempotency_key="hlo")
    gate.drain()
    assert h.result()[1]["converged"]
    kinds = [r["kind"] for r in read_journal(str(tmp_path / "envj"))]
    assert {"admitted", "dispatched", "chunk", "completed"} <= set(
        kinds
    ), kinds
    assert text() == baseline


# ---------------------------------------------------------------------------
# CLI: the tier-1 smoke + the subprocess drills
# ---------------------------------------------------------------------------


def _load_padur():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "padur", os.path.join(REPO, "tools", "padur.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_padur_check_smoke(capsys):
    """tools/padur.py --check: journal round-trip + forced torn-tail
    recovery + gate recovery/idempotency, in-process (tier-1)."""
    padur = _load_padur()
    rc = padur.main(["--check"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "padur --check: OK" in out


def test_sigterm_graceful_shutdown_subprocess(tmp_path):
    """Satellite: SIGTERM takes the drain-or-checkpoint path (the PR 7
    `shutdown(drain=False)` ladder) instead of dying mid-slab — the
    exit-code contract is 0 after a clean signalled shutdown, and the
    journal records it (`shutdown` record after the `epoch` one)."""
    jd = str(tmp_path / "j")
    uf = str(tmp_path / "url")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "padur.py"),
         "serve", "--journal-dir", jd, "--port", "0",
         "--url-file", uf],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        t0 = time.monotonic()
        while not os.path.exists(uf):
            assert proc.poll() is None, proc.stdout.read()
            assert time.monotonic() - t0 < 90, "server never came up"
            time.sleep(0.05)
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    out = proc.stdout.read()
    assert rc == 0, out
    assert "padur: shutdown (checkpoint) rc=0" in out
    kinds = [r["kind"] for r in read_journal(jd)]
    assert "shutdown" in kinds, kinds


@pytest.mark.slow
def test_crash_drill_sigkill_full(capsys):
    """THE acceptance drill: SIGKILL the serving gate mid-slab over
    HTTP, restart against the same journal + checkpoint dir, and every
    admitted request completes bitwise-equal to its solo solve or
    fails typed — zero lost, zero duplicated, idempotent resubmit
    serves the original result (tools/padur.py --drill)."""
    padur = _load_padur()
    rc = padur.main(["--drill"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "padur --drill: OK" in out
