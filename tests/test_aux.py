"""Aux-subsystem tests: PTimer, fail-fast prun, distance metrics.

Mirrors the reference coverage of test/test_p_timers.jl and
test/test_exception.jl (SURVEY.md §5.1, §5.3).
"""
import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa


def test_ptimer_sections_and_stats(capsys):
    def driver(parts):
        t = pa.PTimer(parts, verbose=True)
        t.tic()
        sum(range(1000))
        t.toc("phase-a")
        with t.section("phase-b"):
            sum(range(10))
        data = t.data
        assert set(data) == {"phase-a", "phase-b"}
        for st in data.values():
            assert st["min"] <= st["avg"] <= st["max"]
            assert st["max"] >= 0
        t.print_timer()
        return True

    assert pa.prun(driver, pa.sequential, 4)
    out = capsys.readouterr().out
    assert "phase-a" in out and "phase-b" in out and "max" in out


def test_ptimer_toc_without_tic():
    def driver(parts):
        t = pa.PTimer(parts)
        with pytest.raises(AssertionError):
            t.toc("nope")

    pa.prun(driver, pa.sequential, 2)


def test_exception_fail_fast(capsys):
    """A driver raising on one part must take the whole job down cleanly
    with the error surfaced (reference: test/test_exception.jl,
    src/MPIBackend.jl:21-36)."""

    class Boom(RuntimeError):
        pass

    def driver(parts):
        def _raise(p):
            if p == 2:
                raise Boom("part 2 exploded")
            return p

        return pa.map_parts(_raise, parts)

    with pytest.raises(Boom):
        pa.prun(driver, pa.tpu, 4)
    assert "aborting job" in capsys.readouterr().out
    # sequential backend propagates too
    with pytest.raises(Boom):
        pa.prun(driver, pa.sequential, 4)


def test_distance_metrics():
    def driver(parts):
        rows = pa.uniform_partition(parts, 12)
        a = pa.PVector(
            pa.map_parts(lambda i: i.lid_to_gid.astype(float), rows.partition), rows
        )
        b = pa.PVector.full(1.0, rows)
        ref_a = np.arange(12.0)
        ref_b = np.ones(12)
        assert pa.sqeuclidean(a, b) == pytest.approx(np.sum((ref_a - ref_b) ** 2))
        assert pa.euclidean(a, b) == pytest.approx(np.linalg.norm(ref_a - ref_b))
        assert pa.cityblock(a, b) == pytest.approx(np.sum(np.abs(ref_a - ref_b)))
        assert pa.chebyshev(a, b) == pytest.approx(np.max(np.abs(ref_a - ref_b)))
        for order in (1.0, 2.0, 3.5):
            assert pa.minkowski(a, b, order) == pytest.approx(
                np.sum(np.abs(ref_a - ref_b) ** order) ** (1 / order)
            )
        return True

    assert pa.prun(driver, pa.sequential, 4)
