"""paprof — phase-attributed profiling and the exchange cost matrix.

The ISSUE-10 tentpole acceptance lives here:

* phase attribution on the 4-part conformance fixture sums to the
  measured per-iteration total within the pinned band
  (`telemetry.profile.PHASE_SUM_BAND`) and reconciles per collective
  kind against `telemetry.comms`'s static per-iteration inventory;
* with profiling off (and on — profiling builds standalone programs)
  the block solver program is byte-identical StableHLO;
* the comms matrix's static side reconciles against
  `comms._exchange_inventory` on BOTH plan families, and the committed
  artifacts cannot drift from a fresh derivation;
* `tools/paprof.py --check` is the tier-1 in-process smoke.

Kept lean (tier-1 sits at ~748s of the 870s budget): ONE (6, 6)
4-part fixture shared module-wide, the split-timer path pinned via
``PA_PROF_TRACE=0`` (deterministic, no trace capture cost), and tiny
trip counts.
"""
import importlib.util
import json
import os
import sys

import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu import telemetry
from partitionedarrays_jl_tpu.models import assemble_poisson
from partitionedarrays_jl_tpu.parallel.tpu import (
    TPUBackend,
    _env_overrides,
    _matrix_operands,
    device_matrix,
    make_cg_fn,
)
from partitionedarrays_jl_tpu.telemetry import commsmatrix as cmx
from partitionedarrays_jl_tpu.telemetry import profile as prof

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def fixture_Ab():
    """The 4-part (6, 6) conformance-scale Poisson operator on a
    (2, 2) device mesh — one staging for the whole module."""
    import jax

    backend = TPUBackend(devices=jax.devices()[:4])

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (6, 6))
        return A

    return pa.prun(driver, backend, (2, 2)), backend


# ---------------------------------------------------------------------------
# phase attribution: the tentpole acceptance
# ---------------------------------------------------------------------------


def test_phase_profile_sums_in_band_and_reconciles(fixture_Ab,
                                                   monkeypatch):
    """Split-timer attribution on the 4-part fixture: the four phases
    sum to the measured per-iteration total within PHASE_SUM_BAND, and
    the per-phase collective split reconciles per kind against
    cg_comms_profile's per-iteration inventory — both recomputed
    independently by `reconcile_phases`."""
    monkeypatch.setenv("PA_PROF_TRACE", "0")
    A, backend = fixture_Ab
    profile = prof.capture_phase_profile(A, backend, reps=3)
    # a loaded host (the full tier-1 suite around this test) can push
    # one capture round out of band on pure timer jitter — the same
    # bounded re-capture discipline as paprof's CLI entry points
    for _retry in range(2):
        if profile is None or profile["in_band"]:
            break
        profile = prof.capture_phase_profile(A, backend, reps=3)
    assert profile is not None
    assert profile["phase_schema_version"] == prof.PHASE_SCHEMA_VERSION
    assert profile["method"] == "split-timer"
    assert set(profile["phases"]) == set(prof.PHASES)
    # keyed by the palint case name + the operator fingerprint
    assert profile["case"] in ("fused", "standard")
    assert profile["fingerprint"] == "g36-p4"
    assert profile["lowering"]["plan"] in ("box", "generic")
    # every phase measured nonnegative, the sum is the attributed total
    s = sum(profile["phases"][p]["s_per_it"] for p in prof.PHASES)
    # phases and the total are rounded to 9 decimals independently
    assert s == pytest.approx(profile["attributed_s_per_it"], abs=1e-8)
    assert all(
        profile["phases"][p]["s_per_it"] >= 0.0 for p in prof.PHASES
    )
    # the pinned band: attributed vs measured
    lo, hi = prof.PHASE_SUM_BAND
    assert profile["band"] == [lo, hi]
    assert lo <= profile["ratio_attributed_over_measured"] <= hi
    assert profile["in_band"] is True
    # per-kind reconciliation, inventory recomputed from the matrix
    dA = device_matrix(A, backend)
    assert prof.reconcile_phases(profile, dA=dA) == []
    # the split itself: permutes ride the halo phase, gathers the dots
    per_it = profile["per_iteration_comms"]
    halo = profile["phases"]["halo_exchange"]["comms"]
    dots = profile["phases"]["dot_allgather"]["comms"]
    assert halo["collective_permute"] == per_it["collective_permute"]
    assert dots["all_gather"] == per_it["all_gather"]
    assert per_it["collective_permute"]["ops"] > 0
    assert per_it["all_gather"]["ops"] > 0
    assert profile["unattributed_comms"] == {}
    # a seeded defect is caught: inflate one phase's gather count
    broken = json.loads(json.dumps(profile))
    broken["phases"]["dot_allgather"]["comms"]["all_gather"]["ops"] += 1
    assert any(
        "all_gather.ops" in m for m in prof.reconcile_phases(broken)
    )


def test_phase_trace_events_merge_shape(fixture_Ab):
    """The patrace merge feed: spans for every phase, synthetic
    iterations consecutive, args carrying the attribution identity.
    The committed artifact is the schema-2 multi-case container; the
    overlap entry additionally carries its boundary_spmv phase."""
    rec = json.load(open(os.path.join(REPO, "PHASE_PROFILE.json")))
    for case in ("standard", "overlap"):
        committed = rec["profiles"][case]
        phases = prof.profile_phases(committed)
        events = prof.phase_trace_events(committed, iterations=2)
        spans = [e for e in events if e.get("cat") == "phase"]
        assert len(spans) == 2 * len(phases)
        assert {e["name"] for e in spans} == set(phases)
        ts = [e["ts"] for e in spans]
        assert ts == sorted(ts)
        assert all(
            e["args"]["case"] == committed["case"] for e in spans
        )
    assert prof.PHASE_BOUNDARY in prof.profile_phases(
        rec["profiles"]["overlap"]
    )
    assert prof.PHASE_BOUNDARY not in prof.profile_phases(
        rec["profiles"]["standard"]
    )


def test_pa_prof_off_noop_and_solver_hlo_identical(fixture_Ab,
                                                   monkeypatch):
    """PA_PROF=0 turns capture into a no-op — and the overhead
    contract: the block solver program is byte-identical StableHLO
    with profiling on, off, or unset (profiling builds standalone
    programs; the solver path never reads PA_PROF*)."""
    A, backend = fixture_Ab
    dA = device_matrix(A, backend)
    ops = _matrix_operands(dA)
    P, W = dA.col_plan.layout.P, dA.col_plan.layout.W
    zb = np.zeros((P, W, 2))

    def text():
        fn = make_cg_fn(dA, tol=1e-9, maxiter=50, rhs_batch=2)
        return fn.jit_fn.lower(zb, zb, zb[..., 0], ops).as_text()

    monkeypatch.setenv("PA_PROF", "1")
    monkeypatch.setenv("PA_PROF_TRACE", "1")
    on = text()
    monkeypatch.setenv("PA_PROF", "0")
    monkeypatch.setenv("PA_PROF_TRACE", "0")
    off = text()
    assert on == off
    assert prof.capture_phase_profile(A, backend) is None


# ---------------------------------------------------------------------------
# the comms matrix
# ---------------------------------------------------------------------------


def test_comms_matrix_static_reconciles_both_plan_families(fixture_Ab):
    """The static per-edge matrix must reconcile exactly with
    comms._exchange_inventory on the box plan AND the generic index
    plan — the two derivations of bytes-on-the-wire can never fork."""
    A, backend = fixture_Ab
    dA = device_matrix(A, backend)
    m = cmx.static_matrix(dA.col_plan, np.float64, K=1, backend=backend)
    assert cmx.reconcile_matrix(m, dA) == []
    assert m["plan"] == "box"
    assert m["rounds"] == m["static"]["ops"] > 0
    with _env_overrides({"PA_TPU_BOX": "0"}):
        A2, _ = fixture_Ab

        def driver(parts):
            a, b, xe, x0 = assemble_poisson(parts, (6, 6))
            return a

        A2 = pa.prun(driver, backend, (2, 2))
        dA2 = device_matrix(A2, backend)
        m2 = cmx.static_matrix(
            dA2.col_plan, np.float64, K=4, backend=backend
        )
        assert m2["plan"] == "generic"
        assert cmx.reconcile_matrix(m2, dA2) == []
    # K scales bytes, not ops
    assert m2["static"]["per_device_bytes"] % 4 == 0
    # every edge labeled by the fabric hook; the virtual CPU mesh is
    # one process, so non-self edges classify as ici
    assert all(e["fabric"] == "ici" for e in m2["edges"]
               if e["src"] != e["dst"])
    # a seeded defect is caught: shrink one wire slab under its payload
    broken = json.loads(json.dumps(m2))
    broken["edges"][0]["wire_slots"] = (
        broken["edges"][0]["payload_slots"] - 1
    )
    assert cmx.reconcile_matrix(broken, dA2) != []


def test_committed_comms_matrix_matches_fresh_static_derivation():
    """COMMS_MATRIX.json is committed from the generic-plan fixture;
    its static side (edges, rounds, bytes) must equal a fresh
    derivation — measured timings may drift, the plan may not."""
    import jax

    committed = json.load(open(os.path.join(REPO, "COMMS_MATRIX.json")))
    assert committed["comms_matrix_schema_version"] == (
        cmx.COMMS_MATRIX_SCHEMA_VERSION
    )
    assert committed["static_check"] == []
    assert committed["attribution"] == "measured-round"
    assert committed["generated_by"] == "paprof"
    backend = TPUBackend(devices=jax.devices()[:4])
    with _env_overrides({"PA_TPU_BOX": "0"}):

        def driver(parts):
            a, b, xe, x0 = assemble_poisson(parts, (6, 6))
            return a

        A = pa.prun(driver, backend, (2, 2))
        dA = device_matrix(A, backend)
        fresh = cmx.static_matrix(
            dA.col_plan, committed["dtype"], K=committed["K"],
            backend=backend,
        )
    static_keys = ("round", "src", "dst", "payload_slots",
                   "wire_slots", "payload_bytes", "wire_bytes")
    committed_static = [
        {k: e[k] for k in static_keys} for e in committed["edges"]
    ]
    fresh_static = [
        {k: e[k] for k in static_keys} for e in fresh["edges"]
    ]
    assert committed_static == fresh_static
    assert committed["static"] == fresh["static"]
    assert all(e["measured_s"] >= 0.0 for e in committed["edges"])


def test_committed_phase_profile_is_reconciled():
    """PHASE_PROFILE.json (the schema-2 container): every committed
    case internally reconciled and in its own recorded band, the
    envelope on the container, and the case set covering the full
    lowering matrix through `phase_case_of` (the ISSUE-17 bugfix: the
    artifact used to commit only the fused body)."""
    rec = json.load(open(os.path.join(REPO, "PHASE_PROFILE.json")))
    assert rec["phase_schema_version"] == prof.PHASE_SCHEMA_VERSION
    profiles = rec["profiles"]
    assert set(profiles) == {
        "standard", "fused", "block_k1_fused", "block_k4_fused",
        "sstep2", "overlap", "twolevel",
    }
    for case, p in profiles.items():
        assert p["case"] == case
        assert prof.reconcile_phases(p) == [], case
        assert p["in_band"] is True, case
        assert p["fingerprint"] == "g36-p4"
    # the s-step entry is attributed per TRIP (unit = s); the overlap
    # entry names its boundary attribution
    assert profiles["sstep2"]["unit"] == 2
    assert profiles["overlap"]["boundary_attribution"] == (
        "structural-nnz-split"
    )
    # the twolevel entry attributes the halo per FABRIC tier (ISSUE
    # 18): both split phases present, the merged halo_exchange absent
    tl_phases = profiles["twolevel"]["phases"]
    for ph in prof.PHASE_HALO_SPLIT:
        assert ph in tl_phases, ph
    assert "halo_exchange" not in tl_phases
    # every lowering-matrix case must map onto a committed entry —
    # paprof --check's coverage gate, pinned here against the artifact
    from partitionedarrays_jl_tpu.parallel.tpu import lowering_matrix

    for case in lowering_matrix():
        assert prof.phase_case_of(case["name"]) in profiles, case["name"]
    assert rec.get("schema_version") == telemetry.ARTIFACT_SCHEMA_VERSION
    assert rec.get("generated_by") == "paprof"
    assert rec.get("platform") and isinstance(rec.get("pa_env"), dict)


# ---------------------------------------------------------------------------
# the operator surface: paprof --check
# ---------------------------------------------------------------------------


def test_paprof_check_smoke(capsys, monkeypatch):
    """`tools/paprof.py --check` in-process: capture, reconcile, comms
    matrix, committed-artifact validation — the tier-1 smoke (reps
    trimmed: the suite sits near its wall-clock budget)."""
    monkeypatch.setenv("PA_PROF_REPS", "3")
    paprof = _load_tool("paprof")
    rc = paprof.main(["--check", "--trace", "0"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "paprof --check: OK" in out
    assert "phase profile:" in out
    assert "comms matrix:" in out
    assert "static reconciliation vs comms inventory: OK" in out
