"""Structural edge cases the reference supports implicitly: rectangular
operators (rows and cols partitioned independently) and parts that own
nothing (more parts than gids) — on the host oracle AND the compiled path."""
import numpy as np

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu.parallel.tpu import (
    DeviceVector,
    device_matrix,
    make_spmv_fn,
)


def _rect_system(parts):
    """8x5 operator, two entries per owned row, over 3 parts."""
    rows = pa.prange(parts, 8)
    cols0 = pa.prange(parts, 5)

    def coo(ri):
        g = np.asarray(ri.oid_to_gid)
        i = np.repeat(g, 2)
        j = np.stack([g % 5, (g + 2) % 5], 1).reshape(-1)
        v = np.ones(len(i), float) * (1.0 + i)
        return i, j, v

    c = pa.map_parts(coo, rows.partition)
    I = pa.map_parts(lambda t: t[0], c)
    J = pa.map_parts(lambda t: t[1], c)
    V = pa.map_parts(lambda t: t[2], c)
    cols = pa.add_gids(cols0, J)
    A = pa.PSparseMatrix.from_coo(I, J, V, rows, cols, ids="global")
    return A, rows, cols


def _rect_dense():
    dense = np.zeros((8, 5))
    for i in range(8):
        dense[i, i % 5] += 1.0 + i
        dense[i, (i + 2) % 5] += 1.0 + i
    return dense


def test_rectangular_spmv_host():
    def driver(parts):
        A, rows, cols = _rect_system(parts)
        x = pa.PVector.full(2.0, cols)
        got = pa.gather_pvector(A @ x)
        np.testing.assert_allclose(got, _rect_dense() @ np.full(5, 2.0))
        return True

    assert pa.prun(driver, pa.sequential, 3)


def test_rectangular_spmv_compiled_matches_host():
    def driver(parts):
        A, rows, cols = _rect_system(parts)
        x = pa.PVector.full(2.0, cols)
        host = pa.gather_pvector(A @ x)
        dA = device_matrix(A, parts.backend)
        dx = DeviceVector.from_pvector(x, parts.backend, dA.col_layout)
        y = make_spmv_fn(dA)(dx.data)
        got = pa.gather_pvector(
            DeviceVector(y, rows, dA.row_layout, parts.backend).to_pvector()
        )
        # XLA may fuse multiply-adds in the row fold (see test_tpu.py), so
        # compare with the established FMA tolerance, not bit equality
        np.testing.assert_allclose(got, host, rtol=1e-14, atol=1e-14)
        return True

    assert pa.prun(driver, pa.tpu, 3)


def test_empty_parts_vector_reductions():
    def driver(parts):
        rows = pa.prange(parts, 3)  # parts 3.. own nothing
        v = pa.PVector.full(1.0, rows)
        assert v.dot(v) == 3.0
        assert float(v.norm()) == np.sqrt(3.0)
        return True

    assert pa.prun(driver, pa.sequential, 5)


def test_empty_parts_compiled_cg():
    def driver(parts):
        rows = pa.prange(parts, 3)
        ident = pa.PSparseMatrix.from_coo(
            pa.map_parts(lambda i: np.asarray(i.oid_to_gid), rows.partition),
            pa.map_parts(lambda i: np.asarray(i.oid_to_gid), rows.partition),
            pa.map_parts(lambda i: np.ones(i.num_oids), rows.partition),
            rows,
            rows,
            ids="global",
        )
        b = pa.PVector.full(1.0, rows)
        x, info = pa.cg(ident, b, tol=1e-12, maxiter=10)
        assert info["converged"]
        np.testing.assert_allclose(pa.gather_pvector(x), np.ones(3))
        return True

    assert pa.prun(driver, pa.tpu, 5)
