"""Interpret-mode checks of the Pallas banded-SpMV kernel against the
reference band-sum semantics (the kernel is the real-TPU hot path; CI runs
it via the Pallas interpreter on CPU — tests/conftest.py sets JAX_PLATFORMS
to cpu)."""
import numpy as np
import pytest

from partitionedarrays_jl_tpu.ops.pallas_dia import (
    LANES,
    dia_spmv_pallas,
    plan_dia_pallas,
)


def _band_reference(vals, x, offsets, n):
    """y[i] = sum_d vals[d, i] * x_padded[i + off_d] on the flat form."""
    y = np.zeros(n, dtype=vals.dtype)
    for d, off in enumerate(offsets):
        src = np.arange(n) + off
        ok = (src >= 0) & (src < n)
        y[ok] += vals[d, np.arange(n)[ok]] * x[src[ok]]
    return y


@pytest.mark.parametrize(
    "n,offsets",
    [
        (6 * LANES * 8, (-LANES * 8, -1, 0, 1, LANES * 8)),  # 2-D-ish stencil
        (4 * LANES * 8, (-3, 0, 5)),                          # asymmetric band
        (2 * LANES * 8, (0,)),                                # pure diagonal
    ],
)
def test_pallas_matches_band_reference(n, offsets):
    rng = np.random.default_rng(7)
    block_rows = 8
    plan = plan_dia_pallas(offsets, n, block_rows=block_rows)
    assert plan is not None
    R, H = plan["n_rows"], plan["halo_rows"]
    vals = np.zeros((len(offsets), plan["padded_len"]), dtype=np.float32)
    vals[:, :n] = rng.standard_normal((len(offsets), n)).astype(np.float32)
    # zero out entries whose shifted read would fall outside [0, n): the
    # framework stores vals=0 there by construction (absent matrix entries)
    for d, off in enumerate(offsets):
        src = np.arange(n) + off
        vals[d, np.arange(n)[(src < 0) | (src >= n)]] = 0.0
    x = rng.standard_normal(n).astype(np.float32)
    xp = np.pad(x, (H * LANES, plan["x_rows"] * LANES - H * LANES - n))

    y = dia_spmv_pallas(
        np.ascontiguousarray(vals.reshape(len(offsets), R, LANES)),
        xp.reshape(-1, LANES),
        offsets,
        R,
        H,
        block_rows,
        interpret=True,
    )
    got = np.asarray(y).reshape(-1)[:n]
    want = _band_reference(vals[:, :n], x, offsets, n)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_plan_rejects_overwide_band():
    assert plan_dia_pallas((-10_000_000, 0, 10_000_000), 1 << 20) is None


def test_plan_geometry():
    plan = plan_dia_pallas((-130, 0, 130), 1000, block_rows=8)
    assert plan["halo_rows"] == 2  # ceil(130/128)
    assert plan["n_rows"] % 8 == 0
    assert plan["padded_len"] == plan["n_rows"] * LANES >= 1000
    # the x operand row count is 8-aligned relative to the block grid: the
    # DMA window (x_rows - n_rows + block_rows) must be a multiple of 8
    assert (plan["x_rows"] - plan["n_rows"] + plan["block_rows"]) % 8 == 0


def test_padded_kernel_matches_band_reference():
    """Direct check of the padded-frame coded kernel (the real-TPU hot
    path) via the Pallas interpreter: full padded vector in, full padded
    vector out, non-owned slots exactly zero."""
    from partitionedarrays_jl_tpu.ops.pallas_dia import (
        PAD_BLOCK_ROWS,
        dia_coded_padded_pallas,
        plan_dia_padded,
    )

    rng = np.random.default_rng(11)
    offsets = (-LANES * 16, -1, 0, 1, LANES * 16)
    kk = (1, 3, 2, 3, 1)  # two constant diagonals, three coded
    code_row = (-1, 0, 1, 2, -1)
    BRL = PAD_BLOCK_ROWS * LANES
    no = BRL + 7 * LANES + 13  # two owned blocks, ragged tail
    plan = plan_dia_padded(offsets, no, n_coded=2)
    assert plan is not None
    nB, o0, g0 = plan["n_blocks"], plan["o0"], plan["g0"]
    assert nB == 2 and o0 == BRL and g0 == 4 * BRL
    D, Dc, kmax = len(offsets), 3, 3
    cb = rng.standard_normal((D, kmax)).astype(np.float32)
    codes = np.zeros((Dc, plan["code_len"]), dtype=np.uint8)
    for d in range(D):
        if kk[d] > 1:
            codes[code_row[d], :no] = rng.integers(0, kk[d], no)
    from partitionedarrays_jl_tpu.ops.pallas_dia import pack_nibble_codes

    packed = pack_nibble_codes(codes)
    Dp = packed.shape[0]
    total = 5 * PAD_BLOCK_ROWS  # one block for ghosts + trash
    x = np.zeros(total * LANES, dtype=np.float32)
    x[o0 : o0 + no] = rng.standard_normal(no).astype(np.float32)
    x[g0 : g0 + 40] = rng.standard_normal(40).astype(np.float32)  # ghosts

    y = dia_coded_padded_pallas(
        cb,
        np.array([no], dtype=np.int32),
        packed.reshape(Dp, -1, LANES),
        x.reshape(-1, LANES),
        offsets,
        kk,
        code_row,
        plan,
        total,
        interpret=True,
    )
    got = np.asarray(y).reshape(-1)
    vals = np.empty((D, no), dtype=np.float32)
    for d in range(D):
        if kk[d] == 1:
            vals[d] = cb[d, 0]
        else:
            vals[d] = cb[d, codes[code_row[d], :no].astype(int)]
    want = _band_reference(vals, x[o0 : o0 + no], offsets, no)
    np.testing.assert_allclose(got[o0 : o0 + no], want, rtol=1e-6, atol=1e-6)
    # every slot outside the owned band — including where the ghosts were —
    # must come back exactly zero
    rest = got.copy()
    rest[o0 : o0 + no] = 0
    assert not rest.any()


def test_padded_kernel_class_accumulator_path():
    """Row-class fast path: K per-class accumulators + ONE select must
    reproduce the per-diagonal-select path bit-for-bit on rows whose
    class coefficients are dense, and match the band reference even with
    zero-skipped coefficients (the skipped terms are the host kernel's
    absent entries)."""
    from partitionedarrays_jl_tpu.ops.pallas_dia import (
        PAD_BLOCK_ROWS,
        dia_coded_padded_pallas,
        pack_nibble_codes,
        plan_dia_padded,
    )

    rng = np.random.default_rng(5)
    offsets = (-LANES * 4, -1, 0, 1, LANES * 4)
    D, K = len(offsets), 2
    kk = (K,) * D
    code_row = (0,) * D
    BRL = PAD_BLOCK_ROWS * LANES
    no = BRL + 3 * LANES + 9
    plan = plan_dia_padded(offsets, no, n_coded=1)
    assert plan is not None
    o0, g0 = plan["o0"], plan["g0"]
    # class 0: dense interior stencil; class 1: diagonal-only (Dirichlet)
    cb = np.zeros((D, K), dtype=np.float32)
    cb[:, 0] = rng.standard_normal(D).astype(np.float32)
    cb[2, 1] = 1.0
    cls_pattern = tuple(
        tuple(bool(cb[d, k] != 0) for d in range(D)) for k in range(K)
    )
    codes = np.zeros((1, plan["code_len"]), dtype=np.uint8)
    codes[0, :no] = rng.integers(0, K, no)
    packed = pack_nibble_codes(codes)
    total = plan["n_blocks"] + 3
    x = np.zeros(total * BRL, dtype=np.float32)
    x[o0 : o0 + no] = rng.standard_normal(no).astype(np.float32)

    args = (
        cb,
        np.array([no], dtype=np.int32),
        packed.reshape(packed.shape[0], -1, LANES),
        x.reshape(-1, LANES),
        offsets,
        kk,
        code_row,
        plan,
        total * PAD_BLOCK_ROWS,
    )
    y_fast = np.asarray(
        dia_coded_padded_pallas(*args, interpret=True, cls_pattern=cls_pattern)
    ).reshape(-1)
    y_sel = np.asarray(
        dia_coded_padded_pallas(*args, interpret=True)
    ).reshape(-1)
    # vs the select path: same per-row term sequence (minus exact-zero
    # skipped terms), so agreement holds to FMA-contraction rounding —
    # XLA may fuse the mul+add chains differently between the two
    # lowerings, which moves individual terms by an ulp
    np.testing.assert_allclose(y_fast, y_sel, rtol=5e-7, atol=5e-7)
    # rows of the diagonal-only class take exactly one product — both
    # paths must agree bitwise there (no accumulation to contract)
    cls1 = np.zeros_like(y_fast, dtype=bool)
    cls1[o0 : o0 + no] = codes[0, :no] == 1
    np.testing.assert_array_equal(y_fast[cls1], y_sel[cls1])
    # vs the band reference with decoded per-element values
    vals = cb[np.arange(D)[:, None], codes[0, :no][None, :].astype(int)]
    want = _band_reference(vals.astype(np.float32), x[o0 : o0 + no], offsets, no)
    np.testing.assert_allclose(y_fast[o0 : o0 + no], want, rtol=1e-6, atol=1e-6)
    rest = y_fast.copy()
    rest[o0 : o0 + no] = 0
    assert not rest.any()
