"""palock — static concurrency & durability-ordering analysis
(`analysis.lock_model` + `analysis.concurrency_lint`) and its runtime
half (`utils.locksan`, ``PA_LOCK_CHECK=1``).

The contracts pinned here:

* **Model soundness** — the whole-package lock/thread inventory names
  every serving-stack lock, sees every spawn's join path, infers
  "callers hold self._lock" helper entry conditions, and the static
  acquisition graph is ACYCLIC (the deadlock argument) with the
  expected cross-subsystem edges.
* **The teeth** — each of the six committed seeded-defect fixtures
  trips EXACTLY its check (and the clean twin none): the paplan
  convention, so a refactor that blinds a check fails loudly.
* **Real package clean-or-waivered** — `lint_concurrency()` is green;
  every waiver carries a real reason AND still names a live finding
  (no stale waivers); the `concurrency-soundness` /
  `durability-ordering` contracts are registered and green.
* **Write-ahead, proven** — the PR 12 durability rules pass on the
  real package, and the seeded ack-before-append mutant fails.
* **Dynamic cross-check** — under ``PA_LOCK_CHECK=1`` the gate/service
  hammer's OBSERVED acquisition edges are cycle-free and a subset of
  the static graph (static says "no cycle possible", dynamic says
  "the model matches reality").
* **Overhead** — ``PA_LOCK_CHECK=0`` is inert (`sanitized` returns the
  raw lock), the solver path never reads PA_LOCK*, and the block
  program lowers to byte-identical StableHLO either way.
* **Regressions** — the first-run findings fixed in this round (the
  `SolveService.stats` read-modify-write races, the bare
  `Registry.counter_value` read) stay fixed, by name.

Budget note: everything host-path runs on the sequential backend's
tiny Poisson fixtures; only the HLO pin touches a device program.
"""
import os
import threading

import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu.analysis import concurrency_lint as cl
from partitionedarrays_jl_tpu.analysis import env_lint
from partitionedarrays_jl_tpu.analysis.concurrency_lint import (
    BLOCKING_WAIVERS,
    CHECK_IDS,
    DAEMON_WAIVERS,
    DURABILITY_RULES,
    FIXTURE_DURABILITY_RULES,
    MANUAL_WAIVERS,
    SEEDED_FIXTURES,
    UNGUARDED_WAIVERS,
    concurrency_report,
    lint_concurrency,
)
from partitionedarrays_jl_tpu.analysis.contracts import contract_by_name
from partitionedarrays_jl_tpu.analysis.lock_model import (
    build_model,
    static_edges,
)
from partitionedarrays_jl_tpu.models import assemble_poisson
from partitionedarrays_jl_tpu.utils import locksan
from partitionedarrays_jl_tpu.utils.locksan import find_cycle, sanitized

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "palock")


def _run(driver):
    assert pa.prun(driver, pa.sequential, (2, 2))


# ---------------------------------------------------------------------------
# the lock model
# ---------------------------------------------------------------------------

#: Every serving-stack lock the model must inventory, with its kind —
#: a lock that silently drops out of the model is a lint blind spot.
EXPECTED_LOCKS = {
    "Gate._lock": "RLock",
    "GateServer._hlock": "Lock",
    "OperatorRegistry._lock": "RLock",
    "Registry.lock": "RLock",
    "RequestJournal._lock": "Lock",
    "SolveService._lock": "RLock",
    "tracing._lock": "Lock",
}


def test_model_inventories_serving_locks_and_threads():
    rep = concurrency_report()
    for name, kind in EXPECTED_LOCKS.items():
        assert name in rep["locks"], f"lock {name} fell out of the model"
        assert rep["locks"][name]["kind"] == kind
    # every spawn in the package is joined on some shutdown path (the
    # thread-shutdown audit: DAEMON_WAIVERS is empty because nothing
    # needs waiving)
    assert rep["threads"], "no thread spawns seen — scanner rot"
    for sp in rep["threads"]:
        assert sp["joined"], f"unjoined spawn: {sp}"
    spawns = {sp["spawn"] for sp in rep["threads"]}
    assert {"SolveService.start", "GateServer.start",
            "FleetMember.start"} <= spawns


def test_model_entry_held_inference_sees_helper_indirection():
    """Private helpers whose EVERY intra-class call site holds the lock
    inherit it as an entry condition — the env_lint-style indirection
    the guarded-by map must see through."""
    held = concurrency_report()["entry_held"]
    for qual, lock in [
        ("frontdoor/scheduler.py:Gate._idem_hit", "Gate._lock"),
        ("service/service.py:SolveService._pop_slab",
         "SolveService._lock"),
        ("frontdoor/journal.py:RequestJournal._rotate",
         "RequestJournal._lock"),
    ]:
        key = f"partitionedarrays_jl_tpu/{qual}"
        assert key in held, f"entry-held inference lost {qual}"
        assert lock in held[key]


def test_static_graph_expected_edges_and_no_cycle():
    """The static deadlock argument: the acquisition graph carries the
    documented cross-subsystem edges and NO cycle. Every edge quotes
    the module:line call chain that witnesses it."""
    edges = static_edges(build_model())
    for e in [
        ("Gate._lock", "SolveService._lock"),
        ("Gate._lock", "RequestJournal._lock"),
        ("OperatorRegistry._lock", "Gate._lock"),
        ("RequestJournal._lock", "Registry.lock"),
        ("SolveService._lock", "Registry.lock"),
    ]:
        assert e in edges, f"static edge {e} vanished"
    for (a, b), (module, line, via) in edges.items():
        assert module.endswith(".py") and line > 0 and "->" in via
    assert find_cycle(list(edges)) is None


def test_find_cycle_detects_and_reports_a_seeded_cycle():
    cyc = find_cycle([("a", "b"), ("b", "c"), ("c", "a"), ("c", "d")])
    assert cyc is not None
    assert cyc[0] == cyc[-1]
    assert set(cyc) == {"a", "b", "c"}
    assert find_cycle([("a", "b"), ("b", "c"), ("a", "c")]) is None


# ---------------------------------------------------------------------------
# the teeth: seeded-defect fixtures (the paplan convention)
# ---------------------------------------------------------------------------


def test_fixture_set_covers_every_check():
    assert set(SEEDED_FIXTURES.values()) == set(CHECK_IDS)


def test_clean_fixture_no_findings():
    out = lint_concurrency(
        os.path.join(FIXTURES, "clean"),
        durability_rules=FIXTURE_DURABILITY_RULES,
    )
    assert out == [], "\n".join(out)


@pytest.mark.parametrize("fixture,expected", sorted(SEEDED_FIXTURES.items()))
def test_seeded_fixture_trips_exactly_its_check(fixture, expected):
    """Each committed defect trips its check and NO other (the negative
    half: a check that starts over-firing fails here too), and every
    finding quotes file:line."""
    rules = (
        FIXTURE_DURABILITY_RULES if fixture == "ack_before_append" else ()
    )
    out = lint_concurrency(
        os.path.join(FIXTURES, fixture), durability_rules=rules
    )
    assert out, f"seeded defect in {fixture} not caught"
    tripped = {s.split("]")[0].lstrip("[") for s in out}
    assert tripped == {expected}, out
    for finding in out:
        assert "mod.py:" in finding, finding  # file:line quoted


# ---------------------------------------------------------------------------
# the real package: clean or waivered, waivers honest
# ---------------------------------------------------------------------------


def test_real_package_lint_green():
    """The acceptance gate: `tools/palock.py --check` in-process."""
    out = lint_concurrency()
    assert out == [], "\n".join(out)


def test_durability_rules_prove_write_ahead():
    """The PR 12 invariant, statically: every journal-acked transition
    has a rule, every rule carries its why, and all pass (the
    ack-before-append fixture proves the same machinery FAILS on the
    inverted order)."""
    assert len(DURABILITY_RULES) >= 6
    transitions = {r.transition for r in DURABILITY_RULES}
    assert {"admitted", "terminal", "adopted", "record"} <= transitions
    for r in DURABILITY_RULES:
        assert len(r.why) > 20, f"rule {r.qualname} needs a real why"
    out = lint_concurrency(checks=["durability-ordering"])
    assert out == [], "\n".join(out)


def test_contracts_registered_and_green():
    for name in ("concurrency-soundness", "durability-ordering"):
        c = contract_by_name(name)
        assert c is not None, f"contract {name} not registered"
        violations = c.check({}, {})
        assert violations == [], violations


def test_waivers_carry_reasons_and_are_not_stale():
    """The NON_LOWERING hygiene rules, applied to palock's tables:
    every waiver carries a >20-char reason AND suppresses a finding
    that still EXISTS (run unwaivered, each key must reappear) — a
    waiver for fixed code is deleted, not kept as armor."""
    for table in (UNGUARDED_WAIVERS, BLOCKING_WAIVERS, DAEMON_WAIVERS,
                  MANUAL_WAIVERS):
        for key, reason in table.items():
            assert len(reason) > 20, f"waiver {key} needs a real reason"
    blob = "\n".join(lint_concurrency(use_waivers=False))
    for key in UNGUARDED_WAIVERS:
        assert repr(key) in blob, f"stale unguarded waiver: {key}"
    for lock, prim in BLOCKING_WAIVERS:
        assert repr(lock) in blob and repr(prim) in blob, (
            f"stale blocking waiver: ({lock}, {prim})"
        )
    # the empty tables stay empty until something real needs them —
    # the fixtures prove both checks still bite
    assert not DAEMON_WAIVERS and not MANUAL_WAIVERS


# ---------------------------------------------------------------------------
# regressions: the first-run findings, fixed by name
# ---------------------------------------------------------------------------


def test_regression_service_stats_bump_exact_under_contention():
    """unguarded-shared-access, fixed: `SolveService.stats` ticks were
    bare ``+= 1`` read-modify-writes racing the worker thread against
    synchronous drivers (first-run palock finding). `_bump` routes
    every tick through the service lock — N threads of ticks land
    exactly."""
    from partitionedarrays_jl_tpu.service import SolveService

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        svc = SolveService(A)
        N_THREADS, N_TICKS = 4, 500

        def work():
            for _ in range(N_TICKS):
                svc._bump("completed")

        threads = [threading.Thread(target=work) for _ in range(N_THREADS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert svc.stats["completed"] == N_THREADS * N_TICKS
        return True

    _run(driver)


def test_regression_counter_value_locked_read():
    """unguarded-shared-access, fixed: `Registry.counter_value` read
    the metrics dict bare while worker threads register counters
    (first-run palock finding). The lint pins the fix: no
    unguarded-shared-access finding may name the registry or the
    service stats again."""
    out = lint_concurrency(checks=["unguarded-shared-access"])
    blob = "\n".join(out)
    assert "Registry._metrics" not in blob, blob
    assert "SolveService.stats" not in blob, blob
    assert out == [], blob


# ---------------------------------------------------------------------------
# the dynamic cross-check: hammers under PA_LOCK_CHECK=1
# ---------------------------------------------------------------------------


def test_hammer_service_under_sanitizer(monkeypatch):
    """The PR 10 worker-thread smoke, re-run with the lock sanitizer
    live: two submitter threads race the background worker; the
    observed acquisition log must be cycle-free and consistent with
    the static graph."""
    from partitionedarrays_jl_tpu.service import SolveService

    monkeypatch.setenv("PA_LOCK_CHECK", "1")
    locksan.reset_observations()

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        svc = SolveService(A, kmax=2).start()
        handles, errors = [], []

        def submit():
            try:
                handles.append(svc.submit(b, x0=x0, tol=1e-9))
            except Exception as e:  # pragma: no cover - failure path
                errors.append(e)

        threads = [threading.Thread(target=submit) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        svc.shutdown(drain=True)
        assert not errors
        assert all(h.result()[1]["converged"] for h in handles)
        return True

    _run(driver)
    events = locksan.observed_events()
    assert events, "sanitizer recorded nothing — the shim fell off"
    assert any(lock == "SolveService._lock" for _, _, lock, _ in events)
    obs = locksan.observed_edges()
    static = set(static_edges(build_model()))
    assert obs <= static, f"observed order outside the static graph: " \
                          f"{obs - static}"
    assert find_cycle(sorted(obs)) is None


def test_hammer_gate_under_sanitizer(monkeypatch, tmp_path):
    """The PR 14/15 gate hammer under the sanitizer: two submitter
    threads race admission (journal append under the gate lock), then
    a drain. The observed edges must include the write-ahead nesting
    Gate._lock -> RequestJournal._lock, stay inside the static graph,
    and carry no cycle; nesting depth >= 2 proves the cross-lock
    window was actually exercised."""
    from partitionedarrays_jl_tpu.frontdoor import Gate

    monkeypatch.setenv("PA_LOCK_CHECK", "1")
    locksan.reset_observations()

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        gate = Gate(journal_dir=str(tmp_path / "j"))
        gate.register("t", A, kmax=2)
        handles, errors = [], []

        def submit(i):
            try:
                handles.append(
                    gate.submit("t", b, x0=x0, tol=1e-9, tag=f"h{i}")
                )
            except Exception as e:  # pragma: no cover - failure path
                errors.append(e)

        threads = [
            threading.Thread(target=submit, args=(i,)) for i in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        gate.drain()
        assert not errors
        assert all(h.result()[1]["converged"] for h in handles)
        return True

    _run(driver)
    obs = locksan.observed_edges()
    assert ("Gate._lock", "RequestJournal._lock") in obs
    static = set(static_edges(build_model()))
    assert obs <= static, f"observed order outside the static graph: " \
                          f"{obs - static}"
    assert find_cycle(sorted(obs)) is None
    assert locksan.observed_max_nesting() >= 2


# ---------------------------------------------------------------------------
# overhead: inert fast path + byte-identical programs
# ---------------------------------------------------------------------------


def test_sanitizer_fast_path_returns_raw_lock(monkeypatch):
    """PA_LOCK_CHECK unset/0 must cost ONE env read at construction and
    zero per acquisition: `sanitized` returns the raw lock object
    itself, not a shim."""
    monkeypatch.delenv("PA_LOCK_CHECK", raising=False)
    raw = threading.Lock()
    assert sanitized(raw, "T._lock") is raw
    monkeypatch.setenv("PA_LOCK_CHECK", "0")
    assert sanitized(raw, "T._lock") is raw
    monkeypatch.setenv("PA_LOCK_CHECK", "1")
    shim = sanitized(raw, "T._lock")
    assert shim is not raw
    locksan.reset_observations()
    with shim:
        pass
    assert any(
        lock == "T._lock" for _, _, lock, _ in locksan.observed_events()
    )
    locksan.reset_observations()


def test_sanitized_lock_supports_condition_protocol(monkeypatch):
    """The service binds ``Condition(self._lock)`` — the shim must
    forward the private wait/notify protocol, popping every RLock
    recursion level on wait and restoring it after."""
    monkeypatch.setenv("PA_LOCK_CHECK", "1")
    locksan.reset_observations()
    lock = sanitized(threading.RLock(), "T._lock")
    cv = threading.Condition(lock)
    with lock:
        with lock:  # re-entrant: two bookkeeping levels to pop
            assert cv.wait(timeout=0.01) is False
            cv.notify_all()
    inner = sanitized(threading.Lock(), "T._inner")
    with lock:
        with inner:
            pass
    assert ("T._lock", "T._inner") in locksan.observed_edges()
    assert locksan.observed_max_nesting() >= 2
    locksan.reset_observations()


def test_pa_lock_check_exempt_and_read_only_in_locksan():
    """The flag is NON_LOWERING (documented reason) and its only reads
    live in utils/locksan.py — the solver path never sees it."""
    assert "PA_LOCK_CHECK" in env_lint.NON_LOWERING
    assert len(env_lint.NON_LOWERING["PA_LOCK_CHECK"]) > 20
    reads = [
        r for r in env_lint.env_read_inventory()
        if r.name == "PA_LOCK_CHECK"
    ]
    assert reads, "PA_LOCK_CHECK reads vanished — stale exemption"
    for r in reads:
        assert r.path.endswith("utils/locksan.py"), r


def test_lock_check_block_program_hlo_identical(monkeypatch):
    """The overhead pin: the compiled block body lowers to
    byte-identical StableHLO with the sanitizer fully enabled vs off —
    PA_LOCK_CHECK is host-side observability, invisible to lowering
    (the PR 6/9/10 convention)."""
    import jax

    from partitionedarrays_jl_tpu.parallel.tpu import (
        TPUBackend,
        _matrix_operands,
        device_matrix,
        make_cg_fn,
    )

    backend = TPUBackend(devices=jax.devices()[:8])
    A = pa.prun(
        lambda parts: assemble_poisson(parts, (6, 6, 6))[0],
        backend, (2, 2, 2),
    )
    dA = device_matrix(A, backend)
    ops = _matrix_operands(dA)
    P, W = dA.col_plan.layout.P, dA.col_plan.layout.W
    zb = np.zeros((P, W, 2))

    def text():
        fn = make_cg_fn(dA, tol=1e-9, maxiter=50, rhs_batch=2)
        return fn.jit_fn.lower(zb, zb, zb[..., 0], ops).as_text()

    monkeypatch.setenv("PA_LOCK_CHECK", "0")
    baseline = text()
    monkeypatch.setenv("PA_LOCK_CHECK", "1")
    assert text() == baseline


# ---------------------------------------------------------------------------
# the CLI
# ---------------------------------------------------------------------------


def _load_palock():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "palock", os.path.join(REPO, "tools", "palock.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_palock_check_smoke(capsys):
    rc = _load_palock().main(["--check"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "palock: OK" in out


def test_palock_fixtures_smoke(capsys):
    rc = _load_palock().main(["--fixtures"])
    out = capsys.readouterr().out
    assert rc == 0, out
    for name in SEEDED_FIXTURES:
        assert f"ok  {name}" in out


def test_lint_module_reexported_from_analysis():
    import partitionedarrays_jl_tpu.analysis as analysis

    assert analysis.lint_concurrency is cl.lint_concurrency
    assert analysis.CHECK_IDS is CHECK_IDS
    assert analysis.find_cycle is find_cycle
