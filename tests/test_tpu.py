"""TPU backend tests on a virtual 8-device CPU mesh.

The `mpiexec -n 8` analog of the reference's MPI suite (SURVEY.md §4): the
same driver bodies run under the TPU backend, and the results are compared
against the sequential oracle — the determinism gate of BASELINE.md.
"""
import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu.models import assemble_poisson, cg, gather_pvector, poisson_fdm_driver
from partitionedarrays_jl_tpu.parallel.tpu import (
    DeviceVector,
    device_matrix,
    make_exchange_fn,
    make_spmv_fn,
)


def test_backend_protocol():
    import jax

    assert len(jax.devices()) == 8, "conftest must provide 8 virtual CPU devices"
    parts = pa.tpu.get_part_ids((2, 2))
    assert parts.shape == (2, 2) and list(parts) == [0, 1, 2, 3]
    assert parts.backend is pa.tpu
    # map_parts preserves the backend identity through planning code
    doubled = pa.map_parts(lambda p: p * 2, parts)
    assert doubled.backend is pa.tpu
    g = pa.gather(doubled)
    assert g.backend is pa.tpu
    assert pa.i_am_main(parts)


def test_too_many_parts_rejected():
    with pytest.raises(AssertionError):
        pa.tpu.get_part_ids(64)


def test_device_vector_roundtrip():
    def driver(parts):
        r = pa.prange(parts, (6, 6), pa.with_ghost)
        v = pa.PVector(
            pa.map_parts(lambda i: i.lid_to_gid.astype(np.float64), r.partition), r
        )
        dv = DeviceVector.from_pvector(v, parts.backend)
        v2 = dv.to_pvector()
        for a, b in zip(v.values, v2.values):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        return True

    assert pa.prun(driver, pa.tpu, (2, 2))


def test_compiled_exchange_matches_host():
    def driver(parts):
        r = pa.prange(parts, (6, 6), pa.with_ghost)
        mk = lambda: pa.PVector(
            pa.map_parts(
                lambda i: np.where(
                    i.lid_to_part == i.part, i.lid_to_gid.astype(np.float64), -1.0
                ),
                r.partition,
            ),
            r,
        )
        # host path
        vh = mk()
        pa.exchange_values(vh.values, vh.values, r.exchanger)
        # device path
        vd = mk()
        dv = DeviceVector.from_pvector(vd, parts.backend)
        dv.data = make_exchange_fn(r, parts.backend)(dv.data)
        v2 = dv.to_pvector()
        for a, b in zip(vh.values, v2.values):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        return True

    assert pa.prun(driver, pa.tpu, (2, 2))


def test_compiled_exchange_periodic_3d():
    def driver(parts):
        r = pa.prange(parts, (4, 4, 4), pa.with_ghost, (True, True, True))
        v = pa.PVector(
            pa.map_parts(
                lambda i: np.where(
                    i.lid_to_part == i.part, i.lid_to_gid.astype(np.float64), -1.0
                ),
                r.partition,
            ),
            r,
        )
        dv = DeviceVector.from_pvector(v, parts.backend)
        dv.data = make_exchange_fn(r, parts.backend)(dv.data)
        v2 = dv.to_pvector()
        for i, vals in zip(r.partition, v2.values):
            assert np.array_equal(np.asarray(vals), i.lid_to_gid.astype(np.float64))
        return True

    assert pa.prun(driver, pa.tpu, (2, 2, 2))


def test_compiled_assembly_matches_host():
    def driver(parts):
        r = pa.prange(parts, (6, 6), pa.with_ghost)
        vh = pa.PVector.full(1.0, r)
        vh.assemble()
        vd = pa.PVector.full(1.0, r)
        dv = DeviceVector.from_pvector(vd, parts.backend)
        dv.data = make_exchange_fn(r, parts.backend, combine="add")(dv.data)
        v2 = dv.to_pvector()
        # device add-combine accumulates into owners; host then zeroes
        # ghosts — compare owned regions only
        for i, a, b in zip(r.partition, vh.values, v2.values):
            assert np.array_equal(
                np.asarray(a)[: i.num_oids], np.asarray(b)[: i.num_oids]
            )
        return True

    assert pa.prun(driver, pa.tpu, (2, 2))


def test_compiled_spmv_matches_host():
    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        dA = device_matrix(A, parts.backend)
        dx = DeviceVector.from_pvector(x_exact, parts.backend, dA.col_layout)
        y = make_spmv_fn(dA)(dx.data)
        host = gather_pvector(b)
        dev = np.asarray(y)
        got = np.zeros_like(host)
        for p, iset in enumerate(A.rows.partition.part_values()):
            got[iset.oid_to_gid] = dev[p, : iset.num_oids]
        # XLA emits fused multiply-adds in the ELL row fold; NumPy cannot,
        # so individual entries may differ by the FMA rounding (<= ~2 ulp)
        # even though the accumulation order is identical.
        np.testing.assert_allclose(got, host, rtol=1e-14, atol=1e-14)
        return True

    assert pa.prun(driver, pa.tpu, (2, 2))


def test_fdm_on_tpu_backend_matches_sequential():
    """The BASELINE.md determinism gate: the same driver, same grid, on the
    sequential oracle and the TPU backend. Iteration counts must be equal
    and the solutions equal to machine precision."""
    err_s, info_s = pa.prun(poisson_fdm_driver, pa.sequential, (2, 2, 2), (10, 10, 10))
    err_t, info_t = pa.prun(poisson_fdm_driver, pa.tpu, (2, 2, 2), (10, 10, 10))
    assert err_s < 1e-5 and err_t < 1e-5
    assert info_t["converged"]
    assert info_s["iterations"] == info_t["iterations"]
    assert abs(err_s - err_t) < 1e-12


def test_fdm_on_tpu_single_part():
    err, info = pa.prun(poisson_fdm_driver, pa.tpu, (1, 1), (8, 8))
    assert err < 1e-5 and info["converged"]


def test_cg_dispatches_to_device():
    """pa.models.cg on TPU-backend data must route to the compiled path and
    agree with the host solve."""

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        x, info = cg(A, b, x0=x0, tol=1e-12)
        return float((x - x_exact).norm()), info["iterations"]

    err_t, it_t = pa.prun(driver, pa.tpu, (2, 2))
    err_s, it_s = pa.prun(driver, pa.sequential, (2, 2))
    assert err_t < 1e-9
    assert it_t == it_s


def test_coded_dia_mode_spmv_matches_host():
    """Coded-diagonal SpMV path: stencil operators draw each diagonal from
    a tiny value set, so `dia_mode == 'coded'`; the device product must
    still match the host kernel to FMA precision."""

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (48, 48, 48))
        dA = device_matrix(A, parts.backend)
        assert dA.dia_mode == "coded", dA.dia_mode
        assert all(k <= dA.CODE_MAX_VALUES for k in dA.dia_kk)
        dx = DeviceVector.from_pvector(x_exact, parts.backend, dA.col_layout)
        y = make_spmv_fn(dA)(dx.data)
        host = gather_pvector(b)
        dev = np.asarray(y)
        got = np.zeros_like(host)
        for p, iset in enumerate(A.rows.partition.part_values()):
            got[iset.oid_to_gid] = dev[p, : iset.num_oids]
        np.testing.assert_allclose(got, host, rtol=1e-14, atol=1e-14)
        return True

    assert pa.prun(driver, pa.tpu, (2, 2, 2))


def test_coded_dia_mode_cg_matches_sequential():
    """CG through the coded-DIA path converges identically to the
    sequential oracle: same iteration count, values to FMA rounding."""
    err_s, info_s = pa.prun(
        poisson_fdm_driver, pa.sequential, (2, 2, 2), (48, 48, 48), tol=1e-8
    )
    err_t, info_t = pa.prun(
        poisson_fdm_driver, pa.tpu, (2, 2, 2), (48, 48, 48), tol=1e-8
    )
    assert info_s["iterations"] == info_t["iterations"]
    np.testing.assert_allclose(err_t, err_s, rtol=1e-12, atol=1e-12)


def test_padded_layout_spmv_matches_host():
    """The real-TPU vector frame (padded block layout + in-frame coded
    kernel) validated on CPU through the Pallas interpreter: same driver,
    forced `padded=True`, must reproduce the host SpMV."""
    from partitionedarrays_jl_tpu.parallel.tpu import DeviceMatrix, make_spmv_fn as mk

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (12, 12, 12))
        dA = DeviceMatrix(A, parts.backend, padded=True)
        assert dA.dia_mode == "coded" and dA.pallas_plan is not None
        lay = dA.row_layout
        assert lay.padded and lay.o0 > 0 and lay.W % lay.o0 == 0
        dx = DeviceVector.from_pvector(x_exact, parts.backend, dA.col_layout)
        y = make_spmv_fn(dA)(dx.data)
        host = gather_pvector(b)
        dev = np.asarray(y)
        got = np.zeros_like(host)
        for p, iset in enumerate(A.rows.partition.part_values()):
            got[iset.oid_to_gid] = dev[p, lay.o0 : lay.o0 + iset.num_oids]
        np.testing.assert_allclose(got, host, rtol=1e-13, atol=1e-13)
        # every non-owned slot of the result must be exactly zero
        for p, iset in enumerate(A.rows.partition.part_values()):
            row = dev[p].copy()
            row[lay.o0 : lay.o0 + iset.num_oids] = 0
            assert not row.any()
        return True

    assert pa.prun(driver, pa.tpu, (2, 2, 2))


def test_compiled_exchange_irregular_graph():
    """BASELINE config 5's structural core: a fully general (non-Cartesian,
    asymmetric) ghost graph from an explicit IndexSet partition, lowered to
    edge-colored ppermute rounds. Halo update and reverse assembly on the
    compiled path must match the host Exchanger bit-for-bit."""
    # the 10-gid 4-part fixture (reference: test_interfaces.jl:177-207)
    LID_TO_GID = [
        [0, 1, 2, 4, 6, 7],
        [1, 3, 4, 9],
        [5, 6, 7, 4, 3, 9],
        [0, 2, 6, 8, 9],
    ]
    LID_TO_PART = [
        [0, 0, 0, 1, 2, 2],
        [0, 1, 1, 3],
        [2, 2, 2, 1, 1, 3],
        [0, 0, 2, 3, 3],
    ]

    def driver(parts):
        partition = pa.map_parts(
            lambda p: pa.IndexSet(p, LID_TO_GID[p], LID_TO_PART[p]), parts
        )
        rows = pa.PRange(10, partition)

        def mk():
            return pa.PVector(
                pa.map_parts(
                    lambda i: np.where(
                        np.asarray(i.lid_to_part) == i.part,
                        100.0 + np.asarray(i.lid_to_gid),
                        -1.0,
                    ),
                    rows.partition,
                ),
                rows,
            )

        # owner -> ghost halo update
        host = pa.exchange_pvector(mk())
        dv = DeviceVector.from_pvector(mk(), parts.backend)
        out = make_exchange_fn(rows, parts.backend)(dv.data)
        got = DeviceVector(out, rows, dv.layout, parts.backend).to_pvector()
        for a, b in zip(host.values, got.values):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        # ghost -> owner assembly (reverse plan, additive combine)
        vh = mk()
        pa.assemble(vh)
        dv2 = DeviceVector.from_pvector(mk(), parts.backend)
        out2 = make_exchange_fn(rows, parts.backend, combine="add")(dv2.data)
        got2 = DeviceVector(out2, rows, dv2.layout, parts.backend).to_pvector()
        for a, b in zip(vh.values, got2.values):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        return True

    assert pa.prun(driver, pa.tpu, 4)


def test_multihost_helpers_single_host():
    """Single-host behavior of the multi-controller helpers: init is a
    no-op, process 0 is MAIN, and fetch_global round-trips a sharded
    array (the multi-host escape hatch degrades to device->host copy)."""
    pa.multihost_init()  # must not raise in a single-process run
    assert pa.is_main_process()

    def driver(parts):
        rows = pa.prange(parts, 64)
        v = pa.PVector(
            pa.map_parts(
                lambda i: np.asarray(i.lid_to_gid, dtype=np.float64),
                rows.partition,
            ),
            rows,
        )
        dv = DeviceVector.from_pvector(v, parts.backend)
        host = pa.fetch_global(dv.data)
        assert host.shape == (4, dv.layout.W)
        back = dv.to_pvector()
        for a, b in zip(v.values, back.values):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        return True

    assert pa.prun(driver, pa.tpu, 4)


def test_padded_frame_solver_parity(monkeypatch):
    """Force the real-TPU padded kernel frame on the CPU mesh (Pallas
    interpret mode): the compiled CG and SpMV must agree with the host
    oracle exactly as the compact frame does. Without this, padded-frame
    bugs are only observable on real hardware."""
    import importlib

    tpu_mod = importlib.import_module("partitionedarrays_jl_tpu.parallel.tpu")
    monkeypatch.setattr(tpu_mod, "_padded_for", lambda backend: True)

    from partitionedarrays_jl_tpu.parallel.tpu import TPUBackend, device_matrix

    def driver(parts):
        A, b, x_exact, x0 = pa.assemble_poisson(parts, (8, 8, 8))
        x, info = pa.cg(A, b, x0=x0, tol=1e-9)
        assert info["converged"]
        err = np.abs(pa.gather_pvector(x) - pa.gather_pvector(x_exact)).max()
        padded = (
            device_matrix(A, parts.backend).padded
            if isinstance(parts.backend, TPUBackend)
            else None
        )
        return float(err), info["iterations"], padded

    err_t, it_t, padded = pa.prun(driver, pa.tpu, (2, 2, 2))
    # the padded DeviceMatrix must actually have been selected
    assert padded
    err_s, it_s, _ = pa.prun(driver, pa.sequential, (2, 2, 2))
    assert it_s == it_t, (it_s, it_t)
    # both solve errors are ~1e-9 magnitudes; compare to rounding noise
    np.testing.assert_allclose(err_t, err_s, rtol=1e-5, atol=1e-12)
    assert err_s < 1e-6 and err_t < 1e-6


def test_pipelined_cg_matches_standard():
    """The lag-1 (pipelined) form: the solution update rides the next
    SpMV; every scalar follows the textbook recurrence, so the residual
    HISTORY must match the standard device loop essentially exactly and
    the solutions must agree to rounding."""
    import jax

    from partitionedarrays_jl_tpu.models import assemble_poisson, gather_pvector
    from partitionedarrays_jl_tpu.parallel.tpu import TPUBackend

    ns = (8, 8, 8)
    tol = 1e-9

    def run(backend, pipelined):
        def driver(parts):
            A, b, x_exact, x0 = assemble_poisson(parts, ns)
            x, info = pa.cg(
                A, b, x0=x0, tol=tol, maxiter=500, pipelined=pipelined
            )
            r = b - A @ x
            return gather_pvector(x), info, r.norm()

        return pa.prun(driver, backend, (2, 2, 2))

    backend = TPUBackend(devices=jax.devices()[:8])
    xs, is_, _ = run(pa.sequential, False)
    xd0, id0, rd0 = run(backend, False)
    xd1, id1, rd1 = run(backend, True)
    for info in (is_, id0, id1):
        assert info["converged"], info
    # identical trajectory: same dots, same order -> same iterations and
    # (to rounding) the same residual history as the standard device loop
    assert id1["iterations"] == id0["iterations"]
    n = id0["iterations"] + 1
    np.testing.assert_allclose(
        np.asarray(id1["residuals"])[:n],
        np.asarray(id0["residuals"])[:n],
        rtol=1e-12,
    )
    np.testing.assert_allclose(np.asarray(xd1), np.asarray(xd0), atol=1e-12)
    np.testing.assert_allclose(np.asarray(xd1), xs, atol=1e-8)
    # honest recomputed residuals meet the relative tolerance
    r0 = float(is_["residuals"][0])
    for rr in (rd0, rd1):
        assert float(rr) <= tol * max(1.0, r0) * 1.5, (float(rr), r0)


def test_stream_staging_after_fused_analysis_padded():
    """Regression (r4 review): an explicit padded=True lowering of a
    banded operator whose offsets exceed the padded plan's reserve takes
    the STREAMING staging branch; when the fused (dense-DIA-free) band
    analysis supplied the det dict, the dense diagonals must be rebuilt
    there — not staged from None as NaN."""
    import jax

    from partitionedarrays_jl_tpu.parallel.tpu import DeviceMatrix, TPUBackend

    backend = TPUBackend(devices=jax.devices()[:1])

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (3, 300000))
        dA = DeviceMatrix(A, backend, padded=True)
        assert dA.dia_mode == "stream"
        vals = np.asarray(dA.dia_vals)
        assert not np.isnan(vals).any()
        return True

    pa.prun(driver, backend, (1, 1))


def test_stencil_fast_declines_unsupported_dtype():
    """Regression (r4 review): dtypes outside the native f32/f64
    envelope must fall back to the generic COO path, not crash the
    fused emitter's post-eligibility check."""

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8), dtype=np.float16)
        assert A.dtype == np.float16
        return True

    pa.prun(driver, pa.sequential, (2, 1))
