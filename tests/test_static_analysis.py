"""palint — the static program-contract analyzer and env-key lint gate.

Four layers, each pinned here:

* **Analyzer unit tests** against COMMITTED lowered-text fixtures
  (tests/fixtures/palint/ — a 4-part (6, 6) Poisson CG program in both
  dialects): exact collective/dtype/copy/while-carry inventories, and
  the migration pin — `analysis.collective_counts` reproduces the raw
  regex counts the three historical per-file helpers produced, on the
  same text.
* **Negative tests**: the dtype-closure contract catches a deliberately
  injected f64 op (the PR 3 poisoning class), the copy-budget contract
  catches copy growth (the PR 2 anomaly class), the loop-residency
  contract catches an injected infeed, and the env lint catches an
  unkeyed lowering-affecting flag in a synthetic package.
* **The env-key lint gate** (tier-1): every lowering-affecting ``PA_*``
  read in the package is key-covered and documented; the classification
  itself is pinned as a fixture so a new flag fails until classified.
* **The contract matrix**: the fast subset every CI run lowers
  (standard / fused / block K∈{1,4} / ABFT pair / f32 probe) holds all
  contracts; the full matrix (with strict-bits and both block bodies)
  is the slow leg `tools/palint.py --check` also runs.
"""
import os
import re
import subprocess
import sys

import pytest

from partitionedarrays_jl_tpu import analysis
from partitionedarrays_jl_tpu.analysis import (
    analyze_text,
    check_contracts,
    classify,
    collective_counts,
    env_lint,
    key_coverage,
    lint_env_keys,
)
from partitionedarrays_jl_tpu.analysis.contracts import COPY_BUDGETS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXDIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "fixtures", "palint")


def _fix(name):
    with open(os.path.join(FIXDIR, name), encoding="utf-8") as f:
        return f.read()


# ---------------------------------------------------------------------------
# analyzer unit tests: committed fixtures with known inventories
# ---------------------------------------------------------------------------


def test_fixture_f64_stablehlo_inventory():
    rep = analyze_text(_fix("cg_4part_f64.stablehlo.txt"))
    assert rep.dialect == "stablehlo"
    assert rep.collectives == {
        "all_gather": 3, "collective_permute": 8,
        "all_reduce": 0, "reduce_scatter": 0,
    }
    assert rep.float_dtypes == {"f64"}
    assert rep.copies == 0  # the op does not exist pre-optimization
    assert rep.host_transfer_ops == []
    # ONE compiled solve loop with the standard body's 14-slot carry
    assert len(rep.while_loops) == 1
    assert len(rep.while_loops[0].carries) == 14
    assert rep.while_loops[0].carry_bytes == 1061
    # payload accounting: 4-part gathers of f64 scalars are visible
    assert rep.collective_bytes["all_gather"] > 0
    assert rep.collective_bytes["collective_permute"] > 0


def test_fixture_f32_stablehlo_closed_over_f32():
    rep = analyze_text(_fix("cg_4part_f32.stablehlo.txt"))
    assert rep.float_dtypes == {"f32"}
    assert rep.f64_lines == []
    assert rep.collectives["collective_permute"] == 8


def test_fixture_compiled_hlo_inventory():
    rep = analyze_text(_fix("cg_4part_f64.hlo.txt"))
    assert rep.dialect == "hlo"
    # collective OP SITES survive compilation unchanged on this program
    assert rep.collectives["all_gather"] == 3
    assert rep.collectives["collective_permute"] == 8
    # the PR 2 canary number this fixture pins: XLA materializes 17
    # copy ops (while-carry copies + fusion roots) for the standard body
    assert rep.copies == 17
    # scatter-add loops + the solve loop
    assert len(rep.while_loops) == 3
    assert max(len(w.carries) for w in rep.while_loops) == 18


def test_hlo_parser_sees_tuple_and_async_collectives():
    """Compiled-HLO op-site counting must survive the two other result
    spellings XLA prints: a TUPLE result (spaces defeat a naive \\S+
    capture) and an async start/done pair (one collective, counted at
    the start op only — done consumes the handle)."""
    txt = "\n".join([
        "ENTRY %main {",
        "  %p0 = f64[9]{0} collective-permute(%x), channel_id=1",
        "  %p1 = (f64[3]{0}, f64[3]{0}) collective-permute(%a, %b)",
        "  %s = (f32[2]{0}, f32[2]{0}, u32[], u32[]) "
        "collective-permute-start(%c)",
        "  %d = f32[2]{0} collective-permute-done(%s)",
        "  %g = (f64[8,2]{1,0}) all-gather(%y), dimensions={0}",
        "  %c0 = f64[9]{0} copy(%x)",
        "  %c1 = (f64[9]{0}, u32[]) copy-start(%x)",
        "  %c2 = f64[9]{0} copy-done(%c1)",
        "}",
    ])
    rep = analyze_text(txt)
    assert rep.dialect == "hlo"
    assert rep.collectives["collective_permute"] == 3  # p0, p1, start
    assert rep.collectives["all_gather"] == 1
    assert rep.collective_bytes["collective_permute"] >= 9 * 8 + 2 * 3 * 8
    assert rep.collective_bytes["all_gather"] == 8 * 2 * 8
    assert rep.copies == 2  # c0 + the start/done pair counted once


def test_collective_counts_pins_legacy_regex_semantics():
    """The migration contract: `analysis.collective_counts` must
    reproduce EXACTLY the numbers the three deleted per-file helpers
    (`len(re.findall(kind, text))` over the lowered text) pinned before
    the refactor — including the quirk that attribute mentions count
    (``all_gather_dim`` makes each StableHLO gather count twice)."""
    for name in ("cg_4part_f64.stablehlo.txt", "cg_4part_f32.stablehlo.txt"):
        txt = _fix(name)
        legacy = {
            k: len(re.findall(k, txt))
            for k in ("collective_permute", "all_gather", "all_reduce")
        }
        assert collective_counts(txt) == legacy
        # and the quirk is real: op sites != raw hits for all_gather
        rep = analyze_text(txt)
        assert legacy["all_gather"] == 2 * rep.collectives["all_gather"]


def test_no_private_collective_counts_definitions_remain():
    """The dedup satellite's acceptance: zero private helper
    definitions in the three migrated files (they import the shared
    one)."""
    for rel in ("test_fused_cg.py", "test_block_cg.py", "test_abft.py"):
        with open(os.path.join(REPO, "tests", rel), encoding="utf-8") as f:
            src = f.read()
        assert "def _collective_counts" not in src, rel
        assert "partitionedarrays_jl_tpu.analysis" in src, rel


# ---------------------------------------------------------------------------
# negative tests: the contracts catch seeded regressions
# ---------------------------------------------------------------------------


def test_dtype_closure_catches_injected_f64():
    """Seed the PR 3 poisoning class into the f32 fixture: one f64
    convert op anywhere in the program must trip dtype-closure."""
    clean = _fix("cg_4part_f32.stablehlo.txt")
    cases = {"probe_f32": {"name": "probe_f32", "tags": {"staged": "f32"}}}
    ok = check_contracts({"probe_f32": analyze_text(clean)}, cases)
    assert not [v for v in ok if v.contract == "dtype-closure"]
    poisoned = clean.replace(
        "func.func public @main",
        '  %poison = stablehlo.convert %arg0 : (tensor<4x46xf32>) -> '
        "tensor<4x46xf64>\n  func.func public @main",
        1,
    )
    rep = analyze_text(poisoned)
    assert "f64" in rep.float_dtypes
    bad = check_contracts({"probe_f32": rep}, cases)
    hits = [v for v in bad if v.contract == "dtype-closure"]
    assert hits, "dtype-closure did not catch the injected f64 op"
    assert "PR 3" in hits[0].message


def test_copy_budget_catches_copy_growth(monkeypatch):
    """Seed the PR 2 anomaly class: a compiled report whose copy count
    exceeds its body's budget must trip copy-budget; at the budget it
    must not."""
    rep = analyze_text(_fix("cg_4part_f64.hlo.txt"))  # copies == 17
    cases = {"probe": {"name": "probe", "tags": {"body": "standard"}}}
    monkeypatch.setitem(COPY_BUDGETS, "probe", 16)
    bad = check_contracts({"probe__compiled": rep}, cases)
    assert [v for v in bad if v.contract == "copy-budget"]
    monkeypatch.setitem(COPY_BUDGETS, "probe", 17)
    ok = check_contracts({"probe__compiled": rep}, cases)
    assert not [v for v in ok if v.contract == "copy-budget"]


def test_loop_residency_catches_injected_infeed():
    """An infeed smuggled INTO the while region must trip
    no-host-transfer-in-loop; the clean fixture must not."""
    clean = _fix("cg_4part_f64.stablehlo.txt")
    cases = {"probe": {"name": "probe", "tags": {}}}
    ok = check_contracts({"probe": analyze_text(clean)}, cases)
    assert not [v for v in ok if v.contract == "no-host-transfer-in-loop"]
    m = re.search(r"^(.*stablehlo\.while.*)$", clean, re.M)
    assert m, "fixture lost its while loop"
    doctored = clean.replace(
        m.group(1),
        m.group(1) + '\n      %hx = "stablehlo.infeed"(%arg0) : '
        "(tensor<4x46xf64>) -> tensor<4x46xf64>",
        1,
    )
    bad = check_contracts({"probe": analyze_text(doctored)}, cases)
    assert [v for v in bad if v.contract == "no-host-transfer-in-loop"]


def test_sanity_contract_guards_parser_rot():
    """If the analyzer stops seeing collectives, the equality contracts
    would pass vacuously — the sanity contract must fail instead."""
    rep = analyze_text("func.func public @main() {\n}\n")
    cases = {"standard": {"name": "standard", "tags": {"body": "standard"}}}
    bad = check_contracts({"standard": rep}, cases)
    assert [v for v in bad if v.contract == "sanity"]


# ---------------------------------------------------------------------------
# env-key lint: the gate, its pinned classification, and its teeth
# ---------------------------------------------------------------------------

#: The pinned clean state (ISSUE 5 satellite): exactly these flags
#: alter tracing/lowering today. A NEW flag landing in either direction
#: fails this test until a human (a) keys it or exempts it with a
#: reason, and (b) updates this fixture + docs/api.md.
EXPECTED_LOWERING_FLAGS = {
    "PA_FAULT_DEVICE",
    "PA_HEALTH_AUDIT_EVERY",
    "PA_HEALTH_AUDIT_TOL",
    "PA_HEALTH_MAX_ROLLBACKS",
    "PA_HEALTH_ROLLBACK_DEPTH",
    "PA_TPU_ABFT",
    "PA_TPU_ABFT_TOL",
    "PA_TPU_BOX",
    "PA_TPU_BSR",
    "PA_TPU_CLASS_ACC",
    "PA_TPU_COMMS_MATRIX",
    "PA_TPU_ELL_GUARD",
    "PA_TPU_ELL_MAX_GATHER",
    "PA_TPU_FUSED_CG",
    "PA_TPU_GMG_BOX",
    "PA_TPU_GMG_STENCIL",
    "PA_TPU_NODE_MAP",
    "PA_TPU_OH_BUCKETS",
    "PA_TPU_OVERLAP",
    "PA_TPU_SD",
    "PA_TPU_SSTEP",
    "PA_TPU_STRICT_BITS",
    "PA_TPU_TWOLEVEL",
    "PA_TRACE_ITERS",
}


def test_env_lint_green():
    """The acceptance gate: every lowering-affecting PA_* read is
    key-covered AND the docs/api.md env table agrees with the source
    inventory in both directions."""
    violations = lint_env_keys()
    assert not violations, "\n".join(str(v) for v in violations)


def test_env_lint_classification_pinned():
    cls = classify()
    lowering = {n for n, e in cls.items() if e["class"] == "lowering"}
    assert lowering == EXPECTED_LOWERING_FLAGS, (
        "lowering-affecting flag set drifted — if you added a flag, key "
        "it (or exempt it with a reason in analysis.env_lint."
        "NON_LOWERING), document it in docs/api.md, and update this "
        f"fixture. diff: +{lowering - EXPECTED_LOWERING_FLAGS} "
        f"-{EXPECTED_LOWERING_FLAGS - lowering}"
    )
    # every exemption names a real read and carries a reason
    for name, reason in env_lint.NON_LOWERING.items():
        assert name in cls, f"stale exemption {name}"
        assert len(reason) > 20, f"exemption {name} needs a real reason"


def test_key_coverage_resolves_through_helpers():
    """The coverage closure must see THROUGH the one-helper-per-mode
    indirections: strict_bits() lives in utils.helpers, abft_enabled()
    in parallel.health, the GMG resolutions in tpu_gmg — all reached
    from the three registered key sites."""
    cov = key_coverage()
    assert cov["PA_TPU_STRICT_BITS"] == "_lowering_env_key"
    assert cov["PA_TPU_ABFT"] == "_lowering_env_key"
    assert cov["PA_TPU_GMG_BOX"] == "_gmg_env_key"
    assert cov["PA_HEALTH_AUDIT_EVERY"] == "_sdc_config"
    assert cov["PA_FAULT_DEVICE"] == "_sdc_config"
    assert cov["PA_TRACE_ITERS"] == "_trace_config"
    assert EXPECTED_LOWERING_FLAGS <= set(cov)


def test_env_lint_catches_unkeyed_flag(tmp_path):
    """The lint's teeth, proven on a synthetic package: a PA_* read
    inside a staging root with NO key site covering it must be flagged;
    adding it to the key site clears it."""
    pkg = tmp_path / "fakepkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "import os\n\n"
        "def make_cg_fn():\n"
        "    return os.environ.get('PA_TPU_FAKEMODE', '0')\n\n"
        "def _lowering_env_key():\n"
        "    return ()\n"
    )
    violations = lint_env_keys(root=str(pkg), check_docs=False)
    assert any("PA_TPU_FAKEMODE" in v for v in violations), violations
    (pkg / "mod.py").write_text(
        "import os\n\n"
        "def make_cg_fn():\n"
        "    return os.environ.get('PA_TPU_FAKEMODE', '0')\n\n"
        "def _lowering_env_key():\n"
        "    return (os.environ.get('PA_TPU_FAKEMODE', '0'),)\n"
    )
    violations = lint_env_keys(root=str(pkg), check_docs=False)
    assert not any("PA_TPU_FAKEMODE" in v for v in violations), violations


def test_key_coverage_not_fooled_by_name_collision(tmp_path):
    """Coverage must be module-qualified: the key site calls its own
    local helper; an UNRELATED module defines a same-named helper that
    reads a PA_* flag consumed by a staging root. A name-only closure
    unions the two definitions, marks the flag key-covered, and the
    lint passes green on exactly the stale-cache bug class it exists to
    catch — the module-qualified closure must flag it instead."""
    pkg = tmp_path / "fakepkg"
    pkg.mkdir()
    (pkg / "keys.py").write_text(
        "import os\n\n"
        "def _resolve():\n"
        "    return ()\n\n\n"
        "def _lowering_env_key():\n"
        "    return _resolve()\n"
    )
    (pkg / "other.py").write_text(
        "import os\n\n"
        "def _resolve():\n"
        "    return os.environ.get('PA_TPU_NEWMODE', '0')\n\n\n"
        "def make_cg_fn():\n"
        "    return _resolve()\n"
    )
    assert "PA_TPU_NEWMODE" not in key_coverage(root=str(pkg))
    violations = lint_env_keys(root=str(pkg), check_docs=False)
    assert any("PA_TPU_NEWMODE" in v for v in violations), violations
    # a key site that genuinely IMPORTS a helper (no local definition)
    # still resolves it cross-module — coverage survives the tightening
    (pkg / "keys.py").write_text(
        "import os\n\n"
        "from .other import _resolve\n\n\n"
        "def _lowering_env_key():\n"
        "    return _resolve()\n"
    )
    assert key_coverage(root=str(pkg)).get("PA_TPU_NEWMODE") == (
        "_lowering_env_key"
    )
    violations = lint_env_keys(root=str(pkg), check_docs=False)
    assert not any("PA_TPU_NEWMODE" in v for v in violations), violations


def test_env_lint_sees_method_and_module_level_reads(tmp_path):
    """The two attribution blind spots a name-only scanner has, both
    closed: (a) a read inside a METHOD that a staging root reaches only
    through an attribute call (`planner.pick_mode()` — the class name
    never appears in the call chain), and (b) a MODULE-LEVEL read
    consumed by a staging root (import-time freeze: no later cache key
    can see a flip, the staleness hazard itself)."""
    pkg = tmp_path / "fakepkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "import os\n\n"
        "_MODLEVEL = os.environ.get('PA_TPU_MODLEVEL', '0')\n\n\n"
        "class Planner:\n"
        "    def pick_mode(self):\n"
        "        return os.environ.get('PA_TPU_METHODMODE', '0')\n\n\n"
        "def make_cg_fn(planner):\n"
        "    return planner.pick_mode(), _MODLEVEL\n\n\n"
        "def _lowering_env_key():\n"
        "    return ()\n"
    )
    violations = lint_env_keys(root=str(pkg), check_docs=False)
    assert any("PA_TPU_METHODMODE" in v for v in violations), violations
    assert any("PA_TPU_MODLEVEL" in v for v in violations), violations


# ---------------------------------------------------------------------------
# the ELL-guard env-key fold (the lint's first real finding) — rekey pin
# ---------------------------------------------------------------------------


def test_ell_guard_envs_rekey_the_lowering(monkeypatch):
    from partitionedarrays_jl_tpu.parallel.tpu import _lowering_env_key

    monkeypatch.delenv("PA_TPU_ELL_MAX_GATHER", raising=False)
    monkeypatch.delenv("PA_TPU_ELL_GUARD", raising=False)
    k0 = _lowering_env_key()
    # NORMALIZED resolution (one helper for guard site and key site):
    # spelling the default explicitly must NOT spuriously rekey
    monkeypatch.setenv("PA_TPU_ELL_MAX_GATHER", "25000000")
    assert _lowering_env_key() == k0
    monkeypatch.setenv("PA_TPU_ELL_MAX_GATHER", "2.5e7")
    assert _lowering_env_key() == k0
    monkeypatch.setenv("PA_TPU_ELL_MAX_GATHER", "123456")
    k1 = _lowering_env_key()
    assert k1 != k0
    monkeypatch.setenv("PA_TPU_ELL_GUARD", "0")
    assert _lowering_env_key() not in (k0, k1)


def test_ell_guard_env_inf_takes_the_graceful_path(monkeypatch):
    """``PA_TPU_ELL_MAX_GATHER=inf`` parses as a float but overflows
    ``int()`` — it must take the same raw-string path as junk (key on
    the spelling, never crash `_lowering_env_key`), and only the ACTIVE
    guard site turns it into an error."""
    from partitionedarrays_jl_tpu.parallel.tpu import (
        _ell_guard_check,
        _ell_guard_env,
        _lowering_env_key,
    )

    monkeypatch.setenv("PA_TPU_ELL_MAX_GATHER", "inf")
    monkeypatch.setenv("PA_TPU_ELL_GUARD", "0")
    assert _ell_guard_env() == ("0", "inf")
    _lowering_env_key()  # must not raise with the guard disabled
    _ell_guard_check(4, 10**9, 10**9, None)  # disabled guard: ignored
    monkeypatch.setenv("PA_TPU_ELL_GUARD", "1")
    with pytest.raises(ValueError, match="PA_TPU_ELL_MAX_GATHER"):
        _ell_guard_check(4, 10, 10, None)


def test_ell_guard_flip_reruns_staging_admission(monkeypatch):
    """The regression the fold closes: stage an ELL matrix under a
    raised footprint ceiling, then drop the ceiling — `device_matrix`
    must RE-RUN admission and refuse, not serve the cached lowering
    staged under the laxer rule."""
    import jax

    import partitionedarrays_jl_tpu as pa
    from partitionedarrays_jl_tpu.models import assemble_poisson
    from partitionedarrays_jl_tpu.parallel.tpu import (
        ELLFootprintError,
        TPUBackend,
        device_matrix,
    )

    # strict-bits forces the pure-ELL lowering; guard=1 enforces on the
    # host mesh too (it only warns there by default)
    monkeypatch.setenv("PA_TPU_STRICT_BITS", "1")
    monkeypatch.setenv("PA_TPU_ELL_GUARD", "1")
    monkeypatch.setenv("PA_TPU_ELL_MAX_GATHER", "1000000")
    backend = TPUBackend(devices=jax.devices()[:4])

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (6, 6))
        return A

    A = pa.prun(driver, backend, (2, 2))
    dA = device_matrix(A, backend)  # stages fine under the high ceiling
    assert dA is device_matrix(A, backend)  # cached while env unchanged
    monkeypatch.setenv("PA_TPU_ELL_MAX_GATHER", "1")
    with pytest.raises(ELLFootprintError):
        device_matrix(A, backend)
    # restoring the ceiling serves the original staged lowering again
    monkeypatch.setenv("PA_TPU_ELL_MAX_GATHER", "1000000")
    assert device_matrix(A, backend) is dA


# ---------------------------------------------------------------------------
# the contract matrix (fast subset in tier-1; full matrix is slow)
# ---------------------------------------------------------------------------


def test_matrix_base_env_pins_every_lowering_flag():
    """The matrix's base env must pin DOWN exactly the flags the lint
    classifies as lowering-affecting — otherwise an ambient shell
    export (`PA_TPU_CLASS_ACC=0`, a raised rollback depth, ...) makes
    every case lower a different program than the one the contracts and
    copy budgets were pinned against."""
    from partitionedarrays_jl_tpu.parallel.tpu import _MATRIX_BASE_ENV

    assert set(_MATRIX_BASE_ENV) == EXPECTED_LOWERING_FLAGS, (
        f"+{set(_MATRIX_BASE_ENV) - EXPECTED_LOWERING_FLAGS} "
        f"-{EXPECTED_LOWERING_FLAGS - set(_MATRIX_BASE_ENV)}"
    )


def test_lowering_matrix_enumerator_well_formed():
    from partitionedarrays_jl_tpu.parallel.tpu import lowering_matrix

    full = lowering_matrix(fast=False)
    fast = lowering_matrix(fast=True)
    names = [c["name"] for c in full]
    assert len(names) == len(set(names))
    assert {c["name"] for c in fast} <= set(names)
    by_name = {c["name"]: c for c in full}
    for c in full:
        off = c["tags"].get("abft_off")
        if off:
            assert off in by_name, (c["name"], off)
            assert "abft" not in by_name[off]["tags"]
        if c["tags"].get("body") == "block":
            assert c["tags"].get("block_of") in by_name
    # the dtype-closure probes are part of the FAST subset — the PR 3
    # class must be caught by every CI run, not just the slow leg
    assert any(c["tags"].get("staged") == "f32" for c in fast)


def _run_matrix(fast, with_runtime=False):
    import jax

    from partitionedarrays_jl_tpu.analysis import build_reports
    from partitionedarrays_jl_tpu.analysis import check_contracts as check
    from partitionedarrays_jl_tpu.parallel.tpu import TPUBackend

    backend = TPUBackend(devices=jax.devices()[:8])
    cases, reports = build_reports(
        backend, fast=fast, with_compiled=True, with_runtime=with_runtime,
        with_plans=True, with_memory=True,
    )
    violations = check(reports, cases)
    assert not violations, "\n".join(str(v) for v in violations)
    # the matrix really lowered: baseline cases present with inventories
    assert reports["standard"].collective_count_total > 0
    assert reports["standard__compiled"].copies <= COPY_BUDGETS["standard"]
    return cases, reports


def test_fast_matrix_contracts_hold():
    """Tier-1: the fast subset of the lowering matrix honors every
    contract (standard/fused/block-K1/K4, the ABFT parity pair, the f32
    dtype-closure probe, both compiled copy-budget legs, the per-case
    plan-soundness audits, and the static memory budgets)."""
    cases, reports = _run_matrix(fast=True)
    # dtype-closure's compiled leg is live, not dead code: the f32-
    # staged probe gets a compiled-HLO report too, so an f64 op XLA
    # introduces only during compilation would still trip the contract
    assert "standard_f32__compiled" in reports
    assert "f64" not in reports["standard_f32__compiled"].float_dtypes
    # the plan audits are live: default-env cases verified the BOX
    # plan, the nobox/ABFT cases the GENERIC plan, the node-aware case
    # its TWO-LEVEL schedule, all with zero defects and the host
    # exchanger alongside
    kinds = {cases[n]["plan_audit"]["kind"] for n in cases}
    assert kinds == {"device-box", "device-generic", "device-twolevel"}
    assert cases["twolevel"]["plan_audit"]["kind"] == "device-twolevel"
    for n in cases:
        audit = cases[n]["plan_audit"]
        assert audit["n_defects"] == 0, (n, audit)
        assert "host-exchanger" in audit["plans"]
    # the memory footprints are live, and the compiled cases' peaks
    # really came from the XLA buffer assignment
    for n in ("standard", "fused", "standard_f32"):
        assert cases[n]["memory"]["peak_source"] == "hlo-buffer-assignment"
    assert cases["standard_nobox"]["memory"]["peak_source"] == "shape-sum"
    # and the committed artifact matches what this build measured for
    # the deterministic shape-sum fields (regenerate with
    # tools/palint.py --write-memory when a lowering legitimately
    # changes its footprint)
    import json

    committed = json.load(
        open(os.path.join(REPO, "MEMORY_FOOTPRINT.json"))
    )["cases"]
    for n in cases:
        fp = cases[n]["memory"]
        assert committed[n]["carry_bytes"] == fp["carry_bytes"], n
        assert committed[n]["plan_bytes"] == fp["plan_bytes"], n
        assert committed[n]["operand_bytes"] == fp["operand_bytes"], n


@pytest.mark.slow
def test_full_matrix_contracts_hold():
    """The full matrix `tools/palint.py --check` gates on (adds both
    block bodies, the nobox/ABFT fused pairs, strict-bits, fused f32).
    ``with_runtime`` probe-solves every case so the
    static-measured-reconciliation contract (the patrace tentpole's
    acceptance criterion) is checked across ALL 15 cases — the fast
    probe legs live in tests/test_telemetry.py. Plan audits and memory
    budgets ride along over the full case set."""
    cases, reports = _run_matrix(fast=False, with_runtime=True)
    assert "strict_standard" in reports
    assert all(c["plan_audit"]["n_defects"] == 0 for c in cases.values())


# ---------------------------------------------------------------------------
# negative tests: the two paplan contracts catch seeded regressions
# (verifier-level negatives live in tests/test_plan_verifier.py)
# ---------------------------------------------------------------------------


def test_plan_soundness_contract_catches_seeded_audit_defect():
    """A case whose plan audit reports ANY defect must trip the
    plan-soundness contract; a clean audit must not."""
    clean = {"name": "probe", "tags": {}, "plan_audit": {
        "kind": "device-box",
        "plans": {"host-exchanger": [], "device-box": []},
        "n_defects": 0,
    }}
    ok = check_contracts({}, {"probe": clean})
    assert not [v for v in ok if v.contract == "plan-soundness"]
    seeded = {"name": "probe", "tags": {}, "plan_audit": {
        "kind": "device-box",
        "plans": {"host-exchanger": [], "device-box": [{
            "check": "ghost-race", "plan": "device-box", "part": 2,
            "message": "overlapping segment slot", "details": {},
        }]},
        "n_defects": 1,
    }}
    bad = check_contracts({}, {"probe": seeded})
    hits = [v for v in bad if v.contract == "plan-soundness"]
    assert hits and "ghost-race" in hits[0].message


def test_memory_budget_contract_catches_growth_and_missing_budget(
    monkeypatch,
):
    """A footprint past its pinned budget must trip memory-budget; at
    the budget it must not; and a matrix case with NO pinned budget
    fails loudly (the new-case discipline)."""
    from partitionedarrays_jl_tpu.analysis.memory_report import (
        MEMORY_BUDGETS,
    )

    fp = {"carry_bytes": 100, "plan_bytes": 10, "operand_bytes": 300,
          "peak_bytes": 500, "peak_source": "shape-sum"}
    case = {"name": "probe", "tags": {}, "memory": dict(fp)}
    monkeypatch.setitem(MEMORY_BUDGETS, "probe", 499)
    bad = check_contracts({}, {"probe": case})
    assert [v for v in bad if v.contract == "memory-budget"]
    monkeypatch.setitem(MEMORY_BUDGETS, "probe", 500)
    ok = check_contracts({}, {"probe": case})
    assert not [v for v in ok if v.contract == "memory-budget"]
    unbudgeted = {"name": "newcase", "tags": {}, "memory": dict(fp)}
    bad = check_contracts({}, {"newcase": unbudgeted})
    hits = [v for v in bad if v.contract == "memory-budget"]
    assert hits and "no pinned" in hits[0].message


# ---------------------------------------------------------------------------
# the CLI gate
# ---------------------------------------------------------------------------


def test_palint_cli_lint_only_green():
    # lint-only leg stays jax-free and fast; the plan-soundness leg's
    # CLI path is exercised in-process by tests/test_plan_verifier.py
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "palint.py"),
         "--check", "--skip-matrix", "--skip-plans"],
        capture_output=True, text=True, timeout=240,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "palint: OK" in out.stdout


def test_palint_cli_exits_nonzero_on_violation(monkeypatch):
    """--check must exit nonzero and print the human-readable diff when
    a contract/lint violation exists (seeded: a stale exemption)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "palint", os.path.join(REPO, "tools", "palint.py")
    )
    palint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(palint)
    monkeypatch.setitem(
        env_lint.NON_LOWERING, "PA_TPU_NEVER_READ",
        "a stale exemption the lint must flag as no longer read",
    )
    rc = palint.main(["--check", "--skip-matrix", "--skip-plans"])
    assert rc == 1
