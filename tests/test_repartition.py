"""In-memory redistribution: PVector/PSparseMatrix migrate onto a
different partition scalably (no gather-to-MAIN), and the redistributed
system solves to the same solution."""
import numpy as np

import partitionedarrays_jl_tpu as pa


def test_repartition_roundtrip_and_solve():
    def driver(parts):
        A, b, xe, x0 = pa.assemble_poisson(parts, (8, 8))
        new_rows = pa.prange(parts, 64)  # 1-D blocks vs the Cartesian rows
        A2 = pa.repartition_psparse(A, new_rows)
        b2 = pa.repartition_pvector(b, A2.cols)
        x02 = pa.repartition_pvector(x0, A2.cols)
        np.testing.assert_array_equal(
            pa.gather_psparse(A2).toarray(), pa.gather_psparse(A).toarray()
        )
        np.testing.assert_array_equal(
            pa.gather_pvector(b2), pa.gather_pvector(b)
        )
        x2, info = pa.cg(A2, b2, x0=x02, tol=1e-12, maxiter=500)
        assert info["converged"]
        err = np.abs(pa.gather_pvector(x2) - pa.gather_pvector(xe)).max()
        assert err < 1e-8
        return True

    assert pa.prun(driver, pa.sequential, (3, 2))


def test_repartition_vector_ghosts_filled():
    """The redistributed vector's ghost layer is exchanged, so it is
    immediately SpMV-ready over the new partition."""

    def driver(parts):
        rows = pa.cartesian_partition(parts, (6, 6), pa.with_ghost)
        v = pa.PVector(
            pa.map_parts(
                lambda i: np.where(
                    np.asarray(i.lid_to_part) == i.part,
                    10.0 + np.asarray(i.lid_to_gid, float),
                    -1.0,
                ),
                rows.partition,
            ),
            rows,
        )
        pa.exchange_pvector(v)
        new_rows = pa.cartesian_partition(parts, (6, 6), pa.with_ghost)
        w = pa.repartition_pvector(v, new_rows)
        for iset, vals in zip(
            new_rows.partition.part_values(), w.values.part_values()
        ):
            np.testing.assert_array_equal(
                np.asarray(vals), 10.0 + np.asarray(iset.lid_to_gid)
            )
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_repartition_size_mismatch_rejected():
    import pytest

    def driver(parts):
        A, b, xe, x0 = pa.assemble_poisson(parts, (4, 4))
        with pytest.raises(AssertionError):
            pa.repartition_psparse(A, pa.prange(parts, 17))
        with pytest.raises(AssertionError):
            pa.repartition_pvector(b, pa.prange(parts, 17))
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_unassembled_ghost_rows_rejected():
    """Nonzero unassembled ghost-row contributions must be rejected, not
    silently dropped (same contract as the checkpoint serializer)."""
    import pytest

    def driver(parts):
        rows0 = pa.prange(parts, 8)
        # every part also contributes to the row AFTER its block: ghosted
        # rows with genuinely unassembled values
        def coo(i):
            g = np.asarray(i.oid_to_gid)
            extra = np.array([(int(g[-1]) + 1) % 8])
            return (
                np.concatenate([g, extra]),
                np.concatenate([g, extra]),
                np.ones(len(g) + 1),
            )

        c = pa.map_parts(coo, rows0.partition)
        I = pa.map_parts(lambda t: t[0], c)
        J = pa.map_parts(lambda t: t[1], c)
        V = pa.map_parts(lambda t: t[2], c)
        rows = pa.add_gids(rows0, I)
        A = pa.PSparseMatrix.from_coo(I, J, V, rows, rows, ids="global")
        with pytest.raises(AssertionError):
            pa.repartition_psparse(A, pa.prange(parts, 8))
        return True

    assert pa.prun(driver, pa.sequential, 4)
