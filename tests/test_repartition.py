"""In-memory redistribution: PVector/PSparseMatrix migrate onto a
different partition scalably (no gather-to-MAIN), and the redistributed
system solves to the same solution."""
import numpy as np

import partitionedarrays_jl_tpu as pa


def test_repartition_roundtrip_and_solve():
    def driver(parts):
        A, b, xe, x0 = pa.assemble_poisson(parts, (8, 8))
        new_rows = pa.prange(parts, 64)  # 1-D blocks vs the Cartesian rows
        A2 = pa.repartition_psparse(A, new_rows)
        b2 = pa.repartition_pvector(b, A2.cols)
        x02 = pa.repartition_pvector(x0, A2.cols)
        np.testing.assert_array_equal(
            pa.gather_psparse(A2).toarray(), pa.gather_psparse(A).toarray()
        )
        np.testing.assert_array_equal(
            pa.gather_pvector(b2), pa.gather_pvector(b)
        )
        x2, info = pa.cg(A2, b2, x0=x02, tol=1e-12, maxiter=500)
        assert info["converged"]
        err = np.abs(pa.gather_pvector(x2) - pa.gather_pvector(xe)).max()
        assert err < 1e-8
        return True

    assert pa.prun(driver, pa.sequential, (3, 2))


def test_repartition_vector_ghosts_filled():
    """The redistributed vector's ghost layer is exchanged, so it is
    immediately SpMV-ready over the new partition."""

    def driver(parts):
        rows = pa.cartesian_partition(parts, (6, 6), pa.with_ghost)
        v = pa.PVector(
            pa.map_parts(
                lambda i: np.where(
                    np.asarray(i.lid_to_part) == i.part,
                    10.0 + np.asarray(i.lid_to_gid, float),
                    -1.0,
                ),
                rows.partition,
            ),
            rows,
        )
        pa.exchange_pvector(v)
        new_rows = pa.cartesian_partition(parts, (6, 6), pa.with_ghost)
        w = pa.repartition_pvector(v, new_rows)
        for iset, vals in zip(
            new_rows.partition.part_values(), w.values.part_values()
        ):
            np.testing.assert_array_equal(
                np.asarray(vals), 10.0 + np.asarray(iset.lid_to_gid)
            )
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_repartition_size_mismatch_rejected():
    import pytest

    def driver(parts):
        A, b, xe, x0 = pa.assemble_poisson(parts, (4, 4))
        with pytest.raises(AssertionError):
            pa.repartition_psparse(A, pa.prange(parts, 17))
        with pytest.raises(AssertionError):
            pa.repartition_pvector(b, pa.prange(parts, 17))
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_unassembled_ghost_rows_rejected():
    """Nonzero unassembled ghost-row contributions must be rejected, not
    silently dropped (same contract as the checkpoint serializer)."""
    import pytest

    def driver(parts):
        rows0 = pa.prange(parts, 8)
        # every part also contributes to the row AFTER its block: ghosted
        # rows with genuinely unassembled values
        def coo(i):
            g = np.asarray(i.oid_to_gid)
            extra = np.array([(int(g[-1]) + 1) % 8])
            return (
                np.concatenate([g, extra]),
                np.concatenate([g, extra]),
                np.ones(len(g) + 1),
            )

        c = pa.map_parts(coo, rows0.partition)
        I = pa.map_parts(lambda t: t[0], c)
        J = pa.map_parts(lambda t: t[1], c)
        V = pa.map_parts(lambda t: t[2], c)
        rows = pa.add_gids(rows0, I)
        A = pa.PSparseMatrix.from_coo(I, J, V, rows, rows, ids="global")
        with pytest.raises(AssertionError):
            pa.repartition_psparse(A, pa.prange(parts, 8))
        return True

    assert pa.prun(driver, pa.sequential, 4)


def test_repartition_cross_part_count_roundtrip():
    """The P -> P' path (elastic shrink/grow): owned data owner-splits
    gid-keyed onto an arbitrary new part count and back — the operator
    and vector survive an 8 -> 6 -> 8 cycle bitwise, and the shrunken
    system solves to the same solution."""

    def driver(parts):
        A, b, xe, x0 = pa.assemble_poisson(parts, (8, 8))
        rows6 = pa.survivor_rows(A.rows, shape=(3, 2))
        A6 = pa.repartition_psparse(A, rows6)
        b6 = pa.repartition_pvector(b, A6.rows)
        assert A6.rows.partition.num_parts == 6
        np.testing.assert_array_equal(
            pa.gather_psparse(A6).toarray(), pa.gather_psparse(A).toarray()
        )
        np.testing.assert_array_equal(
            pa.gather_pvector(b6), pa.gather_pvector(b)
        )
        x6, info = pa.cg(
            A6, b6, x0=pa.repartition_pvector(x0, A6.cols), tol=1e-9
        )
        assert info["converged"]
        assert (
            np.abs(pa.gather_pvector(x6) - pa.gather_pvector(xe)).max()
            < 1e-6
        )
        # and back up to the original 8-part partition, bitwise
        A8 = pa.repartition_psparse(A6, A.rows)
        np.testing.assert_array_equal(
            pa.gather_psparse(A8).toarray(), pa.gather_psparse(A).toarray()
        )
        b8 = pa.repartition_pvector(b6, b.rows)
        np.testing.assert_array_equal(
            pa.gather_pvector(b8), pa.gather_pvector(b)
        )
        return True

    assert pa.prun(driver, pa.sequential, (4, 2))


def test_repartition_empty_owned_part_keeps_dtype():
    """The PR 3 f64-poisoning class in the repartition `_fill`: a part
    owning ZERO rows migrates an empty array, and deriving the output
    dtype from it would silently promote f32 to f64. The dtype is
    threaded from the SOURCE vector/matrix on both routing paths."""

    def driver(parts):
        # 4 gids over 6 parts: parts 4 and 5 own nothing
        rows = pa.prange(parts, 4)
        assert any(
            i.num_lids == 0 for i in rows.partition.part_values()
        )
        v = pa.PVector(
            pa.map_parts(
                lambda i: np.asarray(i.oid_to_gid, np.float32) + 1.0,
                rows.partition,
            ),
            rows,
        )
        assert v.dtype == np.float32
        # cross-count: onto 2 parts and back onto the empty-part layout
        rows2 = pa.survivor_rows(rows, shape=(2,))
        w = pa.repartition_pvector(v, rows2)
        assert all(
            np.asarray(p).dtype == np.float32
            for p in w.values.part_values()
        )
        u = pa.repartition_pvector(w, rows)
        assert all(
            np.asarray(p).dtype == np.float32
            for p in u.values.part_values()
        )
        np.testing.assert_array_equal(
            pa.gather_pvector(u), pa.gather_pvector(v)
        )
        # same-count path (1-D blocks vs 1-D blocks is an identity
        # route, but it still exercises the exchanger _fill)
        rows_same = pa.prange(parts, 4)
        s = pa.repartition_pvector(v, rows_same)
        assert all(
            np.asarray(p).dtype == np.float32
            for p in s.values.part_values()
        )
        # matrices thread A.dtype the same way
        I = pa.map_parts(
            lambda i: np.asarray(i.oid_to_gid, np.int64), rows.partition
        )
        V = pa.map_parts(
            lambda g: np.ones(len(g), np.float32), I
        )
        A = pa.assemble_matrix_from_coo(I, I, V, rows)
        A2 = pa.repartition_psparse(A, rows2)
        assert A2.dtype == np.float32
        return True

    assert pa.prun(driver, pa.sequential, (3, 2))
