"""L1 tests: backend protocol, prun, map_parts on the sequential backend.

Behavior mirrors reference src/Interfaces.jl:12-124 and
src/SequentialBackend.jl, 0-based.
"""
import numpy as np
import pytest

from partitionedarrays_jl_tpu import (
    MAIN,
    SequentialData,
    get_backend,
    get_main_part,
    get_part,
    get_part_ids,
    i_am_main,
    map_main,
    map_parts,
    num_parts,
    prun,
    prun_debug,
    sequential,
    unzip,
)


def test_prun_linear():
    out = {}

    def driver(parts):
        out["n"] = num_parts(parts)
        out["vals"] = list(parts)
        return "done"

    assert prun(driver, sequential, 4) == "done"
    assert out["n"] == 4
    assert out["vals"] == [0, 1, 2, 3]


def test_prun_cartesian_grid():
    def driver(parts):
        assert parts.shape == (2, 2)
        assert list(parts) == [0, 1, 2, 3]
        return True

    assert prun(driver, sequential, (2, 2))
    assert prun_debug(driver, sequential, (2, 2))


def test_map_parts_and_broadcast():
    parts = sequential.get_part_ids(3)
    squares = map_parts(lambda p: p * p, parts)
    assert list(squares) == [0, 1, 4]
    shifted = map_parts(lambda p, s: p + s, parts, 10)  # non-PData broadcast
    assert list(shifted) == [10, 11, 12]
    both = map_parts(lambda a, b: a + b, squares, shifted)
    assert list(both) == [10, 12, 16]


def test_map_parts_mismatched_counts():
    a = sequential.get_part_ids(3)
    b = sequential.get_part_ids(4)
    with pytest.raises(AssertionError):
        map_parts(lambda x, y: x + y, a, b)


def test_get_part_and_main():
    parts = sequential.get_part_ids(4)
    vals = map_parts(lambda p: p * 100, parts)
    assert get_part(vals, 2) == 200
    assert get_main_part(vals) == 0
    with pytest.raises(AssertionError):
        get_part(vals)  # no local part in a 4-part sequential run
    single = sequential.get_part_ids(1)
    assert get_part(single) == 0


def test_i_am_main_and_map_main():
    parts = sequential.get_part_ids(3)
    assert i_am_main(parts)
    r = map_main(lambda p: p + 42, parts)
    assert list(r) == [42, None, None]


def test_get_part_ids_from_pdata_and_backend():
    parts = sequential.get_part_ids((2, 3))
    again = get_part_ids(parts)
    assert again.shape == (2, 3)
    assert list(again) == list(range(6))
    direct = get_part_ids(sequential, 2)
    assert list(direct) == [0, 1]
    assert get_backend(parts) is sequential
    assert MAIN == 0


def test_unzip():
    parts = sequential.get_part_ids(3)
    pairs = map_parts(lambda p: (p, p * 2), parts)
    a, b = unzip(pairs, 2)
    assert list(a) == [0, 1, 2]
    assert list(b) == [0, 2, 4]


def test_map_parts_with_numpy_chunks():
    parts = sequential.get_part_ids(2)
    chunks = map_parts(lambda p: np.arange(3) + 10 * p, parts)
    doubled = map_parts(lambda c: c * 2, chunks)
    assert list(doubled.get_part(1)) == [20, 22, 24]
