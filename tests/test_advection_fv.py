"""FV upwind advection-diffusion: the nonsymmetric model driver, solved
with BiCGStab on both backends (reference domain: FD/FV/FE — README.md:13)."""
import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa


def test_operator_is_nonsymmetric_and_diagonally_dominant():
    def driver(parts):
        A, b, xh, x0 = pa.assemble_advection_fv(parts, (8, 8), velocity=(2.0, -1.0))
        d = pa.gather_psparse(A).toarray()
        assert not np.allclose(d, d.T)  # upwinding breaks symmetry
        # weak diagonal dominance row-wise (M-matrix structure)
        off = np.abs(d).sum(1) - np.abs(np.diag(d))
        assert (np.diag(d) >= off - 1e-12).all()
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


@pytest.mark.parametrize("nparts", [(2, 2), (4, 1)])
def test_fv_bicgstab_sequential(nparts):
    err, info = pa.prun(
        lambda parts: pa.advection_fv_driver(parts, (12, 12)),
        pa.sequential,
        nparts,
    )
    assert info["converged"]
    assert err < 1e-5


def test_fv_bicgstab_tpu_matches_sequential():
    def run(backend):
        return pa.prun(
            lambda parts: pa.advection_fv_driver(parts, (10, 10, 6), velocity=(1.0, -0.5, 0.25)),
            backend,
            (2, 2, 2),
        )

    err_s, info_s = run(pa.sequential)
    err_t, info_t = run(pa.tpu)
    assert info_s["converged"] and info_t["converged"]
    assert err_s < 1e-5 and err_t < 1e-5
    # the compiled path must reach the same solution quality, not just
    # limp under the gate
    assert abs(err_t - err_s) < 1e-8


def test_velocity_dimension_validated():
    def driver(parts):
        with pytest.raises(AssertionError):
            pa.assemble_advection_fv(parts, (8, 8), velocity=(1.0, 1.0, 1.0))
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))
