"""Test harness configuration.

Mirrors the reference's CI story (SURVEY.md §4): the whole tree runs on one
machine. The TPU-backend tests run on a virtual 8-device CPU mesh via
``xla_force_host_platform_device_count`` (the `mpiexec -n 8` analog), and
float64 is enabled so correctness checks match the sequential oracle.

This file must set the environment before anything imports jax.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "true")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
