"""Test harness configuration.

Mirrors the reference's CI story (SURVEY.md §4): the whole tree runs on one
machine. The TPU-backend tests run on a virtual 8-device CPU mesh via
``xla_force_host_platform_device_count`` (the `mpiexec -n 8` analog), and
float64 is enabled so correctness checks match the sequential oracle.

This file must set the environment before anything imports jax.
"""
import os
import sys

# Plain assignment, not setdefault: the image's sitecustomize exports
# JAX_PLATFORMS=axon (the real-TPU tunnel), which must not leak into tests.
# The sitecustomize also pre-imports jax, so env vars alone are too late —
# the config must be updated through the API as well.
os.environ["JAX_PLATFORMS"] = "cpu"
# The suite's error-path probes assert that contract checks raise; a
# stripped-checks environment (PA_TPU_CHECKS=0) is a production tuning,
# not a supported test configuration — pin checks on before the package
# reads the flag at import.
os.environ["PA_TPU_CHECKS"] = "1"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_ENABLE_X64"] = "true"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
