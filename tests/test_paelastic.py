"""Elastic degraded-mode solves (parallel/elastic.py + the P -> P'
cross-part-count repartition/checkpoint tentpole): shrink-shape
arithmetic, cross-count checkpoint round trips, plan-fingerprint
invariants of repartitioned systems, the tenant-budget re-check at the
shrunken footprint, and the tools/paelastic.py drill wiring. The
part-loss x PA_ELASTIC recovery rows live in test_chaos_matrix.py
(round 19)."""
import importlib.util
import os

import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu.models import (
    assemble_poisson,
    cg,
    gather_pvector,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# shrink shapes + survivor partitions
# ---------------------------------------------------------------------------


def test_shrink_shape_rules(monkeypatch):
    """First >1 axis decrements; a dead part id is shrunk OUT of the
    grid (so a re-run of the same fault spec is inert on the
    survivors); the PA_ELASTIC_MIN_PARTS floor refuses typed."""
    assert pa.shrink_shape((4, 2)) == (3, 2)
    assert pa.shrink_shape((1, 3)) == (1, 2)
    assert pa.shrink_shape((4,)) == (3,)
    # dead part 5 is a valid id on (3,2)=6 — keep shrinking to (2,2)=4
    assert pa.shrink_shape((4, 2), dead_part=5) == (2, 2)
    with pytest.raises(ValueError):
        pa.shrink_shape((1, 1))
    monkeypatch.setenv("PA_ELASTIC_MIN_PARTS", "6")
    with pytest.raises(ValueError):
        pa.shrink_shape((4, 2), dead_part=3)


def test_survivor_rows_ghost_free_and_verified():
    """The survivor partition is ghost-free 1-D blocks over the new
    grid, and a system repartitioned onto it carries a derived column
    plan that passes ALL five static checks."""
    from partitionedarrays_jl_tpu.analysis.plan_verifier import check_plan

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        rows6 = pa.survivor_rows(A.rows, shape=(3, 2))
        assert not rows6.ghost
        assert rows6.partition.num_parts == 6
        assert rows6.ngids == A.rows.ngids
        A6 = pa.repartition_psparse(A, rows6)
        check_plan(
            A6.cols.exchanger,
            parts=A6.cols.partition.part_values(),
            context="test_survivor_rows",
        )
        return True

    assert pa.prun(driver, pa.sequential, (4, 2))


# ---------------------------------------------------------------------------
# cross-part-count checkpoint round trips (the tentpole contract)
# ---------------------------------------------------------------------------


def test_checkpoint_roundtrip_8_4_8_bitwise(tmp_path, monkeypatch):
    """8 -> 4 -> 8: a solver-state checkpoint written at 8 parts
    restores onto 4 (under PA_ELASTIC=1), re-saves, and restores back
    onto the original 8-part partition BITWISE — the gid-keyed format
    is partition-independent, elasticity adds routing, never
    arithmetic."""
    d8 = str(tmp_path / "p8")
    d4 = str(tmp_path / "p4")
    monkeypatch.setenv("PA_ELASTIC", "1")

    def save8(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        x_ref, _ = cg(A, b, x0=x0, tol=1e-9)
        ck = pa.SolverCheckpointer(d8, every=1)
        ck.save_state({"x": x_ref}, {"method": "cg", "it": 9, "tol": 1e-9})
        ck.wait()
        return gather_pvector(x_ref)

    g_ref = pa.prun(save8, pa.sequential, (4, 2))

    def hop4(parts):
        rows = pa.uniform_partition(parts, 64)
        st = pa.load_solver_state(d8, {"x": rows})
        assert int(st["meta"]["it"]) == 9
        ck = pa.SolverCheckpointer(d4, every=1)
        ck.save_state({"x": st["x"]}, dict(st["meta"]))
        ck.wait()
        return gather_pvector(st["x"])

    g4 = pa.prun(hop4, pa.sequential, (2, 2))
    np.testing.assert_array_equal(g4, g_ref)

    def back8(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        st = pa.load_solver_state(d4, {"x": A.cols})
        assert int(st["meta"]["it"]) == 9
        return gather_pvector(st["x"])

    g8 = pa.prun(back8, pa.sequential, (4, 2))
    np.testing.assert_array_equal(g8, g_ref)


def test_solver_state_cross_count_refused_without_elastic(
    tmp_path, monkeypatch
):
    """Satellite 2: the SOLVER-STATE restore path refuses a mismatched
    part count with PA_ELASTIC unset — typed `CheckpointShapeError`
    naming BOTH part counts and the escape hatch. The generic
    load_checkpoint/load_pvector loaders stay ungated (pinned by
    test_checkpoint.py's cross-partition round trips)."""
    d = str(tmp_path / "ck")
    monkeypatch.delenv("PA_ELASTIC", raising=False)

    def save4(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        ck = pa.SolverCheckpointer(d, every=1)
        ck.save_state({"x": x0}, {"method": "cg", "it": 2, "tol": 1e-9})
        ck.wait()
        return True

    assert pa.prun(save4, pa.sequential, (2, 2))

    def load2(parts):
        rows = pa.uniform_partition(parts, 64)
        with pytest.raises(pa.CheckpointShapeError) as ei:
            pa.load_solver_state(d, {"x": rows})
        msg = str(ei.value)
        assert "4 parts" in msg and "2 parts" in msg
        assert "PA_ELASTIC" in msg
        # the escape hatch works in the same process
        os.environ["PA_ELASTIC"] = "1"
        try:
            st = pa.load_solver_state(d, {"x": rows})
        finally:
            os.environ.pop("PA_ELASTIC", None)
        assert st is not None and int(st["meta"]["it"]) == 2
        return True

    assert pa.prun(load2, pa.sequential, 2)

    def load4(parts):
        # SAME part count stays ungated with PA_ELASTIC unset
        rows = pa.uniform_partition(parts, 64)
        st = pa.load_solver_state(d, {"x": rows})
        assert st is not None and int(st["meta"]["it"]) == 2
        return True

    assert pa.prun(load4, pa.sequential, (2, 2))


# ---------------------------------------------------------------------------
# plan-fingerprint invariants across shrink/grow
# ---------------------------------------------------------------------------


def test_repartitioned_plans_distinct_but_canonical_fingerprint_survives():
    """Satellite 3: a shrink DERIVES a genuinely different plan
    (`plan_fingerprint`-distinct — fewer parts, different slots) that
    still passes every static check; and a full shrink/grow cycle back
    onto the original partition preserves the LAYOUT-INDEPENDENT
    `canonical_exchange_fingerprint` — the same global columns cross
    the same edges, however the ghost lids got renumbered."""
    from partitionedarrays_jl_tpu.analysis.plan_verifier import (
        canonical_exchange_fingerprint,
        check_plan,
        plan_fingerprint,
    )

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        f_orig = plan_fingerprint(A.cols.exchanger)
        c_orig = canonical_exchange_fingerprint(
            A.cols.exchanger, A.cols.partition
        )
        rows6 = pa.survivor_rows(A.rows, shape=(3, 2))
        A6 = pa.repartition_psparse(A, rows6)
        f6 = plan_fingerprint(A6.cols.exchanger)
        assert f6 != f_orig
        check_plan(
            A6.cols.exchanger,
            parts=A6.cols.partition.part_values(),
            context="shrunken",
        )
        # grow back onto the ORIGINAL ghost-free row partition
        A8 = pa.repartition_psparse(A6, A.rows)
        check_plan(
            A8.cols.exchanger,
            parts=A8.cols.partition.part_values(),
            context="grown-back",
        )
        c_back = canonical_exchange_fingerprint(
            A8.cols.exchanger, A8.cols.partition
        )
        assert c_back == c_orig
        # and the operator itself survived the cycle bitwise
        np.testing.assert_array_equal(
            pa.gather_psparse(A8).toarray(), pa.gather_psparse(A).toarray()
        )
        return True

    assert pa.prun(driver, pa.sequential, (4, 2))


# ---------------------------------------------------------------------------
# the tenant-budget re-check at the shrunken footprint
# ---------------------------------------------------------------------------


def test_shrink_rechecks_memory_budget(monkeypatch):
    """Service integration: elastic shrink re-checks the tenant memory
    budget at the NEW footprint (fewer parts => wider per-part rows) —
    an impossible budget refuses typed with both part counts in the
    diagnostics, and nothing half-migrated escapes."""
    from partitionedarrays_jl_tpu.frontdoor.tenancy import TenantBudgetError

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        monkeypatch.setenv("PA_GATE_MEM_BUDGET", "1")
        with pytest.raises(TenantBudgetError) as ei:
            pa.shrink_system(A, b)
        d = ei.value.diagnostics
        assert d["from_parts"] == 8 and d["to_parts"] == 6
        assert d["footprint_bytes"] > d["budget_bytes"]
        monkeypatch.delenv("PA_GATE_MEM_BUDGET")
        # with headroom the same shrink admits and marks degraded
        from partitionedarrays_jl_tpu.parallel import elastic

        elastic._DEGRADED.clear()
        A2, b2, x2, info = pa.shrink_system(A, b)
        assert info["to_parts"] == 6 and x2 is None
        assert elastic.degraded_state()["to_parts"] == 6
        elastic._DEGRADED.clear()
        return True

    assert pa.prun(driver, pa.sequential, (4, 2))


# ---------------------------------------------------------------------------
# CLI: the tier-1 smoke + the full drill
# ---------------------------------------------------------------------------


def _load_paelastic():
    spec = importlib.util.spec_from_file_location(
        "paelastic", os.path.join(REPO, "tools", "paelastic.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_paelastic_check_smoke(capsys):
    """tools/paelastic.py --check: shrink shapes, cross-count round
    trip + f32 dtype pin, typed refusal, one small shrink-and-resume
    (tier-1)."""
    paelastic = _load_paelastic()
    rc = paelastic.main(["--check"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "paelastic --check: OK" in out


@pytest.mark.slow
def test_paelastic_drill_full(capsys):
    """THE acceptance drill: part 6 dies mid-solve on the 8-part
    fixture, the run shrinks to 6 survivors, resumes from the last
    chunk checkpoint within tolerance, BITWISE equals the cold solve
    from the same x_k, narrates the whole trail, and grows back
    (tools/paelastic.py --drill; --dry-run keeps the committed
    ELASTIC_BENCH.json untouched)."""
    paelastic = _load_paelastic()
    rc = paelastic.main(["--drill", "--dry-run"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "paelastic --drill: OK" in out
