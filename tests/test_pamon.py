"""The pamon observability plane (round 12): deterministic histograms,
the typed metric registry, SLO/throughput accounting, and the overhead
pin.

The tentpole's hard contracts, pinned here:

* **Determinism.** Histogram bucket edges are module constants — two
  histograms fed the same values are byte-identical JSON; merge is
  associative; quantile estimates BRACKET the true quantile;
  snapshot→delta→apply_delta round-trips exactly. No wall-clock ever
  enters a deterministic field.
* **Thread safety.** Counters, the record/event layer, and histograms
  all serialize on the ONE registry lock — the two-thread hammer
  asserts exact totals (the PR 9 satellite: the service background
  worker used to race the submitting thread on bare dict/list
  mutation).
* **Observing stays free.** With the registry fully enabled (PA_MON on,
  metrics flowing) the compiled block program is byte-identical
  StableHLO to the PA_MON=0 build, and the service slab still consumes
  the bare block body's cached program (program-cache HIT — zero extra
  collectives by construction; the measured drained-throughput
  marginal is banded in SERVICE_BENCH.json).
* **The adaptive-K input.** Finished slabs feed the EWMA throughput
  model; its curve/suggest_k readouts are the measured per-RHS surface
  ROADMAP item 1 was blocked on.

Plus the operator surfaces: `tools/pamon.py --check` (the tier-1
smoke) and `tools/patrace.py --service` (per-slab timeline join).
"""
import importlib.util
import json
import os
import threading

import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu import telemetry
from partitionedarrays_jl_tpu.models import assemble_poisson
from partitionedarrays_jl_tpu.service import SolveService
from partitionedarrays_jl_tpu.telemetry.histogram import (
    BUCKET_BOUNDS,
    LatencyHistogram,
    apply_delta,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# histogram determinism
# ---------------------------------------------------------------------------


def test_histogram_fixed_bounds_and_byte_stable_json():
    """The bucket layout is a module constant (4/decade, 1e-7..1e4 s),
    and identical observations produce byte-identical JSON — no
    wall-clock, no data-dependent layout."""
    assert len(BUCKET_BOUNDS) == 45
    assert BUCKET_BOUNDS[0] == pytest.approx(1e-7)
    assert BUCKET_BOUNDS[-1] == pytest.approx(1e4)
    assert all(
        b2 > b1 for b1, b2 in zip(BUCKET_BOUNDS, BUCKET_BOUNDS[1:])
    )
    # ratio between consecutive edges is the fixed 10^(1/4) factor
    assert BUCKET_BOUNDS[1] / BUCKET_BOUNDS[0] == pytest.approx(
        10.0 ** 0.25
    )
    values = [3e-8, 1e-4, 1e-4, 0.02, 0.5, 7.0, 1e5]
    a, b = LatencyHistogram(), LatencyHistogram()
    for v in values:
        a.observe(v)
    for v in values:
        b.observe(v)
    assert a.to_json() == b.to_json()
    snap = json.loads(a.to_json())
    assert set(snap) == {
        "histogram_schema_version", "count", "sum", "min", "max",
        "buckets",
    }
    # underflow and overflow both land (first and last bucket index)
    assert snap["buckets"]["0"] == 1
    assert snap["buckets"][str(len(BUCKET_BOUNDS))] == 1
    # round-trip through the snapshot is exact
    assert LatencyHistogram.from_snapshot(snap).to_json() == a.to_json()


def test_histogram_merge_associative_and_commutative():
    rng = np.random.default_rng(7)
    parts = [rng.lognormal(-6, 3, 50) for _ in range(3)]
    hs = []
    for p in parts:
        h = LatencyHistogram()
        for v in p:
            h.observe(float(v))
        hs.append(h)
    ab_c = hs[0].copy().merge(hs[1]).merge(hs[2])
    a_bc = hs[0].copy().merge(hs[1].copy().merge(hs[2]))
    c_ba = hs[2].copy().merge(hs[1]).merge(hs[0])
    # counts/min/max/quantiles agree exactly; sums up to fp fold order
    for other in (a_bc, c_ba):
        assert other.counts == ab_c.counts
        assert (other.total, other.min, other.max) == (
            ab_c.total, ab_c.min, ab_c.max,
        )
        assert other.sum == pytest.approx(ab_c.sum, rel=1e-12)
    # merged == histogram of the concatenation
    flat = LatencyHistogram()
    for p in parts:
        for v in p:
            flat.observe(float(v))
    assert flat.counts == ab_c.counts


def test_histogram_quantile_brackets_true_quantile():
    rng = np.random.default_rng(11)
    values = np.sort(rng.lognormal(-5, 2, 400))
    h = LatencyHistogram()
    for v in values:
        h.observe(float(v))
    for q in (0.05, 0.25, 0.5, 0.9, 0.99):
        true_q = float(values[min(len(values) - 1,
                                  max(0, int(np.ceil(q * len(values))) - 1))])
        lo, hi = h.quantile_bounds(q)
        assert lo <= true_q <= hi, (q, lo, true_q, hi)
        assert h.quantile(q) == hi  # the conservative upper edge
        # the bracket is one fixed bucket wide at most
        assert hi / max(lo, 1e-300) <= 10.0 ** 0.25 + 1e-9 or lo == hi
    assert h.quantile_bounds(0.0)[0] == h.min
    assert h.quantile(1.0) == h.max


def test_histogram_snapshot_delta_roundtrip():
    h = LatencyHistogram()
    for v in (1e-3, 2e-3, 0.5):
        h.observe(v)
    snap_a = h.snapshot()
    for v in (1e-6, 0.5, 20.0):
        h.observe(v)
    snap_b = h.snapshot()
    delta = h.delta(snap_a)
    assert delta["count"] == 3
    assert apply_delta(snap_a, delta) == snap_b
    # an empty delta round-trips too (min/max keep the earlier state)
    assert apply_delta(snap_b, h.delta(snap_b)) == snap_b
    # the round-trip is exact for ARBITRARY data, not just friendly
    # values: float sums do not invert under IEEE subtraction, so the
    # delta carries the current sum verbatim (review finding — 27/2000
    # random round-trips mismatched under the naive prev+diff scheme)
    rng = np.random.default_rng(3)
    g = LatencyHistogram()
    prev = g.snapshot()
    for _ in range(200):
        for v in rng.lognormal(0, 5, 10):
            g.observe(float(v))
        cur = g.snapshot()
        assert apply_delta(prev, g.delta(prev)) == cur
        prev = cur


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------


def test_registry_types_exporters_and_catalog_enforcement():
    reg = telemetry.registry()
    reg.reset("t_pamon")
    try:
        c = reg.counter("t_pamon.c")
        c.inc()
        c.inc(2)
        g = reg.gauge("t_pamon.g")
        g.set(4.0)
        g.inc()
        g.dec(2.0)
        h = reg.histogram("t_pamon.h")
        h.observe(0.25)
        lc = reg.counter("t_pamon.slo", labels={"tol_class": "1e-08"})
        lc.inc(5)
        snap = reg.snapshot("t_pamon")
        assert snap["counters"] == {
            "t_pamon.c": 3, "t_pamon.slo{tol_class=1e-08}": 5,
        }
        assert snap["gauges"] == {"t_pamon.g": 3.0}
        assert snap["histograms"]["t_pamon.h"]["count"] == 1
        # deterministic JSON (sorted keys, no wall-clock)
        assert reg.to_json("t_pamon") == reg.to_json("t_pamon")
        prom = reg.to_prometheus()
        assert "pa_t_pamon_c 3" in prom
        assert "pa_t_pamon_g 3" in prom
        assert '# TYPE pa_t_pamon_h histogram' in prom
        assert "pa_t_pamon_h_count 1" in prom
        assert 'pa_t_pamon_slo{tol_class="1e-08"} 5' in prom
        # cumulative le buckets end at +Inf == count
        inf_line = [ln for ln in prom.splitlines()
                    if ln.startswith('pa_t_pamon_h_bucket{le="+Inf"}')]
        assert inf_line == ['pa_t_pamon_h_bucket{le="+Inf"} 1']
        # a declared name must be touched with its declared kind
        with pytest.raises(TypeError):
            reg.gauge("lowering_cache.hit")
        with pytest.raises(TypeError):
            reg.counter("service.queue_wait_s")
        with pytest.raises(TypeError):
            reg.gauge("events.solve_aborted")
    finally:
        reg.reset("t_pamon")


def test_registry_two_thread_hammer():
    """The PR 9 thread-safety satellite, as a lean hammer: two threads
    bump ONE counter, observe ONE histogram, and emit events into the
    SAME active record; every total must be exact (the pre-registry
    code raced on bare dict/list mutation from the service worker)."""
    reg = telemetry.registry()
    reg.reset("t_hammer")
    rec = telemetry.begin_record("t-hammer")
    N_BUMP, N_OBS, N_EV = 2000, 500, 200
    errors = []

    def work():
        try:
            c = reg.counter("t_hammer.c")
            h = reg.histogram("t_hammer.h")
            for i in range(N_BUMP):
                c.inc()
            for i in range(N_OBS):
                h.observe(1e-3)
            for i in range(N_EV):
                telemetry.emit_event("t_hammer", label="x", i=i)
        except Exception as e:  # pragma: no cover - failure path
            errors.append(e)

    threads = [threading.Thread(target=work) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    try:
        assert not errors
        assert reg.counter_value("t_hammer.c") == 2 * N_BUMP
        assert reg.histogram("t_hammer.h").count == 2 * N_OBS
        assert len(rec.events_of("t_hammer")) == 2 * N_EV
        assert telemetry.counter("events.t_hammer") >= 2 * N_EV
    finally:
        rec.finish(None)
        telemetry.clear_history()
        reg.reset("t_hammer")
        reg.reset("events.t_hammer")


# ---------------------------------------------------------------------------
# the throughput model
# ---------------------------------------------------------------------------


def test_throughput_model_ewma_suggest_k_and_kill_switch(monkeypatch):
    m = telemetry.ThroughputModel(alpha=0.5)
    m.observe_slab("op", "float32", 4, 0.010, 10)
    m.observe_slab("op", "float32", 4, 0.020, 10)  # EWMA: 0.015
    assert m.s_per_it("op", "float32", 4) == pytest.approx(0.015)
    assert m.per_rhs("op", "float32", 4) == pytest.approx(0.015 / 4)
    m.observe_slab("op", "float32", 1, 0.004, 10)
    m.observe_slab("op", "float32", 8, 0.016, 10)
    # per-RHS: K=1 -> 4.0e-3, K=4 -> 3.75e-3, K=8 -> 2.0e-3
    assert m.curve("op", "float32") == pytest.approx(
        {1: 0.004, 4: 0.00375, 8: 0.002}
    )
    assert m.suggest_k("op", "float32", queue_depth=64, kmax=8) == 8
    assert m.suggest_k("op", "float32", queue_depth=6, kmax=8) == 4
    assert m.suggest_k("op", "float32", queue_depth=1, kmax=8) == 1
    # unmeasured operator: fall back to the static min(queue, kmax)
    assert m.suggest_k("other", "float32", 3, 8) == 3
    # export/load round-trip preserves the table
    again = telemetry.ThroughputModel.load(m.export())
    assert again.export()["entries"] == m.export()["entries"]
    # degenerate observations are refused, kill switch gates updates
    m.observe_slab("op", "float32", 4, 0.0, 10)
    m.observe_slab("op", "float32", 4, 0.5, 0)
    assert m.s_per_it("op", "float32", 4) == pytest.approx(0.015)
    monkeypatch.setenv("PA_MON", "0")
    m.observe_slab("op", "float32", 4, 99.0, 10)
    assert m.s_per_it("op", "float32", 4) == pytest.approx(0.015)


# ---------------------------------------------------------------------------
# service instrumentation end-to-end
# ---------------------------------------------------------------------------


def _counters(*names):
    return {n: telemetry.counter(n) for n in names}


def test_service_lifecycle_metrics_end_to_end():
    """One drained service exercises the whole declared surface:
    lifecycle histograms with the right observation counts, gauges in
    their terminal state, SLO attainment for the deadline class, and a
    throughput-model entry under the service's fingerprint."""
    reg = telemetry.registry()

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        before_h = {
            n: reg.histogram(n).count
            for n in ("service.queue_wait_s", "service.slab_wait_s",
                      "service.solve_s", "service.total_s",
                      "service.deadline_slack_s")
        }
        before_c = _counters("service.admitted", "service.completed",
                             "service.slabs", "service.slabs_ragged")
        before_slo = reg.counter(
            "service.slo.requests", labels={"tol_class": "1e-09"}
        ).value
        before_hits = reg.counter(
            "service.slo.hits", labels={"tol_class": "1e-09"}
        ).value
        telemetry.reset_model()
        svc = SolveService(A, kmax=4)
        hs = [
            svc.submit(b, x0=x0, tol=1e-9, deadline=3600.0,
                       tag=f"m-{i}")
            for i in range(3)  # 3 < kmax: a ragged slab
        ]
        svc.drain()
        for h in hs:
            assert h.result()[1]["converged"]
            assert h.finished_at is not None
            assert h.finished_at >= h.submitted_at
        d_c = {
            k: telemetry.counter(k) - v for k, v in before_c.items()
        }
        assert d_c["service.admitted"] == 3
        assert d_c["service.completed"] == 3
        assert d_c["service.slabs"] == 1
        assert d_c["service.slabs_ragged"] == 1
        d_h = {
            n: reg.histogram(n).count - c for n, c in before_h.items()
        }
        assert d_h["service.queue_wait_s"] == 3
        assert d_h["service.total_s"] == 3
        assert d_h["service.deadline_slack_s"] == 3
        assert d_h["service.slab_wait_s"] == 1
        assert d_h["service.solve_s"] >= 1  # one per chunk
        # gauges: drained service, nothing queued or in flight; the
        # last slab was 3 of 4 wide and ragged
        snap = reg.snapshot("service")
        assert snap["gauges"]["service.queue_depth"] == 0
        assert snap["gauges"]["service.inflight_slabs"] == 0
        assert snap["gauges"]["service.slab_utilization"] == 0.75
        assert 0 < snap["gauges"]["service.ragged_fraction"] <= 1
        # SLO: all three deadline-carrying requests hit the 1e-09 class
        assert reg.counter(
            "service.slo.requests", labels={"tol_class": "1e-09"}
        ).value - before_slo == 3
        assert reg.counter(
            "service.slo.hits", labels={"tol_class": "1e-09"}
        ).value - before_hits == 3
        # the slab fed the throughput model under this service's key
        model = telemetry.throughput_model()
        dtype = str(np.dtype(b.dtype))
        curve = model.curve(svc.fingerprint, dtype)
        assert 3 in curve and curve[3] > 0
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_pa_mon_kill_switch_gates_instrumentation(monkeypatch):
    """PA_MON=0: counters and records keep working (their PR 6
    contracts), but histograms/gauges/throughput stay silent."""
    monkeypatch.setenv("PA_MON", "0")
    reg = telemetry.registry()

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        before_h = reg.histogram("service.total_s").count
        before_sl = reg.histogram("service.deadline_slack_s").count
        before_c = telemetry.counter("service.completed")
        before_slo = reg.counter(
            "service.slo.requests", labels={"tol_class": "1e-09"}
        ).value
        telemetry.reset_model()
        svc = SolveService(A, kmax=2)
        h = svc.submit(b, x0=x0, tol=1e-9, deadline=3600.0, tag="off")
        svc.drain()
        assert h.result()[1]["converged"]
        assert telemetry.counter("service.completed") == before_c + 1
        # SLO attainment is a COUNTER — always on, like every counter
        assert reg.counter(
            "service.slo.requests", labels={"tol_class": "1e-09"}
        ).value == before_slo + 1
        # ...while the histograms stay silent
        assert reg.histogram("service.total_s").count == before_h
        assert reg.histogram(
            "service.deadline_slack_s"
        ).count == before_sl
        assert telemetry.throughput_model().curve(
            svc.fingerprint, str(np.dtype(b.dtype))
        ) == {}
        # the event/record layer is untouched by PA_MON
        assert h.record.finished
        assert any(e.kind == "request_done" for e in h.record.events)
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


# ---------------------------------------------------------------------------
# the overhead pin: observing stays free
# ---------------------------------------------------------------------------


def test_block_program_hlo_identical_with_registry_enabled(monkeypatch):
    """The acceptance pin: a registry-on build (PA_MON=1, metrics
    flowing through the registry) lowers the block body to
    byte-identical StableHLO vs the killed plane (PA_MON=0) — the
    program-cache-hit leg lives in
    test_service.py::test_service_consumes_bare_block_program, which
    runs under the default-enabled registry."""
    import jax

    from partitionedarrays_jl_tpu.parallel.tpu import (
        TPUBackend,
        _matrix_operands,
        device_matrix,
        make_cg_fn,
    )

    backend = TPUBackend(devices=jax.devices()[:8])

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (6, 6, 6))
        return A

    A = pa.prun(driver, backend, (2, 2, 2))
    dA = device_matrix(A, backend)
    ops = _matrix_operands(dA)
    P, W = dA.col_plan.layout.P, dA.col_plan.layout.W
    zb = np.zeros((P, W, 2))

    def text():
        fn = make_cg_fn(dA, tol=1e-9, maxiter=50, rhs_batch=2)
        return fn.jit_fn.lower(zb, zb, zb[..., 0], ops).as_text()

    # fully enabled AND carrying live data (a non-empty registry must
    # not leak anything into a traced program)
    telemetry.registry().histogram("service.solve_s").observe(0.01)
    on = text()
    monkeypatch.setenv("PA_MON", "0")
    off = text()
    assert on == off


# ---------------------------------------------------------------------------
# the operator surfaces: pamon --check, patrace --service
# ---------------------------------------------------------------------------


def test_pamon_check_smoke(capsys):
    """`tools/pamon.py --check` is the tier-1 smoke of the whole plane:
    demo service, invariant assertions, every render surface."""
    pamon = _load_tool("pamon")
    rc = pamon.main(["--check"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "pamon --check: OK" in out
    assert "service.total_s" in out
    assert "SLO attainment" in out
    # the committed model rendered (the repo ships THROUGHPUT_MODEL.json)
    assert "throughput model" in out
    assert "reference curve" in out


def test_patrace_service_timeline_joins_slab(tmp_path, monkeypatch,
                                             capsys):
    """`tools/patrace.py --service`: the poisoned-column incident —
    previously smeared across K per-request records — reads as ONE
    slab story: formation, the verdict, the ejection, each request's
    outcome, with the cross-record duplicates deduped."""
    d = str(tmp_path / "svc-recs")
    monkeypatch.setenv("PA_METRICS_DIR", d)

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        bad = b.copy()

        def poison(i, vals):
            if int(i.part) == 0:
                np.asarray(vals)[0] = np.nan

        pa.map_parts(poison, bad.rows.partition, bad.values)
        svc = SolveService(A, kmax=3, retries=0)
        svc.submit(b, x0=x0, tol=1e-9, tag="tl-good")
        svc.submit(bad, x0=x0, tol=1e-9, tag="tl-bad")
        svc.submit(b, x0=x0, tol=1e-9, tag="tl-good2")
        svc.drain()
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))
    patrace = _load_tool("patrace")
    rc = patrace.main(["--service", "--dir", d])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "slab 0: K=3" in out
    assert "tl-good, tl-bad, tl-good2" in out
    # the story is joined AND deduped: each lifecycle line once
    assert out.count("column_ejected") == 1
    assert out.count("request_failed:tl-bad") == 1
    assert out.count("slab_formed:K=3") == 1
    assert "outcomes:" in out
    assert "tl-bad FAILED(NonFiniteError)" in out
    assert "tl-good converged" in out


def test_patrace_service_all_ejected_slab_shows_retry_story(
    tmp_path, monkeypatch, capsys
):
    """ISSUE-14 BUGFIX pin: a slab whose EVERY request is ejected and
    retried solo must render the retry continuation — the injected
    faults, the typed health errors, the aborted attempts of the
    nested solo solves — inside the incident view, not just the bare
    formed/ejected/done skeleton. Pre-fix those events were dropped as
    unnamed (the nested records never name the request); now they join
    by their ejection-window timing, annotated ``retry_of``."""
    from partitionedarrays_jl_tpu.parallel.faults import inject_faults

    d = str(tmp_path / "svc-recs")
    monkeypatch.setenv("PA_METRICS_DIR", d)

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        svc = SolveService(A, kmax=2, retries=1, retry_backoff=0.0)
        # one one-shot fault per slab column: BOTH columns eject, both
        # solo retries heal (the faults do not refire)
        with inject_faults("nan@part=1,call=5;nan@part=1,call=9",
                           seed=1):
            r0 = svc.submit(b, x0=x0, tol=1e-9, tag="ej-0")
            r1 = svc.submit(b, x0=x0, tol=1e-9, tag="ej-1")
            svc.drain()
        assert r0.state == "done" and r1.state == "done"
        assert svc.stats["ejected"] == 2
        assert svc.stats["retried_solo"] == 2
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))
    patrace = _load_tool("patrace")
    rc = patrace.main(["--service", "--dir", d])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "slab 0: K=2" in out
    # the continuation story renders inside the slab timeline
    assert "fault_injected:nan" in out
    assert "health_error:NonFiniteError" in out
    assert "solve_aborted:NonFiniteError" in out
    # ejection-window attribution: retry-window events name their
    # owner (the first fault fires in the SLAB pass, pre-ejection —
    # the in-window ones carry retry_of)
    assert out.count("column_ejected") == 2
    assert "ej-0 converged" in out and "ej-1 converged" in out


# ---------------------------------------------------------------------------
# round 13 (ISSUE 10): exporter label hygiene, labeled-histogram
# concurrency, adaptive K
# ---------------------------------------------------------------------------


def test_prometheus_label_hygiene_with_hostile_value():
    """Exposition-format escaping: a label value carrying backslash,
    double quote, and newline must render escaped (\\\\, \\", \\n), the
    scrape must stay line-structured, and a LABELED histogram must emit
    ``_bucket``/``_sum``/``_count`` all carrying the identical escaped
    label set with the +Inf bucket equal to ``_count``."""
    import re

    reg = telemetry.registry()
    reg.reset("t_esc")
    try:
        hostile = 'wei"rd\\lab\nel'
        reg.counter("t_esc.c", labels={"tag": hostile}).inc(3)
        h = reg.histogram("t_esc.h", labels={"tag": hostile})
        h.observe(0.5)
        h.observe(2.0)
        prom = reg.to_prometheus()
        esc = 'tag="wei\\"rd\\\\lab\\nel"'
        assert "pa_t_esc_c{%s} 3" % esc in prom
        # every series line still parses as one NAME{LABELS} VALUE line
        # (an unescaped newline/quote would shatter this)
        for ln in prom.splitlines():
            if ln.startswith("#") or not ln:
                continue
            assert re.fullmatch(
                r"[a-zA-Z_:][a-zA-Z0-9_:]*(\{.*\})? \S+", ln
            ), ln
        hist_lines = [
            ln for ln in prom.splitlines()
            if ln.startswith("pa_t_esc_h")
        ]
        buckets = [ln for ln in hist_lines if "_bucket{" in ln]
        sums = [ln for ln in hist_lines if ln.startswith("pa_t_esc_h_sum")]
        counts = [
            ln for ln in hist_lines if ln.startswith("pa_t_esc_h_count")
        ]
        assert buckets and len(sums) == 1 and len(counts) == 1
        # identical escaped label set on every series of the family
        assert all(esc in ln for ln in buckets + sums + counts)
        assert sums[0] == "pa_t_esc_h_sum{%s} 2.5" % esc
        assert counts[0] == "pa_t_esc_h_count{%s} 2" % esc
        inf = [ln for ln in buckets if 'le="+Inf"' in ln]
        assert len(inf) == 1 and inf[0].endswith(" 2")
    finally:
        reg.reset("t_esc")


def test_labeled_histogram_two_thread_observe_vs_snapshot_hammer():
    """ISSUE-10 lean concurrency pin: one thread observes a LABELED
    histogram while another snapshots it through the shared lock —
    every snapshot must be internally consistent (bucket sum == count)
    and the final total exact. Bounded work, no sleeps."""
    reg = telemetry.registry()
    reg.reset("t_lh")
    try:
        labels = {"tol_class": "1e-08"}
        h = reg.histogram("t_lh.h", labels=labels)
        N = 3000
        torn = []
        done = threading.Event()

        def observer():
            for i in range(N):
                h.observe(1e-3 if i % 2 else 1e-1)
            done.set()

        def snapshotter():
            while not done.is_set():
                snap = h.snapshot()
                if sum(snap["buckets"].values()) != snap["count"]:
                    torn.append(snap)
            # one read after the writer finished: the final state
            snap = reg.snapshot("t_lh")["histograms"][
                "t_lh.h{tol_class=1e-08}"
            ]
            torn.extend(
                [snap]
                if sum(snap["buckets"].values()) != snap["count"]
                else []
            )

        threads = [
            threading.Thread(target=observer),
            threading.Thread(target=snapshotter),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not torn, torn[:1]
        assert h.count == N
    finally:
        reg.reset("t_lh")


def test_histogram_merge_associative_across_label_sets():
    """Per-label-set histograms roll up into one view in ANY grouping
    order: (a+b)+c == a+(b+c) == (c+b)+a, bucket-exactly — the
    property that lets per-class SLO histograms aggregate."""
    feeds = {
        "1e-06": [1e-4, 2e-3, 2e-3],
        "1e-08": [5e-2, 7e-1],
        "1e-10": [3.0, 3e-5, 9e-2, 2e-2],
    }
    hists = {}
    for cls, values in feeds.items():
        h = LatencyHistogram()
        for v in values:
            h.observe(v)
        hists[cls] = h
    a, b, c = (hists[k] for k in sorted(feeds))
    left = a.copy().merge(b).merge(c)
    bc = b.copy().merge(c)
    right = a.copy().merge(bc)
    rev = c.copy().merge(b).merge(a)
    assert left.snapshot() == right.snapshot()
    assert left.counts == rev.counts
    assert left.total == rev.total == sum(len(v) for v in feeds.values())
    assert left.min == rev.min and left.max == rev.max


def test_adaptive_k_picks_measured_optimum_and_static_path_unchanged(
    monkeypatch,
):
    """ISSUE-10 satellite: PA_SERVE_ADAPTIVE_K=1 caps the slab at
    suggest_k's measured per-RHS optimum (a deep queue picks the
    measured-best width, not kmax); off (default) the static
    PA_SERVE_KMAX path coalesces exactly as before."""
    from partitionedarrays_jl_tpu.service.batcher import effective_kmax

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        telemetry.reset_model()
        try:
            svc = SolveService(A, kmax=4, queue_depth=16)
            m = telemetry.throughput_model()
            dt = str(np.dtype(b.dtype))
            # measured per-RHS curve with its optimum at K=2:
            # K=1 -> 4.0e-3, K=2 -> 1.5e-3, K=4 -> 4.0e-3 per RHS
            m.observe_slab(svc.fingerprint, dt, 1, 0.004, 10)
            m.observe_slab(svc.fingerprint, dt, 2, 0.003, 10)
            m.observe_slab(svc.fingerprint, dt, 4, 0.016, 10)
            handles = [
                svc.submit(b, x0=x0, tol=1e-9, tag=f"ad-{i}")
                for i in range(6)
            ]
            # adaptive ON: a 6-deep queue forms a width-2 slab
            monkeypatch.setenv("PA_SERVE_ADAPTIVE_K", "1")
            assert effective_kmax(svc._queue, svc.kmax,
                                  svc.fingerprint) == 2
            # ...and a chunk-boundary top_up of a width-2 RUNNING slab
            # honors the same cap (anchor = the slab, base = its
            # width): no refill back toward the static kmax
            from partitionedarrays_jl_tpu.service.batcher import top_up

            queue = list(svc._queue)
            slab = [queue.pop(0), queue.pop(0)]
            cap = effective_kmax(queue, svc.kmax, svc.fingerprint,
                                 anchor=slab[0], base=len(slab))
            assert cap == 2
            assert top_up(queue, slab, cap) == []
            assert len(queue) == 4  # nothing consumed
            assert svc.step() == 2
            # OFF (the default): the static path runs kmax wide
            monkeypatch.delenv("PA_SERVE_ADAPTIVE_K")
            assert effective_kmax(svc._queue, svc.kmax,
                                  svc.fingerprint) == 4
            assert svc.step() == 4
            assert svc.pending() == 0
            for h in handles:
                x, info = h.result()
                assert info["converged"]
            # an UNMEASURED operator under adaptive K falls back to
            # the static min(depth, kmax) policy
            monkeypatch.setenv("PA_SERVE_ADAPTIVE_K", "1")
            svc2 = SolveService(A, kmax=4, queue_depth=16)
            telemetry.reset_model()
            q = [svc2.submit(b, x0=x0, tol=1e-9, tag="un-0"),
                 svc2.submit(b, x0=x0, tol=1e-9, tag="un-1")]
            assert effective_kmax(svc2._queue, svc2.kmax,
                                  svc2.fingerprint) == 2
            assert svc2.step() == 2
            for h in q:
                h.result()
        finally:
            telemetry.reset_model()
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))
