"""Persistent XLA compilation cache (round-5 directive 1).

The warm-setup headline rests on two properties: (a) enabling the cache
writes the compiled solver programs to disk, and (b) rebuilding the same
program after the in-process executable caches are cleared produces
IDENTICAL iterates (the disk-served executable is the same program, not
a recompile drift). Both are cheap to pin on the CPU mesh; the timing
claim itself lives in SCALE_BENCH.json (first_solve_cold_s /
first_solve_warm_s) measured on the real chip.

Round 9 (patrace): cache behavior is asserted on the telemetry
COUNTERS (``persistent_cache.{hit,miss}`` bridged from jax.monitoring,
``lowering_cache.{hit,miss,stale_rekey}`` / ``program_cache.{hit,miss}``
from the package's own caches) — a deterministic signal, unlike the
wall-clock compile-time floors such assertions used to lean on.
"""
import os

import jax
import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu.models import assemble_poisson
from partitionedarrays_jl_tpu.parallel.tpu import (
    DeviceVector,
    TPUBackend,
    _b_on_cols_layout,
    device_matrix,
    make_cg_fn,
)


def test_enable_populates_dir_and_warm_rebuild_matches(tmp_path):
    cache_dir = str(tmp_path / "xla")
    prev = pa.compilation_cache_dir()
    prev_secs = jax.config.jax_persistent_cache_min_compile_time_secs
    got = pa.enable_compilation_cache(cache_dir)
    try:
        assert got == cache_dir == pa.compilation_cache_dir()
        assert os.path.isdir(cache_dir)
        # compile-time floor would skip tiny CPU programs; drop it so the
        # test exercises the write+read path deterministically
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

        backend = TPUBackend(devices=jax.devices()[:8])
        from partitionedarrays_jl_tpu import telemetry

        def driver(parts):
            Ah, bh, xe, x0 = assemble_poisson(
                parts, (12, 12, 12), dtype=np.float64
            )
            dA = device_matrix(Ah, backend)
            db = _b_on_cols_layout(bh, dA)
            dx0 = DeviceVector.from_pvector(
                pa.PVector.full(0.0, Ah.cols, dtype=np.float64),
                backend, dA.col_layout,
            )
            base = telemetry.counters("persistent_cache")
            solve = make_cg_fn(dA, tol=1e-10, maxiter=500)
            out = solve(db.data, dx0.data, None)
            x_cold = np.asarray(out[0])
            it_cold = int(out[3])
            assert it_cold > 0
            # cold compile against the fresh cache dir: misses only —
            # the counters are the deterministic signal (no wall-clock)
            cold = telemetry.counters("persistent_cache")
            assert (
                cold.get("persistent_cache.miss", 0)
                > base.get("persistent_cache.miss", 0)
            )
            assert cold.get("persistent_cache.hit", 0) == base.get(
                "persistent_cache.hit", 0
            )

            # warm rebuild: executables dropped, program rebuilt — the
            # persistent cache serves the XLA executable from disk
            jax.clear_caches()
            solve2 = make_cg_fn(dA, tol=1e-10, maxiter=500)
            out2 = solve2(db.data, dx0.data, None)
            assert int(out2[3]) == it_cold
            np.testing.assert_array_equal(np.asarray(out2[0]), x_cold)
            warm = telemetry.counters("persistent_cache")
            assert (
                warm.get("persistent_cache.hit", 0)
                > cold.get("persistent_cache.hit", 0)
            ), "warm rebuild did not hit the persistent cache"
            return True

        assert pa.prun(driver, backend, (2, 2, 2))
        entries = os.listdir(cache_dir)
        assert entries, "persistent cache wrote no entries"
    finally:
        if prev is not None:
            pa.enable_compilation_cache(prev)
        else:
            # fully restore: tmp_path is pruned by pytest, so the cache
            # config must not keep pointing there for later tests
            import partitionedarrays_jl_tpu.utils.compile_cache as cc

            jax.config.update("jax_compilation_cache_dir", None)
            cc._enabled_dir = None
        # restore what was actually set before the test, not a literal —
        # LAST, because enable_compilation_cache above re-pins 1.0
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", prev_secs
        )


def test_lowering_and_program_cache_counters(monkeypatch):
    """The package's own two caches are observable: `device_matrix`'s
    per-matrix staging cache bumps ``lowering_cache.{hit,miss,
    stale_rekey}`` (stale_rekey = a matrix staged before under a
    DIFFERENT `_lowering_env_key` — an env flip re-ran staging
    admission, the palint bug class, now a measurable counter) and
    `_krylov_fn_for` bumps ``program_cache.{hit,miss}``."""
    from partitionedarrays_jl_tpu import telemetry
    from partitionedarrays_jl_tpu.parallel.tpu import _krylov_fn_for

    backend = TPUBackend(devices=jax.devices()[:4])

    def delta(after, before, name):
        return after.get(name, 0) - before.get(name, 0)

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        t0 = telemetry.counters("lowering_cache")
        dA = device_matrix(A, backend)
        assert device_matrix(A, backend) is dA
        t1 = telemetry.counters("lowering_cache")
        assert delta(t1, t0, "lowering_cache.miss") == 1
        assert delta(t1, t0, "lowering_cache.hit") == 1
        assert delta(t1, t0, "lowering_cache.stale_rekey") == 0

        # a lowering-env flip re-keys: staging admission re-runs,
        # visibly (PA_TPU_ABFT is in _lowering_env_key; PA_TRACE_ITERS
        # would NOT trip this — it keys the compiled program, not the
        # staging cache)
        monkeypatch.setenv("PA_TPU_ABFT", "1")
        device_matrix(A, backend)
        t2 = telemetry.counters("lowering_cache")
        assert delta(t2, t1, "lowering_cache.stale_rekey") == 1
        assert delta(t2, t1, "lowering_cache.miss") == 0
        monkeypatch.delenv("PA_TPU_ABFT")

        p0 = telemetry.counters("program_cache")
        solve = _krylov_fn_for(dA, "cg", 1e-9, 50)
        assert _krylov_fn_for(dA, "cg", 1e-9, 50) is solve
        p1 = telemetry.counters("program_cache")
        assert delta(p1, p0, "program_cache.miss") == 1
        assert delta(p1, p0, "program_cache.hit") == 1
        return True

    assert pa.prun(driver, backend, (2, 2))


def test_env_var_hook(monkeypatch, tmp_path):
    import partitionedarrays_jl_tpu.utils.compile_cache as cc

    prev_dir = cc.compilation_cache_dir()
    # _maybe_enable_from_env / enable_compilation_cache pin the compile-
    # time floor to their own value — save what was ACTUALLY set before
    # the test and restore it (not a literal) in the finally
    prev_secs = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        target = str(tmp_path / "envcache")
        monkeypatch.setenv("PA_TPU_COMPILE_CACHE", target)
        cc._maybe_enable_from_env()
        assert cc.compilation_cache_dir() == target
        assert os.path.isdir(target)
        # disable spellings are no-ops (never a crash, never a dir
        # literally named "false" in the cwd)
        for v in ("0", "false", "off", "no", ""):
            monkeypatch.setenv("PA_TPU_COMPILE_CACHE", v)
            before = cc.compilation_cache_dir()
            cc._maybe_enable_from_env()
            assert cc.compilation_cache_dir() == before
            assert not os.path.exists(os.path.join(os.getcwd(), v or "x"))
    finally:
        # restore global cache config: tmp_path is pruned by pytest, so
        # leaving the cache pointed there poisons later >=1s compiles
        if prev_dir is not None:
            cc.enable_compilation_cache(prev_dir)
        else:
            jax.config.update("jax_compilation_cache_dir", None)
            cc._enabled_dir = None
        # LAST: the enable call above re-pins the floor — put back the
        # pre-test value
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", prev_secs
        )
