"""Persistent XLA compilation cache (round-5 directive 1).

The warm-setup headline rests on two properties: (a) enabling the cache
writes the compiled solver programs to disk, and (b) rebuilding the same
program after the in-process executable caches are cleared produces
IDENTICAL iterates (the disk-served executable is the same program, not
a recompile drift). Both are cheap to pin on the CPU mesh; the timing
claim itself lives in SCALE_BENCH.json (first_solve_cold_s /
first_solve_warm_s) measured on the real chip.
"""
import os

import jax
import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu.models import assemble_poisson
from partitionedarrays_jl_tpu.parallel.tpu import (
    DeviceVector,
    TPUBackend,
    _b_on_cols_layout,
    device_matrix,
    make_cg_fn,
)


def test_enable_populates_dir_and_warm_rebuild_matches(tmp_path):
    cache_dir = str(tmp_path / "xla")
    prev = pa.compilation_cache_dir()
    prev_secs = jax.config.jax_persistent_cache_min_compile_time_secs
    got = pa.enable_compilation_cache(cache_dir)
    try:
        assert got == cache_dir == pa.compilation_cache_dir()
        assert os.path.isdir(cache_dir)
        # compile-time floor would skip tiny CPU programs; drop it so the
        # test exercises the write+read path deterministically
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

        backend = TPUBackend(devices=jax.devices()[:8])

        def driver(parts):
            Ah, bh, xe, x0 = assemble_poisson(
                parts, (12, 12, 12), dtype=np.float64
            )
            dA = device_matrix(Ah, backend)
            db = _b_on_cols_layout(bh, dA)
            dx0 = DeviceVector.from_pvector(
                pa.PVector.full(0.0, Ah.cols, dtype=np.float64),
                backend, dA.col_layout,
            )
            solve = make_cg_fn(dA, tol=1e-10, maxiter=500)
            out = solve(db.data, dx0.data, None)
            x_cold = np.asarray(out[0])
            it_cold = int(out[3])
            assert it_cold > 0

            # warm rebuild: executables dropped, program rebuilt — the
            # persistent cache serves the XLA executable from disk
            jax.clear_caches()
            solve2 = make_cg_fn(dA, tol=1e-10, maxiter=500)
            out2 = solve2(db.data, dx0.data, None)
            assert int(out2[3]) == it_cold
            np.testing.assert_array_equal(np.asarray(out2[0]), x_cold)
            return True

        assert pa.prun(driver, backend, (2, 2, 2))
        entries = os.listdir(cache_dir)
        assert entries, "persistent cache wrote no entries"
    finally:
        if prev is not None:
            pa.enable_compilation_cache(prev)
        else:
            # fully restore: tmp_path is pruned by pytest, so the cache
            # config must not keep pointing there for later tests
            import partitionedarrays_jl_tpu.utils.compile_cache as cc

            jax.config.update("jax_compilation_cache_dir", None)
            cc._enabled_dir = None
        # restore what was actually set before the test, not a literal —
        # LAST, because enable_compilation_cache above re-pins 1.0
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", prev_secs
        )


def test_env_var_hook(monkeypatch, tmp_path):
    import partitionedarrays_jl_tpu.utils.compile_cache as cc

    prev_dir = cc.compilation_cache_dir()
    # _maybe_enable_from_env / enable_compilation_cache pin the compile-
    # time floor to their own value — save what was ACTUALLY set before
    # the test and restore it (not a literal) in the finally
    prev_secs = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        target = str(tmp_path / "envcache")
        monkeypatch.setenv("PA_TPU_COMPILE_CACHE", target)
        cc._maybe_enable_from_env()
        assert cc.compilation_cache_dir() == target
        assert os.path.isdir(target)
        # disable spellings are no-ops (never a crash, never a dir
        # literally named "false" in the cwd)
        for v in ("0", "false", "off", "no", ""):
            monkeypatch.setenv("PA_TPU_COMPILE_CACHE", v)
            before = cc.compilation_cache_dir()
            cc._maybe_enable_from_env()
            assert cc.compilation_cache_dir() == before
            assert not os.path.exists(os.path.join(os.getcwd(), v or "x"))
    finally:
        # restore global cache config: tmp_path is pruned by pytest, so
        # leaving the cache pointed there poisons later >=1s compiles
        if prev_dir is not None:
            cc.enable_compilation_cache(prev_dir)
        else:
            jax.config.update("jax_compilation_cache_dir", None)
            cc._enabled_dir = None
        # LAST: the enable call above re-pins the floor — put back the
        # pre-test value
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", prev_secs
        )
