"""L4 integration tests: PRange constructor catalog + Exchanger.

Mirrors the reference conformance coverage of PRange variants and exchanges
(reference: test/test_interfaces.jl:177-499), fixtures re-derived 0-based
for this framework's C-order layout.
"""
import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa


def parts4():
    return pa.sequential.get_part_ids(4)


def parts22():
    return pa.sequential.get_part_ids((2, 2))


def test_uniform_partition():
    r = pa.uniform_partition(parts4(), 10)
    assert len(r) == 10 and r.num_parts == 4 and not r.ghost
    # balanced, remainder over trailing parts: sizes 2,2,3,3
    assert list(r.num_oids()) == [2, 2, 3, 3]
    assert [list(i.oid_to_gid) for i in r.partition] == [
        [0, 1],
        [2, 3],
        [4, 5, 6],
        [7, 8, 9],
    ]
    assert list(r.gid_to_part(np.arange(10))) == [0, 0, 1, 1, 2, 2, 2, 3, 3, 3]


def test_variable_partition():
    parts = parts4()
    noids = pa.map_parts(lambda p: [3, 1, 4, 2][p], parts)
    r = pa.variable_partition(parts, noids)
    assert len(r) == 10
    assert [i.firstgid for i in r.partition] == [0, 3, 4, 8]
    assert list(r.gid_to_part(np.arange(10))) == [0, 0, 0, 1, 2, 2, 2, 2, 3, 3]


def test_variable_partition_with_ghosts_and_exchange():
    parts = parts4()
    noids = pa.map_parts(lambda p: 3, parts)  # each owns 3 of 12
    # each part ghosts the first gid of the next part (ring)
    hid_gid = pa.map_parts(lambda p: np.array([(3 * (p + 1)) % 12]), parts)
    hid_part = pa.map_parts(lambda p: np.array([(p + 1) % 4]), parts)
    r = pa.variable_partition(parts, noids, hid_to_gid=hid_gid, hid_to_part=hid_part)
    assert r.ghost
    ex = r.exchanger
    assert [list(x) for x in ex.parts_rcv] == [[1], [2], [3], [0]]
    assert [list(x) for x in ex.parts_snd] == [[3], [0], [1], [2]]
    # owner packs its first owned lid for its predecessor
    assert [list(t.data) for t in ex.lids_snd] == [[0], [0], [0], [0]]
    assert [list(t.data) for t in ex.lids_rcv] == [[3], [3], [3], [3]]


def _halo_update_invariant(r: pa.PRange):
    """After exchanging owner->ghost, every ghost slot holds its gid."""
    vals = pa.map_parts(
        lambda i: np.where(
            i.lid_to_part == i.part, i.lid_to_gid.astype(np.float64), -1.0
        ),
        r.partition,
    )
    pa.exchange_values(vals, vals, r.exchanger)
    for i, v in zip(r.partition, vals):
        assert np.array_equal(np.asarray(v), i.lid_to_gid.astype(np.float64))


def test_cartesian_partition_no_ghost():
    r = pa.cartesian_partition(parts22(), (4, 4))
    assert len(r) == 16 and not r.ghost
    assert [list(i.oid_to_gid) for i in r.partition] == [
        [0, 1, 4, 5],
        [2, 3, 6, 7],
        [8, 9, 12, 13],
        [10, 11, 14, 15],
    ]


def test_cartesian_partition_with_ghost():
    r = pa.cartesian_partition(parts22(), (4, 4), pa.with_ghost)
    i0 = r.partition.get_part(0)
    assert list(i0.oid_to_gid) == [0, 1, 4, 5]
    assert list(i0.hid_to_gid) == [2, 6, 8, 9, 10]
    assert list(i0.hid_to_part) == [1, 1, 2, 2, 3]
    ex = r.exchanger
    assert [sorted(x) for x in ex.parts_rcv] == [[1, 2, 3], [0, 2, 3], [0, 1, 3], [0, 1, 2]]
    # part 1 wants gids [1, 5] from part 0 -> part 0 packs lids [1, 3]
    p0_snd_row = list(ex.lids_snd.get_part(0)[list(ex.parts_snd.get_part(0)).index(1)])
    assert p0_snd_row == [1, 3]
    _halo_update_invariant(r)


def test_cartesian_partition_periodic():
    r = pa.cartesian_partition(parts22(), (4, 4), pa.with_ghost, periodic=(True, True))
    i0 = r.partition.get_part(0)
    # extended box is 4x4: 4 owned + 12 ghosts (wraps in both dims)
    assert i0.num_oids == 4 and i0.num_hids == 12
    _halo_update_invariant(r)


def test_cartesian_3d_with_ghost_invariant():
    parts = pa.sequential.get_part_ids((2, 2, 2))
    r = pa.cartesian_partition(parts, (4, 4, 4), pa.with_ghost)
    # interior part boxes: 2x2x2 owned, extended 3x3x3 -> 19 ghosts
    assert list(r.num_oids()) == [8] * 8
    assert list(r.num_hids()) == [19] * 8
    _halo_update_invariant(r)


def test_periodic_single_part_dim_rejected():
    parts = pa.sequential.get_part_ids((1, 2))
    with pytest.raises(NotImplementedError):
        pa.cartesian_partition(parts, (4, 4), pa.with_ghost, periodic=(True, False))


def test_p_cartesian_indices():
    parts = parts22()
    ci = pa.p_cartesian_indices(parts, (4, 4))
    assert ci.get_part(3).shape == (2, 2)
    assert [list(x) for x in ci.get_part(3).ranges] == [[2, 3], [2, 3]]
    cig = pa.p_cartesian_indices(parts, (4, 4), pa.with_ghost)
    assert cig.get_part(0).shape == (3, 3)
    cip = pa.p_cartesian_indices(parts, (4, 4), pa.with_ghost, periodic=(True, True))
    assert [list(x) for x in cip.get_part(0).ranges] == [[3, 0, 1, 2], [3, 0, 1, 2]]
    assert list(ci.get_part(0).gids((4, 4))) == [0, 1, 4, 5]


def test_add_gids_and_renumber():
    parts = parts4()
    r = pa.uniform_partition(parts, 10)
    touched = pa.map_parts(lambda p: np.array([(2 * p + 5) % 10, p % 2]), parts)
    r2 = pa.add_gids(r, touched)
    # original untouched; copy has ghosts and a working exchanger
    assert not r.ghost and r2.ghost
    assert list(r.num_hids()) == [0, 0, 0, 0]
    assert all(h > 0 for h in r2.num_hids())
    _halo_update_invariant(r2)
    # in-place version mutates
    pa.add_gids_inplace(r, touched)
    assert r.ghost and pa.lids_are_equal(r, r2)
    # renumbering round-trip through the extended partition
    ids = pa.map_parts(lambda p: np.array([(2 * p + 5) % 10]), parts)
    orig = [list(x) for x in ids]
    pa.to_lids(r, ids)
    pa.to_gids(r, ids)
    assert [list(x) for x in ids] == orig


def test_assembly_reverse_exchange():
    # ghost->owner accumulation: each gid ends with 1 + (#parts ghosting it)
    r = pa.cartesian_partition(parts22(), (4, 4), pa.with_ghost)
    vals = pa.map_parts(lambda i: np.ones(i.num_lids), r.partition)
    pa.exchange_values(vals, vals, r.exchanger.reverse(), combine_op=np.add)
    multiplicity = np.zeros(16)
    for i in r.partition:
        np.add.at(multiplicity, i.hid_to_gid, 1.0)
    for i, v in zip(r.partition, vals):
        got_owned = np.asarray(v)[i.oid_to_lid]
        assert np.array_equal(got_owned, 1.0 + multiplicity[i.oid_to_gid])


def test_prange_dispatcher_and_equality():
    parts = parts4()
    a = pa.prange(parts, 10)
    b = pa.uniform_partition(parts, 10)
    assert pa.oids_are_equal(a, b) and pa.hids_are_equal(a, b) and pa.prange_eq(a, b)
    c = pa.prange(parts22(), (4, 4), pa.with_ghost)
    assert c.ghost and len(c) == 16
    noids = pa.map_parts(lambda p: p + 1, parts)
    d = pa.prange(parts, noids)
    assert len(d) == 10
    assert not pa.prange_eq(a, d)


def test_empty_exchanger_and_buffers():
    parts = parts4()
    e = pa.empty_exchanger(parts)
    assert [len(x) for x in e.parts_rcv] == [0, 0, 0, 0]
    r = pa.cartesian_partition(parts22(), (4, 4), pa.with_ghost)
    buf = pa.allocate_rcv_buffer(np.float32, r.exchanger)
    assert buf.get_part(0).data.dtype == np.float32
    assert int(buf.get_part(0).ptrs[-1]) == 5
