"""Golden-fixture conformance suite.

The reference's full-surface integration test carries hand-built 4-part
fixtures with exact expected values (reference: test/test_interfaces.jl).
SURVEY.md §4 calls these "golden data worth porting verbatim" — this file
is that port, translated once to 0-based ids (parts 1..4 -> 0..3,
gids 1..10 -> 0..9).  Where the reference checks Cartesian gid tables it
assumes Julia's column-major numbering; this framework numbers C-order, so
those fixtures live (re-derived) in test_prange.py instead.
"""
import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa


@pytest.fixture
def parts():
    return pa.sequential.get_part_ids(4)


@pytest.fixture
def parts22():
    return pa.sequential.get_part_ids((2, 2))


# ---------------------------------------------------------------------------
# the asymmetric 4-part neighbor graph (reference: test_interfaces.jl:19-63)
# ---------------------------------------------------------------------------

PARTS_RCV = [[1, 2], [3], [0, 1], [0, 2]]
PARTS_SND = [[2, 3], [0, 2], [0, 3], [1]]
# data_snd = 10*(part+1) per neighbor -> each receiver sees its senders' tags
EXPECTED_RCV = [[20, 30], [40], [10, 20], [10, 30]]


def _graph(parts):
    rcv = pa.map_parts(lambda p: np.array(PARTS_RCV[p]), parts)
    snd = pa.map_parts(lambda p: np.array(PARTS_SND[p]), parts)
    return rcv, snd


def test_exchange_fixed_size_golden(parts):
    rcv, snd = _graph(parts)
    data_snd = pa.map_parts(lambda s, p: np.full(len(s), 10 * (p + 1)), snd, parts)
    data_rcv = pa.exchange(data_snd, rcv, snd)
    for p, got in enumerate(data_rcv.part_values()):
        assert list(got) == EXPECTED_RCV[p]


def test_async_exchange_golden(parts):
    rcv, snd = _graph(parts)
    data_snd = pa.map_parts(lambda s, p: np.full(len(s), 10 * (p + 1)), snd, parts)
    data_rcv, t = pa.async_exchange(data_snd, rcv, snd)
    pa.schedule_and_wait(t)
    for p, got in enumerate(data_rcv.part_values()):
        assert list(got) == EXPECTED_RCV[p]


def test_discover_parts_snd_golden(parts):
    rcv, _ = _graph(parts)
    snd2 = pa.discover_parts_snd(rcv)
    for p, got in enumerate(snd2.part_values()):
        assert sorted(got) == PARTS_SND[p]


# ---------------------------------------------------------------------------
# reductions and scans (reference: test_interfaces.jl:65-124)
# ---------------------------------------------------------------------------


def test_reduce_golden(parts):
    one_based = pa.map_parts(lambda p: p + 1, parts)
    a = pa.reduce_main(lambda x, y: x + y, one_based, 0)
    assert pa.get_main_part(a) == 1 + 2 + 3 + 4
    b = pa.reduce_all(lambda x, y: x + y, one_based, 0)
    assert all(v == 10 for v in b.part_values())
    assert pa.preduce(lambda x, y: x + y, one_based, 0) == 10
    assert pa.sum_parts(one_based) == 10


SCAN_IN = [4, 2, 6, 3]


def test_iscan_golden(parts):
    a = pa.map_parts(lambda p: SCAN_IN[p], parts)
    b = pa.iscan(lambda x, y: x + y, a, 0)
    assert list(b.part_values()) == [4, 6, 12, 15]
    b, n = pa.iscan(lambda x, y: x + y, a, 0, with_total=True)
    assert n == 15
    b, n = pa.iscan_all(lambda x, y: x + y, a, 0, with_total=True)
    assert n == 15
    for v in b.part_values():
        assert list(v) == [4, 6, 12, 15]


def test_xscan_golden(parts):
    a = pa.map_parts(lambda p: SCAN_IN[p], parts)
    b = pa.xscan(lambda x, y: x + y, a, 1)
    assert list(b.part_values()) == [1, 5, 7, 13]
    b, n = pa.xscan(lambda x, y: x + y, a, 1, with_total=True)
    assert n == 16
    b, n = pa.xscan_all(lambda x, y: x + y, a, 1, with_total=True)
    assert n == 16
    for v in b.part_values():
        assert list(v) == [1, 5, 7, 13]


# ---------------------------------------------------------------------------
# the 10-gid 4-part IndexSet partition + Exchanger plan
# (reference: test_interfaces.jl:177-207) — layout-independent golden data
# ---------------------------------------------------------------------------

LID_TO_GID = [
    [0, 1, 2, 4, 6, 7],
    [1, 3, 4, 9],
    [5, 6, 7, 4, 3, 9],
    [0, 2, 6, 8, 9],
]
LID_TO_PART = [
    [0, 0, 0, 1, 2, 2],
    [0, 1, 1, 3],
    [2, 2, 2, 1, 1, 3],
    [0, 0, 2, 3, 3],
]
# exact expected plan (0-based translation of :191-207)
EXP_PARTS_SND = [[1, 3], [0, 2], [0, 3], [1, 2]]
EXP_LIDS_SND = [
    [[1], [0, 2]],
    [[2], [2, 1]],
    [[1, 2], [1]],
    [[4], [4]],
]
NGIDS = 10


@pytest.fixture
def partition(parts):
    return pa.map_parts(
        lambda p: pa.IndexSet(p, LID_TO_GID[p], LID_TO_PART[p]), parts
    )


def test_exchanger_plan_golden(parts, partition):
    ex = pa.Exchanger.from_partition(partition)
    for p in range(4):
        snd = list(ex.parts_snd.part_values()[p])
        lids = [list(t) for t in ex.lids_snd.part_values()[p]]
        got = dict(zip(snd, lids))
        want = dict(zip(EXP_PARTS_SND[p], EXP_LIDS_SND[p]))
        assert got == want


def test_exchanger_halo_update_golden(parts, partition):
    ex = pa.Exchanger.from_partition(partition)

    def mk(p, iset):
        v = np.zeros(iset.num_lids)
        owners = np.asarray(iset.lid_to_part)
        v[owners == p] = 10.0 * (p + 1)
        return v

    values = pa.map_parts(mk, parts, partition)
    pa.exchange_values(values, ex)
    for p, (v, iset) in enumerate(zip(values.part_values(), partition.part_values())):
        owners = np.asarray(iset.lid_to_part)
        assert np.array_equal(v, 10.0 * (owners + 1))


def test_exchanger_explicit_buffers_golden(parts, partition):
    # reference :229-251 — rcv-side untouched at owned lids, overwritten at
    # ghosts; snd buffer never mutated
    ex = pa.Exchanger.from_partition(partition)
    values_rcv = pa.map_parts(lambda i: np.full(i.num_lids, 10.0), partition)
    values_snd = pa.map_parts(lambda i: np.full(i.num_lids, 20.0), partition)
    pa.exchange_values(values_rcv, values_snd, ex)
    for p, (v, iset) in enumerate(zip(values_rcv.part_values(), partition.part_values())):
        owners = np.asarray(iset.lid_to_part)
        assert np.all(v[owners == p] == 10.0)
        assert np.all(v[owners != p] == 20.0)
    for v in values_snd.part_values():
        assert np.all(v == 20.0)


def test_exchanger_table_payload_golden(parts, partition):
    # ragged per-lid payloads (reference :253-274): 3 values per lid,
    # 100*(part+1) + 10*(gid+1) + (i+1), stamped by owners only
    ex = pa.Exchanger.from_partition(partition)

    def mk(p, iset):
        rows = []
        owners = np.asarray(iset.lid_to_part)
        gids = np.asarray(iset.lid_to_gid)
        for lid in range(iset.num_lids):
            if owners[lid] == p:
                rows.append(
                    [100 * (p + 1) + 10 * (int(gids[lid]) + 1) + i for i in (1, 2, 3)]
                )
            else:
                rows.append([0, 0, 0])
        return pa.Table.from_rows(rows)

    values = pa.map_parts(mk, parts, partition)
    pa.exchange_values(values, ex)
    for p, (t, iset) in enumerate(zip(values.part_values(), partition.part_values())):
        owners = np.asarray(iset.lid_to_part)
        gids = np.asarray(iset.lid_to_gid)
        for lid in range(iset.num_lids):
            want = [
                100 * (int(owners[lid]) + 1) + 10 * (int(gids[lid]) + 1) + i
                for i in (1, 2, 3)
            ]
            assert list(t[lid]) == want


def test_exchanger_reverse_assembly_golden(parts, partition):
    # reference :276-287: stamp 10*(part+1) on EVERY lid, push ghost copies
    # to owners with +, then forward-exchange. Owner value of gid g ends as
    # 10 * sum over holders of g of (holder+1); ghosts mirror owners.
    ex_rcv = pa.Exchanger.from_partition(partition)
    ex_snd = ex_rcv.reverse()
    values = pa.map_parts(lambda p, i: np.full(i.num_lids, 10.0 * (p + 1)), parts, partition)
    pa.exchange_values(values, ex_snd, combine=np.add)
    pa.exchange_values(values, ex_rcv)

    holders = {g: [] for g in range(NGIDS)}
    for p in range(4):
        for g in LID_TO_GID[p]:
            holders[g].append(p)
    for p, (v, iset) in enumerate(zip(values.part_values(), partition.part_values())):
        gids = np.asarray(iset.lid_to_gid)
        for lid, g in enumerate(gids):
            assert v[lid] == 10.0 * sum(q + 1 for q in holders[int(g)])


# ---------------------------------------------------------------------------
# PRange over the explicit partition (reference :289-372)
# ---------------------------------------------------------------------------


def test_prange_from_explicit_partition(parts, partition):
    ids = pa.PRange(NGIDS, partition)
    assert ids.num_parts == 4
    assert len(ids) == NGIDS
    ids2 = ids.copy()
    assert ids2 is not ids and ids2.partition is not ids.partition
    assert pa.prange_eq(ids, ids2)
    for iset in ids.partition.part_values():
        np.testing.assert_array_equal(
            pa.get_lid_to_gid(iset), np.asarray(iset.lid_to_gid)
        )
        np.testing.assert_array_equal(
            pa.get_lid_to_part(iset), np.asarray(iset.lid_to_part)
        )
        np.testing.assert_array_equal(
            pa.get_oid_to_lid(iset), np.asarray(iset.oid_to_lid)
        )
        np.testing.assert_array_equal(
            pa.get_hid_to_lid(iset), np.asarray(iset.hid_to_lid)
        )


GIDS_GHOSTS = [[0, 3, 5], [2, 0, 1, 7], [0, 8, 5], [2, 1, 7, 9]]
TOUCHED = [[3, 5], [0, 1], [0, 8], [2]]


def test_add_gids_and_touched_hids_golden(parts):
    ids2 = pa.uniform_partition(parts, NGIDS)
    assert not ids2.ghost
    gids = pa.map_parts(lambda p: np.array(GIDS_GHOSTS[p]), parts)
    owners = pa.map_parts(lambda g: ids2.gid_to_part(g), gids)
    ids3 = pa.add_gids(ids2, gids, owners)
    assert ids3.ghost
    ids3b = pa.add_gids(ids2, gids)  # owner lookup derived from gid_to_part
    assert pa.prange_eq(ids3, ids3b)

    gids2 = pa.map_parts(lambda p: np.array(TOUCHED[p]), parts)
    hids = pa.touched_hids(ids3, gids2)
    for h, g2, iset in zip(
        hids.part_values(), gids2.part_values(), ids3.partition.part_values()
    ):
        lids = np.asarray(iset.hid_to_lid)[np.asarray(h)]
        np.testing.assert_array_equal(np.asarray(iset.lid_to_gid)[lids], g2)

    # round-trip renumbering (reference :346-347)
    pa.to_lids(ids3, gids)
    pa.to_gids(ids3, gids)
    for g, want in zip(gids.part_values(), GIDS_GHOSTS):
        assert list(g) == want


def test_variable_partition_golden(parts):
    a = pa.map_parts(lambda p: SCAN_IN[p], parts)
    ids5 = pa.variable_partition(parts, a)
    want_gids = [[0, 1, 2, 3], [4, 5], [6, 7, 8, 9, 10, 11], [12, 13, 14]]
    for p, iset in enumerate(ids5.partition.part_values()):
        assert list(iset.lid_to_gid) == want_gids[p]
    want_owner = [0, 0, 0, 0, 1, 1, 2, 2, 2, 2, 2, 2, 3, 3, 3]
    np.testing.assert_array_equal(ids5.gid_to_part(np.arange(15)), want_owner)


# ---------------------------------------------------------------------------
# PVector over the golden partition (reference :501-643 highlights)
# ---------------------------------------------------------------------------


def test_pvector_coo_over_ghosted_range(parts):
    ids2 = pa.uniform_partition(parts, NGIDS)
    gids = pa.map_parts(lambda p: np.array(GIDS_GHOSTS[p]), parts)
    ids3 = pa.add_gids(ids2, gids)
    v = pa.pvector(
        pa.map_parts(np.copy, gids),
        pa.map_parts(lambda g: g.astype(float), gids),
        ids3,
        ids="global",
    )
    u = 2.0 * v
    for uv, vv in zip(u.values.part_values(), v.values.part_values()):
        np.testing.assert_array_equal(uv, 2 * vv)
    u = v + u
    for uv, vv in zip(u.values.part_values(), v.values.part_values()):
        np.testing.assert_array_equal(uv, 3 * vv)

    # reductions over OWNED entries (reference :513-520): each owned gid
    # appears with value == its gid where touched, else 0
    assert v.any(lambda i: i > 4)
    assert not v.any(lambda i: i > 10)
    assert v.all(lambda i: i < 10)
    assert not v.all(lambda i: i < 4)
    assert v.maximum() == 9  # gid 9 accumulated once
    assert v.minimum() == 0
    assert v.maximum(lambda i: i - 1) == 8
    assert v.minimum(lambda i: i - 1) == -1

    w = v.copy()
    w.scale(-1.0)
    assert (v + w).all(lambda i: i == 0)
    assert w == w
    assert w != v
    assert pa.sqeuclidean(w, v) == pytest.approx((w - v).norm() ** 2)
    assert pa.euclidean(w, v) == pytest.approx((w - v).norm())


def test_axis_compat_predicates(parts):
    ids2 = pa.uniform_partition(parts, NGIDS)
    gids = pa.map_parts(lambda p: np.array(GIDS_GHOSTS[p]), parts)
    ids3 = pa.add_gids(ids2, gids)
    u = pa.pvector(1.0, ids2)
    w = pa.pvector(3.0, ids3)
    assert pa.oids_are_equal(u.rows, u.rows)
    assert pa.hids_are_equal(u.rows, u.rows)
    assert pa.lids_are_equal(u.rows, u.rows)
    assert pa.oids_are_equal(u.rows, w.rows)
    assert not pa.hids_are_equal(u.rows, w.rows)
    assert not pa.lids_are_equal(u.rows, w.rows)


# ---------------------------------------------------------------------------
# the COO PSparseMatrix fixture (reference :686-733), 0-based
# ---------------------------------------------------------------------------

COO_I = [[0, 1, 0, 1], [2, 2, 3], [4, 4, 5, 6], [8, 8, 7, 9]]
COO_J = [[1, 5, 0, 1], [2, 7, 3], [4, 5, 5, 6], [8, 1, 7, 9]]
COO_V = [
    [1.0, 2.0, 30.0, 10.0],
    [10.0, 2.0, 30.0],
    [10.0, 2.0, 30.0, 1.0],
    [10.0, 2.0, 30.0, 50.0],
]


def _golden_matrix(parts):
    I = pa.map_parts(lambda p: np.array(COO_I[p]), parts)
    J = pa.map_parts(lambda p: np.array(COO_J[p]), parts)
    V = pa.map_parts(lambda p: np.array(COO_V[p]), parts)
    return pa.PSparseMatrix.from_coo(I, J, V, NGIDS, NGIDS, ids="global")


def _dense_golden():
    M = np.zeros((NGIDS, NGIDS))
    for I, J, V in zip(COO_I, COO_J, COO_V):
        for i, j, v in zip(I, J, V):
            M[i, j] += v
    return M


def test_golden_matrix_spmv(parts):
    A = _golden_matrix(parts)
    pa.local_view(A)
    pa.global_view(A)
    x = pa.pvector(1.0, A.cols)
    y = A @ x
    want = _dense_golden() @ np.ones(NGIDS)
    got = pa.gather_pvector(y)
    np.testing.assert_allclose(got, want)
    dy = y - y
    assert dy.norm() == 0.0


def test_matrix_views_read_write(parts):
    A = _golden_matrix(parts)
    dense = _dense_golden()
    gv = pa.global_view(A)
    # part 0 owns global rows 0-2 (uniform 10 over 4 parts: sizes 2,2,3,3)
    g0 = gv.part_values()[0]
    assert g0[0, 1] == dense[0, 1]
    assert g0[0, 5] == 0.0  # local (ghost col) but not stored -> 0 read
    g0[0, 1] = 7.0
    g0.add(0, 1, 1.0)
    assert g0[0, 1] == 8.0
    g0[0, 1] = dense[0, 1]
    with pytest.raises(Exception):
        g0[0, 5] = 1.0  # write-guard on unstored entry
    with pytest.raises(Exception):
        g0[0, 3]  # gid not local on this part

    lv = pa.local_view(A, A.rows, A.cols)
    l0 = lv.part_values()[0]
    r0 = A.rows.partition.part_values()[0]
    c0 = A.cols.partition.part_values()[0]
    gi, gj = np.asarray(r0.lid_to_gid), np.asarray(c0.lid_to_gid)
    for li in range(min(2, r0.num_lids)):
        for lj in range(c0.num_lids):
            assert l0[li, lj] == dense[gi[li], gj[lj]]


def test_num_free_functions(parts, partition):
    ids = pa.PRange(NGIDS, partition)
    assert pa.num_gids(ids) == NGIDS
    assert list(pa.num_lids(ids)) == [6, 4, 6, 5]
    assert list(pa.num_oids(ids)) == [3, 2, 3, 2]
    assert list(pa.num_hids(ids)) == [3, 2, 3, 3]
    iset = partition.part_values()[0]
    assert pa.num_lids(iset) == 6 and pa.num_oids(iset) == 3


def test_golden_matrix_solves(parts):
    A = _golden_matrix(parts)
    y = pa.pvector(1.0, A.rows)

    x, info = pa.cg(A, y, tol=1e-14, maxiter=500)
    r = A @ x - y
    assert r.norm() < 1e-5  # reference runs cg unchecked (:708-712); the
    # hard 1e-9 gates below are on the direct paths, as in the reference

    x = pa.direct_solve(A, y)
    assert isinstance(x, pa.PVector)
    assert (A @ x - y).norm() < 1e-9

    factors = pa.lu(A)
    x2 = factors.solve(y)
    assert (A @ x2 - y).norm() < 1e-9
    factors = factors.refactorize(A)
    x3 = factors.solve(y)
    assert (A @ x3 - y).norm() < 1e-9


def test_cartesian_uneven_grid_golden(parts22):
    """The (5,4) grid over a (2,2) part grid — the reference's uneven-
    remainder fixture (reference: test/test_interfaces.jl:382-470),
    translated to this framework's conventions: 0-based, C-order gids
    (gid = i*ncols + j), part axes in the same C-order. The trailing part
    along the split dimension takes the remainder (5 -> 2+3), exactly as
    the reference's `_oid_to_gid` does."""
    r = pa.cartesian_partition(parts22, (5, 4))
    expected_owned = [
        [0, 1, 4, 5],
        [2, 3, 6, 7],
        [8, 9, 12, 13, 16, 17],
        [10, 11, 14, 15, 18, 19],
    ]
    assert r.ngids == 20
    for iset, want in zip(r.partition.part_values(), expected_owned):
        assert iset.oid_to_gid.tolist() == want
        assert iset.num_hids == 0

    rg = pa.cartesian_partition(parts22, (5, 4), pa.with_ghost)
    expected_lid_to_gid = [
        [0, 1, 4, 5, 2, 6, 8, 9, 10],
        [2, 3, 6, 7, 1, 5, 9, 10, 11],
        [8, 9, 12, 13, 16, 17, 4, 5, 6, 10, 14, 18],
        [10, 11, 14, 15, 18, 19, 5, 6, 7, 9, 13, 17],
    ]
    expected_owners = [
        [0, 0, 0, 0, 1, 1, 2, 2, 3],
        [1, 1, 1, 1, 0, 0, 2, 3, 3],
        [2, 2, 2, 2, 2, 2, 0, 0, 1, 3, 3, 3],
        [3, 3, 3, 3, 3, 3, 0, 1, 1, 2, 2, 2],
    ]
    for iset, gids, owners in zip(
        rg.partition.part_values(), expected_lid_to_gid, expected_owners
    ):
        assert iset.lid_to_gid.tolist() == gids
        assert iset.lid_to_part.tolist() == owners

    ci = pa.p_cartesian_indices(parts22, (5, 4))
    expected_ranges = [
        ([0, 1], [0, 1]),
        ([0, 1], [2, 3]),
        ([2, 3, 4], [0, 1]),
        ([2, 3, 4], [2, 3]),
    ]
    for p, (ri, cj) in enumerate(expected_ranges):
        got = ci.get_part(p).ranges
        assert got[0].tolist() == ri and got[1].tolist() == cj
