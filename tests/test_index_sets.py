"""L4 unit tests: index sets (pure, no parallelism).

Mirrors the reference's IndexSetsTests coverage (reference:
test/IndexSetsTests.jl:1-94) re-derived 0-based: IndexRange invariants
including mutation, lazy lookup behavior, and the explicit IndexSet.
"""
import numpy as np
import pytest

from partitionedarrays_jl_tpu import (
    CartesianGidToPart,
    ExtendedIndexRange,
    IndexRange,
    IndexSet,
    LinearGidToPart,
)


def test_index_range_basic():
    # part 1 owns gids [4, 9) with ghosts 2 (owner 0) and 11 (owner 2)
    i = IndexRange(1, 5, 4, hid_to_gid=[2, 11], hid_to_part=[0, 2])
    assert i.num_oids == 5
    assert i.num_hids == 2
    assert i.num_lids == 7
    assert list(i.lid_to_gid) == [4, 5, 6, 7, 8, 2, 11]
    assert list(i.lid_to_part) == [1, 1, 1, 1, 1, 0, 2]
    assert list(i.oid_to_lid) == [0, 1, 2, 3, 4]
    assert list(i.hid_to_lid) == [5, 6]
    assert list(i.lid_to_ohid) == [0, 1, 2, 3, 4, -1, -2]
    assert list(i.oid_to_gid) == [4, 5, 6, 7, 8]
    assert list(i.hid_to_gid) == [2, 11]
    assert list(i.hid_to_part) == [0, 2]


def test_index_range_lookup_and_renumber():
    i = IndexRange(1, 5, 4, hid_to_gid=[11, 2], hid_to_part=[2, 0])
    # vectorized gid->lid: arithmetic on owned range, search over ghosts
    assert list(i.gids_to_lids([4, 8, 11, 2, 99])) == [0, 4, 5, 6, -1]
    assert list(i.has_gids([4, 99])) == [True, False]
    ids = np.array([2, 4, 11])
    i.to_lids(ids)
    assert list(ids) == [6, 0, 5]
    i.to_gids(ids)
    assert list(ids) == [2, 4, 11]
    with pytest.raises(AssertionError):
        i.to_lids(np.array([57]))


def test_index_range_mutation():
    i = IndexRange(0, 3, 0)
    assert i.num_hids == 0
    lids = i.add_gids(np.array([5, 3, 5, 1]), np.array([1, 1, 1, 0]))
    # gid 1 is owned; 5 and 3 appended in first-touch order
    assert list(lids) == [3, 4, 3, 1]
    assert list(i.lid_to_gid) == [0, 1, 2, 5, 3]
    assert list(i.hid_to_part) == [1, 1]
    lid = i.add_gid(7, 2)
    assert lid == 5
    assert i.num_lids == 6
    with pytest.raises(AssertionError):
        i.add_gids(np.array([99]), np.array([0]))  # own part as ghost owner


def test_index_set_explicit():
    s = IndexSet(2, lid_to_gid=[7, 3, 9, 0], lid_to_part=[2, 1, 2, 0])
    # owned/ghost derived from lid_to_part
    assert list(s.oid_to_lid) == [0, 2]
    assert list(s.hid_to_lid) == [1, 3]
    assert list(s.lid_to_ohid) == [0, -1, 1, -2]
    assert list(s.oid_to_gid) == [7, 9]
    assert list(s.hid_to_gid) == [3, 0]
    assert list(s.gids_to_lids([9, 3, 4])) == [2, 1, -1]


def test_index_set_touched_hids():
    s = IndexSet(2, lid_to_gid=[7, 3, 9, 0], lid_to_part=[2, 1, 2, 0])
    # gids touch ghost 0 (hid 1) then ghost 3 (hid 0); dedup first-touch
    assert list(s.touched_hids([0, 9, 0, 3, 42])) == [1, 0]


def test_find_lid_map():
    a = IndexSet(0, lid_to_gid=[4, 2, 7], lid_to_part=[0, 0, 1])
    b = IndexSet(0, lid_to_gid=[7, 4, 2, 9], lid_to_part=[1, 0, 0, 0])
    assert list(a.find_lid_map(b)) == [1, 2, 0]


def test_extended_index_range():
    e = ExtendedIndexRange(
        0, noids=3, firstgid=0, lid_to_gid=[0, 1, 2, 8], lid_to_part=[0, 0, 0, 1]
    )
    assert e.num_oids == 3
    assert list(e.gids_to_lids([8, 1])) == [3, 1]
    assert e.noids_range == (0, 3)


def test_linear_gid_to_part():
    g2p = LinearGidToPart(10, np.array([0, 2, 4, 7]))
    assert list(g2p(np.arange(10))) == [0, 0, 1, 1, 2, 2, 2, 3, 3, 3]


def test_cartesian_gid_to_part():
    # 4x4 cells, 2x2 parts, balanced: each part owns a 2x2 box (C-order)
    g2p = CartesianGidToPart((4, 4), (np.array([0, 2]), np.array([0, 2])))
    expected = np.array(
        [
            [0, 0, 1, 1],
            [0, 0, 1, 1],
            [2, 2, 3, 3],
            [2, 2, 3, 3],
        ]
    ).ravel()
    assert list(g2p(np.arange(16))) == list(expected)


def test_index_set_equality_helpers():
    a = IndexSet(0, [0, 1, 5], [0, 0, 1])
    b = IndexSet(0, [0, 1, 5], [0, 0, 1])
    c = IndexSet(0, [0, 1, 6], [0, 0, 1])
    assert a.oids_eq(b) and a.hids_eq(b) and a.lids_eq(b)
    assert a.oids_eq(c) and not a.hids_eq(c) and not a.lids_eq(c)
