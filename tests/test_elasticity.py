"""Unstructured tet elasticity (BASELINE configs[4]): mesh/element sanity,
partition-independent assembly over an irregular Morton ghost graph, and
the end-to-end PCG gate (reference tolerance: test/test_fem_sa.jl:137)."""
import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu.models.elasticity_tet import (
    assemble_elasticity_tet,
    elasticity_tet_driver,
    morton_permutation,
    p1_elasticity_ke,
    tet_mesh,
)


def test_tet_mesh_conforming_and_positive():
    coords, tets, boundary = tet_mesh((4, 5, 3), jitter=0.15, seed=3)
    e = coords[tets[:, 1:]] - coords[tets[:, :1]]
    vols = np.linalg.det(e) / 6.0
    assert (vols > 0).all()
    # the tet volumes tile the hex cells exactly (conforming split)
    assert np.isclose(vols.sum(), 3.0 * 4.0 * 2.0)
    # boundary nodes kept unjittered on the box faces
    assert np.array_equal(
        coords[boundary], np.round(coords[boundary])
    )


def test_element_stiffness_symmetric_with_rigid_nullspace():
    coords, tets, _ = tet_mesh((3, 3, 3), jitter=0.2, seed=1)
    ke = p1_elasticity_ke(coords, tets)
    assert np.allclose(ke, np.swapaxes(ke, 1, 2))
    # translations and infinitesimal rotations produce zero force
    for e in (0, len(tets) // 2, len(tets) - 1):
        X = coords[tets[e]]
        rig = np.zeros((12, 6))
        for a in range(4):
            rig[3 * a : 3 * a + 3, :3] = np.eye(3)
            x, y, z = X[a]
            rig[3 * a : 3 * a + 3, 3:] = np.array(
                [[0, -z, y], [z, 0, -x], [-y, x, 0]]
            )
        assert np.abs(ke[e] @ rig).max() < 1e-12
        # PSD apart from the 6 rigid modes
        w = np.linalg.eigvalsh(ke[e])
        assert w[:6].max() < 1e-11 and w[6] > 1e-11


def test_morton_blocks_are_irregular_neighbor_graph():
    coords, _, _ = tet_mesh((6, 6, 6), jitter=0.1, seed=0)
    perm = morton_permutation(coords)
    assert np.array_equal(np.sort(perm), np.arange(len(coords)))

    def driver(parts):
        A, b, xh, x0 = assemble_elasticity_tet(parts, (6, 6, 6))
        ex = A.cols.exchanger
        nn = [len(np.asarray(p)) for p in ex.parts_rcv.part_values()]
        counts = [
            np.diff(np.asarray(t.ptrs)) for t in ex.lids_rcv.part_values()
        ]
        return nn, counts

    nn, counts = pa.prun(driver, pa.sequential, 4)
    # every part has at least 2 neighbors and the per-neighbor message
    # sizes are NOT all equal: a genuinely variable-size exchange
    assert min(nn) >= 2
    sizes = np.concatenate([c for c in counts if len(c)])
    assert sizes.min() >= 1 and len(np.unique(sizes)) > 1


def test_assembly_partition_independent():
    def rhs(nparts):
        def d(parts):
            A, b, xh, x0 = assemble_elasticity_tet(parts, (4, 4, 4))
            return pa.gather_pvector(b), pa.gather_pvector(A @ xh)
        return pa.prun(d, pa.sequential, nparts)

    b1, ax1 = rhs(1)
    b4, ax4 = rhs(4)
    b6, ax6 = rhs(6)
    np.testing.assert_allclose(b4, b1, rtol=0, atol=1e-13)
    np.testing.assert_allclose(b6, b1, rtol=0, atol=1e-13)
    np.testing.assert_allclose(ax4, ax1, rtol=0, atol=1e-13)


@pytest.mark.parametrize("nparts", [4, 7])
def test_elasticity_end_to_end(nparts):
    err, info = pa.prun(
        lambda parts: elasticity_tet_driver(parts, (5, 5, 5)),
        pa.sequential,
        nparts,
    )
    assert info["converged"]
    assert err < 1e-5


def test_elasticity_tpu_matches_sequential():
    """Config-5 on the compiled path: the same unstructured system solved
    under the TPU backend must match the sequential oracle."""
    def d(backend):
        def driver(parts):
            A, b, xh, x0 = assemble_elasticity_tet(parts, (5, 5, 5))
            x, info = pa.pcg(A, b, x0=x0, tol=1e-12, maxiter=500)
            return pa.gather_pvector(x), info["iterations"]
        return pa.prun(driver, backend, 4)

    xs, it_s = d(pa.sequential)
    xt, it_t = d(pa.tpu)
    assert it_t == it_s
    np.testing.assert_allclose(xt, xs, rtol=0, atol=1e-10)


def test_irregular_lowerings_engage_and_match():
    """The irregular-graph fast paths: the tet-elasticity operator
    lowers to the supernode-dense (SD) MXU path by default (round 4),
    to 3x3 node-block BSR with PA_TPU_SD=0, and to padded ELL with both
    off; all three products must match each other and the host oracle
    to rounding."""
    import os

    from partitionedarrays_jl_tpu.parallel.tpu import (
        DeviceMatrix, DeviceVector, device_matrix, make_spmv_fn,
    )

    def driver(parts):
        A, b, xh, x0 = assemble_elasticity_tet(parts, (4, 4, 4))
        backend = parts.backend
        dA = device_matrix(A, backend)
        assert dA.sd_bs == 3 and dA.bsr_bs is None, (dA.sd_bs, dA.bsr_bs)
        dx = DeviceVector.from_pvector(xh, backend, dA.col_layout)
        y_sd = np.asarray(make_spmv_fn(dA)(dx.data))
        os.environ["PA_TPU_SD"] = "0"
        try:
            dA_bsr = DeviceMatrix(A, backend)
            assert dA_bsr.bsr_bs == 3, dA_bsr.bsr_bs
            dxb = DeviceVector.from_pvector(xh, backend, dA_bsr.col_layout)
            y_bsr = np.asarray(make_spmv_fn(dA_bsr)(dxb.data))
            os.environ["PA_TPU_BSR"] = "0"
            try:
                dA_ell = DeviceMatrix(A, backend)
            finally:
                del os.environ["PA_TPU_BSR"]
        finally:
            del os.environ["PA_TPU_SD"]
        assert dA_ell.bsr_bs is None and dA_ell.sd_bs is None
        dx2 = DeviceVector.from_pvector(xh, backend, dA_ell.col_layout)
        y_ell = np.asarray(make_spmv_fn(dA_ell)(dx2.data))
        np.testing.assert_allclose(y_bsr, y_ell, rtol=1e-12, atol=1e-12)
        np.testing.assert_allclose(y_sd, y_ell, rtol=1e-10, atol=1e-10)
        host = pa.gather_pvector(A @ xh)
        got = np.zeros_like(host)
        for p, iset in enumerate(A.rows.partition.part_values()):
            got[np.asarray(iset.oid_to_gid)] = y_sd[p, : iset.num_oids]
        np.testing.assert_allclose(got, host, rtol=1e-10, atol=1e-10)
        return True

    assert pa.prun(driver, pa.tpu, 4)


def test_oh_node_block_path_engages_and_matches():
    """Round-4 directive 7: on a multi-part irregular lowering the A_oh
    boundary block must take the node-block gather path (one index per
    ghost NODE), not per-element ELL — and match the ELL-forced product
    and the host oracle."""
    import os

    from partitionedarrays_jl_tpu.parallel.tpu import (
        DeviceMatrix, DeviceVector, device_matrix, make_spmv_fn,
    )

    def driver(parts):
        A, b, xh, x0 = assemble_elasticity_tet(parts, (4, 4, 4))
        backend = parts.backend
        dA = device_matrix(A, backend)
        assert dA.oh_nnz > 0, "multi-part run must have boundary coupling"
        assert dA.ohb_bs == 3, "node-block A_oh did not engage"
        assert dA.oh_vals is None, "ELL A_oh staged alongside node-block"
        dx = DeviceVector.from_pvector(xh, backend, dA.col_layout)
        y_blk = np.asarray(make_spmv_fn(dA)(dx.data))
        os.environ["PA_TPU_SD"] = "0"
        os.environ["PA_TPU_BSR"] = "0"
        try:
            dA_ell = DeviceMatrix(A, backend)
        finally:
            del os.environ["PA_TPU_SD"], os.environ["PA_TPU_BSR"]
        assert dA_ell.ohb_bs is None and dA_ell.oh_vals is not None
        dx2 = DeviceVector.from_pvector(xh, backend, dA_ell.col_layout)
        y_ell = np.asarray(make_spmv_fn(dA_ell)(dx2.data))
        np.testing.assert_allclose(y_blk, y_ell, rtol=1e-10, atol=1e-10)
        host = pa.gather_pvector(A @ xh)
        got = np.zeros_like(host)
        for p, iset in enumerate(A.rows.partition.part_values()):
            got[np.asarray(iset.oid_to_gid)] = y_blk[p, : iset.num_oids]
        np.testing.assert_allclose(got, host, rtol=1e-10, atol=1e-10)
        return True

    assert pa.prun(driver, pa.tpu, 4)


def test_sd_width_buckets():
    """Round-5 directive 3: the SD lowering pads contiguous group
    chunks to their own union maximum (one einsum per bucket) instead
    of one global width. A mesh big enough for several groups must
    produce >1 bucket, the bucketed widths must never exceed the global
    maximum, and the product must still match the host oracle."""
    from partitionedarrays_jl_tpu.parallel.tpu import (
        DeviceVector, device_matrix, make_spmv_fn,
    )

    def driver(parts):
        A, b, xh, x0 = assemble_elasticity_tet(parts, (8, 8, 8))
        backend = parts.backend
        dA = device_matrix(A, backend)
        assert dA.sd_bs == 3, dA.sd_bs
        assert len(dA.sd_idx) == len(dA.sd_vals) > 1, len(dA.sd_idx)
        widths = [v.shape[-1] for v in dA.sd_vals]
        # the bucketed form must actually SAVE padding: the old global
        # width padded every group to (G + global emax); at least one
        # bucket must come out strictly narrower
        bs, G = dA.sd_bs, dA.sd_g
        emax_global = max(i.shape[-1] for i in dA.sd_idx)
        global_width = (G + emax_global) * bs
        assert max(widths) == global_width, (widths, global_width)
        assert min(widths) < global_width, (widths, global_width)
        dx = DeviceVector.from_pvector(xh, backend, dA.col_layout)
        y = np.asarray(make_spmv_fn(dA)(dx.data))
        host = pa.gather_pvector(A @ xh)
        got = np.zeros_like(host)
        for p, iset in enumerate(A.rows.partition.part_values()):
            got[np.asarray(iset.oid_to_gid)] = y[p, : iset.num_oids]
        np.testing.assert_allclose(got, host, rtol=1e-10, atol=1e-10)
        return True

    assert pa.prun(driver, pa.tpu, 1)
