"""Structural scalability: the per-part cost drivers of the halo exchange
(neighbor count, message sizes, ppermute color rounds) must stay constant
as the part grid grows at fixed per-part volume — the property behind the
reference's strong-scaling claim (reference: README.md:49-63)."""
import numpy as np

import partitionedarrays_jl_tpu as pa


def _halo_stats(pgrid, cells_per_part):
    ns = tuple(p * c for p, c in zip(pgrid, cells_per_part))

    def driver(parts):
        rows = pa.cartesian_partition(parts, ns, pa.with_ghost)
        ex = rows.exchanger
        nn, msg = [], []
        for prcv, t in zip(
            ex.parts_rcv.part_values(), ex.lids_rcv.part_values()
        ):
            nn.append(len(np.asarray(prcv)))
            msg.append(int(t.ptrs[-1]))
        return max(nn), max(msg)

    return pa.prun(driver, pa.sequential, pgrid)


def test_halo_cost_constant_per_part():
    cells = (6, 6, 6)
    nn2, msg2 = _halo_stats((2, 2, 2), cells)
    nn3, msg3 = _halo_stats((3, 3, 3), cells)
    # interior parts of the 3^3 grid have the full 26-neighbor stencil;
    # growing the grid further must not grow either quantity
    # full 26-neighbor stencil; ghost shell of a 6^3 block is 8^3 - 6^3
    assert nn3 == 26 and msg3 == (6 + 2) ** 3 - 6 ** 3
    nn4, msg4 = _halo_stats((4, 4, 4), cells)
    assert nn4 == nn3
    assert msg4 == msg3


def test_exchange_rounds_bounded_by_neighbor_colors():
    """The compiled exchange lowers to one ppermute per color; for a 3-D
    halo graph the color count is bounded by the neighbor count (26), not
    by the part count."""
    from partitionedarrays_jl_tpu.parallel.tpu import device_exchange_plan

    def driver(parts):
        rows = pa.cartesian_partition(parts, (8, 8, 8), pa.with_ghost)
        return device_exchange_plan(rows, False).R

    assert pa.prun(driver, pa.tpu, (2, 2, 2)) <= 26
