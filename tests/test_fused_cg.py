"""The fused streaming CG body (`make_cg_fn(fused=True)`, the
``PA_TPU_FUSED_CG`` default outside strict-bits).

The fusion's three contracts, each pinned here:

* **Trajectory identity.** Every scalar follows the textbook recurrence
  on the same dots in the same order, so the iterate sequence matches
  the standard (unfused) body — bit-for-bit under strict-bits
  arithmetic, where the unfused body is the oracle. Pinned on the
  asymmetric 4-part conformance partition (the 10-gid fixture of
  test_conformance.py / reference test_interfaces.jl:177-207), whose
  ghost graph exercises the generic exchange plan.
* **Collective parity.** The fused body restructures the VECTOR sweeps;
  it must not add collectives (the preconditioned pair of reductions
  actually shares one all_gather). Asserted on the lowered HLO of the
  compiled programs — the same A/B discipline the round-1 in-graph
  health guard was verified with.
* **Kernel fold parity.** On the padded coded frame the direction
  update rides the Pallas kernel's window pass (`_padded_kernel`
  has_pfold); validated on CPU through the Pallas interpreter exactly
  like the other padded-frame tests.
"""
import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu.models import (
    assemble_poisson,
    gather_pvector,
    jacobi_preconditioner,
)
from partitionedarrays_jl_tpu.parallel.tpu import (
    DeviceVector,
    TPUBackend,
    _b_on_cols_layout,
    device_matrix,
    make_cg_fn,
    tpu_cg,
)


def _backend(n=8):
    import jax

    return TPUBackend(devices=jax.devices()[:n])


def test_fused_cg_matches_standard_device_loop():
    """Default mode, f64: identical iteration counts, residual history to
    tight rounding, solutions to rounding; the info dict records which
    body ran."""

    def run(fused):
        def driver(parts):
            A, b, xe, x0 = assemble_poisson(parts, (8, 8, 8))
            x, info = tpu_cg(A, b, x0=x0, tol=1e-9, maxiter=500, fused=fused)
            return gather_pvector(x), info

        return pa.prun(driver, _backend(), (2, 2, 2))

    xf, inf_f = run(True)
    xu, inf_u = run(False)
    assert inf_f["cg_body"] == "fused" and inf_u["cg_body"] == "standard"
    assert inf_f["converged"] and inf_u["converged"]
    assert inf_f["iterations"] == inf_u["iterations"]
    n = inf_u["iterations"] + 1
    np.testing.assert_allclose(
        np.asarray(inf_f["residuals"])[:n],
        np.asarray(inf_u["residuals"])[:n],
        rtol=1e-12,
    )
    np.testing.assert_allclose(np.asarray(xf), np.asarray(xu), atol=1e-10)


def test_fused_pcg_matches_standard_and_shares_gather():
    """Preconditioned fused loop: same trajectory as the standard PCG
    body (its r·z / r·r reductions ride ONE all_gather — collective
    count covered by the HLO test below)."""

    def run(fused):
        def driver(parts):
            A, b, xe, x0 = assemble_poisson(parts, (8, 8, 8))
            mv = jacobi_preconditioner(A)
            x, info = tpu_cg(
                A, b, x0=x0, tol=1e-9, maxiter=500, minv=mv, fused=fused
            )
            return gather_pvector(x), info

        return pa.prun(driver, _backend(), (2, 2, 2))

    xf, inf_f = run(True)
    xu, inf_u = run(False)
    assert inf_f["converged"] and inf_u["converged"]
    assert inf_f["iterations"] == inf_u["iterations"]
    np.testing.assert_allclose(np.asarray(xf), np.asarray(xu), atol=1e-8)


# ---------------------------------------------------------------------------
# strict-bits trajectory identity on the 4-part conformance fixture
# ---------------------------------------------------------------------------

# the 10-gid 4-part fixture (reference: test_interfaces.jl:177-207), each
# part's lids reordered owned-first (same ownership, same ghost sets, same
# neighbor graph — the block split requires owned-first local layouts)
LID_TO_GID = [
    [0, 1, 2, 4, 6, 7],
    [3, 4, 1, 9],
    [5, 6, 7, 4, 3, 9],
    [8, 9, 0, 2, 6],
]
LID_TO_PART = [
    [0, 0, 0, 1, 2, 2],
    [1, 1, 0, 3],
    [2, 2, 2, 1, 1, 3],
    [3, 3, 0, 0, 2],
]


def _fixture_spd_system(parts):
    """A symmetric positive-definite operator over the conformance
    partition: couplings only between MUTUALLY visible gid pairs (each
    owner holds the other's gid), so both triangle entries exist and the
    assembled matrix is exactly symmetric; a dominant diagonal makes it
    SPD."""
    owner = {}
    for p, (gids, ps) in enumerate(zip(LID_TO_GID, LID_TO_PART)):
        for g, q in zip(gids, ps):
            if q == p:
                owner[g] = p
    visible = [set(g) for g in LID_TO_GID]
    pairs = {
        (a, b)
        for a in range(10)
        for b in range(10)
        if a != b and b in visible[owner[a]] and a in visible[owner[b]]
    }

    def triplets(p):
        I, J, V = [], [], []
        for g, q in zip(LID_TO_GID[p], LID_TO_PART[p]):
            if q != p:
                continue
            I.append(g)
            J.append(g)
            V.append(40.0 + g)
            for b in sorted(visible[p]):
                if (g, b) in pairs:
                    I.append(g)
                    J.append(b)
                    V.append(-(1.0 + (g + b) % 3))
        return np.array(I), np.array(J), np.array(V, dtype=np.float64)

    partition = pa.map_parts(
        lambda p: pa.IndexSet(p, LID_TO_GID[p], LID_TO_PART[p]), parts
    )
    rows = pa.PRange(10, partition)
    I = pa.map_parts(lambda p: triplets(p)[0], parts)
    J = pa.map_parts(lambda p: triplets(p)[1], parts)
    V = pa.map_parts(lambda p: triplets(p)[2], parts)
    A = pa.PSparseMatrix.from_coo(I, J, V, rows, rows.copy(), ids="global")
    b = pa.PVector(
        pa.map_parts(
            lambda i: np.where(
                np.asarray(i.lid_to_part) == i.part,
                np.sin(1.0 + np.asarray(i.lid_to_gid, dtype=np.float64)),
                0.0,
            ),
            A.rows.partition,
        ),
        A.rows,
    )
    return A, b


def test_strict_bits_fused_trajectory_identity(monkeypatch):
    """Under strict-bits arithmetic the fused body must reproduce the
    unfused oracle's ITERATE SEQUENCE bit for bit: same iteration count,
    identical residual-history bits, identical solution bits — on the
    asymmetric 4-part conformance partition."""
    monkeypatch.setenv("PA_TPU_STRICT_BITS", "1")
    backend = _backend(4)

    def run(fused):
        def driver(parts):
            A, b = _fixture_spd_system(parts)
            x, info = tpu_cg(
                A, b, tol=1e-12, maxiter=200, fused=fused
            )
            return gather_pvector(x), info

        return pa.prun(driver, backend, 4)

    xf, inf_f = run(True)
    xu, inf_u = run(False)
    assert inf_f["cg_body"] == "fused" and inf_u["cg_body"] == "standard"
    assert inf_f["converged"] and inf_u["converged"]
    assert inf_f["iterations"] == inf_u["iterations"]
    assert inf_f["iterations"] > 3  # a real trajectory, not a 1-step solve
    n = inf_u["iterations"] + 1
    np.testing.assert_array_equal(
        np.asarray(inf_f["residuals"])[:n], np.asarray(inf_u["residuals"])[:n]
    )
    np.testing.assert_array_equal(np.asarray(xf), np.asarray(xu))


def test_strict_bits_default_resolves_to_standard_body(monkeypatch):
    """Strict-bits keeps the unfused body as the oracle by DEFAULT: the
    env resolution must not hand strict mode the fused form."""
    monkeypatch.setenv("PA_TPU_STRICT_BITS", "1")
    from partitionedarrays_jl_tpu.parallel.tpu import _fused_cg_enabled

    assert not _fused_cg_enabled()
    monkeypatch.delenv("PA_TPU_STRICT_BITS")
    assert _fused_cg_enabled()
    monkeypatch.setenv("PA_TPU_FUSED_CG", "0")
    assert not _fused_cg_enabled()


# ---------------------------------------------------------------------------
# HLO A/B: the fused body must not add collectives
# ---------------------------------------------------------------------------


# the shared analyzer (one definition for the whole test tree — this
# file used to carry a private regex copy; analysis.collective_counts
# keeps the identical raw-substring semantics, pinned by
# tests/test_static_analysis.py against a committed fixture)
from partitionedarrays_jl_tpu.analysis import collective_counts  # noqa: E402


def test_fused_body_no_extra_collectives():
    """Lower the fused and unfused compiled CG programs and count the
    collectives in the HLO: the fusion restructures vector sweeps only —
    per-kind collective counts must not grow (the same A/B that verified
    the in-graph health guard costs zero extra collectives)."""
    backend = _backend()

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (6, 6, 6))
        return A, b

    A, b = pa.prun(driver, backend, (2, 2, 2))
    dA = device_matrix(A, backend)
    db = _b_on_cols_layout(b, dA)
    dx0 = DeviceVector.from_pvector(
        pa.PVector.full(0.0, A.cols), backend, dA.col_layout
    )
    from partitionedarrays_jl_tpu.parallel.tpu import _matrix_operands

    ops = _matrix_operands(dA)
    fused = make_cg_fn(dA, tol=1e-9, maxiter=100, fused=True)
    unfused = make_cg_fn(dA, tol=1e-9, maxiter=100, fused=False)
    cf = collective_counts(fused, db.data, dx0.data, db.data, ops)
    cu = collective_counts(unfused, db.data, dx0.data, db.data, ops)
    assert any(cu.values()), "unfused program shows no collectives at all"
    for kind in cu:
        assert cf[kind] <= cu[kind], (kind, cf, cu)


def test_fused_pcg_fewer_gathers_than_standard():
    """The preconditioned fused body's paired r·z / r·r reduction rides
    ONE all_gather where the standard body pays two — the fused PCG
    program must show strictly fewer gathers."""
    backend = _backend()

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (6, 6, 6))
        return A, b

    A, b = pa.prun(driver, backend, (2, 2, 2))
    dA = device_matrix(A, backend)
    db = _b_on_cols_layout(b, dA)
    dx0 = DeviceVector.from_pvector(
        pa.PVector.full(0.0, A.cols), backend, dA.col_layout
    )
    from partitionedarrays_jl_tpu.parallel.tpu import _matrix_operands

    ops = _matrix_operands(dA)
    fused = make_cg_fn(dA, tol=1e-9, maxiter=100, precond=True, fused=True)
    unfused = make_cg_fn(dA, tol=1e-9, maxiter=100, precond=True, fused=False)
    cf = collective_counts(fused, db.data, dx0.data, db.data, ops)
    cu = collective_counts(unfused, db.data, dx0.data, db.data, ops)
    assert cf["all_gather"] < cu["all_gather"], (cf, cu)


# ---------------------------------------------------------------------------
# padded coded frame: the in-kernel direction fold (Pallas interpret)
# ---------------------------------------------------------------------------


def test_fused_padded_frame_kernel_fold_parity(monkeypatch):
    """Force the real-TPU padded frame on the CPU mesh: the fused CG
    then routes the direction fold through the Pallas kernel's pfold
    variant (interpret mode), and must agree with the standard body —
    same iterations, same solution to rounding."""
    import importlib

    tpu_mod = importlib.import_module("partitionedarrays_jl_tpu.parallel.tpu")
    monkeypatch.setattr(tpu_mod, "_padded_for", lambda backend: True)
    backend = _backend()

    def run(fused):
        def driver(parts):
            # f32 like the real padded flagship frame: the f64 plan
            # legitimately fails the pfold VMEM gate (doubled windows) and
            # would silently fall back to the jnp fold
            A, b, xe, x0 = assemble_poisson(
                parts, (8, 8, 8), dtype=np.float32
            )
            dA = device_matrix(A, parts.backend)
            assert dA.padded and dA.dia_mode == "coded"
            assert dA.pallas_plan is not None
            from partitionedarrays_jl_tpu.ops.pallas_dia import pfold_vmem_ok

            # the kernel fold must actually be reachable for this plan —
            # otherwise this test silently degrades to the jnp fold
            assert pfold_vmem_ok(dA.pallas_plan)
            x, info = tpu_cg(A, b, x0=x0, tol=1e-5, maxiter=500, fused=fused)
            return gather_pvector(x), info

        return pa.prun(driver, backend, (2, 2, 2))

    xf, inf_f = run(True)
    xu, inf_u = run(False)
    assert inf_f["converged"] and inf_u["converged"]
    assert inf_f["iterations"] == inf_u["iterations"]
    np.testing.assert_allclose(
        np.asarray(xf), np.asarray(xu), atol=5e-4, rtol=1e-4
    )


def test_fused_and_pipelined_mutually_exclusive():
    backend = _backend()

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (6, 6))
        return A

    A = pa.prun(driver, backend, (2, 2))
    dA = device_matrix(A, backend)
    with pytest.raises(ValueError):
        make_cg_fn(dA, tol=1e-9, maxiter=10, pipelined=True, fused=True)


def test_pcg_gmg_branch_rejects_explicit_fused():
    """The GMG-preconditioned device program compiles its own PCG body
    with no fused variant — an explicit fused flag there must raise, not
    silently run the same body twice under an A/B label."""
    backend = _backend()

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8, 8))
        h = pa.gmg_hierarchy(parts, A, (8, 8, 8), coarse_threshold=30)
        from partitionedarrays_jl_tpu.models import pcg

        with pytest.raises(ValueError, match="no fused variant"):
            pcg(A, b, x0=x0, minv=h, tol=1e-8, fused=True)
        return True

    assert pa.prun(driver, backend, (2, 2, 2))
