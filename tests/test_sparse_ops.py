"""L6 unit tests: local sparse kernels (pure, no parallelism).

Mirrors the reference's SparseUtilsTests coverage
(reference: test/SparseUtilsTests.jl:1-65): compresscoo / nzindex /
nziterator / block extraction / SpMV, over the CSR host format and the ELL
device format.
"""
import numpy as np
import pytest

from partitionedarrays_jl_tpu import (
    CSRMatrix,
    ELLMatrix,
    compresscoo,
    csr_block,
    csr_spmv,
    indextype,
    nz_triplets,
    nzindex,
    nziterator,
)


def _example():
    # 4x5 with a duplicate entry at (1, 2): 3 + 4 = 7
    I = [0, 1, 1, 1, 3, 2, 0]
    J = [0, 2, 4, 2, 1, 3, 4]
    V = [1.0, 3.0, 5.0, 4.0, 6.0, 2.0, 9.0]
    return compresscoo(I, J, V, 4, 5)


def test_compresscoo_dedup_and_sort():
    A = _example()
    assert A.shape == (4, 5)
    assert A.nnz == 6
    dense = A.toarray()
    expected = np.zeros((4, 5))
    expected[0, 0] = 1.0
    expected[0, 4] = 9.0
    expected[1, 2] = 7.0  # accumulated duplicate
    expected[1, 4] = 5.0
    expected[3, 1] = 6.0
    expected[2, 3] = 2.0
    assert np.array_equal(dense, expected)
    # columns sorted within each row
    for r in range(4):
        row = A.indices[A.indptr[r] : A.indptr[r + 1]]
        assert np.all(np.diff(row) > 0)


def test_compresscoo_custom_combine():
    A = compresscoo([0, 0], [1, 1], [3.0, 4.0], 1, 2, combine=lambda a, b: max(a, b))
    assert A.toarray()[0, 1] == 4.0


def test_compresscoo_bounds_check():
    with pytest.raises(AssertionError):
        compresscoo([5], [0], [1.0], 4, 5)


def test_nzindex():
    A = _example()
    k = nzindex(A, [1, 0, 3, 2], [2, 0, 1, 0])
    assert k[0] >= 0 and A.data[k[0]] == 7.0
    assert k[1] >= 0 and A.data[k[1]] == 1.0
    assert k[2] >= 0 and A.data[k[2]] == 6.0
    assert k[3] == -1  # not stored
    assert indextype(A) == np.int32


def test_nziterator_and_triplets():
    A = _example()
    trip = sorted(nziterator(A))
    assert trip[0] == (0, 0, 1.0)
    I, J, V = nz_triplets(A)
    assert len(I) == A.nnz
    B = compresscoo(I, J, V, *A.shape)
    assert np.array_equal(B.toarray(), A.toarray())


def test_csr_spmv_matches_dense():
    rng = np.random.default_rng(0)
    I = rng.integers(0, 30, 200)
    J = rng.integers(0, 20, 200)
    V = rng.standard_normal(200)
    A = compresscoo(I, J, V, 30, 20)
    x = rng.standard_normal(20)
    assert np.allclose(csr_spmv(A, x), A.toarray() @ x)
    y = np.ones(30)
    out = csr_spmv(A, x, y=y, alpha=2.0, beta=0.5)
    assert np.allclose(out, 0.5 * np.ones(30) + 2.0 * (A.toarray() @ x))
    assert out is y


def test_spmv_with_empty_rows():
    A = compresscoo([2], [1], [5.0], 4, 3)
    x = np.array([1.0, 2.0, 3.0])
    assert np.array_equal(csr_spmv(A, x), [0.0, 0.0, 10.0, 0.0])


def test_ell_from_csr_and_spmv():
    rng = np.random.default_rng(1)
    I = rng.integers(0, 17, 120)
    J = rng.integers(0, 11, 120)
    V = rng.standard_normal(120)
    A = compresscoo(I, J, V, 17, 11)
    E = ELLMatrix.from_csr(A)
    assert E.row_width == int(np.max(np.diff(A.indptr)))
    x = rng.standard_normal(11)
    assert np.allclose(E.spmv(x), A.toarray() @ x)
    # padded wider
    E2 = ELLMatrix.from_csr(A, row_width=E.row_width + 3)
    assert np.allclose(E2.spmv(x), A.toarray() @ x)
    with pytest.raises(AssertionError):
        ELLMatrix.from_csr(A, row_width=E.row_width - 1)


def test_csr_block_split():
    # 4x6, split cols at 4: lower has cols 0..3, upper cols 4..5 remapped
    I = [0, 0, 1, 2, 3, 3]
    J = [1, 4, 3, 5, 0, 4]
    V = [1.0, 2.0, 3.0, 4.0, 5.0, 6.0]
    A = compresscoo(I, J, V, 4, 6)
    rows = np.arange(4)
    lo = csr_block(A, rows, 4, want_upper=False)
    hi = csr_block(A, rows, 4, want_upper=True, col_offset=4)
    assert lo.shape == (4, 4) and hi.shape == (4, 2)
    d = np.zeros((4, 6))
    d[:, :4] = lo.toarray()
    d[:, 4:] = hi.toarray()
    assert np.array_equal(d, A.toarray())
    # row subset
    sub = csr_block(A, np.array([3, 0]), 6, want_upper=False)
    assert np.array_equal(sub.toarray(), A.toarray()[[3, 0], :])


def test_empty_matrix():
    A = compresscoo([], [], [], 3, 3)
    assert A.nnz == 0
    assert np.array_equal(csr_spmv(A, np.ones(3)), np.zeros(3))
    E = ELLMatrix.from_csr(A)
    assert E.row_width == 0
