"""patx — end-to-end distributed request tracing
(`partitionedarrays_jl_tpu.telemetry.tracing` + the propagation seams).

The contracts pinned here:

* **W3C traceparent hygiene** — strict parse; a fuzz sweep of
  truncated/overlong/non-hex/zero-id/bad-version headers over the live
  HTTP surface never 500s: each one mints a fresh trace and bumps
  `gate.traceparent_invalid`.
* **One span tree per request** — in-process gate submit → drain yields
  rpc.request → gate.queue + slab.solve → chunk with zero orphans, the
  per-kind breakdown summing within the parent durations, the
  `SolveRecord` stamped with the trace (`record.trace`), and events
  carrying `trace_id`/`span_id`.
* **HTTP propagation** — a client traceparent is JOINED (same
  trace_id acknowledged and echoed), a missing one is minted.
* **Overhead** — the solver path never reads PA_TX*: the block program
  lowers to byte-identical StableHLO with tracing on+persisting vs
  killed (the PR 6/9/10 convention), and PA_TX=0 takes the inert path
  (no spans retained).
* **patx --check** — the tier-1 CLI smoke (ephemeral HTTP gate →
  reconstruct → span-tree invariants).

Budget note: everything runs on the sequential backend's tiny Poisson
fixtures except the one HLO pin (8-part 6³, the test_pagate pattern).
"""
import json
import os
import urllib.request

import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu import telemetry
from partitionedarrays_jl_tpu.frontdoor import Gate, serve_gate
from partitionedarrays_jl_tpu.models import assemble_poisson, gather_pvector
from partitionedarrays_jl_tpu.telemetry import tracing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _poisson(grid=(8, 8)):
    return pa.prun(
        lambda parts: assemble_poisson(parts, grid), pa.sequential, (2, 2)
    )


# ---------------------------------------------------------------------------
# traceparent parsing
# ---------------------------------------------------------------------------

_VALID_TP = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"

#: The fuzz corpus: every way a hostile/broken client mangles the
#: header. Each must parse to None (and, over HTTP, mint a fresh
#: trace instead of 500ing).
_MALFORMED = [
    "",                                          # empty
    "00",                                        # truncated at version
    _VALID_TP[:-4],                              # truncated flags
    _VALID_TP + "-extra",                        # overlong (extra field)
    _VALID_TP + "00",                            # overlong (glued)
    _VALID_TP.replace("-", ""),                  # no separators
    "00-" + "zz" * 16 + "-" + "cd" * 8 + "-01",  # non-hex trace id
    "00-" + "ab" * 16 + "-" + "xy" * 8 + "-01",  # non-hex span id
    "00-" + "AB" * 16 + "-" + "cd" * 8 + "-01",  # uppercase hex
    "ff-" + "ab" * 16 + "-" + "cd" * 8 + "-01",  # forbidden version
    "0-" + "ab" * 16 + "-" + "cd" * 8 + "-01",   # short version
    "00-" + "00" * 16 + "-" + "cd" * 8 + "-01",  # all-zero trace id
    "00-" + "ab" * 16 + "-" + "00" * 8 + "-01",  # all-zero span id
    "00-" + "ab" * 17 + "-" + "cd" * 8 + "-01",  # overlong trace id
    "00-" + "ab" * 16 + "-" + "cd" * 7 + "-01",  # short span id
    "garbage",
]


def test_traceparent_parse_strict():
    ctx = tracing.parse_traceparent(_VALID_TP)
    assert ctx is not None
    assert ctx.trace_id == "ab" * 16 and ctx.span_id == "cd" * 8
    assert ctx.traceparent() == _VALID_TP
    # surrounding whitespace is tolerated (proxies pad headers)
    assert tracing.parse_traceparent(f"  {_VALID_TP} ") is not None
    for bad in _MALFORMED:
        assert tracing.parse_traceparent(bad) is None, bad
    assert tracing.parse_traceparent(None) is None
    assert tracing.parse_traceparent(123) is None


def test_mint_trace_shape_and_uniqueness():
    a, b = tracing.mint_trace(), tracing.mint_trace()
    assert tracing.parse_traceparent(a.traceparent()) is not None
    assert a.trace_id != b.trace_id


# ---------------------------------------------------------------------------
# span store + tree algebra
# ---------------------------------------------------------------------------


def test_span_persistence_and_tree(tmp_path, monkeypatch):
    monkeypatch.setenv("PA_TX_DIR", str(tmp_path))
    root = tracing.start_span("rpc.request", name="r")
    child = tracing.start_span("gate.queue", name="r", parent=root)
    grand = tracing.start_span("slab.solve", name="r", parent=child.ctx)
    grand.end()
    child.end()
    # root left OPEN: it must surface as an interrupted span (the
    # crash-stitching input) — from the file reader AND the ring
    spans = tracing.load_spans(str(tmp_path))
    assert {s["kind"] for s in spans} == {
        "rpc.request", "gate.queue", "slab.solve"
    }
    by_kind = {s["kind"]: s for s in spans}
    assert by_kind["rpc.request"]["status"] == "interrupted"
    assert by_kind["rpc.request"]["dur_s"] is None
    assert by_kind["gate.queue"]["status"] == "ok"
    roots, orphans = tracing.span_tree(spans)
    assert [r["kind"] for r in roots] == ["rpc.request"]
    assert orphans == []
    assert tracing.verify_trace(spans, root.trace_id) == []
    # an orphan IS detected (synthetic span naming a ghost parent)
    ghost = dict(by_kind["slab.solve"], span_id="f" * 16,
                 parent_id="e" * 16)
    problems = tracing.verify_trace(spans + [ghost], root.trace_id)
    assert any("ORPHAN" in p for p in problems)
    # a remote-parented root is a root, not an orphan
    remote = tracing.start_span(
        "rpc.request", name="q",
        parent=tracing.mint_trace(), remote=True,
    )
    remote.end()
    mine = tracing.spans_for(remote.trace_id,
                             spans=tracing.load_spans(str(tmp_path)))
    roots2, orphans2 = tracing.span_tree(mine)
    assert len(roots2) == 1 and not orphans2
    root.end()


def test_tracing_kill_switch_is_inert(tmp_path, monkeypatch):
    monkeypatch.setenv("PA_TX", "0")
    monkeypatch.setenv("PA_TX_DIR", str(tmp_path))
    before = telemetry.counter("tx.spans")
    s = tracing.start_span("rpc.request", name="off")
    assert s.recording is False
    s.end()
    assert telemetry.counter("tx.spans") == before
    assert tracing.load_spans(str(tmp_path)) == []


# ---------------------------------------------------------------------------
# one span tree per request, in-process
# ---------------------------------------------------------------------------


def test_gate_request_yields_one_sound_span_tree():
    A, b, xe, x0 = _poisson((8, 8))

    gate = Gate()
    gate.register("t", A, kmax=2)
    h1 = gate.submit("t", b, x0=x0, tol=1e-9, tag="tx-1",
                     slo_class="interactive")
    h2 = gate.submit("t", b, x0=x0, tol=1e-9, tag="tx-2")
    gate.drain()
    assert h1.result()[1]["converged"]
    assert h1.trace is not None and h2.trace is not None
    assert h1.trace.trace_id != h2.trace.trace_id
    spans = tracing.recorded_spans()
    for h in (h1, h2):
        tid = h.trace.trace_id
        assert tracing.verify_trace(spans, tid) == []
        mine = [s for s in spans if s["trace_id"] == tid]
        kinds = {s["kind"] for s in mine}
        assert {"rpc.request", "gate.queue", "slab.solve",
                "chunk"} <= kinds
        roots, orphans = tracing.span_tree(mine)
        assert len(roots) == 1 and not orphans
        assert roots[0]["kind"] == "rpc.request"
        assert roots[0]["status"] == "done"
        by_id = {s["span_id"]: s for s in mine}
        for s in mine:
            if s["kind"] in ("gate.queue", "slab.solve"):
                assert by_id[s["parent_id"]]["kind"] == "rpc.request"
            if s["kind"] == "chunk":
                assert by_id[s["parent_id"]]["kind"] == "slab.solve"
        # the breakdown is the acceptance shape: queue + solve within
        # the root, solve dominant for a drained request
        summ = tracing.trace_summary(mine, tid)
        assert summ["dominant"] == "slab.solve"
        assert (
            summ["by_kind_s"]["gate.queue"]
            + summ["by_kind_s"]["slab.solve"]
            <= summ["total_s"] * 1.05 + 5e-3
        )
    # the record/span join: record.trace == the root span context,
    # and terminal events carry the trace ids
    rec = h1.request.record
    assert rec.trace == {
        "trace_id": h1.trace.trace_id, "span_id": h1.trace.span_id,
    }
    done = [e for e in rec.events if e.kind == "request_done"]
    assert done and done[0].details["trace_id"] == h1.trace.trace_id


# ---------------------------------------------------------------------------
# HTTP propagation + the malformed-header fuzz
# ---------------------------------------------------------------------------


def test_http_propagation_and_malformed_traceparent_never_500():
    A, b, xe, x0 = _poisson((8, 8))
    gate = Gate(start_workers=True)
    gate.register("t", A, kmax=4)
    srv = serve_gate(gate, port=0)
    try:
        bg = list(map(float, gather_pvector(b)))
        body = json.dumps({
            "tenant": "t", "b": bg, "tol": 1e-9, "maxiter": 50,
        }).encode()

        def post(headers):
            req = urllib.request.Request(
                srv.url + "/v1/solve", data=body,
                headers={"Content-Type": "application/json", **headers},
                method="POST",
            )
            with urllib.request.urlopen(req) as resp:
                return resp.status, json.loads(resp.read()), dict(
                    resp.headers
                )

        # a VALID traceparent is joined: same trace_id acknowledged in
        # the payload and echoed in the response header
        ctx = tracing.mint_trace()
        status, payload, headers = post(
            {"traceparent": ctx.traceparent()}
        )
        assert status == 202
        assert payload["trace_id"] == ctx.trace_id
        echoed = tracing.parse_traceparent(headers.get("traceparent"))
        assert echoed is not None and echoed.trace_id == ctx.trace_id
        # ... and the server-side root records the REMOTE parent
        root = next(
            s for s in tracing.recorded_spans()
            if s["trace_id"] == ctx.trace_id
            and s["kind"] == "rpc.request"
        )
        assert root["remote"] and root["parent_id"] == ctx.span_id

        # the fuzz sweep: every malformed header admits (202), mints a
        # FRESH trace, bumps the counter — never 500s
        bad0 = telemetry.counter("gate.traceparent_invalid")
        seen_traces = set()
        for i, bad in enumerate(_MALFORMED):
            status, payload, _ = post({"traceparent": bad})
            assert status == 202, (bad, status, payload)
            assert payload["trace_id"] != ctx.trace_id, bad
            assert payload["trace_id"] not in seen_traces, bad
            seen_traces.add(payload["trace_id"])
            assert telemetry.counter(
                "gate.traceparent_invalid"
            ) == bad0 + i + 1, bad
        # no header at all: minted, NOT counted as invalid
        status, payload, _ = post({})
        assert status == 202 and payload.get("trace_id")
        assert telemetry.counter(
            "gate.traceparent_invalid"
        ) == bad0 + len(_MALFORMED)
        gate.drain()
    finally:
        srv.stop()


def test_healthz_readiness_fields():
    """/healthz is readiness-probe grade: queue depth, resident tenant
    list, journal epoch, uptime (the ISSUE-14 enrichment — asserted
    here next to its producer; tests/test_pagate.py keeps the endpoint
    suite)."""
    A, b, xe, x0 = _poisson((8, 8))
    gate = Gate()
    gate.register("t", A, kmax=2)
    srv = serve_gate(gate, port=0)
    try:
        with urllib.request.urlopen(srv.url + "/healthz") as resp:
            health = json.loads(resp.read())
        assert health["ok"] is True
        assert health["queue_depth"] == 0
        assert health["resident"] == ["t"]
        assert health["journal_epoch"] is None  # journal off
        assert isinstance(health["uptime_s"], float)
        assert health["uptime_s"] >= 0.0
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# overhead: byte-identical programs, tracing on/off
# ---------------------------------------------------------------------------


def test_tracing_on_block_program_hlo_identical(tmp_path, monkeypatch):
    """The overhead pin: the compiled block body lowers to
    byte-identical StableHLO with the span plane fully enabled (PA_TX=1
    + a persistence dir + live spans open) vs killed — the solver path
    never reads a PA_TX* flag."""
    import jax

    from partitionedarrays_jl_tpu.parallel.tpu import (
        TPUBackend,
        _matrix_operands,
        device_matrix,
        make_cg_fn,
    )

    backend = TPUBackend(devices=jax.devices()[:8])
    A = pa.prun(
        lambda parts: assemble_poisson(parts, (6, 6, 6))[0],
        backend, (2, 2, 2),
    )
    dA = device_matrix(A, backend)
    ops = _matrix_operands(dA)
    P, W = dA.col_plan.layout.P, dA.col_plan.layout.W
    zb = np.zeros((P, W, 2))

    def text():
        fn = make_cg_fn(dA, tol=1e-9, maxiter=50, rhs_batch=2)
        return fn.jit_fn.lower(zb, zb, zb[..., 0], ops).as_text()

    monkeypatch.setenv("PA_TX", "0")
    baseline = text()
    monkeypatch.setenv("PA_TX", "1")
    monkeypatch.setenv("PA_TX_DIR", str(tmp_path))
    with tracing.span("rpc.request", name="hlo-pin"):
        assert text() == baseline
    assert text() == baseline


# ---------------------------------------------------------------------------
# the CLI smoke
# ---------------------------------------------------------------------------


def test_patx_check_smoke(capsys):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "patx", os.path.join(REPO, "tools", "patx.py")
    )
    patx = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(patx)
    rc = patx.main(["--check"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "patx --check: OK" in out


def test_patx_render_list_and_phase_mount(tmp_path, monkeypatch):
    """patx rendering surface: --list/--slow ranking, the tree render,
    and --phases mounting solver.phase children under slab.solve from
    the committed PHASE_PROFILE.json."""
    import importlib.util

    monkeypatch.setenv("PA_TX_DIR", str(tmp_path))
    with tracing.span("rpc.request", name="fast") as root:
        with tracing.span("slab.solve", name="fast", parent=root):
            pass
    with tracing.span("rpc.request", name="slowreq") as root2:
        import time as _t

        with tracing.span("slab.solve", name="slowreq",
                          parent=root2) as slab:
            # a chunk child FILLING the slab: the mounted phases are an
            # alternate decomposition — verify_trace sums children per
            # KIND, so chunk + solver.phase must not double-count
            with tracing.span("chunk", name="slowreq", parent=slab):
                _t.sleep(0.02)
    spans = tracing.load_spans(str(tmp_path))
    assert len(tracing.trace_ids(spans)) == 2
    # --slow ranks the sleeper first
    summs = sorted(
        (tracing.trace_summary(spans, t) for t in
         tracing.trace_ids(spans)),
        key=lambda r: -r["total_s"],
    )
    assert summs[0]["total_s"] > summs[1]["total_s"]
    # phase mount: synthetic solver.phase children under slab.solve,
    # scaled to the slab duration with shares preserved
    profile = json.load(open(os.path.join(REPO, "PHASE_PROFILE.json")))
    added = tracing.mount_phase_spans(spans, profile)
    slabs = [s for s in spans if s["kind"] == "slab.solve"]
    # the schema-v2 container mounts the standard body's profile
    std = profile["profiles"]["standard"] if "profiles" in profile \
        else profile
    assert len(added) == len(slabs) * len(std["phases"])
    for s in slabs:
        kids = [a for a in added if a["parent_id"] == s["span_id"]]
        assert {k["kind"] for k in kids} == {"solver.phase"}
        assert sum(k["dur_s"] for k in kids) == pytest.approx(
            s["dur_s"], rel=1e-6
        )
    # the tree render + verify stay sound with the mount included
    tid = slabs[0]["trace_id"]
    assert tracing.verify_trace(spans + added, tid) == []
    out = tracing.render_trace(spans + added, tid)
    assert "solver.phase:dot_allgather" in out
    # the CLI front end agrees (patx <trace_id> on the same dir)
    spec = importlib.util.spec_from_file_location(
        "patx", os.path.join(REPO, "tools", "patx.py")
    )
    patx = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(patx)
    assert patx.main([tid, "--dir", str(tmp_path)]) == 0
