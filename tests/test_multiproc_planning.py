"""The multiprocess planning path (tools/plan_multiproc.py) must compute
the SAME per-part matrices as the in-process assembly fast path — the
testable form of the "planning is embarrassingly parallel per part"
claim (round-4 directive 3; reference analog: per-rank local assembly,
test/test_fdm.jl:52-81)."""
import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu import native


@pytest.mark.skipif(not native.available(), reason="native layer required")
def test_multiproc_planning_matches_inprocess():
    import sys, os

    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools")
    )
    from plan_multiproc import run

    ns, pshape = (20, 18, 16), (2, 2, 1)
    w1, f1 = run(ns, pshape, 1, dtype="float64", decoupled=False)
    w2, f2 = run(ns, pshape, 2, dtype="float64", decoupled=False)
    # process count cannot change the matrices (last slot is wall time)
    assert [r[:5] for r in f1] == [r[:5] for r in f2]
    assert len(f2) == 4 and sorted(r[0] for r in f2) == [0, 1, 2, 3]

    # pin the checksums to the real API's per-part CSR blocks
    def driver(parts):
        A, b, xe, x0 = pa.assemble_poisson(parts, ns)
        out = []
        for p, M in enumerate(A.values.part_values()):
            out.append(
                (
                    p,
                    int(M.nnz),
                    float(M.data.sum(dtype=np.float64)),
                    int(M.indices.sum(dtype=np.int64)),
                    int(M.indptr[-1]),
                )
            )
        return out

    api = pa.prun(driver, pa.sequential, pshape)
    assert [r[:5] for r in f2] == api
