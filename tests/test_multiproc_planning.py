"""The multiprocess planning path (tools/plan_multiproc.py) must compute
the SAME per-part matrices as the in-process assembly fast path — the
testable form of the "planning is embarrassingly parallel per part"
claim (round-4 directive 3; reference analog: per-rank local assembly,
test/test_fdm.jl:52-81)."""
import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu import native


@pytest.mark.skipif(not native.available(), reason="native layer required")
def test_multiproc_planning_matches_inprocess():
    import sys, os

    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools")
    )
    from plan_multiproc import run

    ns, pshape = (20, 18, 16), (2, 2, 1)
    w1, f1 = run(ns, pshape, 1, dtype="float64", decoupled=False)
    w2, f2 = run(ns, pshape, 2, dtype="float64", decoupled=False)
    # process count cannot change the matrices (last slot is wall time)
    assert [r[:5] for r in f1] == [r[:5] for r in f2]
    assert len(f2) == 4 and sorted(r[0] for r in f2) == [0, 1, 2, 3]

    # pin the checksums to the real API's per-part CSR blocks
    def driver(parts):
        A, b, xe, x0 = pa.assemble_poisson(parts, ns)
        out = []
        for p, M in enumerate(A.values.part_values()):
            out.append(
                (
                    p,
                    int(M.nnz),
                    float(M.data.sum(dtype=np.float64)),
                    int(M.indices.sum(dtype=np.int64)),
                    int(M.indptr[-1]),
                )
            )
        return out

    api = pa.prun(driver, pa.sequential, pshape)
    assert [r[:5] for r in f2] == api


@pytest.mark.skipif(not native.available(), reason="native layer required")
def test_parallel_emit_byte_identical():
    """K spawned workers over row slabs write the SAME CSR (and b) as
    the one-shot native emission — the zero-stitch property that makes
    PA_TPU_PLAN_PROCS safe to flip on (round-5 directive 6)."""
    from partitionedarrays_jl_tpu.models.poisson_fdm import (
        stencil_ghost_slabs,
    )
    from partitionedarrays_jl_tpu.native.parallel_emit import (
        slab_nnz,
        stencil_emit_parallel,
    )

    ns = (20, 18, 16)
    lo, hi = (3, 0, 2), (17, 9, 16)
    arms = np.array([-1.0] * 6)
    gg = stencil_ghost_slabs(lo, hi, ns)
    xtab = np.concatenate(
        [
            np.sin(0.5 + (d + 1.0) * np.arange(ns[d]) / (ns[d] + 1.0))
            for d in range(3)
        ]
    )
    ser = native.stencil_emit(
        ns, lo, hi, 6.0, arms, gg, np.float64, decouple=True, xtab=xtab
    )
    par = stencil_emit_parallel(
        ns, lo, hi, 6.0, arms, gg, np.float64, 2, decouple=True, xtab=xtab
    )
    assert ser is not None and par is not None
    for a, b in zip(ser, par):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the closed-form nnz the shm layout is sized from must match the
    # emission's actual nnz
    assert slab_nnz(ns, lo, hi, 0, hi[0] - lo[0]) == len(ser[1])


@pytest.mark.skipif(not native.available(), reason="native layer required")
def test_plan_procs_env_flag_matches_default(monkeypatch):
    """PA_TPU_PLAN_PROCS=2 routes the box fast path's emission through
    the spawned workers; the assembled operator must be identical."""
    ns = (14, 12, 10)

    def driver(parts):
        A, b, xe, x0 = pa.assemble_poisson(parts, ns, decoupled=True)
        return [
            (
                int(M.nnz),
                float(M.data.sum(dtype=np.float64)),
                int(M.indices.sum(dtype=np.int64)),
            )
            for M in A.values.part_values()
        ] + [float(np.asarray(v, dtype=np.float64).sum()) for v in b.values]

    base = pa.prun(driver, pa.sequential, (2, 1, 1))
    monkeypatch.setenv("PA_TPU_PLAN_PROCS", "2")
    multi = pa.prun(driver, pa.sequential, (2, 1, 1))
    assert base == multi
