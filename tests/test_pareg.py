"""pareg — the perf ledger and regression sentinel (ISSUE 10).

Acceptance pins: PERF_LEDGER.json covers every committed
``*_BENCH.json`` with values equal to their sources (the companion
coverage test lives in test_doc_consistency.py), `pareg --check` is
green on the committed set, and it exits NONZERO on the committed
seeded-regression fixture. Pure-JSON layer — no jax, no devices."""
import importlib.util
import json
import os

import pytest

from partitionedarrays_jl_tpu.telemetry import artifacts, ledger

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(
    REPO, "tests", "fixtures", "pareg", "SEEDED_REGRESSION_BENCH.json"
)


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_ledger_builds_and_covers_every_committed_artifact():
    led = ledger.build_ledger(REPO)
    assert led["ledger_schema_version"] == ledger.LEDGER_SCHEMA_VERSION
    names = {os.path.basename(p) for p in ledger.artifact_paths(REPO)}
    assert names, "no committed *_BENCH.json artifacts found"
    assert set(led["artifacts"]) == names
    # every artifact contributes at least one metric series
    for name in names:
        assert led["artifacts"][name]["metrics"], name
    # series keys are namespaced by their artifact
    for key in led["series"]:
        art = key.split(":", 1)[0]
        assert art in names, key


def test_pareg_check_green_on_committed_set(capsys):
    pareg = _load_tool("pareg")
    rc = pareg.main(["--check"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "pareg --check: OK" in out


def test_pareg_check_exits_nonzero_on_seeded_regression(capsys):
    """The acceptance pin: the committed seeded-regression fixture
    (a lying in_band flag + an on-device out-of-band measurement)
    trips the sentinel."""
    pareg = _load_tool("pareg")
    rc = pareg.main(["--check", FIXTURE])
    cap = capsys.readouterr()
    assert rc != 0
    assert "REGRESSION" in cap.err
    assert "inconsistent" in cap.err
    assert "pareg --check: FAILED" in cap.out


def test_check_artifact_rule_set(tmp_path):
    """Unit-level sentinel rules: envelope, band arithmetic, device
    gating by platform, cpu-canary exemption, ledger staleness."""
    rec = {
        "schema_version": 1, "generated_by": "t", "platform": "cpu",
        "pa_env": {},
        "bands": {
            "ok": {"lo": 1.0, "hi": 2.0, "kind": "canary",
                   "measured": 1.5, "in_band": True},
            "dev": {"lo": 1.0, "hi": 2.0, "kind": "device",
                    "measured": 5.0, "in_band": False},
            "canary_unmeasured": {"lo": 0.9, "hi": 1.1,
                                  "kind": "device", "measured": None,
                                  "in_band": None},
        },
    }
    # cpu platform: the out-of-band device value does not gate, the
    # unmeasured canary is exempt, the flags are consistent -> healthy
    assert ledger.check_artifact("X_BENCH.json", rec) == []
    # the same record measured on tpu IS a regression
    tpu = json.loads(json.dumps(rec))
    tpu["platform"] = "tpu"
    fails = ledger.check_artifact("X_BENCH.json", tpu)
    assert any("REGRESSION" in f and "dev" in f for f in fails)
    # a non-device band out of its bounds gates on ANY platform
    bad = json.loads(json.dumps(rec))
    bad["bands"]["ok"]["measured"] = 9.9
    bad["bands"]["ok"]["in_band"] = False
    fails = ledger.check_artifact("X_BENCH.json", bad)
    assert any("REGRESSION" in f and ":ok" in f for f in fails)
    # a lying in_band flag is its own failure even when gated off
    liar = json.loads(json.dumps(rec))
    liar["bands"]["dev"]["in_band"] = True
    assert any(
        "inconsistent" in f
        for f in ledger.check_artifact("X_BENCH.json", liar)
    )
    # missing envelope
    naked = {"bands": rec["bands"]}
    assert any(
        "envelope" in f
        for f in ledger.check_artifact("X_BENCH.json", naked)
    )


def test_update_ledger_appends_points_and_detects_staleness(tmp_path):
    """The trajectory grows: a regenerated artifact with a changed
    value appends a series point; checking the NEW artifact against
    the OLD ledger reports staleness."""
    art = tmp_path / "MINI_BENCH.json"
    rec = {
        "schema_version": 1, "generated_by": "t", "platform": "cpu",
        "pa_env": {},
        "bands": {"m": {"lo": 0.0, "hi": 10.0, "kind": "canary",
                        "measured": 4.0, "in_band": True}},
    }
    art.write_text(json.dumps(rec))
    led1 = ledger.build_ledger(str(tmp_path))
    assert led1["series"]["MINI_BENCH.json:m"][0]["value"] == 4.0
    # unchanged artifact: update is a no-op on the series
    led_same = ledger.update_ledger(led1, str(tmp_path))
    assert led_same["series"] == led1["series"]
    # regenerated artifact: the history grows, latest point wins
    rec["bands"]["m"]["measured"] = 6.0
    art.write_text(json.dumps(rec))
    stale = ledger.check_artifact("MINI_BENCH.json", rec, ledger=led1)
    assert any("stale" in f for f in stale)
    led2 = ledger.update_ledger(led1, str(tmp_path))
    points = led2["series"]["MINI_BENCH.json:m"]
    assert [p["value"] for p in points] == [4.0, 6.0]
    assert ledger.check_artifact("MINI_BENCH.json", rec,
                                 ledger=led2) == []
    # last-known-good is quoted when a fresh value regresses
    rec["bands"]["m"]["measured"] = 99.0
    rec["bands"]["m"]["in_band"] = False
    fails = ledger.check_artifact("MINI_BENCH.json", rec, ledger=led2)
    assert any("last known good: 6.0" in f for f in fails)


def test_check_repo_flags_orphaned_ledger_entries(tmp_path):
    """The reverse coverage direction: a ledger entry whose source
    artifact vanished (deleted/renamed without --update) must trip the
    sentinel — the artifact table may not reference ghosts."""
    art = tmp_path / "GONE_BENCH.json"
    art.write_text(json.dumps({
        "schema_version": 1, "generated_by": "t", "platform": "cpu",
        "pa_env": {},
        "bands": {"m": {"lo": 0.0, "hi": 1.0, "kind": "canary",
                        "measured": 0.5, "in_band": True}},
    }))
    led = ledger.build_ledger(str(tmp_path))
    (tmp_path / ledger.LEDGER_NAME).write_text(json.dumps(led))
    assert ledger.check_repo(str(tmp_path)) == []
    art.unlink()
    fails = ledger.check_repo(str(tmp_path))
    assert any("GONE_BENCH.json" in f and "no such artifact" in f
               for f in fails)


def test_content_hash_ignores_pa_env_noise():
    rec = {"schema_version": 1, "pa_env": {"PA_X": "1"}, "v": 2}
    other = dict(rec, pa_env={"PA_Y": "0"})
    assert ledger.content_hash(rec) == ledger.content_hash(other)
    assert ledger.content_hash(rec) != ledger.content_hash(
        dict(rec, v=3)
    )


def test_pareg_update_writes_through_shared_envelope(tmp_path, capsys,
                                                     monkeypatch):
    """--update writes PERF_LEDGER.json through telemetry.artifacts
    (the committed file's envelope is pinned by test_doc_consistency);
    here: the dry-run output is the stamped record."""
    pareg = _load_tool("pareg")
    rc = pareg.main(["--update", "--dry-run"])
    out = capsys.readouterr().out
    assert rc == 0
    rec = json.loads(out[: out.rindex("}") + 1])
    assert rec["ledger_schema_version"] == ledger.LEDGER_SCHEMA_VERSION
    assert rec["schema_version"] == artifacts.ARTIFACT_SCHEMA_VERSION
    assert rec["generated_by"] == "pareg"
