"""The fault-kind x detector x recovery-path matrix, executable.

docs/resilience.md documents which layer catches each injected fault
kind and what happens next; this file IS that table as tier-1 smoke
tests — one short solve per kind, asserting the documented outcome
(typed error, self-heal to the fault-free answer, or clean
completion), so the matrix can never silently rot into prose.

| kind       | detector                     | documented outcome        |
|------------|------------------------------|---------------------------|
| nan        | free scalar guard            | NonFiniteError; recovery restarts and reproduces the clean run |
| nan + ABFT | exchange slab checksum       | SilentCorruptionError -> in-memory rollback self-heal |
| bitflip    | (none by default)            | SILENT wrong answer — the threat model (pinned in test_abft.py) |
| bitflip + ABFT | exchange slab checksum   | rollback self-heal, bitwise |
| bitflip + audit | true-residual audit     | rollback self-heal, bitwise |
| drop       | exchange deadline            | ExchangeTimeoutError, typed; survivable by restart |
| delay      | nothing to detect            | clean completion (slow host is not an error) |
| controller | runtime surface              | ControllerLostError; survivable by restart |

Round 9 (patrace): each case ALSO asserts its telemetry story — the
injected fault, the detector that fired, and the recovery path taken
all appear as structured events in the solve's `SolveRecord`
(``info.record``, or the aborted record in the history ring for the
typed-raise paths). No recovery may be silent in the event log.

Round 10 (pasolve): the solve service adds request-level rows to the
matrix — faults and overload hitting the MULTI-TENANT layer, each with
its documented outcome and event trail:

| condition               | detector            | documented outcome   |
|-------------------------|---------------------|----------------------|
| queue over depth bound  | admission control   | AdmissionRejected (typed backpressure) + admission_rejected event |
| deadline past at chunk boundary | service clock | SolveDeadlineError + deadline_expired/health_error events; co-batched requests unaffected |
| poisoned column in a shared slab | per-column verdict export | that request ejected + typed NonFiniteError; co-batched requests complete clean (column_verdict/column_ejected/request_failed events) |

Round 12 (pamon): each service row ALSO asserts its METRIC deltas —
the registry counters and histogram counts the incident must move
(rejection/expiry/ejection counters, total-latency and SLO
accounting), so the event log and the metrics plane can never
silently drift apart: an incident that narrates but does not count
(or counts but does not narrate) fails here.

Round 11 (paplan): a corrupted *plan* (mutated slot indices — not wire
data) is a fault class every runtime row above is blind to until the
wrong answer lands; with ``PA_PLAN_VERIFY=1`` it is caught STATICALLY:

| condition               | detector            | documented outcome   |
|-------------------------|---------------------|----------------------|
| corrupted exchange plan | static plan verifier at the build site | PlanSoundnessError (typed, with check + part/slot diagnostics) + plan_defect/health_error events, BEFORE any solve runs |

Round 14 (pagate): the front door adds the TENANCY/overload rows —
failures hitting the multi-OPERATOR layer, each with its documented
outcome, event trail, and metric deltas (docs/service.md Front door):

| condition               | detector            | documented outcome   |
|-------------------------|---------------------|----------------------|
| operator footprint over PA_GATE_MEM_BUDGET | registry admission | TenantBudgetError (typed; tenant never registered) + tenant_budget_rejected event + gate.budget_rejected counter |
| gate queue past the shed watermark | SLO-class shed policy | lowest class refused with typed LoadShedded (retry_after_s / HTTP 429 Retry-After) + load_shedded event + gate.shed{slo_class=…}; DISTINCT from service.rejected{reason=queue_full} |
| eviction during an in-flight chunked solve | LRU paging + PR 7 checkpoint path | request_checkpointed at the chunk boundary, tenant_evicted/tenant_requeued/tenant_paged_in events, checkpoint_restore on resume, and the request COMPLETES from its saved iterate |

Round 15 (padur): the DURABILITY rows — the gate's own death, each
with its documented outcome, event trail, and metric deltas
(docs/resilience.md Durability):

| condition               | detector            | documented outcome   |
|-------------------------|---------------------|----------------------|
| gate killed mid-solve (kill -9 semantics: state abandoned, no shutdown) | write-ahead journal replay at the next start | Gate.recover() resumes the in-flight request from its chunk-checkpointed iterate (gate.recovered{outcome=resumed}, request_recovered/gate_recovered/checkpoint_restore events) and it COMPLETES; nothing lost, nothing duplicated |
| torn journal tail (crash mid-append) | per-record CRC32 at replay | tail truncated (journal.truncated + journal_truncated event), clean prefix recovered intact; mid-file corruption raises typed JournalCorruptError instead |
| duplicate idempotency-key submit | gate key map (journal-rebuilt) | original id + bitwise result returned (gate.idempotent_hits + idempotent_replay event); service.admitted does NOT move — a single solve, across restarts included |

Round 16 (pafleet): the REPLICATION rows — faults hitting the fleet
layer, each with its documented outcome, event trail, and metric
deltas (docs/service.md Gate fleet):

| condition               | detector            | documented outcome   |
|-------------------------|---------------------|----------------------|
| replica killed (kill -9 semantics: lease goes stale) | peer lease watcher | the rendezvous-ranked survivor adopts the dead replica's journal (fleet.lease_missed + fleet_lease_missed, fleet.adopted{outcome=…} + request_adopted/fleet_adopted) and completes its live requests under their ORIGINAL rids — zero lost; the victim's journal carries the adopted marker, so a restarted victim refuses typed (AdoptedByPeer) — zero duplicated; ONE stitched trace across the hop |
| overload on one replica with peer headroom | shed-forward peer picker | HTTP 307 to the shallowest live-leased peer (fleet.forwarded + fleet_forwarded) instead of 429; `http_solve` follows with the same idempotency key + traceparent, the request solves on the peer, one stitched trace |
| torn/corrupt lease file | lease CRC at the reader | typed LeaseCorruptError from check_peers — REFUSED takeover (no adoption, no adopted marker, fleet.lease_missed does NOT move); pick_peer degrades to None (429 fallback), never a false forward |

Round 17 (paspec): the convergence observatory adds the PREDICTIVE
refusal row — overload the scheduler can see COMING instead of
discovering by burning iterations (docs/observability.md "Convergence
observatory"):

| condition               | detector            | documented outcome   |
|-------------------------|---------------------|----------------------|
| infeasible deadline on a measured operator (PA_SPEC_ADMIT=1) | spectral forecast x measured s_per_it at admission | DeadlineInfeasible (typed, predicted_s/available_s diagnostics) + deadline_infeasible/health_error events + spec.infeasible counter; NEVER dispatched — zero iterations, service.admitted/slabs do not move; distinct by type and metric from queue-full AdmissionRejected, LoadShedded, and post-hoc SolveDeadlineError expiry |

Round 18 (panode): the two-level exchange adds the STAGED-SCHEDULE
row — a corruption class the five flat plan checks are blind to,
because the flat logical-delivery view stays sound:

| condition               | detector            | documented outcome   |
|-------------------------|---------------------|----------------------|
| two-level schedule with a mutated representative slot (scatter lane redirected into stage trash) | schedule simulation in verify_twolevel_plan | PlanSoundnessError (typed, coverage diagnostics) + plan_defect/health_error events, BEFORE any solve runs |

Round 19 (paelastic): part LOSS — a casualty no same-partition restart
can ever outwait (its exchange contribution is gone for good), so the
recovery ladder forks on ``PA_ELASTIC`` instead of burning budget:

| condition               | detector            | documented outcome   |
|-------------------------|---------------------|----------------------|
| part loss, PA_ELASTIC=1 | exchange choke point (part_loss clause) | elastic shrink onto the survivor grid + resume from the last chunk checkpoint: elastic_shrink/checkpoint_restore/restart events, elastic.shrink{reason=part_loss} + elastic.crosspart_restores deltas, a tenant.repartition span, info["elastic"] ledger — and the NEXT full-capacity solve emits elastic_restore (grow back) |
| part loss, PA_ELASTIC=0 | exchange choke point (part_loss clause) | typed PartLossError escalates IMMEDIATELY to the caller's checkpoint tier — zero restarts attempted (no silent same-partition retry loop), no restart events, restart budget untouched |

Round 20 (palock): the THREAD-LIFECYCLE row — the leak class the
static leaked-thread check forbids at the AST level, asserted live:

| condition               | detector            | documented outcome   |
|-------------------------|---------------------|----------------------|
| drained shutdown of every thread-spawning component (SolveService worker, FleetMember beat/watch) | palock leaked-thread check + this row | zero live threads survive: shutdown(drain=True) joins the worker after finishing the queue, FleetMember.stop() joins beat+watch; the process-wide live-thread set returns to its pre-start baseline (no non-daemon thread may outlive its owner — daemon spawns need a DAEMON_WAIVERS reason) |
"""
import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu import telemetry
from partitionedarrays_jl_tpu.models import (
    assemble_poisson,
    cg,
    gather_pvector,
    solve_with_recovery,
)
from partitionedarrays_jl_tpu.parallel.faults import inject_faults
from partitionedarrays_jl_tpu.parallel.health import (
    ControllerLostError,
    ExchangeTimeoutError,
    NonFiniteError,
    SilentCorruptionError,
)


def _run(driver):
    assert pa.prun(driver, pa.sequential, (2, 2))


def _has_event(rec, kind, label=None):
    """Does the record log an event of ``kind`` (and ``label``)?"""
    return any(
        e.kind == kind and (label is None or e.label == label)
        for e in rec.events
    )


def _metric_state(*names):
    """Counter values + histogram counts before an incident (the
    service rows assert exact DELTAS against this, not absolutes — the
    registry is process-wide and other tests feed it). Labeled
    counters spell their label inline: ``name{key=value}``."""
    reg = telemetry.registry()
    out = {}
    for name in names:
        if name.endswith("_s"):
            out[name] = reg.histogram(name).count
        elif "{" in name:
            base, rest = name.split("{", 1)
            key, value = rest.rstrip("}").split("=", 1)
            out[name] = reg.counter(base, labels={key: value}).value
        else:
            out[name] = telemetry.counter(name)
    return out


def test_matrix_nan_typed_then_recovers():
    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        x_clean, _ = cg(A, b, x0=x0, tol=1e-9)
        with inject_faults("nan@part=1,call=9", seed=1):
            with pytest.raises(NonFiniteError):
                cg(A, b, x0=x0, tol=1e-9)
        # the aborted solve's record survives with the whole story:
        # the injected fault, the detector, and the abort itself
        aborted = telemetry.last_record("cg")
        assert aborted.status == "raised"
        assert _has_event(aborted, "fault_injected", "nan")
        assert _has_event(aborted, "health_error", "NonFiniteError")
        with inject_faults("nan@part=1,call=9", seed=1):
            x, info = solve_with_recovery(A, b, x0=x0, tol=1e-9)
        assert info["converged"] and info["restarts"] == 1
        # the recovery record logs the fault, the detector, AND the
        # recovery path taken (restart) — nothing healed silently
        rec = info.record
        assert _has_event(rec, "fault_injected", "nan")
        assert _has_event(rec, "health_error", "NonFiniteError")
        restarts = [e for e in rec.events if e.kind == "restart"]
        assert len(restarts) == 1
        assert restarts[0].label == "NonFiniteError"
        np.testing.assert_array_equal(
            gather_pvector(x_clean), gather_pvector(x)
        )
        return True

    _run(driver)


def test_matrix_nan_under_abft_heals_in_memory(monkeypatch):
    monkeypatch.setenv("PA_TPU_ABFT", "1")

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        x_clean, _ = cg(A, b, x0=x0, tol=1e-9)
        with inject_faults("nan@part=1,call=9", seed=1):
            x, info = cg(A, b, x0=x0, tol=1e-9)
        assert info["converged"] and info["sdc"]["rollbacks"] == 1
        # in-memory self-heal, but NOT silent: the record logs the
        # fault, the detection, and the rollback (with its iteration)
        rec = info.record
        assert _has_event(rec, "fault_injected", "nan")
        rolls = [e for e in rec.events if e.kind == "sdc_rollback"]
        assert _has_event(rec, "sdc_detection") and len(rolls) == 1
        assert rolls[0].iteration is not None
        np.testing.assert_array_equal(
            gather_pvector(x_clean), gather_pvector(x)
        )
        return True

    _run(driver)


def test_matrix_bitflip_under_abft_heals_bitwise(monkeypatch):
    monkeypatch.setenv("PA_TPU_ABFT", "1")
    monkeypatch.setenv("PA_HEALTH_AUDIT_EVERY", "6")

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        x_clean, _ = cg(A, b, x0=x0, tol=1e-9)
        with inject_faults("bitflip@part=1,call=9,bit=51", seed=7) as st:
            x, info = cg(A, b, x0=x0, tol=1e-9)
        assert any(e["kind"] == "bitflip" for e in st.events)
        assert info["converged"] and info["sdc"]["detections"] == 1
        # event completeness: fault kind + detection + rollback, with
        # the iteration the recovery rewound to
        rec = info.record
        assert _has_event(rec, "fault_injected", "bitflip")
        assert _has_event(rec, "sdc_detection", "cg")
        rolls = [e for e in rec.events if e.kind == "sdc_rollback"]
        assert len(rolls) == 1
        assert "restored_iteration" in rolls[0].details
        np.testing.assert_array_equal(
            gather_pvector(x_clean), gather_pvector(x)
        )
        return True

    _run(driver)


def test_matrix_drop_typed_timeout():
    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        with inject_faults("drop@part=2,call=5", seed=0) as st:
            with pytest.raises(ExchangeTimeoutError) as ei:
                cg(A, b, x0=x0, tol=1e-9)
        assert ei.value.diagnostics["missing_parts"] == [2]
        assert st.events[0]["kind"] == "drop"
        aborted = telemetry.last_record("cg")
        assert aborted.status == "raised"
        assert _has_event(aborted, "fault_injected", "drop")
        assert _has_event(aborted, "health_error", "ExchangeTimeoutError")
        return True

    _run(driver)


def test_matrix_delay_completes_clean():
    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        with inject_faults("delay@call=3,seconds=0.0", seed=0) as st:
            x, info = cg(A, b, x0=x0, tol=1e-9)
        assert info["converged"]  # a slow host is not an error
        assert st.events[0]["kind"] == "delay"
        # the record shows the injection AND that nothing needed to
        # recover: no detector fired, no recovery path was taken
        rec = info.record
        assert _has_event(rec, "fault_injected", "delay")
        for kind in ("health_error", "sdc_detection", "sdc_rollback",
                     "restart"):
            assert not _has_event(rec, kind), kind
        return True

    _run(driver)


def test_matrix_controller_typed_then_recovers():
    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        with inject_faults("controller@call=6", seed=0):
            with pytest.raises(ControllerLostError):
                cg(A, b, x0=x0, tol=1e-9)
        with inject_faults("controller@call=6", seed=0):
            x, info = solve_with_recovery(A, b, x0=x0, tol=1e-9)
        assert info["converged"] and info["restarts"] == 1
        assert info["recovery"]["attempts"] == 2
        rec = info.record
        assert _has_event(rec, "fault_injected", "controller")
        assert _has_event(rec, "health_error", "ControllerLostError")
        assert _has_event(rec, "restart", "ControllerLostError")
        return True

    _run(driver)


def test_matrix_service_admission_rejected():
    """Service row 1: overload hits the bounded queue — the documented
    outcome is TYPED backpressure (AdmissionRejected with machine-
    readable diagnostics), never unbounded buffering or a silent drop,
    and the rejection is an event (the counter always ticks)."""
    from partitionedarrays_jl_tpu.service import (
        AdmissionRejected,
        SolveService,
    )

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        svc = SolveService(A, queue_depth=1)
        held = svc.submit(b, x0=x0, tol=1e-9, tag="held")
        before = telemetry.counter("events.admission_rejected")
        m0 = _metric_state("service.rejected{reason=queue_full}",
                           "service.admitted",
                           "service.completed")
        with pytest.raises(AdmissionRejected) as ei:
            svc.submit(b, x0=x0, tol=1e-9, tag="over")
        assert ei.value.diagnostics["reason"] == "queue_full"
        assert telemetry.counter("events.admission_rejected") == before + 1
        # the metrics plane counted the same incident the event log
        # narrated: one rejection, zero admissions
        m1 = _metric_state("service.rejected{reason=queue_full}",
                           "service.admitted",
                           "service.completed")
        assert m1["service.rejected{reason=queue_full}"] == (
            m0["service.rejected{reason=queue_full}"] + 1
        )
        assert m1["service.admitted"] == m0["service.admitted"]
        # the queued request is untouched by the rejection
        svc.drain()
        assert held.result()[1]["converged"]
        m2 = _metric_state("service.completed")
        assert m2["service.completed"] == m0["service.completed"] + 1
        return True

    _run(driver)


def test_matrix_service_deadline_expiry():
    """Service row 2: a request's deadline passes at a chunk boundary —
    typed SolveDeadlineError (in the SolverHealthError family, so the
    health_error event fires) with the full story in the request's
    record; the co-batched deadline-free request completes."""
    from partitionedarrays_jl_tpu.parallel.health import SolveDeadlineError
    from partitionedarrays_jl_tpu.service import SolveService

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        t = {"now": 0.0}

        def clock():
            t["now"] += 1.0
            return t["now"]

        svc = SolveService(A, kmax=2, chunk=4, clock=clock)
        m0 = _metric_state(
            "service.deadline_expired", "service.failed",
            "service.completed", "service.total_s",
            "service.deadline_slack_s", "service.slo.requests{tol_class=1e-09}",
            "service.slo.hits{tol_class=1e-09}",
        )
        rd = svc.submit(b, x0=x0, tol=1e-9, deadline=0.5, tag="tight")
        rf = svc.submit(b, x0=x0, tol=1e-9, tag="free")
        svc.drain()
        with pytest.raises(SolveDeadlineError):
            rd.result()
        assert rf.result()[1]["converged"]
        rec = rd.record
        assert rec.status == "raised"
        assert _has_event(rec, "deadline_expired", "tight")
        assert _has_event(rec, "health_error", "SolveDeadlineError")
        assert _has_event(rec, "request_failed", "tight")
        # metric deltas, not just events: the expiry counted, both
        # requests' total latencies landed, and the SLO accounting for
        # the 1e-09 class saw one deadline-carrying request and NO hit
        m1 = _metric_state(
            "service.deadline_expired", "service.failed",
            "service.completed", "service.total_s",
            "service.deadline_slack_s", "service.slo.requests{tol_class=1e-09}",
            "service.slo.hits{tol_class=1e-09}",
        )
        d = {k: m1[k] - m0[k] for k in m0}
        assert d["service.deadline_expired"] == 1, d
        assert d["service.failed"] == 1 and d["service.completed"] == 1, d
        assert d["service.total_s"] == 2, d
        assert d["service.deadline_slack_s"] == 1, d
        assert d["service.slo.requests{tol_class=1e-09}"] == 1, d
        assert d["service.slo.hits{tol_class=1e-09}"] == 0, d
        return True

    _run(driver)


def test_matrix_service_poisoned_column_ejection():
    """Service row 3: a NaN-poisoned b shares a slab with clean
    requests — the poisoned request is ejected with a typed
    NonFiniteError and its event trail, the co-batched requests
    complete equal to their clean solo solves, and nothing heals
    silently."""
    from partitionedarrays_jl_tpu.service import SolveService

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        x_clean, _ = cg(A, b, x0=x0, tol=1e-9)
        bad = b.copy()

        def poison(i, vals):
            if int(i.part) == 0:
                np.asarray(vals)[0] = np.nan

        pa.map_parts(poison, bad.rows.partition, bad.values)
        svc = SolveService(A, kmax=3, retries=0)
        m0 = _metric_state(
            "service.ejected", "service.failed", "service.completed",
            "service.retried_solo", "service.slabs",
            "service.queue_wait_s", "service.total_s",
        )
        h_good = svc.submit(b, x0=x0, tol=1e-9, tag="good")
        h_bad = svc.submit(bad, x0=x0, tol=1e-9, tag="bad")
        h_good2 = svc.submit(b, x0=x0, tol=1e-9, tag="good2")
        svc.drain()
        assert svc.stats["slabs"] == 1  # one shared slab
        # metric deltas: one slab, one ejection (NO solo retry —
        # retries=0), one failure, two completions, and all three
        # requests' queue-wait + total-latency observations
        m1 = _metric_state(
            "service.ejected", "service.failed", "service.completed",
            "service.retried_solo", "service.slabs",
            "service.queue_wait_s", "service.total_s",
        )
        d = {k: m1[k] - m0[k] for k in m0}
        assert d["service.slabs"] == 1, d
        assert d["service.ejected"] == 1, d
        assert d["service.retried_solo"] == 0, d
        assert d["service.failed"] == 1 and d["service.completed"] == 2, d
        assert d["service.queue_wait_s"] == 3, d
        assert d["service.total_s"] == 3, d
        with pytest.raises(NonFiniteError):
            h_bad.result()
        for h in (h_good, h_good2):
            x, info = h.result()
            assert info["converged"]
            np.testing.assert_array_equal(
                gather_pvector(x), gather_pvector(x_clean)
            )
        rec = h_bad.record
        assert rec.status == "raised"
        assert _has_event(rec, "column_verdict")
        assert _has_event(rec, "column_ejected")
        assert _has_event(rec, "request_failed", "bad")
        # the clean requests' records show no failure of their own
        assert not _has_event(h_good.record, "request_failed", "good")
        return True

    _run(driver)


def test_matrix_corrupted_plan_caught_statically(monkeypatch):
    """paplan row: a corrupted exchange PLAN — mutated slot indices,
    the class every runtime detector above would only see as a wrong
    answer or a hang — is refused at the plan BUILD site under
    ``PA_PLAN_VERIFY=1``: typed `PlanSoundnessError` with the failing
    check and part/slot diagnostics, the ``plan_defect`` event
    emitted, and NO solve ever started."""
    from partitionedarrays_jl_tpu.parallel.health import PlanSoundnessError
    from partitionedarrays_jl_tpu.parallel.tpu import device_exchange_plan

    monkeypatch.setenv("PA_PLAN_VERIFY", "1")
    monkeypatch.setenv("PA_TPU_BOX", "0")  # the generic plan reads lids

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        rows = A.cols
        # corrupt the host plan in place: an overlapping ghost slot
        ex = rows.exchanger
        t = next(t for t in ex.lids_rcv.part_values() if len(t.data) >= 2)
        t.data[1] = t.data[0]
        before = telemetry.counter("events.plan_defect")
        health_before = telemetry.counter("events.health_error")
        last = telemetry.last_record()
        with pytest.raises(PlanSoundnessError) as ei:
            device_exchange_plan(rows)
        assert "ghost-race" in ei.value.diagnostics["checks"]
        d = ei.value.diagnostics["defects"][0]
        assert d["part"] is not None and d["check"] == "ghost-race"
        # the static catch is narrated (one plan_defect event per
        # failing check class + the health_error every typed failure
        # emits) and happened BEFORE any solve — no new SolveRecord
        assert telemetry.counter("events.plan_defect") == (
            before + len(ei.value.diagnostics["checks"])
        )
        assert telemetry.counter("events.health_error") == health_before + 1
        assert telemetry.last_record() is last
        return True

    _run(driver)


def test_matrix_corrupted_twolevel_schedule_caught_statically(monkeypatch):
    """panode row (ISSUE 18): a corrupted TWO-LEVEL schedule — a
    representative's scatter lane redirected into the stage trash, so
    the flat logical-delivery view stays perfectly sound and only the
    staged schedule drops the delivery — is exactly the defect class
    the five flat checks are blind to. The schedule simulation in
    `verify_twolevel_plan` catches it statically: typed
    `PlanSoundnessError` with check diagnostics, the ``plan_defect``
    event trail, and NO solve ever started. The clean two-level build
    passes the same ``PA_PLAN_VERIFY=1`` construction gate first."""
    from partitionedarrays_jl_tpu.analysis import plan_verifier as pv
    from partitionedarrays_jl_tpu.parallel.health import PlanSoundnessError
    from partitionedarrays_jl_tpu.parallel.tpu import device_exchange_plan

    monkeypatch.setenv("PA_PLAN_VERIFY", "1")
    monkeypatch.setenv("PA_TPU_BOX", "0")  # the generic two-level plan
    monkeypatch.setenv("PA_TPU_TWOLEVEL", "1")
    monkeypatch.setenv("PA_TPU_NODE_MAP", "0,0,1,1")

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        rows = A.cols
        # the clean build verifies sound AT the construction gate
        plan = device_exchange_plan(rows)
        assert hasattr(plan, "tl_rounds")
        rd = next(r for r in plan.tl_rounds if r.tier == "scatter")
        dst = int(rd.perm[0][1])
        strash = plan.layout.W + plan.stage_width
        lane = int(np.argmax(rd.rcv_idx[dst] != strash))
        assert rd.rcv_idx[dst, lane] != strash
        rd.rcv_idx[dst, lane] = strash
        before = telemetry.counter("events.plan_defect")
        health_before = telemetry.counter("events.health_error")
        last = telemetry.last_record()
        with pytest.raises(PlanSoundnessError) as ei:
            pv.check_plan(plan, context="chaos-twolevel")
        assert "coverage" in ei.value.diagnostics["checks"]
        d = ei.value.diagnostics["defects"][0]
        assert d["part"] is not None and d["check"]
        assert ei.value.diagnostics["context"] == "chaos-twolevel"
        # narrated (one plan_defect event per failing check class + the
        # health_error) and BEFORE any solve — no new SolveRecord
        assert telemetry.counter("events.plan_defect") == (
            before + len(ei.value.diagnostics["checks"])
        )
        assert telemetry.counter("events.health_error") == health_before + 1
        assert telemetry.last_record() is last
        return True

    _run(driver)


def test_matrix_never_returns_silently_wrong(monkeypatch):
    """The bottom line of the matrix: with the defense on, a PERSISTENT
    bitflip stream either heals or raises typed — across the whole
    ladder it never returns a wrong iterate labelled converged."""
    monkeypatch.setenv("PA_TPU_ABFT", "1")
    monkeypatch.setenv("PA_HEALTH_MAX_ROLLBACKS", "1")

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        with inject_faults("bitflip@part=*,after=0,bit=51,prob=0.5", seed=9):
            with pytest.raises(SilentCorruptionError):
                solve_with_recovery(
                    A, b, x0=x0, tol=1e-9, max_restarts=1
                )
        # even the give-up path is fully narrated: the aborted outer
        # record carries the detections, the exhausted rollbacks, the
        # escalation, and the abort marker
        aborted = telemetry.last_record("solve_with_recovery")
        assert aborted.status == "raised"
        assert aborted.error["type"] == "SilentCorruptionError"
        assert _has_event(aborted, "fault_injected", "bitflip")
        assert _has_event(aborted, "sdc_detection")
        assert _has_event(aborted, "sdc_escalation")
        assert _has_event(aborted, "solve_aborted", "SilentCorruptionError")
        return True

    _run(driver)


# ---------------------------------------------------------------------------
# round 14 — the front-door (pagate) rows
# ---------------------------------------------------------------------------


def test_matrix_gate_budget_exceeded_admission():
    """Gate row 1: an operator whose static footprint exceeds
    PA_GATE_MEM_BUDGET outright — the documented outcome is the typed
    TenantBudgetError at REGISTRATION (capacity planning, not
    per-request backpressure): the tenant is never admitted, the
    refusal is evented AND counted, and no service ever runs."""
    from partitionedarrays_jl_tpu.frontdoor import (
        Gate,
        TenantBudgetError,
    )

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        m0 = _metric_state("gate.budget_rejected")
        ev0 = telemetry.counter("events.tenant_budget_rejected")
        gate = Gate(mem_budget_bytes=4096)
        with pytest.raises(TenantBudgetError) as ei:
            gate.register("toolarge", A, footprint_bytes=8192)
        assert ei.value.diagnostics == {
            "tenant": "toolarge", "footprint_bytes": 8192,
            "budget_bytes": 4096,
        }
        m1 = _metric_state("gate.budget_rejected")
        assert m1["gate.budget_rejected"] == m0["gate.budget_rejected"] + 1
        assert telemetry.counter("events.tenant_budget_rejected") == ev0 + 1
        assert gate.residency() == []  # never admitted
        return True

    _run(driver)


def test_matrix_gate_load_shed_distinct_from_queue_full():
    """Gate row 2: overload past the shed watermark — the documented
    outcome for the LOWEST class is the typed LoadShedded carrying a
    retry_after_s (HTTP 429 + Retry-After on the wire), counted under
    gate.shed{slo_class=…} and narrated by the load_shedded event,
    while the queue-full AdmissionRejected reason counter does NOT
    move — the two overload behaviors stay separable in /metrics."""
    from partitionedarrays_jl_tpu.frontdoor import Gate, LoadShedded
    from partitionedarrays_jl_tpu.service import AdmissionRejected

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        gate = Gate(shed_watermark=1)
        gate.register("t", A, kmax=2)
        m0 = _metric_state(
            "gate.shed{slo_class=besteffort}",
            "service.rejected{reason=queue_full}",
        )
        ev0 = telemetry.counter("events.load_shedded")
        held = gate.submit("t", b, x0=x0, tol=1e-9,
                           slo_class="besteffort", tag="held")
        with pytest.raises(LoadShedded) as ei:
            gate.submit("t", b, x0=x0, tol=1e-9,
                        slo_class="besteffort", tag="over")
        assert not isinstance(ei.value, AdmissionRejected)
        assert ei.value.retry_after_s > 0.0
        assert ei.value.diagnostics["slo_class"] == "besteffort"
        assert ei.value.diagnostics["watermark"] == 1
        m1 = _metric_state(
            "gate.shed{slo_class=besteffort}",
            "service.rejected{reason=queue_full}",
        )
        assert m1["gate.shed{slo_class=besteffort}"] == (
            m0["gate.shed{slo_class=besteffort}"] + 1
        )
        assert m1["service.rejected{reason=queue_full}"] == (
            m0["service.rejected{reason=queue_full}"]
        ), "shedding must never masquerade as queue-full backpressure"
        assert telemetry.counter("events.load_shedded") == ev0 + 1
        # the held request is untouched: it drains to a clean result
        gate.drain()
        assert held.result()[1]["converged"]
        # patx continuity: the shed refusal is ONE one-span trace
        # (gate.shed, status=shed) — no dangling request spans — and
        # the held request's trace is a complete orphan-free tree
        from partitionedarrays_jl_tpu.telemetry import tracing

        spans = tracing.recorded_spans()
        shed_spans = [
            s for s in spans
            if s["kind"] == "gate.shed" and s["name"] == "over"
        ]
        assert len(shed_spans) == 1
        shed_tid = shed_spans[0]["trace_id"]
        assert tracing.verify_trace(spans, shed_tid) == []
        assert [
            s["kind"] for s in spans if s["trace_id"] == shed_tid
        ] == ["gate.shed"]
        assert shed_spans[0]["status"] == "shed"
        held_tid = held.trace.trace_id
        assert held_tid != shed_tid
        assert tracing.verify_trace(spans, held_tid) == []
        roots, orphans = tracing.span_tree(
            [s for s in spans if s["trace_id"] == held_tid]
        )
        assert len(roots) == 1 and not orphans
        return True

    _run(driver)


def test_matrix_gate_eviction_during_inflight_checkpoint_resume(tmp_path):
    """Gate row 3: a tenant is EVICTED while one of its chunked solves
    is in flight — the documented outcome is the PR 7 checkpoint path:
    the iterate checkpoints at the chunk boundary
    (request_checkpointed), the tenant pages out (tenant_evicted), the
    drained request re-enters the gate's EDF queue (tenant_requeued),
    and after the next page-in it RESUMES from the saved iterate
    (checkpoint_restore) and completes. Driven synchronously: the stop
    flag is raised mid-slab exactly as a live eviction's
    shutdown(drain=False) would at the next chunk boundary."""
    from partitionedarrays_jl_tpu.frontdoor import Gate

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (12, 12))
        x_direct, _ = cg(A, b, x0=x0, tol=1e-9)
        gate = Gate(checkpoint_dir=str(tmp_path))
        gate.register("t", A, kmax=2, chunk=4)
        m0 = _metric_state(
            "service.checkpointed", "gate.evictions", "gate.page_ins",
            "service.completed",
        )
        # a deadline-carrying request runs CHUNKED; dispatch it, then
        # signal stop mid-slab (what a concurrent eviction does) so
        # the first chunk boundary checkpoints the iterate
        h = gate.submit("t", b, x0=x0, tol=1e-9, deadline=3600.0,
                        slo_class="interactive", tag="inflight")
        gate.pump(dispatch_only=True)  # into the tenant's batcher
        svc = gate.service("t")
        svc._stop = True
        svc.step()  # one chunk, then checkpoint at the boundary
        assert h.request.state == "checkpointed"
        it_before = h.request.iterations
        assert it_before > 0
        rec_ck = h.request.record
        assert _has_event(rec_ck, "request_checkpointed", "inflight")
        ev_requeue0 = telemetry.counter("events.tenant_requeued")
        gate.evict("t")
        # the eviction requeued the checkpointed request with its
        # saved iterate as x0
        assert telemetry.counter("events.tenant_requeued") == (
            ev_requeue0 + 1
        )
        assert h.state == "gate-queued"
        assert h.kwargs["x0"] is not None
        res = {r["tenant"]: r for r in gate.residency()}
        assert not res["t"]["resident"]
        # drain: page back in, re-stage, resume from the checkpoint
        gate.drain()
        x, info = h.result()
        assert info["converged"]
        np.testing.assert_allclose(
            gather_pvector(x), gather_pvector(x_direct),
            rtol=0, atol=1e-6,
        )
        m1 = _metric_state(
            "service.checkpointed", "gate.evictions", "gate.page_ins",
            "service.completed",
        )
        d = {k: m1[k] - m0[k] for k in m0}
        assert d["service.checkpointed"] == 1, d
        assert d["gate.evictions"] == 1, d
        assert d["gate.page_ins"] == 1, d
        assert d["service.completed"] == 1, d
        # the resume is narrated end to end
        assert _has_event(h.request.record, "request_done", "inflight")
        assert telemetry.counter("events.checkpoint_restore") > 0
        # patx continuity: the whole eviction/requeue/resume story is
        # ONE trace — the root, BOTH gate-queue waits (the requeue
        # flagged), the checkpointed AND the resumed slab rides, the
        # re-stage page-in — with correct parentage and zero orphans
        from partitionedarrays_jl_tpu.telemetry import tracing

        gate.account()
        tid = h.trace.trace_id
        spans = tracing.recorded_spans()
        assert tracing.verify_trace(spans, tid) == []
        mine = [s for s in spans if s["trace_id"] == tid]
        roots, orphans = tracing.span_tree(mine)
        assert len(roots) == 1 and not orphans
        assert roots[0]["kind"] == "rpc.request"
        queues = [s for s in mine if s["kind"] == "gate.queue"]
        assert len(queues) == 2
        assert [bool(s["attrs"].get("requeued")) for s in queues].count(
            True
        ) == 1
        solves = [s for s in mine if s["kind"] == "slab.solve"]
        assert {s["status"] for s in solves} == {"checkpointed", "ok"}
        assert any(s["kind"] == "tenant.page_in" for s in mine), (
            "the re-stage page-in must land in the request's trace"
        )
        by_id = {s["span_id"]: s for s in mine}
        for s in queues + solves:
            assert by_id[s["parent_id"]]["kind"] == "rpc.request"
        return True

    _run(driver)


# ---------------------------------------------------------------------------
# round 15 — the durability (padur) rows
# ---------------------------------------------------------------------------


def test_matrix_gate_crash_midsolve_journal_recovery(tmp_path):
    """Durability row 1: the gate dies mid-solve (kill -9 semantics —
    the first gate's state is ABANDONED, no shutdown or eviction path
    runs). The write-ahead journal has the admitted/dispatched/chunk
    records, so a fresh gate over the same journal dir resumes the
    request from its chunk-checkpointed iterate and COMPLETES it:
    gate.recovered{outcome=resumed} counts it, request_recovered /
    gate_recovered / checkpoint_restore narrate it, and the journal
    ends with exactly one completed record for the rid (zero lost,
    zero duplicated)."""
    from partitionedarrays_jl_tpu.frontdoor import Gate, read_journal

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (12, 12))
        x_direct, _ = cg(A, b, x0=x0, tol=1e-9)
        jd = str(tmp_path / "journal")
        g1 = Gate(journal_dir=jd, checkpoint_dir=str(tmp_path / "c1"))
        g1.register("t", A, kmax=2, chunk=4)
        h = g1.submit("t", b, x0=x0, tol=1e-9, deadline=3600.0,
                      slo_class="interactive", tag="crashy",
                      idempotency_key="crash-key")
        g1.pump(dispatch_only=True)
        svc = g1.service("t")
        svc._stop = True  # freeze after one chunk: the kill window
        svc.step()
        assert h.request.iterations > 0
        # ---- crash: g1 is abandoned with its request mid-flight ----
        m0 = _metric_state(
            "gate.recovered{outcome=resumed}", "service.completed",
        )
        ev0 = telemetry.counter("events.request_recovered")
        evg0 = telemetry.counter("events.gate_recovered")
        evr0 = telemetry.counter("events.checkpoint_restore")
        g2 = Gate(journal_dir=jd, checkpoint_dir=str(tmp_path / "c2"))
        g2.register("t", A, kmax=2, chunk=4)
        summary = g2.recover()
        assert summary["resumed"] == 1, summary
        assert telemetry.counter("events.request_recovered") == ev0 + 1
        assert telemetry.counter("events.gate_recovered") == evg0 + 1
        assert telemetry.counter("events.checkpoint_restore") == evr0 + 1
        g2.drain()
        x, info = g2.handle(h.rid).result()
        assert info["converged"]
        np.testing.assert_allclose(
            gather_pvector(x), gather_pvector(x_direct),
            rtol=0, atol=1e-6,
        )
        m1 = _metric_state(
            "gate.recovered{outcome=resumed}", "service.completed",
        )
        d = {k: m1[k] - m0[k] for k in m0}
        assert d["gate.recovered{outcome=resumed}"] == 1, d
        assert d["service.completed"] == 1, d
        completed = [
            r for r in read_journal(jd)
            if r.get("kind") == "completed" and r.get("rid") == h.rid
        ]
        assert len(completed) == 1, "zero lost, zero duplicated"
        # patx continuity: the recovered request keeps its ORIGINAL
        # trace_id; the post-crash root stitches to the pre-crash root
        # (left interrupted by the abandoned gate); zero orphans — one
        # tree across the "kill"
        from partitionedarrays_jl_tpu.telemetry import tracing

        g2.account()
        h2 = g2.handle(h.rid)
        tid = h.trace.trace_id
        assert h2.trace.trace_id == tid, (
            "recovery must preserve the original trace_id"
        )
        spans = tracing.recorded_spans()
        assert tracing.verify_trace(spans, tid) == []
        mine = [s for s in spans if s["trace_id"] == tid]
        roots_list = [s for s in mine if s["kind"] == "rpc.request"]
        pre = [s for s in roots_list if not s["attrs"].get("recovered")]
        post = [s for s in roots_list if s["attrs"].get("recovered")]
        assert len(pre) == 1 and len(post) == 1
        assert pre[0]["status"] == "interrupted", (
            "the abandoned gate's root must surface as interrupted"
        )
        assert post[0]["parent_id"] == pre[0]["span_id"], (
            "the recovered root must parent to the pre-crash root"
        )
        assert post[0]["attrs"]["recovered"] == "resumed"
        _, orphans = tracing.span_tree(mine)
        assert not orphans
        return True

    _run(driver)


def test_matrix_torn_journal_tail_truncates_typed(tmp_path):
    """Durability row 2: a crash mid-append tears the LAST journal
    record — replay truncates it (journal.truncated counter +
    journal_truncated event) and the clean prefix recovers intact; a
    defective record that is NOT the tail is real corruption and
    raises the typed JournalCorruptError instead of silently dropping
    acknowledged history."""
    from partitionedarrays_jl_tpu.frontdoor import (
        Gate,
        JournalCorruptError,
        RequestJournal,
        read_journal,
    )

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        jd = str(tmp_path / "journal")
        g1 = Gate(journal_dir=jd)
        g1.register("t", A, kmax=4)
        h = g1.submit("t", b, x0=x0, tol=1e-9, tag="pre-tear")
        g1.drain()
        x1 = gather_pvector(h.result()[0])
        # tear the tail: a half-written record, as a crash mid-append
        # would leave it
        last = sorted(g1.journal.segments())[-1]
        with open(last, "ab") as f:
            f.write(b'{"kind":"completed","seq":999,"x":[0.123')
        m0 = _metric_state("journal.truncated")
        ev0 = telemetry.counter("events.journal_truncated")
        g2 = Gate(journal_dir=jd)
        g2.register("t", A, kmax=4)
        summary = g2.recover()
        m1 = _metric_state("journal.truncated")
        assert m1["journal.truncated"] == m0["journal.truncated"] + 1
        assert telemetry.counter("events.journal_truncated") == ev0 + 1
        # the clean prefix survived: the completed request still serves
        assert summary["completed"] == 1, summary
        np.testing.assert_array_equal(
            g2.handle(h.rid).result()[0], x1
        )
        # mid-file corruption is NOT a torn tail: typed refusal
        jc = str(tmp_path / "corrupt")
        jx = RequestJournal(jc, fsync=False)
        jx.append("shed", tag="aaaa", slo_class="x", depth=0)
        jx.append("shed", tag="bbbb", slo_class="x", depth=1)
        jx.close()
        seg = sorted(jx.segments())[0]
        data = bytearray(open(seg, "rb").read())
        data[data.find(b"aaaa")] = ord("z")
        open(seg, "wb").write(bytes(data))
        with pytest.raises(JournalCorruptError):
            read_journal(jc, strict=True)
        return True

    _run(driver)


def test_matrix_duplicate_idempotency_key_single_solve(tmp_path):
    """Durability row 3: a duplicate idempotency-key submit — the
    retried-timed-out-POST scenario — returns the ORIGINAL id and
    bitwise result and never starts a second solve: gate.idempotent_hits
    counts it, idempotent_replay narrates it, and service.admitted does
    not move; the key map survives a gate restart via the journal."""
    from partitionedarrays_jl_tpu.frontdoor import Gate

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        jd = str(tmp_path / "journal")
        g1 = Gate(journal_dir=jd)
        g1.register("t", A, kmax=4)
        h1 = g1.submit("t", b, x0=x0, tol=1e-9, tag="orig",
                       idempotency_key="dup-key")
        g1.drain()
        x1 = gather_pvector(h1.result()[0])
        m0 = _metric_state(
            "gate.idempotent_hits", "service.admitted",
            "service.completed",
        )
        ev0 = telemetry.counter("events.idempotent_replay")
        h2 = g1.submit("t", b, idempotency_key="dup-key")
        assert h2 is h1, "the original handle, not a second request"
        np.testing.assert_array_equal(gather_pvector(h2.result()[0]), x1)
        m1 = _metric_state(
            "gate.idempotent_hits", "service.admitted",
            "service.completed",
        )
        d = {k: m1[k] - m0[k] for k in m0}
        assert d["gate.idempotent_hits"] == 1, d
        assert d["service.admitted"] == 0, "a replay admits NOTHING"
        assert d["service.completed"] == 0, d
        assert telemetry.counter("events.idempotent_replay") == ev0 + 1
        # across a crash: the journal rebuilds the key map
        g2 = Gate(journal_dir=jd)
        g2.register("t", A, kmax=4)
        g2.recover()
        h3 = g2.submit("t", b, idempotency_key="dup-key")
        assert h3.rid == h1.rid
        np.testing.assert_array_equal(h3.result()[0], x1)
        m2 = _metric_state("gate.idempotent_hits", "service.admitted")
        assert m2["gate.idempotent_hits"] == (
            m1["gate.idempotent_hits"] + 1
        )
        assert m2["service.admitted"] == m1["service.admitted"]
        return True

    _run(driver)


# ---------------------------------------------------------------------------
# round 16 — the fleet (pafleet) rows
# ---------------------------------------------------------------------------


def test_matrix_fleet_replica_death_peer_adopts_journal(tmp_path):
    """Fleet row 1: a replica dies with kill -9 semantics (state
    abandoned, lease goes stale) while holding a queued request — the
    documented outcome is journal-backed peer failover: the
    rendezvous-ranked survivor counts the missed lease, adopts the
    victim's journal, and completes the request under its ORIGINAL rid
    bitwise-equal to the solo solve (zero lost); the adopted marker in
    the victim's journal makes a restarted victim refuse typed
    (AdoptedByPeer — zero duplicated), exactly one completed record
    exists across the journal union, and patx stitches ONE trace
    across the replica hop."""
    import os
    import time

    from partitionedarrays_jl_tpu.frontdoor import (
        Gate,
        RecoveredError,
        fleet,
        read_journal,
    )

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        x_direct, _ = cg(A, b, x0=x0, tol=1e-9)
        fd = str(tmp_path / "fleet")
        g0dir = os.path.join(fd, "g0")
        os.makedirs(g0dir)
        victim = Gate(journal_dir=g0dir, rid_namespace="g0")
        victim.register("t", A, kmax=4)
        h = victim.submit("t", b, x0=x0, tol=1e-9, tag="orphaned",
                          idempotency_key="fleet-key")
        fleet.write_lease(
            os.path.join(g0dir, fleet.LEASE_NAME), "g0", depth=1
        )
        # ---- kill -9: the victim is abandoned mid-queue ----
        survivor = Gate(
            journal_dir=os.path.join(fd, "g1"), rid_namespace="g1"
        )
        survivor.register("t", A, kmax=4)
        member = fleet.FleetMember(fd, "g1", survivor, lease_s=0.05)
        member.heartbeat()
        m0 = _metric_state(
            "fleet.lease_missed", "fleet.adopted{outcome=requeued}",
            "service.admitted",
        )
        ev0 = telemetry.counter("events.fleet_lease_missed")
        eva0 = telemetry.counter("events.request_adopted")
        time.sleep(0.2)  # > 3 x lease_s: the victim's lease is stale
        adopted = member.check_peers()
        assert set(adopted) == {"g0"}, adopted
        assert adopted["g0"]["requeued"] == 1, adopted
        m1 = _metric_state(
            "fleet.lease_missed", "fleet.adopted{outcome=requeued}",
            "service.admitted",
        )
        d = {k: m1[k] - m0[k] for k in m0}
        assert d["fleet.lease_missed"] == 1, d
        assert d["fleet.adopted{outcome=requeued}"] == 1, d
        assert telemetry.counter("events.fleet_lease_missed") == ev0 + 1
        assert telemetry.counter("events.request_adopted") == eva0 + 1
        # the sweep is once-per-death: a second pass adopts nothing
        assert member.check_peers() == {}
        # the ORIGINAL rid completes on the survivor, bitwise
        survivor.drain()
        x, info = survivor.handle(h.rid).result()
        assert info["converged"]
        np.testing.assert_array_equal(
            gather_pvector(x), gather_pvector(x_direct)
        )
        # zero lost, zero duplicated: one completed record across the
        # union, and the victim's journal carries the adopted marker
        union = read_journal(g0dir) + read_journal(
            os.path.join(fd, "g1")
        )
        completed = [
            r for r in union
            if r.get("kind") == "completed" and r.get("rid") == h.rid
        ]
        assert len(completed) == 1, "exactly one solve fleet-wide"
        assert any(
            r.get("kind") == "adopted" and r.get("rid") == h.rid
            and r.get("by") == "g1"
            for r in read_journal(g0dir)
        )
        # a RESTARTED victim folds the marker and refuses typed —
        # never a second solve (service.admitted moved exactly once)
        back = Gate(journal_dir=g0dir, rid_namespace="g0")
        back.register("t", A, kmax=4)
        s = back.recover()
        assert s["adopted_away"] == 1, s
        with pytest.raises(RecoveredError, match="adopted") as ei:
            back.handle(h.rid).result()
        assert ei.value.error_type == "AdoptedByPeer"
        m2 = _metric_state("service.admitted")
        assert m2["service.admitted"] == m0["service.admitted"] + 1
        # an idempotent resubmit against the survivor replays the
        # original rid (the key map crossed the hop with the journal)
        assert survivor.submit(
            "t", b, idempotency_key="fleet-key"
        ).rid == h.rid
        # patx continuity: ONE trace — the adopted root parents into
        # the victim's interrupted root, zero orphans
        from partitionedarrays_jl_tpu.telemetry import tracing

        survivor.account()
        tid = h.trace.trace_id
        spans = tracing.recorded_spans()
        assert tracing.verify_trace(spans, tid) == []
        mine = [s for s in spans if s["trace_id"] == tid]
        roots = [s for s in mine if s["kind"] == "rpc.request"]
        pre = [s for s in roots if not s["attrs"].get("recovered")]
        post = [s for s in roots if s["attrs"].get("recovered")]
        # the survivor's adoption AND the restarted victim's
        # adopted_away terminal each stitch a recovered root — both
        # must parent into the single interrupted pre-crash root
        assert len(pre) == 1 and len(post) >= 1
        assert all(s["parent_id"] == pre[0]["span_id"] for s in post)
        assert any(
            s["attrs"].get("adopted_from") == g0dir for s in post
        )
        _, orphans = tracing.span_tree(mine)
        assert not orphans
        return True

    _run(driver)


def test_matrix_fleet_shed_forward_redirect(tmp_path):
    """Fleet row 2: overload on one replica while a live-leased peer
    has headroom — the documented outcome is a 307 shed-forward
    (fleet.forwarded + fleet_forwarded) instead of the 429: the client
    reposts the identical body to the peer, the request SOLVES there
    (rid carries the peer's namespace), and the whole exchange — the
    shed refusal on the owner plus the solve on the peer — is ONE
    stitched trace."""
    import os

    from partitionedarrays_jl_tpu.frontdoor import (
        Gate,
        fleet,
        http_solve,
        serve_gate,
    )
    from partitionedarrays_jl_tpu.models.solvers import gather_pvector

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        fd = str(tmp_path / "fleet")
        g0 = Gate(shed_watermark=1, rid_namespace="g0")
        g0.register("t", A, kmax=4)
        g1 = Gate(rid_namespace="g1", start_workers=True)
        g1.register("t", A, kmax=4)
        srv0, srv1 = serve_gate(g0, port=0), serve_gate(g1, port=0)
        try:
            m0f = fleet.FleetMember(fd, "g0", g0, server=srv0,
                                    lease_s=30.0)
            m1f = fleet.FleetMember(fd, "g1", g1, server=srv1,
                                    lease_s=30.0)
            m0f.heartbeat()
            m1f.heartbeat()
            m0f.map.write_url("g0", srv0.url)
            m1f.map.write_url("g1", srv1.url)
            srv0.peer_picker = m0f.pick_peer
            # build g0's backlog past the watermark with dispatch held
            g0.paused = True
            held = g0.submit("t", b, x0=x0, tol=1e-9,
                             slo_class="interactive", tag="held")
            m0 = _metric_state(
                "fleet.forwarded", "gate.shed{slo_class=besteffort}",
            )
            ev0 = telemetry.counter("events.fleet_forwarded")
            bg, x0g = gather_pvector(b), gather_pvector(x0)
            out = http_solve(
                srv0.url, "t", bg, x0=x0g, tol=1e-9,
                slo_class="besteffort", tag="forwarded",
                idempotency_key="fwd-key",
            )
            assert out["state"] == "done", out
            assert out["id"].startswith("g1-"), (
                "the solve must land on the PEER's rid namespace"
            )
            m1 = _metric_state(
                "fleet.forwarded", "gate.shed{slo_class=besteffort}",
            )
            d = {k: m1[k] - m0[k] for k in m0}
            assert d["fleet.forwarded"] == 1, d
            assert d["gate.shed{slo_class=besteffort}"] == 1, (
                "the shed still counts — forwarding rides ON the "
                "refusal, it does not hide it"
            )
            assert telemetry.counter("events.fleet_forwarded") == (
                ev0 + 1
            )
            # one stitched trace: the owner's shed span AND the peer's
            # request tree share the client's trace id, zero orphans
            from partitionedarrays_jl_tpu.telemetry import tracing

            g1.account()
            tid = out["trace_id"]
            spans = tracing.recorded_spans()
            assert tracing.verify_trace(spans, tid) == []
            mine = [s for s in spans if s["trace_id"] == tid]
            kinds = {s["kind"] for s in mine}
            assert "gate.shed" in kinds, "the refusal is in-trace"
            assert "rpc.request" in kinds, "the peer solve is in-trace"
            _, orphans = tracing.span_tree(mine)
            assert not orphans
            # the held request was untouched by the forward
            g0.paused = False
            g0.drain()
            assert held.result()[1]["converged"]
        finally:
            srv0.stop(drain=False)
            srv1.stop(drain=False)
        return True

    _run(driver)


def test_matrix_fleet_torn_lease_refuses_takeover(tmp_path):
    """Fleet row 3: a peer's lease file is torn (crash or disk fault
    mid-write straight to the final name) — the documented outcome is
    the typed `LeaseCorruptError` REFUSING takeover: a corrupt lease
    is evidence of unknown state, not of death, and a false takeover
    (two replicas solving one journal) is the one unrecoverable
    outcome. No adoption happens, no adopted marker lands, the
    fleet.lease_missed/fleet.adopted counters do NOT move, and
    pick_peer degrades to None (the 429 fallback) instead of
    forwarding into the unknown."""
    import os

    from partitionedarrays_jl_tpu.frontdoor import (
        Gate,
        LeaseCorruptError,
        fleet,
        read_journal,
    )

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        fd = str(tmp_path / "fleet")
        g0dir = os.path.join(fd, "g0")
        os.makedirs(g0dir)
        victim = Gate(journal_dir=g0dir, rid_namespace="g0")
        victim.register("t", A, kmax=4)
        victim.submit("t", b, x0=x0, tol=1e-9, tag="in-limbo")
        lease_path = os.path.join(g0dir, fleet.LEASE_NAME)
        fleet.write_lease(lease_path, "g0", depth=1)
        raw = open(lease_path).read()
        open(lease_path, "w").write(raw[: len(raw) // 2])  # torn
        survivor = Gate(
            journal_dir=os.path.join(fd, "g1"), rid_namespace="g1"
        )
        survivor.register("t", A, kmax=4)
        member = fleet.FleetMember(fd, "g1", survivor, lease_s=0.05)
        member.heartbeat()
        m0 = _metric_state("fleet.lease_missed")
        a0 = sum(
            v for k, v in telemetry.registry().snapshot()[
                "counters"
            ].items() if k.startswith("fleet.adopted")
        )
        with pytest.raises(LeaseCorruptError, match="refusing"):
            member.check_peers()
        m1 = _metric_state("fleet.lease_missed")
        a1 = sum(
            v for k, v in telemetry.registry().snapshot()[
                "counters"
            ].items() if k.startswith("fleet.adopted")
        )
        assert m1["fleet.lease_missed"] == m0["fleet.lease_missed"], (
            "a corrupt lease is NOT a missed lease"
        )
        assert a1 == a0, "no adoption on a refused takeover"
        assert not any(
            r.get("kind") == "adopted" for r in read_journal(g0dir)
        ), "no adopted marker may land on a refusal"
        assert member.pick_peer() is None, (
            "forwarding degrades to the 429 fallback, never a guess"
        )
        # a fresh heartbeat heals the lease and the fleet resumes:
        # g0 is live again, so the sweep finds nothing stale
        fleet.write_lease(lease_path, "g0", depth=1)
        assert member.check_peers() == {}
        return True

    _run(driver)


# ---------------------------------------------------------------------------
# round 17 — the convergence-observatory (paspec) row
# ---------------------------------------------------------------------------


def test_matrix_infeasible_deadline_refused_at_admission(monkeypatch):
    """Paspec row: an infeasible-deadline request under PA_SPEC_ADMIT=1
    is refused typed AT ADMISSION — never dispatched, zero solver
    iterations burned — with the full event trail and metric deltas,
    and stays DISTINCT from the queue-full, shed, and expiry rows (its
    own type, its own counter, its own event kind)."""
    from partitionedarrays_jl_tpu.parallel.health import (
        DeadlineInfeasible,
        SolveDeadlineError,
    )
    from partitionedarrays_jl_tpu.service import (
        AdmissionRejected,
        SolveService,
    )

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        svc = SolveService(A, kmax=2)
        # train: one completed request measures spectrum + throughput
        h = svc.submit(b, x0=x0, tol=1e-9, tag="train")
        svc.drain()
        assert h.result()[1]["converged"]
        m0 = _metric_state(
            "spec.infeasible", "service.admitted", "service.completed",
            "service.deadline_expired",
            "service.rejected{reason=queue_full}",
            "events.deadline_infeasible", "events.health_error",
        )
        slabs0 = svc.stats["slabs"]
        monkeypatch.setenv("PA_SPEC_ADMIT", "1")
        with pytest.raises(DeadlineInfeasible) as ei:
            svc.submit(b, x0=x0, tol=1e-9, deadline=1e-9, tag="doomed")
        # typed + diagnosable: the prediction that refused it is on the
        # error, and the type is NONE of its refusal-ladder neighbors
        d = ei.value.diagnostics
        assert d["predicted_s"] > d["available_s"]
        assert d["predicted_iters"] >= 1 and d["s_per_it"] > 0
        assert not isinstance(ei.value, SolveDeadlineError)
        assert not isinstance(ei.value, AdmissionRejected)
        m1 = _metric_state(
            "spec.infeasible", "service.admitted", "service.completed",
            "service.deadline_expired",
            "service.rejected{reason=queue_full}",
            "events.deadline_infeasible", "events.health_error",
        )
        delta = {k: m1[k] - m0[k] for k in m0}
        # its own counter and events moved ...
        assert delta["spec.infeasible"] == 1, delta
        assert delta["events.deadline_infeasible"] == 1, delta
        assert delta["events.health_error"] == 1, delta
        # ... and NOTHING was admitted, dispatched, or mis-binned into
        # the neighboring refusal rows: zero iterations spent
        assert delta["service.admitted"] == 0, delta
        assert delta["service.deadline_expired"] == 0, delta
        assert delta["service.rejected{reason=queue_full}"] == 0, delta
        assert svc.stats["slabs"] == slabs0
        assert svc.stats["infeasible"] == 1
        # default-off contract: the same hopeless deadline is ADMITTED
        # with PA_SPEC_ADMIT unset (pre-paspec behavior preserved —
        # whatever happens next is the post-hoc chunk-boundary expiry
        # row's business, not admission's)
        monkeypatch.delenv("PA_SPEC_ADMIT")
        h2 = svc.submit(b, x0=x0, tol=1e-9, deadline=1e-9, tag="legacy")
        m2 = _metric_state("service.admitted")
        assert m2["service.admitted"] == m1["service.admitted"] + 1
        svc.drain()
        assert h2.done()
        return True

    _run(driver)


# ---------------------------------------------------------------------------
# round 19 — the part-loss (paelastic) rows
# ---------------------------------------------------------------------------


def test_matrix_part_loss_elastic_shrinks_and_resumes(
    tmp_path, monkeypatch
):
    """Paelastic row 1: a lost part under PA_ELASTIC=1 shrinks the
    partition over the survivors and resumes from the last chunk
    checkpoint — one stitched event trail + metric deltas + the
    tenant.repartition span, and the next full-capacity solve
    announces grow-back."""
    from partitionedarrays_jl_tpu.parallel import elastic
    from partitionedarrays_jl_tpu.models.solvers import solve_with_recovery
    from partitionedarrays_jl_tpu.telemetry.tracing import (
        clear_spans,
        recorded_spans,
    )

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        x_clean, _ = cg(A, b, x0=x0, tol=1e-9)
        elastic._DEGRADED.clear()
        m0 = _metric_state(
            "elastic.shrink{reason=part_loss}",
            "elastic.crosspart_restores",
            "events.elastic_shrink", "events.elastic_restore",
        )
        clear_spans()
        monkeypatch.setenv("PA_ELASTIC", "1")
        with inject_faults("part_loss@part=3,after=6", seed=1):
            x, info = solve_with_recovery(
                A, b, x0=x0, checkpoint_dir=str(tmp_path), every=3,
                tol=1e-9,
            )
        monkeypatch.delenv("PA_ELASTIC")
        # the elastic ledger: 4 -> 2 survivors, resumed from the last
        # chunk checkpoint, converged to the clean answer — and NO
        # restart budget burned on the casualty
        el = info["elastic"]
        assert el["from_parts"] == 4 and el["to_parts"] == 2
        assert el["dead_part"] == 3
        assert el["checkpoint_iteration"] and el["checkpoint_iteration"] > 0
        assert info["converged"] and info["restarts"] == 0
        assert (
            np.abs(gather_pvector(x) - gather_pvector(x_clean)).max()
            < 1e-7
        )
        srcs = info["recovery"]["restart_sources"]
        assert [s["from"] for s in srcs] == ["elastic_shrink_checkpoint"]
        assert info["recovery"]["checkpoint_restarts"] == 1
        # the stitched trail: every stage narrates ...
        rec = telemetry.last_record("solve_with_recovery")
        assert _has_event(rec, "fault_injected", "part_loss")
        assert _has_event(rec, "health_error", "PartLossError")
        assert _has_event(rec, "elastic_shrink", "part_loss")
        assert _has_event(rec, "checkpoint_restore")
        assert _has_event(rec, "restart", "PartLossError")
        # ... and counts (event log and metrics plane agree)
        m1 = _metric_state(
            "elastic.shrink{reason=part_loss}",
            "elastic.crosspart_restores",
            "events.elastic_shrink", "events.elastic_restore",
        )
        assert m1["elastic.shrink{reason=part_loss}"] \
            - m0["elastic.shrink{reason=part_loss}"] == 1
        assert m1["elastic.crosspart_restores"] \
            - m0["elastic.crosspart_restores"] == 1
        assert m1["events.elastic_shrink"] \
            - m0["events.elastic_shrink"] == 1
        assert m1["events.elastic_restore"] \
            - m0["events.elastic_restore"] == 0
        spans = [
            s for s in recorded_spans()
            if s["kind"] == "tenant.repartition"
        ]
        assert len(spans) == 1
        assert spans[0]["attrs"]["from_parts"] == 4
        assert spans[0]["attrs"]["to_parts"] == 2
        # grow back: capacity returned — the next full-grid solve says so
        x2, info2 = solve_with_recovery(A, b, x0=x0, tol=1e-9)
        rec2 = telemetry.last_record("solve_with_recovery")
        assert _has_event(rec2, "elastic_restore", "grow_back")
        assert not elastic.degraded_state()
        return True

    _run(driver)


def test_matrix_part_loss_without_elastic_escalates_typed(monkeypatch):
    """Paelastic row 2: with PA_ELASTIC=0 a lost part escalates as a
    typed PartLossError to the caller's checkpoint tier IMMEDIATELY —
    no same-partition retry loop, zero restarts attempted, no restart
    events — because the casualty's contribution can never arrive."""
    from partitionedarrays_jl_tpu.parallel.health import PartLossError
    from partitionedarrays_jl_tpu.models.solvers import solve_with_recovery

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        monkeypatch.delenv("PA_ELASTIC", raising=False)
        m0 = _metric_state("events.restart", "events.elastic_shrink")
        with inject_faults("part_loss@part=3,after=6", seed=1):
            with pytest.raises(PartLossError) as ei:
                solve_with_recovery(A, b, x0=x0, tol=1e-9, max_restarts=2)
        # typed + diagnosable: the dead part and the exchange call are
        # on the error, and the loss is NOT a timeout
        from partitionedarrays_jl_tpu.parallel.health import (
            ExchangeTimeoutError,
        )

        assert ei.value.diagnostics["part"] == 3
        assert ei.value.diagnostics["call"] == 6
        assert not isinstance(ei.value, ExchangeTimeoutError)
        # the aborted record carries the whole story ...
        aborted = telemetry.last_record("solve_with_recovery")
        assert aborted.status == "raised"
        assert _has_event(aborted, "fault_injected", "part_loss")
        assert _has_event(aborted, "health_error", "PartLossError")
        # ... and NO restart was attempted or narrated: the budget was
        # not burned spinning on a permanent casualty
        assert not _has_event(aborted, "restart")
        m1 = _metric_state("events.restart", "events.elastic_shrink")
        assert m1["events.restart"] - m0["events.restart"] == 0
        assert m1["events.elastic_shrink"] \
            - m0["events.elastic_shrink"] == 0
        return True

    _run(driver)


# ---------------------------------------------------------------------------
# round 20: palock — thread lifecycle
# ---------------------------------------------------------------------------


def test_matrix_drained_shutdown_leaves_zero_live_threads(tmp_path):
    """Palock row: the thread-shutdown audit, live. Every component
    that spawns threads (the service worker, the fleet member's
    beat/watch pair) must return the process to its pre-start
    live-thread baseline on a drained shutdown/stop — the dynamic twin
    of the static leaked-thread check (which proves, at the AST level,
    that every `threading.Thread` in the package has a join on some
    shutdown path; DAEMON_WAIVERS is empty because nothing needs
    waiving)."""
    import os
    import threading

    from partitionedarrays_jl_tpu.frontdoor import Gate, fleet
    from partitionedarrays_jl_tpu.service import SolveService

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        baseline = set(threading.enumerate())
        # -- the service worker: start -> submit -> drained shutdown --
        svc = SolveService(A, kmax=2).start()
        h = svc.submit(b, x0=x0, tol=1e-9)
        stats = svc.shutdown(drain=True)
        assert stats["completed"] == 1 and h.result()[1]["converged"]
        assert not svc._worker.is_alive()
        # -- the fleet member's beat/watch pair: start -> stop --------
        fd = str(tmp_path / "fleet")
        os.makedirs(os.path.join(fd, "g0"), exist_ok=True)
        gate = Gate(journal_dir=os.path.join(fd, "g0"),
                    rid_namespace="g0")
        member = fleet.FleetMember(fd, "g0", gate, lease_s=0.05).start()
        assert any(
            t.name.startswith("pafleet-") for t in threading.enumerate()
        )
        member.stop()
        assert member._threads == []
        # -- the baseline holds: nothing outlived its owner -----------
        leaked = [
            t for t in threading.enumerate()
            if t not in baseline and t.is_alive()
        ]
        assert leaked == [], f"threads outlived shutdown: {leaked}"
        # non-daemon leaks would also hang interpreter exit — assert
        # the stronger process-wide property directly
        assert [
            t for t in threading.enumerate()
            if not t.daemon and t is not threading.main_thread()
            and t not in baseline
        ] == []
        return True

    _run(driver)
