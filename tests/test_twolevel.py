"""panode — the node-aware two-level exchange plan (round 18).

The ISSUE-18 acceptance contracts, each pinned here:

* **Same delivery, different schedule.** The two-level plan's
  base-class state IS the flat logical-delivery view: all five PR 8
  plan-verifier checks pass on it unchanged (both plan families), the
  logical index arrays equal the flat plan's bit-for-bit, and the
  host plan's `canonical_exchange_fingerprint` is invariant across
  flat <-> two-level construction.
* **Bitwise identity.** Every schedule hop is a pure copy, so the CG
  trajectory with the two-level plan on is bit-for-bit the flat
  plan's on the 4-part conformance fixture — residual history AND
  solution. Under strict-bits the env resolves to the flat plan (the
  bitwise oracle), pinned as lowered-program identity.
* **Measured, not guessed.** ``PA_TPU_TWOLEVEL=auto`` builds the
  two-level plan only where `twolevel_decision`'s cost model says
  aggregation pays (node pairs < slow edges); a chain topology whose
  aggregation buys nothing keeps the flat plan.
* **One fabric view (the bench_ici threading bugfix).** A node map
  set through ``PA_TPU_NODE_MAP`` reaches BOTH plan construction and
  the comms-matrix edge labels — `classify_edge`'s ``node_of``
  priority beats the backend's process indices, and
  `tools/bench_ici.comms_record` commits the same view the plan was
  built from.
"""
import importlib.util
import os

import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu.analysis import plan_verifier as pv
from partitionedarrays_jl_tpu.models import assemble_poisson, gather_pvector
from partitionedarrays_jl_tpu.parallel.tpu import (
    TPUBackend,
    TWOLEVEL_TIERS,
    TwoLevelDeviceExchangePlan,
    _matrix_operands,
    device_exchange_plan,
    device_matrix,
    make_cg_fn,
    tpu_cg,
)
from partitionedarrays_jl_tpu.telemetry import commsmatrix as cmx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _backend(n=4):
    import jax

    return TPUBackend(devices=jax.devices()[:n])


# ---------------------------------------------------------------------------
# plan soundness: five checks, logical-view equality, fingerprints
# ---------------------------------------------------------------------------


def test_twolevel_generic_plan_passes_checks_and_keeps_delivery(
    monkeypatch,
):
    assert len(pv.PLAN_CHECKS) == 5
    monkeypatch.setenv("PA_TPU_BOX", "0")

    def driver(parts):
        A, _b, _xe, _x0 = assemble_poisson(parts, (8, 8))
        rows = A.cols
        ref = pv.referenced_ghosts(A)
        canon = pv.canonical_exchange_fingerprint(
            rows.exchanger, rows.partition
        )
        flat = device_exchange_plan(rows)
        assert not hasattr(flat, "tl_rounds")

        monkeypatch.setenv("PA_TPU_TWOLEVEL", "1")
        monkeypatch.setenv("PA_TPU_NODE_MAP", "0,0,1,1")
        plan = device_exchange_plan(rows)
        assert isinstance(plan, TwoLevelDeviceExchangePlan)
        assert plan is not flat
        # all five checks on the logical view + the schedule simulation
        assert pv.verify_plan(plan, referenced=ref) == []
        # the logical-delivery view IS the flat plan's, bit for bit
        assert plan.perms == flat.perms
        for attr in ("snd_idx", "snd_mask", "rcv_idx"):
            assert np.array_equal(
                getattr(plan, attr), getattr(flat, attr)
            ), attr
        # two-level construction staged nothing into the HOST plan
        assert pv.canonical_exchange_fingerprint(
            rows.exchanger, rows.partition
        ) == canon
        # schedule structure: known tiers only, the node tier crosses
        # the slow fabric and everything else stays fast
        tiers = [rd.tier for rd in plan.tl_rounds]
        assert set(tiers) <= set(TWOLEVEL_TIERS)
        assert "node" in tiers
        for rd in plan.tl_rounds:
            fabric = plan.fabric_of_round(rd)
            assert fabric == ("dcn" if rd.tier == "node" else "ici")
        assert plan.wire_rounds == sum(
            1 for rd in plan.tl_rounds if rd.perm
        )
        assert plan.node_of == (0, 0, 1, 1)
        assert plan.decision["use"] is True
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_twolevel_box_plan_passes_checks(monkeypatch):
    """The box-family sibling: the default cartesian partition keeps
    its box structure and still aggregates through the node tier."""
    from partitionedarrays_jl_tpu.parallel.tpu_box import (
        TwoLevelBoxExchangePlan,
    )

    def driver(parts):
        A, _b, _xe, _x0 = assemble_poisson(parts, (8, 8))
        rows = A.cols
        ref = pv.referenced_ghosts(A)
        monkeypatch.setenv("PA_TPU_TWOLEVEL", "1")
        monkeypatch.setenv("PA_TPU_NODE_MAP", "0,0,1,1")
        plan = device_exchange_plan(rows)
        assert isinstance(plan, TwoLevelBoxExchangePlan)
        assert hasattr(plan, "tl_rounds")
        assert pv.verify_plan(plan, referenced=ref) == []
        assert "node" in {rd.tier for rd in plan.tl_rounds}
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


# ---------------------------------------------------------------------------
# bitwise identity: trajectory pin + the strict-bits oracle
# ---------------------------------------------------------------------------


def test_twolevel_solve_bitwise_identical_on_4part_fixture(monkeypatch):
    """The staged detour is pure copies: CG with the two-level plan on
    is bit-for-bit the flat generic plan's solve — residual history
    and gathered solution — on the 4-part conformance fixture."""
    monkeypatch.setenv("PA_TPU_BOX", "0")

    def run():
        def driver(parts):
            A, b, _xe, x0 = assemble_poisson(parts, (8, 8))
            x, info = tpu_cg(A, b, x0=x0, tol=1e-10, maxiter=200)
            return gather_pvector(x), info

        return pa.prun(driver, _backend(), (2, 2))

    x_flat, inf_flat = run()
    monkeypatch.setenv("PA_TPU_TWOLEVEL", "1")
    monkeypatch.setenv("PA_TPU_NODE_MAP", "0,0,1,1")
    x_two, inf_two = run()
    assert inf_flat["converged"] and inf_two["converged"]
    assert inf_two["iterations"] == inf_flat["iterations"]
    rf = np.asarray(inf_flat["residuals"], dtype=np.float64)
    rt = np.asarray(inf_two["residuals"], dtype=np.float64)
    assert rt.tobytes() == rf.tobytes()
    assert np.asarray(x_two).tobytes() == np.asarray(x_flat).tobytes()


def test_strict_bits_keeps_the_flat_plan_as_oracle(monkeypatch):
    """Strict-bits resolves PA_TPU_TWOLEVEL to 0 (the PR 17 refusal
    convention): the plan stays flat and the lowered CG program is
    byte-identical StableHLO with the env on or off — program
    identity, the strongest bitwise claim."""
    monkeypatch.setenv("PA_TPU_STRICT_BITS", "1")
    monkeypatch.setenv("PA_TPU_BOX", "0")
    monkeypatch.setenv("PA_TPU_NODE_MAP", "0,0,1,1")
    backend = _backend()

    def text():
        def driver(parts):
            A, _b, _xe, _x0 = assemble_poisson(parts, (6, 6))
            return A

        A = pa.prun(driver, backend, (2, 2))
        dA = device_matrix(A, backend)
        assert not hasattr(dA.col_plan, "tl_rounds")
        ops = _matrix_operands(dA)
        P, W = dA.col_plan.layout.P, dA.col_plan.layout.W
        z = np.zeros((P, W))
        fn = make_cg_fn(dA, tol=1e-9, maxiter=50, fused=False)
        return fn.jit_fn.lower(z, z, z, ops).as_text()

    off = text()
    monkeypatch.setenv("PA_TPU_TWOLEVEL", "1")
    on = text()
    assert on == off


# ---------------------------------------------------------------------------
# auto mode: the cost model decides per neighbor graph
# ---------------------------------------------------------------------------


def test_auto_mode_builds_only_where_aggregation_pays(monkeypatch):
    monkeypatch.setenv("PA_TPU_BOX", "0")
    monkeypatch.setenv("PA_TPU_TWOLEVEL", "auto")

    def pays(parts):
        # (2, 4) rows split across 2 nodes: 8 slow edges -> 2 pairs
        A, _b, _xe, _x0 = assemble_poisson(parts, (8, 8))
        plan = device_exchange_plan(A.cols)
        assert hasattr(plan, "tl_rounds")
        d = plan.decision
        assert d["mode"] == "auto" and d["use"] is True
        assert d["node_pair_edges"] < d["slow_edges_flat"]
        assert d["twolevel_modeled_s"] < d["flat_modeled_s"]
        return True

    def declines(parts):
        # a 1D chain: ONE cross-node boundary, 2 slow edges, 2 ordered
        # node pairs — aggregation merges nothing, the flat plan stays
        A, _b, _xe, _x0 = assemble_poisson(parts, (16, 8))
        plan = device_exchange_plan(A.cols)
        assert not hasattr(plan, "tl_rounds")
        return True

    monkeypatch.setenv("PA_TPU_NODE_MAP", "0,0,0,0,1,1,1,1")
    assert pa.prun(pays, pa.sequential, (2, 4))
    assert pa.prun(declines, pa.sequential, (8, 1))


# ---------------------------------------------------------------------------
# the fabric hook threads (bench_ici bugfix regression)
# ---------------------------------------------------------------------------


def test_node_map_threads_plan_and_matrix(monkeypatch):
    """ONE node map, both consumers: the plan the env selects and the
    matrix record's fabric labels derive from the same
    ``PA_TPU_NODE_MAP`` — `classify_edge`'s ``node_of`` priority beats
    the backend's process indices (on this single-process host every
    edge would otherwise label ici)."""
    monkeypatch.setenv("PA_TPU_BOX", "0")
    monkeypatch.setenv("PA_TPU_TWOLEVEL", "1")
    monkeypatch.setenv("PA_TPU_NODE_MAP", "0,0,1,1")
    backend = _backend()
    node_of = [0, 0, 1, 1]

    def driver(parts):
        A, _b, _xe, _x0 = assemble_poisson(parts, (8, 8))
        return A

    A = pa.prun(driver, backend, (2, 2))
    dA = device_matrix(A, backend)
    plan = dA.col_plan
    assert hasattr(plan, "tl_rounds")
    assert tuple(plan.node_of) == tuple(node_of)
    # the two-level matrix labels through the plan's own map: every
    # edge's fabric is node-arithmetic on the SAME node_of
    m = cmx.static_matrix(plan, np.float64, backend=backend)
    assert m["plan"] == "twolevel"
    assert m["node_of"] == node_of
    for e in m["edges"]:
        want = (
            "self" if e["src"] == e["dst"]
            else "ici" if node_of[e["src"]] == node_of[e["dst"]]
            else "dcn"
        )
        assert e["fabric"] == want, e
    assert m["fabric_summary"]["dcn"]["edges"] == sum(
        1 for rd in plan.tl_rounds
        if rd.perm and rd.tier == "node" for _ in rd.perm
    )
    # node_of priority over the backend's (single-process) view
    assert cmx.classify_edge(
        0, 3, backend=backend, P=4, node_of=node_of
    ) == "dcn"
    assert cmx.classify_edge(0, 3, backend=backend, P=4) == "ici"


def test_bench_ici_comms_record_threads_the_hook(monkeypatch):
    """The ported bench: `tools/bench_ici.comms_record` commits a
    schema-v2 matrix labeled by the SAME fabric hook plan construction
    consumed — the two-level path through the plan's own node map, the
    flat path through the `classify_edge` override (the regression:
    the old bench recorded no matrix, so a custom hook could reach the
    plan but never the committed record)."""
    # import the tool module without executing its __main__ leg; it
    # pins JAX_PLATFORMS/XLA_FLAGS at import — snapshot and restore
    saved = {
        k: os.environ.get(k) for k in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    try:
        spec = importlib.util.spec_from_file_location(
            "bench_ici", os.path.join(REPO, "tools", "bench_ici.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    backend = _backend(8)
    nmap = "0,0,0,0,1,1,1,1"
    monkeypatch.setenv("PA_TPU_BOX", "0")
    monkeypatch.setenv("PA_TPU_NODE_MAP", nmap)

    # flat path: the map reaches the record through the classify
    # override (the plan itself stays flat with PA_TPU_TWOLEVEL unset)
    monkeypatch.delenv("PA_TPU_TWOLEVEL", raising=False)
    m_flat = mod.comms_record(pa, backend)
    assert m_flat["comms_matrix_schema_version"] == (
        cmx.COMMS_MATRIX_SCHEMA_VERSION
    )
    assert m_flat["plan"] == "generic"
    assert m_flat["static_check"] == []
    assert m_flat["fabric_summary"]["dcn"]["edges"] > 0

    # two-level path: the same map built the plan AND labels the record
    monkeypatch.setenv("PA_TPU_TWOLEVEL", "1")
    m_two = mod.comms_record(pa, backend)
    assert m_two["plan"] == "twolevel"
    assert m_two["node_of"] == [int(t) for t in nmap.split(",")]
    assert m_two["static_check"] == []
    # one fabric view: the flat record's slow-edge count is what the
    # plan's decision said it was aggregating
    assert m_flat["fabric_summary"]["dcn"]["edges"] == (
        m_two["decision"]["slow_edges_flat"]
    )
