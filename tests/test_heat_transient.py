"""Transient heat driver: implicit Euler with one amortized solver setup
per march (hierarchy built once, reused every step)."""
import numpy as np

import partitionedarrays_jl_tpu as pa


def test_heat_march_reaches_steady_state():
    def driver(parts):
        err, its = pa.heat_transient_driver(
            parts, (10, 10, 10), dt=2.0, nsteps=60, tol=1e-10
        )
        # the march's fixed point IS the steady Poisson solution; with
        # dt=2 the slowest mode contracts by >1/1.4 per step
        assert err < 1e-6, err
        # steps are cheap: the warm-started, well-conditioned step
        # system needs only a handful of PCG iterations
        assert max(its[5:]) <= max(its[:3]), its
        return True

    assert pa.prun(driver, pa.sequential, (2, 2, 2))


def test_heat_march_compiled_parity():
    """On the TPU backend every step runs the SAME cached compiled
    V-cycle-PCG program; the march must agree with the host oracle."""

    def driver(parts):
        return pa.heat_transient_driver(
            parts, (8, 8, 8), dt=2.0, nsteps=30, tol=1e-10
        )

    err_s, its_s = pa.prun(driver, pa.sequential, (2, 2, 2))
    err_t, its_t = pa.prun(driver, pa.tpu, (2, 2, 2))
    assert err_s < 1e-5 and err_t < 1e-5
    assert its_s == its_t, (its_s, its_t)
    np.testing.assert_allclose(err_t, err_s, rtol=1e-6, atol=1e-12)


def test_heat_step_operator_structure():
    """B = I + dt*A on interior rows, exact identity on boundary rows;
    symmetric (the decoupled operator's symmetry is inherited)."""

    def driver(parts):
        B, bh, mask, u0, xs = pa.assemble_heat(parts, (6, 6), dt=0.25)
        M = pa.gather_psparse(B).toarray()
        assert np.abs(M - M.T).max() == 0.0
        mk = pa.gather_pvector(mask)
        bdry = mk == 0.0
        # boundary rows: exact identity
        np.testing.assert_array_equal(M[bdry][:, bdry], np.eye(bdry.sum()))
        assert not M[bdry][:, ~bdry].any()
        # interior diagonal: 1 + dt * 6 for the 2-D 5-point interior rows
        # away from the boundary coupling (stencil center is 4 in 2-D)
        A, b, _, _ = pa.assemble_poisson(parts, (6, 6))
        Ah = pa.decouple_dirichlet(A)
        Am = pa.gather_psparse(Ah).toarray()
        np.testing.assert_allclose(
            M[~bdry][:, ~bdry], 0.25 * Am[~bdry][:, ~bdry] + np.eye((~bdry).sum())
        )
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))
