"""The padded-ELL device-fault footprint guard (tpu.py:_ell_guard_check).

The 64^3 tet-elasticity probe (IRREGULAR_BENCH.json) showed the generic
padded-ELL lowering's gather kernels FAULT a real TPU worker outright at
that scale, while SD and BSR on the same operator run fine. The guard
used to live only in tools/bench_irregular.py's leg selection; this file
pins its library form: the lowering itself refuses (real TPU) or warns
(host mesh) BEFORE staging an over-ceiling ELL program, whether ELL was
auto-selected (every fast path declined) or forced by strict-bits mode —
so no documented env-flag combination can reach the device-fault path.

The 64^3 strict-bits case itself is covered two ways: the ceiling
arithmetic against the RECORDED 64^3 operator shape (no assembly — the
mean-width lower bound already exceeds the ceiling), and the end-to-end
refusal exercised at test scale with the ceiling shrunk via env.
"""
import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu.models import assemble_poisson, gather_pvector
from partitionedarrays_jl_tpu.parallel.tpu import (
    ELL_MAX_GATHER,
    DeviceMatrix,
    ELLFootprintError,
    TPUBackend,
)


def _backend():
    import jax

    return TPUBackend(devices=jax.devices()[:8])


def test_recorded_64cube_footprint_exceeds_default_ceiling():
    """The operator that faulted the worker must be refused by the
    DEFAULT ceiling: at the recorded 64^3 shape (IRREGULAR_BENCH.json:
    786432 dofs, 27955824 nnz) even the MEAN row width — a lower bound
    on the padded ELL width — puts the footprint past the ceiling."""
    dofs, nnz = 786432, 27955824
    mean_width_floor = -(-nnz // dofs)  # ceil; true padded L is >= this
    assert dofs * mean_width_floor > ELL_MAX_GATHER
    # ...while the largest ELL program ever measured healthy (32^3,
    # 98304 dofs x width<=64) stays well inside it
    assert 98304 * 64 < ELL_MAX_GATHER


def test_strict_bits_refuses_cleanly_past_ceiling(monkeypatch):
    """Strict-bits forces the pure-ELL lowering; past the ceiling the
    build must raise the typed error (enforced mode stands in for the
    real-TPU platform check) instead of staging the faulting program."""
    monkeypatch.setenv("PA_TPU_STRICT_BITS", "1")
    monkeypatch.setenv("PA_TPU_ELL_GUARD", "1")
    monkeypatch.setenv("PA_TPU_ELL_MAX_GATHER", "1000")
    backend = _backend()

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (16, 16, 16))
        with pytest.raises(ELLFootprintError) as ei:
            DeviceMatrix(A, parts.backend)
        assert "strict-bits" in str(ei.value)
        assert "PA_TPU_ELL_MAX_GATHER" in str(ei.value)
        return True

    assert pa.prun(driver, backend, (2, 2, 2))


def test_auto_selected_ell_refuses_cleanly_past_ceiling(monkeypatch):
    """Same refusal when ELL is AUTO-selected: a scattered (non-banded)
    operator declines DIA, SD/BSR are off, so ELL is the fallback — and
    past the ceiling the guard must refuse with the auto-select wording,
    not the strict-bits one."""
    monkeypatch.setenv("PA_TPU_ELL_GUARD", "1")
    monkeypatch.setenv("PA_TPU_ELL_MAX_GATHER", "100")
    monkeypatch.setenv("PA_TPU_SD", "0")
    monkeypatch.setenv("PA_TPU_BSR", "0")
    backend = _backend()
    n, per = 800, 100  # 8 parts x 100 owned rows

    def driver(parts):
        def trip(p, k):
            rows_ = np.arange(p * per, (p + 1) * per, dtype=np.int64)
            loc = rows_ - p * per
            # pseudo-random couplings INSIDE the part (they must land in
            # the A_oo block): per-row offsets scatter, so the union
            # blows the DIA_MAX_OFFSETS cap and DIA detection declines
            I = np.concatenate([rows_, rows_, rows_])
            J = np.concatenate(
                [
                    rows_,
                    p * per + (loc * 7 + 13) % per,
                    p * per + (loc * 11 + 5) % per,
                ]
            )
            V = np.concatenate(
                [np.full(per, 10.0), np.full(per, 1.0), np.full(per, 1.0)]
            )
            return (I, J, V)[k]

        I = pa.map_parts(lambda p: trip(p, 0), parts)
        J = pa.map_parts(lambda p: trip(p, 1), parts)
        V = pa.map_parts(lambda p: trip(p, 2), parts)
        A = pa.PSparseMatrix.from_coo(I, J, V, n, n, ids="global")
        with pytest.raises(ELLFootprintError) as ei:
            DeviceMatrix(A, parts.backend)
        assert "declined" in str(ei.value)
        return True

    assert pa.prun(driver, backend, 8)


def test_below_ceiling_strict_bits_runs_cleanly(monkeypatch):
    """The other half of the regression contract: UNDER the ceiling the
    strict-bits ELL program runs end-to-end — device CG bit-identical to
    the sequential oracle, exactly as tests/test_strict_bits.py pins."""
    monkeypatch.setenv("PA_TPU_STRICT_BITS", "1")
    monkeypatch.setenv("PA_TPU_ELL_GUARD", "1")
    backend = _backend()

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8, 8))
        x, info = pa.cg(A, b, x0=x0, tol=1e-9, maxiter=400)
        assert info["converged"]
        return gather_pvector(x), info["iterations"]

    xt, it_t = pa.prun(driver, backend, (2, 2, 2))
    xs, it_s = pa.prun(driver, pa.sequential, (2, 2, 2))
    assert it_t == it_s
    np.testing.assert_array_equal(np.asarray(xt), np.asarray(xs))


def test_host_mesh_warns_instead_of_refusing(monkeypatch):
    """Default (auto) mode on a CPU mesh: over-ceiling ELL is slow, not
    unsafe — the lowering warns and proceeds, and the staged program
    still computes the right product."""
    monkeypatch.setenv("PA_TPU_STRICT_BITS", "1")
    monkeypatch.setenv("PA_TPU_ELL_MAX_GATHER", "1000")
    monkeypatch.delenv("PA_TPU_ELL_GUARD", raising=False)
    backend = _backend()

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (12, 12, 12))
        with pytest.warns(UserWarning, match="padded-ELL"):
            dA = DeviceMatrix(A, parts.backend)
        from partitionedarrays_jl_tpu.parallel.tpu import (
            DeviceVector, make_spmv_fn,
        )

        dx = DeviceVector.from_pvector(xe, parts.backend, dA.col_layout)
        y = make_spmv_fn(dA)(dx.data)
        host = gather_pvector(b)
        dev = np.asarray(y)
        got = np.zeros_like(host)
        for p, iset in enumerate(A.rows.partition.part_values()):
            got[iset.oid_to_gid] = dev[p, : iset.num_oids]
        np.testing.assert_array_equal(got, host)  # strict: bit-exact
        return True

    assert pa.prun(driver, backend, (2, 2, 2))
