"""Doc/artifact traceability guard (round-5 rule: every number in the
docs traces to a committed artifact or carries its round tag).

Two stale-doc classes have actually shipped in this repo's history —
a capability claim that code had already obsoleted (docs/roadmap.md §1
"still require equal per-part boxes", contradicted by the shape-variant
`lax.switch` transfers in tpu_gmg.py and GMG_BENCH.json), and
historical bench numbers quoted without their round tag (the round-4
"11.1 GFLOP/s" lived only in a commit message). This file makes the
traceability rule enforce itself:

* known-stale claim patterns must not reappear in committed docs;
* superseded historical figures may only appear in a paragraph that
  carries a round/era tag;
* the committed artifacts and the bench guards that gate them must
  agree (band bounds in the artifact == the guard tables in tools/).
"""
import importlib.util
import json
import os
import re

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DOC_FILES = [
    "README.md",
    "docs/performance.md",
    "docs/roadmap.md",
    "docs/design.md",
    "docs/api.md",
    "docs/migration.md",
    "docs/resilience.md",
    "docs/static_analysis.md",
    "docs/observability.md",
    "docs/service.md",
]

#: Claims proven wrong by shipped code: these exact phrases must never
#: come back (each entry documents what obsoleted it).
BANNED_PATTERNS = [
    (
        r"still require equal per-part boxes",
        "obsoleted by the shape-variant lax.switch transfers "
        "(tpu_gmg.py, round 5; GMG_BENCH.json records the paths)",
    ),
    (
        r"practical floor under current XLA\s+while-loop semantics",
        "the round-2 conclusion was size-specific; superseded by the "
        "round-6 fused streaming CG body at large N",
    ),
]

#: Historical figures superseded by later rounds: quoting one is fine
#: ONLY in a paragraph that names its era (round N / rN / historical).
HISTORICAL_FIGURES = [
    "876 s",      # r2 assembly, now 30-108 s
    "365 s",      # r3 GMG hierarchy, now 54-139 s
    "299 s",      # r2 lowering, now 27-77 s
    "797 ms",     # r1 V-cycle, now 7.7 ms
    "9.32 ms",    # r5 standard-body CG iteration, now 6.77 ms fused
    "9.323",      # same figure as recorded in the r5 artifact
]
ERA_TAG = re.compile(r"(historical|rounds?\s*[0-9]|\br[0-9]\b)", re.I)


def _doc_paragraphs():
    for rel in DOC_FILES:
        path = os.path.join(REPO, rel)
        if not os.path.exists(path):
            continue
        text = open(path, encoding="utf-8").read()
        for para in re.split(r"\n\s*\n", text):
            yield rel, para


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_no_banned_stale_claims():
    hits = []
    for rel, para in _doc_paragraphs():
        for pat, why in BANNED_PATTERNS:
            if re.search(pat, para):
                hits.append((rel, pat, why))
    assert not hits, (
        "stale claims back in the docs (each was proven wrong by shipped "
        f"code): {hits}"
    )


def test_historical_figures_carry_their_round_tag():
    untagged = []
    for rel, para in _doc_paragraphs():
        for fig in HISTORICAL_FIGURES:
            if fig in para and not ERA_TAG.search(para):
                untagged.append((rel, fig, para[:120]))
    assert not untagged, (
        "superseded figures quoted without a round/era tag — either tag "
        f"the paragraph or update the number: {untagged}"
    )


def test_scale_bench_artifact_agrees_with_guard_bands():
    """The committed flagship artifact and the bench guard must agree:
    identical band bounds, and the recorded device metrics inside them
    (a lowered band with a stale artifact — or vice versa — is exactly
    the drift this file exists to catch)."""
    bench_scale = _load_tool("bench_scale")
    rec = json.load(open(os.path.join(REPO, "SCALE_BENCH.json")))
    for key, (lo, hi, kind) in bench_scale.SCALE_BANDS.items():
        band = rec["bands"].get(key)
        assert band is not None, f"artifact missing band {key}"
        assert (band["lo"], band["hi"]) == (lo, hi), (
            f"band bounds for {key} drifted: guard ({lo}, {hi}) vs "
            f"artifact ({band['lo']}, {band['hi']})"
        )
        if kind == "device":
            assert band["in_band"], (key, band)
    assert rec["bands_ok_device"] is True


def test_irregular_artifact_agrees_with_guard_bands():
    bench_irr = _load_tool("bench_irregular")
    rec = json.load(open(os.path.join(REPO, "IRREGULAR_BENCH.json")))
    assert rec["methodology"] == bench_irr.METHODOLOGY
    banded = 0
    for row in rec["sizes"]:
        n = row["n"]
        if row.get("lowering") == "sd" and n in bench_irr.BANDS_SD:
            lo, hi = bench_irr.BANDS_SD[n]
            band = row.get("band")
            assert band is not None, f"SD row n={n} missing its band"
            assert (band["lo"], band["hi"]) == (lo, hi), (n, band)
            assert band["measured"] == row[f"{row['lowering']}_gflops"]
            assert row["in_band"] == (lo <= band["measured"] <= hi)
            banded += 1
    # every measured size is banded (the 48^3/64^3 rows used to ship
    # silently unbanded — round-6 satellite)
    assert banded == len(rec["sizes"]), (banded, len(rec["sizes"]))


def test_multirhs_artifact_agrees_with_guard_bands():
    """The committed multi-RHS flagship artifact and the bench guard
    must agree: identical band bounds, recorded device metrics inside
    them, and the curve rows the bands were derived from actually
    present and self-consistent (per_rhs = block / K; the K=8 speedup
    claim in the docs traces to THIS record)."""
    bench_mr = _load_tool("bench_multirhs")
    rec = json.load(open(os.path.join(REPO, "MULTIRHS_BENCH.json")))
    assert rec["methodology"] == bench_mr.METHODOLOGY
    assert rec["ks"] == list(bench_mr.KS)
    by_k = {row["K"]: row for row in rec["curve"]}
    assert set(by_k) == set(rec["ks"])
    for row in rec["curve"]:
        assert abs(
            row["per_rhs_s_per_it"] - row["block_s_per_it"] / row["K"]
        ) <= 1e-4 * row["per_rhs_s_per_it"], row  # artifact rounding
    for key, (lo, hi, kind) in bench_mr.MULTIRHS_BANDS.items():
        band = rec["bands"].get(key)
        assert band is not None, f"artifact missing band {key}"
        assert (band["lo"], band["hi"]) == (lo, hi), (key, band)
        k = int(key.rsplit("k", 1)[-1])
        assert band["measured"] == by_k[k]["per_rhs_speedup_vs_k1"], (
            key, band, by_k[k],
        )
        if kind == "device":
            assert band["in_band"], (key, band)
    # the acceptance floor: >= 1.5x per-RHS at K=8 on a >= 320^3 size
    assert rec["n"] >= 320 and rec["dofs"] == rec["n"] ** 3
    assert by_k[8]["per_rhs_speedup_vs_k1"] >= 1.5
    assert rec["bands_ok_device"] is True


def test_metric_catalog_agrees_with_registry_both_directions():
    """docs/observability.md's '### Metric catalog' table is the
    exhaustive declared-metric surface, machine-checked against
    `telemetry.registry.CATALOG` in BOTH directions: a metric the
    package declares (and bumps) that the table omits is an
    undocumented signal; a row naming an undeclared metric is a ghost.
    Type, unit, labels, and the bumped-at site must match the spec —
    the table may not claim an instrumentation point the code moved."""
    import re as _re

    from partitionedarrays_jl_tpu.telemetry import CATALOG

    text = open(
        os.path.join(REPO, "docs", "observability.md"), encoding="utf-8"
    ).read()
    m = re.search(
        r"### Metric catalog(.*?)\n## ", text, flags=re.S
    )
    assert m, "docs/observability.md lost its '### Metric catalog'"
    rows = _re.findall(
        r"^\| `([^`]+)` \| (\w+) \| (\S+) \| (.+?) \| `([^`]+)` \|",
        m.group(1), flags=_re.M,
    )
    assert rows, "metric catalog table unparsable (format drifted?)"
    documented = {r[0] for r in rows}
    declared = set(CATALOG)
    assert declared - documented == set(), (
        f"declared metrics missing from the doc table: "
        f"{declared - documented}"
    )
    assert documented - declared == set(), (
        f"ghost rows documenting undeclared metrics: "
        f"{documented - declared}"
    )
    for name, kind, unit, labels, where in rows:
        spec = CATALOG[name]
        assert kind == spec.kind, (name, kind, spec.kind)
        assert unit == spec.unit, (name, unit, spec.unit)
        assert where == spec.where, (name, where, spec.where)
        doc_labels = (
            () if labels.strip() in ("—", "-", "")
            else tuple(s.strip() for s in labels.split(","))
        )
        assert doc_labels == spec.labels, (name, doc_labels, spec.labels)


def test_throughput_model_ties_to_multirhs():
    """The committed THROUGHPUT_MODEL.json (round 12 — the adaptive-K
    input) must be the real thing: schema-versioned under the shared
    artifact envelope, its online-measured entries internally
    consistent (per_rhs = s_per_it/K, EWMA fed by >= 2 samples — a
    one-shot value is a bench row, not an online model), measured at
    every K the SERVICE_BENCH sweep ran, and its reference curve EQUAL
    to the committed MULTIRHS device record at every overlapping K —
    the committed model can never drift from the device curve it
    converges to."""
    from partitionedarrays_jl_tpu import telemetry

    bench_svc = _load_tool("bench_service")
    rec = json.load(open(os.path.join(REPO, "THROUGHPUT_MODEL.json")))
    mr = json.load(open(os.path.join(REPO, "MULTIRHS_BENCH.json")))
    assert rec["throughput_schema_version"] == (
        telemetry.THROUGHPUT_SCHEMA_VERSION
    )
    # the shared artifact envelope
    assert rec.get("schema_version") == telemetry.ARTIFACT_SCHEMA_VERSION
    assert rec.get("generated_by") == "bench_service"
    assert rec.get("platform") and isinstance(rec.get("pa_env"), dict)
    assert 0.0 < rec["ewma_alpha"] <= 1.0
    # online-measured entries: loadable, consistent, covering the sweep
    model = telemetry.ThroughputModel.load(rec)
    entries = rec["entries"]
    assert entries, "committed model must hold measured entries"
    for e in entries:
        assert abs(
            e["per_rhs_s_per_it"] - e["s_per_it"] / e["K"]
        ) <= 1e-6 * e["per_rhs_s_per_it"], e
        assert e["samples"] >= 2, (e, "an online EWMA needs >= 2 samples")
        assert e["iterations"] >= e["samples"], e
    fp = rec["operator_fingerprint"]
    dtype = rec["dtype"]
    measured_ks = set(model.curve(fp, dtype))
    assert measured_ks == set(bench_svc.KS), (measured_ks, bench_svc.KS)
    # suggest_k reads the committed curve coherently: never wider than
    # the queue, and the argmin of the measured per-RHS curve when wide
    curve = model.curve(fp, dtype)
    best = min(curve, key=lambda k: (curve[k], -k))
    assert model.suggest_k(fp, dtype, queue_depth=64, kmax=64) == best
    assert model.suggest_k(fp, dtype, queue_depth=1, kmax=64) == 1
    # the reference curve IS the MULTIRHS device record
    ref = rec["reference_curve"]
    assert ref["source"] == "MULTIRHS_BENCH.json"
    assert (ref["n"], ref["dtype"]) == (mr["n"], mr["dtype"])
    mr_by_k = {str(r["K"]): r for r in mr["curve"]}
    assert set(ref["per_rhs_s_per_it"]) == set(mr_by_k)
    for k, row in mr_by_k.items():
        assert ref["per_rhs_s_per_it"][k] == row["per_rhs_s_per_it"], k
        assert ref["per_rhs_speedup_vs_k1"][k] == (
            row["per_rhs_speedup_vs_k1"]
        ), k


def test_service_artifact_inherits_multirhs_floor():
    """The committed solve-service artifact (round 10) and its bench
    guard must agree — and the artifact's device claim must be
    TRACEABLE: the per-RHS gains it records are inherited from the
    committed MULTIRHS_BENCH.json record (the service feeds the
    identical compiled block program — tests/test_service.py pins the
    program-cache hit), so the two artifacts must carry EQUAL values,
    with the K=8 ≥ 1.5x acceptance floor intact. The locally measured
    service rows must be internally consistent (requests/s = K / wall,
    ratio = solo/service)."""
    bench_svc = _load_tool("bench_service")
    rec = json.load(open(os.path.join(REPO, "SERVICE_BENCH.json")))
    mr = json.load(open(os.path.join(REPO, "MULTIRHS_BENCH.json")))
    assert rec["methodology"] == bench_svc.METHODOLOGY
    assert rec["ks"] == list(bench_svc.KS)
    mr_by_k = {row["K"]: row for row in mr["curve"]}
    inh = rec["inherited"]
    assert inh["source"] == "MULTIRHS_BENCH.json"
    assert inh["per_rhs_gain_k8"] == mr_by_k[8]["per_rhs_speedup_vs_k1"]
    assert inh["per_rhs_gain_k16"] == mr_by_k[16]["per_rhs_speedup_vs_k1"]
    for key, (lo, hi, kind) in bench_svc.SERVICE_BANDS.items():
        band = rec["bands"].get(key)
        assert band is not None, f"artifact missing band {key}"
        assert (band["lo"], band["hi"], band["kind"]) == (lo, hi, kind), (
            key, band,
        )
        assert band["measured"] == inh[key]
        if kind == "device":
            assert band["in_band"], (key, band)
    # the acceptance floor, traceable to the MULTIRHS device record
    assert inh["per_rhs_gain_k8"] >= 1.5
    assert rec["bands_ok_device"] is True
    by_k = {row["K"]: row for row in rec["service_rows"]}
    assert set(by_k) == set(rec["ks"])
    for row in rec["service_rows"]:
        for leg in ("service", "solo"):
            rps = row[f"{leg}_requests_per_s"]
            assert abs(rps - row["K"] / row[f"{leg}_wall_s"]) <= 1e-3 * rps
        ratio = row["solo_wall_s"] / row["service_wall_s"]
        assert abs(row["service_vs_solo"] - ratio) <= 1e-2 * ratio, row
    # round 12: the metrics-on/off marginal — the drained requests/s
    # with the observability plane on vs killed must be recorded,
    # internally consistent, and inside its committed canary band (the
    # PR 9 acceptance criterion: metrics are measurably ~free)
    marg = rec["metrics_marginal"]
    ratio = marg["on_requests_per_s"] / marg["off_requests_per_s"]
    assert abs(marg["ratio_on_off"] - ratio) <= 1e-2 * ratio, marg
    for key, (lo, hi, kind) in bench_svc.METRICS_BANDS.items():
        band = rec["bands"][key]
        assert (band["lo"], band["hi"], band["kind"]) == (lo, hi, kind)
        assert band["measured"] == marg["ratio_on_off"]
        assert band["in_band"] and lo <= band["measured"] <= hi, band
    # round 16: the tracing-on/off marginal (patx) — same canary
    # convention; the ledger sentinel picks the band up like every
    # other (test_perf_ledger_covers_every_bench_artifact below)
    tx = rec["tracing_marginal"]
    ratio = tx["on_requests_per_s"] / tx["off_requests_per_s"]
    assert abs(tx["ratio_on_off"] - ratio) <= 1e-2 * ratio, tx
    for key, (lo, hi, kind) in bench_svc.TRACING_BANDS.items():
        band = rec["bands"][key]
        assert (band["lo"], band["hi"], band["kind"]) == (lo, hi, kind)
        assert band["measured"] == tx["ratio_on_off"]
        assert band["in_band"] and lo <= band["measured"] <= hi, band
    # the locally measured per-RHS table agrees with itself and covers
    # the sweep (its committed twin is THROUGHPUT_MODEL.json, checked
    # in test_throughput_model_ties_to_multirhs)
    per_rhs = {r["K"]: r for r in rec["measured_per_rhs"]}
    assert set(per_rhs) == set(rec["ks"])
    for r in rec["measured_per_rhs"]:
        assert abs(
            r["per_rhs_s_per_it"] - r["s_per_it"] / r["K"]
        ) <= 1e-6 * r["per_rhs_s_per_it"], r


def test_scale_curve_fused_headline_consistent_with_bench():
    """SCALE_CURVE's 464^3 fused marginal and SCALE_BENCH's full-solve
    per-iteration must describe the same kernel: marginal <= full-solve
    (the full solve carries dispatch overhead) and within ~15%."""
    curve = json.load(open(os.path.join(REPO, "SCALE_CURVE.json")))
    rec = json.load(open(os.path.join(REPO, "SCALE_BENCH.json")))
    row = next(r for r in curve["sizes"] if r["n"] == rec["n"])
    marginal_ms = row["cg_s_per_it"] * 1e3
    full_ms = rec["per_iteration_ms"]
    assert marginal_ms <= full_ms <= 1.15 * marginal_ms, (
        marginal_ms, full_ms,
    )
    # the A/B leg is present wherever the fused default is the headline
    assert "cg_unfused_s_per_it" in row and "cg_fused_speedup" in row


def test_abft_artifact_agrees_with_guard_bands():
    """The committed ABFT clean-path artifact (round 8) and the bench
    guard must agree: identical band bounds, the recorded
    collective-count parity (the zero-extra-collectives claim) actually
    TRUE with identical per-kind counts, and the overhead rows
    self-consistent. Device-kind bands gate only records measured on
    real TPUs — a cpu-platform record is the structural canary (its
    note must say so), never silently passed off as the acceptance
    number."""
    bench_abft = _load_tool("bench_abft")
    rec = json.load(open(os.path.join(REPO, "ABFT_BENCH.json")))
    assert rec["methodology"] == bench_abft.METHODOLOGY
    for key, (lo, hi, kind) in bench_abft.ABFT_BANDS.items():
        band = rec["bands"].get(key)
        assert band is not None, f"artifact missing band {key}"
        assert (band["lo"], band["hi"], band["kind"]) == (lo, hi, kind), (
            key, band,
        )
    par = rec["collective_parity"]
    assert par["parity"] is True
    assert par["counts_on"] == par["counts_off"]
    assert any(par["counts_on"].values()), "parity probe saw no collectives"
    for row in rec["sizes"]:
        assert row["dofs"] == row["n"] ** 3
        ratio = row["abft_on_s_per_it"] / row["abft_off_s_per_it"]
        assert abs(row["overhead_ratio"] - ratio) <= 1e-3 * ratio, row
    if rec["platform"] == "tpu":
        ns = {row["n"] for row in rec["sizes"]}
        assert set(bench_abft.DEVICE_SIZES) <= ns
        assert rec["bands_ok_device"] is True
    else:
        # the canary must declare itself: platform recorded, device
        # verdict left open, and the note explains the gating
        assert rec["bands_ok_device"] is None
        assert "real TPUs" in rec["note"]


def test_env_var_table_agrees_with_source_both_directions():
    """docs/api.md's '## Environment variables' table is the exhaustive
    env-flag surface, machine-checked against the package's actual
    reads (analysis.env_lint AST inventory) in BOTH directions: a flag
    the source reads but the table omits is an undocumented knob; a row
    the source no longer reads is a ghost. (The same invariant gates
    tools/palint.py --check; this copy keeps the doc-consistency suite
    self-contained.)"""
    from partitionedarrays_jl_tpu.analysis import (
        documented_env_names,
        env_read_inventory,
    )

    documented = documented_env_names(os.path.join(REPO, "docs", "api.md"))
    read = {r.name for r in env_read_inventory()}
    assert documented, "docs/api.md lost its '## Environment variables' table"
    assert read - documented == set(), (
        f"flags read in the package but undocumented: {read - documented}"
    )
    assert documented - read == set(), (
        f"ghost rows documenting flags never read: {documented - read}"
    )


def test_env_table_lowering_rows_name_their_key_site():
    """Every table row classed `lowering` must name the key site the
    lint actually resolves it through — the docs may not claim a
    coverage the AST cannot see."""
    from partitionedarrays_jl_tpu.analysis import key_coverage
    from partitionedarrays_jl_tpu.analysis.env_lint import (
        classify,
        env_table_rows,
    )

    cov = key_coverage()
    cls = classify()
    rows = env_table_rows(os.path.join(REPO, "docs", "api.md"))
    # parser-rot guard: a table reformat that breaks the shared row
    # extraction must fail here, not silently skip the invariants below
    assert len(rows) >= len(cls), (len(rows), len(cls))
    for name, rest in rows:
        entry = cls.get(name)
        # a ghost row (flag never read) is the both-directions test's
        # finding — skip here so each failure stays self-explanatory
        if entry is None:
            continue
        if entry["class"] == "lowering":
            assert name in cov, f"{name} documented lowering but unkeyed"
            assert f"`{cov[name]}`" in rest, (
                f"row for {name} must name its key site `{cov[name]}`"
            )
        else:
            assert "| lowering |" not in rest, name


def test_obs_artifact_agrees_with_guard_bands():
    """The committed telemetry-overhead artifact (round 9) and the
    bench guard must agree: identical band bounds, the recorded
    HLO-identity and collective-parity probes actually TRUE (telemetry
    off is the pre-telemetry program; the trace ring adds zero
    collectives), and the overhead rows self-consistent. Device-kind
    bands gate only records measured on real TPUs — a cpu-platform
    record is the structural canary (its note must say so)."""
    bench_obs = _load_tool("bench_obs")
    rec = json.load(open(os.path.join(REPO, "OBS_BENCH.json")))
    assert rec["methodology"] == bench_obs.METHODOLOGY
    assert rec["trace_depth"] == bench_obs.TRACE_DEPTH
    for key, (lo, hi, kind) in bench_obs.OBS_BANDS.items():
        band = rec["bands"].get(key)
        assert band is not None, f"artifact missing band {key}"
        assert (band["lo"], band["hi"], band["kind"]) == (lo, hi, kind), (
            key, band,
        )
    ident = rec["identity"]
    assert ident["hlo_identity"] is True
    assert ident["parity"] is True
    assert ident["counts_on"] == ident["counts_off"]
    assert any(ident["counts_on"].values()), "probe saw no collectives"
    for row in rec["sizes"]:
        assert row["dofs"] == row["n"] ** 3
        ratio = row["trace_on_s_per_it"] / row["trace_off_s_per_it"]
        assert abs(row["overhead_ratio"] - ratio) <= 1e-3 * ratio, row
    if rec["platform"] == "tpu":
        ns = {row["n"] for row in rec["sizes"]}
        assert set(bench_obs.DEVICE_SIZES) <= ns
        assert rec["bands_ok_device"] is True
    else:
        assert rec["bands_ok_device"] is None
        assert "real TPUs" in rec["note"]


def test_sstep_artifact_agrees_with_guard_bands():
    """The committed s-step/overlap A/B artifact (round 17) and the
    bench guard must agree: identical device-knee band bounds (the
    >= 1.15x s-step acceptance), speedup rows self-consistent with the
    per-body marginals, the suggest_s policy block reproducible from
    the committed SPECTRUM.json through `telemetry.suggest_s`, and the
    docs/performance.md claims tied to the artifact. Device-kind bands
    gate only records measured on real TPUs — a cpu-platform record is
    the structural canary (its note must say so) and carries the wide
    canary sanity bands instead."""
    from partitionedarrays_jl_tpu import telemetry

    bench_sstep = _load_tool("bench_sstep")
    rec = json.load(open(os.path.join(REPO, "SSTEP_BENCH.json")))
    assert rec["methodology"] == bench_sstep.METHODOLOGY
    assert rec["sstep"] == bench_sstep.SSTEP
    for key, (lo, hi, kind) in bench_sstep.SSTEP_BANDS.items():
        band = rec["bands"].get(key)
        assert band is not None, f"artifact missing band {key}"
        assert (band["lo"], band["hi"], band["kind"]) == (lo, hi, kind), (
            key, band,
        )
    std = rec["bodies"]["standard"]["s_per_it"]
    for body, row in rec["bodies"].items():
        if body == "standard":
            continue
        ratio = std / row["s_per_it"]
        assert abs(row["speedup_vs_standard"] - ratio) <= (
            1e-3 * ratio
        ), (body, row)
    # the policy block must be what telemetry.suggest_s derives from
    # the committed spectrum store today (artifact and policy cannot
    # drift apart silently)
    spec_rec = json.load(open(os.path.join(REPO, "SPECTRUM.json")))
    by_key = {
        (e["fingerprint"], e["dtype"], e["minv_class"]): e
        for e in spec_rec["entries"]
    }
    assert rec["suggest_s"], "artifact lost its suggest_s policy block"
    for row in rec["suggest_s"]:
        e = by_key[(row["fingerprint"], row["dtype"], row["minv_class"])]
        pol = telemetry.suggest_s(
            {"kappa": e.get("kappa"), "rate": e.get("rate"),
             "samples": e.get("samples", 1)},
            e["dtype"], tol=1e-8,
        )
        assert row["suggested_s"] == pol["s"], row
        assert row["policy"] == pol["policy"]
        assert row["gather_factor"] == pol["gather_factor"]
    if rec["platform"] == "tpu":
        assert rec["bands_ok_device"] is True
    else:
        assert rec["bands_ok_device"] is None
        assert "real TPUs" in rec["note"]
        for key, (lo, hi, kind) in bench_sstep.CANARY_BANDS.items():
            band = rec["bands"].get(key)
            assert band is not None, f"canary record missing band {key}"
            assert band["kind"] == kind and band["in_band"] is True
    # the docs claim the knee the artifact enforces
    perf = open(os.path.join(REPO, "docs", "performance.md")).read()
    assert "SSTEP_BENCH.json" in perf
    knee = bench_sstep.SSTEP_BANDS["sstep2_speedup_vs_standard"][0]
    assert f"{knee:.2f}" in perf, (
        "docs/performance.md must state the device knee the band pins"
    )


def test_twolevel_artifact_agrees_with_guard_bands():
    """The committed flat-vs-two-level A/B artifact (round 18) and the
    bench guard must agree: identical band bounds, the static
    reductions recomputable from the recorded per-fabric summaries,
    the synthetic-fit decision self-consistent (dcn fit engaged, both
    modeled costs present, ``use`` true), and the docs claims tied to
    the artifact. Static-kind bands gate on EVERY platform; the
    device-kind exchange speedup gates only records measured on real
    TPUs — a cpu-platform record is the structural canary."""
    bench_twolevel = _load_tool("bench_twolevel")
    rec = json.load(open(os.path.join(REPO, "TWOLEVEL_BENCH.json")))
    assert rec["methodology"] == bench_twolevel.METHODOLOGY
    assert rec["node_map"] == bench_twolevel.NODE_MAP
    assert rec["synth_model"] == bench_twolevel.SYNTH_MODEL
    for key, (lo, hi, kind) in bench_twolevel.TWOLEVEL_BANDS.items():
        band = rec["bands"].get(key)
        assert band is not None, f"artifact missing band {key}"
        assert (band["lo"], band["hi"], band["kind"]) == (lo, hi, kind), (
            key, band,
        )
        if kind == "static":
            # deterministic plan/model structure: in band everywhere
            assert band["in_band"] is True, (key, band)
    # the static reductions are the per-fabric summaries' arithmetic
    dcn_f = rec["flat"]["fabric_summary"]["dcn"]
    dcn_t = rec["twolevel"]["fabric_summary"]["dcn"]
    red = rec["reductions"]
    assert red["dcn_edge_reduction"] == round(
        dcn_f["edges"] / dcn_t["edges"], 4
    )
    assert red["dcn_wire_reduction"] == round(
        dcn_f["wire_bytes"] / dcn_t["wire_bytes"], 4
    )
    assert red["extra_ici_wire_rounds"] == sum(
        1 for t in rec["twolevel"]["round_tiers"]
        if t in ("gather", "scatter")
    )
    # the measured-not-guessed decision: the dcn fit engaged (the
    # synthetic matrix carries two distinct dcn payload sizes) and the
    # modeled speedup band row is the decision's own cost ratio
    fit = rec["synthetic_fit"]["model"]
    dec = rec["synthetic_fit"]["decision"]
    assert fit["dcn"]["source"] == "fit"
    assert dec["use"] is True
    assert dec["model_source"] != "default"
    modeled = dec["flat_modeled_s"] / dec["twolevel_modeled_s"]
    measured = rec["bands"]["modeled_speedup"]["measured"]
    assert abs(measured - modeled) <= 1e-3 * modeled, (measured, modeled)
    ratio = rec["flat"]["exchange_s"] / rec["twolevel"]["exchange_s"]
    assert abs(rec["exchange_speedup"] - ratio) <= 1e-3 * ratio
    # the two-level block carries the plan's OWN fabric view
    assert rec["twolevel"]["node_of"] == [
        int(x) for x in rec["node_map"].split(",")
    ]
    assert rec["twolevel"]["decision"]["use"] is True
    assert rec["twolevel"]["decision"]["node_pair_edges"] == (
        dcn_t["edges"]
    )
    if rec["platform"] == "tpu":
        assert rec["bands_ok_device"] is True
    else:
        assert rec["bands_ok_device"] is None
        assert "real TPUs" in rec["note"]
        for key, (lo, hi, kind) in bench_twolevel.CANARY_BANDS.items():
            band = rec["bands"].get(key)
            assert band is not None, f"canary record missing band {key}"
            assert band["kind"] == kind and band["in_band"] is True
    # the docs claim what the bands enforce
    perf = open(os.path.join(REPO, "docs", "performance.md")).read()
    assert "TWOLEVEL_BENCH.json" in perf
    knee = bench_twolevel.TWOLEVEL_BANDS["twolevel_exchange_speedup"][0]
    assert f"≥ {knee:g}×" in perf, (
        "docs/performance.md must state the device knee the band pins"
    )


def test_committed_comms_matrix_fabric_summaries_pin_both_ways():
    """The v2 schema's per-fabric summary is DERIVED state: for the
    committed COMMS_MATRIX.json — the top-level flat record AND its
    ``twolevel`` sub-record — the stored summary must equal the
    recomputation from the stored edge rows (stale-summary direction),
    and every fabric in the summary must be present among the edges
    (phantom-summary direction)."""
    from partitionedarrays_jl_tpu.telemetry import commsmatrix as cmx

    rec = json.load(open(os.path.join(REPO, "COMMS_MATRIX.json")))
    assert rec["comms_matrix_schema_version"] == (
        cmx.COMMS_MATRIX_SCHEMA_VERSION
    )
    tl = rec["twolevel"]
    for label, m in (("flat", rec), ("twolevel", tl)):
        assert m["fabric_summary"] == cmx.fabric_summary(m["edges"]), (
            label
        )
        assert set(m["fabric_summary"]) == {
            e["fabric"] for e in m["edges"]
        }, label
    # the sub-record is the node-aware fixture's own fabric view: the
    # plan kind, its node map, a recorded decision, and slow-fabric
    # traffic that the flat record (single-process host) cannot have
    assert tl["plan"] == "twolevel"
    assert tl["node_of"] == [0, 0, 1, 1]
    assert tl["decision"]["use"] is True
    assert tl["fabric_summary"]["dcn"]["edges"] == (
        tl["decision"]["node_pair_edges"]
    )
    assert "dcn" not in rec["fabric_summary"]


def test_memory_footprint_artifact_agrees_with_budgets():
    """The committed static-memory footprint table (the paplan
    tentpole's admission-budget artifact, written by
    ``tools/palint.py --write-memory``) and the ``memory-budget``
    contract's pinned budgets must agree: identical budget tables
    (artifact == analysis.memory_report.MEMORY_BUDGETS), one row per
    FULL-matrix case, every recorded peak inside its budget, and the
    rows internally consistent (a compiled-leg peak comes from the
    buffer assignment, everything else from the conservative
    shape-sum)."""
    from partitionedarrays_jl_tpu.analysis import memory_report
    from partitionedarrays_jl_tpu.parallel.tpu import lowering_matrix

    rec = json.load(open(os.path.join(REPO, "MEMORY_FOOTPRINT.json")))
    assert rec["memory_schema_version"] == (
        memory_report.MEMORY_SCHEMA_VERSION
    )
    assert rec["budgets"] == {
        k: v for k, v in memory_report.MEMORY_BUDGETS.items()
    }, "artifact budgets drifted from MEMORY_BUDGETS — regenerate with "\
       "tools/palint.py --write-memory"
    names = {c["name"] for c in lowering_matrix(fast=False)}
    assert set(rec["cases"]) == names, (
        f"+{set(rec['cases']) - names} -{names - set(rec['cases'])}"
    )
    for name, fp in rec["cases"].items():
        budget = rec["budgets"][name]
        assert 0 < fp["peak_bytes"] <= budget, (name, fp, budget)
        assert fp["carry_bytes"] > 0, (name, "solve case must carry state")
        assert fp["plan_bytes"] > 0 and fp["operand_bytes"] > 0, (name, fp)
        assert fp["peak_source"] in ("hlo-buffer-assignment", "shape-sum")
        if fp["peak_source"] == "shape-sum":
            assert fp["peak_bytes"] == (
                fp["operand_bytes"] + 2 * fp["carry_bytes"]
            ), (name, fp)
    # the shared artifact envelope (telemetry.artifacts)
    assert rec.get("schema_version") and rec.get("generated_by")
    assert rec.get("platform") and isinstance(rec.get("pa_env"), dict)


def test_repro_artifacts_carry_the_shared_envelope():
    """tools/bench_repro.py writes through the shared schema-versioned
    artifact writer — the committed ``docs/repro_r*.json`` records must
    carry the full envelope like every ``*_BENCH.json`` (round-11
    port of the two straggler bench tools)."""
    paths = sorted(
        f for f in os.listdir(os.path.join(REPO, "docs"))
        if re.fullmatch(r"repro_r\d+\.json", f)
    )
    assert paths, "no committed repro records found"
    for name in paths:
        rec = json.load(open(os.path.join(REPO, "docs", name)))
        assert rec.get("schema_version"), name
        assert rec.get("generated_by") == "bench_repro", name
        assert rec.get("platform"), name
        assert isinstance(rec.get("pa_env"), dict), name
        # the record body the study documents is still intact
        assert rec["reps"] == len(rec["halo"]) == len(rec["spmv"]), name
        for k in ("halo", "halo_host_oracle", "spmv"):
            s = rec[k + "_stats"]
            assert s["min"] <= s["median"] <= s["max"], (name, k)


def test_perf_ledger_covers_every_bench_artifact_and_equals_sources():
    """The committed PERF_LEDGER.json (round 13 — the perf trajectory
    as a machine-checked object) must COVER every committed
    ``*_BENCH.json`` and carry, as each series' latest point, exactly
    the value its source artifact records — the ledger can never fork
    from the artifacts it summarizes. It also rides the shared
    artifact envelope like everything else committed."""
    from partitionedarrays_jl_tpu.telemetry import (
        ARTIFACT_SCHEMA_VERSION,
        ledger,
    )

    led = json.load(open(os.path.join(REPO, "PERF_LEDGER.json")))
    assert led["ledger_schema_version"] == ledger.LEDGER_SCHEMA_VERSION
    assert led.get("schema_version") == ARTIFACT_SCHEMA_VERSION
    assert led.get("generated_by") == "pareg"
    assert led.get("platform") and isinstance(led.get("pa_env"), dict)
    # the tracked set: every *_BENCH.json plus the banded extras the
    # ledger declares (round 17 added SPECTRUM.json)
    names = sorted(
        os.path.basename(p) for p in ledger.artifact_paths(REPO)
    )
    assert names, "no committed bench artifacts found"
    assert any(n.endswith("_BENCH.json") for n in names)
    assert "SPECTRUM.json" in names
    assert sorted(led["artifacts"]) == names, (
        "ledger coverage drifted — run tools/pareg.py --update"
    )
    for name in names:
        rec = json.load(open(os.path.join(REPO, name)))
        metrics = ledger.extract_metrics(name, rec)
        assert metrics, f"{name}: no extractable metrics"
        assert sorted(metrics) == led["artifacts"][name]["metrics"]
        assert led["artifacts"][name]["source_hash"] == (
            ledger.content_hash(rec)
        ), f"{name}: ledger is stale — run tools/pareg.py --update"
        for key, row in metrics.items():
            points = led["series"][f"{name}:{key}"]
            assert points[-1]["value"] == row["value"], (name, key)
            assert points[-1]["lo"] == row["lo"], (name, key)
            assert points[-1]["hi"] == row["hi"], (name, key)
    # the sentinel itself is green on the committed set (the same
    # invariant tools/pareg.py --check gates in tier-1)
    assert ledger.check_repo(REPO) == []


def test_every_committed_bench_artifact_is_schema_versioned():
    """Every committed ``*_BENCH.json`` carries the FULL shared artifact
    envelope (telemetry.artifacts): ``schema_version``, the generating
    tool, the accelerator ``platform``, and the ``pa_env`` snapshot —
    everything the writer unconditionally stamps. An artifact written
    around the shared writer (or hand-stamped with only the two
    eyeball-able keys) fails here, keeping the schema claim in
    docs/observability.md enforceable."""
    from partitionedarrays_jl_tpu.telemetry import ARTIFACT_SCHEMA_VERSION

    paths = sorted(
        f for f in os.listdir(REPO) if f.endswith("_BENCH.json")
    )
    assert paths, "no committed *_BENCH.json artifacts found"
    for name in paths:
        rec = json.load(open(os.path.join(REPO, name)))
        assert rec.get("schema_version") == ARTIFACT_SCHEMA_VERSION, (
            f"{name} missing/mismatched schema_version "
            f"(want {ARTIFACT_SCHEMA_VERSION}, "
            f"got {rec.get('schema_version')!r})"
        )
        assert rec.get("generated_by"), (
            f"{name} must name its generating tool"
        )
        assert rec.get("platform"), (
            f"{name} must record the platform it was measured on"
        )
        assert isinstance(rec.get("pa_env"), dict), (
            f"{name} must carry the PA_* environment snapshot "
            "(the writer stamps it unconditionally — empty is fine)"
        )


def test_gate_artifact_agrees_with_guard_bands():
    """The committed front-door artifact (round 14 — ROADMAP item 1's
    acceptance leg) and the bench guard must agree: identical band
    bounds, a multi-client leg with N>=2 tenants under a budget that
    FORCED at least one eviction during load, the per-class attainment
    read from the pamon registry deltas equal to the client-side
    outcome table, and the interactive class meeting its target WHILE
    shedding was active — measured, not asserted. Canary-kind bands
    gate on every platform."""
    bench_gate = _load_tool("bench_gate")
    rec = json.load(open(os.path.join(REPO, "GATE_BENCH.json")))
    assert rec["methodology"] == bench_gate.METHODOLOGY
    for key, (lo, hi, kind) in bench_gate.GATE_BANDS.items():
        band = rec["bands"].get(key)
        assert band is not None, f"artifact missing band {key}"
        assert (band["lo"], band["hi"], band["kind"]) == (lo, hi, kind), (
            key, band,
        )
        assert band["in_band"], (key, band)
    # N>=2 operators under a budget that cannot hold them all resident
    assert len(rec["tenants"]) >= 2
    assert rec["budget_bytes"] < sum(
        t["footprint_bytes"] for t in rec["tenants"]
    )
    multi = rec["multi_client"]
    assert multi["clients"] >= 2
    assert multi["evictions_during_load"] >= 1
    # shedding was ACTIVE, absorbed entirely by the lowest class,
    # and the interactive target held while it was
    assert multi["shed_total"] >= 1
    per = multi["per_class"]
    assert per["besteffort"]["shed"] == multi["shed_total"]
    assert per["interactive"]["shed"] == 0
    target = multi["attainment_target"]
    assert rec["bands"]["interactive_attainment"]["lo"] == target
    assert per["interactive"]["attainment"] >= target
    # attainment is the pamon readout, consistent with the client side
    for cls, row in per.items():
        assert row["pamon_requests"] == row["submitted"] - row["shed"], (
            cls, row,
        )
        assert row["pamon_hits"] == row["done"], (cls, row)
        if row["pamon_requests"]:
            want = row["pamon_hits"] / row["pamon_requests"]
            assert abs(row["attainment"] - want) <= 1e-6, (cls, row)
    # eviction cost is internally consistent
    ev = rec["eviction_cost"]
    ratio = ev["cold_solve_s"] / ev["warm_solve_s"]
    assert abs(ev["ratio"] - ratio) <= 1e-2 * max(ratio, 1.0), ev
    assert abs(
        ev["page_in_overhead_s"]
        - max(0.0, ev["cold_solve_s"] - ev["warm_solve_s"])
    ) <= 2e-6, ev  # fields round independently of their difference
    # round 18's saturation leg: an open-loop offered-load curve with
    # a measured knee — the knee is the LAST level that met the SLO
    # (all done, interactive attainment >= target, sustained/offered
    # >= ratio target), and the knee bands are derived from it, not
    # asserted independently
    sat = rec["saturation"]
    assert sat["probe_base_rps"] > 0
    curve = sat["curve"]
    assert [lv["capacity_multiple"] for lv in curve] == list(
        sat["levels_capacity_multiples"]
    )
    for lv in curve:
        assert lv["requests"] == sat["requests_per_level"]
        assert lv["offered_rps"] > 0 and lv["window_s"] > 0
        want_sust = lv["sustained_rps"] / lv["offered_rps"]
        # fields round to 6 decimals independently of their quotient
        assert abs(lv["sustained_ratio"] - want_sust) <= 1e-4, lv
        want_ok = (
            lv["done"] == lv["requests"]
            and lv["attainment"]["interactive"]
            >= sat["attainment_target"]
            and lv["sustained_ratio"] >= sat["sustain_ratio_target"]
        )
        assert lv["meets_slo"] == want_ok, lv
        # pamon saw every completed request of the window
        assert lv["pamon_count"] == lv["done"], lv
        assert lv["pamon_p99_s"] >= lv["pamon_p50_s"], lv
    knee = sat["knee"]
    assert knee is not None, "the committed curve must exhibit a knee"
    ok_levels = [lv for lv in curve if lv["meets_slo"]]
    assert ok_levels and knee == ok_levels[-1]
    assert rec["bands"]["saturation_knee_rps"]["measured"] == (
        knee["offered_rps"]
    )
    assert rec["bands"]["saturation_attainment_at_knee"]["measured"] == (
        knee["attainment"]["interactive"]
    )
    # the shared artifact envelope
    assert rec.get("schema_version") and rec.get("generated_by") == (
        "bench_gate"
    )
    assert rec.get("platform") and isinstance(rec.get("pa_env"), dict)


def test_spectrum_artifact_agrees_with_analytic_and_bands():
    """The committed SPECTRUM.json (round 17 — the convergence
    observatory) is the real thing: shared artifact envelope, a
    loadable schema-versioned store, a conformance block whose
    ANALYTIC eigenvalues equal a fresh closed-form recomputation, a κ̂
    band whose measured ratio is arithmetically consistent with its
    own numbers AND the documented [0.5, 1.05] window (Ritz converges
    from inside — the ratio may never exceed ~1), and >= 3 forecast
    (operator, tol) pairs with the worst relative error in band. The
    perf ledger covers it like every bench artifact (the coverage test
    above picks it up via telemetry.ledger.artifact_paths)."""
    from partitionedarrays_jl_tpu import telemetry
    from partitionedarrays_jl_tpu.telemetry import ledger

    path = os.path.join(REPO, "SPECTRUM.json")
    rec = json.load(open(path))
    # envelope + schema + store round-trip
    assert rec.get("schema_version") == telemetry.ARTIFACT_SCHEMA_VERSION
    assert rec.get("generated_by") == "paspec"
    assert rec.get("platform") and isinstance(rec.get("pa_env"), dict)
    assert rec["spectrum_schema_version"] == (
        telemetry.SPECTRUM_SCHEMA_VERSION
    )
    store = telemetry.SpectrumStore.load(rec)
    conf = rec["conformance"]
    spec = store.spec(conf["fingerprint"], conf["dtype"],
                      conf["minv_class"])
    assert spec is not None and spec["samples"] >= 1
    # the analytic pin: closed form recomputed fresh, not trusted
    lo, hi = telemetry.poisson_fdm_analytic_extremes(rec["probe"]["ns"])
    assert conf["analytic_lam_min"] == lo
    assert conf["analytic_lam_max"] == hi
    assert conf["analytic_kappa"] == pytest.approx(hi / lo, rel=1e-12)
    # Ritz estimates lie INSIDE the analytic spectrum (to rounding)
    assert conf["estimated_lam_min"] >= 0.99 * lo
    assert conf["estimated_lam_max"] <= 1.01 * hi
    band = rec["bands"]["spectrum_kappa_ratio"]
    ratio = conf["estimated_kappa"] / conf["analytic_kappa"]
    assert band["measured"] == pytest.approx(ratio, abs=1e-6)
    assert (band["lo"], band["hi"]) == (0.5, 1.05)
    assert band["in_band"] is True
    assert band["lo"] <= band["measured"] <= band["hi"]
    # the forecast acceptance: >= 3 pairs, worst error banded
    fband = rec["bands"]["spectrum_forecast_rel_error_max"]
    pairs = rec["forecast"]
    assert len(pairs) >= 3
    errs = [p["rel_error"] for p in pairs]
    assert all(e is not None for e in errs)
    assert fband["measured"] == pytest.approx(max(errs), abs=1e-6)
    assert fband["in_band"] is True and max(errs) <= fband["hi"]
    for p in pairs:
        assert p["rel_error"] == pytest.approx(
            abs(p["predicted"] - p["actual"]) / max(1, p["actual"]),
            abs=1e-6,
        )
    # tighter tol may never forecast FEWER iterations (monotonicity)
    preds = [p["predicted"] for p in sorted(
        pairs, key=lambda p: -p["tol"]
    )]
    assert preds == sorted(preds)
    # the ledger folds it in (extract_metrics sees the bands table)
    assert path in ledger.artifact_paths(REPO)
    metrics = ledger.extract_metrics("SPECTRUM.json", rec)
    assert set(metrics) == {
        "spectrum_kappa_ratio", "spectrum_forecast_rel_error_max"
    }
