"""PA_TPU_STRICT_BITS=1: the literal form of the BASELINE.md gate
("bit-exact vs SequentialBackend"). In strict mode the compiled CG —
SpMV, halo exchange, dots, axpys — must reproduce the sequential oracle
bit for bit: identical iteration counts, identical residual-history
bits, identical solution bits. The default mode trades this for the
coded-DIA kernels and FMA contraction (agreement to rounding, covered
by tests/test_tpu.py); this file pins the strict contract.

Workload: the 3-D Poisson FDM driver (reference baseline workload,
/root/reference/test/test_fdm.jl:8-120) on a 2x2x2 part grid, f64.
"""
import os

import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu.models import assemble_poisson, gather_pvector


def _fdm_cg(parts, ns):
    A, b, x_exact, x0 = assemble_poisson(parts, ns)
    x, info = pa.cg(A, b, x0=x0, tol=1e-8, maxiter=400)
    return gather_pvector(x), info


@pytest.fixture
def strict_env(monkeypatch):
    monkeypatch.setenv("PA_TPU_STRICT_BITS", "1")
    yield


def test_strict_cg_bit_exact_vs_sequential(strict_env):
    import jax

    from partitionedarrays_jl_tpu.parallel.tpu import TPUBackend

    ns = (6, 6, 6)
    xs, infos = pa.prun(_fdm_cg, pa.sequential, (2, 2, 2), ns)
    backend = TPUBackend(devices=jax.devices()[:8])
    xt, infot = pa.prun(_fdm_cg, backend, (2, 2, 2), ns)
    assert infos["iterations"] == infot["iterations"]
    n = infot["iterations"] + 1
    np.testing.assert_array_equal(
        np.asarray(infos["residuals"])[:n], np.asarray(infot["residuals"])[:n]
    )
    np.testing.assert_array_equal(xs, xt)  # bit-identical solutions


def test_strict_spmv_bit_exact_vs_sequential(strict_env):
    """One overlapped SpMV (boundary rows mix owned and ghost terms) is
    already bit-exact in strict mode — the ELL fold order matches the
    host csr_spmv + mul_into pair exactly."""
    import jax

    from partitionedarrays_jl_tpu.parallel.tpu import (
        DeviceVector, TPUBackend, device_matrix, make_spmv_fn,
    )

    ns = (5, 4, 3)

    def build(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, ns)
        return A, x_exact

    backend = TPUBackend(devices=jax.devices()[:8])
    A, xe = pa.prun(build, backend, (2, 2, 2))
    dA = device_matrix(A, backend)
    assert dA.dia_mode is None, "strict mode must force the ELL lowering"
    y_host = gather_pvector(A @ xe)
    dx = DeviceVector.from_pvector(xe, backend, dA.col_layout)
    spmv = make_spmv_fn(dA)
    y_dev = DeviceVector(
        spmv(dx.data), A.rows, dA.row_layout, backend
    ).to_pvector()
    np.testing.assert_array_equal(y_host, gather_pvector(y_dev))


def test_default_mode_unaffected():
    """Without the flag the coded-DIA lowering still engages (the strict
    gate must not leak into the default path)."""
    import jax

    from partitionedarrays_jl_tpu.parallel.tpu import TPUBackend, device_matrix

    assert os.environ.get("PA_TPU_STRICT_BITS", "0") != "1"

    def build(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8, 8))
        return A

    backend = TPUBackend(devices=jax.devices()[:8])
    A = pa.prun(build, backend, (2, 2, 2))
    dA = device_matrix(A, backend)
    assert dA.dia_mode == "coded"
