"""PA_TPU_STRICT_BITS=1: the literal form of the BASELINE.md gate
("bit-exact vs SequentialBackend"). In strict mode the compiled CG —
SpMV, halo exchange, dots, axpys — must reproduce the sequential oracle
bit for bit: identical iteration counts, identical residual-history
bits, identical solution bits. The default mode trades this for the
coded-DIA kernels and FMA contraction (agreement to rounding, covered
by tests/test_tpu.py); this file pins the strict contract.

Workload: the 3-D Poisson FDM driver (reference baseline workload,
/root/reference/test/test_fdm.jl:8-120) on a 2x2x2 part grid, f64.
"""
import os

import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu.models import assemble_poisson, gather_pvector


def _fdm_cg(parts, ns):
    A, b, x_exact, x0 = assemble_poisson(parts, ns)
    x, info = pa.cg(A, b, x0=x0, tol=1e-8, maxiter=400)
    return gather_pvector(x), info


@pytest.fixture
def strict_env(monkeypatch):
    monkeypatch.setenv("PA_TPU_STRICT_BITS", "1")
    yield


def test_strict_cg_bit_exact_vs_sequential(strict_env):
    import jax

    from partitionedarrays_jl_tpu.parallel.tpu import TPUBackend

    ns = (6, 6, 6)
    xs, infos = pa.prun(_fdm_cg, pa.sequential, (2, 2, 2), ns)
    backend = TPUBackend(devices=jax.devices()[:8])
    xt, infot = pa.prun(_fdm_cg, backend, (2, 2, 2), ns)
    assert infos["iterations"] == infot["iterations"]
    n = infot["iterations"] + 1
    np.testing.assert_array_equal(
        np.asarray(infos["residuals"])[:n], np.asarray(infot["residuals"])[:n]
    )
    np.testing.assert_array_equal(xs, xt)  # bit-identical solutions


def test_strict_spmv_bit_exact_vs_sequential(strict_env):
    """One overlapped SpMV (boundary rows mix owned and ghost terms) is
    already bit-exact in strict mode — the ELL fold order matches the
    host csr_spmv + mul_into pair exactly."""
    import jax

    from partitionedarrays_jl_tpu.parallel.tpu import (
        DeviceVector, TPUBackend, device_matrix, make_spmv_fn,
    )

    ns = (5, 4, 3)

    def build(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, ns)
        return A, x_exact

    backend = TPUBackend(devices=jax.devices()[:8])
    A, xe = pa.prun(build, backend, (2, 2, 2))
    dA = device_matrix(A, backend)
    assert dA.dia_mode is None, "strict mode must force the ELL lowering"
    y_host = gather_pvector(A @ xe)
    dx = DeviceVector.from_pvector(xe, backend, dA.col_layout)
    spmv = make_spmv_fn(dA)
    y_dev = DeviceVector(
        spmv(dx.data), A.rows, dA.row_layout, backend
    ).to_pvector()
    np.testing.assert_array_equal(y_host, gather_pvector(y_dev))


def test_default_mode_unaffected():
    """Without the flag the coded-DIA lowering still engages (the strict
    gate must not leak into the default path)."""
    import jax

    from partitionedarrays_jl_tpu.parallel.tpu import TPUBackend, device_matrix

    assert os.environ.get("PA_TPU_STRICT_BITS", "0") != "1"

    def build(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8, 8))
        return A

    backend = TPUBackend(devices=jax.devices()[:8])
    A = pa.prun(build, backend, (2, 2, 2))
    dA = device_matrix(A, backend)
    assert dA.dia_mode == "coded"


def test_strict_product_fence_blocks_fma_and_preserves_ieee():
    """The codegen-level fence in `_strict_rounded_product` must (a)
    force the product to its own IEEE rounding — the bare form measurably
    FMA-contracts through LLVM on the CPU backend — while (b) passing
    finite values (including -0.0) through bit-unchanged and (c)
    propagating NaN. Pinned empirically because the fence's strength is
    an LLVM-pipeline property, not an XLA guarantee: a jax upgrade could
    silently re-enable contraction."""
    import jax
    import jax.numpy as jnp

    from partitionedarrays_jl_tpu.parallel.tpu import _strict_rounded_product

    rng = np.random.default_rng(0)
    N = 100_000
    a = rng.standard_normal(N).astype(np.float32)
    b = rng.standard_normal(N).astype(np.float32)
    c = rng.standard_normal(N).astype(np.float32)

    @jax.jit
    def fenced(a, b, c):
        return _strict_rounded_product(a * b) + c

    @jax.jit
    def bare(a, b, c):
        return a * b + c

    # exact two-step f32: round(a*b), then round(+c) (f64 emulation)
    prod = (a.astype(np.float64) * b.astype(np.float64)).astype(np.float32)
    two_step = (prod.astype(np.float64) + c.astype(np.float64)).astype(
        np.float32
    )
    n_fenced = int((np.asarray(fenced(a, b, c)) != two_step).sum())
    n_bare = int((np.asarray(bare(a, b, c)) != two_step).sum())
    assert n_fenced == 0, f"fence failed to block FMA on {n_fenced}/{N}"
    # if the bare form no longer contracts either, the platform changed
    # and this test is vacuous — flag it for re-evaluation, don't pass
    assert n_bare > 0, "bare a*b+c no longer FMA-contracts: re-check fence"

    out = np.asarray(
        jax.jit(_strict_rounded_product)(
            jnp.array([1.5, np.nan, -2.0, 0.0, -0.0])
        )
    )
    assert out[0] == 1.5 and out[2] == -2.0
    assert np.isnan(out[1])  # NaN poison propagates (no silent zeroing)
    assert not np.signbit(out[3]) and np.signbit(out[4])  # ±0.0 preserved
