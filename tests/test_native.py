"""Native planning accelerator: equivalence with the NumPy fallback and
graceful degradation when disabled."""
import os
import shutil

import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu import native

# these tests compare the native kernels against the fallback, so they
# need the native layer; under PA_TPU_NATIVE=0 the rest of the suite IS
# the fallback coverage
pytestmark = pytest.mark.skipif(
    os.environ.get("PA_TPU_NATIVE") == "0",
    reason="native layer disabled via PA_TPU_NATIVE=0",
)


def _with_native(enabled):
    """Temporarily force the native layer on/off (restores in fixture)."""
    saved = (native._lib, native._tried)
    if not enabled:
        native._lib, native._tried = None, True
    return saved


@pytest.mark.skipif(shutil.which("g++") is None, reason="no g++ toolchain")
def test_native_builds_and_loads():
    assert native.available(), "g++ toolchain present: native layer must build"


def test_box_gids_to_lids_matches_fallback():
    rng = np.random.default_rng(0)
    grid, lo, hi = (13, 9, 17), (3, 0, 5), (11, 4, 16)
    gids = rng.integers(-5, 13 * 9 * 17 + 5, size=4000)
    out_native = np.full(len(gids), -1, dtype=np.int32)
    assert native.box_gids_to_lids(gids, grid, lo, hi, out_native)
    # NumPy oracle
    coords = np.unravel_index(np.clip(gids, 0, 13 * 9 * 17 - 1), grid)
    owned = (gids >= 0) & (gids < 13 * 9 * 17)
    local = []
    for c, l, h in zip(coords, lo, hi):
        owned &= (c >= l) & (c < h)
        local.append(np.clip(c - l, 0, None))
    expect = np.full(len(gids), -1, dtype=np.int32)
    expect[owned] = np.ravel_multi_index(
        [x[owned] for x in local], tuple(h - l for l, h in zip(lo, hi))
    )
    np.testing.assert_array_equal(out_native, expect)


def test_cartesian_lookup_same_with_and_without_native():
    def run():
        def driver(parts):
            rows = pa.cartesian_partition(parts, (7, 6), pa.with_ghost)
            iset = rows.partition.get_part(2)
            q = np.arange(-2, 44)
            return iset.gids_to_lids(q).copy()

        return pa.prun(driver, pa.sequential, (2, 2))

    with_native = run()
    saved = _with_native(False)
    try:
        without = run()
    finally:
        native._lib, native._tried = saved
    np.testing.assert_array_equal(with_native, without)


def test_coo_to_csr_matches_numpy_path():
    from partitionedarrays_jl_tpu.ops.sparse import compresscoo

    rng = np.random.default_rng(7)
    m, n, nnz = 50, 40, 3000  # heavy duplicates and one long row
    I = rng.integers(0, m, size=nnz)
    I[:200] = 7  # a >64-entry row to hit the comparison-sort path
    J = rng.integers(0, n, size=nnz)
    V = rng.standard_normal(nnz)
    A_nat = compresscoo(I, J, V, m, n)
    saved = _with_native(False)
    try:
        A_np = compresscoo(I, J, V, m, n)
    finally:
        native._lib, native._tried = saved
    np.testing.assert_array_equal(A_nat.indptr, A_np.indptr)
    np.testing.assert_array_equal(A_nat.indices, A_np.indices)
    # duplicate groups: native sums strictly left-to-right in original
    # order (the well-defined contract, matching Julia's sparse()); the
    # NumPy fallback's reduceat uses SIMD partial sums and may differ by
    # rounding. Bit-check native against an explicit L2R oracle instead.
    np.testing.assert_allclose(A_nat.data, A_np.data, rtol=1e-13, atol=1e-15)
    for k in range(0, len(A_nat.data), 97):
        r = np.searchsorted(A_nat.indptr, k, side="right") - 1
        c = A_nat.indices[k]
        sel = (I == r) & (J == c)
        acc = None  # strict left-to-right fold (np.add.reduce is pairwise)
        for v in V[sel]:
            acc = v if acc is None else acc + v
        assert A_nat.data[k] == acc


def test_csr_split_matches_csr_block():
    from partitionedarrays_jl_tpu.ops.sparse import compresscoo, csr_block

    rng = np.random.default_rng(8)
    m, n, nnz, thr = 60, 50, 900, 33
    A = compresscoo(
        rng.integers(0, m, nnz), rng.integers(0, n, nnz),
        rng.standard_normal(nnz), m, n,
    )
    halves = native.csr_split_by_col(A.indptr, A.indices, A.data, m, thr)
    assert halves is not None
    (ipo, co, vo), (iph, ch, vh) = halves
    rows_all = np.arange(m)
    lo = csr_block(A, rows_all, thr, want_upper=False)
    hi = csr_block(A, rows_all, thr, want_upper=True, col_offset=thr)
    np.testing.assert_array_equal(ipo, lo.indptr)
    np.testing.assert_array_equal(co, lo.indices)
    np.testing.assert_array_equal(vo, lo.data)
    np.testing.assert_array_equal(iph, hi.indptr)
    np.testing.assert_array_equal(ch, hi.indices)
    np.testing.assert_array_equal(vh, hi.data)


def test_unique_small_matches_numpy():
    rng = np.random.default_rng(11)
    few = rng.choice([1.5, -2.25, 0.0, 7.125], size=5000)
    u, ok = native.unique_small(few, 8)
    assert ok
    np.testing.assert_array_equal(u, np.unique(few))
    many, ok2 = native.unique_small(rng.standard_normal(100), 8)
    assert not ok2
    u0, ok0 = native.unique_small(np.empty(0), 8)
    assert ok0 and len(u0) == 0


def test_row_classes_matches_numpy_fallback():
    rng = np.random.default_rng(12)
    D, stride, n = 5, 9000, 8123  # n < stride exercises the strided read
    base = rng.standard_normal((4, D))  # 4 classes
    ids = rng.integers(0, 4, size=stride)
    dia = base[ids].T.copy()
    table, codes, ok = native.row_classes(dia, n, 8)
    assert ok
    saved = _with_native(False)
    try:
        t_np, c_np, ok_np = native.row_classes(dia, n, 8)
    finally:
        native._lib, native._tried = saved
    assert ok_np
    # class ORDER may differ (first-touch vs lexicographic); the decoded
    # per-row tuples must be identical
    np.testing.assert_array_equal(table[codes], t_np[c_np])
    # overflow: > K classes
    _, _, ok_over = native.row_classes(rng.standard_normal((3, 64)), 64, 8)
    assert not ok_over


def test_ic0_native_matches_fallback_and_is_exact_when_full():
    """IC(0): native kernel vs the pure-NumPy fallback, and exactness on
    a tridiagonal SPD matrix (full lower pattern -> IC(0) IS Cholesky)."""
    import scipy.sparse as sp

    n = 64
    rng = np.random.default_rng(7)
    d = 2.0 + rng.random(n)
    A = sp.diags([-np.ones(n - 1), d, -np.ones(n - 1)], [-1, 0, 1]).tocsr()
    low = sp.tril(A).tocsr()
    low.sort_indices()
    lv, fail = native.ic0(low.indptr, low.indices, low.data, n)
    assert fail == -1
    saved = _with_native(False)
    try:
        lv_np, fail_np = native.ic0(low.indptr, low.indices, low.data, n)
    finally:
        native._lib, native._tried = saved
    assert fail_np == -1
    np.testing.assert_allclose(lv, lv_np, rtol=1e-14)
    L = sp.csr_matrix((lv, low.indices, low.indptr), shape=(n, n))
    np.testing.assert_allclose((L @ L.T).toarray(), A.toarray(), atol=1e-12)
    # breakdown reporting: an indefinite diagonal fails at its row
    bad = sp.diags([np.where(np.arange(n) == 5, -1.0, 1.0)], [0]).tocsr()
    lv_b, fail_b = native.ic0(bad.indptr, bad.indices, bad.data, n)
    assert lv_b is None and fail_b == 5
