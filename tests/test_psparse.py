"""L5 tests: PSparseMatrix build, block views, SpMV, assembly, solvers.

Mirrors the reference conformance coverage
(reference: test/test_interfaces.jl:645-734), re-derived 0-based.
"""
import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu.models import cg, direct_solve, gather_psparse, gather_pvector, lu


def parts4():
    return pa.sequential.get_part_ids(4)


def laplacian_1d(n=12):
    """1-D Laplacian with Dirichlet identity end rows over 4 parts."""
    parts = parts4()
    rows = pa.uniform_partition(parts, n)

    def _coo(iset):
        gi = iset.oid_to_gid
        interior = (gi > 0) & (gi < n - 1)
        I = [gi[~interior], gi[interior], gi[interior], gi[interior]]
        J = [gi[~interior], gi[interior], gi[interior] - 1, gi[interior] + 1]
        V = [
            np.ones(int((~interior).sum())),
            np.full(int(interior.sum()), 2.0),
            np.full(int(interior.sum()), -1.0),
            np.full(int(interior.sum()), -1.0),
        ]
        return np.concatenate(I), np.concatenate(J), np.concatenate(V)

    coo = pa.map_parts(_coo, rows.partition)
    I = pa.map_parts(lambda c: c[0], coo)
    J = pa.map_parts(lambda c: c[1], coo)
    V = pa.map_parts(lambda c: c[2], coo)
    cols = pa.add_gids(rows, J)
    A = pa.PSparseMatrix.from_coo(I, J, V, rows, cols, ids="global")
    return A, rows, cols


def test_from_coo_and_gather():
    A, rows, cols = laplacian_1d()
    assert A.shape == (12, 12)
    G = gather_psparse(A).toarray()
    expected = np.zeros((12, 12))
    expected[0, 0] = expected[11, 11] = 1.0
    for i in range(1, 11):
        expected[i, i] = 2.0
        expected[i, i - 1] = -1.0
        expected[i, i + 1] = -1.0
    assert np.array_equal(G, expected)


def test_block_views():
    A, rows, cols = laplacian_1d()

    def _check(ri, ci, blk_oo, blk_oh, full):
        no_r, no_c = ri.num_oids, ci.num_oids
        d = full.toarray()
        assert np.array_equal(blk_oo.toarray(), d[:no_r, :no_c])
        assert np.array_equal(blk_oh.toarray(), d[:no_r, no_c:])

    pa.map_parts(
        _check,
        A.rows.partition,
        A.cols.partition,
        A.owned_owned_values,
        A.owned_ghost_values,
        A.values,
    )


def test_spmv_matches_gathered():
    A, rows, cols = laplacian_1d()
    x = pa.PVector(
        pa.map_parts(lambda i: np.sin(i.lid_to_gid.astype(float)), cols.partition),
        cols,
    )
    y = A @ x
    assert np.allclose(gather_pvector(y), gather_psparse(A).toarray() @ gather_pvector(x))
    # alpha/beta accumulation form
    c = pa.PVector.full(1.0, rows)
    A.mul_into(c, x, alpha=2.0, beta=0.5)
    assert np.allclose(
        gather_pvector(c), 0.5 + 2.0 * (gather_psparse(A).toarray() @ gather_pvector(x))
    )


def test_spmv_axis_contract():
    A, rows, cols = laplacian_1d()
    bad = pa.PVector.full(1.0, rows)  # missing the column ghost layer
    with pytest.raises(AssertionError):
        A @ bad


def test_scalar_ops():
    A, rows, cols = laplacian_1d()
    B = 2.0 * A
    assert np.array_equal(gather_psparse(B).toarray(), 2.0 * gather_psparse(A).toarray())
    C = -A
    assert np.array_equal(gather_psparse(C).toarray(), -gather_psparse(A).toarray())


def test_assemble_coo_migration():
    # triplets written on the "wrong" part migrate to row owners
    parts = parts4()
    rows0 = pa.uniform_partition(parts, 8)
    ghosts = pa.map_parts(lambda p: np.array([(2 * p + 2) % 8]), parts)
    rows = pa.add_gids(rows0, ghosts)
    # each part writes 1.0 into (g, g) for its ghost row g (owned by p+1)
    I = pa.map_parts(lambda p: np.array([(2 * p + 2) % 8]), parts)
    J = pa.map_parts(lambda p: np.array([(2 * p + 2) % 8]), parts)
    V = pa.map_parts(lambda p: np.array([1.0]), parts)
    I2, J2, V2 = pa.assemble_coo(I, J, V, rows)
    # every shipped triplet landed on its owner with the local copy zeroed
    for p, (i2, v2) in enumerate(zip(I2.part_values(), V2.part_values())):
        own_gid = 2 * p
        assert (np.asarray(v2) != 0).sum() == 1
        nz = np.asarray(i2)[np.asarray(v2) != 0]
        assert list(nz) == [own_gid]
    A = pa.PSparseMatrix.from_coo(I2, J2, V2, rows, rows.copy(), ids="global")
    G = gather_psparse(A).toarray()
    assert np.array_equal(np.diag(G), [1.0, 0, 1.0, 0, 1.0, 0, 1.0, 0])


def test_matrix_exchanger_halo_and_assembly():
    # matrix with ghost rows: parts hold copies of remote rows
    parts = parts4()
    rows0 = pa.uniform_partition(parts, 8)
    ghosts = pa.map_parts(lambda p: np.array([(2 * p + 2) % 8]), parts)
    rows = pa.add_gids(rows0, ghosts)
    cols = rows.copy()
    # each part stores (g,g)=5 for its ghost row g and (o,o)=p+1 for first owned o
    I = pa.map_parts(lambda p: np.array([(2 * p + 2) % 8, 2 * p]), parts)
    J = pa.map_parts(lambda p: np.array([(2 * p + 2) % 8, 2 * p]), parts)
    V = pa.map_parts(lambda p: np.array([5.0, float(p + 1)]), parts)
    A = pa.PSparseMatrix.from_coo(I, J, V, rows, cols, ids="global")
    # assembly: ghost-row values add into the owner entry, ghosts zeroed
    A.assemble()
    for ri, M in zip(A.rows.partition, A.values.part_values()):
        own_lid = 0
        k = M.indptr[own_lid]
        assert M.data[k] == pytest.approx(5.0 + (ri.part + 1))
        for h in ri.hid_to_lid:
            assert np.all(M.data[M.indptr[h] : M.indptr[h + 1]] == 0.0)
    # halo update: owners push their values back out to ghost copies
    A.exchange()
    for ri, M in zip(A.rows.partition, A.values.part_values()):
        for h in ri.hid_to_lid:
            assert np.all(M.data[M.indptr[h] : M.indptr[h + 1]] != 0.0)


def test_exchange_coo_replication():
    parts = parts4()
    rows0 = pa.uniform_partition(parts, 8)
    ghosts = pa.map_parts(lambda p: np.array([(2 * p + 2) % 8]), parts)
    rows = pa.add_gids(rows0, ghosts)
    # owners hold (g, g, g+1.0) for each owned gid
    I = pa.map_parts(lambda i: i.oid_to_gid.copy(), rows.partition)
    J = pa.map_parts(lambda i: i.oid_to_gid.copy(), rows.partition)
    V = pa.map_parts(lambda i: i.oid_to_gid.astype(float) + 1.0, rows.partition)
    I2, J2, V2 = pa.exchange_coo(I, J, V, rows)
    # every part now also holds the triplet of its ghost row
    for iset, i2, v2 in zip(rows.partition, I2.part_values(), V2.part_values()):
        g = int(iset.hid_to_gid[0])
        hit = np.asarray(i2) == g
        assert hit.sum() == 1
        assert np.asarray(v2)[hit][0] == g + 1.0


def test_cg_and_direct_solve():
    A, rows, cols = laplacian_1d()
    x_exact = pa.PVector(
        pa.map_parts(
            lambda i: np.cos(i.lid_to_gid.astype(float)), cols.partition
        ),
        cols,
    )
    b = A @ x_exact
    # Dirichlet rows are identity: the start vector must carry the exact
    # boundary values so CG's residual stays in the SPD interior subspace
    # (same device as the reference driver, test/test_fdm.jl:98-110).
    x0 = pa.PVector(
        pa.map_parts(
            lambda i: np.where(
                (i.lid_to_gid == 0) | (i.lid_to_gid == 11),
                np.cos(i.lid_to_gid.astype(float)),
                0.0,
            ),
            cols.partition,
        ),
        cols,
    )
    x, info = cg(A, b, x0=x0, tol=1e-12)
    assert info["converged"]
    assert (x - x_exact).norm() < 1e-9
    xd = direct_solve(A, b)
    assert (xd - x_exact).norm() < 1e-9
    f = lu(A)
    xl = f.solve(b)
    assert (xl - x_exact).norm() < 1e-9
    # residual check mirroring the reference's norm(A*x-y) < 1e-9
    assert (A @ xl - b).norm() < 1e-9
