"""L0 unit tests: ragged Table storage + ptr arithmetic.

Mirrors the reference's Helpers coverage (reference: src/Helpers.jl:63-156),
0-based.
"""
import numpy as np
import pytest

from partitionedarrays_jl_tpu import (
    Table,
    counts_to_ptrs,
    empty_table,
    generate_data_and_ptrs,
    get_data,
    get_ptrs,
    length_to_ptrs,
    ptrs_to_counts,
    rewind_ptrs,
)


def test_length_to_ptrs_roundtrip():
    counts = np.array([3, 0, 2, 4])
    ptrs = length_to_ptrs(counts)
    assert list(ptrs) == [0, 3, 3, 5, 9]
    assert list(ptrs_to_counts(ptrs)) == [3, 0, 2, 4]
    assert counts_to_ptrs is length_to_ptrs


def test_rewind_ptrs():
    ptrs = np.array([3, 5, 9, 9], dtype=np.int32)
    rewind_ptrs(ptrs)
    assert list(ptrs) == [0, 3, 5, 9]


def test_generate_data_and_ptrs():
    rows = [np.array([1, 2]), np.array([], dtype=np.int64), np.array([3, 4, 5])]
    data, ptrs = generate_data_and_ptrs(rows)
    assert list(data) == [1, 2, 3, 4, 5]
    assert list(ptrs) == [0, 2, 2, 5]


def test_table_rows_and_views():
    t = Table.from_rows([[1.0, 2.0], [], [3.0]])
    assert len(t) == 3
    assert list(t[0]) == [1.0, 2.0]
    assert list(t[1]) == []
    assert list(t[2]) == [3.0]
    assert t.row_length(1) == 0
    assert list(t.counts()) == [2, 0, 1]
    # rows are views: writing through them mutates the flat data
    t[0][:] = [7.0, 8.0]
    assert list(get_data(t)[:2]) == [7.0, 8.0]
    assert list(get_ptrs(t)) == [0, 2, 2, 3]


def test_table_equality_and_empty():
    a = Table.from_rows([[1, 2], [3]])
    b = Table.from_rows([[1, 2], [3]])
    c = Table.from_rows([[1], [2, 3]])
    assert a == b
    assert a != c
    e = empty_table(np.int32)
    assert len(e) == 1 - 1
    assert list(e.counts()) == []


def test_table_from_all_empty_rows():
    t = Table.from_rows([[], [], []])
    assert len(t) == 3
    assert all(t.row_length(i) == 0 for i in range(3))


def test_table_bad_ptrs_rejected():
    with pytest.raises(AssertionError):
        Table(np.zeros(2), np.array([1, 2], dtype=np.int32))
