"""Silent-data-corruption (SDC) defense: in-graph ABFT checksums,
true-residual audits, and bounded in-memory rollback (round 8).

THE acceptance scenario, pinned in two halves:

* **The failing-silently baseline.** A FINITE bitflip (high mantissa
  bit) in a halo payload sails through every finiteness guard: the
  recurrence "converges" (``converged=True``) to an answer that is
  WRONG far beyond the solve tolerance. This test must keep passing —
  it is the threat model, executable.
* **The defense.** With ``PA_TPU_ABFT=1`` (and/or an audit period) the
  same spec either SELF-HEALS — detection at the exchange checksum or
  the true-residual audit, in-memory rollback to the newest audited
  ring state, clean replay, final iterate BITWISE equal to the
  fault-free run, zero disk I/O — or raises a typed
  `SilentCorruptionError` (persistent corruption exhausts the rollback
  budget and escalates to `solve_with_recovery`'s checkpoint tier). A
  silently wrong iterate is never returned.

Clean-path contract on the compiled (device) bodies: ABFT ON vs OFF is
bitwise identical under strict-bits on the 4-part conformance fixture
(standard, fused, and rhs_batch=4 block bodies), and the lowered HLO
carries the SAME per-kind collective counts — the checksum/audit lanes
ride the existing all_gather/ppermute payloads (`_pdot_extra_factory`,
the widened exchange rounds, and the audit's operand select on the one
SpMV call site).
"""
import os
import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu.models import (
    assemble_poisson,
    cg,
    gather_pvector,
    jacobi_preconditioner,
    pcg,
    solve_with_recovery,
)
from partitionedarrays_jl_tpu.parallel.faults import inject_faults
from partitionedarrays_jl_tpu.parallel.health import SilentCorruptionError


@pytest.fixture
def sdc_env(monkeypatch):
    """ABFT + a short audit period, cleaned up per test."""

    def set_env(abft="1", audit="6", max_rb=None, depth=None):
        if abft is not None:
            monkeypatch.setenv("PA_TPU_ABFT", abft)
        if audit is not None:
            monkeypatch.setenv("PA_HEALTH_AUDIT_EVERY", audit)
        if max_rb is not None:
            monkeypatch.setenv("PA_HEALTH_MAX_ROLLBACKS", max_rb)
        if depth is not None:
            monkeypatch.setenv("PA_HEALTH_ROLLBACK_DEPTH", depth)

    return set_env


def _setup(parts, ns=(12, 12)):
    return assemble_poisson(parts, ns)


# ---------------------------------------------------------------------------
# the threat model: a finite bitflip fails SILENTLY without the defense
# ---------------------------------------------------------------------------


def test_bitflip_baseline_completes_silently_wrong():
    """ABFT off (the default): a high-mantissa-bit flip in a halo
    payload produces converged=True and an answer wrong by orders of
    magnitude more than the solve tolerance — the failure class the
    finiteness guards cannot see. Executable threat model: if this test
    ever fails because the answer came back right, the baseline moved
    and the defense tests below must be re-derived."""

    def driver(parts):
        A, b, x_exact, x0 = _setup(parts)
        x_clean, info_clean = cg(A, b, x0=x0, tol=1e-10)
        assert info_clean["converged"]
        with inject_faults("bitflip@part=1,call=20,bit=51", seed=3) as st:
            x_bad, info_bad = cg(A, b, x0=x0, tol=1e-10)
        assert [e["kind"] for e in st.events] == ["bitflip"]
        assert info_bad["converged"], "recurrence converged on paper"
        err = float(
            np.abs(gather_pvector(x_bad) - gather_pvector(x_clean)).max()
        )
        assert err > 1e-7, f"corruption no longer visible (err={err})"
        assert "sdc" not in info_bad  # defense inactive by default
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


# ---------------------------------------------------------------------------
# the defense, host oracle: detect -> rollback -> bitwise self-heal
# ---------------------------------------------------------------------------


def test_exchange_checksum_self_heals_bitwise(sdc_env):
    """One-shot bitflip with ABFT on: the slab checksum catches it AT
    the exchange, the ring rewinds <= audit_every iterations, the clean
    replay reproduces the fault-free run bit for bit. No disk involved
    anywhere (no checkpoint was ever configured)."""
    sdc_env()

    def driver(parts):
        A, b, x_exact, x0 = _setup(parts)
        x_clean, _ = cg(A, b, x0=x0, tol=1e-10)
        with inject_faults("bitflip@part=1,call=20,bit=51", seed=5) as st:
            x_rec, info = cg(A, b, x0=x0, tol=1e-10)
        assert any(e["kind"] == "bitflip" for e in st.events)
        assert info["converged"]
        assert info["sdc"]["detections"] == 1
        assert info["sdc"]["rollbacks"] == 1
        assert info["sdc"]["escalations"] == 0
        assert info["sdc"]["audit_iterations"] > 0
        np.testing.assert_array_equal(
            gather_pvector(x_clean), gather_pvector(x_rec)
        )
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_audit_only_mode_catches_drift(sdc_env):
    """ABFT checksums OFF, audit ON: the corruption lands (no exchange
    guard), the recurrence silently diverges from the true residual,
    and the next ``b - A x`` audit catches the drift — same rollback,
    same bitwise self-heal."""
    sdc_env(abft="0", audit="6")

    def driver(parts):
        A, b, x_exact, x0 = _setup(parts)
        x_clean, _ = cg(A, b, x0=x0, tol=1e-10)
        with inject_faults("bitflip@part=1,call=20,bit=51", seed=5):
            x_rec, info = cg(A, b, x0=x0, tol=1e-10)
        assert info["converged"]
        assert info["sdc"]["detections"] == 1
        assert info["sdc"]["rollbacks"] == 1
        np.testing.assert_array_equal(
            gather_pvector(x_clean), gather_pvector(x_rec)
        )
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_nan_slab_trips_checksum_before_finiteness(sdc_env):
    """With ABFT on even a NaN payload is caught by the slab checksum
    (NaN fails the comparison) and HEALS IN MEMORY — strictly better
    than the default path, where the NaN reaches the solver state and
    recovery means a restart."""
    sdc_env()

    def driver(parts):
        A, b, x_exact, x0 = _setup(parts)
        x_clean, _ = cg(A, b, x0=x0, tol=1e-10)
        with inject_faults("nan@part=1,call=20", seed=5):
            x_rec, info = cg(A, b, x0=x0, tol=1e-10)
        assert info["converged"] and info["sdc"]["rollbacks"] == 1
        np.testing.assert_array_equal(
            gather_pvector(x_clean), gather_pvector(x_rec)
        )
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_pcg_self_heals_bitwise(sdc_env):
    sdc_env()

    def driver(parts):
        A, b, x_exact, x0 = _setup(parts)
        minv = jacobi_preconditioner(A)
        x_clean, _ = pcg(A, b, x0=x0, minv=minv, tol=1e-10)
        with inject_faults("bitflip@part=2,call=15,bit=50", seed=2):
            x_rec, info = pcg(A, b, x0=x0, minv=minv, tol=1e-10)
        assert info["converged"] and info["sdc"]["rollbacks"] == 1
        np.testing.assert_array_equal(
            gather_pvector(x_clean), gather_pvector(x_rec)
        )
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_persistent_corruption_escalates_typed(sdc_env):
    """A repeating fault defeats rollback (every replay re-corrupts):
    after PA_HEALTH_MAX_ROLLBACKS rollbacks the next detection raises
    SilentCorruptionError carrying the counters — never a silently
    wrong iterate."""
    sdc_env(max_rb="2")

    def driver(parts):
        A, b, x_exact, x0 = _setup(parts)
        with inject_faults("bitflip@part=1,after=0,bit=51", seed=5):
            with pytest.raises(SilentCorruptionError) as ei:
                cg(A, b, x0=x0, tol=1e-10)
        sdc = ei.value.diagnostics["sdc"]
        assert sdc["rollbacks"] == 2 and sdc["escalations"] == 1
        assert sdc["detections"] == 3
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_rollback_uses_no_disk_and_recovery_ledger(tmp_path, sdc_env):
    """Criterion: in-memory rollback recovers from a single bitflip
    with ZERO checkpoint I/O — the configured checkpoint directory
    stays empty — and the solve_with_recovery ledger reports the
    in-memory tier (rollbacks consumed, no restarts, no checkpoint
    generations used)."""
    sdc_env()
    d = str(tmp_path / "ck")

    def driver(parts):
        A, b, x_exact, x0 = _setup(parts)
        x_clean, _ = cg(A, b, x0=x0, tol=1e-10)
        with inject_faults("bitflip@part=1,call=20,bit=51", seed=5):
            # every=10_000: no periodic checkpoint is ever due, so any
            # file in `d` would have come from the recovery path
            x_rec, info = solve_with_recovery(
                A, b, method="cg", x0=x0, checkpoint_dir=d, every=10_000,
                tol=1e-10,
            )
        assert info["converged"] and info["restarts"] == 0
        led = info["recovery"]
        assert led["attempts"] == 1
        assert led["rollbacks"] == 1 and led["detections"] == 1
        assert led["checkpoint_restarts"] == 0
        assert not os.path.exists(os.path.join(d, "manifest.json"))
        np.testing.assert_array_equal(
            gather_pvector(x_clean), gather_pvector(x_rec)
        )
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_escalation_reaches_checkpoint_tier(tmp_path, sdc_env):
    """The full ladder: persistent corruption exhausts the in-memory
    budget, SilentCorruptionError escalates to solve_with_recovery,
    whose restarts also fail (the fault repeats) until max_restarts —
    the final raise is typed and the ledger records every tier."""
    sdc_env(max_rb="1")
    d = str(tmp_path / "ck")

    def driver(parts):
        A, b, x_exact, x0 = _setup(parts)
        with inject_faults("bitflip@part=1,after=0,bit=51", seed=5):
            with pytest.raises(SilentCorruptionError):
                solve_with_recovery(
                    A, b, method="cg", x0=x0, checkpoint_dir=d, every=5,
                    tol=1e-10, max_restarts=1,
                )
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_recovery_ledger_records_checkpoint_restart(tmp_path):
    """Without the SDC layer, the classic path (NaN -> NonFiniteError ->
    checkpoint restart) now reports itself in the ledger: one restart
    from the exact-recurrence checkpoint, its iteration recorded."""
    d = str(tmp_path / "ck")

    def driver(parts):
        A, b, x_exact, x0 = _setup(parts)
        with inject_faults("nan@part=1,call=20", seed=5):
            x, info = solve_with_recovery(
                A, b, method="cg", x0=x0, checkpoint_dir=d, every=6,
                tol=1e-9,
            )
        assert info["converged"] and info["restarts"] == 1
        led = info["recovery"]
        assert led["attempts"] == 2
        assert led["checkpoint_restarts"] == 1
        src = led["restart_sources"][0]
        assert src["failure"] == "NonFiniteError"
        assert src["from"] == "checkpoint_state"
        assert src["checkpoint_iteration"] > 0
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_slab_checksums_handle_empty_and_block_slabs(sdc_env):
    """Regression (review findings): the sender-side checksum must
    survive (a) a part whose TRAILING slab is empty (np.add.reduceat's
    empty-row misindexing — replaced by a cumsum) and (b) an (L, K)
    block slab, whose K columns fold into one scalar checksum per slab
    on both sides."""
    from partitionedarrays_jl_tpu.parallel.collectives import (
        _slab_checksums,
        _verify_slab_checksums,
    )
    from partitionedarrays_jl_tpu.parallel.sequential import SequentialData
    from partitionedarrays_jl_tpu.utils.table import Table

    # part 0 sends [3-word slab to 1, EMPTY trailing slab to 2]; part 1
    # sends an (L, K)=(4, 2) block slab to 0; part 2 sends nothing
    t0 = Table(np.array([1.0, 2.0, 3.0]), np.array([0, 3, 3]))
    t1 = Table(np.arange(8.0).reshape(4, 2), np.array([0, 4]))
    t2 = Table(np.empty((0,)), np.array([0]))
    snd = SequentialData([t0, t1, t2])
    sums = _slab_checksums(snd)
    np.testing.assert_allclose(sums[0][0], [6.0, 0.0])
    np.testing.assert_allclose(sums[1][0], [28.0])
    parts_snd = SequentialData(
        [np.array([1, 2]), np.array([0]), np.empty(0, dtype=int)]
    )
    parts_rcv = SequentialData(
        [np.array([1]), np.array([0]), np.array([0])]
    )

    def rcv_for(block):
        return SequentialData(
            [
                Table(block, np.array([0, 4])),
                Table(np.array([1.0, 2.0, 3.0]), np.array([0, 3])),
                Table(np.empty((0,)), np.array([0, 0])),
            ]
        )

    _verify_slab_checksums(
        rcv_for(np.arange(8.0).reshape(4, 2)), parts_rcv, parts_snd,
        sums, 1e-12,
    )
    # a flipped word in the block slab trips the verify
    from partitionedarrays_jl_tpu.parallel.health import (
        SilentCorruptionError as SCE,
    )

    with pytest.raises(SCE):
        _verify_slab_checksums(
            rcv_for(np.arange(8.0).reshape(4, 2) + np.eye(4, 2) * 0.5),
            parts_rcv, parts_snd, sums, 1e-12,
        )


# ---------------------------------------------------------------------------
# device backend: in-graph detection/rollback on the compiled bodies
# ---------------------------------------------------------------------------


def _tpu_backend(n=8):
    import jax

    try:
        from partitionedarrays_jl_tpu.parallel.tpu import TPUBackend

        return TPUBackend(devices=jax.devices()[:n])
    except Exception as e:  # pragma: no cover - environment-dependent
        pytest.skip(f"device mesh unavailable: {e}")


def test_device_in_graph_rollback_self_heals(sdc_env, monkeypatch):
    """PA_FAULT_DEVICE (the compiled loop's chaos seam) corrupts the
    SpMV product at one trip; the in-graph checksum lanes detect it,
    the device-resident ring re-selects the last audited state, and the
    replayed trajectory lands bitwise on the fault-free answer — for
    the standard AND fused bodies."""
    backend = _tpu_backend()

    def run(fault):
        def driver(parts):
            A, b, xe, x0 = assemble_poisson(parts, (8, 8, 8))
            out = {}
            for fused in (False, True):
                x, info = cg(A, b, x0=x0, tol=1e-9, fused=fused)
                out[fused] = (gather_pvector(x), info)
            return out

        if fault:
            monkeypatch.setenv(
                "PA_FAULT_DEVICE", "spmv@trip=8,part=1,factor=1e3"
            )
        else:
            monkeypatch.delenv("PA_FAULT_DEVICE", raising=False)
        return pa.prun(driver, backend, (2, 2, 2))

    sdc_env(audit="5")
    clean = run(False)
    faulted = run(True)
    for fused in (False, True):
        xc, ic = clean[fused]
        xf, inf = faulted[fused]
        assert ic["sdc"]["detections"] == 0
        assert inf["sdc"]["detections"] == 1
        assert inf["sdc"]["rollbacks"] == 1
        assert inf["converged"]
        np.testing.assert_array_equal(xc, xf)


def test_device_escalation_raises_typed(sdc_env, monkeypatch):
    backend = _tpu_backend()
    sdc_env(audit="5", max_rb="0")
    monkeypatch.setenv("PA_FAULT_DEVICE", "spmv@trip=8,part=1,factor=1e3")

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8, 8))
        with pytest.raises(SilentCorruptionError) as ei:
            cg(A, b, x0=x0, tol=1e-9)
        assert ei.value.diagnostics["sdc"]["escalations"] == 1
        return True

    assert pa.prun(driver, backend, (2, 2, 2))


def test_device_block_in_graph_rollback(sdc_env, monkeypatch):
    """The (…, K) block program: per-column checksum lanes, whole-block
    ring restore — faulted block solve self-heals bitwise per column."""
    backend = _tpu_backend()
    sdc_env(audit="5")

    def run():
        def driver(parts):
            A, b, xe, x0 = assemble_poisson(parts, (8, 8, 8))
            B, X0 = [b, b.copy()], [x0, x0.copy()]
            xs, info = cg(A, B=B, X0=X0, tol=1e-9)
            return [gather_pvector(x) for x in xs], info

        return pa.prun(driver, backend, (2, 2, 2))

    clean, info_c = run()
    monkeypatch.setenv("PA_FAULT_DEVICE", "spmv@trip=7,part=1,factor=1e3")
    healed, info_f = run()
    assert info_c["sdc"]["detections"] == 0
    assert info_f["sdc"]["detections"] == 1 and info_f["sdc"]["rollbacks"] == 1
    assert info_f["converged"]
    for a, c in zip(clean, healed):
        np.testing.assert_array_equal(a, c)


# ---------------------------------------------------------------------------
# clean-path contracts: strict-bits identity + collective parity
# ---------------------------------------------------------------------------

# the 10-gid 4-part conformance fixture (reference test_interfaces.jl:
# 177-207, owned-first local layouts) — the asymmetric partition whose
# ghost graph exercises the generic exchange plan
LID_TO_GID = [
    [0, 1, 2, 4, 6, 7],
    [3, 4, 1, 9],
    [5, 6, 7, 4, 3, 9],
    [8, 9, 0, 2, 6],
]
LID_TO_PART = [
    [0, 0, 0, 1, 2, 2],
    [1, 1, 0, 3],
    [2, 2, 2, 1, 1, 3],
    [3, 3, 0, 0, 2],
]


def _fixture_spd_system(parts):
    owner = {}
    for p, (gids, ps) in enumerate(zip(LID_TO_GID, LID_TO_PART)):
        for g, q in zip(gids, ps):
            if q == p:
                owner[g] = p
    visible = [set(g) for g in LID_TO_GID]
    pairs = {
        (a, b)
        for a in range(10)
        for b in range(10)
        if a != b and b in visible[owner[a]] and a in visible[owner[b]]
    }

    def triplets(p):
        I, J, V = [], [], []
        for g, q in zip(LID_TO_GID[p], LID_TO_PART[p]):
            if q != p:
                continue
            I.append(g)
            J.append(g)
            V.append(40.0 + g)
            for b in sorted(visible[p]):
                if (g, b) in pairs:
                    I.append(g)
                    J.append(b)
                    V.append(-(1.0 + (g + b) % 3))
        return np.array(I), np.array(J), np.array(V, dtype=np.float64)

    partition = pa.map_parts(
        lambda p: pa.IndexSet(p, LID_TO_GID[p], LID_TO_PART[p]), parts
    )
    rows = pa.PRange(10, partition)
    I = pa.map_parts(lambda p: triplets(p)[0], parts)
    J = pa.map_parts(lambda p: triplets(p)[1], parts)
    V = pa.map_parts(lambda p: triplets(p)[2], parts)
    A = pa.PSparseMatrix.from_coo(I, J, V, rows, rows.copy(), ids="global")
    b = pa.PVector(
        pa.map_parts(
            lambda i: np.where(
                np.asarray(i.lid_to_part) == i.part,
                np.sin(1.0 + np.asarray(i.lid_to_gid, dtype=np.float64)),
                0.0,
            ),
            A.rows.partition,
        ),
        A.rows,
    )
    return A, b


def test_strict_bits_abft_on_off_identity(monkeypatch):
    """No fault active: under strict-bits the SDC machinery must not
    move a single bit of the trajectory — residual history and solution
    bitwise identical with ABFT ON vs OFF, on the standard body, the
    fused body, and the rhs_batch=4 block body (audits DO run — they
    are stall trips whose state re-selects bit-exactly)."""
    monkeypatch.setenv("PA_TPU_STRICT_BITS", "1")
    backend = _tpu_backend(4)

    def run(abft, mode):
        if abft:
            monkeypatch.setenv("PA_TPU_ABFT", "1")
            monkeypatch.setenv("PA_HEALTH_AUDIT_EVERY", "4")
        else:
            monkeypatch.delenv("PA_TPU_ABFT", raising=False)
            monkeypatch.delenv("PA_HEALTH_AUDIT_EVERY", raising=False)

        def driver(parts):
            A, b = _fixture_spd_system(parts)
            if mode == "block":
                xs, info = cg(A, B=[b, b.copy(), b.copy(), b.copy()],
                              tol=1e-12, maxiter=200)
                return [gather_pvector(x) for x in xs], info
            x, info = cg(
                A, b, tol=1e-12, maxiter=200,
                fused=(mode == "fused"),
            )
            return gather_pvector(x), info

        return pa.prun(driver, backend, 4)

    for mode in ("standard", "fused", "block"):
        x_on, inf_on = run(True, mode)
        x_off, inf_off = run(False, mode)
        assert inf_on["iterations"] == inf_off["iterations"]
        assert inf_on["iterations"] > 3
        assert inf_on["sdc"]["detections"] == 0
        assert inf_on["sdc"]["audit_iterations"] > 0
        assert "sdc" not in inf_off
        n = inf_off["iterations"] + 1
        np.testing.assert_array_equal(
            np.asarray(inf_on["residuals"])[:n],
            np.asarray(inf_off["residuals"])[:n],
        )
        if mode == "block":
            for a, c in zip(x_on, x_off):
                np.testing.assert_array_equal(a, c)
        else:
            np.testing.assert_array_equal(x_on, x_off)


# the shared analyzer (one definition for the whole test tree — this
# file used to carry a private regex copy; analysis.collective_counts
# keeps the identical raw-substring semantics, pinned by
# tests/test_static_analysis.py against a committed fixture)
from partitionedarrays_jl_tpu.analysis import collective_counts  # noqa: E402


def test_abft_collective_count_parity(monkeypatch):
    """HLO A/B: the ABFT-on program must carry the SAME per-kind
    collective counts as the ABFT-off program — detection rides widened
    payloads (checksum lanes on the dot gather, one extra slot per
    exchange round) and the audit reuses the loop's one SpMV via an
    operand select, never a second exchange. Pinned with PA_TPU_BOX=0
    on both sides so the A/B compares like plans (ABFT itself pins the
    generic plan; see _box_exchange_enabled)."""
    from partitionedarrays_jl_tpu.parallel.tpu import (
        _matrix_operands,
        device_matrix,
        make_block_cg_fn,
        make_cg_fn,
    )

    monkeypatch.setenv("PA_TPU_BOX", "0")
    monkeypatch.setenv("PA_HEALTH_AUDIT_EVERY", "8")
    backend = _tpu_backend()

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (6, 6, 6))
        return A, b

    A, b = pa.prun(driver, backend, (2, 2, 2))

    def counts(abft, fused, rhs_batch=None):
        if abft:
            monkeypatch.setenv("PA_TPU_ABFT", "1")
        else:
            monkeypatch.delenv("PA_TPU_ABFT", raising=False)
        dA = device_matrix(A, backend)
        ops = _matrix_operands(dA)
        if rhs_batch:
            fn = make_block_cg_fn(dA, 1e-9, 100, rhs_batch, fused=fused)
            db = np.zeros(
                (dA.col_plan.layout.P, dA.col_plan.layout.W, rhs_batch)
            )
            args = (db, db, db[..., 0], ops)
        else:
            fn = make_cg_fn(dA, 1e-9, 100, fused=fused)
            db = np.zeros((dA.col_plan.layout.P, dA.col_plan.layout.W))
            args = (db, db, db, ops)
        return collective_counts(fn, *args)

    for fused in (False, True):
        con = counts(True, fused)
        coff = counts(False, fused)
        assert any(coff.values())
        assert con == coff, (fused, con, coff)
    con = counts(True, True, rhs_batch=4)
    coff = counts(False, True, rhs_batch=4)
    assert con == coff, ("block", con, coff)


def test_abft_pins_generic_exchange_plan(monkeypatch):
    """ABFT mode keeps the generic index plan (its round checksums are
    implemented there — same precedent as strict-bits), even on a
    box-eligible Cartesian partition."""
    from partitionedarrays_jl_tpu.parallel.tpu import device_matrix
    from partitionedarrays_jl_tpu.parallel.tpu_box import BoxExchangePlan

    backend = _tpu_backend()

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (6, 6, 6))
        return A

    A = pa.prun(driver, backend, (2, 2, 2))
    monkeypatch.setenv("PA_TPU_ABFT", "1")
    dA_on = device_matrix(A, backend)
    assert not isinstance(dA_on.col_plan, BoxExchangePlan)
    assert dA_on.abft_w is not None
    monkeypatch.delenv("PA_TPU_ABFT")
    dA_off = device_matrix(A, backend)
    assert dA_off.abft_w is None
