"""Worker for the two-process multi-host smoke test (spawned by
tests/test_multihost.py — the `mpiexec` analog of the reference's MPI
suite, reference: test/mpi/mpiexec.jl:3-15, run over `jax.distributed`
on CPU instead of an MPI launcher).

argv: <coordinator_port> <process_id> <num_processes>
Each process contributes 4 virtual CPU devices; the global mesh spans 8.
"""
import os
import sys

port, pid, nprocs = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4"
).strip()
os.environ["JAX_ENABLE_X64"] = "true"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import partitionedarrays_jl_tpu as pa  # noqa: E402

# join the cluster BEFORE any backend use (jax.devices() would pin the
# local-only runtime)
pa.multihost_init(
    coordinator_address=f"localhost:{port}",
    num_processes=nprocs,
    process_id=pid,
)
assert jax.process_count() == nprocs, jax.process_count()
devs = jax.devices()
assert len(devs) == 4 * nprocs, devs
local = [d for d in devs if d.process_index == jax.process_index()]
assert len(local) == 4, local
assert pa.is_main_process() == (pid == 0)

from partitionedarrays_jl_tpu.models import poisson_fdm_driver  # noqa: E402
from partitionedarrays_jl_tpu.parallel.tpu import TPUBackend  # noqa: E402

backend = TPUBackend(devices=devs)
err, info = pa.prun(
    poisson_fdm_driver, backend, (2, 2, 2), (8, 8, 8), tol=1e-8, maxiter=200
)
assert info["iterations"] > 0, info
assert err < 1e-5, err

# cross-process replication check: every controller must see the same
# compiled-solve result (replicated planning + deterministic collectives)
from partitionedarrays_jl_tpu.parallel.multihost import fetch_global  # noqa: E402
import numpy as np  # noqa: E402

one_per_proc = [
    next(d for d in devs if d.process_index == p) for p in range(nprocs)
]
mine = np.full((nprocs,), err)  # this controller's value in every slot
ga = jax.make_array_from_callback(
    (nprocs,),
    jax.sharding.NamedSharding(
        jax.sharding.Mesh(np.array(one_per_proc), ("h",)),
        jax.sharding.PartitionSpec("h"),
    ),
    lambda idx: mine[idx],
)
vals = fetch_global(ga)  # slot p = process p's locally computed err
assert np.allclose(vals, err), vals

print(f"MULTIHOST_OK pid={pid} err={err:.3e} iters={info['iterations']}")
