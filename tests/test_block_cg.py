"""Block multi-RHS CG (`make_cg_fn(rhs_batch=K)` / `cg(B=...)` /
`pcg(B=...)`): the operator streams once per K right-hand sides.

The block program's three contracts, each pinned here:

* **Per-column trajectory identity.** Every column follows the textbook
  single-vector recurrence with per-column α/β — column k's iterate
  sequence IS the K=1 program's sequence for (b_k, x0_k), bit-for-bit
  under strict-bits arithmetic (pinned on the asymmetric 4-part
  conformance partition, like the fused-body tests). Converged columns
  freeze (α=0 / state re-select) rather than exiting, so ragged blocks
  keep every column's solo trajectory.
* **Collective parity, K-independent.** The dot payloads widen from
  scalars to (K,) / (K, 2) stacks riding the SAME all_gathers
  (`_pdot_owned_factory`), and the halo ppermutes ship (…, K) slabs —
  the per-iteration collective count in the lowered HLO must not depend
  on K, for both the standard and the fused body.
* **Lowering-independent SpMM.** Every SpMV lowering (coded-DIA,
  XLA-DIA, SD, BSR, ELL) accepts the (P, W, K) block operand and agrees
  with K separate SpMVs (bitwise under strict-bits, where the ELL path
  is the oracle).
"""
import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu.models import (
    assemble_poisson,
    gather_pvector,
    jacobi_preconditioner,
)
from partitionedarrays_jl_tpu.models.solvers import cg, pcg
from partitionedarrays_jl_tpu.parallel.pvector import _write_owned
from partitionedarrays_jl_tpu.parallel.tpu import (
    DeviceVector,
    TPUBackend,
    _block_on_cols_layout,
    _matrix_operands,
    device_matrix,
    make_cg_fn,
    make_spmv_fn,
    tpu_block_cg,
    tpu_cg,
)

from test_fused_cg import _fixture_spd_system


def _backend(n=8):
    import jax

    return TPUBackend(devices=jax.devices()[:n])


def _rand_rhs(A, seed):
    v = pa.PVector.full(0.0, A.cols)

    def fill(i, vals):
        rng = np.random.default_rng(seed + int(i.part))
        _write_owned(i, vals, rng.standard_normal(i.num_oids))

    pa.map_parts(fill, v.rows.partition, v.values)
    return v


def _ragged_block(A, b):
    """Three RHS of very different difficulty: the assembled b, a random
    vector, and a tiny constant forcing — their solo iteration counts
    differ, which is the point (ragged convergence)."""
    w = pa.PVector.full(0.0, A.cols)

    def fill(i, vals):
        _write_owned(i, vals, np.full(i.num_oids, 1e-3))

    pa.map_parts(fill, w.rows.partition, w.values)
    return [b, _rand_rhs(A, 11), w]


# ---------------------------------------------------------------------------
# block SpMM parity across lowerings
# ---------------------------------------------------------------------------


def test_block_spmv_matches_columns_coded_dia():
    backend = _backend()

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8, 8))
        return A

    A = pa.prun(driver, backend, (2, 2, 2))
    dA = device_matrix(A, backend)
    assert dA.dia_mode == "coded"  # the stencil fast path engaged
    spmv = make_spmv_fn(dA)
    Bs = [_rand_rhs(A, 7 * k) for k in range(4)]
    yblk = np.asarray(spmv(_block_on_cols_layout(Bs, dA)))
    assert yblk.shape[-1] == 4
    for k, bk in enumerate(Bs):
        dx = DeviceVector.from_pvector(bk, backend, dA.col_layout)
        np.testing.assert_allclose(
            yblk[..., k], np.asarray(spmv(dx.data)), rtol=0, atol=1e-12
        )


def test_block_spmv_strict_bits_ell_bitwise(monkeypatch):
    """Strict-bits forces the pure-ELL lowering and the generic exchange
    plan; the block product must equal the column products BITWISE."""
    monkeypatch.setenv("PA_TPU_STRICT_BITS", "1")
    backend = _backend()

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8, 8))
        return A

    A = pa.prun(driver, backend, (2, 2, 2))
    dA = device_matrix(A, backend)
    assert dA.oo_vals is not None  # ELL path
    spmv = make_spmv_fn(dA)
    Bs = [_rand_rhs(A, 3 * k) for k in range(3)]
    yblk = np.asarray(spmv(_block_on_cols_layout(Bs, dA)))
    for k, bk in enumerate(Bs):
        dx = DeviceVector.from_pvector(bk, backend, dA.col_layout)
        np.testing.assert_array_equal(yblk[..., k], np.asarray(spmv(dx.data)))


def test_block_spmv_matches_columns_sd_and_bsr():
    """The irregular-graph lowerings (SD einsum buckets, node-block BSR,
    and the bucketed node-block A_oh boundary path) take the block
    operand: one (G·bs, U·bs) @ (U·bs, K) einsum per bucket."""
    import os

    from partitionedarrays_jl_tpu.models.elasticity_tet import (
        assemble_elasticity_tet,
    )
    from partitionedarrays_jl_tpu.parallel.tpu import DeviceMatrix

    def driver(parts):
        A, b, xh, x0 = assemble_elasticity_tet(parts, (4, 4, 4))
        backend = parts.backend
        dA = device_matrix(A, backend)
        assert dA.sd_bs == 3 and dA.ohb_bs == 3, (dA.sd_bs, dA.ohb_bs)
        Bs = [_rand_rhs(A, 13 * k) for k in range(3)]
        xblk = _block_on_cols_layout(Bs, dA)
        y_sd = np.asarray(make_spmv_fn(dA)(xblk))
        os.environ["PA_TPU_SD"] = "0"
        try:
            dA_bsr = DeviceMatrix(A, backend)
            assert dA_bsr.bsr_bs == 3
            y_bsr = np.asarray(
                make_spmv_fn(dA_bsr)(_block_on_cols_layout(Bs, dA_bsr))
            )
        finally:
            del os.environ["PA_TPU_SD"]
        np.testing.assert_allclose(y_sd, y_bsr, rtol=1e-10, atol=1e-10)
        for k, bk in enumerate(Bs):
            dx = DeviceVector.from_pvector(bk, backend, dA.col_layout)
            yk = np.asarray(make_spmv_fn(dA)(dx.data))
            np.testing.assert_allclose(
                y_sd[..., k], yk, rtol=1e-12, atol=1e-12
            )
        return True

    assert pa.prun(driver, pa.tpu, 4)


# ---------------------------------------------------------------------------
# ragged convergence: every column matches its solo trajectory
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("fused", [False, True])
def test_block_cg_ragged_columns_match_solo(fused):
    backend = _backend()

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8, 8))
        return A, _ragged_block(A, b)

    A, B = pa.prun(driver, backend, (2, 2, 2))
    xs, info = cg(A, B=B, tol=1e-8, maxiter=400, fused=fused)
    assert info["cg_body"] == ("fused" if fused else "standard")
    assert info["rhs_batch"] == 3
    its = info["iterations_per_column"]
    assert len(set(its)) > 1, f"block is not ragged: {its}"
    assert info["iterations"] == max(its)
    for k, bk in enumerate(B):
        xk, ik = tpu_cg(A, bk, tol=1e-8, maxiter=400, fused=fused)
        assert ik["iterations"] == its[k], (k, ik["iterations"], its)
        np.testing.assert_allclose(
            gather_pvector(xs[k]), gather_pvector(xk), rtol=0, atol=1e-10
        )
        n = ik["iterations"] + 1
        np.testing.assert_allclose(
            np.asarray(info["columns"][k]["residuals"])[:n],
            np.asarray(ik["residuals"])[:n],
            rtol=1e-12,
        )
        # frozen tail: nothing is logged past a column's freeze point
        hist_k = np.asarray(info["columns"][k]["residuals"])
        assert len(hist_k) == n


def test_block_pcg_matches_solo_and_host():
    backend = _backend()

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8, 8))
        return A, _ragged_block(A, b)

    A, B = pa.prun(driver, backend, (2, 2, 2))
    mv = jacobi_preconditioner(A)
    xs, info = pcg(A, B=B, minv=mv, tol=1e-8, maxiter=400)
    for k, bk in enumerate(B):
        xk, ik = pcg(A, bk, minv=mv, tol=1e-8, maxiter=400)
        assert ik["iterations"] == info["iterations_per_column"][k]
        np.testing.assert_allclose(
            gather_pvector(xs[k]), gather_pvector(xk), rtol=0, atol=1e-9
        )


def test_host_backend_block_runs_solo_loops():
    """On the host backend `cg(B=...)` solves the columns with the solo
    loop — the oracle semantics — and reports the same info shape."""

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (6, 6, 6))
        B = [b, _rand_rhs(A, 5)]
        xs, info = cg(A, B=B, tol=1e-9, maxiter=300)
        assert info["cg_body"] == "host" and info["rhs_batch"] == 2
        for k, bk in enumerate(B):
            xk, ik = cg(A, bk, tol=1e-9, maxiter=300)
            assert ik["iterations"] == info["iterations_per_column"][k]
            np.testing.assert_array_equal(
                gather_pvector(xs[k]), gather_pvector(xk)
            )
        return True

    assert pa.prun(driver, pa.sequential, (2, 2, 2))


# ---------------------------------------------------------------------------
# K=1 degenerate batch == the unbatched program
# ---------------------------------------------------------------------------


def test_k1_degenerate_batch_equals_unbatched(monkeypatch):
    """Under strict-bits the K=1 block program must reproduce the
    unbatched program bit-for-bit: same iterations, identical residual
    bits, identical solution bits."""
    monkeypatch.setenv("PA_TPU_STRICT_BITS", "1")
    backend = _backend(4)

    def driver(parts):
        A, b = _fixture_spd_system(parts)
        return A, b

    A, b = pa.prun(driver, backend, 4)
    xs, binfo = tpu_block_cg(A, [b], tol=1e-12, maxiter=200)
    xk, sinfo = tpu_cg(A, b, tol=1e-12, maxiter=200)
    assert binfo["columns"][0]["iterations"] == sinfo["iterations"]
    assert sinfo["iterations"] > 3
    np.testing.assert_array_equal(
        gather_pvector(xs[0]), gather_pvector(xk)
    )
    n = sinfo["iterations"] + 1
    np.testing.assert_array_equal(
        np.asarray(binfo["columns"][0]["residuals"])[:n],
        np.asarray(sinfo["residuals"])[:n],
    )


@pytest.mark.parametrize("fused", [False, True])
def test_strict_bits_block_per_column_identity(fused, monkeypatch):
    """The tentpole pin: per-column BITWISE identity against the K=1
    oracle under strict-bits on the asymmetric 4-part conformance
    fixture, for a RAGGED block (different per-column freeze points),
    with both the standard and the fused body."""
    monkeypatch.setenv("PA_TPU_STRICT_BITS", "1")
    backend = _backend(4)

    def driver(parts):
        A, b = _fixture_spd_system(parts)
        # second column: a different, rougher RHS (solo counts differ)
        b2 = pa.PVector(
            pa.map_parts(
                lambda i: np.where(
                    np.asarray(i.lid_to_part) == i.part,
                    np.cos(2.0 + 3.0 * np.asarray(i.lid_to_gid, dtype=np.float64)),
                    0.0,
                ),
                A.rows.partition,
            ),
            A.rows,
        )
        return A, [b, b2]

    A, B = pa.prun(driver, backend, 4)
    xs, binfo = tpu_block_cg(A, B, tol=1e-10, maxiter=200, fused=fused)
    assert binfo["cg_body"] == ("fused" if fused else "standard")
    for k, bk in enumerate(B):
        xk, sinfo = tpu_cg(A, bk, tol=1e-10, maxiter=200, fused=fused)
        assert (
            binfo["columns"][k]["iterations"] == sinfo["iterations"]
        ), (k, binfo["iterations_per_column"], sinfo["iterations"])
        np.testing.assert_array_equal(
            gather_pvector(xs[k]), gather_pvector(xk)
        )
        n = sinfo["iterations"] + 1
        np.testing.assert_array_equal(
            np.asarray(binfo["columns"][k]["residuals"])[:n],
            np.asarray(sinfo["residuals"])[:n],
        )


@pytest.mark.parametrize("K", [3, 5])
def test_strict_bits_ragged_odd_widths_k3_k5(K, monkeypatch):
    """Ragged parity at ODD/PRIME slab widths — the shapes the solve
    service's re-batching actually produces (a K=8 slab that lost
    ejected/converged columns re-runs at K=3, 5, ...). Same contract as
    the K=2 pin above: per-column BITWISE identity against the K=1
    oracle under strict-bits on the 4-part conformance fixture, with
    per-column freeze points (no residuals logged past a column's
    freeze)."""
    monkeypatch.setenv("PA_TPU_STRICT_BITS", "1")
    backend = _backend(4)

    def driver(parts):
        A, b = _fixture_spd_system(parts)
        B = [b]
        for j in range(1, K):
            # distinct roughness per column: solo counts differ (the
            # ragged point), deterministically
            B.append(
                pa.PVector(
                    pa.map_parts(
                        lambda i, j=j: np.where(
                            np.asarray(i.lid_to_part) == i.part,
                            np.cos(
                                2.0 + (j + 2.0)
                                * np.asarray(i.lid_to_gid, dtype=np.float64)
                            ),
                            0.0,
                        ),
                        A.rows.partition,
                    ),
                    A.rows,
                )
            )
        return A, B

    A, B = pa.prun(driver, backend, 4)
    xs, binfo = tpu_block_cg(A, B, tol=1e-10, maxiter=200)
    assert binfo["rhs_batch"] == K
    its = binfo["iterations_per_column"]
    assert len(set(its)) > 1, f"block is not ragged: {its}"
    for k, bk in enumerate(B):
        xk, sinfo = tpu_cg(A, bk, tol=1e-10, maxiter=200)
        assert its[k] == sinfo["iterations"], (k, its, sinfo["iterations"])
        np.testing.assert_array_equal(
            gather_pvector(xs[k]), gather_pvector(xk)
        )
        n = sinfo["iterations"] + 1
        np.testing.assert_array_equal(
            np.asarray(binfo["columns"][k]["residuals"])[:n],
            np.asarray(sinfo["residuals"])[:n],
        )
        # freeze-on-convergence: nothing logged past the freeze point
        assert len(np.asarray(binfo["columns"][k]["residuals"])) == n


# ---------------------------------------------------------------------------
# fused × batched interaction under the env default
# ---------------------------------------------------------------------------


def test_fused_env_default_applies_to_block(monkeypatch):
    """PA_TPU_FUSED_CG governs the block body exactly like the solo
    body: default ON, =0 reverts to standard — and both bodies agree on
    trajectories."""
    backend = _backend()

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8, 8))
        return A, _ragged_block(A, b)

    A, B = pa.prun(driver, backend, (2, 2, 2))
    xs_f, inf_f = cg(A, B=B, tol=1e-8, maxiter=400)
    assert inf_f["cg_body"] == "fused"
    monkeypatch.setenv("PA_TPU_FUSED_CG", "0")
    xs_u, inf_u = cg(A, B=B, tol=1e-8, maxiter=400)
    assert inf_u["cg_body"] == "standard"
    assert (
        inf_f["iterations_per_column"] == inf_u["iterations_per_column"]
    )
    for xf, xu in zip(xs_f, xs_u):
        np.testing.assert_allclose(
            gather_pvector(xf), gather_pvector(xu), rtol=0, atol=1e-10
        )


# ---------------------------------------------------------------------------
# HLO A/B: collective count per iteration is K-independent
# ---------------------------------------------------------------------------


# the shared analyzer (one definition for the whole test tree — this
# file used to carry a private regex copy; analysis.collective_counts
# keeps the identical raw-substring semantics, pinned by
# tests/test_static_analysis.py against a committed fixture)
from partitionedarrays_jl_tpu.analysis import collective_counts  # noqa: E402


@pytest.mark.parametrize("fused", [False, True])
@pytest.mark.parametrize("precond", [False, True])
def test_block_collective_count_k_independent(fused, precond):
    backend = _backend()

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (6, 6, 6))
        return A, b

    A, b = pa.prun(driver, backend, (2, 2, 2))
    dA = device_matrix(A, backend)
    ops = _matrix_operands(dA)
    mv = None
    if precond:
        dmv = DeviceVector.from_pvector(
            jacobi_preconditioner(A), backend, dA.col_layout
        )
        mv = dmv.data
    counts = {}
    for K in (1, 4, 8):
        Bs = [b] * K
        db = _block_on_cols_layout(Bs, dA)
        dx0 = _block_on_cols_layout(
            [pa.PVector.full(0.0, A.cols) for _ in range(K)],
            dA, with_ghosts=True,
        )
        fn = make_cg_fn(
            dA, tol=1e-9, maxiter=50, fused=fused, precond=precond,
            rhs_batch=K,
        )
        counts[K] = collective_counts(
            fn, db, dx0, db[..., 0] if mv is None else mv, ops
        )
    assert any(counts[1].values()), "no collectives found at all"
    assert counts[1] == counts[4] == counts[8], counts


def test_block_matches_solo_collective_counts():
    """The K=1 block program must not pay MORE collectives than the solo
    program of the same body — widening payloads is free, extra rounds
    are not."""
    backend = _backend()

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (6, 6, 6))
        return A, b

    A, b = pa.prun(driver, backend, (2, 2, 2))
    dA = device_matrix(A, backend)
    ops = _matrix_operands(dA)
    db1 = _block_on_cols_layout([b], dA)
    dx01 = _block_on_cols_layout(
        [pa.PVector.full(0.0, A.cols)], dA, with_ghosts=True
    )
    db = DeviceVector.from_pvector(b, backend, dA.col_layout)
    dx0 = DeviceVector.from_pvector(
        pa.PVector.full(0.0, A.cols), backend, dA.col_layout
    )
    for fused in (False, True):
        blk = make_cg_fn(dA, tol=1e-9, maxiter=50, fused=fused, rhs_batch=1)
        solo = make_cg_fn(dA, tol=1e-9, maxiter=50, fused=fused)
        cb = collective_counts(blk, db1, dx01, db1[..., 0], ops)
        cs = collective_counts(solo, db.data, dx0.data, db.data, ops)
        for kind in cs:
            assert cb[kind] <= cs[kind], (fused, kind, cb, cs)


# ---------------------------------------------------------------------------
# guard rails
# ---------------------------------------------------------------------------


def test_block_rejects_pipelined_and_checkpoint():
    backend = _backend()

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (6, 6))
        return A, b

    A, b = pa.prun(driver, backend, (2, 2))
    dA = device_matrix(A, backend)
    with pytest.raises(ValueError, match="single-RHS"):
        make_cg_fn(dA, tol=1e-9, maxiter=10, pipelined=True, rhs_batch=2)
    with pytest.raises(ValueError, match="single-RHS"):
        cg(A, B=[b, b], pipelined=True)
    with pytest.raises(ValueError, match="single-RHS"):
        cg(A, B=[b], checkpoint=object())
    with pytest.raises(Exception):
        cg(A, b, B=[b])  # both b and B
    with pytest.raises(Exception, match="at least one"):
        cg(A, B=[])  # empty block fails with the friendly message
    with pytest.raises(Exception, match="at least one"):
        pcg(A, B=iter(()))  # generator B is normalized before the check
