"""palock fixture: seeded UNGUARDED-SHARED-ACCESS defect.

``count`` is written under the lock in one method and read bare in
another — the torn-read/lost-update class the guarded-by inference
exists to catch. Exactly the ``unguarded-shared-access`` check (and no
other) must flag this package.
"""
import threading


class Tally:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def bump(self):
        with self._lock:
            self.count += 1

    def read(self):
        return self.count  # seeded defect: bare read of a guarded attr
