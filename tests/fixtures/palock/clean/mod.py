"""palock fixture: CLEAN — every check passes.

The control for the six seeded-defect siblings: a journaling gate that
appends before acking, a worker whose stop flag and thread are owned
correctly, no blocking syscalls under a lock, no manual acquire, no
lock-order inversion.
"""
import threading


class Journal:
    def __init__(self):
        self.records = []

    def append(self, kind, **payload):
        self.records.append((kind, payload))
        return payload


class Gate:
    def __init__(self):
        self._lock = threading.Lock()
        self._handles = {}
        self.journal = Journal()

    def admit(self, rid):
        with self._lock:
            rec = self.journal.append("admitted", rid=rid)
            self._handles[rid] = rec  # ack AFTER the append
            return rec

    def poll(self, rid):
        with self._lock:
            return self._handles.get(rid)


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._thread = None
        self._stop = False

    def start(self):
        t = threading.Thread(target=self._run, daemon=True)
        self._thread = t
        t.start()

    def _run(self):
        while True:
            with self._lock:
                if self._stop:
                    return

    def shutdown(self):
        with self._lock:
            self._stop = True
        if self._thread is not None:
            self._thread.join()
