"""palock fixture: seeded MANUAL-ACQUIRE defect.

``.acquire()`` with no ``try/finally`` release: an exception between
the two calls leaks the lock forever. Exactly the ``manual-acquire``
check must flag this package.
"""
import threading


class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0

    def put(self, v):
        self._lock.acquire()  # seeded defect: no try/finally
        self.value = v
        self._lock.release()
