"""palock fixture: seeded ACK-BEFORE-APPEND durability defect.

The handle becomes poll-visible BEFORE the journal append: a crash in
between acknowledges a request the journal never heard of — the exact
write-ahead inversion the PR 12 invariant forbids. Exactly the
``durability-ordering`` check (under `FIXTURE_DURABILITY_RULES`) must
flag this package.
"""
import threading


class Journal:
    def __init__(self):
        self.records = []

    def append(self, kind, **payload):
        self.records.append((kind, payload))
        return payload


class Gate:
    def __init__(self):
        self._lock = threading.Lock()
        self._handles = {}
        self.journal = Journal()

    def admit(self, rid):
        with self._lock:
            self._handles[rid] = rid  # seeded defect: ack first
            rec = self.journal.append("admitted", rid=rid)
            return rec

    def poll(self, rid):
        with self._lock:
            return self._handles.get(rid)
