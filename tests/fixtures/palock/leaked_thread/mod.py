"""palock fixture: seeded LEAKED-THREAD defect.

A non-daemon thread spawned and never joined on any shutdown path: the
process hangs at exit (or the thread dies mid-write under a daemon
flag nobody reasoned about). Exactly the ``leaked-thread`` check must
flag this package.
"""
import threading


class Poller:
    def __init__(self):
        self._thread = None

    def start(self):
        t = threading.Thread(target=self._poll)  # seeded: never joined
        self._thread = t
        t.start()

    def _poll(self):
        pass
