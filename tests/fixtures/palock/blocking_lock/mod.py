"""palock fixture: seeded BLOCKING-UNDER-LOCK defect.

``os.fsync`` runs inside the lock region: every concurrent ``put``
serializes behind a disk flush. Exactly the ``blocking-under-lock``
check must flag this package (fixture roots get no waiver table).
"""
import os
import threading


class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._fh = open(os.devnull, "wb")

    def put(self, data):
        with self._lock:
            self._fh.write(data)
            os.fsync(self._fh.fileno())  # seeded defect: syscall under lock
