"""palock fixture: seeded LOCK-ORDER-CYCLE defect.

``ab`` nests a→b while ``ba`` nests b→a: two threads running them
concurrently deadlock. The static acquisition graph has the 2-cycle;
exactly the ``lock-order-cycle`` check must flag this package.
"""
import threading


class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.x = 0

    def ab(self):
        with self._a:
            with self._b:
                self.x += 1

    def ba(self):
        with self._b:  # seeded defect: inverted acquisition order
            with self._a:
                self.x += 1
