"""Distributed geometric multigrid: transfers, Galerkin product,
V-cycle convergence, and the Dirichlet decoupling transform.

Beyond-reference capability (the reference's solver story stops at
Krylov loops); everything here is built from the framework's own COO
assembly/migration machinery, so these tests double as integration
coverage of rectangular PSparseMatrix operators."""
import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa


def _poisson(parts, ns):
    A, b, x_exact, x0 = pa.assemble_poisson(parts, ns)
    return A, b, x_exact, x0


def test_decouple_dirichlet_symmetric_same_solution():
    def driver(parts):
        ns = (8, 8, 8)
        A, b, x_exact, _ = _poisson(parts, ns)
        Ah, bh = pa.decouple_dirichlet(A, b)
        M = pa.gather_psparse(Ah).toarray()
        assert np.abs(M - M.T).max() == 0.0
        xs = np.linalg.solve(M, pa.gather_pvector(bh))
        assert np.abs(xs - pa.gather_pvector(x_exact)).max() < 1e-10
        # sparsity pattern untouched: same indptr/indices per part
        def same_pattern(M0, M1):
            np.testing.assert_array_equal(M0.indptr, M1.indptr)
            np.testing.assert_array_equal(M0.indices, M1.indices)
            return True

        pa.map_parts(same_pattern, A.values, Ah.values)
        return True

    assert pa.prun(driver, pa.sequential, (2, 2, 2))


def test_decouple_matrix_only_variant():
    def driver(parts):
        A, b, _, _ = _poisson(parts, (6, 6))
        Ah = pa.decouple_dirichlet(A)  # no rhs: returns just the operator
        M = pa.gather_psparse(Ah).toarray()
        assert np.abs(M - M.T).max() == 0.0
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_interpolation_and_restriction_are_transposes():
    def driver(parts):
        nfs, ncs = (9, 9), (5, 5)
        fine_rows = pa.cartesian_partition(parts, nfs, pa.no_ghost)
        coarse_rows = pa.cartesian_partition(parts, ncs, pa.no_ghost)
        P = pa.interpolation_cartesian(nfs, ncs, fine_rows, coarse_rows)
        R = pa.restriction_from(P, coarse_rows)
        Pm = pa.gather_psparse(P).toarray()
        Rm = pa.gather_psparse(R).toarray()
        np.testing.assert_allclose(Rm, Pm.T, atol=0)
        # every fine row interpolates with unit weight sum
        np.testing.assert_allclose(Pm.sum(axis=1), 1.0, atol=1e-14)
        # coarse points map from their coincident fine point with weight 1
        assert Pm[0, 0] == 1.0 and Pm[2, 1] == 1.0
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_galerkin_product_matches_dense_triple_product():
    def driver(parts):
        ns = (9, 9)
        A, b, _, _ = _poisson(parts, ns)
        Ah = pa.decouple_dirichlet(A)
        ncs = (5, 5)
        coarse_rows = pa.cartesian_partition(parts, ncs, pa.no_ghost)
        P = pa.interpolation_cartesian(ns, ncs, Ah.rows, coarse_rows)
        Ac = pa.galerkin_cartesian(Ah, ns, ncs, coarse_rows)
        Pm = pa.gather_psparse(P).toarray()
        Am = pa.gather_psparse(Ah).toarray()
        Acm = pa.gather_psparse(Ac).toarray()
        np.testing.assert_allclose(Acm, Pm.T @ Am @ Pm, atol=1e-12)
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_gmg_solve_converges_and_pcg_preconditioned():
    def driver(parts):
        ns = (20, 20, 20)
        A, b, x_exact, _ = _poisson(parts, ns)
        Ah, bh = pa.decouple_dirichlet(A, b)
        h = pa.gmg_hierarchy(parts, Ah, ns, coarse_threshold=200, pre=2, post=2)
        assert len(h.levels) >= 2
        x, info = pa.gmg_solve(h, bh, tol=1e-9)
        assert info["converged"], info
        err = np.abs(pa.gather_pvector(x) - pa.gather_pvector(x_exact)).max()
        assert err < 1e-6, err
        # V-cycle-preconditioned CG: the hierarchy is callable minv
        xp, ip = pa.pcg(Ah, bh, minv=h, tol=1e-9)
        assert ip["converged"] and ip["iterations"] <= 20, ip["iterations"]
        errp = np.abs(pa.gather_pvector(xp) - pa.gather_pvector(x_exact)).max()
        assert errp < 1e-6, errp
        return True

    assert pa.prun(driver, pa.sequential, (2, 2, 2))


def test_gmg_near_grid_independent_iterations():
    """The multigrid property: iteration counts stay O(10) while the DOF
    count grows 8x — no Krylov method on its own can do that."""

    def run(ns):
        def driver(parts):
            A, b, _, _ = _poisson(parts, ns)
            Ah, bh = pa.decouple_dirichlet(A, b)
            h = pa.gmg_hierarchy(
                parts, Ah, ns, coarse_threshold=500, pre=2, post=2
            )
            _, ip = pa.pcg(Ah, bh, minv=h, tol=1e-9)
            return ip["iterations"]

        return pa.prun(driver, pa.sequential, (2, 2, 2))

    it_small = run((12, 12, 12))
    it_big = run((24, 24, 24))
    assert it_small <= 15 and it_big <= 15, (it_small, it_big)
    assert it_big <= it_small + 4, (it_small, it_big)


def test_gmg_runs_on_tpu_backend():
    """The V-cycle is backend-generic PData algebra: same driver on the
    (virtual-mesh) TPU backend, eager per-op execution."""

    def driver(parts):
        ns = (12, 12, 12)
        A, b, x_exact, _ = _poisson(parts, ns)
        Ah, bh = pa.decouple_dirichlet(A, b)
        h = pa.gmg_hierarchy(parts, Ah, ns, coarse_threshold=300)
        x, info = pa.gmg_solve(h, bh, tol=1e-8)
        assert info["converged"]
        err = np.abs(pa.gather_pvector(x) - pa.gather_pvector(x_exact)).max()
        return float(err)

    err_s = pa.prun(driver, pa.sequential, (2, 2, 2))
    err_t = pa.prun(driver, pa.tpu, (2, 2, 2))
    assert err_s < 1e-6 and err_t < 1e-6
    np.testing.assert_allclose(err_t, err_s, rtol=1e-6)


def test_gmg_hierarchy_rejects_mismatched_dims():
    def driver(parts):
        A, b, _, _ = _poisson(parts, (6, 6))
        with pytest.raises(AssertionError):
            pa.gmg_hierarchy(parts, A, (7, 6))
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_compiled_vcycle_iteration_parity():
    """On the TPU backend the whole V-cycle (and the V-cycle-preconditioned
    CG) runs as ONE compiled program (parallel/tpu_gmg.py); iteration
    counts must match the host oracle exactly, and solutions to rounding."""

    def driver(parts):
        ns = (16, 16, 16)
        A, b, x_exact, _ = _poisson(parts, ns)
        Ah, bh = pa.decouple_dirichlet(A, b)
        h = pa.gmg_hierarchy(parts, Ah, ns, coarse_threshold=100, pre=2, post=2)
        x1, i1 = pa.gmg_solve(h, bh, tol=1e-9)
        x2, i2 = pa.pcg(Ah, bh, minv=h, tol=1e-9)
        e1 = np.abs(pa.gather_pvector(x1) - pa.gather_pvector(x_exact)).max()
        e2 = np.abs(pa.gather_pvector(x2) - pa.gather_pvector(x_exact)).max()
        assert i1["converged"] and i2["converged"]
        return i1["iterations"], i2["iterations"], e1, e2

    s1, s2, es1, es2 = pa.prun(driver, pa.sequential, (2, 2, 2))
    t1, t2, et1, et2 = pa.prun(driver, pa.tpu, (2, 2, 2))
    assert (s1, s2) == (t1, t2), ((s1, s2), (t1, t2))
    assert max(es1, es2, et1, et2) < 1e-6
    np.testing.assert_allclose(et1, es1, rtol=1e-5)
    np.testing.assert_allclose(et2, es2, rtol=1e-5)


def test_compiled_vcycle_mixed_padded_compact_frames(monkeypatch):
    """The real-TPU frame configuration: the square coded level operator
    takes the PADDED kernel frame (o0 = one pad block) while the
    rectangular transfers stay compact (o0 = 0). Forcing `_padded_for`
    on the CPU mesh reproduces it with the Pallas kernel interpreted —
    this is the layout mix the compiled V-cycle's cross-frame slices
    must survive (a plain-CPU run cannot catch it: every frame is
    compact there)."""
    import importlib

    tpu_mod = importlib.import_module("partitionedarrays_jl_tpu.parallel.tpu")
    monkeypatch.setattr(tpu_mod, "_padded_for", lambda backend: True)

    def driver(parts):
        ns = (12, 12, 12)
        A, b, x_exact, _ = _poisson(parts, ns)
        Ah, bh = pa.decouple_dirichlet(A, b)
        h = pa.gmg_hierarchy(parts, Ah, ns, coarse_threshold=100)
        x, info = pa.gmg_solve(h, bh, tol=1e-8)
        assert info["converged"], info
        err = np.abs(pa.gather_pvector(x) - pa.gather_pvector(x_exact)).max()
        assert err < 1e-6, err
        # the level operator really must have taken the padded frame for
        # this test to mean anything
        from partitionedarrays_jl_tpu.parallel.tpu import device_matrix

        dA0 = device_matrix(h.levels[0].A, parts.backend)
        dP0 = device_matrix(h.levels[0].P, parts.backend)
        assert dA0.padded and not dP0.padded
        return True

    assert pa.prun(driver, pa.tpu, (2, 2, 2))


def test_gmg_deep_coarsening_empty_coarse_parts():
    """Aggressive coarsening can leave coarse grids with fewer cells
    than parts (empty parts on coarse levels); the hierarchy, the host
    V-cycle, and the compiled program must all survive it with
    iteration parity."""

    def driver(parts):
        ns = (17, 17)
        A, b, x_exact, _ = _poisson(parts, ns)
        Ah, bh = pa.decouple_dirichlet(A, b)
        h = pa.gmg_hierarchy(parts, Ah, ns, coarse_threshold=8)
        # the (3, 3) coarse grid split over a (2, 4) part grid leaves
        # genuinely empty parts in one dimension
        assert any(
            i.num_oids == 0
            for i in h.coarse_A.rows.partition.part_values()
        )
        x, info = pa.gmg_solve(h, bh, tol=1e-9)
        assert info["converged"]
        err = np.abs(pa.gather_pvector(x) - pa.gather_pvector(x_exact)).max()
        assert err < 1e-6, err
        return info["iterations"]

    it_s = pa.prun(driver, pa.sequential, (2, 4))
    it_t = pa.prun(driver, pa.tpu, (2, 4))
    assert it_s == it_t, (it_s, it_t)


def test_w_cycle_host_and_compiled():
    """W-cycle (γ = 2): fewer stationary iterations than the V-cycle on
    the same hierarchy settings, identical host/compiled iteration
    counts."""

    def run(backend, cycle):
        def driver(parts):
            ns = (20, 20, 20)
            A, b, x_exact, _ = _poisson(parts, ns)
            Ah, bh = pa.decouple_dirichlet(A, b)
            h = pa.gmg_hierarchy(
                parts, Ah, ns, coarse_threshold=30, cycle=cycle
            )
            assert len(h.levels) >= 3  # a W-cycle needs depth to differ
            x, info = pa.gmg_solve(h, bh, tol=1e-9)
            assert info["converged"]
            err = np.abs(
                pa.gather_pvector(x) - pa.gather_pvector(x_exact)
            ).max()
            assert err < 1e-6, err
            return info["iterations"]

        return pa.prun(driver, backend, (2, 2, 2))

    it_v = run(pa.sequential, "v")
    it_w = run(pa.sequential, "w")
    assert it_w <= it_v, (it_w, it_v)
    it_w_t = run(pa.tpu, "w")
    assert it_w_t == it_w, (it_w_t, it_w)

    # plumbing guard that cannot pass by convergence coincidence: one
    # W-cycle at depth 3 visits the coarse solver 2^(L-1) = 4 times
    def count_coarse(parts):
        ns = (20, 20, 20)
        A, b, _, _ = _poisson(parts, ns)
        Ah, bh = pa.decouple_dirichlet(A, b)
        h = pa.gmg_hierarchy(parts, Ah, ns, coarse_threshold=30, cycle="w")
        assert len(h.levels) == 3
        calls = []
        orig = h.coarse_solver.solve
        h.coarse_solver.solve = lambda v: (calls.append(1), orig(v))[1]
        h.vcycle(bh)
        return len(calls)

    assert pa.prun(count_coarse, pa.sequential, (2, 2, 2)) == 4


def test_gmg_variable_coefficient_operator():
    """GMG beyond the constant stencil: a 2-D diffusion operator with a
    smoothly varying coefficient k(x, y) (5-point FDM, harmonic-mean
    arm weights). Every diagonal carries many distinct values, so the
    device lowering takes the streaming-DIA path rather than the coded
    one, and the exact Galerkin product must handle arbitrary values.
    The V-cycle-preconditioned CG must still converge fast."""
    ns = (33, 33)

    def assemble_var(parts):
        rows = pa.cartesian_partition(parts, ns, pa.no_ghost)
        cis = pa.p_cartesian_indices(parts, ns, pa.no_ghost)

        def k_field(cx, cy):
            return 1.0 + 0.8 * np.sin(0.37 * cx) * np.cos(0.23 * cy)

        def coo(ci):
            grid = ci.grid()
            cx, cy = [g.ravel() for g in grid]
            gid = np.ravel_multi_index((cx, cy), ns)
            interior = (cx > 0) & (cx < ns[0] - 1) & (cy > 0) & (cy < ns[1] - 1)
            I, J, V = [gid[~interior]], [gid[~interior]], [np.ones((~interior).sum())]
            gi = gid[interior]
            icx, icy = cx[interior], cy[interior]
            diag = np.zeros(len(gi))
            for dx, dy in ((-1, 0), (1, 0), (0, -1), (0, 1)):
                kn = 2.0 / (
                    1.0 / k_field(icx, icy)
                    + 1.0 / k_field(icx + dx, icy + dy)
                )
                I.append(gi)
                J.append(np.ravel_multi_index((icx + dx, icy + dy), ns))
                V.append(-kn)
                diag += kn
            I.append(gi)
            J.append(gi)
            V.append(diag)
            return np.concatenate(I), np.concatenate(J), np.concatenate(V)

        c = pa.map_parts(coo, cis)
        I = pa.map_parts(lambda t: t[0], c)
        J = pa.map_parts(lambda t: t[1], c)
        V = pa.map_parts(lambda t: t[2], c)
        cols = pa.add_gids(rows, J)
        return pa.PSparseMatrix.from_coo(I, J, V, rows, cols, ids="global")

    def driver(parts):
        A = assemble_var(parts)
        Ah = pa.decouple_dirichlet(A)
        M = pa.gather_psparse(Ah).toarray()
        assert np.abs(M - M.T).max() < 1e-13  # harmonic means: symmetric
        xs = pa.PVector.full(1.0, Ah.cols)
        bs = Ah @ xs
        h = pa.gmg_hierarchy(parts, Ah, ns, coarse_threshold=50, pre=2, post=2)
        x, info = pa.pcg(Ah, bs, minv=h, tol=1e-10)
        assert info["converged"] and info["iterations"] <= 25, info["iterations"]
        err = np.abs(pa.gather_pvector(x) - pa.gather_pvector(xs)).max()
        assert err < 1e-7, err
        return info["iterations"]

    it_s = pa.prun(driver, pa.sequential, (2, 2))
    it_t = pa.prun(driver, pa.tpu, (2, 2))
    assert it_s == it_t, (it_s, it_t)


def test_fgmres_gmg_compiled_matches_host():
    """Compiled flexible GMRES with the inlined V-cycle preconditioner
    (parallel/tpu_gmg.py:make_fgmres_gmg_fn) vs the host
    fgmres(minv=hierarchy): same Arnoldi/Givens/restart algorithm, so
    the gate is iteration parity (+-1 for FP reassociation in the basis
    updates) and solution accuracy."""

    def driver(parts):
        ns = (12, 12, 12)
        A, b, x_exact, _ = _poisson(parts, ns)
        Ah, bh = pa.decouple_dirichlet(A, b)
        h = pa.gmg_hierarchy(parts, Ah, ns, coarse_threshold=100)
        xh, ih = pa.fgmres(Ah, bh, minv=h, tol=1e-9, restart=10)
        assert ih["converged"], ih
        xt, it_ = pa.tpu_fgmres_gmg(h, bh, tol=1e-9, restart=10)
        assert it_["converged"], it_
        errh = np.abs(pa.gather_pvector(xh) - pa.gather_pvector(x_exact)).max()
        errt = np.abs(pa.gather_pvector(xt) - pa.gather_pvector(x_exact)).max()
        assert errh < 1e-7 and errt < 1e-7, (errh, errt)
        assert abs(ih["iterations"] - it_["iterations"]) <= 1, (
            ih["iterations"], it_["iterations"],
        )
        return True

    assert pa.prun(driver, pa.tpu, (2, 2, 2))


def test_fgmres_gmg_restart_cycles():
    """A restart smaller than the iteration count forces multiple outer
    cycles through the compiled while_loop; convergence must survive."""

    def driver(parts):
        ns = (12, 12)
        A, b, x_exact, _ = _poisson(parts, ns)
        Ah, bh = pa.decouple_dirichlet(A, b)
        h = pa.gmg_hierarchy(parts, Ah, ns, coarse_threshold=30)
        xt, info = pa.tpu_fgmres_gmg(h, bh, tol=1e-10, restart=3)
        assert info["converged"], info
        err = np.abs(pa.gather_pvector(xt) - pa.gather_pvector(x_exact)).max()
        assert err < 1e-7, err
        return True

    assert pa.prun(driver, pa.tpu, (2, 2))


def test_gmg_coarse_agglomeration_iteration_parity():
    """agg_threshold moves coarse levels onto a 2x-strided sub-grid of
    parts (empty boxes elsewhere). Placement must not change the math:
    same iteration counts and solution as the full-mesh hierarchy, on
    the host loop AND the compiled program."""

    def driver(parts, agg):
        ns = (24, 24, 24)
        A, b, x_exact, _ = _poisson(parts, ns)
        Ah, bh = pa.decouple_dirichlet(A, b)
        h = pa.gmg_hierarchy(
            parts, Ah, ns, coarse_threshold=100,
            agg_threshold=agg,
        )
        if agg:
            # some level must actually be agglomerated: a coarse
            # partition with empty parts while cells >= parts
            assert any(
                min(
                    i.num_oids
                    for i in lvl.A.rows.partition.part_values()
                ) == 0
                and lvl.A.rows.ngids >= lvl.A.rows.num_parts
                for lvl in h.levels[1:]
            ) or min(
                i.num_oids
                for i in h.coarse_A.rows.partition.part_values()
            ) == 0
        x, info = pa.gmg_solve(h, bh, tol=1e-9)
        assert info["converged"]
        err = np.abs(pa.gather_pvector(x) - pa.gather_pvector(x_exact)).max()
        assert err < 1e-6, err
        xp, infop = pa.tpu_gmg_pcg(h, bh, tol=1e-9)
        assert infop["converged"]
        errp = np.abs(
            pa.gather_pvector(xp) - pa.gather_pvector(x_exact)
        ).max()
        assert errp < 1e-6, errp
        return info["iterations"], infop["iterations"]

    it_full = pa.prun(driver, pa.tpu, (2, 2, 2), 0)
    it_agg = pa.prun(driver, pa.tpu, (2, 2, 2), 2000)
    assert it_full == it_agg, (it_full, it_agg)


def test_fgmres_gmg_tight_tolerance_f64():
    """Round-3 postscript: an apparent FGMRES convergence-flag stall at
    this config came from probes that ran the DEVICE in f32 (no x64)
    while comparing against the f64 host loop — the Arnoldi residual
    estimate simply floors near f32 epsilon, as any f32 Krylov does.
    Under the suite's f64 config, host and device both converge."""

    def driver(parts):
        ns = (16, 16, 16)
        A, b, x_exact, _ = _poisson(parts, ns)
        Ah, bh = pa.decouple_dirichlet(A, b)
        h = pa.gmg_hierarchy(parts, Ah, ns, coarse_threshold=100)
        xt, info = pa.tpu_fgmres_gmg(h, bh, tol=1e-8, restart=12, maxiter=40)
        err = np.abs(pa.gather_pvector(xt) - pa.gather_pvector(x_exact)).max()
        assert err < 1e-5, err
        xh, ih = pa.fgmres(Ah, bh, minv=h, tol=1e-8, restart=12, maxiter=40)
        assert ih["converged"]
        return info["converged"], info["iterations"], ih["iterations"]

    conv, it_d, it_h = pa.prun(driver, pa.tpu, (2, 2, 2))
    assert conv
    assert abs(it_d - it_h) <= 1, (it_d, it_h)


def test_galerkin_fused_asymmetric_dense_parity():
    """Round-4 fused Galerkin (COO-free shell-exchange + native CSR
    emission, models/gmg.py:_galerkin_fused): dense triple-product
    parity on an ASYMMETRIC 3-D grid with uneven per-part boxes, plus
    the CSR structural contract the emission kernel promises (column-
    sorted rows in local ids, owned columns before ghosts)."""

    def driver(parts):
        ns = (7, 6, 9)
        A, b, _, _ = _poisson(parts, ns)
        Ah = pa.decouple_dirichlet(A)
        ncs = tuple((n + 1) // 2 for n in ns)
        coarse_rows = pa.cartesian_partition(parts, ncs, pa.no_ghost)
        P = pa.interpolation_cartesian(ns, ncs, Ah.rows, coarse_rows)
        Ac = pa.galerkin_cartesian(Ah, ns, ncs, coarse_rows)
        Pm = pa.gather_psparse(P).toarray()
        Am = pa.gather_psparse(Ah).toarray()
        Acm = pa.gather_psparse(Ac).toarray()
        np.testing.assert_allclose(Acm, Pm.T @ Am @ Pm, atol=1e-12)

        # structural contract of the fused emission
        def _check_struct(M):
            for r in range(M.shape[0]):
                c = M.indices[M.indptr[r] : M.indptr[r + 1]]
                assert (np.diff(c) > 0).all(), (r, c)  # strictly sorted
            return True

        assert all(
            pa.map_parts(_check_struct, Ac.values).part_values()
        )
        return True

    assert pa.prun(driver, pa.sequential, (2, 2, 2))
    assert pa.prun(driver, pa.sequential, (3, 1, 2))


@pytest.mark.parametrize(
    "ns,pshape",
    [
        ((40, 38, 36), (1, 1, 1)),
        ((37, 41, 39), (2, 2, 1)),
        ((48, 50), (2, 2)),
    ],
)
def test_classed_collapse_bit_identical(ns, pshape):
    """Round-4 directive 1: the classed Galerkin collapse (rep-box +
    broadcast expansion, default-on) must produce BIT-identical coarse
    operators to the full native collapse — same kernel arithmetic, same
    fine-row order per coarse row. Pins _zone_reps margins,
    galerkin_classify_dim, and the sub_coords kernel path."""
    import os

    from partitionedarrays_jl_tpu.models import assemble_poisson
    from partitionedarrays_jl_tpu.models.gmg import galerkin_cartesian
    from partitionedarrays_jl_tpu.parallel.prange import (
        cartesian_partition, no_ghost,
    )
    from partitionedarrays_jl_tpu.parallel.psparse import (
        psparse_global_triplets,
    )

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(
            parts, ns, dtype=np.float32, decoupled=True
        )
        ncs = tuple((n + 1) // 2 for n in ns)
        Ac1 = galerkin_cartesian(
            A, ns, ncs, cartesian_partition(parts, ncs, no_ghost)
        )
        os.environ["PA_TPU_GMG_CLASSED"] = "0"
        try:
            Ac2 = galerkin_cartesian(
                A, ns, ncs, cartesian_partition(parts, ncs, no_ghost)
            )
        finally:
            del os.environ["PA_TPU_GMG_CLASSED"]
        for (i1, j1, v1), (i2, j2, v2) in zip(
            psparse_global_triplets(Ac1).part_values(),
            psparse_global_triplets(Ac2).part_values(),
        ):
            o1, o2 = np.lexsort((j1, i1)), np.lexsort((j2, i2))
            assert np.array_equal(i1[o1], i2[o2])
            assert np.array_equal(j1[o1], j2[o2])
            assert np.array_equal(v1[o1], v2[o2]), "values drifted"
        return True

    assert pa.prun(driver, pa.sequential, pshape)


def test_classed_collapse_declines_variable_coefficients():
    """The zone-uniformity proof must reject operators whose values are
    not a function of boundary distance — the classed path silently
    producing wrong coarse operators for variable coefficients would be
    the worst possible failure mode."""
    from partitionedarrays_jl_tpu.models.gmg import _classed_collapse

    def driver(parts):
        ns = (24, 22, 20)
        A, b, xe, x0 = pa.assemble_poisson(parts, ns)
        # perturb one interior value: no zone function can explain it
        M = A.values.part_values()[0]
        k = len(M.data) // 2
        M.data[k] *= 1.5
        ri = A.rows.partition.part_values()[0]
        ci = A.cols.partition.part_values()[0]
        ncs = tuple((n + 1) // 2 for n in ns)
        dim = len(ns)
        flo, fhi = ri.box_lo, ri.box_hi
        elo = [max(0, (flo[d] - 1) // 2) for d in range(dim)]
        ehi = [min(ncs[d], fhi[d] // 2 + 1) for d in range(dim)]
        out = _classed_collapse(ri, ci, M, ns, ncs, flo, fhi, elo, ehi)
        assert out is None, "classed collapse accepted a non-classed operator"
        return True

    assert pa.prun(driver, pa.sequential, (1, 1, 1))


def _stencil_level_info(h, backend):
    """(descs_or_False, has_shmask) per level of the staged hierarchy."""
    from partitionedarrays_jl_tpu.parallel.tpu_gmg import _device_hierarchy

    dh = _device_hierarchy(h, backend)
    return [
        (
            len(l["stencil"]) if "stencil" in l else False,
            "shmask" in l,
        )
        for l in dh["levels"]
    ]


def test_stencil_transfer_unequal_boxes():
    """Round-5 directive 4: unequal Cartesian splits take the matrix-free
    stencil transfer via per-descriptor `lax.switch` branches — compiled
    GMG and GMG-PCG must match the sequential oracle exactly on
    iteration counts (and to rounding on the solution)."""
    ns = (17, 14, 10)  # (9,8)/(7,7)/(5,5) boxes: multi-variant plans

    def driver(parts):
        A0, b0, xe, _ = pa.assemble_poisson(parts, ns)
        A, b = pa.decouple_dirichlet(A0, b0)
        h = pa.gmg_hierarchy(parts, A, ns, coarse_threshold=50)
        x1, i1 = pa.gmg_solve(h, b, tol=1e-9)
        x2, i2 = pa.pcg(A, b, minv=h, tol=1e-9)
        err = np.abs(pa.gather_pvector(x1) - pa.gather_pvector(xe)).max()
        assert i1["converged"] and i2["converged"]
        info = (
            _stencil_level_info(h, parts.backend)
            if parts.backend is pa.tpu
            else None
        )
        return i1["iterations"], i2["iterations"], float(err), info

    s = pa.prun(driver, pa.sequential, (2, 2, 2))
    t = pa.prun(driver, pa.tpu, (2, 2, 2))
    assert (s[0], s[1]) == (t[0], t[1]), (s, t)
    assert max(s[2], t[2]) < 1e-6
    # the run must actually have exercised the multi-variant switch
    assert any(
        isinstance(d, int) and d > 1 for d, _ in t[3]
    ), t[3]


def test_stencil_transfer_periodic():
    """Round-5 directive 4: periodic (torus) levels take the stencil
    transfer with the wrapped segments masked to zero — matching the
    truncating assembled-S oracle — instead of falling back to the
    assembled-matrix path."""
    ns = (12, 12, 12)

    def driver(parts):
        A, b, xe, x0 = pa.assemble_poisson_periodic(parts, ns, shift=1.0)
        h = pa.gmg_hierarchy(parts, A, ns, coarse_threshold=100)
        x1, i1 = pa.gmg_solve(h, b, tol=1e-9)
        x2, i2 = pa.pcg(A, b, minv=h, tol=1e-9)
        err = np.abs(pa.gather_pvector(x1) - pa.gather_pvector(xe)).max()
        assert i1["converged"] and i2["converged"]
        info = (
            _stencil_level_info(h, parts.backend)
            if parts.backend is pa.tpu
            else None
        )
        return i1["iterations"], i2["iterations"], float(err), info

    s = pa.prun(driver, pa.sequential, (2, 2, 2))
    t = pa.prun(driver, pa.tpu, (2, 2, 2))
    assert (s[0], s[1]) == (t[0], t[1]), (s, t)
    assert max(s[2], t[2]) < 1e-7
    # level 0 (7-point halo: no corner slabs) must DECLINE; the Galerkin
    # level must ENGAGE with the wrapped-segment mask staged
    assert t[3][0][0] is False, t[3]
    assert any(d and m for d, m in t[3]), t[3]


def test_aligned_coarse_split_engages_stencil_on_odd_extents():
    """The hierarchy's coarse cuts are ceil(fine_cut/2)-aligned, so odd
    coarse extents (58 -> 29 -> 15, the flagship's deep levels) keep
    st in {0, 1} and the stencil fast path engages — the default
    remainder-last split put a coarse point's even fine position in the
    neighbor part (st = -1) and silently fell back to assembled
    transfers."""
    ns = (58, 58, 58)

    def driver(parts):
        A0, b0, xe, _ = pa.assemble_poisson(parts, ns)
        A, b = pa.decouple_dirichlet(A0, b0)
        h = pa.gmg_hierarchy(parts, A, ns, coarse_threshold=100)
        x, info = pa.gmg_solve(h, b, tol=1e-8)
        assert info["converged"]
        err = np.abs(pa.gather_pvector(x) - pa.gather_pvector(xe)).max()
        assert err < 1e-6, err
        return _stencil_level_info(h, parts.backend), [
            lvl.ncs for lvl in h.levels
        ]

    info, ncs = pa.prun(driver, pa.tpu, (2, 2, 2))
    # every Galerkin level (full 27-point shell) must take the stencil
    # path — including the odd-extent 29->15 transition
    assert all(d for d, _ in info[1:]), (info, ncs)


def test_cartesian_partition_dim_firsts():
    """Explicit per-dim cuts override the balanced split (zero-size
    blocks allowed); invalid cuts are rejected."""
    parts = pa.sequential.get_part_ids((2, 2))

    def driver(parts):
        r = pa.cartesian_partition(
            parts, (6, 6), pa.no_ghost, dim_firsts=[[0, 2], [0, 5]]
        )
        boxes = [
            (tuple(i.box_lo), tuple(i.box_hi))
            for i in r.partition.part_values()
        ]
        assert boxes == [
            ((0, 0), (2, 5)),
            ((0, 5), (2, 6)),
            ((2, 0), (6, 5)),
            ((2, 5), (6, 6)),
        ], boxes
        assert r.ngids == 36
        # gid->part honors the custom cuts
        g2p = r.gid_to_part
        assert int(g2p(np.array([0]))[0]) == 0
        assert int(g2p(np.array([5]))[0]) == 1  # col 5 -> second block
        assert int(g2p(np.array([2 * 6]))[0]) == 2  # row 2 -> third
        with pytest.raises(AssertionError):
            pa.cartesian_partition(
                parts, (6, 6), pa.no_ghost, dim_firsts=[[1, 2], [0, 5]]
            )
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_matrix_s_fallback_gets_box_plan_on_agglomerated_levels():
    """docs/roadmap.md §4 (round-7 satellite): the matrix-S fallback's
    cols exchanger must take the slice-based box plan whenever its ghost
    set is slab-shaped — including AGGLOMERATED coarse levels, whose
    inactive parts own empty boxes (the case that used to fail the slab
    detector outright and silently lower to the generic gather plan).
    Also pins solve parity: the box-plan program reproduces the
    full-mesh hierarchy's iteration count."""
    import os

    from partitionedarrays_jl_tpu.parallel.tpu_box import BoxExchangePlan
    from partitionedarrays_jl_tpu.parallel.tpu_gmg import _device_hierarchy

    os.environ["PA_TPU_GMG_STENCIL"] = "0"  # force the matrix-S path
    try:

        def driver(parts):
            ns = (16, 16, 16)
            A, b, x_exact, _ = _poisson(parts, ns)
            Ah, bh = pa.decouple_dirichlet(A, b)
            h = pa.gmg_hierarchy(
                parts, Ah, ns, coarse_threshold=30, agg_threshold=200,
            )
            # the hierarchy must actually agglomerate somewhere
            assert any(
                min(
                    i.num_oids
                    for i in lvl.A.rows.partition.part_values()
                ) == 0
                for lvl in h.levels[1:]
            ) or min(
                i.num_oids
                for i in h.coarse_A.rows.partition.part_values()
            ) == 0
            dh = _device_hierarchy(h, parts.backend)
            s_levels = [l for l in dh["levels"] if "dS" in l]
            assert s_levels, "no level took the matrix-S fallback"
            for l in s_levels:
                assert isinstance(l["dS"].col_plan, BoxExchangePlan), (
                    "matrix-S cols exchanger lowered to the generic "
                    "gather plan on a slab-shaped ghost set"
                )
            x, info = pa.tpu_gmg_pcg(h, bh, tol=1e-9)
            assert info["converged"]
            err = np.abs(
                pa.gather_pvector(x) - pa.gather_pvector(x_exact)
            ).max()
            assert err < 1e-6, err
            return info["iterations"]

        it_agg = pa.prun(driver, pa.tpu, (2, 2, 2))
        assert it_agg > 0
    finally:
        del os.environ["PA_TPU_GMG_STENCIL"]


def test_f32_hierarchy_stages_f32_end_to_end():
    """docs/roadmap.md §4 (round-7 satellite): an f32 hierarchy must
    stage f32 everywhere — transfers (P/R/S), coarse inverse, smoother
    diagonals — with no f64 detour on host. The interpolation weights
    are exact powers of 1/2, so the f32 transfers lose nothing."""
    from partitionedarrays_jl_tpu.parallel.tpu_gmg import _device_hierarchy

    def driver(parts):
        ns = (16, 16, 16)
        A, b, x_exact, x0 = pa.assemble_poisson(
            parts, ns, dtype=np.float32
        )
        h = pa.gmg_hierarchy(parts, A, ns, coarse_threshold=30)
        for lvl in h.levels:
            assert lvl.A.dtype == np.float32
            assert lvl.dinv.dtype == np.float32
            # lazily-built assembled transfers inherit the level dtype
            assert lvl.P.dtype == np.float32, lvl.P.dtype
            assert lvl.R.dtype == np.float32, lvl.R.dtype
        assert h.coarse_A.dtype == np.float32
        dh = _device_hierarchy(h, parts.backend)
        assert dh["cinv"].dtype == np.float32, dh["cinv"].dtype
        for l in dh["levels"]:
            assert np.dtype(l["dinv"].dtype) == np.float32
            if "dS" in l:
                dS = l["dS"]
                staged = next(
                    a
                    for a in (dS.dia_cb, dS.dia_vals, dS.oo_vals)
                    if a is not None
                )
                assert np.dtype(staged.dtype) == np.float32, staged.dtype
        # and the preconditioner still works at f32
        x, info = pa.pcg(A, b, x0=x0, minv=h, tol=1e-4, maxiter=200)
        assert info["converged"]
        return True

    assert pa.prun(driver, pa.tpu, (2, 2, 2))
