"""pastep — the communication-avoiding s-step CG body and the
interior/boundary overlap SpMV schedule (round 17).

Four contracts, each pinned here:

* **Degenerate identity.** ``sstep=1`` is DEFINED as the textbook
  standard body (`_resolve_sstep` normalizes 0 and 1 to the same
  program) — pinned as lowered-program identity under strict-bits, the
  strongest possible bitwise claim: identical StableHLO text implies
  bit-identical trajectories.
* **Schedule-only overlap.** The overlap body splits the SpMV tail into
  interior rows (fenced against the in-flight halo rounds) and boundary
  rows finished on arrival — it changes WHEN, never WHAT. Pinned as a
  bitwise run-to-run comparison under strict-bits on the 4-part
  fixture: identical residual bits, identical solution bits.
* **Gather collapse + refusal matrix.** The s >= 2 body replaces the
  textbook 2 scalar all_gathers per iteration with ONE block gather per
  trip (asserted on lowered HLO, the test_fused_cg A/B discipline), and
  every composition it cannot honor refuses typed when EXPLICIT
  (`LoweringConflictError`) or falls back with a stderr note when
  env-driven — the pipelined-SDC precedent.
* **Widened plans.** The depth-s exchange plan is the depth-1 plan's
  round structure tagged ``ghost_depth`` — both plan families (generic
  and box) pass all five PR 8 plan-verifier checks, the depth-1 plan
  stays the SAME cached instance, and the host plan's
  `canonical_exchange_fingerprint` is untouched.

Plus the `suggest_s` policy arithmetic (telemetry.spectrum): stability
budget, unmeasured degradation to s=1, and the gather-count forecast
the paspec CLI leg surfaces.
"""
import math

import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu import telemetry
from partitionedarrays_jl_tpu.analysis import collective_counts, lower_text
from partitionedarrays_jl_tpu.analysis import plan_verifier as pv
from partitionedarrays_jl_tpu.models import assemble_poisson, gather_pvector
from partitionedarrays_jl_tpu.parallel.health import LoweringConflictError
from partitionedarrays_jl_tpu.parallel.tpu import (
    DeviceVector,
    TPUBackend,
    WidenedDeviceExchangePlan,
    _b_on_cols_layout,
    _matrix_operands,
    device_exchange_plan,
    device_matrix,
    make_cg_fn,
    tpu_cg,
)
from partitionedarrays_jl_tpu.parallel.tpu_box import WidenedBoxExchangePlan


def _backend(n=4):
    import jax

    return TPUBackend(devices=jax.devices()[:n])


def _staged(parts=(2, 2), ns=(8, 8)):
    """A staged 4-part system: (dA, db, dx0, ops) ready for
    make_cg_fn lowering — the test_fused_cg HLO idiom."""
    backend = _backend()

    def driver(p):
        A, b, xe, x0 = assemble_poisson(p, ns)
        return A, b

    A, b = pa.prun(driver, backend, parts)
    dA = device_matrix(A, backend)
    db = _b_on_cols_layout(b, dA)
    dx0 = DeviceVector.from_pvector(
        pa.PVector.full(0.0, A.cols), backend, dA.col_layout
    )
    return dA, db, dx0, _matrix_operands(dA)


# ---------------------------------------------------------------------------
# bitwise: s=1 and overlap against the textbook body under strict-bits
# ---------------------------------------------------------------------------


def test_sstep1_is_the_textbook_program_under_strict(monkeypatch):
    """``sstep=1`` (the degenerate depth) lowers to the IDENTICAL
    StableHLO as the standard body under strict-bits — program identity
    is the bitwise claim, with no run needed."""
    monkeypatch.setenv("PA_TPU_STRICT_BITS", "1")
    dA, db, dx0, ops = _staged()
    one = make_cg_fn(dA, tol=1e-9, maxiter=100, sstep=1)
    std = make_cg_fn(dA, tol=1e-9, maxiter=100)
    t1 = lower_text(one, db.data, dx0.data, db.data, ops)
    t0 = lower_text(std, db.data, dx0.data, db.data, ops)
    assert t1 == t0


def test_overlap_body_bitwise_identical_under_strict(monkeypatch):
    """PA_TPU_OVERLAP=1 under strict-bits: the interior/boundary split
    reorders the schedule only — residual history and solution are
    bit-for-bit the standard body's on the 4-part fixture."""
    monkeypatch.setenv("PA_TPU_STRICT_BITS", "1")

    def run():
        def driver(parts):
            A, b, xe, x0 = assemble_poisson(parts, (8, 8))
            x, info = tpu_cg(A, b, x0=x0, tol=1e-10, maxiter=200)
            return gather_pvector(x), info

        return pa.prun(driver, _backend(), (2, 2))

    x_std, inf_std = run()
    monkeypatch.setenv("PA_TPU_OVERLAP", "1")
    x_ovl, inf_ovl = run()
    assert inf_std["converged"] and inf_ovl["converged"]
    assert inf_ovl["iterations"] == inf_std["iterations"]
    rs = np.asarray(inf_std["residuals"], dtype=np.float64)
    ro = np.asarray(inf_ovl["residuals"], dtype=np.float64)
    assert ro.tobytes() == rs.tobytes()
    assert np.asarray(x_ovl).tobytes() == np.asarray(x_std).tobytes()


# ---------------------------------------------------------------------------
# s >= 2: gather collapse on lowered HLO; convergence on the real solve
# ---------------------------------------------------------------------------


def test_sstep2_program_collapses_gathers():
    """The s-step program carries ONE block all_gather per s-iteration
    trip where the textbook body pays 2 scalar gathers per iteration —
    strictly fewer all_gathers in the lowered program (the collective
    budget palint pins per lowering-matrix case)."""
    dA, db, dx0, ops = _staged()
    ca = make_cg_fn(dA, tol=1e-9, maxiter=100, sstep=2)
    std = make_cg_fn(dA, tol=1e-9, maxiter=100, fused=False)
    cc = collective_counts(ca, db.data, dx0.data, db.data, ops)
    cu = collective_counts(std, db.data, dx0.data, db.data, ops)
    assert cu["all_gather"] > 0
    assert cc["all_gather"] < cu["all_gather"], (cc, cu)


def test_sstep2_converges_and_matches_standard(monkeypatch):
    """PA_TPU_SSTEP=2 end to end through `tpu_cg`: the body label says
    so, the solve converges, and the solution matches the textbook
    body's to rounding (the monomial basis at s=2 is far inside the
    f64 stability budget on this operator)."""

    def run():
        def driver(parts):
            A, b, xe, x0 = assemble_poisson(parts, (8, 8))
            x, info = tpu_cg(A, b, x0=x0, tol=1e-9, maxiter=400)
            return gather_pvector(x), info

        return pa.prun(driver, _backend(), (2, 2))

    monkeypatch.setenv("PA_TPU_FUSED_CG", "0")
    x_std, inf_std = run()
    monkeypatch.setenv("PA_TPU_SSTEP", "2")
    x_ca, inf_ca = run()
    assert inf_std["cg_body"] == "standard"
    assert inf_ca["cg_body"] == "sstep2"
    assert inf_std["converged"] and inf_ca["converged"]
    assert inf_ca["iterations"] <= 2 * inf_std["iterations"]
    np.testing.assert_allclose(
        np.asarray(x_ca), np.asarray(x_std), atol=1e-7
    )


# ---------------------------------------------------------------------------
# refusal matrix: explicit conflicts refuse typed, env conflicts fall back
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "kwargs",
    [
        {"fused": True},
        {"rhs_batch": 2},
        {"pipelined": True},
        {"precond": True},
    ],
    ids=["fused", "rhs_batch", "pipelined", "precond"],
)
def test_explicit_sstep_conflicts_refuse_typed(kwargs):
    dA, _, _, _ = _staged()
    with pytest.raises(LoweringConflictError) as ei:
        make_cg_fn(dA, tol=1e-9, maxiter=50, sstep=2, **kwargs)
    assert ei.value.diagnostics["conflict"][0] == "sstep"


def test_explicit_sstep_refuses_under_strict_bits(monkeypatch):
    dA, _, _, _ = _staged()
    monkeypatch.setenv("PA_TPU_STRICT_BITS", "1")
    with pytest.raises(LoweringConflictError):
        make_cg_fn(dA, tol=1e-9, maxiter=50, sstep=2)


def test_explicit_sstep_refuses_with_sdc_defense(monkeypatch):
    dA, _, _, _ = _staged()
    monkeypatch.setenv("PA_TPU_ABFT", "1")
    with pytest.raises(LoweringConflictError) as ei:
        make_cg_fn(dA, tol=1e-9, maxiter=50, sstep=2)
    assert "SDC" in ei.value.diagnostics["conflict"][1]


def test_env_sstep_falls_back_with_note(monkeypatch, capfd):
    """Env-driven PA_TPU_SSTEP meeting an incompatible explicit form:
    the explicit request wins, the builder reverts to the textbook body
    and says so on stderr (the pipelined-SDC precedent)."""
    dA, _, _, _ = _staged()
    monkeypatch.setenv("PA_TPU_SSTEP", "2")
    fn = make_cg_fn(dA, tol=1e-9, maxiter=50, precond=True)
    assert fn is not None
    err = capfd.readouterr().err
    assert "PA_TPU_SSTEP" in err and "does not compose" in err


# ---------------------------------------------------------------------------
# widened plans: both families pass all five checks; depth 1 untouched
# ---------------------------------------------------------------------------


def test_widened_plans_pass_all_five_checks(monkeypatch):
    assert len(pv.PLAN_CHECKS) == 5

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (6, 6))
        rows = A.cols
        ref = pv.referenced_ghosts(A)
        canon_before = pv.canonical_exchange_fingerprint(
            rows.exchanger, rows.partition
        )

        # box family (the default on a cartesian partition)
        wide = device_exchange_plan(rows, depth=2)
        assert isinstance(wide, WidenedBoxExchangePlan)
        assert wide.ghost_depth == 2
        assert pv.verify_plan(wide, referenced=ref) == []
        base = device_exchange_plan(rows)
        # depth 1 is the exact pre-s-step object: the SAME cached
        # instance, and the widened plan shares its slot/round structure
        assert base is device_exchange_plan(rows, depth=1)
        assert not isinstance(base, WidenedBoxExchangePlan)
        assert pv.plan_fingerprint(wide) == pv.plan_fingerprint(base)

        # generic family (PA_TPU_BOX=0 reads the host lids)
        monkeypatch.setenv("PA_TPU_BOX", "0")
        rows._device_plan = {}
        for attr in ("_device_layout", "_box_info"):
            if hasattr(rows, attr):
                delattr(rows, attr)
        gwide = device_exchange_plan(rows, depth=2)
        assert isinstance(gwide, WidenedDeviceExchangePlan)
        assert gwide.ghost_depth == 2
        assert pv.verify_plan(gwide, referenced=ref) == []
        gbase = device_exchange_plan(rows, depth=1)
        assert gbase is device_exchange_plan(rows)
        assert pv.plan_fingerprint(gwide) == pv.plan_fingerprint(gbase)

        # widening staged nothing into the HOST plan: the canonical
        # (layout-independent) fingerprint is untouched
        assert pv.canonical_exchange_fingerprint(
            rows.exchanger, rows.partition
        ) == canon_before
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


# ---------------------------------------------------------------------------
# suggest_s: the spectrum-driven depth policy
# ---------------------------------------------------------------------------


def test_suggest_s_policy_arithmetic():
    # unmeasured spec degrades to the always-safe s=1
    out = telemetry.suggest_s(None, dtype="float64")
    assert out["s"] == 1 and out["policy"] == "unmeasured-default"

    # hopeless conditioning clamps to 1; a perfectly conditioned
    # operator rides the cap
    assert telemetry.suggest_s({"kappa": 1e300})["s"] == 1
    assert telemetry.suggest_s({"kappa": 0.9})["s"] == telemetry.SSTEP_MAX

    # the stability budget is dtype-aware: same kappa, wider eps,
    # shallower depth — and the exact floors are pinned
    assert telemetry.sstep_stability_limit(40.0, "float64") == 7
    assert telemetry.sstep_stability_limit(40.0, "float32") == 2

    out = telemetry.suggest_s(
        {"kappa": 40.0, "rate": 0.5, "samples": 8}, dtype="float64",
        tol=1e-8,
    )
    assert out["policy"] == "largest-stable"
    assert out["s"] == 7 and out["gather_factor"] == 14
    assert len(out["candidates"]) == telemetry.SSTEP_MAX
    assert all(c["gather_factor"] == 2 * c["s"] for c in out["candidates"])
    assert all(
        c["stable"] == (c["s"] <= 7) for c in out["candidates"]
    )
    fc = out["forecast"]
    assert fc["standard_gathers"] == 2 * fc["predicted_iters"]
    assert fc["sstep_gathers"] == math.ceil(fc["predicted_iters"] / 7)
