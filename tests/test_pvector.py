"""L5 tests: PVector algebra, reductions, views, exchange/assembly.

Mirrors the reference conformance coverage
(reference: test/test_interfaces.jl:501-643), re-derived 0-based.
"""
import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa


def parts4():
    return pa.sequential.get_part_ids(4)


def ghosted_rows():
    parts = parts4()
    noids = pa.map_parts(lambda p: 3, parts)
    hid_gid = pa.map_parts(lambda p: np.array([(3 * (p + 1)) % 12]), parts)
    hid_part = pa.map_parts(lambda p: np.array([(p + 1) % 4]), parts)
    return pa.variable_partition(parts, noids, hid_to_gid=hid_gid, hid_to_part=hid_part)


def test_constructors_and_props():
    rows = ghosted_rows()
    v = pa.PVector.full(2.5, rows)
    assert len(v) == 12 and v.dtype == np.float64
    assert [len(x) for x in v.owned_values] == [3, 3, 3, 3]
    assert [len(x) for x in v.ghost_values] == [1, 1, 1, 1]
    u = v.similar()
    assert u.rows is rows
    w = pa.pvector(rows)
    assert w.rows is rows
    z = pa.pvector(1.0, rows)
    assert z.sum() == 12.0


def test_no_random_access():
    v = pa.PVector.full(0.0, ghosted_rows())
    with pytest.raises(NotImplementedError):
        v[3]


def test_algebra_and_reductions():
    rows = ghosted_rows()
    a = pa.PVector(
        pa.map_parts(lambda i: i.lid_to_gid.astype(float), rows.partition), rows
    )
    b = pa.PVector.full(1.0, rows)
    c = a + b
    assert c.sum() == sum(range(12)) + 12
    d = 2.0 * a - b / 1.0
    assert d.sum() == 2 * sum(range(12)) - 12
    assert (-a).sum() == -sum(range(12))
    assert a.dot(b) == sum(range(12))
    assert a.norm() == pytest.approx(np.sqrt(sum(g * g for g in range(12))))
    assert a.norm(1) == pytest.approx(sum(range(12)))
    assert a.maximum() == 11.0 and a.minimum() == 0.0
    assert a.any(lambda x: x > 10.0) and not a.all(lambda x: x > 0.0)
    assert a == a.copy()
    assert not (a == b)


def test_axpy_and_fill():
    rows = ghosted_rows()
    x = pa.PVector.full(3.0, rows)
    y = pa.PVector.full(1.0, rows)
    y.axpy(2.0, x)
    assert y.sum() == 12 * 7.0
    y.fill(0.0)
    assert y.sum() == 0.0


def test_zip_map_mismatched_rows_owned_only():
    rows1 = ghosted_rows()
    rows2 = ghosted_rows()  # equal partition, different object
    a = pa.PVector.full(1.0, rows1)
    b = pa.PVector.full(2.0, rows2)
    c = a + b  # owned-only path
    assert c.rows is rows1
    assert c.sum() == 36.0
    for i, v in zip(c.rows.partition, c.values):
        assert np.all(np.asarray(v)[i.hid_to_lid] == 0.0)


def test_coo_constructor_accumulates():
    parts = parts4()
    rows = pa.uniform_partition(parts, 8)  # 2 owned per part
    # every part contributes 1.0 twice to its first owned gid
    I = pa.map_parts(lambda p: np.array([2 * p, 2 * p]), parts)
    V = pa.map_parts(lambda p: np.array([1.0, 1.0]), parts)
    v = pa.PVector.from_coo(I, V, rows, ids="global")
    assert v.sum() == 8.0
    g = pa.gather_pvector(v)
    assert list(g) == [2.0, 0.0] * 4


def test_coo_constructor_builds_rows_from_n():
    parts = parts4()
    # part p scatters into gid (2p+2) % 8 — not owned by p
    I = pa.map_parts(lambda p: np.array([(2 * p + 2) % 8]), parts)
    V = pa.map_parts(lambda p: np.array([float(p + 1)]), parts)
    v = pa.PVector.from_coo(I, V, 8, ids="global")
    assert v.rows.ghost
    v.assemble()
    g = pa.gather_pvector(v)
    assert list(g) == [4.0, 0.0, 1.0, 0.0, 2.0, 0.0, 3.0, 0.0]


def test_exchange_and_assemble():
    rows = ghosted_rows()
    v = pa.PVector(
        pa.map_parts(
            lambda i: np.where(i.lid_to_part == i.part, i.lid_to_gid.astype(float), -1.0),
            rows.partition,
        ),
        rows,
    )
    v.exchange()
    for i, vals in zip(rows.partition, v.values):
        assert np.array_equal(np.asarray(vals), i.lid_to_gid.astype(float))
    # assembly: ghosts add into owners then zero out
    w = pa.PVector.full(1.0, rows)
    w.assemble()
    for i, vals in zip(rows.partition, w.values):
        vals = np.asarray(vals)
        assert np.all(vals[i.hid_to_lid] == 0.0)
    # each part's first owned gid is ghosted by its predecessor -> 2.0
    g = pa.gather_pvector(w)
    assert list(g) == [2.0, 1.0, 1.0] * 4


def test_async_exchange_overlap_window():
    rows = ghosted_rows()
    v = pa.PVector(
        pa.map_parts(
            lambda i: np.where(i.lid_to_part == i.part, i.lid_to_gid.astype(float), -1.0),
            rows.partition,
        ),
        rows,
    )
    t = v.async_exchange()
    # ghosts are NOT yet updated: the unpack is deferred to wait()
    assert all(np.asarray(vals)[i.hid_to_lid[0]] == -1.0 for i, vals in zip(rows.partition, v.values))
    t.wait()
    for i, vals in zip(rows.partition, v.values):
        assert np.array_equal(np.asarray(vals), i.lid_to_gid.astype(float))


def test_global_view_write_and_guard():
    rows = ghosted_rows()
    v = pa.PVector.full(0.0, rows)
    gv = pa.global_view(v)

    def _write(part, view, iset):
        gids = iset.lid_to_gid[:2]
        view[gids] = [10.0, 20.0]
        view.add_at(gids[:1], [5.0])
        assert view[int(gids[0])] == 15.0
        with pytest.raises(AssertionError):
            view[np.array([(int(iset.lid_to_gid[0]) + 6) % 12])]  # non-local gid

    pa.map_parts(_write, pa.get_part_ids(rows.partition), gv, rows.partition)


def test_local_view_reindex():
    parts = parts4()
    rows = pa.uniform_partition(parts, 8)
    ghosted = pa.add_gids(
        rows, pa.map_parts(lambda p: np.array([(2 * p + 2) % 8]), parts)
    )
    v = pa.PVector(
        pa.map_parts(lambda i: i.lid_to_gid.astype(float) * 10, rows.partition), rows
    )
    lv = pa.local_view(v, ghosted)

    def _check(part, view, iset):
        # owned lids of the ghosted range resolve into the parent
        assert view[0] == iset.lid_to_gid[0] * 10
        # the ghost lid is missing from the parent -> reads as 0, write guarded
        hlid = int(iset.hid_to_lid[0])
        assert view[hlid] == 0.0
        with pytest.raises(AssertionError):
            view[np.array([hlid])] = [1.0]

    pa.map_parts(_check, pa.get_part_ids(rows.partition), lv, ghosted.partition)


def test_copy_into_across_partitions():
    parts = parts4()
    rows = pa.uniform_partition(parts, 8)
    ghosted = pa.add_gids(
        rows, pa.map_parts(lambda p: np.array([(2 * p + 2) % 8]), parts)
    )
    src = pa.PVector(
        pa.map_parts(lambda i: i.lid_to_gid.astype(float), rows.partition), rows
    )
    dst = pa.PVector.full(-1.0, ghosted)
    src.copy_into(dst)
    for i, vals in zip(ghosted.partition, dst.values):
        vals = np.asarray(vals)
        assert np.array_equal(vals[i.oid_to_lid], i.oid_to_gid.astype(float))
        assert np.all(vals[i.hid_to_lid] == -1.0)  # ghosts untouched
