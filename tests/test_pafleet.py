"""pafleet — the replicated gate fleet
(`partitionedarrays_jl_tpu.frontdoor.fleet` + `Gate.adopt` + journal
retention + the fleet-aware `http_solve`).

The contracts pinned here:

* **Rendezvous routing** — `route(tenant, replicas)` is deterministic
  from any client (no shared state) and minimally disruptive: on
  membership change only the tenants whose top-ranked replica changed
  move; a dead replica's adopter is unique and deterministic.
* **Lease files** — CRC'd canonical JSON published by atomic
  tmp+rename: round-trips verbatim, and a torn or bit-flipped lease
  raises the typed `LeaseCorruptError` — corruption refuses takeover
  instead of triggering a false one.
* **Journal retention** (``PA_GATE_JOURNAL_KEEP``) — `prune` drops
  only epochs a LATER ``recovered`` record proves replayed; dropping
  an unrecovered epoch raises the typed `JournalRetentionError` and
  unlinks nothing. A gate restarting under the knob compacts live
  requests into the current epoch first, so a SECOND restart recovers
  them from the retained set alone; terminal history ages out (the
  documented idempotency-replay horizon).
* **Client resilience (satellite bugfix)** — `http_solve(retries=N)`
  now retries a 503 `AdmissionRejected` with exponential backoff
  under the same ``timeout_s`` budget it already used for 429 (the
  prior behavior returned the raw 503 payload on the first try);
  ``retries=0`` stays one-shot. A 307 shed-forward is FOLLOWED (hop
  cap 4) — the resubmit and all subsequent polls go to the peer.

The cross-replica failover/forward/torn-lease rows live in
tests/test_chaos_matrix.py; the full kill -9 fleet drill (subprocess,
SIGKILL one replica mid-load) runs under the ``slow`` marker via
``tools/pafleet.py --drill``.
"""
import json
import os
import urllib.error

import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu import telemetry
from partitionedarrays_jl_tpu.frontdoor import (
    Gate,
    JournalRetentionError,
    LeaseCorruptError,
    RequestJournal,
    http_solve,
    journal_keep,
    read_lease,
    rendezvous_rank,
    route,
    write_lease,
)
from partitionedarrays_jl_tpu.models import (
    assemble_poisson,
    gather_pvector,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _poisson(grid=(8, 8)):
    return pa.prun(
        lambda parts: assemble_poisson(parts, grid), pa.sequential, (2, 2)
    )


def _counter(name, labels=None):
    return telemetry.registry().counter(name, labels=labels).value


# ---------------------------------------------------------------------------
# rendezvous routing
# ---------------------------------------------------------------------------


def test_rendezvous_deterministic_and_minimal_movement():
    """Routing is a pure function of (key, membership): stable across
    calls and input orderings; growing the fleet moves ONLY tenants
    captured by the new replica; shrinking it moves ONLY the dead
    replica's tenants; the adopter of a dead replica is rank[0] among
    survivors — unique, no election."""
    reps = ["g0", "g1", "g2"]
    tenants = [f"tenant-{i}" for i in range(200)]
    owners = {t: route(t, reps) for t in tenants}
    assert owners == {t: route(t, list(reversed(reps))) for t in tenants}
    # every replica actually owns someone (sha256 spreads the keys)
    assert {owners[t] for t in tenants} == set(reps)
    # growth: a tenant either stays put or moves TO the new replica
    for t in tenants:
        after = route(t, reps + ["g3"])
        assert after == owners[t] or after == "g3", (t, owners[t], after)
    # shrink: only g1's tenants move
    for t in tenants:
        after = route(t, ["g0", "g2"])
        if owners[t] != "g1":
            assert after == owners[t], (t, owners[t], after)
    # the dead replica's adopter is deterministic and total-ordered
    ranked = rendezvous_rank("g1", ["g0", "g2"])
    assert sorted(ranked) == ["g0", "g2"]
    assert ranked == rendezvous_rank("g1", ["g2", "g0"])


# ---------------------------------------------------------------------------
# lease files
# ---------------------------------------------------------------------------


def test_lease_roundtrip_torn_and_crc_flip_typed(tmp_path):
    path = str(tmp_path / "lease.json")
    assert read_lease(path) is None, "absent lease reads as None"
    write_lease(path, "g0", depth=3)
    got = read_lease(path)
    assert got["replica"] == "g0" and got["depth"] == 3
    assert got["wall"] > 0
    raw = open(path).read()
    # torn write (crash mid-write straight to the final name): typed
    open(path, "w").write(raw[: len(raw) // 2])
    with pytest.raises(LeaseCorruptError, match="unparseable"):
        read_lease(path)
    # valid JSON whose payload no longer matches its CRC: typed too
    rec = json.loads(raw)
    rec["depth"] = 999
    open(path, "w").write(json.dumps(rec))
    with pytest.raises(LeaseCorruptError, match="CRC"):
        read_lease(path)
    # a fresh heartbeat heals the file
    write_lease(path, "g0", depth=0)
    assert read_lease(path)["depth"] == 0


# ---------------------------------------------------------------------------
# journal retention (PA_GATE_JOURNAL_KEEP)
# ---------------------------------------------------------------------------


def test_journal_keep_parsing(monkeypatch):
    for raw, want in (
        (None, None), ("", None), ("0", None), ("-3", None),
        ("junk", None), ("1", 1), ("2", 2), ("7", 7),
    ):
        if raw is None:
            monkeypatch.delenv("PA_GATE_JOURNAL_KEEP", raising=False)
        else:
            monkeypatch.setenv("PA_GATE_JOURNAL_KEEP", raw)
        assert journal_keep() == want, (raw, want)


def test_prune_refuses_unrecovered_epoch_then_prunes(tmp_path):
    jd = str(tmp_path / "j")
    j1 = RequestJournal(jd, fsync=False)
    j1.append("admitted", rid="r1-0", tenant="t")
    j1.close()
    j2 = RequestJournal(jd, fsync=False)  # epoch 2
    before = sorted(j2.segments())
    # epoch 1 has no later `recovered` record: live state, typed refusal
    with pytest.raises(JournalRetentionError, match="epoch"):
        j2.prune(1)
    assert sorted(j2.segments()) == before, "refusal unlinks NOTHING"
    # a recovery in this epoch proves epoch 1 was folded in
    j2.append("recovered", completed=0, requeued=1)
    p0 = _counter("journal.pruned")
    ev0 = telemetry.counter("events.journal_pruned")
    pruned = j2.prune(1)
    assert pruned, "epoch 1's segments must be dropped"
    epochs = {
        int(os.path.basename(s).split("-")[1]) for s in j2.segments()
    }
    assert epochs == {j2.epoch}
    assert _counter("journal.pruned") == p0 + len(pruned)
    assert telemetry.counter("events.journal_pruned") == ev0 + 1
    # idempotent: nothing left to prune
    assert j2.prune(1) == []
    j2.close()


def test_gate_retention_recovers_live_from_retained_set(
    tmp_path, monkeypatch
):
    """Under ``PA_GATE_JOURNAL_KEEP=1`` a recovering gate compacts
    live requests into the current epoch BEFORE pruning the old ones,
    so a second crash-recovery needs only the retained set; terminal
    history ages out (the documented idempotency-replay horizon)."""

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        jd = str(tmp_path / "j")
        g1 = Gate(journal_dir=jd)
        g1.register("t", A, kmax=4)
        hdone = g1.submit("t", b, x0=x0, tol=1e-9, tag="old-done")
        g1.drain()
        hdone.result()
        hq = g1.submit("t", b, x0=x0, tol=1e-9, tag="live-queued")
        # ---- crash; restart under retention ----
        monkeypatch.setenv("PA_GATE_JOURNAL_KEEP", "1")
        ev0 = telemetry.counter("events.journal_pruned")
        g2 = Gate(journal_dir=jd)
        g2.register("t", A, kmax=4)
        summary = g2.recover()
        assert summary["completed"] == 1 and summary["requeued"] == 1
        assert telemetry.counter("events.journal_pruned") == ev0 + 1
        epochs = {
            int(os.path.basename(s).split("-")[1])
            for s in g2.journal.segments()
        }
        assert epochs == {g2.journal.epoch}, (
            "only the current epoch survives KEEP=1"
        )
        g2.drain()
        x2 = gather_pvector(g2.handle(hq.rid).result()[0])
        # ---- second crash: only the retained set exists on disk ----
        g3 = Gate(journal_dir=jd)
        g3.register("t", A, kmax=4)
        s3 = g3.recover()
        assert s3["completed"] == 1, s3
        # a recovered terminal serves its RECORDED result (gathered)
        np.testing.assert_array_equal(
            np.asarray(g3.handle(hq.rid).result()[0]), x2
        )
        # the pre-retention terminal aged out with its epoch
        assert g3.handle(hdone.rid) is None, (
            "terminal history beyond KEEP is the documented horizon"
        )
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


# ---------------------------------------------------------------------------
# http_solve: the 503 retry bugfix + 307 shed-forward follow
# (injected failures — no real server; idiom shared with test_padur)
# ---------------------------------------------------------------------------


class _FakeResponse:
    def __init__(self, status, payload):
        self.status = status
        self._payload = payload

    def read(self):
        return json.dumps(self._payload).encode()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class _FakeHTTPError(urllib.error.HTTPError):
    def __init__(self, url, code, payload, headers=None):
        import email.message

        msg = email.message.Message()
        for k, v in (headers or {}).items():
            msg[k] = str(v)
        super().__init__(url, code, "err", msg, None)
        self._payload = payload

    def read(self):
        return json.dumps(self._payload).encode()


_DONE = {"id": "r1-0", "state": "done", "x": [1.0, 2.0],
         "info": {"converged": True, "iterations": 3,
                  "status": "converged"}}


def test_http_solve_retries_503_with_backoff():
    """THE satellite bugfix: a 503 `AdmissionRejected` (queue-full /
    draining backpressure — no Retry-After hint) retries with
    exponential backoff under ``timeout_s`` instead of returning the
    raw error payload on the first try."""
    sleeps = []
    script = [
        _FakeHTTPError("u", 503, {"error": "AdmissionRejected",
                                  "message": "queue full"}),
        _FakeHTTPError("u", 503, {"error": "AdmissionRejected",
                                  "message": "queue full"}),
        _FakeResponse(202, {"id": "r1-0", "state": "gate-queued"}),
        _FakeResponse(200, _DONE),
    ]

    def opener(req):
        ev = script.pop(0)
        if isinstance(ev, Exception):
            raise ev
        return ev

    out = http_solve(
        "http://fake", "t", [0.0, 0.0], tol=1e-9, retries=3,
        opener=opener, sleep=sleeps.append, poll_s=0.0, timeout_s=60.0,
    )
    assert out["state"] == "done" and out["x"] == [1.0, 2.0]
    assert not script, "every scripted exchange must be consumed"
    # no server hint -> exponential client backoff: 0.05, then 0.1
    assert sleeps[:2] == [0.05, 0.1], sleeps


def test_http_solve_503_exhausts_retries_typed():
    """Past ``retries`` the typed payload surfaces (never an endless
    loop), and ``retries=0`` keeps the one-shot contract unchanged."""
    def opener_503(req):
        raise _FakeHTTPError(
            "u", 503, {"error": "AdmissionRejected", "message": "full"}
        )

    out = http_solve("http://fake", "t", [0.0], retries=2,
                     opener=opener_503, sleep=lambda s: None,
                     timeout_s=60.0)
    assert out["http_status"] == 503
    assert out["error"] == "AdmissionRejected"
    out0 = http_solve(
        "http://fake", "t", [0.0], opener=opener_503,
        sleep=lambda s: (_ for _ in ()).throw(
            AssertionError("retries=0 must not sleep")),
    )
    assert out0["http_status"] == 503


def test_http_solve_follows_shed_forward_307():
    """A fleet shed-forward (307 + ``Location``) is followed
    independent of ``retries``: the submit reposts the identical body
    to the peer and every subsequent poll goes to the peer too."""
    urls = []
    script = [
        _FakeHTTPError(
            "u", 307,
            {"error": "LoadShedded", "forwarded_to": "http://peer:9"},
            {"Location": "http://peer:9/v1/solve", "Retry-After": "1"},
        ),
        _FakeResponse(202, {"id": "g1-r1-0", "state": "gate-queued"}),
        _FakeResponse(200, dict(_DONE, id="g1-r1-0")),
    ]
    bodies = []

    def opener(req):
        urls.append(req.full_url)
        if req.data is not None:
            bodies.append(json.loads(req.data))
        ev = script.pop(0)
        if isinstance(ev, Exception):
            raise ev
        return ev

    out = http_solve(
        "http://fake", "t", [0.0, 0.0], tol=1e-9,
        idempotency_key="fwd-key", opener=opener,
        sleep=lambda s: None, poll_s=0.0,
    )
    assert out["state"] == "done" and not script
    assert urls == [
        "http://fake/v1/solve",          # the shedding replica
        "http://peer:9/v1/solve",        # the forwarded resubmit
        "http://peer:9/v1/solve/g1-r1-0",  # polls follow the peer
    ]
    # the peer sees the IDENTICAL body: same idempotency key, so a
    # forwarded duplicate can never double-solve
    assert bodies[0] == bodies[1]
    assert bodies[1]["idempotency_key"] == "fwd-key"


def test_http_solve_redirect_hop_cap():
    """A thrashing fleet that ping-pongs redirects is bounded: after 4
    hops the typed 307 payload surfaces instead of looping."""
    calls = []

    def opener(req):
        calls.append(req.full_url)
        raise _FakeHTTPError(
            "u", 307, {"error": "LoadShedded"},
            {"Location": "http://peer:9/v1/solve"},
        )

    out = http_solve("http://fake", "t", [0.0], opener=opener,
                     sleep=lambda s: None)
    assert out["http_status"] == 307
    assert len(calls) == 5, "initial POST + 4 followed hops, no more"


# ---------------------------------------------------------------------------
# CLI: the tier-1 smoke + the subprocess drill
# ---------------------------------------------------------------------------


def _load_pafleet():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "pafleet", os.path.join(REPO, "tools", "pafleet.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_pafleet_check_smoke(capsys):
    """tools/pafleet.py --check: routing + failover adoption +
    shed-forward + retention, in-process (tier-1)."""
    pafleet = _load_pafleet()
    rc = pafleet.main(["--check"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "pafleet --check: OK" in out


@pytest.mark.slow
def test_fleet_drill_sigkill_failover_full(capsys):
    """THE acceptance drill: two serving replicas under concurrent
    `http_solve` load, SIGKILL the tenant's owner mid-flight, and the
    survivor adopts its journal — every admitted request completes
    bitwise-equal to its solo solve or fails typed, none duplicated,
    one stitched trace per request across the replica hop, per-class
    SLO attainment reported from the survivor
    (tools/pafleet.py --drill)."""
    pafleet = _load_pafleet()
    rc = pafleet.main(["--drill"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "pafleet --drill: OK" in out
