"""Resilience layer: seeded fault injection (parallel/faults.py), typed
health guards (parallel/health.py), and checkpoint-based auto-restart
(`solve_with_recovery` / `resume_solve`).

The load-bearing contract (ISSUE 1 acceptance): a corrupted halo payload
at iteration k is detected within one solver iteration, the solve
auto-restarts from the last checkpoint, and the recovered run's answer
matches the fault-free run — np.allclose always, BITWISE on the same
partition (the host checkpoints carry the full recurrence state, so a
resume replays the exact trajectory). Everything runs on the sequential
backend under JAX_PLATFORMS=cpu (conftest); the device variants use the
8-device CPU mesh TPUBackend and skip when it cannot be built.
"""
import os

import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu.models import (
    assemble_poisson,
    cg,
    gather_pvector,
    resume_solve,
    solve_with_recovery,
)
from partitionedarrays_jl_tpu.parallel.checkpoint import (
    SolverCheckpointer,
    load_solver_state,
)
from partitionedarrays_jl_tpu.parallel.faults import (
    FaultClause,
    FaultSpec,
    active_fault_state,
    faults_active,
    inject_faults,
)
from partitionedarrays_jl_tpu.parallel.health import (
    ControllerLostError,
    ExchangeTimeoutError,
    NonFiniteError,
    SolverBreakdownError,
    SolverStagnationError,
    retry_with_backoff,
)


def _setup(parts, ns=(8, 8)):
    return assemble_poisson(parts, ns)


# ---------------------------------------------------------------------------
# spec grammar
# ---------------------------------------------------------------------------


def test_fault_spec_grammar():
    spec = FaultSpec.parse(
        "nan@part=1,call=3; bitflip@part=*,after=2,prob=0.25;"
        "drop@part=2,call=5; delay@seconds=0.5; controller@call=7"
    )
    kinds = [c.kind for c in spec.clauses]
    assert kinds == ["nan", "bitflip", "drop", "delay", "controller"]
    assert spec.clauses[0] == FaultClause("nan", part=1, call=3)
    assert spec.clauses[1].part is None and spec.clauses[1].after == 2
    assert spec.clauses[1].prob == 0.25
    assert spec.clauses[3].seconds == 0.5
    # clause matching: exact call, open call, after-threshold
    assert spec.clauses[0].matches(3, 1) and not spec.clauses[0].matches(4, 1)
    assert not spec.clauses[0].matches(3, 0)
    assert spec.clauses[1].matches(2, 0) and spec.clauses[1].matches(9, 3)
    assert not spec.clauses[1].matches(1, 0)


def test_fault_spec_rejects_garbage():
    with pytest.raises(ValueError):
        FaultSpec.parse("meteor@part=1")
    with pytest.raises(ValueError):
        FaultSpec.parse("nan@part")
    with pytest.raises(ValueError):
        FaultSpec.parse("nan@color=red")


def test_fault_spec_bit_key():
    spec = FaultSpec.parse("bitflip@part=1,call=3,bit=51")
    assert spec.clauses[0].bit == 51
    assert FaultSpec.parse("bitflip@part=1").clauses[0].bit is None


def test_corruption_is_shape_polymorphic_and_seed_stable_across_k():
    """PR-3 block exchanges ship (slots, K) slabs; the chaos harness's
    entry selection must corrupt the SAME wire slots for any K, hitting
    one word per selected slot (column 0) — pinned here for K in {1, 4}
    with a fixed seed, for both corruption kinds."""
    from partitionedarrays_jl_tpu.parallel.faults import _corrupt_array

    L = 16
    base = np.linspace(1.0, 2.0, L)

    def corrupted_slots(kind, k, bit=None):
        rng = np.random.default_rng(123)
        a = (
            base.copy()
            if k == 1
            else np.tile(base[:, None], (1, k)).copy()
        )
        ref = a.copy()
        n = _corrupt_array(a, kind, 0.25, rng, bit=bit)
        diff = a != ref
        if k > 1:
            # only column 0 of a selected slot is touched — one wire
            # word, exactly what the K=1 payload of the same spec flips
            assert not diff[:, 1:].any()
            hit = set(np.nonzero(diff[:, 0] | ~np.isfinite(a[:, 0]))[0])
        else:
            hit = set(np.nonzero(diff | ~np.isfinite(a))[0])
        assert n == len(hit)
        return hit, (a[sorted(hit), 0] if k > 1 else a[sorted(hit)])

    for kind, bit in (("nan", None), ("bitflip", None), ("bitflip", 51)):
        s1, v1 = corrupted_slots(kind, 1, bit)
        s4, v4 = corrupted_slots(kind, 4, bit)
        assert s1 == s4 and len(s1) >= 1, (kind, s1, s4)
        np.testing.assert_array_equal(v1, v4)
    # the fixed seed's selection itself is pinned (seed-stability):
    s, _ = corrupted_slots("bitflip", 4, 51)
    assert s == {1, 2, 3, 4, 11, 13}


def test_high_bit_flip_is_large_but_finite():
    """bit=51 on f64 flips the mantissa MSB: a ~0.5 relative error that
    stays FINITE — the dangerous silent-corruption model the SDC layer
    exists for (tests/test_abft.py pins the end-to-end story)."""
    from partitionedarrays_jl_tpu.parallel.faults import _corrupt_array

    rng = np.random.default_rng(0)
    a = np.full(8, 1.5)
    _corrupt_array(a, "bitflip", 1.0, rng, bit=51)
    assert np.isfinite(a).all()
    rel = np.abs(a - 1.5) / 1.5
    assert (rel[rel > 0] > 0.2).all()


def test_env_var_activation(monkeypatch):
    assert not faults_active()
    monkeypatch.setenv("PA_FAULT_SPEC", "nan@part=0,call=0")
    monkeypatch.setenv("PA_FAULT_SEED", "7")
    assert faults_active()
    st = active_fault_state()
    assert st.seed == 7 and st.spec.clauses[0].kind == "nan"
    # the state (and its call counter) is cached per env value
    assert active_fault_state() is st
    monkeypatch.delenv("PA_FAULT_SPEC")
    assert not faults_active() and active_fault_state() is None


# ---------------------------------------------------------------------------
# fault classes on the sequential backend
# ---------------------------------------------------------------------------


def test_corrupted_exchange_detected_within_one_iteration():
    """NaN-poisoned halo payload at a known exchange call -> the solver's
    free scalar guard raises a typed NonFiniteError on THAT iteration,
    with per-part diagnostics naming the poisoned vectors."""
    k = 9

    def driver(parts):
        A, b, x_exact, x0 = _setup(parts)
        # warm run: builds + caches the exchanger plans, so the faulted
        # run's exchange calls map 1:1 onto solver iterations (call 0 =
        # the initial residual's A@x0, call i = iteration i's A@p)
        _, info_clean = cg(A, b, x0=x0, tol=1e-9)
        assert info_clean["converged"] and info_clean["iterations"] > k
        with inject_faults(f"nan@part=1,call={k}", seed=3) as st:
            with pytest.raises(NonFiniteError) as ei:
                cg(A, b, x0=x0, tol=1e-9)
        assert abs(ei.value.diagnostics["iteration"] - k) <= 1
        assert ei.value.diagnostics["parts"], "no per-part diagnostics"
        assert [e["kind"] for e in st.events] == ["nan"]
        assert st.events[0]["call"] == k and st.events[0]["part"] == 1
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_exchange_level_validation(monkeypatch):
    """PA_HEALTH_EXCHANGE=1: the receiving side of the exchange itself
    rejects a non-finite payload, reporting receiver part and sending
    neighbor — one reduction earlier than the solver guard."""
    monkeypatch.setenv("PA_HEALTH_EXCHANGE", "1")

    def driver(parts):
        rows = pa.prange(parts, (8, 8), pa.with_ghost)
        v = pa.PVector.full(1.0, rows)
        v.exchange()  # warm: plan-building exchanges carry int payloads
        with inject_faults("nan@part=0,call=0", seed=0):
            with pytest.raises(NonFiniteError) as ei:
                v.exchange()
        parts_diag = ei.value.diagnostics["parts"]
        assert parts_diag, "no receiver diagnostics"
        # part 0's poisoned payload shows up as from_parts == {0: n}
        assert any(
            0 in d["from_parts"] for d in parts_diag.values()
        )
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_bitflip_is_silent_but_recorded():
    """A mantissa bitflip stays finite — the point of the fault class is
    that finiteness guards canNOT see it (silent corruption); the
    injection record and the changed answer witness it."""

    def driver(parts):
        A, b, x_exact, x0 = _setup(parts)
        x_clean, _ = cg(A, b, x0=x0, tol=1e-9)
        with inject_faults("bitflip@part=1,call=4,prob=1.0", seed=11) as st:
            x_flip, info = cg(A, b, x0=x0, tol=1e-9)
        assert any(e["kind"] == "bitflip" for e in st.events)
        assert np.isfinite(gather_pvector(x_flip)).all()
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_dropped_part_triggers_timeout_path():
    def driver(parts):
        A, b, x_exact, x0 = _setup(parts)
        with inject_faults("drop@part=2,call=5", seed=0) as st:
            with pytest.raises(ExchangeTimeoutError) as ei:
                cg(A, b, x0=x0, tol=1e-9)
        assert ei.value.diagnostics["missing_parts"] == [2]
        assert st.events[0] == {"kind": "drop", "call": 5, "part": 2}
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_controller_failure_is_typed_and_survivable():
    def driver(parts):
        A, b, x_exact, x0 = _setup(parts)
        with inject_faults("controller@call=6", seed=0):
            with pytest.raises(ControllerLostError):
                cg(A, b, x0=x0, tol=1e-9)
        # ControllerLostError subclasses SolverHealthError, so the
        # recovery driver treats it as survivable-by-restart
        with inject_faults("controller@call=6", seed=0):
            x, info = solve_with_recovery(
                A, b, method="cg", x0=x0, tol=1e-9
            )
        assert info["restarts"] == 1 and info["converged"]
        assert info["failures"][0]["type"] == "ControllerLostError"
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_controller_clause_outside_grid_is_inert():
    """The spec grammar's promise — an id outside this run's part grid
    matches nothing — must hold for `controller` clauses too (it used to
    be checked only for drop/delay): a spec written for a larger mesh
    must not kill a smaller run."""

    def driver(parts):
        A, b, x_exact, x0 = _setup(parts)
        with inject_faults("controller@part=9,call=3", seed=0) as st:
            x, info = cg(A, b, x0=x0, tol=1e-9)
        assert info["converged"]
        assert not st.events  # the clause fired nothing
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_delay_fault_records_event():
    def driver(parts):
        A, b, x_exact, x0 = _setup(parts)
        with inject_faults("delay@call=2,seconds=0.0", seed=0) as st:
            x, info = cg(A, b, x0=x0, tol=1e-9)
        assert info["converged"]  # a slow host is not an error
        assert st.events[0]["kind"] == "delay"
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


# ---------------------------------------------------------------------------
# health guards beyond injection
# ---------------------------------------------------------------------------


def test_breakdown_raises_typed_error():
    """p'Ap == 0 on an indefinite operator is a typed
    SolverBreakdownError (not a strippable assert): diag(1, -1) with
    b = (1, 1) breaks down on the very first iteration."""

    def driver(parts):
        rows = pa.prange(parts, 2)
        g = pa.map_parts(lambda i: np.asarray(i.oid_to_gid), rows.partition)
        V = pa.map_parts(lambda gi: np.where(gi == 0, 1.0, -1.0), g)
        A = pa.PSparseMatrix.from_coo(g, g, V, rows, rows, ids="global")
        b = pa.PVector.full(1.0, rows)
        with pytest.raises(SolverBreakdownError) as ei:
            cg(A, b, tol=1e-12)
        assert ei.value.diagnostics["iteration"] == 0
        return True

    assert pa.prun(driver, pa.sequential, 1)


def test_stagnation_detector_unit():
    from partitionedarrays_jl_tpu.parallel.health import StagnationDetector

    os.environ["PA_HEALTH_STAGNATION_WINDOW"] = "4"
    try:
        det = StagnationDetector("unit")
        for i, r in enumerate([10.0, 5.0, 2.0, 1.0]):  # improving: no trip
            det.update(r, i)
        with pytest.raises(SolverStagnationError) as ei:
            for i in range(4, 9):
                det.update(0.999, i)  # flat: trips after the window
        assert ei.value.diagnostics["window"] == 4
    finally:
        del os.environ["PA_HEALTH_STAGNATION_WINDOW"]


def test_stagnation_guard_opt_in(monkeypatch):
    """PA_HEALTH_STAGNATION=1 turns a flat-lining residual into a typed
    error instead of a silent maxiter burn. The fixture: cg WITHOUT the
    boundary-imposing x0 runs on the nonsymmetric-coupled Dirichlet
    system and plateaus far above tol."""
    monkeypatch.setenv("PA_HEALTH_STAGNATION", "1")
    monkeypatch.setenv("PA_HEALTH_STAGNATION_WINDOW", "8")

    def driver(parts):
        A, b, x_exact, x0 = _setup(parts)
        with pytest.raises(SolverStagnationError) as ei:
            cg(A, b, tol=1e-12)
        assert ei.value.diagnostics["window"] == 8
        assert ei.value.diagnostics["best_residual"] > 1e-12
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_retry_with_backoff():
    calls = []
    sleeps = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    assert (
        retry_with_backoff(
            flaky, attempts=4, backoff=0.25, sleep=sleeps.append,
            describe="flaky-io",
        )
        == "ok"
    )
    assert len(calls) == 3 and sleeps == [0.25, 0.5]

    with pytest.raises(OSError):
        retry_with_backoff(
            lambda: (_ for _ in ()).throw(OSError("hard")),
            attempts=2, backoff=0.0, sleep=sleeps.append,
        )
    # non-listed exceptions pass straight through, no retry burn
    boom = []

    def wrong_type():
        boom.append(1)
        raise KeyError("x")

    with pytest.raises(KeyError):
        retry_with_backoff(wrong_type, attempts=5, backoff=0.0)
    assert len(boom) == 1


def test_retry_backoff_zero_is_true_zero():
    """backoff=0 means NO sleeping, ever — the second delay used to
    silently become 0.1 s via the doubling bootstrap, so callers asking
    for no backoff (tests, in-process service retries) still slept."""
    sleeps = []
    with pytest.raises(OSError):
        retry_with_backoff(
            lambda: (_ for _ in ()).throw(OSError("hard")),
            attempts=4, backoff=0.0, sleep=sleeps.append,
        )
    assert sleeps == [0.0, 0.0, 0.0]


def test_retry_give_up_abandons_remaining_attempts():
    """``give_up`` (the solve service's deadline hook): once the
    predicate trips, the remaining attempts are abandoned and the last
    failure re-raises immediately — no sleep, no further calls."""
    calls, sleeps = [], []

    def failing():
        calls.append(1)
        raise OSError("down")

    with pytest.raises(OSError):
        retry_with_backoff(
            failing, attempts=10, backoff=0.0, sleep=sleeps.append,
            give_up=lambda: len(calls) >= 3,
        )
    assert len(calls) == 3  # not 10: give_up cut the budget
    assert sleeps == [0.0, 0.0]  # and never slept after the trip
    # a never-true predicate changes nothing
    calls.clear(), sleeps.clear()
    with pytest.raises(OSError):
        retry_with_backoff(
            failing, attempts=2, backoff=0.0, sleep=sleeps.append,
            give_up=lambda: False,
        )
    assert len(calls) == 2


def test_retry_jitter_seeded_and_decorrelated(monkeypatch):
    """PA_RETRY_JITTER (or jitter_seed=): seeded decorrelated jitter —
    delays are drawn from U[base, 3*previous] (capped), reproducible
    per seed, different across seeds (co-failing ranks spread out),
    and OFF by default (the classic deterministic doubling)."""

    def always_fail():
        raise OSError("transient")

    def delays(**kw):
        sleeps = []
        with pytest.raises(OSError):
            retry_with_backoff(
                always_fail, attempts=5, backoff=0.25,
                sleep=sleeps.append, **kw,
            )
        return sleeps

    # off by default: deterministic doubling
    assert delays() == [0.25, 0.5, 1.0, 2.0]
    a = delays(jitter_seed=7)
    b = delays(jitter_seed=7)
    c = delays(jitter_seed=8)
    assert a == b, "same seed must reproduce the same delay sequence"
    assert a != c, "distinct seeds must decorrelate"
    assert a[0] == 0.25  # the first delay is the base either way
    prev = a[0]
    for d in a[1:]:
        assert 0.25 <= d <= max(0.25, 3 * prev) + 1e-12, (a,)
        prev = d
    # the env knob is the same switch (value = seed)
    monkeypatch.setenv("PA_RETRY_JITTER", "7")
    assert delays() == a
    monkeypatch.setenv("PA_RETRY_JITTER", "0")
    assert delays() == [0.25, 0.5, 1.0, 2.0]
    # jitter composes with the true-zero policy: base 0 stays 0
    monkeypatch.setenv("PA_RETRY_JITTER", "3")
    sleeps = []
    with pytest.raises(OSError):
        retry_with_backoff(
            always_fail, attempts=3, backoff=0.0, sleep=sleeps.append,
        )
    assert sleeps == [0.0, 0.0]


def test_multihost_init_retries_explicit_spec(monkeypatch):
    """An explicit cluster spec retries RuntimeError (coordinator not up
    yet) with backoff before failing; a bad-value spec fails fast."""
    import jax

    from partitionedarrays_jl_tpu.parallel.multihost import multihost_init

    tries = []

    def fake_init(coordinator_address=None, num_processes=None, process_id=None):
        tries.append(coordinator_address)
        if len(tries) < 3:
            raise RuntimeError("connection refused")

    monkeypatch.setattr(jax.distributed, "initialize", fake_init)
    monkeypatch.setenv("PA_RETRY_BACKOFF", "0.0")
    multihost_init("10.0.0.1:1234", 2, 0, attempts=3)
    assert len(tries) == 3

    def bad_spec(**kw):
        raise ValueError("num_processes must be positive")

    monkeypatch.setattr(jax.distributed, "initialize", bad_spec)
    with pytest.raises(ValueError):
        multihost_init("10.0.0.1:1234", -1, 0, attempts=3)


# ---------------------------------------------------------------------------
# checkpoint / restart
# ---------------------------------------------------------------------------


def test_checkpointer_roundtrip(tmp_path):
    d = str(tmp_path / "ck")

    def driver(parts):
        A, b, x_exact, x0 = _setup(parts)
        ck = SolverCheckpointer(d, every=5)
        assert not ck.due(0) and ck.due(5) and not ck.due(7)
        assert not ck.has_state()
        assert load_solver_state(d, {}) is None
        x, info = cg(A, b, x0=x0, tol=1e-9, checkpoint=ck)
        assert info["converged"] and ck.has_state()
        st = load_solver_state(d, {"x": A.cols, "r": b.rows, "p": A.cols})
        assert st["meta"]["method"] == "cg"
        assert st["meta"]["it"] % 5 == 0 and st["meta"]["it"] > 0
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_fault_recovery_reproduces_clean_run(tmp_path, monkeypatch):
    """THE acceptance scenario: corrupted halo payload at iteration k →
    detected within one iteration → auto-restart from the last
    checkpoint → same answer as the fault-free run. Bitwise on the same
    partition, in default AND strict-bits mode."""
    for strict in ("0", "1"):
        monkeypatch.setenv("PA_TPU_STRICT_BITS", strict)
        d = str(tmp_path / f"ck{strict}")

        def driver(parts):
            A, b, x_exact, x0 = _setup(parts, ns=(12, 12))
            x_clean, info_clean = cg(A, b, x0=x0, tol=1e-9)
            assert info_clean["converged"]
            with inject_faults("nan@part=1,call=20", seed=5) as st:
                x_rec, info_rec = solve_with_recovery(
                    A, b, method="cg", x0=x0, checkpoint_dir=d, every=6,
                    tol=1e-9,
                )
            assert [e["kind"] for e in st.events] == ["nan"]
            assert info_rec["converged"] and info_rec["restarts"] == 1
            assert info_rec["failures"][0]["type"] == "NonFiniteError"
            a, c = gather_pvector(x_clean), gather_pvector(x_rec)
            np.testing.assert_allclose(a, c, rtol=0, atol=0)  # bitwise
            # the recovered run solves the PDE, not just itself
            assert (
                float(np.linalg.norm(c - gather_pvector(x_exact))) < 1e-6
            )
            return True

        assert pa.prun(driver, pa.sequential, (2, 2))


def test_recovery_without_checkpoint_dir_restarts_from_scratch():
    def driver(parts):
        A, b, x_exact, x0 = _setup(parts)
        with inject_faults("nan@part=0,call=7", seed=1):
            x, info = solve_with_recovery(
                A, b, method="cg", x0=x0, tol=1e-9, max_restarts=1
            )
        assert info["converged"] and info["restarts"] == 1
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_recovery_exhausts_restart_budget():
    def driver(parts):
        A, b, x_exact, x0 = _setup(parts)
        # after=0: every exchange is poisoned, restarts cannot help
        with inject_faults("nan@part=0,after=0", seed=1):
            with pytest.raises(NonFiniteError):
                solve_with_recovery(
                    A, b, method="cg", x0=x0, tol=1e-9, max_restarts=2
                )
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_pcg_recovery_with_jacobi(tmp_path):
    d = str(tmp_path / "ck")

    def driver(parts):
        from partitionedarrays_jl_tpu.models import jacobi_preconditioner, pcg

        A, b, x_exact, x0 = _setup(parts, ns=(12, 12))
        minv = jacobi_preconditioner(A)
        x_clean, info_clean = pcg(A, b, x0=x0, minv=minv, tol=1e-9)
        with inject_faults("nan@part=2,call=15", seed=2):
            x_rec, info_rec = solve_with_recovery(
                A, b, method="pcg", minv=minv, x0=x0, checkpoint_dir=d,
                every=4, tol=1e-9,
            )
        assert info_rec["converged"] and info_rec["restarts"] == 1
        np.testing.assert_array_equal(
            gather_pvector(x_clean), gather_pvector(x_rec)
        )
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_resume_onto_different_part_count(tmp_path, monkeypatch):
    """The checkpoint is partition-independent: a 4-part run's solver
    state resumes on 3 parts and still converges to the PDE solution.
    Since the elastic round, the solver-state tier gates the part-count
    mismatch behind PA_ELASTIC=1 (typed CheckpointShapeError otherwise
    — tests/test_paelastic.py pins the refusal)."""
    monkeypatch.setenv("PA_ELASTIC", "1")
    d = str(tmp_path / "ck")
    ref = {}

    def save4(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (24,))
        ck = SolverCheckpointer(d, every=4)
        # stop mid-solve: the checkpoint holds a genuinely unconverged state
        cg(A, b, x0=x0, tol=1e-12, maxiter=9, checkpoint=ck)
        ref["exact"] = gather_pvector(x_exact)
        return True

    def resume3(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (24,))
        # tol/maxiter default to the checkpointed run's values (here the
        # deliberately-tiny maxiter=9) — override both to run to the end
        x, info = resume_solve(d, A, b, tol=1e-10, maxiter=500)
        assert info["resumed_from_iteration"] == 8
        assert info["converged"]
        np.testing.assert_allclose(
            gather_pvector(x), ref["exact"], atol=1e-8
        )
        return True

    assert pa.prun(save4, pa.sequential, 4)
    assert pa.prun(resume3, pa.sequential, 3)


def test_recovery_restarts_from_iterate_only_checkpoint(tmp_path):
    """A checkpoint directory holding an ITERATE-ONLY state (exactly what
    the chunked device path of the same job writes: {"x"} with no r/p or
    rs scalar) must not crash the host recovery path — the restart falls
    back to the checkpointed iterate, same contract as resume_solve."""
    d = str(tmp_path / "ck")

    def driver(parts):
        A, b, x_exact, x0 = _setup(parts)
        # seed the directory with an x-only checkpoint mid-trajectory
        x_mid, _ = cg(A, b, x0=x0, tol=1e-12, maxiter=6)
        seeder = SolverCheckpointer(d, every=1)
        seeder.save_state({"x": x_mid}, {"method": "cg", "it": 6, "tol": 1e-9})
        seeder.wait()
        # `every` large: the failing attempt writes no full-state
        # checkpoint of its own, so the restart sees ONLY the x-only one
        with inject_faults("nan@part=0,call=7", seed=1):
            x, info = solve_with_recovery(
                A, b, method="cg", x0=x0, checkpoint_dir=d, every=10_000,
                tol=1e-9, max_restarts=1,
            )
        assert info["converged"] and info["restarts"] == 1
        err = float(
            np.abs(gather_pvector(x) - gather_pvector(x_exact)).max()
        )
        assert err < 1e-6, err
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_resume_solve_rejects_empty_dir(tmp_path):
    def driver(parts):
        A, b, x_exact, x0 = _setup(parts)
        with pytest.raises(ValueError):
            resume_solve(str(tmp_path / "nothing"), A, b)
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


# ---------------------------------------------------------------------------
# device backend (8-device CPU mesh; skipped when unavailable)
# ---------------------------------------------------------------------------


def _tpu_backend():
    import jax

    try:
        from partitionedarrays_jl_tpu.parallel.tpu import TPUBackend

        return TPUBackend(devices=jax.devices()[:8])
    except Exception as e:  # pragma: no cover - environment-dependent
        pytest.skip(f"device mesh unavailable: {e}")


def test_device_nonfinite_guard_raises_typed():
    """The compiled CG's in-graph isfinite guard exits the loop within
    one iteration of NaN poisoning and the host wrapper raises the same
    typed NonFiniteError as the host loop."""
    backend = _tpu_backend()

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        # poison ONE owned entry of b: the first residual reduction sees it
        bad = pa.map_parts(
            lambda i, v: np.where(
                np.arange(len(np.asarray(v))) == 0, np.nan, np.asarray(v)
            )
            if int(i.part) == 1
            else np.asarray(v),
            b.rows.partition,
            b.values,
        )
        b_bad = pa.PVector(bad, b.rows)
        with pytest.raises(NonFiniteError) as ei:
            cg(A, b_bad, x0=x0, tol=1e-9)
        assert ei.value.diagnostics["iteration"] <= 1
        return True

    assert pa.prun(driver, backend, (2, 2))


def test_device_resume_from_host_checkpoint(tmp_path):
    """Cross-backend restore: a host run's FULL-state checkpoint resumes
    on the device backend (iterate-only restart — the compiled loop
    cannot ingest mid-recurrence state) and still converges."""
    backend = _tpu_backend()
    d = str(tmp_path / "ck")

    def save(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        cg(
            A, b, x0=x0, tol=1e-12, maxiter=7,
            checkpoint=SolverCheckpointer(d, every=3),
        )
        return True

    assert pa.prun(save, pa.sequential, (2, 2))

    def resume_dev(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        x, info = resume_solve(d, A, b, tol=1e-9, maxiter=500)
        assert info["resumed_from_iteration"] == 6
        assert info["converged"]
        err = float(
            np.linalg.norm(gather_pvector(x) - gather_pvector(x_exact))
        )
        assert err < 1e-6, err
        return True

    assert pa.prun(resume_dev, backend, (2, 2))


def test_device_chunked_recovery_converges(tmp_path):
    """solve_with_recovery on the device backend: the compiled solve runs
    in checkpointed chunks and matches the one-shot device solve to
    solver tolerance."""
    backend = _tpu_backend()
    d = str(tmp_path / "ck")

    def driver(parts):
        A, b, x_exact, x0 = assemble_poisson(parts, (8, 8))
        x_one, info_one = cg(A, b, x0=x0, tol=1e-9)
        x, info = solve_with_recovery(
            A, b, method="cg", x0=x0, checkpoint_dir=d, every=10, tol=1e-9
        )
        assert info["converged"] and info["restarts"] == 0
        err = float(
            np.linalg.norm(gather_pvector(x) - gather_pvector(x_one))
        )
        assert err < 1e-7, err
        return True

    assert pa.prun(driver, backend, (2, 2))
