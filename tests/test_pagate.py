"""pagate — the out-of-process multi-tenant front door
(`partitionedarrays_jl_tpu.frontdoor`).

The contracts pinned here:

* **Tenancy / budget** — N operators admitted against
  ``PA_GATE_MEM_BUDGET`` (the MEMORY_FOOTPRINT.json shape-sum
  convention), LRU eviction when the budget forces it, typed
  `TenantBudgetError` for an operator that can never fit.
* **EDF** — completed-request order under EDF never inverts two
  same-tenant deadlines (exact at slab width 1 — stronger than the
  one-chunk-boundary tolerance the invariant allows).
* **Shedding** — past the watermark the lowest class is refused with
  the typed, ``retry_after``-carrying `LoadShedded` (distinct from
  `AdmissionRejected`) while ``interactive`` keeps 100% attainment.
* **Eviction** — a page-out/page-in cycle re-stages the operator to a
  `plan_fingerprint`-IDENTICAL device plan and reproduces the solve
  BITWISE (the PR 8 rebuild invariant riding the gate).
* **RPC** — a request submitted over HTTP returns bitwise the same
  iterate as the same request submitted in-process, and the gate adds
  zero in-graph work (byte-identical StableHLO with the gate enabled).

Budget note: everything host-path runs on the sequential backend (tiny
Poisson grids, milliseconds); only the eviction-bitwise and HLO pins
touch device programs, on the tiny 4-/8-part fixtures.
"""
import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu import telemetry
from partitionedarrays_jl_tpu.analysis import plan_verifier as pv
from partitionedarrays_jl_tpu.frontdoor import (
    Gate,
    LoadShedded,
    TenantBudgetError,
    http_solve,
    operator_footprint_bytes,
    serve_gate,
    shed_classes,
)
from partitionedarrays_jl_tpu.models import assemble_poisson, gather_pvector
from partitionedarrays_jl_tpu.service import AdmissionRejected

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _poisson(grid=(8, 8)):
    return pa.prun(
        lambda parts: assemble_poisson(parts, grid), pa.sequential, (2, 2)
    )


def _counter(name, labels=None):
    return telemetry.registry().counter(name, labels=labels).value


# ---------------------------------------------------------------------------
# tenancy: budget admission + LRU paging
# ---------------------------------------------------------------------------


def test_budget_admission_and_lru_eviction():
    """Two tenants under a one-resident budget: registering the second
    evicts the first (LRU), routing a request back pages it in again,
    and the residency table + gate counters narrate every move."""
    A1, b1, xe1, x01 = _poisson((8, 8))
    A2, b2, xe2, x02 = _poisson((10, 10))
    fp1 = operator_footprint_bytes(A1, 4)
    fp2 = operator_footprint_bytes(A2, 4)
    assert fp1 > 0 and fp2 > fp1  # bigger grid, bigger footprint
    ev0 = _counter("gate.evictions")
    pi0 = _counter("gate.page_ins")
    gate = Gate(mem_budget_bytes=max(fp1, fp2) + 8)
    gate.register("t1", A1, kmax=4)
    gate.register("t2", A2, kmax=4)  # must evict t1
    res = {r["tenant"]: r for r in gate.residency()}
    assert not res["t1"]["resident"] and res["t2"]["resident"]
    assert res["t1"]["footprint_bytes"] == fp1
    assert gate.registry.resident_bytes() == fp2
    assert _counter("gate.evictions") == ev0 + 1
    assert _counter("gate.page_ins") == pi0 + 2
    # routing to the evicted tenant pages it back in (and evicts t2)
    h = gate.submit("t1", b1, x0=x01, tol=1e-9, slo_class="interactive")
    gate.drain()
    assert h.result()[1]["converged"]
    res = {r["tenant"]: r for r in gate.residency()}
    assert res["t1"]["resident"] and not res["t2"]["resident"]
    assert _counter("gate.evictions") == ev0 + 2
    assert _counter("gate.page_ins") == pi0 + 3


def test_operator_too_big_for_budget_is_typed():
    A, b, xe, x0 = _poisson((8, 8))
    gate = Gate(mem_budget_bytes=1000)
    with pytest.raises(TenantBudgetError) as ei:
        gate.register("huge", A, footprint_bytes=2000)
    assert ei.value.diagnostics["budget_bytes"] == 1000
    assert "huge" not in {r["tenant"] for r in gate.residency()}


# ---------------------------------------------------------------------------
# EDF
# ---------------------------------------------------------------------------


def test_edf_same_tenant_completion_order_never_inverts():
    """The EDF invariant at slab width 1 (each dispatch is its own
    slab, so the tolerance collapses to EXACT order): completion order
    equals deadline order regardless of submission order."""
    A, b, xe, x0 = _poisson((8, 8))
    gate = Gate()
    gate.register("t", A, kmax=1)
    rng = np.random.default_rng(7)
    deadlines = [100.0, 400.0, 200.0, 600.0, 300.0, 500.0]
    order = rng.permutation(len(deadlines))
    handles = {}
    for i in order:
        handles[deadlines[i]] = gate.submit(
            "t", b, x0=x0, tol=1e-9, deadline=deadlines[i],
            slo_class="interactive", tag=f"edf-{deadlines[i]:.0f}",
        )
    gate.drain()
    for h in handles.values():
        assert h.result()[1]["converged"]
    finished = sorted(
        handles.items(), key=lambda kv: kv[1].request.finished_at
    )
    assert [d for d, _ in finished] == sorted(deadlines), (
        "EDF must complete same-tenant requests in deadline order"
    )
    # deadline-free requests sort last (behind every deadline)
    hf = gate.submit("t", b, x0=x0, tol=1e-9, tag="edf-free")
    hd = gate.submit("t", b, x0=x0, tol=1e-9, deadline=900.0,
                     slo_class="interactive", tag="edf-late")
    gate.drain()
    assert hd.request.finished_at < hf.request.finished_at


# ---------------------------------------------------------------------------
# SLO-class shedding
# ---------------------------------------------------------------------------


def test_shed_policy_function():
    classes = ("interactive", "batch", "besteffort")
    assert shed_classes(0, classes, 4) == ()
    assert shed_classes(3, classes, 4) == ()
    assert shed_classes(4, classes, 4) == ("besteffort",)
    assert shed_classes(400, classes, 4) == ("besteffort",)
    assert shed_classes(10, ("only",), 1) == ()  # nothing to sacrifice


def test_shed_keeps_interactive_and_is_distinct_from_queue_full():
    """Past the watermark: besteffort sheds typed (LoadShedded with a
    positive retry_after_s, counted under gate.shed) while interactive
    keeps being admitted and reaches 100% attainment; LoadShedded is
    NOT an AdmissionRejected and moves neither service.rejected
    reason."""
    A, b, xe, x0 = _poisson((8, 8))
    gate = Gate(shed_watermark=2)
    gate.register("t", A, kmax=4)
    shed0 = _counter("gate.shed", labels={"slo_class": "besteffort"})
    rej0 = _counter("service.rejected",
                    labels={"reason": "queue_full"})
    req0 = _counter("gate.slo.requests",
                    labels={"slo_class": "interactive"})
    hit0 = _counter("gate.slo.hits",
                    labels={"slo_class": "interactive"})
    backlog = [
        gate.submit("t", b, x0=x0, tol=1e-9, slo_class="besteffort")
        for _ in range(2)
    ]
    with pytest.raises(LoadShedded) as ei:
        gate.submit("t", b, x0=x0, tol=1e-9, slo_class="besteffort")
    assert not isinstance(ei.value, AdmissionRejected)
    assert ei.value.retry_after_s > 0.0
    assert ei.value.diagnostics["slo_class"] == "besteffort"
    assert ei.value.diagnostics["depth"] == 2
    hi = gate.submit("t", b, x0=x0, tol=1e-9, deadline=600.0,
                     slo_class="interactive")
    gate.drain()
    assert hi.result()[1]["converged"]
    for h in backlog:
        assert h.result()[1]["converged"]
    assert _counter(
        "gate.shed", labels={"slo_class": "besteffort"}
    ) == shed0 + 1
    assert _counter(
        "service.rejected", labels={"reason": "queue_full"}
    ) == rej0, "shedding must not count as queue-full backpressure"
    assert _counter(
        "gate.slo.requests", labels={"slo_class": "interactive"}
    ) == req0 + 1
    assert _counter(
        "gate.slo.hits", labels={"slo_class": "interactive"}
    ) == hit0 + 1
    # the pamon gate view renders residency + attainment from exactly
    # this snapshot (no new collection)
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "pamon", os.path.join(REPO, "tools", "pamon.py")
    )
    pamon = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pamon)
    view = pamon.render_gate(telemetry.registry().snapshot())
    assert "front door (pagate)" in view
    assert "tenant t" in view
    assert "class=interactive" in view and "attainment=" in view


# ---------------------------------------------------------------------------
# eviction: page-out/page-in reproduces the solve bitwise
# ---------------------------------------------------------------------------


def test_eviction_pageout_pagein_bitwise_and_plan_fingerprint():
    """The eviction pin: solve, page the tenant out (device buffers
    dropped), route a request back in — the re-staged device exchange
    plan is `plan_fingerprint`-IDENTICAL (the PR 8 rebuild invariant)
    and the solve reproduces BITWISE with the same iteration count."""
    import jax

    from test_fused_cg import _fixture_spd_system

    backend = pa.TPUBackend(devices=jax.devices()[:4])
    A, b = pa.prun(
        lambda parts: _fixture_spd_system(parts), backend, 4
    )
    gate = Gate()
    gate.register("t", A, kmax=2)
    h1 = gate.submit("t", b, tol=1e-10, maxiter=200)
    gate.drain()
    x1, i1 = h1.result()
    assert i1["converged"]
    assert A._device, "the solve must have staged device buffers"
    dA = next(iter(A._device.values()))
    fp0 = pv.plan_fingerprint(dA.col_plan)
    misses0 = telemetry.counter("lowering_cache.miss")
    gate.evict("t")
    assert not A._device, "eviction must drop the device staging"
    h2 = gate.submit("t", b, tol=1e-10, maxiter=200)  # auto page-in
    gate.drain()
    x2, i2 = h2.result()
    assert telemetry.counter("lowering_cache.miss") == misses0 + 1, (
        "the page-in must RE-stage (a cache hit would mean eviction "
        "never dropped the buffers)"
    )
    dA2 = next(iter(A._device.values()))
    assert pv.plan_fingerprint(dA2.col_plan) == fp0
    assert i2["converged"] and i2["iterations"] == i1["iterations"]
    np.testing.assert_array_equal(
        gather_pvector(x1), gather_pvector(x2)
    )


# ---------------------------------------------------------------------------
# RPC: the HTTP surface
# ---------------------------------------------------------------------------


def test_http_roundtrip_bitwise_and_endpoints():
    """Submit-poll-fetch over HTTP returns bitwise the same iterate as
    the same request submitted in-process, and the operational
    endpoints (healthz / tenants / metrics) serve the gate's state."""
    A, b, xe, x0 = _poisson((8, 8))
    gate = Gate(start_workers=True)
    gate.register("p8", A, kmax=4)
    srv = serve_gate(gate, port=0)
    try:
        bg, x0g = gather_pvector(b), gather_pvector(x0)
        out = http_solve(srv.url, "p8", bg, x0=x0g, tol=1e-9,
                         slo_class="interactive", tag="http-req")
        assert out["state"] == "done" and out["info"]["converged"]
        h = gate.submit("p8", b, x0=x0, tol=1e-9, tag="inproc-req")
        gate.drain()
        x_in, info_in = h.result()
        np.testing.assert_array_equal(
            np.asarray(out["x"]), gather_pvector(x_in)
        )
        assert out["info"]["iterations"] == info_in["iterations"]
        with urllib.request.urlopen(srv.url + "/healthz") as resp:
            health = json.loads(resp.read())
        assert health["ok"] and health["tenants"] == 1
        # readiness-probe grade (ISSUE 14): depth, residency, journal
        # epoch, uptime
        assert health["queue_depth"] == 0
        assert health["resident"] == ["p8"]
        assert health["journal_epoch"] is None  # journal off here
        assert (
            isinstance(health["uptime_s"], float)
            and health["uptime_s"] >= 0.0
        )
        with urllib.request.urlopen(srv.url + "/v1/tenants") as resp:
            tenants = json.loads(resp.read())
        assert tenants["tenants"][0]["tenant"] == "p8"
        assert tenants["tenants"][0]["resident"]
        with urllib.request.urlopen(srv.url + "/metrics") as resp:
            prom = resp.read().decode()
        assert "pa_gate_page_ins" in prom
        assert "pa_gate_slo_requests" in prom
        # unknown tenant and unknown request are typed 404s
        ghost = http_solve(srv.url, "ghost", bg)
        assert ghost["http_status"] == 404
        assert ghost["error"] == "UnknownTenant"
        try:
            urllib.request.urlopen(srv.url + "/v1/solve/r999999")
            raise AssertionError("unknown request must 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        srv.stop()


def test_gate_enabled_block_program_hlo_identical(monkeypatch):
    """The overhead pin (the PR 6/9 convention): with every PA_GATE_*
    knob set and a gate actively serving, the block body lowers to
    byte-identical StableHLO vs the no-gate baseline — the front door
    adds ZERO in-graph work."""
    import jax

    from partitionedarrays_jl_tpu.parallel.tpu import (
        TPUBackend,
        _matrix_operands,
        device_matrix,
        make_cg_fn,
    )

    backend = TPUBackend(devices=jax.devices()[:8])
    A = pa.prun(
        lambda parts: assemble_poisson(parts, (6, 6, 6))[0],
        backend, (2, 2, 2),
    )
    dA = device_matrix(A, backend)
    ops = _matrix_operands(dA)
    P, W = dA.col_plan.layout.P, dA.col_plan.layout.W
    zb = np.zeros((P, W, 2))

    def text():
        fn = make_cg_fn(dA, tol=1e-9, maxiter=50, rhs_batch=2)
        return fn.jit_fn.lower(zb, zb, zb[..., 0], ops).as_text()

    baseline = text()
    monkeypatch.setenv("PA_GATE_MEM_BUDGET", "123456789")
    monkeypatch.setenv("PA_GATE_CLASSES", "interactive,besteffort")
    monkeypatch.setenv("PA_GATE_SHED_DEPTH", "5")
    monkeypatch.setenv("PA_GATE_PORT", "0")
    As, bs, xes, x0s = _poisson((8, 8))
    gate = Gate()
    gate.register("seq", As, kmax=2)
    h = gate.submit("seq", bs, x0=x0s, tol=1e-9, deadline=600.0,
                    slo_class="interactive")
    gate.drain()
    assert h.result()[1]["converged"]
    assert text() == baseline


def test_pagate_check_smoke(capsys):
    """The tier-1 smoke: tools/pagate.py --check serves on an ephemeral
    port, forces one shed and one eviction, and asserts outcomes,
    events, and metric deltas in-process."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "pagate", os.path.join(REPO, "tools", "pagate.py")
    )
    pagate = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pagate)
    rc = pagate.main(["--check"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "pagate --check: OK" in out
