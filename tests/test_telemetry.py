"""The patrace observability layer (partitionedarrays_jl_tpu.telemetry).

The tentpole's hard contract, pinned here with the same discipline as
ABFT (tests/test_abft.py):

* **Telemetry OFF is free.** The compiled CG program with
  ``PA_TRACE_ITERS`` unset/0 is byte-identical StableHLO to the same
  build under ``PA_METRICS=0`` — the record layer is host-side only and
  can never reach a traced program.
* **Telemetry ON adds ZERO collectives.** The α/β trace ring is a
  replicated while-carry of scalars the dot gathers already replicated;
  per-kind collective counts are identical ON vs OFF.
* **Trajectory identity.** Under strict-bits the residual history and
  solution are BITWISE identical with the trace ring on, off, and with
  the whole record layer killed — and the recorded α/β entries obey the
  CG recurrence against the residual history itself.
* **Static-vs-measured reconciliation.** A finished solve's runtime
  comms accounting (plan model × iterations) equals what the lowered
  program statically implies, per collective kind in ops AND bytes
  (probe legs here; the full 15-case matrix runs under the slow marker
  in test_static_analysis.py and `tools/palint.py --check`).

Plus the host-side machinery: SolveRecord/InfoDict compat, event
nesting, the metrics registry, record persistence + the patrace CLI,
the PTimer trace bridge, and the shared artifact writer.
"""
import json
import os

import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu import telemetry
from partitionedarrays_jl_tpu.analysis import collective_counts
from partitionedarrays_jl_tpu.models import assemble_poisson, cg
from partitionedarrays_jl_tpu.parallel.tpu import (
    TPUBackend,
    device_matrix,
    make_cg_fn,
    tpu_cg,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _backend(n=8):
    import jax

    return TPUBackend(devices=jax.devices()[:n])


def _probe(backend):
    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (6, 6, 6))
        return A, b, x0

    return pa.prun(driver, backend, (2, 2, 2))


# ---------------------------------------------------------------------------
# the hard contract: OFF is HLO-identical, ON adds zero collectives
# ---------------------------------------------------------------------------


def test_trace_off_program_hlo_identical_across_telemetry_env(monkeypatch):
    """PA_METRICS (the record layer) and PA_TRACE_ITERS=0 (explicit
    trace-off) lower the IDENTICAL program — byte-equal StableHLO. Only
    a nonzero trace depth may change the traced program (and that via
    its registered key site, covered by test_static_analysis.py)."""
    backend = _backend()
    A, b, _x0 = _probe(backend)
    dA = device_matrix(A, backend)
    from partitionedarrays_jl_tpu.parallel.tpu import _matrix_operands

    ops = _matrix_operands(dA)
    P = dA.col_plan.layout.P
    z = np.zeros((P, dA.col_plan.layout.W))

    def text():
        fn = make_cg_fn(dA, tol=1e-9, maxiter=50)
        return fn.jit_fn.lower(z, z, z, ops).as_text()

    base = text()
    monkeypatch.setenv("PA_METRICS", "0")
    off = text()
    monkeypatch.delenv("PA_METRICS")
    monkeypatch.setenv("PA_TRACE_ITERS", "0")
    explicit = text()
    assert base == off == explicit


def test_trace_ring_adds_zero_collectives(monkeypatch):
    """The α/β ring rides the while carry: per-kind collective counts
    identical with PA_TRACE_ITERS on vs off."""
    backend = _backend()
    A, b, _x0 = _probe(backend)
    dA = device_matrix(A, backend)
    from partitionedarrays_jl_tpu.parallel.tpu import _matrix_operands

    ops = _matrix_operands(dA)
    z = np.zeros((dA.col_plan.layout.P, dA.col_plan.layout.W))
    off = collective_counts(make_cg_fn(dA, tol=1e-9, maxiter=50),
                            z, z, z, ops)
    monkeypatch.setenv("PA_TRACE_ITERS", "16")
    fn_on = make_cg_fn(dA, tol=1e-9, maxiter=50)
    assert fn_on.trace_iters == 16
    on = collective_counts(fn_on, z, z, z, ops)
    assert any(off.values()), "probe program shows no collectives"
    assert on == off, (on, off)


def test_strict_bits_trajectory_bitwise_with_trace_ring(monkeypatch):
    """Under strict-bits the solve trajectory is BITWISE identical with
    the trace ring on, off, and with PA_METRICS=0 — and the recorded
    α/β obey the CG recurrence against the residual history (β_i =
    (h_{i+1}/h_i)², h = √rs, in the unpreconditioned standard body)."""
    monkeypatch.setenv("PA_TPU_STRICT_BITS", "1")
    backend = _backend()
    A, b, x0 = _probe(backend)

    def solve():
        def driver(parts):
            x, info = tpu_cg(A, b, x0=x0, tol=1e-9, maxiter=100)
            return np.asarray(pa.gather_pvector(x)), info

        return pa.prun(driver, backend, (2, 2, 2))

    x_off, inf_off = solve()
    monkeypatch.setenv("PA_TRACE_ITERS", "64")
    x_on, inf_on = solve()
    monkeypatch.delenv("PA_TRACE_ITERS")
    monkeypatch.setenv("PA_METRICS", "0")
    x_kill, inf_kill = solve()
    monkeypatch.delenv("PA_METRICS")

    assert inf_on["iterations"] == inf_off["iterations"] == \
        inf_kill["iterations"]
    np.testing.assert_array_equal(x_on, x_off)
    np.testing.assert_array_equal(x_kill, x_off)
    np.testing.assert_array_equal(
        np.asarray(inf_on["residuals"]), np.asarray(inf_off["residuals"])
    )

    # the traced ring ties back to the trajectory it rode along with
    rec = inf_on.record
    it = inf_on["iterations"]
    assert rec.trace_start == 0
    assert len(rec.alpha) == len(rec.beta) == it
    hist = np.asarray(inf_on["residuals"])
    np.testing.assert_allclose(
        np.asarray(rec.beta), (hist[1:it + 1] / hist[:it]) ** 2,
        rtol=1e-10,
    )
    assert all(a > 0 for a in rec.alpha)  # SPD operator

    # the killed layer returned an inert record: nothing retained
    assert getattr(inf_kill, "record").enabled is False
    assert inf_kill.record.events == []

    # overflowing ring (depth < iterations): a TRUE ring — the record
    # keeps the LAST `depth` committed iterations, un-rotated, with
    # trace_start marking the window; the trajectory is untouched
    depth = max(2, it - 2)
    monkeypatch.setenv("PA_TRACE_ITERS", str(depth))
    x_ring, inf_ring = solve()
    monkeypatch.delenv("PA_TRACE_ITERS")
    np.testing.assert_array_equal(x_ring, x_off)
    rr = inf_ring.record
    assert rr.trace_start == it - depth
    assert len(rr.alpha) == len(rr.beta) == depth
    a = np.arange(rr.trace_start, it)
    np.testing.assert_allclose(
        np.asarray(rr.beta), (hist[a + 1] / hist[a]) ** 2, rtol=1e-10,
    )


# ---------------------------------------------------------------------------
# static-vs-measured comms reconciliation (fast probe legs)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("case_name", ["standard", "standard_abft"])
def test_comms_reconciliation_probe(case_name):
    """The runtime accounting a finished probe solve reports equals the
    lowered program's static expectation — per kind, ops AND bytes, at
    the solve's trip count (the SDC-defended leg counts while-loop
    trips, not committed iterations). Full matrix: slow marker +
    `tools/palint.py --check`."""
    from partitionedarrays_jl_tpu.analysis.program_report import analyze_text
    from partitionedarrays_jl_tpu.parallel.tpu import (
        case_probe_solve,
        case_program_text,
        lowering_matrix,
    )

    backend = _backend()
    case = {c["name"]: c for c in lowering_matrix(fast=False)}[case_name]
    rec = case_probe_solve(backend, case)
    assert rec.comms is not None and rec.comms["iterations"] > 0
    report = analyze_text(case_program_text(backend, case))
    mismatches = telemetry.reconcile(report, rec.comms)
    assert not mismatches, "\n".join(mismatches)
    obs = rec.comms["observed"]
    assert obs["collective_permute"]["ops"] > 0
    assert obs["all_gather"]["bytes"] > 0


# ---------------------------------------------------------------------------
# records, events, the info-dict compat view
# ---------------------------------------------------------------------------


def test_host_solve_returns_infodict_with_record():
    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        x, info = cg(A, b, x0=x0, tol=1e-9)
        assert isinstance(info, dict)  # every legacy consumer holds
        assert dict(info)["converged"] == info["converged"]
        rec = info.record
        assert rec.solver == "cg" and rec.finished
        assert rec.status != "raised" and rec.iterations == \
            info["iterations"]
        assert rec.config["backend"] == "host"
        assert rec.config["tol"] == 1e-9
        assert rec.config["pa_env"].get("PA_TPU_CHECKS") == "1"
        assert rec.wall_s > 0
        assert len(rec.residuals) == info["iterations"] + 1
        assert telemetry.last_record("cg") is rec
        # round-trips through the persisted-JSON shape
        d = rec.as_dict()
        assert d["schema_version"] == telemetry.RECORD_SCHEMA_VERSION
        json.dumps(d)
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))


def test_event_nesting_and_kill_switch(monkeypatch):
    outer = telemetry.begin_record("outer")
    inner = telemetry.begin_record("inner")
    telemetry.emit_event("checkpoint_save", label="x", iteration=3, n=1)
    assert telemetry.current_record() is inner
    inner.finish(None)
    telemetry.emit_event("restart", label="y")
    outer.finish(None)
    # the outer scope saw BOTH events; the inner only its own
    assert [e.kind for e in outer.events] == ["checkpoint_save", "restart"]
    assert [e.kind for e in inner.events] == ["checkpoint_save"]
    assert inner.events[0].iteration == 3
    assert inner.events[0].details == {"n": 1}

    monkeypatch.setenv("PA_METRICS", "0")
    ghost = telemetry.begin_record("ghost")
    telemetry.emit_event("restart")
    ghost.finish(None)
    assert ghost.enabled is False and ghost.events == []
    assert telemetry.last_record("ghost") is None


def test_metrics_registry():
    telemetry.reset_counters("t_test")
    assert telemetry.counter("t_test.a") == 0
    telemetry.bump("t_test.a")
    telemetry.bump("t_test.a", 2)
    telemetry.bump("t_test.b")
    assert telemetry.counter("t_test.a") == 3
    snap = telemetry.counters("t_test")
    assert snap == {"t_test.a": 3, "t_test.b": 1}
    telemetry.reset_counters("t_test")
    assert telemetry.counters("t_test") == {}


# ---------------------------------------------------------------------------
# persistence + the patrace CLI
# ---------------------------------------------------------------------------


def test_record_persistence_and_patrace_cli(monkeypatch, tmp_path, capsys):
    d = str(tmp_path / "recs")
    monkeypatch.setenv("PA_METRICS_DIR", d)

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (8, 8))
        cg(A, b, x0=x0, tol=1e-9)
        cg(A, b, x0=x0, tol=1e-6)
        return True

    assert pa.prun(driver, pa.sequential, (2, 2))
    paths = telemetry.list_persisted_records(d)
    assert len(paths) == 2
    rec = telemetry.load_record(paths[-1])
    assert rec["schema_version"] == telemetry.RECORD_SCHEMA_VERSION
    assert rec["solver"] == "cg" and rec["iterations"] > 0

    # drive the CLI in-process (a subprocess would re-import jax and
    # burn ~8s of the tier-1 budget for no added coverage)
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "patrace_cli", os.path.join(REPO, "tools", "patrace.py")
    )
    patrace = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(patrace)
    out_trace = str(tmp_path / "trace.json")
    rc = patrace.main(
        ["--list", "--last", "--trace", out_trace, "--n", "2", "--dir", d]
    )
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "record:" in out and "solver=cg" in out
    assert "events [" in out
    trace = json.load(open(out_trace))
    assert trace["metadata"]["schema_version"] == \
        telemetry.TRACE_SCHEMA_VERSION
    spans = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    assert len(spans) == 2  # one complete span per record
    assert all(s["dur"] > 0 for s in spans)


# ---------------------------------------------------------------------------
# the PTimer bridge + the shared artifact writer
# ---------------------------------------------------------------------------


def test_ptimer_trace_bridge(tmp_path):
    def driver(parts):
        t = pa.PTimer(parts)
        t.tic(barrier=True)
        sum(range(1000))
        t.toc("stage")
        t.tic(barrier=False)
        sum(range(10))
        t.toc("solve")
        data = t.data_json()
        assert data["schema_version"] == 1
        assert set(data["sections"]) == {"stage", "solve"}
        assert [s["name"] for s in data["spans"]] == ["stage", "solve"]
        # the barrier drain is its own recorded cost, not hidden
        assert data["spans"][0]["barrier_s"] >= 0.0
        assert data["spans"][1]["barrier_s"] == 0.0
        evs = t.trace_events()
        names = [e["name"] for e in evs]
        assert "stage" in names and "solve" in names
        if data["spans"][0]["barrier_s"] > 0:
            assert "stage:tic_barrier" in names
        # lands on the same timeline as solver records
        combined = telemetry.chrome_trace(records=[], timers=[t])
        assert any(e.get("cat") == "ptimer"
                   for e in combined["traceEvents"])
        out = str(tmp_path / "ptimer.json")
        t.print_timer(json_path=out)
        if os.path.exists(out):  # written on MAIN only
            assert json.load(open(out))["sections"]
        return True

    assert pa.prun(driver, pa.sequential, 2)


def test_artifact_writer_envelope(tmp_path, capsys):
    rec = telemetry.stamp({"x": 1, "platform": "tpu"}, tool="t")
    # setdefault discipline: a tool-recorded platform survives stamping
    assert rec["platform"] == "tpu"
    assert rec["schema_version"] == telemetry.ARTIFACT_SCHEMA_VERSION
    assert rec["generated_by"] == "t"
    path = str(tmp_path / "X_BENCH.json")
    telemetry.write(path, {"y": 2}, tool="bench_x")
    on_disk = json.load(open(path))
    assert on_disk["schema_version"] == telemetry.ARTIFACT_SCHEMA_VERSION
    assert on_disk["generated_by"] == "bench_x"
    assert on_disk["y"] == 2 and "pa_env" in on_disk
    # dry-run prints, never touches the path
    telemetry.write(str(tmp_path / "no.json"), {"z": 3}, dry_run=True)
    assert not os.path.exists(str(tmp_path / "no.json"))
    assert '"z": 3' in capsys.readouterr().out
