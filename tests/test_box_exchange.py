"""Extended-box halo exchange (parallel/tpu_box.py): slice-based
pack/unpack for Cartesian partitions.

Reference anchor: the Exchanger data path these plans lower
(/root/reference/src/Interfaces.jl:846-889) and the FDM ghost layout
(/root/reference/test/test_fdm.jl:82-100). The box plan must be value-
equivalent to both the generic gather plan and the host oracle on every
Cartesian workload, and must DECLINE (fall back) on anything without the
uniform-box structure."""
import os

import numpy as np
import pytest

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu.parallel.tpu import (
    DeviceVector,
    TPUBackend,
    device_exchange_plan,
    make_exchange_fn,
)
from partitionedarrays_jl_tpu.parallel.tpu_box import (
    BoxExchangePlan,
    analyze_box_structure,
)


def _ramp(rows):
    """Deterministic per-part values: gid-derived, so any slot shuffle
    that misroutes a single element changes some compared value."""
    vals = pa.map_parts(
        lambda i: np.asarray(i.lid_to_gid, dtype=np.float64) * 2.0
        + 1.0
        + 0.001 * i.part,
        rows.partition,
    )
    return pa.PVector(vals, rows)


def _exchange_device(parts, rows, combine="set"):
    v = _ramp(rows)
    vh = v.copy()
    if combine == "set":
        vh.exchange()
    else:
        vh.assemble()
    dv = DeviceVector.from_pvector(v, parts.backend)
    fn = make_exchange_fn(rows, parts.backend, combine=combine)
    out = DeviceVector(
        fn(dv.data), rows, dv.layout, parts.backend
    ).to_pvector()
    for a, b in zip(out.values.part_values(), vh.values.part_values()):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-14)
    return True


@pytest.mark.parametrize(
    "ns,grid",
    [
        ((8, 8, 8), (2, 2, 2)),
        ((9, 7, 8), (2, 2, 2)),  # uneven cells, equal part boxes not req'd
        ((12, 12), (2, 4)),
        ((16,), (4,)),
    ],
)
def test_with_ghost_detection_and_parity(ns, grid):
    def driver(parts):
        rows = pa.prange(parts, ns, pa.with_ghost)
        info = analyze_box_structure(rows)
        # round-4: equal AND unequal Cartesian splits take the fast path
        # (unequal boxes become pack-slice variants switched per shard)
        sets = rows.partition.part_values()
        shapes = {i.box_shape for i in sets}
        assert info is not None, (ns, grid)
        assert len(info.box_shapes) == len(shapes)
        plan = device_exchange_plan(rows, False)
        assert isinstance(plan, BoxExchangePlan)
        assert _exchange_device(parts, rows)
        assert _exchange_device(parts, rows, combine="add")
        return True

    assert pa.prun(driver, pa.tpu, grid)


def test_periodic_detection_and_parity():
    def driver(parts):
        rows = pa.prange(
            parts, (8, 8), pa.with_ghost, periodic=(True, True)
        )
        assert analyze_box_structure(rows) is not None
        assert _exchange_device(parts, rows)
        assert _exchange_device(parts, rows, combine="add")
        return True

    assert pa.prun(driver, pa.tpu, (2, 2))


def test_stencil_discovery_cols_detection():
    """The assemble_poisson cols PRange (add_gids ghost discovery with
    Dirichlet-trimmed boundary faces) must still detect: the slab design
    packs bounding slabs and masks orphan slots."""

    def driver(parts):
        A, b, xe, x0 = pa.assemble_poisson(parts, (8, 8, 8))
        info = analyze_box_structure(A.cols)
        assert info is not None
        # trimmed faces -> orphan slots exist, and the mask knows them
        assert not info.seg_mask.all()
        assert _exchange_device(parts, A.cols)
        assert _exchange_device(parts, A.cols, combine="add")
        return True

    assert pa.prun(driver, pa.tpu, (2, 2, 2))


def test_unequal_boxes_take_variant_fast_path():
    """(7, 8) cells over (2, 2) parts -> box shapes (3, 4) and (4, 4):
    round-4 directive 6 — unequal splits no longer fall back; the plan
    carries per-shard pack-slice VARIANTS (lax.switch in the body) and
    must match the host oracle in both directions."""

    def driver(parts):
        rows = pa.prange(parts, (7, 8), pa.with_ghost)
        info = analyze_box_structure(rows)
        assert info is not None and len(info.box_shapes) == 2, info
        plan = device_exchange_plan(rows, False)
        assert isinstance(plan, BoxExchangePlan)
        assert _exchange_device(parts, rows)
        assert _exchange_device(parts, rows, combine="add")
        return True

    assert pa.prun(driver, pa.tpu, (2, 2))


@pytest.mark.parametrize(
    "ns,grid",
    [
        ((7, 9, 11), (2, 2, 2)),  # all dims unequal: 8 shape variants
        ((31,), (4,)),
        ((13, 8), (3, 2)),
    ],
)
def test_unequal_boxes_variant_parity(ns, grid):
    """Unequal-split parity sweep: forward and reverse exchanges through
    the variant fast path must match the host oracle exactly."""

    def driver(parts):
        rows = pa.prange(parts, ns, pa.with_ghost)
        assert isinstance(
            device_exchange_plan(rows, False), BoxExchangePlan
        )
        assert _exchange_device(parts, rows)
        assert _exchange_device(parts, rows, combine="add")
        return True

    assert pa.prun(driver, pa.tpu, grid)


def test_irregular_partition_falls_back():
    """Non-Cartesian index sets have no box metadata at all."""

    def driver(parts):
        rows = pa.uniform_partition(parts, 64)
        gids = pa.map_parts(
            lambda i: (np.asarray(i.oid_to_gid[:1]) + 17) % 64,
            rows.partition,
        )
        rows = pa.add_gids(rows, gids)
        assert analyze_box_structure(rows) is None
        assert _exchange_device(parts, rows)
        return True

    assert pa.prun(driver, pa.tpu, 4)


def test_cg_and_spmv_parity_through_box_plan():
    """End-to-end: the compiled CG (whose SpMV body embeds the box
    exchange) matches the sequential oracle's iterations and solution."""

    def driver(parts):
        A, b, xe, x0 = pa.assemble_poisson(parts, (8, 8, 8))
        plan = device_exchange_plan(A.cols, False)
        assert isinstance(plan, BoxExchangePlan)
        x, info = pa.cg(A, b, x0=x0, tol=1e-10, maxiter=400)
        err = np.abs(pa.gather_pvector(x) - pa.gather_pvector(xe)).max()
        assert info["converged"]
        return float(err), info["iterations"]

    err_t, it_t = pa.prun(driver, pa.tpu, (2, 2, 2))

    def seq_driver(parts):
        A, b, xe, x0 = pa.assemble_poisson(parts, (8, 8, 8))
        x, info = pa.cg(A, b, x0=x0, tol=1e-10, maxiter=400)
        return info["iterations"]

    it_s = pa.prun(seq_driver, pa.sequential, (2, 2, 2))
    assert err_t < 1e-6
    assert it_t == it_s


def test_env_flag_disables_box_plan():
    def driver(parts):
        rows = pa.prange(parts, (8, 8), pa.with_ghost)
        os.environ["PA_TPU_BOX"] = "0"
        try:
            plan = device_exchange_plan(rows, False)
            assert not isinstance(plan, BoxExchangePlan)
            assert _exchange_device(parts, rows)
        finally:
            del os.environ["PA_TPU_BOX"]
        plan = device_exchange_plan(rows, False)
        assert isinstance(plan, BoxExchangePlan)
        return True

    assert pa.prun(driver, pa.tpu, (2, 2))


def test_box_and_generic_plans_agree_slotwise():
    """The two plans over the SAME layout must produce identical device
    arrays (not just identical PVectors): exchange is used inside
    compiled solvers that read raw slots."""
    import jax

    def driver(parts):
        rows = pa.prange(parts, (8, 8, 8), pa.with_ghost)
        v = _ramp(rows)
        dv = DeviceVector.from_pvector(v, parts.backend)
        from partitionedarrays_jl_tpu.parallel.tpu import (
            DeviceExchangePlan, _box_dummy_operands, _shard_exchange, _stage,
        )

        backend = parts.backend
        plan_box = device_exchange_plan(rows, False)
        assert isinstance(plan_box, BoxExchangePlan)
        layout = plan_box.layout
        plan_gen = DeviceExchangePlan(rows.exchanger, layout)
        mesh = backend.mesh(layout.P)
        spec = backend.parts_spec()

        def run(plan, si, sm, ri):
            from partitionedarrays_jl_tpu.parallel.tpu import _shard_map
            shard_map = _shard_map()

            body = _shard_exchange(plan, "set")

            @jax.jit
            def fn(x, a, b, c):
                return shard_map(
                    lambda xs, as_, bs, cs: body(
                        xs[0], as_[0], bs[0], cs[0]
                    )[None],
                    mesh=mesh,
                    in_specs=(spec,) * 4,
                    out_specs=spec,
                    check_vma=False,
                )(x, a, b, c)

            return np.asarray(fn(dv.data, si, sm, ri))

        P = layout.P
        out_box = run(plan_box, *_box_dummy_operands(backend, P))
        out_gen = run(
            plan_gen,
            _stage(backend, plan_gen.snd_idx, P),
            _stage(backend, plan_gen.snd_mask, P),
            _stage(backend, plan_gen.rcv_idx, P),
        )
        # orphan slots may differ (box ships whole slabs); every REAL
        # slot — owned + mapped ghosts — must agree exactly
        o0 = layout.o0
        for p, iset in enumerate(rows.partition.part_values()):
            np.testing.assert_array_equal(
                out_box[p, o0 : o0 + iset.num_oids],
                out_gen[p, o0 : o0 + iset.num_oids],
            )
            hs = layout.hid_slots[p]
            np.testing.assert_array_equal(out_box[p, hs], out_gen[p, hs])
        return True

    assert pa.prun(driver, pa.tpu, (2, 2, 2))
