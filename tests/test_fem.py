"""End-to-end: 2-D Q1 FEM assembly with remote rows + CG (sequential + TPU).

Mirrors the reference FEM coverage (reference: test/test_fem_sa.jl): the
assembly touches rows owned by other parts, exercising COO migration and
PVector ghost->owner assembly.
"""
import numpy as np

import partitionedarrays_jl_tpu as pa
from partitionedarrays_jl_tpu.models import gather_pvector
from partitionedarrays_jl_tpu.models.fem_q1 import (
    fem_q1_driver,
    fem_q1_rhs_via_global_view,
)


def test_fem_2d_4_parts():
    err, info = pa.prun(fem_q1_driver, pa.sequential, (2, 2), (8, 8))
    assert info["converged"]
    assert err < 1e-5


def test_fem_uneven_grid():
    err, info = pa.prun(fem_q1_driver, pa.sequential, (2, 2), (9, 7))
    assert info["converged"]
    assert err < 1e-5


def test_fem_matches_single_part():
    err1, info1 = pa.prun(fem_q1_driver, pa.sequential, (1, 1), (8, 8))
    err4, info4 = pa.prun(fem_q1_driver, pa.sequential, (2, 2), (8, 8))
    assert err1 < 1e-5 and err4 < 1e-5
    assert info1["iterations"] == info4["iterations"]


def test_fem_on_tpu_backend():
    err_t, info_t = pa.prun(fem_q1_driver, pa.tpu, (2, 2), (8, 8))
    err_s, info_s = pa.prun(fem_q1_driver, pa.sequential, (2, 2), (8, 8))
    assert err_t < 1e-5 and info_t["converged"]
    assert info_t["iterations"] == info_s["iterations"]


def test_rhs_global_view_assembly():
    """Each interior node is touched by its 4 adjacent elements, boundary
    nodes by fewer; the assembled rhs counts element touches per node."""
    b = pa.prun(fem_q1_rhs_via_global_view, pa.sequential, (2, 2), (6, 6))
    g = gather_pvector(b)
    counts = g.reshape(6, 6)
    assert counts[2, 3] == 4.0  # interior: 4 elements
    assert counts[0, 0] == 1.0  # corner: 1 element
    assert counts[0, 2] == 2.0  # edge: 2 elements
    # ghost entries were zeroed after assembly
    for i, vals in zip(b.rows.partition, b.values):
        assert np.all(np.asarray(vals)[i.hid_to_lid] == 0.0)
